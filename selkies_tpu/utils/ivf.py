"""IVF container writer (VP8/VP9 elementary frames → decodable file).

The WS media plane ships raw codec frames; IVF is the standard thin
container for offline tooling and conformance tests (FFmpeg decodes it
directly).
"""

from __future__ import annotations

import struct

_FOURCC = {"vp8": b"VP80", "vp9": b"VP90", "av1": b"AV01"}


def ivf_file(frames: list[bytes], codec: str, width: int, height: int, fps: int) -> bytes:
    fourcc = _FOURCC[codec]
    out = struct.pack(
        "<4sHH4sHHIIII", b"DKIF", 0, 32, fourcc, width, height, fps, 1, len(frames), 0
    )
    for i, f in enumerate(frames):
        out += struct.pack("<IQ", len(f), i) + f
    return out
