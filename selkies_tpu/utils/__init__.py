"""Shared utilities: bitstream writers/readers, small helpers."""
