"""Tiny asyncio helpers shared across the signalling and transport layers."""

from __future__ import annotations

import asyncio
from typing import Any


async def maybe_await(result: Any) -> None:
    """Await `result` if the callback returned a coroutine (callbacks across
    the codebase may be sync or async)."""
    if asyncio.iscoroutine(result):
        await result
