"""Persistent XLA compilation cache for fast encoder (re)builds.

The resilience ladder's RESTART and RECYCLE rungs (resilience/
supervisor.py) rebuild encoders and fleet services; without a
compilation cache every rebuild pays the full XLA compile again — tens
of seconds of dead air exactly when a session is trying to recover. With
the disk cache, a rebuilt program with identical HLO loads in a fraction
of the time, and a restarted *process* (supervisor-level recovery, CI
reruns) warm-starts too.

``SELKIES_JAX_CACHE`` controls it: unset/``1``/``on`` → enabled under the
system temp dir; a path → enabled there; ``0``/``off`` → disabled.
"""

from __future__ import annotations

import logging
import os
import tempfile

logger = logging.getLogger("utils.jaxcache")

_done = False


def enable_persistent_compilation_cache() -> None:
    """Idempotent; call before building jitted programs. Failures degrade
    to uncached compiles — never to a crash."""
    global _done
    if _done:
        return
    _done = True
    mode = os.environ.get("SELKIES_JAX_CACHE", "1").strip()
    if mode.lower() in ("0", "off", "false", ""):
        logger.info("persistent compilation cache disabled (SELKIES_JAX_CACHE)")
        return
    path = (mode if mode.lower() not in ("1", "on", "true")
            else os.path.join(tempfile.gettempdir(), "selkies-tpu-jax-cache"))
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        try:
            # cache everything that takes real time; tiny programs stay
            # in-memory only
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        except Exception:
            logger.info("jax build without min-compile-time knob; using defaults")
        logger.info("persistent compilation cache at %s", path)
    except Exception:
        logger.exception("persistent compilation cache unavailable; "
                         "compiles will not be reused across restarts")
