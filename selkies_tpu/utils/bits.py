"""Bit-exact bitstream writer/reader for video codec syntax.

Used by the host-side header writers (SPS/PPS/slice headers) and the pure
Python CAVLC packer (the C++ packer in native/ mirrors this byte-for-byte).
MSB-first bit order as required by H.264/HEVC/VP9 bitstream syntax.
"""

from __future__ import annotations

__all__ = ["BitWriter", "BitReader", "emulation_prevent", "annexb_nal"]


class BitWriter:
    """MSB-first bit accumulator with Exp-Golomb helpers."""

    def __init__(self) -> None:
        self._buf = bytearray()
        self._acc = 0  # bits accumulated, MSB-aligned within _nbits
        self._nbits = 0

    def write_bits(self, value: int, nbits: int) -> None:
        if nbits < 0 or value < 0 or value >> nbits:
            raise ValueError(f"value {value} does not fit in {nbits} bits")
        self._acc = (self._acc << nbits) | (value & ((1 << nbits) - 1))
        self._nbits += nbits
        while self._nbits >= 8:
            self._nbits -= 8
            self._buf.append((self._acc >> self._nbits) & 0xFF)
        self._acc &= (1 << self._nbits) - 1

    def write_bit(self, bit: int) -> None:
        self.write_bits(bit & 1, 1)

    def write_ue(self, value: int) -> None:
        """Unsigned Exp-Golomb (ue(v))."""
        if value < 0:
            raise ValueError("ue(v) requires non-negative value")
        code = value + 1
        nbits = code.bit_length()
        self.write_bits(0, nbits - 1)
        self.write_bits(code, nbits)

    def write_se(self, value: int) -> None:
        """Signed Exp-Golomb (se(v)): 1→1, -1→2, 2→3, -2→4 ..."""
        self.write_ue(2 * value - 1 if value > 0 else -2 * value)

    @property
    def bit_position(self) -> int:
        return len(self._buf) * 8 + self._nbits

    def byte_align(self, bit: int = 0) -> None:
        while self._nbits % 8:
            self.write_bit(bit)

    def rbsp_trailing_bits(self) -> None:
        self.write_bit(1)
        self.byte_align(0)

    def get_bytes(self) -> bytes:
        if self._nbits:
            raise ValueError(f"bitstream not byte aligned ({self._nbits} bits pending)")
        return bytes(self._buf)

    def get_partial(self) -> tuple[bytes, int]:
        """(buffer including a zero-padded partial last byte, total bit count).

        Used to hand an unaligned prefix (e.g. a slice header) to the C++
        packer, which continues appending at the exact bit position.
        """
        total_bits = self.bit_position
        if self._nbits:
            last = (self._acc << (8 - self._nbits)) & 0xFF
            return bytes(self._buf) + bytes([last]), total_bits
        return bytes(self._buf), total_bits


class BitReader:
    """MSB-first reader, for tests and the conformance mini-decoder."""

    def __init__(self, data: bytes) -> None:
        self._data = data
        self.pos = 0  # bit position

    def read_bits(self, nbits: int) -> int:
        value = 0
        for _ in range(nbits):
            byte = self._data[self.pos >> 3]
            value = (value << 1) | ((byte >> (7 - (self.pos & 7))) & 1)
            self.pos += 1
        return value

    def read_bit(self) -> int:
        return self.read_bits(1)

    def read_ue(self) -> int:
        zeros = 0
        while self.read_bit() == 0:
            zeros += 1
            if zeros > 63:
                raise ValueError("malformed ue(v)")
        return (1 << zeros) - 1 + (self.read_bits(zeros) if zeros else 0)

    def read_se(self) -> int:
        k = self.read_ue()
        return (k + 1) // 2 if k % 2 else -(k // 2)

    @property
    def bits_left(self) -> int:
        return len(self._data) * 8 - self.pos


def emulation_prevent(rbsp: bytes) -> bytes:
    """Insert 0x03 after any 0x0000 followed by a byte <= 0x03 (H.264 7.4.1.1)."""
    out = bytearray()
    zeros = 0
    for b in rbsp:
        if zeros >= 2 and b <= 0x03:
            out.append(0x03)
            zeros = 0
        out.append(b)
        zeros = zeros + 1 if b == 0 else 0
    return bytes(out)


def annexb_nal(nal_ref_idc: int, nal_unit_type: int, rbsp: bytes, long_start: bool = True) -> bytes:
    """Wrap an RBSP payload as an Annex-B NAL unit with start code."""
    header = bytes([(nal_ref_idc << 5) | nal_unit_type])
    start = b"\x00\x00\x00\x01" if long_start else b"\x00\x00\x01"
    return start + header + emulation_prevent(rbsp)
