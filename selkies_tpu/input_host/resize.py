"""Dynamic server-side display resize (xrandr driver).

Parity target: reference resize.py — fit the requested WxH under the output
ceiling (7680x4320, or 2560x1600 on DVI outputs), create the mode with a
``cvt -r`` reduced-blanking modeline when missing, apply it with xrandr,
and set DPI / cursor size through xfconf.  All shell-outs run through one
``_run`` helper and are injectable for tests (no X needed).
"""

from __future__ import annotations

import logging
import re
import subprocess
from shutil import which
from typing import Callable

logger = logging.getLogger("resize")

MAX_RES = (7680, 4320)
MAX_RES_DVI = (2560, 1600)  # hardware-accelerator ceiling on DVI outputs

Runner = Callable[[list[str]], "subprocess.CompletedProcess[str]"]


def _run(cmd: list[str]) -> "subprocess.CompletedProcess[str]":
    return subprocess.run(cmd, capture_output=True, text=True, timeout=10)


def fit_res(w: int, h: int, max_w: int, max_h: int) -> tuple[int, int]:
    """Scale (w, h) down uniformly until it fits, snapped to even."""
    if w < max_w and h < max_h:
        return w, h
    scale = min(max_w / w, max_h / h)
    new_w, new_h = int(w * scale), int(h * scale)
    return new_w + new_w % 2, new_h + new_h % 2


def parse_xrandr(output: str) -> tuple[str | None, str | None, list[str]]:
    """Return (connected output name, current WxH, supported mode list)."""
    screen_name = None
    current = None
    modes: list[str] = []
    for line in output.splitlines():
        line = line.strip()
        m = re.match(r"(\S+) connected", line)
        if m:
            screen_name = m.group(1)
        m = re.match(r".*current (\d+) x (\d+).*", line)
        if m:
            current = f"{m.group(1)}x{m.group(2)}"
        if screen_name is not None:
            m = re.match(r"^(\d+x\d+)\s", line)
            if m:
                modes.append(m.group(1))
    return screen_name, current, sorted(modes)


def get_new_res(res: str, runner: Runner = _run):
    """(curr_res, fitted_res, modes, max_res, screen_name) for a request."""
    out = runner(["xrandr"]).stdout
    screen_name, curr_res, modes = parse_xrandr(out)
    if screen_name is None:
        logger.error("no connected output in xrandr output")
        return curr_res or res, res, modes, res, None
    max_w, max_h = MAX_RES_DVI if screen_name.startswith("DVI") else MAX_RES
    w, h = (int(v) for v in res.split("x"))
    new_w, new_h = fit_res(w, h, max_w, max_h)
    return curr_res or res, f"{new_w}x{new_h}", modes, f"{max_w}x{max_h}", screen_name


def generate_modeline(res: str, runner: Runner = _run) -> tuple[str, str]:
    """Reduced-blanking CVT modeline for "WxH" / "W H" / "W H hz" input."""
    if "x" in res:
        w, h = res.split("x")
        hz = "60"
    else:
        parts = res.split()
        if len(parts) == 2:
            (w, h), hz = parts, "60"
        elif len(parts) == 3:
            w, h, hz = parts
        else:
            raise ValueError(f"unsupported resolution format: {res!r}")
    out = runner(["cvt", "-r", w, h, hz]).stdout
    m = re.search(r'Modeline\s+"[^"]*"\s+(.*)', out)
    if not m:
        raise RuntimeError(f"cvt produced no modeline for {res!r}")
    return f"{w}x{h}", m.group(1).strip()


def resize_display(res: str, runner: Runner = _run) -> bool:
    """Apply a WxH resolution, creating the xrandr mode if needed."""
    curr_res, new_res, modes, _max_res, screen_name = get_new_res(res, runner)
    if screen_name is None:
        return False
    if curr_res == new_res:
        logger.info("display already %s, skipping resize", new_res)
        return False
    if new_res not in modes:
        mode, modeline = generate_modeline(new_res, runner)
        r = runner(["xrandr", "--newmode", mode, *modeline.split()])
        if r.returncode != 0:
            logger.error("xrandr --newmode failed: %s%s", r.stdout, r.stderr)
            return False
        r = runner(["xrandr", "--addmode", screen_name, mode])
        if r.returncode != 0:
            logger.error("xrandr --addmode failed: %s%s", r.stdout, r.stderr)
            return False
    r = runner(["xrandr", "--output", screen_name, "--mode", new_res])
    if r.returncode != 0:
        logger.error("xrandr --output failed: %s%s", r.stdout, r.stderr)
        return False
    logger.info("display resized to %s", new_res)
    return True


def set_dpi(dpi: int, runner: Runner = _run) -> bool:
    if not which("xfconf-query"):
        logger.warning("xfconf-query not found; cannot set DPI")
        return False
    r = runner(["xfconf-query", "-c", "xsettings", "-p", "/Xft/DPI",
                "-s", str(dpi), "--create", "-t", "int"])
    if r.returncode != 0:
        logger.error("failed to set DPI %d: %s%s", dpi, r.stdout, r.stderr)
        return False
    return True


def set_cursor_size(size: int, runner: Runner = _run) -> bool:
    if not which("xfconf-query"):
        logger.warning("xfconf-query not found; cannot set cursor size")
        return False
    r = runner(["xfconf-query", "-c", "xsettings", "-p", "/Gtk/CursorThemeSize",
                "-s", str(size), "--create", "-t", "int"])
    if r.returncode != 0:
        logger.error("failed to set cursor size %d: %s%s", size, r.stdout, r.stderr)
        return False
    return True


def entrypoint() -> None:
    """Console script ``selkies-tpu-resize WxH``."""
    import sys

    logging.basicConfig(level=logging.INFO)
    if len(sys.argv) < 2:
        print(f"USAGE: {sys.argv[0]} WxH")
        raise SystemExit(1)
    print(resize_display(sys.argv[1]))


if __name__ == "__main__":
    entrypoint()
