"""Clipboard backends.

The reference shells out to ``xsel`` (webrtc_input.py:401-414).  We keep
that as the production backend (gated on the binary being present) and add
an in-memory backend for tests and headless hosts.
"""

from __future__ import annotations

import logging
import shutil
import subprocess
from typing import Protocol

logger = logging.getLogger("input.clipboard")


class ClipboardBackend(Protocol):
    def read(self) -> str | None: ...

    def write(self, data: str) -> bool: ...


class XselClipboard:
    """xsel --clipboard subprocess backend."""

    @staticmethod
    def available() -> bool:
        return shutil.which("xsel") is not None

    def read(self) -> str | None:
        try:
            result = subprocess.run(
                ("xsel", "--clipboard", "--output"),
                check=True, text=True, capture_output=True, timeout=3,
            )
            return result.stdout
        except (subprocess.SubprocessError, OSError) as exc:
            logger.warning("clipboard read failed: %s", exc)
            return None

    def write(self, data: str) -> bool:
        try:
            subprocess.run(
                ("xsel", "--clipboard", "--input"),
                input=data.encode(), check=True, timeout=3,
            )
            return True
        except (subprocess.SubprocessError, OSError) as exc:
            logger.warning("clipboard write failed: %s", exc)
            return False


class MemoryClipboard:
    """In-process clipboard for tests / no-X hosts."""

    def __init__(self, initial: str = ""):
        self.data = initial

    def read(self) -> str | None:
        return self.data

    def write(self, data: str) -> bool:
        self.data = data
        return True


def open_best_clipboard() -> ClipboardBackend:
    if XselClipboard.available():
        return XselClipboard()
    logger.info("xsel not found; using in-memory clipboard")
    return MemoryClipboard()
