"""Host input injection: keyboard/mouse/gamepad/clipboard/resize into X11.

Parity with the reference's webrtc_input.py/gamepad.py/resize.py via ctypes
bindings against libX11/libXtst/libXfixes/libXrandr (no python-xlib dep).
"""
