"""Host input injection: keyboard/mouse/gamepad/clipboard/resize into X11.

Parity with the reference's webrtc_input.py/gamepad.py/resize.py via ctypes
bindings against libX11/libXtst/libXfixes/libXrandr (no python-xlib dep).
"""

from selkies_tpu.input_host.backends import (
    FakeBackend,
    InputBackend,
    UinputMouseProxy,
    X11Backend,
    open_best_backend,
)
from selkies_tpu.input_host.clipboard import (
    ClipboardBackend,
    MemoryClipboard,
    XselClipboard,
    open_best_clipboard,
)
from selkies_tpu.input_host.gamepad import GamepadServer
from selkies_tpu.input_host.handler import HostInput
from selkies_tpu.input_host.x11 import CursorImage, X11Display, X11Unavailable

__all__ = [
    "ClipboardBackend",
    "CursorImage",
    "FakeBackend",
    "GamepadServer",
    "HostInput",
    "InputBackend",
    "MemoryClipboard",
    "UinputMouseProxy",
    "X11Backend",
    "X11Display",
    "X11Unavailable",
    "XselClipboard",
    "open_best_backend",
    "open_best_clipboard",
]
