"""Pluggable input-injection backends.

The reference injects via pynput/XTest directly inside WebRTCInput
(webrtc_input.py:262-399); we factor the device boundary into a Backend
protocol so the protocol handler is testable without an X server:

* ``X11Backend`` — ctypes XTest injection (production).
* ``UinputMouseProxy`` — msgpack-over-unix-dgram relative-mouse proxy,
  wire-compatible with the reference's --uinput_mouse_socket flow
  (webrtc_input.py:159-164): payload {"args": [(type, code), value],
  "kwargs": {"syn": bool}}.
* ``FakeBackend`` — records every call; used by tests and headless CI.
"""

from __future__ import annotations

import logging
import socket
from typing import Protocol

import msgpack

from selkies_tpu.input_host import input_codes as codes
from selkies_tpu.input_host.x11 import CursorImage, X11Display

logger = logging.getLogger("input.backends")

# X core protocol pointer buttons
X_BTN_LEFT = 1
X_BTN_MIDDLE = 2
X_BTN_RIGHT = 3
X_BTN_SCROLL_UP = 4
X_BTN_SCROLL_DOWN = 5


class InputBackend(Protocol):
    def key(self, keysym: int, down: bool) -> None: ...

    def pointer_position(self, x: int, y: int) -> None: ...

    def pointer_motion(self, dx: int, dy: int) -> None: ...

    def button(self, x_button: int, down: bool) -> None: ...

    def scroll(self, up: bool) -> None: ...

    def sync(self) -> None: ...


class X11Backend:
    """XTest injection through the ctypes display wrapper."""

    def __init__(self, display: X11Display | None = None):
        self.display = display or X11Display.open()

    def key(self, keysym: int, down: bool) -> None:
        # Generic 105-key layouts map keysym 60 ('<') to keycode 94, whose
        # shifted sym is '>'; route '<' through ',' instead (reference
        # webrtc_input.py:325-330).
        if keysym == 60 and self.display.keysym_to_keycode(60) == 94:
            keysym = 44
        self.display.fake_key(keysym, down)

    def pointer_position(self, x: int, y: int) -> None:
        self.display.fake_motion(x, y)

    def pointer_motion(self, dx: int, dy: int) -> None:
        self.display.fake_relative_motion(dx, dy)

    def button(self, x_button: int, down: bool) -> None:
        self.display.fake_button(x_button, down)

    def scroll(self, up: bool) -> None:
        b = X_BTN_SCROLL_UP if up else X_BTN_SCROLL_DOWN
        self.display.fake_button(b, True)
        self.display.fake_button(b, False)

    def sync(self) -> None:
        self.display.sync()

    # cursor monitor hooks (consumed by HostInput.start_cursor_monitor)
    def cursor_image(self) -> CursorImage | None:
        return self.display.get_cursor_image()


_UINPUT_BTN = {
    X_BTN_LEFT: (codes.EV_KEY, codes.BTN_LEFT),
    X_BTN_MIDDLE: (codes.EV_KEY, codes.BTN_MIDDLE),
    X_BTN_RIGHT: (codes.EV_KEY, codes.BTN_RIGHT),
}


class UinputMouseProxy:
    """Relative-mouse half of a backend: forwards to a uinput helper over a
    unix datagram socket (containers without XTest pointer access)."""

    def __init__(self, socket_path: str):
        self.socket_path = socket_path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)

    def _emit(self, etype_code: tuple[int, int], value: int, syn: bool = True) -> None:
        payload = {"args": [tuple(etype_code), value], "kwargs": {"syn": syn}}
        try:
            self._sock.sendto(msgpack.packb(payload, use_bin_type=True), self.socket_path)
        except OSError as exc:
            logger.warning("uinput proxy send failed: %s", exc)

    def pointer_motion(self, dx: int, dy: int) -> None:
        self._emit((codes.EV_REL, codes.REL_X), dx, syn=False)
        self._emit((codes.EV_REL, codes.REL_Y), dy)

    def button(self, x_button: int, down: bool) -> None:
        mapped = _UINPUT_BTN.get(x_button)
        if mapped is not None:
            self._emit(mapped, 1 if down else 0)

    def scroll(self, up: bool) -> None:
        self._emit((codes.EV_REL, codes.REL_WHEEL), 1 if up else -1)

    def close(self) -> None:
        self._sock.close()


class FakeBackend:
    """Records injected events; stands in for X in tests/headless runs."""

    def __init__(self):
        self.events: list[tuple] = []
        self.keysym_keycode_overrides: dict[int, int] = {}
        self.fake_cursor: CursorImage | None = None

    def key(self, keysym: int, down: bool) -> None:
        self.events.append(("key", keysym, down))

    def pointer_position(self, x: int, y: int) -> None:
        self.events.append(("pos", x, y))

    def pointer_motion(self, dx: int, dy: int) -> None:
        self.events.append(("move", dx, dy))

    def button(self, x_button: int, down: bool) -> None:
        self.events.append(("button", x_button, down))

    def scroll(self, up: bool) -> None:
        self.events.append(("scroll", up))

    def sync(self) -> None:
        self.events.append(("sync",))

    def cursor_image(self) -> CursorImage | None:
        return self.fake_cursor


def open_best_backend() -> InputBackend:
    """X11 when a display is reachable, otherwise the fake recorder."""
    try:
        return X11Backend()
    except Exception as exc:  # X11Unavailable or library load issues
        logger.warning("X11 backend unavailable (%s); using FakeBackend", exc)
        return FakeBackend()
