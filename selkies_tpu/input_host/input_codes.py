"""Linux input event codes used by the virtual gamepad and uinput proxy.

Constants from the kernel's uapi ``input-event-codes.h`` (reference
counterpart: input_event_codes.py) — only the subset the gamepad mapping
and mouse proxy need.
"""

# event types
EV_SYN = 0x00
EV_KEY = 0x01
EV_REL = 0x02
EV_ABS = 0x03

# relative axes
REL_X = 0x00
REL_Y = 0x01
REL_WHEEL = 0x08

# mouse buttons
BTN_LEFT = 0x110
BTN_RIGHT = 0x111
BTN_MIDDLE = 0x112

# gamepad buttons
BTN_GAMEPAD = 0x130
BTN_A = 0x130
BTN_B = 0x131
BTN_C = 0x132
BTN_X = 0x133
BTN_Y = 0x134
BTN_Z = 0x135
BTN_TL = 0x136
BTN_TR = 0x137
BTN_TL2 = 0x138
BTN_TR2 = 0x139
BTN_SELECT = 0x13A
BTN_START = 0x13B
BTN_MODE = 0x13C
BTN_THUMBL = 0x13D
BTN_THUMBR = 0x13E

# absolute axes
ABS_X = 0x00
ABS_Y = 0x01
ABS_Z = 0x02
ABS_RX = 0x03
ABS_RY = 0x04
ABS_RZ = 0x05
ABS_THROTTLE = 0x06
ABS_RUDDER = 0x07
ABS_HAT0X = 0x10
ABS_HAT0Y = 0x11
