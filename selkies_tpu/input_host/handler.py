"""HostInput — the data-channel input protocol handler.

Parity target: ``WebRTCInput`` (webrtc_input.py:82-736).  Parses the full
client→server CSV vocabulary (``kd ku kr m m2 p vb ab js cr cw r s
_arg_fps _arg_resize _f _l _stats_video _stats_audio pong``) and turns
each message into a host-side effect through the pluggable injection
backend, the clipboard backend, and the per-js# gamepad servers, emitting
orchestrator callbacks for everything else.

Differences by design: injection goes through ``InputBackend`` (ctypes
XTest or fake) instead of pynput; the cursor monitor polls the XFixes
cursor serial instead of decoding X events.
"""

from __future__ import annotations

import asyncio
import base64
import io
import logging
import os
import re
import time
from typing import Any, Callable

from PIL import Image

from selkies_tpu.input_host.backends import (
    FakeBackend,
    InputBackend,
    UinputMouseProxy,
    X_BTN_LEFT,
    X_BTN_MIDDLE,
    X_BTN_RIGHT,
    open_best_backend,
)
from selkies_tpu.input_host.clipboard import ClipboardBackend, open_best_clipboard
from selkies_tpu.input_host.gamepad import GamepadServer
from selkies_tpu.input_host.x11 import CursorImage

logger = logging.getLogger("input.handler")

_RES_RE = re.compile(r"^\d+x\d+$")
_SCALE_RE = re.compile(r"^\d+(\.\d+)?$")

# Keysyms cleared by a keyboard reset (stuck-modifier recovery,
# webrtc_input.py:234-260): modifiers plus f/m (fullscreen hotkeys) and Esc.
RESET_KEYSYMS = (
    65507, 65505, 65513,  # L ctrl/shift/alt
    65508, 65506, 65027,  # R ctrl/shift, AltGr
    65511, 65512,         # meta
    102, 70, 109, 77,     # f F m M
    65307,                # Escape
)

NUM_GAMEPADS = 4


class HostInput:
    def __init__(
        self,
        backend: InputBackend | None = None,
        clipboard: ClipboardBackend | None = None,
        uinput_mouse_socket_path: str = "",
        js_socket_path: str = "/tmp",
        enable_clipboard: str = "false",
        enable_cursors: bool = True,
        cursor_size: int = 16,
        cursor_scale: float = 1.0,
        cursor_debug: bool = False,
    ):
        self.backend = backend if backend is not None else open_best_backend()
        self.clipboard = clipboard if clipboard is not None else open_best_clipboard()
        self.uinput_mouse: UinputMouseProxy | None = (
            UinputMouseProxy(uinput_mouse_socket_path) if uinput_mouse_socket_path else None
        )
        self.js_socket_paths = {
            i: os.path.join(js_socket_path, f"selkies_js{i}.sock") for i in range(NUM_GAMEPADS)
        }
        self.gamepads: dict[int, GamepadServer] = {}
        self.enable_clipboard = enable_clipboard
        self.enable_cursors = enable_cursors
        self.cursor_size = cursor_size
        self.cursor_scale = cursor_scale
        self.cursor_debug = cursor_debug
        self.cursor_cache: dict[int, dict] = {}
        self.button_mask = 0
        self.ping_start: float | None = None
        self._clipboard_running = False
        self._cursors_running = False

        # orchestrator callbacks (reference webrtc_input.py:114-139)
        warn = logger.warning
        self.on_video_encoder_bit_rate: Callable[[int], Any] = lambda b: warn("unhandled on_video_encoder_bit_rate")
        self.on_audio_encoder_bit_rate: Callable[[int], Any] = lambda b: warn("unhandled on_audio_encoder_bit_rate")
        self.on_mouse_pointer_visible: Callable[[bool], Any] = lambda v: warn("unhandled on_mouse_pointer_visible")
        self.on_clipboard_read: Callable[[str], Any] = lambda d: warn("unhandled on_clipboard_read")
        self.on_set_fps: Callable[[int], Any] = lambda f: warn("unhandled on_set_fps")
        self.on_set_enable_resize: Callable[[bool, str | None], Any] = lambda e, r: warn("unhandled on_set_enable_resize")
        self.on_client_fps: Callable[[int], Any] = lambda f: warn("unhandled on_client_fps")
        self.on_media_ack: Callable[[int, float], Any] = lambda seq, ms: None
        self.on_client_latency: Callable[[int], Any] = lambda l: warn("unhandled on_client_latency")
        self.on_resize: Callable[[str], Any] = lambda r: warn("unhandled on_resize")
        self.on_scaling_ratio: Callable[[float], Any] = lambda s: warn("unhandled on_scaling_ratio")
        self.on_ping_response: Callable[[float], Any] = lambda l: warn("unhandled on_ping_response")
        self.on_cursor_change: Callable[[dict | None], Any] = lambda m: warn("unhandled on_cursor_change")
        self.on_client_webrtc_stats: Callable[[str, str], Any] = lambda t, s: warn("unhandled on_client_webrtc_stats")

    # ------------------------------------------------------------------
    # lifecycle

    async def connect(self) -> None:
        self.reset_keyboard()

    async def disconnect(self) -> None:
        await self.stop_js_server()
        self.stop_clipboard()
        self.stop_cursor_monitor()

    # ------------------------------------------------------------------
    # keyboard / mouse injection

    def reset_keyboard(self) -> None:
        logger.info("resetting keyboard modifiers")
        for keysym in RESET_KEYSYMS:
            self.backend.key(keysym, down=False)

    def send_keypress(self, keysym: int, down: bool) -> None:
        try:
            self.backend.key(keysym, down)
        except Exception as exc:
            logger.error("failed to send keypress: %s", exc)

    def send_mouse(self, x: int, y: int, button_mask: int, scroll_magnitude: int, relative: bool) -> None:
        if relative:
            if self.uinput_mouse is not None:
                self.uinput_mouse.pointer_motion(x, y)
            else:
                self.backend.pointer_motion(x, y)
        else:
            self.backend.pointer_position(x, y)

        if button_mask != self.button_mask:
            for i in range(5):
                if not (button_mask ^ self.button_mask) & (1 << i):
                    continue
                down = bool(button_mask & (1 << i))
                if i < 3:
                    x_button = (X_BTN_LEFT, X_BTN_MIDDLE, X_BTN_RIGHT)[i]
                    # buttons/scroll ride the uinput proxy whenever it is
                    # configured (reference webrtc_input.py:294-310)
                    if self.uinput_mouse is not None:
                        self.uinput_mouse.button(x_button, down)
                    else:
                        self.backend.button(x_button, down)
                elif button_mask != 0:  # bits 3/4: wheel up/down edges
                    up = i == 3
                    # repeat per scroll magnitude for smoother trackpads
                    for _ in range(max(1, scroll_magnitude)):
                        if self.uinput_mouse is not None:
                            self.uinput_mouse.scroll(up)
                        else:
                            self.backend.scroll(up)
            self.button_mask = button_mask

        if not relative:
            self.backend.sync()

    # ------------------------------------------------------------------
    # clipboard

    def read_clipboard(self) -> str | None:
        return self.clipboard.read()

    def write_clipboard(self, data: str) -> bool:
        return self.clipboard.write(data)

    async def start_clipboard(self) -> None:
        if self.enable_clipboard not in ("true", "out"):
            logger.info("outbound clipboard disabled")
            return
        logger.info("starting clipboard monitor")
        self._clipboard_running = True
        last = ""
        while self._clipboard_running:
            data = await asyncio.to_thread(self.read_clipboard)
            if data and data != last:
                self.on_clipboard_read(data)
                last = data
            await asyncio.sleep(0.5)
        logger.info("clipboard monitor stopped")

    def stop_clipboard(self) -> None:
        self._clipboard_running = False

    # ------------------------------------------------------------------
    # cursor monitor

    async def start_cursor_monitor(self) -> None:
        if not self.enable_cursors:
            return
        getter = getattr(self.backend, "cursor_image", None)
        if getter is None:
            logger.warning("backend has no cursor support; cursor monitor off")
            return
        display = getattr(self.backend, "display", None)
        if display is not None and display.has_xfixes:
            display.select_cursor_events()
        logger.info("starting cursor monitor")
        self.cursor_cache = {}
        self._cursors_running = True
        last_serial = -1
        while self._cursors_running:
            if display is not None:
                await asyncio.to_thread(display.drain_events)
            try:
                cur = await asyncio.to_thread(getter)
            except Exception as exc:
                logger.warning("cursor fetch failed: %s", exc)
                cur = None
            if cur is not None and cur.serial != last_serial:
                last_serial = cur.serial
                if cur.serial not in self.cursor_cache:
                    self.cursor_cache[cur.serial] = self.cursor_to_msg(
                        cur, self.cursor_scale, self.cursor_size
                    )
                self.on_cursor_change(self.cursor_cache[cur.serial])
            await asyncio.sleep(0.1)
        logger.info("cursor monitor stopped")

    def stop_cursor_monitor(self) -> None:
        self._cursors_running = False

    def cursor_to_msg(self, cursor: CursorImage, scale: float = 1.0, cursor_size: int = -1) -> dict:
        if cursor_size > -1:
            w = h = cursor_size
            xhot = int(cursor_size / cursor.width * cursor.xhot) if cursor.width else 0
            yhot = int(cursor_size / cursor.height * cursor.yhot) if cursor.height else 0
        else:
            w, h = int(cursor.width * scale), int(cursor.height * scale)
            xhot, yhot = int(cursor.xhot * scale), int(cursor.yhot * scale)
        png = self.cursor_to_png(cursor, w, h)
        override = "none" if sum(cursor.argb) == 0 else None
        return {
            "curdata": base64.b64encode(png).decode(),
            "handle": cursor.serial,
            "override": override,
            "hotspot": {"x": xhot, "y": yhot},
        }

    @staticmethod
    def cursor_to_png(cursor: CursorImage, resize_w: int, resize_h: int) -> bytes:
        rgba = bytearray()
        for px in cursor.argb:
            rgba += bytes(((px >> 16) & 0xFF, (px >> 8) & 0xFF, px & 0xFF, (px >> 24) & 0xFF))
        im = Image.frombytes("RGBA", (cursor.width, cursor.height), bytes(rgba), "raw")
        if (cursor.width, cursor.height) != (resize_w, resize_h):
            im = im.resize((resize_w, resize_h))
        with io.BytesIO() as f:
            im.save(f, "PNG")
            return f.getvalue()

    # ------------------------------------------------------------------
    # gamepads

    async def _js_connect(self, js_num: int, name: str, num_btns: int, num_axes: int) -> None:
        path = self.js_socket_paths.get(js_num)
        if path is None:
            logger.error("no socket path for js%d", js_num)
            return
        logger.info("gamepad js%d connect: %r (%d btns, %d axes)", js_num, name, num_btns, num_axes)
        old = self.gamepads.pop(js_num, None)
        if old is not None:
            await old.stop()
        js = GamepadServer(path)
        await js.start()
        self.gamepads[js_num] = js

    async def _js_disconnect(self, js_num: int | None = None) -> None:
        if js_num is None:
            for js in self.gamepads.values():
                await js.stop()
            self.gamepads = {}
            return
        js = self.gamepads.pop(js_num, None)
        if js is not None:
            await js.stop()

    async def stop_js_server(self) -> None:
        await self._js_disconnect()

    # ------------------------------------------------------------------
    # ping

    def send_ping(self, when: float) -> None:
        self.ping_start = when

    # ------------------------------------------------------------------
    # the protocol

    async def on_message(self, msg: str) -> None:
        toks = msg.split(",")
        cmd = toks[0]
        try:
            if cmd == "pong":
                if self.ping_start is None:
                    logger.warning("received pong before ping")
                    return
                latency_ms = round((time.time() - self.ping_start) / 2 * 1000, 3)
                self.on_ping_response(latency_ms)
            elif cmd == "kd":
                self.send_keypress(int(toks[1]), down=True)
            elif cmd == "ku":
                self.send_keypress(int(toks[1]), down=False)
            elif cmd == "kr":
                self.reset_keyboard()
            elif cmd in ("m", "m2"):
                relative = cmd == "m2"
                try:
                    x, y, button_mask, scroll_magnitude = (int(v) for v in toks[1:])
                except (ValueError, IndexError):
                    x, y, button_mask, scroll_magnitude = 0, 0, self.button_mask, 0
                    relative = False
                try:
                    self.send_mouse(x, y, button_mask, scroll_magnitude, relative)
                except Exception as exc:
                    logger.warning("failed to send mouse event: %s", exc)
            elif cmd == "p":
                self.on_mouse_pointer_visible(bool(int(toks[1])))
            elif cmd == "vb":
                self.on_video_encoder_bit_rate(int(toks[1]))
            elif cmd == "ab":
                self.on_audio_encoder_bit_rate(int(toks[1]))
            elif cmd == "js":
                await self._on_js_message(toks)
            elif cmd == "cr":
                if self.enable_clipboard in ("true", "out"):
                    data = self.read_clipboard()
                    if data:
                        self.on_clipboard_read(data)
                else:
                    logger.warning("clipboard read rejected: outbound disabled")
            elif cmd == "cw":
                if self.enable_clipboard in ("true", "in"):
                    data = base64.b64decode(toks[1]).decode("utf-8")
                    self.write_clipboard(data)
                else:
                    logger.warning("clipboard write rejected: inbound disabled")
            elif cmd == "r":
                res = toks[1]
                if _RES_RE.match(res):
                    w, h = (int(v) + int(v) % 2 for v in res.split("x"))
                    self.on_resize(f"{w}x{h}")
                else:
                    logger.warning("invalid resolution: %s", res)
            elif cmd == "s":
                if _SCALE_RE.match(toks[1]):
                    self.on_scaling_ratio(float(toks[1]))
                else:
                    logger.warning("invalid scale: %s", toks[1])
            elif cmd == "_arg_fps":
                self.on_set_fps(int(toks[1]))
            elif cmd == "_arg_resize":
                if len(toks) != 3:
                    logger.error("_arg_resize expects <enabled>,<res>")
                    return
                enabled = toks[1].lower() == "true"
                res: str | None = None
                if _RES_RE.match(toks[2]):
                    w, h = (int(v) + int(v) % 2 for v in toks[2].split("x"))
                    res = f"{w}x{h}"
                self.on_set_enable_resize(enabled, res)
            elif cmd == "_ack":
                self.on_media_ack(int(toks[1]), float(toks[2]))
            elif cmd == "_f":
                self.on_client_fps(int(toks[1]))
            elif cmd == "_l":
                self.on_client_latency(int(toks[1]))
            elif cmd in ("_stats_video", "_stats_audio"):
                result = self.on_client_webrtc_stats(cmd, ",".join(toks[1:]))
                if asyncio.iscoroutine(result):
                    await result
            else:
                logger.info("unknown data channel message: %s", msg)
        except (ValueError, IndexError) as exc:
            logger.error("malformed input message %r: %s", msg, exc)

    async def _on_js_message(self, toks: list[str]) -> None:
        sub = toks[1]
        js_num = int(toks[2])
        if sub == "c":
            name = base64.b64decode(toks[3]).decode()[:255]
            num_axes, num_btns = int(toks[4]), int(toks[5])
            await self._js_connect(js_num, name, num_btns, num_axes)
        elif sub == "d":
            await self._js_disconnect(js_num)
        elif sub == "b":
            js = self.gamepads.get(js_num)
            if js is None:
                logger.error("js%d not connected", js_num)
                return
            js.send_btn(int(toks[3]), float(toks[4]))
        elif sub == "a":
            js = self.gamepads.get(js_num)
            if js is None:
                logger.error("js%d not connected", js_num)
                return
            js.send_axis(int(toks[3]), float(toks[4]))
        else:
            logger.warning("unhandled joystick command: %s", sub)
