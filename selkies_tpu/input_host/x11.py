"""Direct ctypes bindings to libX11 / libXtst / libXfixes.

The reference reaches X through python-xlib + pynput (webrtc_input.py:22-35);
neither is in this image, so we bind the three shared libraries directly.
Capabilities: XTest key/button/motion injection (abs + relative), keysym →
keycode resolution with on-the-fly spare-keycode mapping for keysyms absent
from the current keymap (what pynput does internally), and the XFixes
cursor-image API used by the cursor monitor (webrtc_input.py:437-553).

Everything degrades gracefully: if the libraries or the DISPLAY are absent,
``X11Display.open()`` raises ``X11Unavailable`` and callers fall back to the
fake backend (tests) or disable the feature (headless hosts).
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import os
from dataclasses import dataclass

logger = logging.getLogger("input.x11")

# X protocol constants
_KEY_PRESS = 2
_CURRENT_TIME = 0
_NO_SYMBOL = 0
XFIXES_DISPLAY_CURSOR_NOTIFY_MASK = 1 << 0


class X11Unavailable(RuntimeError):
    pass


class _XFixesCursorImage(ctypes.Structure):
    _fields_ = [
        ("x", ctypes.c_short),
        ("y", ctypes.c_short),
        ("width", ctypes.c_ushort),
        ("height", ctypes.c_ushort),
        ("xhot", ctypes.c_ushort),
        ("yhot", ctypes.c_ushort),
        ("cursor_serial", ctypes.c_ulong),
        ("pixels", ctypes.POINTER(ctypes.c_ulong)),
        ("atom", ctypes.c_ulong),
        ("name", ctypes.c_char_p),
    ]


@dataclass
class CursorImage:
    """Snapshot of the current cursor: ARGB pixels row-major."""

    width: int
    height: int
    xhot: int
    yhot: int
    serial: int
    argb: list[int]


def _load(*names: str) -> ctypes.CDLL | None:
    for name in names:
        try:
            return ctypes.CDLL(name)
        except OSError:
            continue
    return None


class X11Display:
    """One X connection with the small API surface the input host needs."""

    def __init__(self, xlib, xtst, xfixes, display_ptr):
        self._x = xlib
        self._xtst = xtst
        self._xfixes = xfixes
        self._dpy = display_ptr
        self._spare_mappings: dict[int, int] = {}  # keysym -> borrowed keycode
        self._min_kc = ctypes.c_int(0)
        self._max_kc = ctypes.c_int(0)
        self._x.XDisplayKeycodes(self._dpy, ctypes.byref(self._min_kc), ctypes.byref(self._max_kc))
        self._cursor_events_selected = False

    # -- lifecycle ------------------------------------------------------

    @classmethod
    def open(cls, display_name: str | None = None) -> "X11Display":
        xlib = _load("libX11.so.6", "libX11.so")
        xtst = _load("libXtst.so.6", "libXtst.so")
        xfixes = _load("libXfixes.so.3", "libXfixes.so")
        if xlib is None or xtst is None:
            raise X11Unavailable("libX11/libXtst not found")
        xlib.XOpenDisplay.restype = ctypes.c_void_p
        xlib.XOpenDisplay.argtypes = [ctypes.c_char_p]
        name = display_name if display_name is not None else os.environ.get("DISPLAY")
        if not name:
            raise X11Unavailable("DISPLAY is not set")
        dpy = xlib.XOpenDisplay(name.encode())
        if not dpy:
            raise X11Unavailable(f"cannot open display {name!r}")
        cls._declare(xlib, xtst, xfixes)
        return cls(xlib, xtst, xfixes, dpy)

    @staticmethod
    def _declare(x, xtst, xfixes) -> None:
        vp, ul, i, ui = ctypes.c_void_p, ctypes.c_ulong, ctypes.c_int, ctypes.c_uint
        x.XDefaultRootWindow.restype = ul
        x.XDefaultRootWindow.argtypes = [vp]
        x.XKeysymToKeycode.restype = ctypes.c_ubyte
        x.XKeysymToKeycode.argtypes = [vp, ul]
        x.XGetKeyboardMapping.restype = ctypes.POINTER(ul)
        x.XGetKeyboardMapping.argtypes = [vp, ctypes.c_ubyte, i, ctypes.POINTER(i)]
        x.XChangeKeyboardMapping.argtypes = [vp, i, i, ctypes.POINTER(ul), i]
        x.XDisplayKeycodes.argtypes = [vp, ctypes.POINTER(i), ctypes.POINTER(i)]
        x.XFlush.argtypes = [vp]
        x.XSync.argtypes = [vp, i]
        x.XPending.restype = i
        x.XPending.argtypes = [vp]
        x.XNextEvent.argtypes = [vp, ctypes.c_char_p]
        x.XFree.argtypes = [vp]
        x.XCloseDisplay.argtypes = [vp]
        xtst.XTestFakeKeyEvent.argtypes = [vp, ui, i, ul]
        xtst.XTestFakeButtonEvent.argtypes = [vp, ui, i, ul]
        xtst.XTestFakeMotionEvent.argtypes = [vp, i, i, i, ul]
        xtst.XTestFakeRelativeMotionEvent.argtypes = [vp, i, i, ul]
        if xfixes is not None:
            xfixes.XFixesQueryExtension.restype = i
            xfixes.XFixesQueryExtension.argtypes = [vp, ctypes.POINTER(i), ctypes.POINTER(i)]
            xfixes.XFixesSelectCursorInput.argtypes = [vp, ul, ul]
            xfixes.XFixesGetCursorImage.restype = ctypes.POINTER(_XFixesCursorImage)
            xfixes.XFixesGetCursorImage.argtypes = [vp]

    def close(self) -> None:
        if self._dpy:
            self._x.XCloseDisplay(self._dpy)
            self._dpy = None

    def flush(self) -> None:
        self._x.XFlush(self._dpy)

    def sync(self) -> None:
        self._x.XSync(self._dpy, 0)

    # -- keyboard -------------------------------------------------------

    def keysym_to_keycode(self, keysym: int) -> int:
        return int(self._x.XKeysymToKeycode(self._dpy, ctypes.c_ulong(keysym)))

    def _find_spare_keycode(self) -> int | None:
        count = self._max_kc.value - self._min_kc.value + 1
        per = ctypes.c_int(0)
        mapping = self._x.XGetKeyboardMapping(
            self._dpy, ctypes.c_ubyte(self._min_kc.value), count, ctypes.byref(per)
        )
        if not mapping:
            return None
        try:
            for kc_off in range(count - 1, -1, -1):
                if all(
                    mapping[kc_off * per.value + s] == _NO_SYMBOL
                    for s in range(per.value)
                ):
                    return self._min_kc.value + kc_off
        finally:
            self._x.XFree(mapping)
        return None

    def _map_spare(self, keysym: int) -> int:
        """Borrow an unused keycode for a keysym missing from the keymap."""
        if keysym in self._spare_mappings:
            return self._spare_mappings[keysym]
        kc = self._find_spare_keycode()
        if kc is None:
            return 0
        syms = (ctypes.c_ulong * 2)(keysym, keysym)
        self._x.XChangeKeyboardMapping(self._dpy, kc, 2, syms, 1)
        self.sync()
        self._spare_mappings[keysym] = kc
        return kc

    def fake_key(self, keysym: int, down: bool) -> None:
        keycode = self.keysym_to_keycode(keysym)
        if keycode == 0:
            keycode = self._map_spare(keysym)
            if keycode == 0:
                logger.warning("no keycode for keysym %d", keysym)
                return
        self._xtst.XTestFakeKeyEvent(self._dpy, keycode, 1 if down else 0, _CURRENT_TIME)
        self.flush()

    # -- pointer --------------------------------------------------------

    def fake_motion(self, x: int, y: int) -> None:
        self._xtst.XTestFakeMotionEvent(self._dpy, -1, int(x), int(y), _CURRENT_TIME)
        self.flush()

    def fake_relative_motion(self, dx: int, dy: int) -> None:
        self._xtst.XTestFakeRelativeMotionEvent(self._dpy, int(dx), int(dy), _CURRENT_TIME)
        self.flush()

    def fake_button(self, button: int, down: bool) -> None:
        self._xtst.XTestFakeButtonEvent(self._dpy, button, 1 if down else 0, _CURRENT_TIME)
        self.flush()

    # -- cursor (XFixes) ------------------------------------------------

    @property
    def has_xfixes(self) -> bool:
        if self._xfixes is None:
            return False
        eb, er = ctypes.c_int(0), ctypes.c_int(0)
        return bool(self._xfixes.XFixesQueryExtension(self._dpy, ctypes.byref(eb), ctypes.byref(er)))

    def select_cursor_events(self) -> None:
        root = self._x.XDefaultRootWindow(self._dpy)
        self._xfixes.XFixesSelectCursorInput(self._dpy, root, XFIXES_DISPLAY_CURSOR_NOTIFY_MASK)
        self.flush()
        self._cursor_events_selected = True

    def drain_events(self) -> int:
        """Discard queued events (cursor changes are detected by serial)."""
        n = 0
        buf = ctypes.create_string_buffer(192)  # sizeof(XEvent) on LP64
        while self._x.XPending(self._dpy) > 0:
            self._x.XNextEvent(self._dpy, buf)
            n += 1
        return n

    def get_cursor_image(self) -> CursorImage | None:
        if self._xfixes is None:
            return None
        ptr = self._xfixes.XFixesGetCursorImage(self._dpy)
        if not ptr:
            return None
        try:
            c = ptr.contents
            n = c.width * c.height
            # pixels are unsigned long on LP64 with ARGB in the low 32 bits
            argb = [c.pixels[i] & 0xFFFFFFFF for i in range(n)]
            return CursorImage(
                width=c.width, height=c.height, xhot=c.xhot, yhot=c.yhot,
                serial=int(c.cursor_serial), argb=argb,
            )
        finally:
            self._x.XFree(ptr)
