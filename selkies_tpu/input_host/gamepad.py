"""Virtual gamepad socket server.

Serves the joystick-interposer wire protocol (reference gamepad.py +
addons/js-interposer/joystick_interposer.c): a unix STREAM socket per
``js#`` where each new client first receives a config struct
``255sHH512H64B`` (name, num_btns, num_axes, btn_map, axes_map) and then a
stream of kernel-format ``struct js_event`` packets (``IhBB``: time-ms,
value, type, number).  Browser W3C standard-gamepad events are remapped to
the Linux xpad layout (triggers → full-range axes, dpad → hat axes,
reference gamepad.py:21-100) before serialisation.

Implemented with ``asyncio.start_unix_server`` (the reference hand-rolls a
non-blocking accept loop + thread sends).
"""

from __future__ import annotations

import asyncio
import logging
import os
import struct
import time

from selkies_tpu.input_host import input_codes as codes

logger = logging.getLogger("gamepad")

JS_EVENT_BUTTON = 0x01
JS_EVENT_AXIS = 0x02

MAX_BTNS = 512
MAX_AXES = 64
ABS_MIN = -32767
ABS_MAX = 32767

CONFIG_STRUCT = struct.Struct(f"255sHH{MAX_BTNS}H{MAX_AXES}B")
EVENT_STRUCT = struct.Struct("IhBB")

# The Linux xpad device exposed to applications: 11 buttons, 8 axes.
XPAD_NAME = "Selkies Controller"
XPAD_BTN_MAP = [
    codes.BTN_A, codes.BTN_B, codes.BTN_X, codes.BTN_Y,
    codes.BTN_TL, codes.BTN_TR, codes.BTN_SELECT, codes.BTN_START,
    codes.BTN_MODE, codes.BTN_THUMBL, codes.BTN_THUMBR,
]
XPAD_AXES_MAP = [
    codes.ABS_X, codes.ABS_Y, codes.ABS_Z, codes.ABS_RX,
    codes.ABS_RY, codes.ABS_RZ, codes.ABS_HAT0X, codes.ABS_HAT0Y,
]

# W3C standard-gamepad button index -> xpad target.
# Buttons 6/7 (triggers) become axes 2/5; dpad 12-15 become hat axes.
W3C_BTN_TO_AXIS = {6: (2, 1), 7: (5, 1), 15: (6, 1), 14: (6, -1), 13: (7, 1), 12: (7, -1)}
W3C_BTN_REMAP = {8: 6, 9: 7, 10: 9, 11: 10, 16: 8}
W3C_AXIS_REMAP = {2: 3, 3: 4}
TRIGGER_AXES = (2, 5)


def _event_ts_ms() -> int:
    return int((time.time() * 1000) % 1_000_000_000)


def axis_value(val: float) -> int:
    """Normalise [-1, 1] stick input to the joystick ABS range."""
    return round(ABS_MIN + ((val + 1) * (ABS_MAX - ABS_MIN)) / 2)


def trigger_value(val: float) -> int:
    """Normalise [0, 1] trigger input to the full ABS range."""
    return round(val * (ABS_MAX - ABS_MIN)) + ABS_MIN


def pack_event(num: int, value: int, is_axis: bool) -> bytes:
    etype = JS_EVENT_AXIS if is_axis else JS_EVENT_BUTTON
    return EVENT_STRUCT.pack(_event_ts_ms(), value, etype, num)


def pack_config(name: str = XPAD_NAME) -> bytes:
    btn_map = XPAD_BTN_MAP + [0] * (MAX_BTNS - len(XPAD_BTN_MAP))
    axes_map = XPAD_AXES_MAP + [0] * (MAX_AXES - len(XPAD_AXES_MAP))
    return CONFIG_STRUCT.pack(name.encode()[:255], len(XPAD_BTN_MAP), len(XPAD_AXES_MAP), *btn_map, *axes_map)


def map_w3c_button(btn_num: int, btn_val: float) -> bytes | None:
    """W3C standard-gamepad button -> js_event bytes (or None if unmappable)."""
    to_axis = W3C_BTN_TO_AXIS.get(btn_num)
    if to_axis is not None:
        axis, sign = to_axis
        if axis in TRIGGER_AXES:
            value = trigger_value(btn_val)
        else:
            value = axis_value(btn_val * sign)
        return pack_event(axis, value, is_axis=True)
    mapped = W3C_BTN_REMAP.get(btn_num, btn_num)
    if mapped >= len(XPAD_BTN_MAP):
        logger.error("button %d exceeds xpad button map", mapped)
        return None
    return pack_event(mapped, int(btn_val), is_axis=False)


def map_w3c_axis(axis_num: int, axis_val: float) -> bytes | None:
    mapped = W3C_AXIS_REMAP.get(axis_num, axis_num)
    if mapped >= len(XPAD_AXES_MAP):
        logger.error("axis %d exceeds xpad axes map", mapped)
        return None
    return pack_event(mapped, axis_value(axis_val), is_axis=True)


class GamepadServer:
    """One unix-socket server per virtual joystick (``/tmp/selkies_js{N}.sock``)."""

    MAX_WRITE_BUFFER = 64 * 1024  # drop clients that stop reading events

    def __init__(self, socket_path: str, name: str = XPAD_NAME):
        self.socket_path = socket_path
        self.name = name
        self._server: asyncio.base_events.Server | None = None
        self._writers: set[asyncio.StreamWriter] = set()

    @property
    def running(self) -> bool:
        return self._server is not None

    @property
    def num_clients(self) -> int:
        return len(self._writers)

    async def start(self) -> None:
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        os.makedirs(os.path.dirname(self.socket_path) or ".", exist_ok=True)
        self._server = await asyncio.start_unix_server(self._on_client, path=self.socket_path)
        logger.info("gamepad server listening on %s", self.socket_path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for w in list(self._writers):
            w.close()
        self._writers.clear()
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass
        logger.info("gamepad server stopped: %s", self.socket_path)

    async def _on_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        logger.info("gamepad client connected on %s", self.socket_path)
        try:
            writer.write(pack_config(self.name))
            await writer.drain()
            await asyncio.sleep(0.5)  # let the interposer finish config read
            # announce neutral state for every button/axis
            for b in range(len(XPAD_BTN_MAP)):
                writer.write(pack_event(b, 0, is_axis=False))
            for a in range(len(XPAD_AXES_MAP)):
                writer.write(pack_event(a, 0, is_axis=True))
            await writer.drain()
        except (ConnectionError, OSError):
            writer.close()
            return
        self._writers.add(writer)
        try:
            # interposer clients never send data; read detects disconnects
            while await reader.read(4096):
                pass
        except (ConnectionError, OSError):
            pass
        finally:
            self._writers.discard(writer)
            writer.close()
            logger.info("gamepad client disconnected from %s", self.socket_path)

    def _broadcast(self, event: bytes | None) -> None:
        if event is None:
            return
        for w in list(self._writers):
            try:
                if w.transport.get_write_buffer_size() > self.MAX_WRITE_BUFFER:
                    # client stopped reading; don't buffer events unboundedly
                    logger.warning("gamepad client not reading; dropping it")
                    self._writers.discard(w)
                    w.close()
                    continue
                w.write(event)
            except (ConnectionError, RuntimeError):
                self._writers.discard(w)

    def send_btn(self, btn_num: int, btn_val: float) -> None:
        self._broadcast(map_w3c_button(btn_num, btn_val))

    def send_axis(self, axis_num: int, axis_val: float) -> None:
        self._broadcast(map_w3c_axis(axis_num, axis_val))
