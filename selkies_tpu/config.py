"""Configuration system: CLI flags ⇄ SELKIES_* env vars ⇄ JSON overlay.

Parity target: the reference's three equivalent config layers
(/root/reference/src/selkies_gstreamer/__main__.py:337-540) — every CLI flag
has a ``SELKIES_<UPPERNAME>`` environment default, and a small set of
runtime-mutable settings (framerate, video/audio bitrate, enable_resize,
encoder) round-trips through a JSON config file so UI changes persist across
reconnects (reference ``set_json_app_argument`` __main__.py:303-333).

This implementation is declarative instead of 500 lines of argparse calls:
a single ``FLAGS`` table drives argparse construction, env defaulting, JSON
overlay, and documentation.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from dataclasses import dataclass, field
from typing import Any, Callable

logger = logging.getLogger("config")

ENV_PREFIX = "SELKIES_"

# Settings the client may mutate at runtime; they persist via the JSON config
# overlay (reference __main__.py:522-540).
JSON_MUTABLE = (
    "framerate",
    "video_bitrate",
    "audio_bitrate",
    "enable_resize",
    "encoder",
)


def _boolish(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("1", "true", "yes", "on")


@dataclass(frozen=True)
class Flag:
    name: str
    default: Any
    help: str
    type: Callable[[str], Any] = str

    @property
    def env(self) -> str:
        return ENV_PREFIX + self.name.upper()


def _f(name: str, default: Any, help: str, type: Callable[[str], Any] | None = None) -> Flag:
    if type is None:
        if isinstance(default, bool):
            type = _boolish
        elif isinstance(default, int):
            type = int
        elif isinstance(default, float):
            type = float
        else:
            type = str
    return Flag(name=name, default=default, help=help, type=type)


# One row per reference flag (__main__.py:337-520), plus TPU-specific flags at
# the end. Defaults mirror the reference where observable.
FLAGS: tuple[Flag, ...] = (
    # network / web
    _f("addr", "0.0.0.0", "Host for the signalling/web server to listen on."),
    _f("port", 8080, "Port for the signalling/web server."),
    _f("web_root", "", "Path to web client root (default: bundled web/ dir)."),
    _f("enable_https", False, "Serve signalling/web over TLS."),
    _f("https_cert", "/etc/ssl/certs/ssl-cert-snakeoil.pem", "TLS certificate path."),
    _f("https_key", "/etc/ssl/private/ssl-cert-snakeoil.key", "TLS key path."),
    _f("enable_basic_auth", False, "Require HTTP basic auth on web/signalling."),
    _f("basic_auth_user", os.environ.get("USER", "selkies"), "Basic auth username."),
    _f("basic_auth_password", "", "Basic auth password (required when enabled)."),
    # STUN/TURN
    _f("stun_host", "stun.l.google.com", "Fallback STUN hostname."),
    _f("stun_port", 19302, "Fallback STUN port."),
    _f("turn_host", "", "TURN server hostname."),
    _f("turn_port", 3478, "TURN server port."),
    _f("turn_protocol", "udp", "TURN transport protocol: udp or tcp."),
    _f("turn_tls", False, "Use TURN over TLS."),
    _f("turn_tls_insecure", False,
       "Skip TLS certificate verification for turns:// (self-signed coturn "
       "fleets / raw-IP TURN hosts whose certs cannot verify)."),
    _f("turn_username", "", "Legacy long-term TURN username."),
    _f("turn_password", "", "Legacy long-term TURN password."),
    _f("turn_shared_secret", "", "HMAC shared secret for short-term TURN credentials."),
    _f("turn_rest_uri", "", "TURN REST API endpoint returning RTC config."),
    _f("turn_rest_username", os.environ.get("USER", "selkies"), "Username sent to TURN REST API."),
    _f("turn_rest_username_auth_header", "x-auth-user", "Header carrying the TURN REST username."),
    _f("turn_rest_protocol_header", "x-turn-protocol", "Header carrying the TURN protocol."),
    _f("turn_rest_tls_header", "x-turn-tls", "Header carrying the TURN TLS flag."),
    _f("enable_cloudflare_turn", False, "Fetch TURN credentials from Cloudflare Calls."),
    _f("cloudflare_turn_token_id", "", "Cloudflare TURN token id."),
    _f("cloudflare_turn_api_token", "", "Cloudflare TURN API token."),
    _f("rtc_config_json", "/tmp/rtc.json", "Path to an RTC config JSON file (watched for changes)."),
    # app lifecycle
    _f("app_ready_file", "/run/appconfig/appready", "Sidecar readiness file to wait for."),
    _f("app_wait_ready", False, "Wait for app_ready_file before starting."),
    # media
    _f("encoder", "tpuh264enc", "Video encoder element (see models.registry; reference gstwebrtc_app.py:1133)."),
    _f("framerate", 60, "Capture/encode framerate."),
    _f("video_bitrate", 2000, "Video bitrate in kbps."),
    _f("audio_bitrate", 320000, "Audio bitrate in bps."),
    _f("audio_channels", 2, "Audio channel count."),
    _f("video_packetloss_percent", 0.0, "Video FEC percentage."),
    _f("audio_packetloss_percent", 0.0, "Audio FEC (Opus in-band) percentage."),
    _f("congestion_control", False, "Enable GCC congestion control driving the encoder rate controller."),
    _f("keyframe_distance", -1.0, "Keyframe distance in seconds (-1 = infinite GOP)."),
    # input / desktop integration
    _f("enable_clipboard", "true", "Clipboard sync: true|false|in|out."),
    _f("audio_device", "", "PulseAudio source device to capture (empty = server default monitor)."),
    _f("enable_cursors", True, "Forward X cursor changes to the client."),
    _f("cursor_size", -1, "XFCE cursor size."),
    _f("debug_cursors", False, "Log cursor change events."),
    _f("enable_resize", False, "Resize the X display to match the client window."),
    _f("js_socket_path", "/tmp", "Directory for gamepad unix sockets (selkies_js{0-3}.sock)."),
    _f("uinput_mouse_socket", "", "Path to a uinput mouse msgpack socket (container mode)."),
    # observability
    _f("enable_metrics_http", False, "Enable the Prometheus metrics HTTP server."),
    _f("metrics_http_port", 8000, "Prometheus metrics port."),
    _f("enable_webrtc_statistics", False, "Dump client WebRTC stats to CSV."),
    _f("webrtc_statistics_dir", "/tmp/webrtc_statistics", "Directory for WebRTC stats CSV files."),
    # config file
    _f("json_config", "/tmp/selkies_config.json", "JSON config overlay path (runtime-mutable settings)."),
    # legacy GPU flag kept for CLI compatibility; ignored by the TPU path
    _f("gpu_id", 0, "Legacy GPU index (ignored; present for CLI compatibility)."),
    # TPU-native additions
    _f("capture_width", 1280, "Capture width when no X display drives resolution (synthetic source)."),
    _f("capture_height", 720, "Capture height when no X display drives resolution (synthetic source)."),
    _f("tpu_device", 0, "TPU chip index this session's encode stream is placed on."),
    _f("tpu_sessions", 1, "Concurrent sessions to place across the TPU mesh (1 chip per stream)."),
    _f("session_displays", "", "Fleet mode: csv of X DISPLAY names, one per session (e.g. ':10,:11'); sessions beyond the list use synthetic sources."),
    _f("session_audio_devices", "", "Fleet mode: csv of PulseAudio source devices, one per session (e.g. 'sink10.monitor,sink11.monitor'); sessions with an empty entry or beyond the list get NO audio (a shared default monitor would leak audio across users)."),
    _f("transport", "auto", "Media transport: auto|webrtc|websocket."),
    _f("debug", False, "Verbose debug logging."),
)

_FLAGS_BY_NAME = {fl.name: fl for fl in FLAGS}


@dataclass
class Config:
    """Resolved configuration; attribute access per flag name."""

    values: dict[str, Any] = field(default_factory=dict)

    def __getattr__(self, name: str) -> Any:
        try:
            return self.__dict__["values"][name]
        except KeyError:
            raise AttributeError(name) from None

    # --- JSON overlay (reference set_json_app_argument __main__.py:303-333) ---

    def set_json_setting(self, name: str, value: Any) -> None:
        """Persist a runtime-mutable setting to the JSON config overlay."""
        if name not in JSON_MUTABLE:
            raise ValueError(f"setting {name!r} is not runtime-mutable")
        self.values[name] = value
        path = self.values["json_config"]
        data: dict[str, Any] = {}
        if os.path.exists(path):
            try:
                with open(path) as fh:
                    data = json.load(fh)
            except (ValueError, OSError):
                logger.warning("could not read JSON config %s; overwriting", path)
        data[name] = value
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(data, fh)
        os.replace(tmp, path)

    def apply_json_overlay(self) -> None:
        path = self.values.get("json_config")
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (ValueError, OSError) as exc:
            logger.warning("ignoring unreadable JSON config %s: %s", path, exc)
            return
        for key, value in data.items():
            if key in JSON_MUTABLE:
                fl = _FLAGS_BY_NAME[key]
                self.values[key] = fl.type(value) if not isinstance(value, bool) else value


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="selkies-tpu",
        description="TPU-native WebRTC remote desktop streaming server.",
    )
    for fl in FLAGS:
        env_val = os.environ.get(fl.env)
        default = fl.type(env_val) if env_val is not None else fl.default
        parser.add_argument(
            f"--{fl.name}",
            default=default,
            type=fl.type,
            help=f"{fl.help} [env: {fl.env}]",
        )
    return parser


def parse_config(argv: list[str] | None = None) -> Config:
    args = build_parser().parse_args(argv)
    cfg = Config(values=vars(args))
    cfg.apply_json_overlay()
    return cfg
