"""WebRTCTransport — the app-facing Transport over a PeerConnection.

Mirrors WebSocketTransport's surface (transport/websocket.py) so the
pipeline app and orchestrator treat both byte planes identically:
send_video/send_audio sinks, the data-channel string plane, connect /
disconnect lifecycle, and GCC feedback taps. SDP/ICE flows through the
on_sdp/on_ice callbacks (wired to the in-process SignallingClient) and
set_remote_sdp/add_remote_ice (called by the app core, pipeline/app.py
set_sdp/set_ice — the methods the round-1 review called dead stubs).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Awaitable, Callable

from selkies_tpu.transport.webrtc.peer import PeerConnection
from selkies_tpu.utils.aio import maybe_await as _maybe_await

logger = logging.getLogger("transport.webrtc")


class WebRTCTransport:
    def __init__(self, *, codec: str = "h264", audio: bool = True,
                 h264_profile: str = "baseline",
                 fec_percentage: int = 20,
                 stun_server: tuple[str, int] | None = None,
                 turn_server: tuple[str, int] | None = None,
                 turn_username: str = "", turn_password: str = "",
                 turn_transport: str = "udp",
                 turn_tls_insecure: bool = False):
        self._kw = dict(codec=codec, audio=audio,
                        h264_profile=h264_profile,
                        fec_percentage=fec_percentage,
                        stun_server=stun_server,
                        turn_server=turn_server, turn_username=turn_username,
                        turn_password=turn_password,
                        turn_transport=turn_transport,
                        turn_tls_insecure=turn_tls_insecure)
        self.pc: PeerConnection | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._input_ch = None
        self.frames_sent = 0
        self.bytes_sent = 0
        # outgoing signalling
        self.on_sdp: Callable[[str, str], Any] = lambda t, s: None
        self.on_ice: Callable[[int, str], Any] = lambda m, c: None
        # session lifecycle + data plane (same names as WebSocketTransport)
        self.on_connect: Callable[[], Any] = lambda: None
        self.on_disconnect: Callable[[], Any] = lambda: None
        self.on_data_message: Callable[[str], Awaitable[None] | None] = lambda m: None
        # GCC taps (per RTP packet, transport-wide-cc feedback)
        self.on_video_sent: Callable[[int, float, int], None] = lambda seq, ms, size: None
        self.on_video_acked: Callable[[int, float], None] = lambda seq, ms: None
        self.on_loss: Callable[[float], None] = lambda fraction: None
        self.on_force_keyframe: Callable[[], None] = lambda: None
        # recovery-ladder taps (transport/recovery.py)
        self.on_nack: Callable[[int], None] = lambda n_seqs: None
        self.on_unrecoverable: Callable[[int], None] = lambda seq: None
        self._fec_override: int | None = None  # ladder-set, survives restarts

    @property
    def connected(self) -> bool:
        return self.pc is not None and self.pc.connected

    def set_codec(self, codec: str, h264_profile: str | None = None) -> None:
        """Pick the negotiated codec (and thereby the RTP payloader) for
        future sessions — the orchestrator calls this once the encoder
        row is built, so an AV1 encoder negotiates AV1, not H.264.
        ``h264_profile`` carries the encoder row's declared profile
        ("baseline"/"main") into the offered fmtp profile-level-id; a
        CABAC row's Main-profile SPS must match the signalling."""
        self._kw["codec"] = codec
        if h264_profile is not None:
            self._kw["h264_profile"] = h264_profile

    def set_ice_servers(self, *, stun_server=None, turn_server=None,
                        turn_username: str = "", turn_password: str = "",
                        turn_transport: str = "udp",
                        turn_tls_insecure: bool | None = None) -> None:
        """Late-bind the resolved STUN/TURN servers (the credential chain
        resolves after construction); applies to the NEXT peer.
        turn_tls_insecure=None keeps the constructor-time setting."""
        self._kw.update(stun_server=stun_server, turn_server=turn_server,
                        turn_username=turn_username, turn_password=turn_password,
                        turn_transport=turn_transport)
        if turn_tls_insecure is not None:
            self._kw["turn_tls_insecure"] = turn_tls_insecure

    # -- session lifecycle -------------------------------------------

    async def start_session(self) -> None:
        """Create the peer, gather, and emit the offer + candidates."""
        await self.stop_session()
        self._loop = asyncio.get_running_loop()
        pc = PeerConnection(loop=self._loop, **self._kw)
        self.pc = pc
        pc.on_force_keyframe = lambda: self.on_force_keyframe()
        pc.on_packet_sent = lambda seq, ms, size: self.on_video_sent(seq, ms, size)
        pc.on_packet_acked = lambda seq, ms: self.on_video_acked(seq, ms)
        pc.on_loss = lambda f: self.on_loss(f)
        pc.on_nack = lambda n: self.on_nack(n)
        pc.on_unrecoverable = lambda seq: self.on_unrecoverable(seq)
        if self._fec_override is not None:
            # a restarted session keeps the ladder's protection level
            # (RecoveryController.attach() re-applies it anyway, but the
            # peer must be ladder-armed BEFORE the answer arrives)
            pc.set_fec_percentage(self._fec_override)
        pc.on_datachannel = self._on_channel
        pc.on_datachannel_message = self._on_dc_message
        pc.on_closed = self._on_pc_closed
        offer = await pc.create_offer()
        await _maybe_await(self.on_sdp("offer", offer))
        for cand in pc.ice.local_candidates:
            await _maybe_await(self.on_ice(0, cand.to_sdp()))

    async def stop_session(self) -> None:
        if self.pc is not None:
            pc, self.pc = self.pc, None
            self._input_ch = None
            pc.close()

    def _on_pc_closed(self) -> None:
        if self.pc is not None:  # unexpected teardown (DTLS failure, BYE)
            self.pc = None
            self._input_ch = None
            _schedule(self._loop, self.on_disconnect)

    # -- signalling in ------------------------------------------------

    def set_remote_sdp(self, sdp_type: str, sdp: str) -> None:
        if self.pc is None or sdp_type != "answer":
            return
        asyncio.ensure_future(self._apply_answer(self.pc, sdp))

    async def _apply_answer(self, pc, sdp: str) -> None:
        # A malformed answer must tear the session down loudly, not leave
        # it hanging until the client's fallback timer.
        try:
            await pc.set_answer(sdp)
        except Exception:
            logger.exception("failed to apply remote answer; closing session")
            if self.pc is not pc:  # a newer session replaced this pc already
                pc.close()
                return
            await self.stop_session()
            await _maybe_await(self.on_disconnect())

    def add_remote_ice(self, mlineindex: int, candidate: str) -> None:
        if self.pc is not None and candidate:
            self.pc.add_remote_candidate(candidate)

    # -- datachannel plane -------------------------------------------

    def _on_channel(self, ch) -> None:
        logger.info("datachannel %r open (stream %d)", ch.label, ch.stream_id)
        if ch.label == "input" or self._input_ch is None:
            self._input_ch = ch
            _schedule(self._loop, self.on_connect)

    def _on_dc_message(self, ch, data: bytes, binary: bool) -> None:
        if binary:
            return  # client control plane is text
        result = self.on_data_message(data.decode("utf-8", "replace"))
        if asyncio.iscoroutine(result):
            asyncio.ensure_future(result)

    @property
    def data_channel_ready(self) -> bool:
        return self.pc is not None and self._input_ch is not None and self.pc.connected

    def send_data_channel(self, message: str) -> None:
        pc, ch, loop = self.pc, self._input_ch, self._loop
        if pc is None or ch is None or loop is None:
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            pc.send_datachannel(ch, message.encode())
        else:  # worker threads (monitors) hop onto the loop
            loop.call_soon_threadsafe(
                lambda: pc.send_datachannel(ch, message.encode()))

    def set_fec_percentage(self, percentage: int) -> None:
        """Live FEC protection level (recovery ladder): applied to the
        current peer immediately and remembered for future sessions."""
        self._fec_override = max(0, int(percentage))
        if self.pc is not None:
            self.pc.set_fec_percentage(self._fec_override)

    # -- media sinks --------------------------------------------------

    async def send_video(self, ef) -> None:
        if self.pc is None or not self.pc.connected:
            return
        self.pc.send_video(ef.au, ef.timestamp_90k,
                           idr=bool(getattr(ef, "idr", False)))
        self.frames_sent += 1
        self.bytes_sent += len(ef.au)

    async def send_audio(self, ea) -> None:
        if self.pc is None or not self.pc.connected:
            return
        self.pc.send_audio(ea.packet, ea.timestamp_48k)


def _schedule(loop: asyncio.AbstractEventLoop | None, cb: Callable[[], Any]) -> None:
    def run() -> None:
        result = cb()
        if asyncio.iscoroutine(result):
            asyncio.ensure_future(result)

    if loop is not None:
        loop.call_soon(run)
    else:  # pragma: no cover - callbacks before start_session
        run()
