"""ICE agent (RFC 8445) over one asyncio UDP socket.

Replaces the libnice half of the reference's webrtcbin
(gstwebrtc_app.py:149-160). The server is always the CONTROLLING agent
(it creates the offer, like webrtcbin's on-negotiation-needed flow) and
uses aggressive nomination: every check carries USE-CANDIDATE, and the
first validated pair is selected. One socket serves every component —
BUNDLE + rtcp-mux mean WebRTC needs exactly one.

Candidate gathering: host (one per local unicast address), server
reflexive (STUN binding through the same socket), relay (TURN
allocation, RFC 5766, long-term credentials from the existing /turn
HMAC chain). Incoming traffic demultiplexes per RFC 7983: STUN here,
everything else (DTLS records, SRTP) to `on_data`.
"""

from __future__ import annotations

import asyncio
import logging
import os
import secrets
import socket
import struct
import time
from dataclasses import dataclass, field

from selkies_tpu.transport.webrtc import stun

logger = logging.getLogger("transport.webrtc.ice")

TYPE_PREF = {"host": 126, "prflx": 110, "srflx": 100, "relay": 0}


@dataclass
class Candidate:
    foundation: str
    component: int
    priority: int
    ip: str
    port: int
    typ: str
    raddr: str | None = None
    rport: int | None = None

    def to_sdp(self) -> str:
        s = (f"candidate:{self.foundation} {self.component} udp "
             f"{self.priority} {self.ip} {self.port} typ {self.typ}")
        if self.raddr is not None:
            s += f" raddr {self.raddr} rport {self.rport}"
        return s

    @classmethod
    def from_sdp(cls, line: str) -> "Candidate":
        line = line.strip()
        if line.startswith("a="):
            line = line[2:]
        if not line.startswith("candidate:"):
            raise ValueError(f"not a candidate line: {line!r}")
        parts = line[len("candidate:"):].split()
        if len(parts) < 8 or parts[2].lower() != "udp":
            raise ValueError(f"unsupported candidate: {line!r}")
        c = cls(foundation=parts[0], component=int(parts[1]),
                priority=int(parts[3]), ip=parts[4], port=int(parts[5]),
                typ=parts[7])
        if "raddr" in parts[8:]:
            # search past the 8 fixed fields: "raddr" is a legal
            # foundation token (RFC 8839 ice-char), so scanning from 0
            # could match the wrong position
            i = parts.index("raddr", 8)
            # a malformed tail ("... raddr" truncated, or some other
            # attribute where "rport" belongs) must fail like every other
            # malformed candidate: add_remote_candidate catches ValueError
            # (this line arrives from the remote browser)
            if i + 3 >= len(parts) or parts[i + 2] != "rport":
                raise ValueError(f"malformed raddr/rport in candidate: {line!r}")
            c.raddr, c.rport = parts[i + 1], int(parts[i + 3])
        return c


def candidate_priority(typ: str, local_pref: int = 65535, component: int = 1) -> int:
    return (TYPE_PREF[typ] << 24) | (local_pref << 8) | (256 - component)


def _local_addresses() -> list[str]:
    """Local unicast IPv4 addresses, default-route first."""
    addrs: list[str] = []
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 53))  # no traffic: just routes
        addrs.append(s.getsockname()[0])
        s.close()
    except OSError:
        pass
    try:
        for info in socket.getaddrinfo(socket.gethostname(), None, socket.AF_INET):
            ip = info[4][0]
            if ip not in addrs and not ip.startswith("127."):
                addrs.append(ip)
    except socket.gaierror:
        pass
    if not addrs:
        addrs.append("127.0.0.1")
    return addrs


@dataclass
class _CheckPair:
    remote: Candidate
    relayed: bool = False  # send via the TURN allocation
    state: str = "waiting"  # waiting | inprogress | succeeded | failed
    nominated: bool = False
    last_tx: float = 0.0
    txid: bytes = b""
    attempts: int = 0


MAX_CHECK_ATTEMPTS = 20  # ~10 s at the 0.5 s pacing before a pair fails
MAX_CHECK_PAIRS = 64  # remote-candidate cap; see add_remote_candidate


class _Proto(asyncio.DatagramProtocol):
    def __init__(self, agent: "IceAgent"):
        self.agent = agent

    def datagram_received(self, data, addr):
        self.agent._on_datagram(data, addr)

    def error_received(self, exc):  # pragma: no cover - platform dependent
        logger.debug("socket error: %s", exc)


class IceAgent:
    """Controlling ICE agent for one bundled transport.

    Lifecycle: `await gather()` -> read `local_candidates` / ufrag/pwd
    into the offer -> `set_remote(ufrag, pwd)` + `add_remote_candidate`
    from the answer/trickle -> `await wait_connected()` -> `send(data)`
    and `on_data(data)` callbacks flow over the selected pair.
    """

    def __init__(self, *, stun_server: tuple[str, int] | None = None,
                 turn_server: tuple[str, int] | None = None,
                 turn_username: str = "", turn_password: str = "",
                 turn_transport: str = "udp", turn_tls_insecure: bool = False,
                 loop: asyncio.AbstractEventLoop | None = None):
        self.local_ufrag = secrets.token_urlsafe(4)
        self.local_pwd = secrets.token_urlsafe(18)
        self.remote_ufrag = ""
        self.remote_pwd = ""
        self.tiebreaker = os.urandom(8)
        self.stun_server = stun_server
        self.turn_server = turn_server
        self.turn_username = turn_username
        self.turn_password = turn_password
        if turn_transport not in ("udp", "tcp", "tls"):
            raise ValueError(f"turn_transport {turn_transport!r}")
        self.turn_transport = turn_transport
        self.turn_tls_insecure = turn_tls_insecure
        self.local_candidates: list[Candidate] = []
        self.on_data = lambda data: None
        self.on_local_candidate = lambda cand: None
        self.on_failed = lambda: None  # fires once on selected-pair death
        self._last_rx = 0.0
        self._loop = loop or asyncio.get_event_loop()
        self._transport: asyncio.DatagramTransport | None = None
        self._pairs: list[_CheckPair] = []
        self._selected: _CheckPair | None = None
        self._connected = asyncio.Event()
        self._closed = False
        self._check_task: asyncio.Task | None = None
        self._pending: dict[bytes, tuple[str, object]] = {}  # txid -> (kind, extra)
        # TURN allocation state
        self._turn_addr_cache: tuple[str, int] | None = None
        self._relay_addr: tuple[str, int] | None = None
        self._turn_realm = ""
        self._turn_nonce = b""
        self._turn_key = b""
        self._turn_perms: dict[str, float] = {}  # peer ip -> last permit time
        self._turn_last_refresh = 0.0
        # TURN over TCP/TLS (RFC 5766 §2.1 / turns:): STUN messages ride
        # a stream with their natural header framing; the relayed
        # transport stays UDP toward the peer
        self._turn_writer: asyncio.StreamWriter | None = None
        self._turn_reader_task: asyncio.Task | None = None

    # -- gathering ----------------------------------------------------

    async def gather(self, port: int = 0) -> None:
        self._transport, _ = await self._loop.create_datagram_endpoint(
            lambda: _Proto(self), local_addr=("0.0.0.0", port)
        )
        sock = self._transport.get_extra_info("socket")
        if sock is not None:
            # an IDR burst is ~100+ packets back-to-back; the default
            # ~212 KB buffers drop half of it on loopback and on real
            # hosts under load
            for opt in (socket.SO_RCVBUF, socket.SO_SNDBUF):
                try:
                    sock.setsockopt(socket.SOL_SOCKET, opt, 4 << 20)
                except OSError:
                    pass
        lport = self._transport.get_extra_info("sockname")[1]
        for i, ip in enumerate(_local_addresses()):
            cand = Candidate(
                foundation=str(i + 1), component=1,
                priority=candidate_priority("host", 65535 - i),
                ip=ip, port=lport, typ="host",
            )
            self.local_candidates.append(cand)
        if self.stun_server:
            try:
                await self._gather_srflx()
            except (asyncio.TimeoutError, OSError) as exc:
                logger.warning("srflx gathering failed: %s", exc)
        if self.turn_server and self.turn_username:
            try:
                if self.turn_transport != "udp":
                    await self._turn_connect()
                await self._gather_relay()
            except (asyncio.TimeoutError, OSError, stun.StunError) as exc:
                logger.warning("TURN allocation failed (%s): %s",
                               self.turn_transport, exc)
        for c in self.local_candidates:
            self.on_local_candidate(c)

    async def _request(self, msg: stun.StunMessage, addr: tuple[str, int],
                       kind: str, timeout: float = 3.0,
                       integrity_key: bytes | None = None,
                       send=None) -> stun.StunMessage:
        """Send a request and await its (error-)response, with retries."""
        fut = self._loop.create_future()
        self._pending[msg.txid] = (kind, fut)
        wire = msg.serialize(integrity_key=integrity_key)
        sendfn = send or (lambda w, a: self._transport.sendto(w, a))
        try:
            if send is not None and self._turn_writer is not None:
                # reliable stream transport: RFC 5389 §7.2.2 — send once,
                # no retransmit schedule (a duplicate authenticated
                # ALLOCATE can draw 437 Allocation Mismatch)
                sendfn(wire, addr)
                return await asyncio.wait_for(asyncio.shield(fut), timeout)
            for backoff in (0.2, 0.4, 0.8, 1.6):
                sendfn(wire, addr)
                try:
                    return await asyncio.wait_for(
                        asyncio.shield(fut), min(backoff, timeout)
                    )
                except asyncio.TimeoutError:
                    timeout -= backoff
                    if timeout <= 0:
                        raise
            raise asyncio.TimeoutError
        finally:
            self._pending.pop(msg.txid, None)

    async def _gather_srflx(self) -> None:
        addr = await self._resolve(self.stun_server)
        req = stun.StunMessage(method=stun.BINDING, cls=stun.REQUEST)
        resp = await self._request(req, addr, "srflx")
        xma = resp.get(stun.ATTR_XOR_MAPPED_ADDRESS)
        if xma is None:
            return
        ip, port = stun.unxor_address(xma, resp.txid)
        base = self.local_candidates[0]
        if any(c.ip == ip and c.port == port for c in self.local_candidates):
            return  # not behind NAT: srflx duplicates host
        self.local_candidates.append(Candidate(
            foundation="srflx1", component=1,
            priority=candidate_priority("srflx"),
            ip=ip, port=port, typ="srflx", raddr=base.ip, rport=base.port,
        ))

    async def _resolve(self, server: tuple[str, int]) -> tuple[str, int]:
        infos = await self._loop.getaddrinfo(
            server[0], server[1], family=socket.AF_INET, type=socket.SOCK_DGRAM
        )
        return infos[0][4]

    # -- TURN client (RFC 5766, long-term credentials; udp/tcp/tls) ---

    async def _turn_connect(self) -> None:
        """Open the turns://-style stream to the TURN server and start
        the reader that feeds its STUN traffic into _on_stun."""
        import ssl as _ssl

        host, port = self.turn_server
        ctx = None
        if self.turn_transport == "tls":
            ctx = _ssl.create_default_context()
            if self.turn_tls_insecure:
                ctx.check_hostname = False
                ctx.verify_mode = _ssl.CERT_NONE
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port, ssl=ctx), 10.0
        )
        self._turn_writer = writer
        self._turn_reader_task = self._loop.create_task(self._turn_read_loop(reader))

    async def _turn_read_loop(self, reader: asyncio.StreamReader) -> None:
        """STUN-over-stream framing: each message is its 20-byte header
        plus the (already 4-aligned) attribute length it declares."""
        addr = self.turn_server
        try:
            while not self._closed:
                hdr = await reader.readexactly(20)
                alen = struct.unpack("!H", hdr[2:4])[0]
                wire = hdr + (await reader.readexactly(alen) if alen else b"")
                try:
                    msg = stun.StunMessage.parse(wire)
                except stun.StunError:
                    continue
                self._on_stun(msg, wire, addr)
        except (asyncio.IncompleteReadError, ConnectionError, asyncio.CancelledError):
            pass
        finally:
            # stream died: the allocation died with it. Tear the relay
            # path down completely so the check loop stops burning 3 s
            # timeouts on refresh/permit requests that can never be sent
            # (direct pairs and the consent timer take it from here).
            if not self._closed and self._turn_writer is not None:
                logger.warning("TURN %s stream lost; relay path down",
                               self.turn_transport)
            self._turn_writer = None
            self._relay_addr = None
            self._turn_perms.clear()
            for pair in self._pairs:
                if pair.relayed:
                    pair.state = "failed"

    # relayed-media backpressure cap: a stalled TCP/TLS path to the TURN
    # server must DROP packets (like UDP would), not buffer megabits/s
    # until the process OOMs — writes come from sync code, so asyncio's
    # drain() flow control can't engage
    TURN_STREAM_BUFFER_CAP = 4 << 20

    def _turn_send_wire(self, wire: bytes, addr) -> None:
        w = self._turn_writer
        if w is not None:
            transport = w.transport
            if transport.is_closing() or (
                transport.get_write_buffer_size() + len(wire)
                > self.TURN_STREAM_BUFFER_CAP
            ):
                return  # drop under backpressure / during teardown
            w.write(wire)
        elif self.turn_transport == "udp":
            self._transport.sendto(wire, addr)
        # stream mode with a dead writer: drop — UDP datagrams to a
        # TCP/TLS TURN port are never valid

    async def _turn_request(self, method: int, attrs: list[tuple[int, bytes]],
                            kind: str) -> stun.StunMessage:
        addr = await self._resolve(self.turn_server) \
            if self._turn_writer is None else self.turn_server
        req = stun.StunMessage(method=method, cls=stun.REQUEST)
        for a, v in attrs:
            req.add(a, v)
        if self._turn_nonce:
            req.add(stun.ATTR_USERNAME, self.turn_username.encode())
            req.add(stun.ATTR_REALM, self._turn_realm.encode())
            req.add(stun.ATTR_NONCE, self._turn_nonce)
            return await self._request(req, addr, kind,
                                       integrity_key=self._turn_key,
                                       send=self._turn_send_wire)
        return await self._request(req, addr, kind, send=self._turn_send_wire)

    async def _gather_relay(self) -> None:
        transport_udp = struct.pack("!BBH", 17, 0, 0)
        attrs = [(stun.ATTR_REQUESTED_TRANSPORT, transport_udp)]
        resp = await self._turn_request(stun.ALLOCATE, attrs, "allocate")
        if resp.cls == stun.ERROR_RESPONSE:
            err = stun.error_code(resp)
            if err and err[0] == 401 and not self._turn_nonce:
                self._turn_realm = (resp.get(stun.ATTR_REALM) or b"").decode()
                self._turn_nonce = resp.get(stun.ATTR_NONCE) or b""
                self._turn_key = stun.long_term_key(
                    self.turn_username, self._turn_realm, self.turn_password
                )
                resp = await self._turn_request(stun.ALLOCATE, attrs, "allocate")
            if resp.cls == stun.ERROR_RESPONSE:
                raise stun.StunError(f"TURN allocate failed: {stun.error_code(resp)}")
        xra = resp.get(stun.ATTR_XOR_RELAYED_ADDRESS)
        if xra is None:
            raise stun.StunError("TURN allocate: no relayed address")
        ip, port = stun.unxor_address(xra, resp.txid)
        self._relay_addr = (ip, port)
        self._turn_last_refresh = time.monotonic()
        base = self.local_candidates[0]
        self.local_candidates.append(Candidate(
            foundation="relay1", component=1,
            priority=candidate_priority("relay"),
            ip=ip, port=port, typ="relay", raddr=base.ip, rport=base.port,
        ))

    # RFC 5766: permissions live 300 s, allocations default 600 s —
    # refresh well inside both or relayed sessions freeze mid-stream
    TURN_PERM_REFRESH = 180.0
    TURN_ALLOC_REFRESH = 240.0

    async def _turn_permit(self, peer_ip: str, force: bool = False) -> None:
        now = time.monotonic()
        if self._relay_addr is None:
            return
        if not force and now - self._turn_perms.get(peer_ip, -1e9) < self.TURN_PERM_REFRESH:
            return
        self._turn_perms[peer_ip] = now
        try:
            await self._turn_request(
                stun.CREATE_PERMISSION,
                [(stun.ATTR_XOR_PEER_ADDRESS,
                  stun.xor_address((peer_ip, 0), b"\x00" * 12))],
                "permission",
            )
        except (asyncio.TimeoutError, stun.StunError) as exc:
            logger.warning("TURN permission for %s failed: %s", peer_ip, exc)
            self._turn_perms.pop(peer_ip, None)

    async def _turn_refresh(self) -> None:
        try:
            await self._turn_request(
                stun.REFRESH, [(stun.ATTR_LIFETIME, struct.pack("!I", 600))],
                "refresh",
            )
        except (asyncio.TimeoutError, stun.StunError) as exc:
            logger.warning("TURN refresh failed: %s", exc)

    def _turn_send(self, data: bytes, peer: tuple[str, int]) -> None:
        ind = stun.StunMessage(method=stun.SEND, cls=stun.INDICATION)
        ind.add(stun.ATTR_XOR_PEER_ADDRESS, stun.xor_address(peer, ind.txid))
        ind.add(stun.ATTR_DATA, data)
        self._turn_send_wire(ind.serialize(fingerprint=False),
                             self._turn_addr_cache)

    # -- checks -------------------------------------------------------

    def set_remote(self, ufrag: str, pwd: str) -> None:
        self.remote_ufrag = ufrag
        self.remote_pwd = pwd
        if self._check_task is None:
            self._check_task = self._loop.create_task(self._check_loop())

    def add_remote_candidate(self, cand: Candidate | str) -> None:
        if isinstance(cand, str):
            try:
                cand = Candidate.from_sdp(cand)
            except ValueError as exc:
                logger.debug("ignoring candidate: %s", exc)
                return
        if cand.component != 1:
            return  # BUNDLE: single component
        if any(p.remote.ip == cand.ip and p.remote.port == cand.port
               for p in self._pairs):
            return
        # candidate lines arrive from the remote peer over signalling and
        # every accepted one makes this host send STUN checks to the
        # named address: an unbounded flood is both a memory leak and a
        # traffic-reflection primitive (the classic "ICE as port scanner")
        # — real browsers gather far fewer (libwebrtc stays under ~32).
        # A relayed allocation doubles the appends below, so reserve both
        # slots up front or the cap could be exceeded by one.
        need = 2 if self._relay_addr is not None else 1
        if len(self._pairs) + need > MAX_CHECK_PAIRS:
            logger.warning("remote candidate limit reached; ignoring %s:%d",
                           cand.ip, cand.port)
            return
        self._pairs.append(_CheckPair(remote=cand))
        if self._relay_addr is not None:
            self._pairs.append(_CheckPair(remote=cand, relayed=True))

    async def _check_loop(self) -> None:
        if self.turn_server:
            try:
                self._turn_addr_cache = await self._resolve(self.turn_server)
            except OSError:
                self._turn_addr_cache = None
        while not self._closed:
            now = time.monotonic()
            for pair in list(self._pairs):
                if pair.state in ("succeeded", "failed"):
                    continue
                if now - pair.last_tx < 0.5:
                    continue
                await self._send_check(pair)
            # keepalive on the selected pair; a browser that crashes or
            # loses its network never sends BYE, so unanswered keepalives
            # are the ONLY liveness signal (20 s ≈ 4 missed keepalives)
            sel = self._selected
            if sel is not None and now - sel.last_tx > 5.0:
                await self._send_check(sel)
            if sel is not None and self._last_rx and now - self._last_rx > 20.0:
                logger.warning("ICE consent expired: no check response in 20 s")
                self._selected = None
                self._connected.clear()
                self._last_rx = 0.0
                try:
                    self.on_failed()
                except Exception:  # pragma: no cover - user callback
                    logger.exception("on_failed callback raised")
            # keep the TURN allocation + the active peer's permission alive
            if self._relay_addr is not None:
                if now - self._turn_last_refresh > self.TURN_ALLOC_REFRESH:
                    self._turn_last_refresh = now
                    await self._turn_refresh()
                if sel is not None and sel.relayed:
                    await self._turn_permit(sel.remote.ip)
            await asyncio.sleep(0.05 if self._selected is None else 1.0)

    async def _send_check(self, pair: _CheckPair) -> None:
        if pair.relayed:
            await self._turn_permit(pair.remote.ip)
        # drop the previous outstanding check for this pair: without this
        # an unreachable candidate leaks a _pending entry per attempt
        self._pending.pop(pair.txid, None)
        pair.attempts += 1
        if pair.attempts > MAX_CHECK_ATTEMPTS and pair is not self._selected:
            pair.state = "failed"
            return
        req = stun.StunMessage(method=stun.BINDING, cls=stun.REQUEST)
        req.add(stun.ATTR_USERNAME,
                f"{self.remote_ufrag}:{self.local_ufrag}".encode())
        req.add(stun.ATTR_ICE_CONTROLLING, self.tiebreaker)
        req.add(stun.ATTR_USE_CANDIDATE, b"")  # aggressive nomination
        req.add(stun.ATTR_PRIORITY,
                struct.pack("!I", candidate_priority("prflx")))
        pair.txid = req.txid
        pair.state = "inprogress"
        pair.last_tx = time.monotonic()
        self._pending[req.txid] = ("check", pair)
        wire = req.serialize(integrity_key=self.remote_pwd.encode())
        self._send_raw(wire, pair)

    def _send_raw(self, data: bytes, pair: _CheckPair) -> None:
        if pair.relayed and (self._turn_addr_cache or self._turn_writer):
            self._turn_send(data, (pair.remote.ip, pair.remote.port))
        else:
            self._transport.sendto(data, (pair.remote.ip, pair.remote.port))

    # -- inbound ------------------------------------------------------

    def _on_datagram(self, data: bytes, addr: tuple[str, int]) -> None:
        if stun.is_stun(data):
            try:
                msg = stun.StunMessage.parse(data)
            except stun.StunError:
                return
            self._on_stun(msg, data, addr)
            return
        self.on_data(data)

    def _on_stun(self, msg: stun.StunMessage, wire: bytes,
                 addr: tuple[str, int]) -> None:
        if msg.cls in (stun.RESPONSE, stun.ERROR_RESPONSE):
            pending = self._pending.get(msg.txid)
            if pending is None:
                return
            kind, extra = pending
            if kind == "check":
                # verify integrity BEFORE consuming the txid: a forged
                # response must not eat the pending slot and cause the
                # peer's genuine signed response to be dropped
                if not msg.check_integrity(self.remote_pwd.encode(), wire):
                    logger.debug("check response failed integrity; ignoring")
                    return
                self._pending.pop(msg.txid, None)
                self._on_check_response(msg, extra)
            else:
                fut = extra
                if not fut.done():
                    fut.set_result(msg)
            return
        if msg.method == stun.DATA and msg.cls == stun.INDICATION:
            inner = msg.get(stun.ATTR_DATA)
            if inner is not None:
                if stun.is_stun(inner):
                    try:
                        self._on_stun(stun.StunMessage.parse(inner), inner, addr)
                    except stun.StunError:
                        pass
                else:
                    self.on_data(inner)
            return
        if msg.method == stun.BINDING and msg.cls == stun.REQUEST:
            self._on_binding_request(msg, wire, addr)

    def _on_binding_request(self, msg: stun.StunMessage, wire: bytes,
                            addr: tuple[str, int]) -> None:
        if not msg.check_integrity(self.local_pwd.encode(), wire):
            resp = stun.StunMessage(method=stun.BINDING,
                                    cls=stun.ERROR_RESPONSE, txid=msg.txid)
            resp.add(stun.ATTR_ERROR_CODE, stun.make_error(401, "Unauthorized"))
            self._transport.sendto(resp.serialize(), addr)
            return
        self._last_rx = time.monotonic()  # peer consent checks count too
        resp = stun.StunMessage(method=stun.BINDING, cls=stun.RESPONSE,
                                txid=msg.txid)
        resp.add(stun.ATTR_XOR_MAPPED_ADDRESS, stun.xor_address(addr, msg.txid))
        self._transport.sendto(
            resp.serialize(integrity_key=self.local_pwd.encode()), addr
        )
        # peer-reflexive discovery: learn pairs we were never told about.
        # Same cap as add_remote_candidate — the peer knows local_pwd, so
        # binding requests from thousands of source ports would otherwise
        # grow _pairs (and the 0.5 s check traffic) without bound.
        if (len(self._pairs) < MAX_CHECK_PAIRS
                and not any(p.remote.ip == addr[0] and p.remote.port == addr[1]
                            for p in self._pairs)):
            self._pairs.append(_CheckPair(remote=Candidate(
                foundation="prflx", component=1,
                priority=candidate_priority("prflx"),
                ip=addr[0], port=addr[1], typ="prflx",
            )))

    @staticmethod
    def _pair_rank(pair: _CheckPair) -> tuple:
        # direct beats relayed regardless of remote candidate priority
        return (not pair.relayed, pair.remote.priority)

    def _on_check_response(self, msg: stun.StunMessage, pair: _CheckPair) -> None:
        # Integrity already verified in _on_stun (RFC 8445 §7.2.5.2.2),
        # before the txid was consumed.
        if msg.cls == stun.ERROR_RESPONSE:
            err = stun.error_code(msg)
            logger.debug("check failed: %s", err)
            pair.state = "failed" if not (err and err[0] == 487) else "waiting"
            return
        pair.state = "succeeded"
        pair.nominated = True
        pair.attempts = 0
        self._last_rx = time.monotonic()
        if self._selected is None or self._pair_rank(pair) > self._pair_rank(self._selected):
            logger.info("ICE %s via %s:%d (%s%s)",
                        "connected" if self._selected is None else "path upgraded",
                        pair.remote.ip, pair.remote.port, pair.remote.typ,
                        " relayed" if pair.relayed else "")
            self._selected = pair
            self._connected.set()

    # -- data plane ---------------------------------------------------

    async def wait_connected(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._connected.wait(), timeout)

    @property
    def connected(self) -> bool:
        return self._connected.is_set()

    def send(self, data: bytes) -> None:
        sel = self._selected
        if sel is None:
            raise ConnectionError("ICE not connected")
        self._send_raw(data, sel)

    def close(self) -> None:
        self._closed = True
        if self._check_task is not None:
            self._check_task.cancel()
        if self._turn_reader_task is not None:
            self._turn_reader_task.cancel()
        if self._turn_writer is not None:
            try:
                self._turn_writer.close()
            except Exception:
                pass
            self._turn_writer = None
        if self._transport is not None:
            self._transport.close()
