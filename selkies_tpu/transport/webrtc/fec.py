"""RED (RFC 2198) + ULP FEC (RFC 5109) for the video stream.

The reference turns this on via webrtcbin's fec-percentage=20
(gstwebrtc_app.py:996-1000): one XOR parity packet protects each group
of media packets so a single loss per group is recovered without a
round trip — what makes 60 fps survivable on real networks, alongside
NACK retransmission for burstier loss.

Wire format mirrors what browsers implement for video red/ulpfec:
media packets go out RED-encapsulated (one-byte RED header, F=0,
block PT = the video PT); every Nth packet a FEC packet follows on the
SAME ssrc/sequence space, RED-encapsulated with block PT = ulpfec,
carrying a level-0 ULP header whose 16-bit mask covers the group.

`protect_group`/`recover` are symmetric so the loopback tests prove the
XOR algebra against packet drops; in production the recovery half runs
in the browser.
"""

from __future__ import annotations

import struct

RED_HEADER_F0 = 0  # final RED block: 1 byte, F bit clear


def red_wrap(block_pt: int, payload: bytes) -> bytes:
    """Single-block RED encapsulation (RFC 2198 §4: F=0, then the data)."""
    return bytes([block_pt & 0x7F]) + payload


def red_unwrap(payload: bytes) -> tuple[int, bytes]:
    """-> (block_pt, inner payload). Only the single-block form is used."""
    if not payload or payload[0] & 0x80:
        raise ValueError("multi-block RED not supported")
    return payload[0] & 0x7F, payload[1:]


def _rtp_fields(pkt: bytes) -> tuple[int, int, int, int, bytes]:
    """(p_x_cc_m_pt word bits we protect, seq, ts, length, payload)."""
    b0, b1, seq = pkt[0], pkt[1], struct.unpack("!H", pkt[2:4])[0]
    ts = struct.unpack("!I", pkt[4:8])[0]
    return b0, b1, seq, ts, pkt[12:]


def build_fec(media_packets: list[bytes]) -> bytes:
    """ULP FEC payload (RFC 5109 §7.3, level 0, 16-bit mask) protecting
    `media_packets` (full RTP packets, consecutive seqs, same ssrc).
    Returns the FEC payload (to be RED-wrapped and sent as RTP)."""
    if not 1 <= len(media_packets) <= 16:
        raise ValueError("a FEC group protects 1..16 packets")
    base_seq = struct.unpack("!H", media_packets[0][2:4])[0]
    prot_len = max(len(p) - 12 for p in media_packets)
    # recovery fields: XOR over the protected packets
    r_b0 = 0
    r_b1 = 0
    r_ts = 0
    r_len = 0
    mask = 0
    payload_xor = bytearray(prot_len)
    for p in media_packets:
        b0, b1, seq, ts, payload = _rtp_fields(p)
        offset = (seq - base_seq) & 0xFFFF
        if offset >= 16:
            raise ValueError("seq span exceeds the 16-bit mask")
        mask |= 1 << (15 - offset)
        r_b0 ^= b0 & 0x3F          # P, X, CC bits (version excluded)
        r_b1 ^= b1                 # M + PT
        r_ts ^= ts
        r_len ^= len(payload)
        for i, byte in enumerate(payload):
            payload_xor[i] ^= byte
    hdr = struct.pack(
        "!BBHIH", r_b0 & 0x3F, r_b1, base_seq, r_ts, r_len
    )  # E=0,L=0 in the first byte's top bits (they are zero here)
    level = struct.pack("!HH", prot_len, mask)
    return hdr + level + bytes(payload_xor)


def recover(fec_payload: bytes, received: dict[int, bytes],
            ssrc: int) -> bytes | None:
    """Rebuild the single missing packet of a FEC group (None if 0 or >1
    are missing). `received`: seq -> full RTP packet."""
    if len(fec_payload) < 14:
        raise ValueError("short FEC payload")
    r_b0, r_b1, base_seq, r_ts, r_len = struct.unpack("!BBHIH", fec_payload[:10])
    prot_len, mask = struct.unpack("!HH", fec_payload[10:14])
    payload_xor = bytearray(fec_payload[14 : 14 + prot_len])
    missing = []
    for off in range(16):
        if not mask & (1 << (15 - off)):
            continue
        seq = (base_seq + off) & 0xFFFF
        pkt = received.get(seq)
        if pkt is None:
            missing.append(seq)
            continue
        b0, b1, _, ts, payload = _rtp_fields(pkt)
        r_b0 ^= b0 & 0x3F
        r_b1 ^= b1
        r_ts ^= ts
        r_len ^= len(payload)
        for i, byte in enumerate(payload[:prot_len]):
            payload_xor[i] ^= byte
    if len(missing) != 1:
        return None
    seq = missing[0]
    hdr = bytes([0x80 | (r_b0 & 0x3F), r_b1]) + struct.pack(
        "!HII", seq, r_ts & 0xFFFFFFFF, ssrc
    )
    return hdr + bytes(payload_xor[:r_len])


class FecEncoder:
    """Groups outgoing video packets and emits parity per the configured
    percentage (reference fec-percentage=20 -> one FEC per 5 packets).

    The percentage is live (:meth:`set_percentage`): the recovery ladder
    (transport/recovery.py) scales it with the measured loss fraction,
    down to 0 — at 0 the encoder stays armed (media keeps its negotiated
    RED encapsulation) but emits no parity at all."""

    def __init__(self, percentage: int = 20):
        self.percentage = int(percentage)
        self.group_size = self._group_size(self.percentage)
        self._group: list[bytes] = []

    @staticmethod
    def _group_size(percentage: int) -> int:
        if percentage <= 0:
            return 0  # protection off: push/flush emit nothing
        return max(1, min(16, round(100 / percentage)))

    def set_percentage(self, percentage: int) -> None:
        """Live protection-level change. Lowering to 0 drops the pending
        group (those packets still have the RTX ring); any other change
        just re-sizes the group — the pending packets emit under the new
        size at the next push/flush, never spanning the old and new
        grouping."""
        pct = int(percentage)
        if pct == self.percentage:
            return
        self.percentage = pct
        self.group_size = self._group_size(pct)
        if self.group_size == 0:
            self._group.clear()

    def begin_au(self, keyframe: bool = False) -> bytes | None:
        """Access-unit boundary: before a KEYFRAME, flush the pending
        group so a protection row never spans an IDR — a recovered
        pre-IDR packet is useless after the refresh, so parity crossing
        the boundary would protect nothing. Returns leftover parity for
        the caller to send (sequenced before the keyframe's packets).
        Plain AU boundaries need no flush here because send_video
        flushes per frame anyway; this keeps the IDR invariant even if
        that per-frame flush is ever relaxed."""
        if not keyframe or not self._group:
            return None
        group, self._group = self._group, []
        return build_fec(group)

    def push(self, media_packet: bytes) -> bytes | None:
        """Track a sent media packet; returns a FEC payload when the
        group fills (caller wraps it in RED + RTP and sends)."""
        if self.group_size == 0:
            return None
        self._group.append(media_packet)
        if len(self._group) < self.group_size:
            return None
        group, self._group = self._group, []
        return build_fec(group)

    def flush(self) -> bytes | None:
        """End-of-frame: emit parity for a partial group (keeps loss
        recovery latency bounded to one frame; a 1-packet group's parity
        is a valid XOR-identity duplicate and still protects the frame's
        marker packet)."""
        if self.group_size == 0 or not self._group:
            return None
        group, self._group = self._group, []
        return build_fec(group)
