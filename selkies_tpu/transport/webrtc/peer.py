"""PeerConnection: ICE + DTLS-SRTP + RTP/RTCP + SCTP datachannels.

The framework's counterpart of the reference's webrtcbin wiring
(gstwebrtc_app.py:149-196 build, :1581-1636 offer flow): the server
creates the offer, the browser answers active (so DTLS runs in server
role here), media flows sendonly over SRTP, input/control rides DCEP
data channels, and RTCP feedback drives the same knobs the framework
already exposes (force_keyframe, GCC bitrate, NACK retransmit buffer).
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import struct
import time

from selkies_tpu.monitoring.telemetry import telemetry
from selkies_tpu.transport.rtp import H264Payloader, OpusPayloader, RtpPacket
from selkies_tpu.transport.webrtc import fec, rtcp, sdp
from selkies_tpu.transport.webrtc.dtls import DtlsEndpoint, is_dtls, make_certificate
from selkies_tpu.transport.webrtc.ice import IceAgent
from selkies_tpu.transport.webrtc.sctp import SctpAssociation
from selkies_tpu.transport.webrtc.srtp import SrtpError, SrtpSession, session_pair

logger = logging.getLogger("transport.webrtc.peer")

RTX_BUFFER = 512  # packets kept for NACK retransmission (~1.7 s at 300 pps)
# NACK-retransmit abuse bounds: a small RTCP compound can request
# hundreds of full-MTU retransmits (amplification), and re-NACKing the
# same seq in a tight loop replays it forever. Legit recovery stays far
# below both bounds (8 Mbit/s at 20% burst loss ≈ 0.2 MB/s of rtx).
RTX_SEQ_FLOOR = 0.04       # s between retransmits of the SAME seq (~RTT/2)
RTX_BUDGET_BYTES = 1_000_000  # token bucket: max rtx bytes per second


class PeerConnection:
    """One browser session's transport. Lifecycle:

        pc = PeerConnection(...)
        offer = await pc.create_offer()        # gathers ICE
        ... signalling: send offer, receive answer + trickle candidates
        await pc.set_answer(answer_sdp)
        pc.add_remote_candidate(line)
        await pc.wait_connected()              # ICE + DTLS + SRTP ready
        pc.send_video(au_bytes, ts_ms); pc.send_audio(opus, ts)
    """

    # min spacing between PLI/FIR-honored keyframes (libwebrtc applies
    # the same ~300 ms floor); see _on_srtcp
    KEYFRAME_MIN_INTERVAL = 0.3

    def __init__(self, *, codec: str = "h264", audio: bool = True,
                 h264_profile: str = "baseline",
                 fec_percentage: int = 20,
                 stun_server=None, turn_server=None,
                 turn_username: str = "", turn_password: str = "",
                 turn_transport: str = "udp",
                 turn_tls_insecure: bool = False,
                 loop: asyncio.AbstractEventLoop | None = None):
        self.codec = codec
        # "baseline" or "main" — the CABAC entropy backend's streams
        # declare Main in the SPS, so the offered fmtp must say so too
        self.h264_profile = h264_profile
        self.audio = audio
        self._loop = loop or asyncio.get_event_loop()
        self.ice = IceAgent(stun_server=stun_server, turn_server=turn_server,
                            turn_username=turn_username,
                            turn_password=turn_password,
                            turn_transport=turn_transport,
                            turn_tls_insecure=turn_tls_insecure, loop=self._loop)
        self.ice.on_data = self._on_transport_data
        self.cert_der, self.key_der, self.fingerprint = make_certificate()
        self.dtls: DtlsEndpoint | None = None
        self.srtp: SrtpSession | None = None
        self.sctp: SctpAssociation | None = None
        self.video_ssrc = struct.unpack("!I", secrets.token_bytes(4))[0] | 1
        self.audio_ssrc = (self.video_ssrc + 1) & 0xFFFFFFFF
        if codec == "av1":
            # rtpav1pay equivalent (reference gstwebrtc_app.py:917-938)
            from selkies_tpu.transport.rtp_av1 import Av1Payloader

            self.video_pay = Av1Payloader(
                payload_type=sdp.VIDEO_PT, ssrc=self.video_ssrc)
        elif codec == "h265":
            # rtph265pay equivalent (reference gstwebrtc_app.py:848-871)
            from selkies_tpu.transport.rtp_h265 import H265Payloader

            self.video_pay = H265Payloader(
                payload_type=sdp.VIDEO_PT, ssrc=self.video_ssrc)
        elif codec in ("vp8", "vp9"):
            # rtpvp8pay/rtpvp9pay equivalents (gstwebrtc_app.py:873-915)
            from selkies_tpu.transport.rtp_vpx import Vp8Payloader, Vp9Payloader

            cls = Vp8Payloader if codec == "vp8" else Vp9Payloader
            self.video_pay = cls(payload_type=sdp.VIDEO_PT, ssrc=self.video_ssrc)
        else:
            self.video_pay = H264Payloader(
                payload_type=sdp.VIDEO_PT, ssrc=self.video_ssrc)
        self.audio_pay = OpusPayloader(
            payload_type=sdp.AUDIO_PT, ssrc=self.audio_ssrc)
        self._remote: sdp.RemoteDescription | None = None
        # RED/ULP FEC (reference fec-percentage=20): armed when the
        # answer accepts both payload types
        self.fec_percentage = int(fec_percentage)
        self._fec: fec.FecEncoder | None = None
        self._fec_live = False  # set_fec_percentage called (recovery ladder)
        self._red_pt = sdp.RED_PT
        self._ulpfec_pt = sdp.ULPFEC_PT
        # injectable clock: the rtx floors/budget below are wall-time
        # rates, and the impairment bench + recovery tests drive this
        # peer on a simulated timeline
        self._clock = time.monotonic
        # net:* impairment shim (transport/impair.py) — None unless a
        # SELKIES_FAULTS net rule is configured, so the clean path pays
        # one attribute load per send
        from selkies_tpu.transport.impair import NetImpairment

        self._impair = NetImpairment.from_faults()
        self._connected = asyncio.Event()
        self._closed = False
        # TWCC send state
        self._twcc_seq = 0
        self._twcc_id = sdp.TWCC_EXT_ID
        self._playout_delay_id: int | None = None
        # NACK retransmit ring
        self._rtx: dict[int, bytes] = {}
        # RTCP sender stats
        self._vid_packets = 0
        self._vid_octets = 0
        self._aud_packets = 0
        self._aud_octets = 0
        self._last_video_ts = 0
        self._tick_task: asyncio.Task | None = None
        # -inf so the FIRST PLI is always honored regardless of the
        # monotonic clock's epoch
        self._last_pli_keyframe = float("-inf")
        self._rtx_last: dict[int, float] = {}   # seq -> last retransmit time
        self._rtx_tokens = float(RTX_BUDGET_BYTES)
        self._rtx_refill_at = self._clock()
        # control surface callbacks
        self.on_force_keyframe = lambda: None
        self.on_packet_sent = lambda seq, send_ms, size: None   # GCC
        self.on_packet_acked = lambda seq, recv_ms: None        # GCC
        self.on_loss = lambda fraction: None                    # GCC
        self.on_nack = lambda n_seqs: None            # recovery ladder
        self.on_unrecoverable = lambda seq: None      # gap past the ring
        self.on_datachannel = lambda ch: None
        self.on_datachannel_message = lambda ch, data, binary: None
        self.on_connected = lambda: None
        self.on_closed = lambda: None

    # -- negotiation --------------------------------------------------

    async def create_offer(self) -> str:
        await self.ice.gather()
        return sdp.build_offer(
            ice_ufrag=self.ice.local_ufrag, ice_pwd=self.ice.local_pwd,
            fingerprint=self.fingerprint, video_ssrc=self.video_ssrc,
            audio_ssrc=self.audio_ssrc, codec=self.codec, audio=self.audio,
            h264_profile=self.h264_profile,
        )

    async def set_answer(self, answer_sdp: str) -> None:
        r = sdp.parse_answer(answer_sdp, prefer=self.codec)
        # An answer without ICE credentials can never connect, and one
        # without a DTLS fingerprint could never be authenticated: fail
        # loudly now (the transport turns this into a clean teardown)
        # instead of hanging the session until the client's retry timer.
        missing = [name for name, val in (("ice-ufrag", r.ice_ufrag),
                                          ("ice-pwd", r.ice_pwd),
                                          ("fingerprint", r.fingerprint)) if not val]
        if missing:
            raise ValueError(f"SDP answer missing required attributes: {missing}")
        if r.video_codec is not None and r.video_codec != self.codec:
            # the browser refused the offered codec (e.g. H.265 in a
            # browser without HEVC WebRTC support): streaming our codec
            # into its decoder would yield a silently black session —
            # fail now so the orchestrator can tear down / fall back
            raise ValueError(
                f"browser answered codec {r.video_codec!r}, offer was "
                f"{self.codec!r}; refusing mismatched media session")
        if r.video_pt is None and "m=video" in answer_sdp:
            # rejected video m-line (JSEP port 0 — parse_answer ignores
            # rtpmaps echoed inside a rejected section — or no rtpmap at
            # all): same black session by a different route
            reason = ("rejected the video section (port 0)"
                      if r.video_rejected else "carries no video codec")
            raise ValueError(
                f"answer {reason} for offered {self.codec!r}; "
                "refusing media session")
        if r.video_pt is not None:
            # pay with the PT the answer actually negotiated, not the
            # static offer PT or any payloader-class default (browsers
            # normally echo the offer, but RFC 3264 lets the answer
            # re-number — tests/test_rtp_pt.py regression-tests every
            # codec payloader through this path)
            self.video_pay.payload_type = r.video_pt
        if r.audio_pt is not None:
            self.audio_pay.payload_type = r.audio_pt
        self._remote = r
        if r.twcc_id is not None:
            self._twcc_id = r.twcc_id
        self._playout_delay_id = r.playout_delay_id
        if ((self.fec_percentage > 0 or self._fec_live)
                and r.red_pt is not None and r.ulpfec_pt is not None):
            # armed even at a live 0 % (recovery ladder): media keeps its
            # negotiated RED encapsulation so a later loss-driven ramp-up
            # needs no renegotiation — only the parity emission gates
            self._fec = fec.FecEncoder(self.fec_percentage)
            self._red_pt, self._ulpfec_pt = r.red_pt, r.ulpfec_pt
        # browser answers a=setup:active -> we are the DTLS server
        dtls_server = r.setup != "passive"
        self.dtls = DtlsEndpoint(
            is_server=dtls_server, cert_der=self.cert_der,
            key_der=self.key_der, peer_fingerprint=r.fingerprint or None,
        )
        self.sctp = SctpAssociation(is_client=not dtls_server,
                                    port=r.sctp_port)
        self.sctp.on_channel_open = lambda ch: self.on_datachannel(ch)
        self.sctp.on_message = (
            lambda ch, d, b: self.on_datachannel_message(ch, d, b))
        self.ice.set_remote(r.ice_ufrag, r.ice_pwd)
        self.ice.on_failed = self.close  # dead peer: tear down + notify
        for cand in r.candidates:
            self.ice.add_remote_candidate(cand)
        self._tick_task = self._loop.create_task(self._tick_loop())
        if not dtls_server:
            # RFC 5763 allows a=setup:passive answers: then WE initiate
            # DTLS once a pair is validated (browsers normally answer
            # active, where the ClientHello arrives from the peer)
            self._loop.create_task(self._kick_client_dtls())

    async def _kick_client_dtls(self) -> None:
        try:
            await self.ice.wait_connected()
        except asyncio.TimeoutError:
            return
        if self.dtls is not None and not self.dtls.handshake_complete:
            self.dtls.handshake_step()
            self._flush_dtls()

    def add_remote_candidate(self, candidate: str) -> None:
        if candidate.strip():
            self.ice.add_remote_candidate(candidate)

    async def wait_connected(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._connected.wait(), timeout)

    @property
    def connected(self) -> bool:
        return self._connected.is_set()

    # -- transport demux ---------------------------------------------

    def _on_transport_data(self, data: bytes) -> None:
        if is_dtls(data):
            self._on_dtls_datagram(data)
        elif len(data) >= 2 and data[0] >> 6 == 2:
            if rtcp.is_rtcp(data):
                self._on_srtcp(data)
            # inbound SRTP media is not expected (sendonly)

    def _on_dtls_datagram(self, data: bytes) -> None:
        d = self.dtls
        if d is None:
            return
        d.put_datagram(data)
        try:
            if not d.handshake_complete:
                if d.handshake_step():
                    self._on_dtls_established()
            if d.handshake_complete:
                for msg in d.recv():
                    if self.sctp is not None:
                        self.sctp.put_packet(msg)
                self._flush_sctp()
        except Exception as exc:
            logger.error("DTLS failure: %s", exc)
            self.close()
            return
        self._flush_dtls()

    def _flush_dtls(self) -> None:
        d = self.dtls
        if d is None or not self.ice.connected:
            return
        for dg in d.take_datagrams():
            self.ice.send(dg)

    def _flush_sctp(self) -> None:
        s, d = self.sctp, self.dtls
        if s is None or d is None or not d.handshake_complete:
            return
        for pkt in s.take_packets():
            d.send(pkt)
        self._flush_dtls()

    def _on_dtls_established(self) -> None:
        keys = self.dtls.srtp_keys
        self.srtp = session_pair(keys, dtls_is_client=not self.dtls.is_server)
        if self.sctp is not None and self.sctp.is_client:
            self.sctp.connect()
            self._flush_sctp()
        logger.info("DTLS-SRTP established (fingerprint verified)")
        self._connected.set()
        self.on_connected()

    # -- RTCP in ------------------------------------------------------

    def _on_srtcp(self, data: bytes) -> None:
        if self.srtp is None:
            return
        try:
            plain = self.srtp.unprotect_rtcp(data)
        except SrtpError as exc:
            logger.debug("SRTCP drop: %s", exc)
            return
        fb = rtcp.parse_compound(plain)
        if fb.pli_ssrcs or fb.fir_ssrcs:
            # libwebrtc-style keyframe floor: a broken/hostile peer
            # flooding PLIs must not turn every frame into an IDR
            # (~10-30x bandwidth + a slower device step). Dropping is
            # safe HERE because browsers re-send PLI until a keyframe
            # arrives; internal keyframe requests (transport handover)
            # bypass this path and are always honored. Shared by the
            # single-session app and the fleet (both wire
            # on_force_keyframe off this peer).
            now = self._clock()
            if now - self._last_pli_keyframe >= self.KEYFRAME_MIN_INTERVAL:
                self._last_pli_keyframe = now
                self.on_force_keyframe()
            else:
                logger.debug("PLI keyframe throttled")
        for blk in fb.reports:
            if blk.ssrc == self.video_ssrc and blk.fraction_lost > 0:
                self.on_loss(blk.fraction_lost)
        if fb.twcc and fb.twcc_ref_time_ms is not None:
            t = fb.twcc_ref_time_ms
            for pkt in fb.twcc:
                if pkt.recv_delta_ms is not None:
                    t += pkt.recv_delta_ms
                    self.on_packet_acked(pkt.seq, t)
        if fb.nacks:
            now = self._clock()
            self._rtx_tokens = min(
                float(RTX_BUDGET_BYTES),
                self._rtx_tokens + (now - self._rtx_refill_at) * RTX_BUDGET_BYTES)
            self._rtx_refill_at = now
            self.on_nack(len(fb.nacks))
        rtx_sent = rtx_dropped = 0
        for seq in fb.nacks:
            wire = self._rtx.get(seq)
            if wire is None:
                # the seq aged out of the ring: no retransmit (and no
                # FEC span) can close this gap — the recovery ladder
                # answers with a forced IDR instead
                self.on_unrecoverable(seq)
                continue
            if self.srtp is not None:
                # abuse bounds (see RTX_SEQ_FLOOR/RTX_BUDGET_BYTES): skip
                # a seq retransmitted within the floor (the rtx is likely
                # still in flight) and stop when the byte budget is dry
                if now - self._rtx_last.get(seq, float("-inf")) < RTX_SEQ_FLOOR:
                    continue
                if self._rtx_tokens < len(wire):
                    rtx_dropped += 1
                    break
                self._rtx_last[seq] = now
                self._rtx_tokens -= len(wire)
                rtx_sent += 1
                # plain retransmission (no rtx ssrc): re-protect fails the
                # SRTP replay rules on some stacks, so resend the original
                # protected packet bytes
                try:
                    self._net_send(wire)
                except ConnectionError:
                    pass
        if telemetry.enabled and (rtx_sent or rtx_dropped):
            if rtx_sent:
                telemetry.count("selkies_rtx_packets_total", n=rtx_sent,
                                result="sent")
            if rtx_dropped:
                telemetry.count("selkies_rtx_packets_total", n=rtx_dropped,
                                result="budget_drop")
        if fb.bye:
            logger.info("peer sent RTCP BYE")
            self.close()

    # -- media out ----------------------------------------------------

    def _send_rtp(self, pkt: RtpPacket, *, audio_stream: bool) -> bytes | None:
        """Protect + send one packet; returns the pre-SRTP wire bytes
        (what ULP FEC protects) or None when the transport isn't up."""
        if self.srtp is None or not self.ice.connected:
            return None
        self._twcc_seq = (self._twcc_seq + 1) & 0xFFFF
        pkt.extensions = [(self._twcc_id, struct.pack("!H", self._twcc_seq))]
        if not audio_stream and self._playout_delay_id is not None:
            # playout-delay min=max=0 (two 12-bit fields): tells the
            # browser to render with ZERO playout buffering — the other
            # half of the latency recipe next to jitterBufferTarget=0
            # (reference: PlayoutDelayExtension on every video packet,
            # gstwebrtc_app.py:1827-1863). Only sent when the answer
            # negotiated the extmap, with the answer's id.
            pkt.extensions.append((self._playout_delay_id, b"\x00\x00\x00"))
        wire = pkt.serialize()
        protected = self.srtp.protect(wire)
        self._net_send(protected)
        now_ms = self._clock() * 1e3
        self.on_packet_sent(self._twcc_seq, now_ms, len(protected))
        if audio_stream:
            self._aud_packets += 1
            self._aud_octets += len(pkt.payload)
        else:
            self._vid_packets += 1
            self._vid_octets += len(pkt.payload)
            self._rtx[pkt.sequence & 0xFFFF] = protected
            while len(self._rtx) > RTX_BUFFER:
                # dicts iterate in insertion order == send order, which
                # stays correct across the 16-bit sequence wrap; the
                # retransmit-floor map is pruned WITH the eviction so a
                # long session never pins dead seqs (they wrap at 65536)
                evicted = next(iter(self._rtx))
                del self._rtx[evicted]
                self._rtx_last.pop(evicted, None)
        return wire

    def _net_send(self, datagram: bytes) -> None:
        """The send boundary every media/rtx datagram crosses: with a
        ``net:*`` fault rule active the NetImpairment shim decides
        drop/delay/duplicate/reorder deterministically; otherwise this
        is ``ice.send`` plus one attribute load."""
        imp = self._impair
        if imp is None:
            self.ice.send(datagram)
            return
        for delay_ms, data in imp.admit(datagram, self._clock() * 1e3):
            if delay_ms <= 0:
                self.ice.send(data)
            else:
                self._loop.call_later(delay_ms / 1e3, self._late_send, data)

    def _late_send(self, data: bytes) -> None:
        if self._closed:
            return
        try:
            self.ice.send(data)
        except ConnectionError:
            pass

    def set_fec_percentage(self, percentage: int) -> None:
        """Live protection-level change (recovery ladder). Takes effect
        on the armed encoder immediately; before the answer arrives it
        just updates the arming percentage — and marks the peer
        ladder-driven, so set_answer arms the encoder even at 0 %."""
        self._fec_live = True
        self.fec_percentage = max(0, int(percentage))
        if self._fec is not None:
            self._fec.set_percentage(self.fec_percentage)

    def send_video(self, au: bytes, timestamp_90k: int, *,
                   idr: bool = False) -> None:
        ts = int(timestamp_90k) & 0xFFFFFFFF
        if self._fec is not None and idr:
            # keyframe boundary: a protection row must not span the IDR
            # (leftover parity belongs to the PREVIOUS frame's timestamp)
            parity = self._fec.begin_au(keyframe=True)
            if parity is not None:
                self._send_fec(parity, self._last_video_ts)
        self._last_video_ts = ts
        for pkt in self.video_pay.payload_au(au, ts):
            if self._fec is not None:
                # RED-encapsulate the media (single block, inner PT = the
                # negotiated codec PT, which set_answer may have renumbered)
                pkt.payload = fec.red_wrap(self.video_pay.payload_type, pkt.payload)
                pkt.payload_type = self._red_pt
            wire = self._send_rtp(pkt, audio_stream=False)
            if self._fec is not None and wire is not None:
                parity = self._fec.push(wire)
                if parity is not None:
                    self._send_fec(parity, ts)
        if self._fec is not None:
            parity = self._fec.flush()  # bound recovery latency to 1 frame
            if parity is not None:
                self._send_fec(parity, ts)

    def _send_fec(self, parity: bytes, ts: int) -> None:
        pkt = RtpPacket(
            payload_type=self._red_pt,
            sequence=self.video_pay._next_seq(),
            timestamp=ts,
            ssrc=self.video_ssrc,
            payload=fec.red_wrap(self._ulpfec_pt, parity),
        )
        self._send_rtp(pkt, audio_stream=False)

    def send_audio(self, opus_packet: bytes, timestamp_48k: int) -> None:
        pkt = self.audio_pay.payload_packet(opus_packet, timestamp_48k)
        self._send_rtp(pkt, audio_stream=True)

    # -- datachannels -------------------------------------------------

    def open_datachannel(self, label: str, protocol: str = ""):
        if self.sctp is None:
            raise ConnectionError("no SCTP association yet")
        ch = self.sctp.open_channel(label, protocol)
        self._flush_sctp()
        return ch

    def send_datachannel(self, ch, data: bytes, binary: bool = False) -> None:
        if self.sctp is None:
            return
        self.sctp.send(ch, data, binary)
        self._flush_sctp()

    # -- housekeeping -------------------------------------------------

    async def _tick_loop(self) -> None:
        last_sr = 0.0
        while not self._closed:
            await asyncio.sleep(0.2)
            if self.sctp is not None:
                self.sctp.tick()
                self._flush_sctp()
            if self.dtls is not None and not self.dtls.handshake_complete:
                self.dtls.handle_timeout()
                self._flush_dtls()
            now = time.monotonic()
            if self.srtp is not None and now - last_sr > 2.0 and self.ice.connected:
                last_sr = now
                sr = rtcp.build_sender_report(
                    self.video_ssrc, self._last_video_ts,
                    self._vid_packets, self._vid_octets,
                ) + rtcp.build_sdes(self.video_ssrc)
                try:
                    self.ice.send(self.srtp.protect_rtcp(sr))
                except (ConnectionError, SrtpError):
                    pass

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._tick_task is not None:
            self._tick_task.cancel()
        try:
            # best-effort goodbyes: the DTLS/ICE state may already be
            # broken (close() runs on DTLS failure too), and a raise here
            # would skip the teardown + on_closed notification
            if self.sctp is not None:
                self.sctp.shutdown()
                self._flush_sctp()
            if self.dtls is not None:
                self.dtls.close()
                self._flush_dtls()
        except Exception as exc:
            logger.debug("teardown flush failed: %s", exc)
        self.ice.close()
        self.on_closed()
