"""Minimal SCTP over DTLS + DCEP data channels (RFC 9260 subset, RFC 8831/8832).

The reference's datachannel is webrtcbin's usrsctp. WebRTC input/control
traffic is tiny (KB/s), so this implementation keeps the full protocol
machine small: reliable ordered delivery, immediate SACKs, fragmentation,
a single fixed RTO retransmit timer, HEARTBEAT echo, and the DCEP
open/ack handshake. No congestion control beyond stop-when-unacked-grows
(input traffic never approaches the default a_rwnd).

Sans-IO: `put_packet` feeds an SCTP packet (one DTLS application
datagram), `take_packets` drains what must be sent, `tick` drives
retransmission. The peer.py layer shuttles these through DtlsEndpoint.
"""

from __future__ import annotations

import logging
import os
import secrets
import struct
import time
from dataclasses import dataclass, field

logger = logging.getLogger("transport.webrtc.sctp")

# chunk types
DATA = 0
INIT = 1
INIT_ACK = 2
SACK = 3
HEARTBEAT = 4
HEARTBEAT_ACK = 5
ABORT = 6
SHUTDOWN = 7
SHUTDOWN_ACK = 8
ERROR = 9
COOKIE_ECHO = 10
COOKIE_ACK = 11
SHUTDOWN_COMPLETE = 14

# DCEP (RFC 8832)
PPID_DCEP = 50
PPID_STRING = 51
PPID_BINARY = 53
PPID_STRING_EMPTY = 56
PPID_BINARY_EMPTY = 57
DCEP_OPEN = 0x03
DCEP_ACK = 0x02
DC_RELIABLE = 0x00

MTU = 1150  # fits one DTLS record under typical 1200-byte path MTU
DEFAULT_RWND = 1024 * 1024
RX_WINDOW_CHUNKS = 2048  # max TSN distance held in the reorder buffer
RX_BUFFER_BYTES = 4 * 1024 * 1024  # reorder-buffer byte budget
# max bytes of in-progress fragmented messages PER ASSOCIATION (summed
# over all stream ids — sids are attacker-chosen 16-bit values, so a
# per-stream cap would multiply by 65536): browsers cap datachannel
# messages well below this (256 KB typical); a peer streaming
# B-fragments with no E bit must not grow memory unboundedly
REASM_MAX_BYTES = 16 * 1024 * 1024
RTO = 1.0
MAX_RETRANS = 10


# -- CRC32c (Castagnoli), reflected, as SCTP requires -----------------

_CRC32C_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ 0x82F63B78 if _c & 1 else _c >> 1
    _CRC32C_TABLE.append(_c)


def crc32c(data: bytes) -> int:
    c = 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ _CRC32C_TABLE[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def _pad(b: bytes) -> bytes:
    return b + b"\x00" * ((4 - len(b) % 4) % 4)


def _chunk(ctype: int, flags: int, value: bytes) -> bytes:
    return struct.pack("!BBH", ctype, flags, 4 + len(value)) + _pad(value)


def _tsn_gt(a: int, b: int) -> bool:
    return ((a - b) & 0xFFFFFFFF) < 0x80000000 and a != b


@dataclass
class Channel:
    stream_id: int
    label: str
    protocol: str = ""
    open: bool = False


@dataclass
class _OutChunk:
    tsn: int
    data: bytes  # the full DATA chunk bytes
    sent_at: float = 0.0
    retrans: int = 0


class SctpAssociation:
    """One SCTP association multiplexing DCEP data channels.

    `is_client` mirrors the DTLS role (RFC 8832: the DTLS client uses
    even stream ids and usually initiates the association; the server
    side here also supports initiating, for server-created channels)."""

    def __init__(self, *, is_client: bool, port: int = 5000):
        self.is_client = is_client
        self.port = port
        self.local_vtag = struct.unpack("!I", os.urandom(4))[0] or 1
        self.remote_vtag = 0
        self.local_tsn = struct.unpack("!I", os.urandom(4))[0]
        self.remote_tsn_seen: int | None = None  # cumulative
        self.established = False
        self.on_channel_open = lambda ch: None
        self.on_message = lambda ch, data, binary: None
        self.channels: dict[int, Channel] = {}
        self._out: list[bytes] = []  # packets ready to send
        self._unacked: list[_OutChunk] = []
        self._ssn: dict[int, int] = {}
        self._next_sid = 0 if is_client else 1
        self._reasm: dict[int, list[tuple[int, int, bytes, int]]] = {}
        self._reasm_total = 0  # in-progress fragment bytes, all streams
        # per-stream running byte totals, kept in lockstep with _reasm:
        # the over-budget eviction picks the largest stream, and summing
        # fragment lists on every append would be O(streams x fragments)
        # exactly in the many-parked-streams case the cap defends against
        self._reasm_bytes: dict[int, int] = {}
        self._rx_out_of_order: dict[int, tuple[int, bytes]] = {}  # tsn -> (flags, chunk value)
        self._rx_buffered = 0  # bytes currently held in _rx_out_of_order
        self._cookie = b""
        self._pending_open: list[Channel] = []
        self._shutdown = False
        self._init_sent = False  # COOKIE-WAIT gate for INIT_ACK (RFC 9260 §5.2.3)

    # -- packet framing ----------------------------------------------

    def _emit(self, *chunks: bytes, vtag: int | None = None) -> None:
        hdr = struct.pack("!HHII", self.port, self.port,
                          self.remote_vtag if vtag is None else vtag, 0)
        pkt = bytearray(hdr + b"".join(chunks))
        struct.pack_into("<I", pkt, 8, crc32c(bytes(pkt[:8]) + b"\x00" * 4 + bytes(pkt[12:])))
        self._out.append(bytes(pkt))

    def take_packets(self) -> list[bytes]:
        out, self._out = self._out, []
        return out

    # -- association setup -------------------------------------------

    def connect(self) -> None:
        """Initiate the association (INIT)."""
        self._init_sent = True
        init = struct.pack("!IIHHI", self.local_vtag, DEFAULT_RWND, 1024, 1024,
                           self.local_tsn)
        self._emit(_chunk(INIT, 0, init), vtag=0)

    def put_packet(self, pkt: bytes) -> None:
        if len(pkt) < 12:
            return
        body = bytearray(pkt)
        crc = struct.unpack_from("<I", body, 8)[0]
        struct.pack_into("!I", body, 8, 0)
        if crc32c(bytes(body)) != crc:
            logger.debug("SCTP checksum mismatch")
            return
        # RFC 9260 §8.5: packets for this association must carry our tag.
        # INIT rides vtag 0 by definition, and ABORT/SHUTDOWN-COMPLETE with
        # the T bit reflect OUR outgoing tag (§8.5.1) — a restarted peer
        # with no association state aborts that way. Everything else with a
        # wrong tag (e.g. a peer restarting mid-stream) is dropped rather
        # than being allowed to corrupt TSN state.
        vtag = struct.unpack_from("!I", pkt, 4)[0]
        first_type = pkt[12] if len(pkt) > 12 else None
        first_flags = pkt[13] if len(pkt) > 13 else 0
        # INIT is only exempt as §8.5.1 defines it: vtag 0, sole chunk —
        # an INIT-first bundle with a stale tag must not smuggle DATA past
        # the check or clobber remote_vtag on a live association.
        if first_type == INIT:
            init_len = struct.unpack_from("!H", pkt, 14)[0] if len(pkt) >= 16 else 0
            padded = init_len + ((4 - init_len % 4) % 4)
            if vtag != 0 or 12 + padded < len(pkt):
                logger.debug("SCTP malformed INIT packet (vtag=%#x); dropping", vtag)
                return
            if self.established:
                # RFC 9260 §5.2.2 restart handling is not implemented (the
                # DTLS tunnel makes a true restart a new association at a
                # higher layer); letting the INIT through would clobber
                # remote_vtag/TSN state on the live association.
                logger.warning("SCTP INIT on established association; dropping")
                return
        elif vtag != self.local_vtag:
            reflected = (first_type in (ABORT, SHUTDOWN_COMPLETE)
                         and (first_flags & 1) and vtag == self.remote_vtag)
            if not reflected:
                logger.debug("SCTP vtag mismatch (%#x != %#x); dropping",
                             vtag, self.local_vtag)
                return
        off = 12
        while off + 4 <= len(pkt):
            ctype, flags, length = struct.unpack_from("!BBH", pkt, off)
            if length < 4 or off + length > len(pkt):
                break
            value = pkt[off + 4 : off + length]
            # RFC 9260 §4.3: INIT MUST be the only chunk in its packet.
            # The first-chunk case was validated above (vtag 0, sole
            # chunk); an INIT smuggled later in a bundle would bypass
            # that and let _on_chunk clobber remote_vtag/remote_tsn_seen
            # on a live association.
            if ctype == INIT and off != 12:
                logger.debug("SCTP bundled INIT; dropping chunk")
            else:
                self._on_chunk(ctype, flags, value)
            off += length + ((4 - length % 4) % 4)

    def _on_chunk(self, ctype: int, flags: int, value: bytes) -> None:
        if ctype == INIT and len(value) >= 16:
            itag, rwnd, os_, is_, itsn = struct.unpack_from("!IIHHI", value, 0)
            self.remote_vtag = itag
            self.remote_tsn_seen = (itsn - 1) & 0xFFFFFFFF
            cookie = secrets.token_bytes(16)
            self._cookie = cookie
            ack = struct.pack("!IIHHI", self.local_vtag, DEFAULT_RWND, 1024,
                              1024, self.local_tsn)
            ack += struct.pack("!HH", 7, 4 + len(cookie)) + cookie  # STATE-COOKIE
            self._emit(_chunk(INIT_ACK, 0, ack))
        elif ctype == INIT_ACK and len(value) >= 16:
            # RFC 9260 §5.2.3: an INIT ACK outside COOKIE-WAIT is
            # discarded — processing it on an established association (or
            # on a side that never sent INIT) would let the peer clobber
            # remote_vtag/remote_tsn_seen and silently break delivery
            if self.established or not self._init_sent:
                logger.debug("SCTP INIT_ACK outside COOKIE-WAIT; dropping")
                return
            itag, rwnd, os_, is_, itsn = struct.unpack_from("!IIHHI", value, 0)
            self.remote_vtag = itag
            self.remote_tsn_seen = (itsn - 1) & 0xFFFFFFFF
            cookie = self._find_param(value[16:], 7)
            self._emit(_chunk(COOKIE_ECHO, 0, cookie or b""))
            self._establish()
        elif ctype == COOKIE_ECHO:
            self._emit(_chunk(COOKIE_ACK, 0, b""))
            self._establish()
        elif ctype == COOKIE_ACK:
            self._establish()
        elif ctype == DATA:
            self._on_data(flags, value)
        elif ctype == SACK and len(value) >= 12:
            cum = struct.unpack_from("!I", value, 0)[0]
            self._unacked = [c for c in self._unacked if _tsn_gt(c.tsn, cum)]
        elif ctype == HEARTBEAT:
            self._emit(_chunk(HEARTBEAT_ACK, 0, value))
        elif ctype == ABORT:
            logger.warning("SCTP association aborted by peer")
            self.established = False
            # an ABORT during COOKIE-WAIT also ends COOKIE-WAIT: without
            # this a later INIT_ACK would pass the §5.2.3 gate and
            # establish the aborted association with peer-chosen state
            self._init_sent = False
        elif ctype == SHUTDOWN:
            self._emit(_chunk(SHUTDOWN_ACK, 0, b""))
            self.established = False
            self._init_sent = False
        elif ctype == SHUTDOWN_ACK:
            self._emit(_chunk(SHUTDOWN_COMPLETE, 0, b""))
            self.established = False
            self._init_sent = False

    @staticmethod
    def _find_param(params: bytes, ptype: int) -> bytes | None:
        off = 0
        while off + 4 <= len(params):
            t, ln = struct.unpack_from("!HH", params, off)
            if ln < 4:
                return None
            if t == ptype:
                return params[off + 4 : off + ln]
            off += ln + ((4 - ln % 4) % 4)
        return None

    def _establish(self) -> None:
        if self.established:
            return
        # COOKIE-WAIT is left for good: without this, an INIT_ACK arriving
        # after ABORT/SHUTDOWN (established=False again, _init_sent still
        # True) would pass the §5.2.3 gate and resurrect the dead
        # association with attacker-chosen remote_vtag/TSN state.
        self._init_sent = False
        self.established = True
        for ch in self._pending_open:
            self._send_dcep_open(ch)
        self._pending_open.clear()

    # -- inbound data -------------------------------------------------

    def _on_data(self, flags: int, value: bytes) -> None:
        if len(value) < 12:
            return
        tsn, sid, ssn, ppid = struct.unpack_from("!IHHI", value, 0)
        if self.remote_tsn_seen is None:
            # no reference TSN yet (COOKIE-WAIT): the drain loop could
            # never release these, so buffering would be an unbounded
            # sink for a peer that sends DATA before handshaking. Any
            # legitimate flow sets remote_tsn_seen via INIT/INIT_ACK
            # before its first DATA can arrive.
            logger.debug("SCTP DATA before handshake; dropping")
            return
        if not _tsn_gt(tsn, self.remote_tsn_seen):
            self._send_sack()  # duplicate
            return
        # receive-window bound: serial arithmetic calls half the 32-bit
        # space "greater", so without a cap a peer could park unbounded
        # far-future TSNs in the reorder buffer (memory DoS). The count
        # cap bounds the TSN distance; the byte budget bounds the actual
        # memory (a DTLS record can carry a ~16 KB chunk, so count alone
        # would still allow ~32 MB parked behind a never-filled gap).
        if ((tsn - self.remote_tsn_seen) & 0xFFFFFFFF) > RX_WINDOW_CHUNKS:
            logger.debug("SCTP DATA tsn %d outside rx window; dropping", tsn)
            return
        if tsn in self._rx_out_of_order:
            # duplicate of an already-buffered out-of-order chunk: still
            # SACK it (mirrors the cumulative-duplicate path above) — a
            # legitimately retransmitted chunk needs ack feedback or the
            # sender keeps hitting RTO on it
            self._send_sack()
            return
        # the budget must never drop the gap-filling chunk (tsn == next
        # expected): it delivers immediately and DRAINS the buffer below,
        # while dropping it would deadlock a full buffer — every
        # retransmission would bounce the same way until the sender's
        # retry cap tears the association down
        if (tsn != ((self.remote_tsn_seen + 1) & 0xFFFFFFFF)
                and self._rx_buffered + len(value) > RX_BUFFER_BYTES):
            logger.debug("SCTP reorder buffer over byte budget; dropping tsn %d", tsn)
            return
        self._rx_buffered += len(value)
        self._rx_out_of_order[tsn] = (flags, value)
        # advance the cumulative TSN over any in-order run
        while True:
            nxt = (self.remote_tsn_seen + 1) & 0xFFFFFFFF
            item = self._rx_out_of_order.pop(nxt, None)
            if item is None:
                break
            self._rx_buffered -= len(item[1])
            self.remote_tsn_seen = nxt
            self._deliver(*item)
        self._send_sack()

    def _deliver(self, flags: int, value: bytes) -> None:
        tsn, sid, ssn, ppid = struct.unpack_from("!IHHI", value, 0)
        payload = value[12:]
        frags = self._reasm.setdefault(sid, [])
        frags.append((flags, ssn, payload, ppid))
        self._reasm_total += len(payload)
        self._reasm_bytes[sid] = self._reasm_bytes.get(sid, 0) + len(payload)
        if not flags & 0x01:  # E bit clear: more fragments coming
            if self._reasm_total > REASM_MAX_BYTES:
                # over the association budget: evict the stream with the
                # LARGEST buffered total, not whichever stream's fragment
                # happened to cross the cap — otherwise attacker-parked
                # B-fragments on other sids persist at the cap while a
                # legitimate large message keeps getting sacrificed
                victim = max(self._reasm_bytes, key=self._reasm_bytes.get)
                vbytes = self._reasm_bytes.pop(victim)
                logger.warning("reassembly over %d bytes; dropping stream "
                               "%d fragment state (%d bytes buffered)",
                               REASM_MAX_BYTES, victim, vbytes)
                self._reasm_total -= vbytes
                del self._reasm[victim]  # empty-list entries would pile up over 64k sids
            return
        # reassemble from the most recent B fragment; an E without any B
        # is malformed — drop the stream's fragment state, not the session
        start = next((i for i in range(len(frags) - 1, -1, -1) if frags[i][0] & 0x02), -1)
        if start < 0:
            self._reasm_total -= self._reasm_bytes.pop(sid)
            del self._reasm[sid]
            return
        msg = b"".join(f[2] for f in frags[start:])
        ppid = frags[start][3]
        del frags[start:]
        self._reasm_bytes[sid] -= len(msg)
        if not frags:
            del self._reasm[sid]
            del self._reasm_bytes[sid]
        self._reasm_total -= len(msg)
        self._on_message_raw(sid, ppid, msg)

    def _on_message_raw(self, sid: int, ppid: int, msg: bytes) -> None:
        if ppid == PPID_DCEP:
            self._on_dcep(sid, msg)
            return
        ch = self.channels.get(sid)
        if ch is None or not ch.open:
            logger.debug("data on unknown stream %d", sid)
            return
        if ppid in (PPID_STRING, PPID_STRING_EMPTY):
            self.on_message(ch, b"" if ppid == PPID_STRING_EMPTY else msg, False)
        else:
            self.on_message(ch, b"" if ppid == PPID_BINARY_EMPTY else msg, True)

    def _on_dcep(self, sid: int, msg: bytes) -> None:
        if not msg:
            return
        if msg[0] == DCEP_OPEN and len(msg) >= 12:
            _t, _ct, _prio, _rel, llen, plen = struct.unpack_from("!BBHIHH", msg, 0)
            label = msg[12 : 12 + llen].decode("utf-8", "replace")
            proto = msg[12 + llen : 12 + llen + plen].decode("utf-8", "replace")
            ch = Channel(stream_id=sid, label=label, protocol=proto, open=True)
            self.channels[sid] = ch
            self._send_data(sid, PPID_DCEP, bytes([DCEP_ACK]))
            self.on_channel_open(ch)
        elif msg[0] == DCEP_ACK:
            ch = self.channels.get(sid)
            if ch is not None and not ch.open:
                ch.open = True
                self.on_channel_open(ch)

    # -- outbound -----------------------------------------------------

    def _send_sack(self) -> None:
        if self.remote_tsn_seen is None:
            return
        gaps = b""  # cumulative-only SACK; missing chunks get retransmitted
        sack = struct.pack("!IIHH", self.remote_tsn_seen, DEFAULT_RWND, 0, 0) + gaps
        self._emit(_chunk(SACK, 0, sack))

    def open_channel(self, label: str, protocol: str = "") -> Channel:
        sid = self._next_sid
        self._next_sid += 2
        ch = Channel(stream_id=sid, label=label, protocol=protocol)
        self.channels[sid] = ch
        if self.established:
            self._send_dcep_open(ch)
        else:
            self._pending_open.append(ch)
        return ch

    def _send_dcep_open(self, ch: Channel) -> None:
        label = ch.label.encode()
        proto = ch.protocol.encode()
        msg = struct.pack("!BBHIHH", DCEP_OPEN, DC_RELIABLE, 0, 0,
                          len(label), len(proto)) + label + proto
        self._send_data(ch.stream_id, PPID_DCEP, msg)

    def send(self, ch: Channel, data: bytes, binary: bool = False) -> None:
        if binary:
            ppid = PPID_BINARY_EMPTY if not data else PPID_BINARY
        else:
            ppid = PPID_STRING_EMPTY if not data else PPID_STRING
        self._send_data(ch.stream_id, ppid, data or b"\x00")

    def _send_data(self, sid: int, ppid: int, msg: bytes) -> None:
        ssn = self._ssn.get(sid, 0)
        self._ssn[sid] = (ssn + 1) & 0xFFFF
        frags = [msg[i : i + MTU] for i in range(0, len(msg), MTU)] or [b""]
        for i, frag in enumerate(frags):
            flags = (0x02 if i == 0 else 0) | (0x01 if i == len(frags) - 1 else 0)
            tsn = self.local_tsn
            self.local_tsn = (self.local_tsn + 1) & 0xFFFFFFFF
            value = struct.pack("!IHHI", tsn, sid, ssn, ppid) + frag
            chunk = _chunk(DATA, flags, value)
            oc = _OutChunk(tsn=tsn, data=chunk, sent_at=time.monotonic())
            self._unacked.append(oc)
            self._emit(chunk)

    def tick(self) -> None:
        """Retransmit timed-out DATA chunks (call ~every 200 ms)."""
        now = time.monotonic()
        for oc in self._unacked:
            if now - oc.sent_at >= RTO:
                if oc.retrans >= MAX_RETRANS:
                    logger.warning("SCTP giving up on tsn %d", oc.tsn)
                    self.established = False
                    return
                oc.retrans += 1
                oc.sent_at = now
                self._emit(oc.data)

    def shutdown(self) -> None:
        if self.established and not self._shutdown:
            self._shutdown = True
            cum = self.remote_tsn_seen or 0
            self._emit(_chunk(SHUTDOWN, 0, struct.pack("!I", cum)))
