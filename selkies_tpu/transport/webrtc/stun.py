"""STUN message codec (RFC 5389) + ICE (RFC 8445) and TURN (RFC 5766)
attributes.

Replaces the STUN half of libnice that the reference gets through
webrtcbin (gstwebrtc_app.py:149-160: stun-server/turn-server props).
Only what ICE + TURN-over-UDP need is implemented; the codec is strict
about lengths and integrity so malformed network input cannot wander
into the agent.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
import zlib
from dataclasses import dataclass, field

MAGIC_COOKIE = 0x2112A442

# methods
BINDING = 0x001
ALLOCATE = 0x003
REFRESH = 0x004
SEND = 0x006
DATA = 0x007
CREATE_PERMISSION = 0x008
CHANNEL_BIND = 0x009

# classes
REQUEST = 0x00
INDICATION = 0x01
RESPONSE = 0x02
ERROR_RESPONSE = 0x03

# attributes
ATTR_MAPPED_ADDRESS = 0x0001
ATTR_USERNAME = 0x0006
ATTR_MESSAGE_INTEGRITY = 0x0008
ATTR_ERROR_CODE = 0x0009
ATTR_CHANNEL_NUMBER = 0x000C
ATTR_LIFETIME = 0x000D
ATTR_XOR_PEER_ADDRESS = 0x0012
ATTR_DATA = 0x0013
ATTR_REALM = 0x0014
ATTR_NONCE = 0x0015
ATTR_XOR_RELAYED_ADDRESS = 0x0016
ATTR_REQUESTED_TRANSPORT = 0x0019
ATTR_XOR_MAPPED_ADDRESS = 0x0020
ATTR_PRIORITY = 0x0024
ATTR_USE_CANDIDATE = 0x0025
ATTR_SOFTWARE = 0x8022
ATTR_FINGERPRINT = 0x8028
ATTR_ICE_CONTROLLED = 0x8029
ATTR_ICE_CONTROLLING = 0x802A

FINGERPRINT_XOR = 0x5354554E


class StunError(ValueError):
    pass


def _pack_type(method: int, cls: int) -> int:
    # RFC 5389 §6: class bits interleave into the method at bits 4 and 8
    return (
        (method & 0x0F80) << 2
        | (cls & 2) << 7
        | (method & 0x0070) << 1
        | (cls & 1) << 4
        | (method & 0x000F)
    )


def _unpack_type(t: int) -> tuple[int, int]:
    method = (t & 0x3E00) >> 2 | (t & 0x00E0) >> 1 | (t & 0x000F)
    cls = (t & 0x0100) >> 7 | (t & 0x0010) >> 4
    return method, cls


def xor_address(addr: tuple[str, int], txid: bytes) -> bytes:
    """Encode (ip, port) as XOR-MAPPED-ADDRESS payload (IPv4/IPv6)."""
    import ipaddress

    ip = ipaddress.ip_address(addr[0])
    port = addr[1] ^ (MAGIC_COOKIE >> 16)
    if ip.version == 4:
        raw = int(ip) ^ MAGIC_COOKIE
        return struct.pack("!BBHI", 0, 0x01, port, raw)
    key = struct.pack("!I", MAGIC_COOKIE) + txid
    raw = bytes(a ^ b for a, b in zip(ip.packed, key))
    return struct.pack("!BBH", 0, 0x02, port) + raw


def unxor_address(payload: bytes, txid: bytes) -> tuple[str, int]:
    import ipaddress

    if len(payload) < 8:
        raise StunError("short xor-address")
    fam = payload[1]
    port = struct.unpack("!H", payload[2:4])[0] ^ (MAGIC_COOKIE >> 16)
    if fam == 0x01:
        ip = struct.unpack("!I", payload[4:8])[0] ^ MAGIC_COOKIE
        return str(ipaddress.ip_address(ip)), port
    if fam == 0x02:
        if len(payload) < 20:
            raise StunError("short xor-address v6")
        key = struct.pack("!I", MAGIC_COOKIE) + txid
        raw = bytes(a ^ b for a, b in zip(payload[4:20], key))
        return str(ipaddress.ip_address(raw)), port
    raise StunError(f"bad address family {fam}")


@dataclass
class StunMessage:
    method: int
    cls: int
    txid: bytes = field(default_factory=lambda: os.urandom(12))
    attrs: list[tuple[int, bytes]] = field(default_factory=list)

    def get(self, attr: int) -> bytes | None:
        for a, v in self.attrs:
            if a == attr:
                return v
        return None

    def add(self, attr: int, value: bytes) -> "StunMessage":
        self.attrs.append((attr, value))
        return self

    # -- building -----------------------------------------------------

    def serialize(self, integrity_key: bytes | None = None,
                  fingerprint: bool = True) -> bytes:
        attrs = b""
        for a, v in self.attrs:
            attrs += struct.pack("!HH", a, len(v)) + v + b"\x00" * ((4 - len(v) % 4) % 4)
        if integrity_key is not None:
            # integrity covers the header with a length that includes the
            # MI attribute itself (RFC 5389 §15.4)
            hdr = struct.pack(
                "!HHI", _pack_type(self.method, self.cls), len(attrs) + 24,
                MAGIC_COOKIE,
            ) + self.txid
            mac = hmac.new(integrity_key, hdr + attrs, hashlib.sha1).digest()
            attrs += struct.pack("!HH", ATTR_MESSAGE_INTEGRITY, 20) + mac
        if fingerprint:
            hdr = struct.pack(
                "!HHI", _pack_type(self.method, self.cls), len(attrs) + 8,
                MAGIC_COOKIE,
            ) + self.txid
            crc = (zlib.crc32(hdr + attrs) & 0xFFFFFFFF) ^ FINGERPRINT_XOR
            attrs += struct.pack("!HHI", ATTR_FINGERPRINT, 4, crc)
        hdr = struct.pack(
            "!HHI", _pack_type(self.method, self.cls), len(attrs), MAGIC_COOKIE
        ) + self.txid
        return hdr + attrs

    # -- parsing ------------------------------------------------------

    @classmethod
    def parse(cls, data: bytes) -> "StunMessage":
        if len(data) < 20:
            raise StunError("short message")
        t, length, cookie = struct.unpack("!HHI", data[:8])
        if t & 0xC000:
            raise StunError("not a STUN message")
        if cookie != MAGIC_COOKIE:
            raise StunError("bad magic cookie")
        if len(data) < 20 + length or length % 4:
            raise StunError("bad length")
        txid = data[8:20]
        method, mcls = _unpack_type(t)
        msg = cls(method=method, cls=mcls, txid=txid)
        off = 20
        end = 20 + length
        while off + 4 <= end:
            a, alen = struct.unpack("!HH", data[off : off + 4])
            if off + 4 + alen > end:
                raise StunError("attribute overruns message")
            msg.attrs.append((a, data[off + 4 : off + 4 + alen]))
            off += 4 + alen + ((4 - alen % 4) % 4)
        return msg

    def check_integrity(self, key: bytes, data: bytes) -> bool:
        """Verify MESSAGE-INTEGRITY over the original wire bytes."""
        off = 20
        end = 20 + struct.unpack("!H", data[2:4])[0]
        while off + 4 <= end:
            a, alen = struct.unpack("!HH", data[off : off + 4])
            if a == ATTR_MESSAGE_INTEGRITY:
                covered = bytearray(data[:off])
                # adjust header length: everything through the MI attr
                struct.pack_into("!H", covered, 2, off + 24 - 20)
                mac = hmac.new(key, bytes(covered), hashlib.sha1).digest()
                return hmac.compare_digest(mac, data[off + 4 : off + 24])
            off += 4 + alen + ((4 - alen % 4) % 4)
        return False


def is_stun(data: bytes) -> bool:
    """Demultiplex per RFC 7983: STUN leads with 0x00-0x03."""
    return len(data) >= 20 and data[0] < 4 and data[4:8] == struct.pack("!I", MAGIC_COOKIE)


def error_code(msg: StunMessage) -> tuple[int, str] | None:
    v = msg.get(ATTR_ERROR_CODE)
    if v is None or len(v) < 4:
        return None
    code = (v[2] & 0x07) * 100 + v[3]
    return code, v[4:].decode("utf-8", "replace")


def make_error(code: int, reason: str) -> bytes:
    return struct.pack("!HBB", 0, code // 100, code % 100) + reason.encode()


def long_term_key(username: str, realm: str, password: str) -> bytes:
    """TURN long-term credential key (RFC 5389 §15.4)."""
    return hashlib.md5(f"{username}:{realm}:{password}".encode()).digest()
