"""RTCP: sender reports out, feedback (RR / PLI / FIR / NACK / TWCC) in.

The reference consumes these inside webrtcbin; here the parsed feedback
drives the same control surfaces the framework already has: PLI/FIR ->
encoder.force_keyframe, RR loss -> GccController.on_loss_report, TWCC
feedback (draft-holmer-rmcat-transport-wide-cc-extensions-01) ->
GccController per-packet ack stream, NACK -> the RTP retransmit buffer.
"""

from __future__ import annotations

import struct
import time
from dataclasses import dataclass, field

RTCP_SR = 200
RTCP_RR = 201
RTCP_SDES = 202
RTCP_BYE = 203
RTCP_RTPFB = 205   # transport-layer feedback: NACK(1), TWCC(15)
RTCP_PSFB = 206    # payload-specific: PLI(1), FIR(4)

NTP_EPOCH = 2208988800  # 1900 -> 1970


def is_rtcp(data: bytes) -> bool:
    """RFC 5761 demux: RTCP packet types 192-223 in the second byte."""
    return len(data) >= 8 and data[0] >> 6 == 2 and 192 <= data[1] <= 223


@dataclass
class ReportBlock:
    ssrc: int
    fraction_lost: float
    packets_lost: int
    highest_seq: int
    jitter: int


@dataclass
class TwccPacket:
    seq: int                # transport-wide sequence number
    recv_delta_ms: float | None  # None = not received


@dataclass
class Feedback:
    pli_ssrcs: list[int] = field(default_factory=list)
    fir_ssrcs: list[int] = field(default_factory=list)
    nacks: list[int] = field(default_factory=list)  # lost RTP seqs
    reports: list[ReportBlock] = field(default_factory=list)
    twcc: list[TwccPacket] = field(default_factory=list)
    twcc_ref_time_ms: float | None = None
    bye: bool = False


def parse_compound(data: bytes) -> Feedback:
    fb = Feedback()
    off = 0
    while off + 4 <= len(data):
        b0, pt, length = struct.unpack_from("!BBH", data, off)
        if b0 >> 6 != 2:
            break
        size = 4 * (length + 1)
        if off + size > len(data):
            break
        body = data[off + 4 : off + size]
        fmt = b0 & 0x1F
        if pt == RTCP_RR:
            _parse_rr(body, fmt, fb)
        elif pt == RTCP_SR and len(body) >= 24:
            # skip sender info (20 bytes past the reporter ssrc) so
            # _parse_rr's own 4-byte ssrc skip lands on the blocks
            _parse_rr(body[20:], fmt, fb)
        elif pt == RTCP_PSFB and fmt == 1 and len(body) >= 8:
            fb.pli_ssrcs.append(struct.unpack_from("!I", body, 4)[0])
        elif pt == RTCP_PSFB and fmt == 4 and len(body) >= 8:
            fb.fir_ssrcs.append(struct.unpack_from("!I", body, 4)[0])
        elif pt == RTCP_RTPFB and fmt == 1:
            _parse_nack(body, fb)
        elif pt == RTCP_RTPFB and fmt == 15:
            _parse_twcc(body, fb)
        elif pt == RTCP_BYE:
            fb.bye = True
        off += size
    return fb


def _parse_rr(body: bytes, count: int, fb: Feedback) -> None:
    off = 4  # skip reporter ssrc
    for _ in range(count):
        if off + 24 > len(body):
            return
        ssrc, fl_cl, ehsn, jitter = struct.unpack_from("!IIII", body, off)
        fb.reports.append(ReportBlock(
            ssrc=ssrc,
            fraction_lost=(fl_cl >> 24) / 256.0,
            packets_lost=fl_cl & 0xFFFFFF,
            highest_seq=ehsn,
            jitter=jitter,
        ))
        off += 24


def _parse_nack(body: bytes, fb: Feedback) -> None:
    off = 8  # sender ssrc + media ssrc
    while off + 4 <= len(body):
        pid, blp = struct.unpack_from("!HH", body, off)
        fb.nacks.append(pid)
        for bit in range(16):
            if blp & (1 << bit):
                fb.nacks.append((pid + bit + 1) & 0xFFFF)
        off += 4


def _parse_twcc(body: bytes, fb: Feedback) -> None:
    """draft-holmer-rmcat-transport-wide-cc-extensions-01 §3.1."""
    if len(body) < 16:
        return
    base_seq, status_count = struct.unpack_from("!HH", body, 8)
    ref_time = int.from_bytes(body[12:15], "big", signed=True)
    fb.twcc_ref_time_ms = ref_time * 64.0
    off = 16
    statuses: list[int] = []
    while len(statuses) < status_count and off + 2 <= len(body):
        chunk = struct.unpack_from("!H", body, off)[0]
        off += 2
        if chunk >> 15 == 0:  # run length
            sym = (chunk >> 13) & 0x3
            run = chunk & 0x1FFF
            statuses.extend([sym] * run)
        else:  # status vector
            if chunk >> 14 & 1:  # two-bit symbols
                for i in range(7):
                    statuses.append((chunk >> (12 - 2 * i)) & 0x3)
            else:  # one-bit symbols
                for i in range(14):
                    statuses.append(1 if chunk & (1 << (13 - i)) else 0)
    statuses = statuses[:status_count]
    for i, sym in enumerate(statuses):
        seq = (base_seq + i) & 0xFFFF
        if sym in (1, 2):  # received (small / large delta)
            if sym == 1 and off + 1 <= len(body):
                delta = body[off] * 0.25
                off += 1
            elif sym == 2 and off + 2 <= len(body):
                delta = struct.unpack_from("!h", body, off)[0] * 0.25
                off += 2
            else:
                break
            fb.twcc.append(TwccPacket(seq=seq, recv_delta_ms=delta))
        else:
            fb.twcc.append(TwccPacket(seq=seq, recv_delta_ms=None))


def build_sender_report(ssrc: int, rtp_ts: int, packets: int, octets: int,
                        now: float | None = None) -> bytes:
    now = time.time() if now is None else now
    ntp = int((now + NTP_EPOCH) * (1 << 32))
    body = struct.pack("!IIIIII", ssrc, (ntp >> 32) & 0xFFFFFFFF,
                       ntp & 0xFFFFFFFF, rtp_ts & 0xFFFFFFFF, packets, octets)
    return struct.pack("!BBH", 0x80, RTCP_SR, len(body) // 4) + body


def build_nack(sender_ssrc: int, media_ssrc: int, seqs: list[int]) -> bytes:
    """Generic NACK (RFC 4585 §6.2.1): pack missing seqs into PID+BLP
    pairs. Receiver-side counterpart of ``_parse_nack`` — the loopback
    recovery harness feeds its output straight into ``_on_srtcp``."""
    pairs: list[tuple[int, int]] = []
    for seq in sorted({s & 0xFFFF for s in seqs}):
        if pairs:
            pid, blp = pairs[-1]
            off = (seq - pid) & 0xFFFF
            if 1 <= off <= 16:
                pairs[-1] = (pid, blp | (1 << (off - 1)))
                continue
        pairs.append((seq, 0))
    body = struct.pack("!II", sender_ssrc, media_ssrc)
    for pid, blp in pairs:
        body += struct.pack("!HH", pid, blp)
    return struct.pack("!BBH", 0x80 | 1, RTCP_RTPFB, len(body) // 4) + body


def build_sdes(ssrc: int, cname: str = "selkies-tpu") -> bytes:
    item = struct.pack("!BB", 1, len(cname)) + cname.encode()
    chunk = struct.pack("!I", ssrc) + item + b"\x00"
    chunk += b"\x00" * ((4 - len(chunk) % 4) % 4)
    return struct.pack("!BBH", 0x81, RTCP_SDES, len(chunk) // 4) + chunk
