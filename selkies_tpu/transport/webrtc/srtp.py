"""SRTP / SRTCP (RFC 3711), profile AES_CM_128_HMAC_SHA1_80.

The reference gets SRTP from webrtcbin's libsrtp; this is a direct
implementation over `cryptography`'s AES-CTR (the media plane here runs
a few hundred packets/s, far below what per-packet Cipher construction
costs). Master keys come from the DTLS EXTRACTOR (dtls.py).

Covers: AES-CM key derivation (§4.3), SRTP encrypt+auth with ROC
tracking (§3.3), SRTCP with the 31-bit index + E bit (§3.4),
receiver-side index estimation and auth verification, and §3.3.2
sliding replay windows for both SRTP and SRTCP (RTCP especially: a
replayed BYE/PLI otherwise acts on the session forever).
"""

from __future__ import annotations

import hmac
import hashlib
import struct

try:
    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes
except ImportError:  # degrade to the ctypes EVP path below
    Cipher = None

AUTH_TAG_LEN = 10
SRTCP_INDEX_LEN = 4


class SrtpError(ValueError):
    pass


def _evp_aes_ctr(key: bytes, iv: bytes, n: int) -> bytes:
    """AES-128-CTR keystream via libcrypto EVP — the fallback when the
    `cryptography` package is absent (images that ship only the system
    OpenSSL). Same output, slower per-call; the media plane runs a few
    hundred packets/s so construction cost is irrelevant."""
    import ctypes
    import ctypes.util

    global _evp
    if "_evp" not in globals():
        lib = ctypes.CDLL(ctypes.util.find_library("crypto") or "libcrypto.so.3")
        lib.EVP_CIPHER_CTX_new.restype = ctypes.c_void_p
        lib.EVP_CIPHER_CTX_free.argtypes = [ctypes.c_void_p]
        lib.EVP_aes_128_ctr.restype = ctypes.c_void_p
        lib.EVP_EncryptInit_ex.argtypes = [ctypes.c_void_p] * 3 + [ctypes.c_char_p] * 2
        lib.EVP_EncryptUpdate.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.c_char_p, ctypes.c_int,
        ]
        _evp = lib
    ctx = _evp.EVP_CIPHER_CTX_new()
    if not ctx:
        raise SrtpError("EVP_CIPHER_CTX_new failed")
    try:
        if _evp.EVP_EncryptInit_ex(ctx, _evp.EVP_aes_128_ctr(), None, key, iv) != 1:
            raise SrtpError("EVP_EncryptInit_ex(aes-128-ctr) failed")
        out = ctypes.create_string_buffer(n + 16)
        outl = ctypes.c_int(0)
        if _evp.EVP_EncryptUpdate(ctx, out, ctypes.byref(outl), b"\x00" * n, n) != 1:
            raise SrtpError("EVP_EncryptUpdate failed")
        return out.raw[: outl.value]
    finally:
        _evp.EVP_CIPHER_CTX_free(ctx)


class ReplayWindow:
    """RFC 3711 §3.3.2 sliding window over packet indices (64 deep)."""

    SIZE = 64

    def __init__(self) -> None:
        self._top = -1  # highest index that passed authentication
        self._mask = 0  # bit k set => (top - k) was seen

    def check(self, index: int) -> bool:
        """True if `index` is new (not replayed, not below the window)."""
        if index > self._top:
            return True
        delta = self._top - index
        if delta >= self.SIZE:
            return False
        return not (self._mask >> delta) & 1

    def commit(self, index: int) -> None:
        """Record an index after its packet authenticated."""
        if index > self._top:
            shift = index - self._top if self._top >= 0 else self.SIZE
            self._mask = ((self._mask << min(shift, self.SIZE)) | 1) & ((1 << self.SIZE) - 1)
            self._top = index
        else:
            self._mask |= 1 << (self._top - index)


def _aes_cm_keystream(key: bytes, iv_int: int, n: int) -> bytes:
    iv = iv_int.to_bytes(16, "big")
    if Cipher is None:
        return _evp_aes_ctr(key, iv, n)
    enc = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return enc.update(b"\x00" * n) + enc.finalize()


def _derive(master_key: bytes, master_salt: bytes, label: int, n: int) -> bytes:
    """RFC 3711 §4.3.1 key derivation (kdr = 0)."""
    x = int.from_bytes(master_salt, "big") ^ (label << 48)
    return _aes_cm_keystream(master_key, x << 16, n)


class _Keys:
    def __init__(self, master_key: bytes, master_salt: bytes, *, rtcp: bool):
        base = 3 if rtcp else 0
        self.cipher = _derive(master_key, master_salt, base + 0, 16)
        self.auth = _derive(master_key, master_salt, base + 1, 20)
        self.salt = _derive(master_key, master_salt, base + 2, 14)


def _rtp_iv(salt: bytes, ssrc: int, index: int) -> int:
    return (int.from_bytes(salt, "big") << 16) ^ (ssrc << 64) ^ (index << 16)


class SrtpSession:
    """One direction pair: protect with local keys, unprotect with remote."""

    def __init__(self, local_key: bytes, local_salt: bytes,
                 remote_key: bytes, remote_salt: bytes):
        self._tx = _Keys(local_key, local_salt, rtcp=False)
        self._tx_rtcp = _Keys(local_key, local_salt, rtcp=True)
        self._rx = _Keys(remote_key, remote_salt, rtcp=False)
        self._rx_rtcp = _Keys(remote_key, remote_salt, rtcp=True)
        self._tx_roc: dict[int, int] = {}
        self._tx_last_seq: dict[int, int] = {}
        self._rx_roc: dict[int, int] = {}
        self._rx_last_seq: dict[int, int] = {}
        self._tx_rtcp_index = 0
        self._rx_replay: dict[int, ReplayWindow] = {}
        self._rx_rtcp_replay: dict[int, ReplayWindow] = {}

    # -- SRTP ---------------------------------------------------------

    @staticmethod
    def _parse_header(pkt: bytes) -> tuple[int, int, int]:
        """-> (header_len, seq, ssrc)."""
        if len(pkt) < 12 or pkt[0] >> 6 != 2:
            raise SrtpError("not an RTP packet")
        cc = pkt[0] & 0x0F
        hlen = 12 + 4 * cc
        if pkt[0] & 0x10:  # header extension
            if len(pkt) < hlen + 4:
                raise SrtpError("truncated RTP extension")
            xlen = struct.unpack("!H", pkt[hlen + 2 : hlen + 4])[0]
            hlen += 4 + 4 * xlen
        if len(pkt) < hlen:
            raise SrtpError("truncated RTP header")
        seq = struct.unpack("!H", pkt[2:4])[0]
        ssrc = struct.unpack("!I", pkt[8:12])[0]
        return hlen, seq, ssrc

    def protect(self, pkt: bytes) -> bytes:
        hlen, seq, ssrc = self._parse_header(pkt)
        last = self._tx_last_seq.get(ssrc)
        roc = self._tx_roc.get(ssrc, 0)
        if last is not None and seq < last and last - seq > 0x8000:
            roc = (roc + 1) & 0xFFFFFFFF  # sender seq wrapped
            self._tx_roc[ssrc] = roc
        self._tx_last_seq[ssrc] = seq
        index = (roc << 16) | seq
        ks = _aes_cm_keystream(
            self._tx.cipher, _rtp_iv(self._tx.salt, ssrc, index), len(pkt) - hlen
        )
        body = bytes(a ^ b for a, b in zip(pkt[hlen:], ks))
        out = pkt[:hlen] + body
        mac = hmac.new(self._tx.auth, out + struct.pack("!I", roc), hashlib.sha1)
        return out + mac.digest()[:AUTH_TAG_LEN]

    def _estimate_index(self, ssrc: int, seq: int) -> int:
        """RFC 3711 §3.3.1 receiver index estimate."""
        roc = self._rx_roc.get(ssrc, 0)
        s_l = self._rx_last_seq.get(ssrc)
        if s_l is None:
            return seq
        v = roc
        if s_l < 0x8000:
            if seq - s_l > 0x8000 and roc > 0:
                v = roc - 1
        else:
            if s_l - seq > 0x8000:
                v = roc + 1
        return (v << 16) | seq

    def unprotect(self, pkt: bytes) -> bytes:
        if len(pkt) < 12 + AUTH_TAG_LEN:
            raise SrtpError("short SRTP packet")
        tag = pkt[-AUTH_TAG_LEN:]
        body = pkt[:-AUTH_TAG_LEN]
        hlen, seq, ssrc = self._parse_header(body)
        index = self._estimate_index(ssrc, seq)
        roc = index >> 16
        window = self._rx_replay.get(ssrc)
        if window is not None and not window.check(index):
            raise SrtpError("SRTP replay")
        mac = hmac.new(self._rx.auth, body + struct.pack("!I", roc), hashlib.sha1)
        if not hmac.compare_digest(mac.digest()[:AUTH_TAG_LEN], tag):
            raise SrtpError("SRTP auth failure")
        # commit ROC/seq/replay state only after auth (window creation too:
        # spoofed SSRCs must not grow the dict)
        self._rx_replay.setdefault(ssrc, ReplayWindow()).commit(index)
        self._rx_roc[ssrc] = roc
        self._rx_last_seq[ssrc] = seq
        ks = _aes_cm_keystream(
            self._rx.cipher, _rtp_iv(self._rx.salt, ssrc, index), len(body) - hlen
        )
        return body[:hlen] + bytes(a ^ b for a, b in zip(body[hlen:], ks))

    # -- SRTCP --------------------------------------------------------

    def protect_rtcp(self, pkt: bytes) -> bytes:
        if len(pkt) < 8:
            raise SrtpError("short RTCP packet")
        ssrc = struct.unpack("!I", pkt[4:8])[0]
        self._tx_rtcp_index = (self._tx_rtcp_index + 1) & 0x7FFFFFFF
        index = self._tx_rtcp_index
        iv = (int.from_bytes(self._tx_rtcp.salt, "big") << 16) ^ (ssrc << 64) ^ (index << 16)
        ks = _aes_cm_keystream(self._tx_rtcp.cipher, iv, len(pkt) - 8)
        body = pkt[:8] + bytes(a ^ b for a, b in zip(pkt[8:], ks))
        trailer = struct.pack("!I", index | 0x80000000)  # E bit: encrypted
        mac = hmac.new(self._tx_rtcp.auth, body + trailer, hashlib.sha1)
        return body + trailer + mac.digest()[:AUTH_TAG_LEN]

    def unprotect_rtcp(self, pkt: bytes) -> bytes:
        if len(pkt) < 8 + SRTCP_INDEX_LEN + AUTH_TAG_LEN:
            raise SrtpError("short SRTCP packet")
        tag = pkt[-AUTH_TAG_LEN:]
        rest = pkt[:-AUTH_TAG_LEN]
        trailer = struct.unpack("!I", rest[-SRTCP_INDEX_LEN:])[0]
        index = trailer & 0x7FFFFFFF
        rtcp_ssrc = struct.unpack("!I", rest[4:8])[0]
        window = self._rx_rtcp_replay.get(rtcp_ssrc)
        if window is not None and not window.check(index):
            raise SrtpError("SRTCP replay")
        mac = hmac.new(self._rx_rtcp.auth, rest, hashlib.sha1)
        if not hmac.compare_digest(mac.digest()[:AUTH_TAG_LEN], tag):
            raise SrtpError("SRTCP auth failure")
        self._rx_rtcp_replay.setdefault(rtcp_ssrc, ReplayWindow()).commit(index)
        body = rest[:-SRTCP_INDEX_LEN]
        encrypted = bool(trailer & 0x80000000)
        if not encrypted:
            return body
        ssrc = struct.unpack("!I", body[4:8])[0]
        iv = (int.from_bytes(self._rx_rtcp.salt, "big") << 16) ^ (ssrc << 64) ^ (index << 16)
        ks = _aes_cm_keystream(self._rx_rtcp.cipher, iv, len(body) - 8)
        return body[:8] + bytes(a ^ b for a, b in zip(body[8:], ks))


def session_pair(keys, dtls_is_client: bool) -> SrtpSession:
    """Build the session from dtls.SrtpKeys for our DTLS role."""
    lk, ls, rk, rs = keys.for_role(dtls_is_client)
    return SrtpSession(lk, ls, rk, rs)
