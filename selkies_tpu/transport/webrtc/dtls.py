"""DTLS 1.2 over ctypes libssl.so.3 with memory BIOs.

The reference's DTLS-SRTP comes packaged inside webrtcbin
(gstwebrtc_app.py:149-196). No GStreamer/pyOpenSSL here, so OpenSSL 3 is
driven directly: records move through in-memory BIOs and the caller
shuttles the datagrams over whatever transport ICE selected. The
`use_srtp` extension negotiates SRTP_AES128_CM_SHA1_80 and the RFC 5764
EXTRACTOR exports the SRTP master keys; the peer certificate is pinned
to the SDP a=fingerprint (WebRTC's only trust anchor).

Self-signed certificates are generated with the `cryptography` package
and loaded as DER, so no files touch disk.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import datetime
import hashlib
import logging
from dataclasses import dataclass

logger = logging.getLogger("transport.webrtc.dtls")

_ssl = ctypes.CDLL(ctypes.util.find_library("ssl") or "libssl.so.3")
_crypto = ctypes.CDLL(ctypes.util.find_library("crypto") or "libcrypto.so.3")

_ssl.DTLS_method.restype = ctypes.c_void_p
_ssl.SSL_CTX_new.restype = ctypes.c_void_p
_ssl.SSL_CTX_new.argtypes = [ctypes.c_void_p]
_ssl.SSL_CTX_free.argtypes = [ctypes.c_void_p]
_ssl.SSL_CTX_use_certificate.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
_ssl.SSL_CTX_use_PrivateKey.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
_ssl.SSL_CTX_set_tlsext_use_srtp.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
_ssl.SSL_CTX_set_cipher_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
_ssl.SSL_new.restype = ctypes.c_void_p
_ssl.SSL_new.argtypes = [ctypes.c_void_p]
_ssl.SSL_free.argtypes = [ctypes.c_void_p]
_ssl.SSL_set_bio.argtypes = [ctypes.c_void_p] * 3
_ssl.SSL_set_accept_state.argtypes = [ctypes.c_void_p]
_ssl.SSL_set_connect_state.argtypes = [ctypes.c_void_p]
_ssl.SSL_do_handshake.argtypes = [ctypes.c_void_p]
_ssl.SSL_get_error.argtypes = [ctypes.c_void_p, ctypes.c_int]
_ssl.SSL_is_init_finished.argtypes = [ctypes.c_void_p]
_ssl.SSL_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
_ssl.SSL_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
# OpenSSL 3 renamed SSL_get_peer_certificate -> SSL_get1_peer_certificate
# (both return a +1-ref X509*). Bind whichever this libssl exports: a
# 1.1-only system must degrade the WebRTC plane at use, not kill every
# import of the transport stack (orchestrator/fleet run fine on the WS
# plane without DTLS).
try:
    _SSL_get_peer_cert = _ssl.SSL_get1_peer_certificate
except AttributeError:  # libssl 1.1
    _SSL_get_peer_cert = _ssl.SSL_get_peer_certificate
_SSL_get_peer_cert.restype = ctypes.c_void_p
_SSL_get_peer_cert.argtypes = [ctypes.c_void_p]
_ssl.SSL_export_keying_material.argtypes = [
    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p,
    ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int,
]
_ssl.SSL_shutdown.argtypes = [ctypes.c_void_p]
_ssl.SSL_ctrl.restype = ctypes.c_long
_ssl.SSL_ctrl.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_long, ctypes.c_void_p]

_crypto.BIO_new.restype = ctypes.c_void_p
_crypto.BIO_new.argtypes = [ctypes.c_void_p]
_crypto.BIO_s_mem.restype = ctypes.c_void_p
_crypto.BIO_write.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
_crypto.BIO_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int]
_crypto.BIO_ctrl_pending.restype = ctypes.c_size_t
_crypto.BIO_ctrl_pending.argtypes = [ctypes.c_void_p]
_crypto.BIO_ctrl.restype = ctypes.c_long
_crypto.BIO_ctrl.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_long, ctypes.c_void_p]
_crypto.d2i_X509.restype = ctypes.c_void_p
_crypto.d2i_X509.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_long]
_crypto.X509_free.argtypes = [ctypes.c_void_p]
_crypto.X509_digest.argtypes = [
    ctypes.c_void_p, ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint),
]
_crypto.EVP_sha256.restype = ctypes.c_void_p
_crypto.d2i_AutoPrivateKey.restype = ctypes.c_void_p
_crypto.d2i_AutoPrivateKey.argtypes = [
    ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_long,
]
_crypto.EVP_PKEY_free.argtypes = [ctypes.c_void_p]
_crypto.ERR_get_error.restype = ctypes.c_ulong
_crypto.ERR_error_string_n.argtypes = [ctypes.c_ulong, ctypes.c_char_p, ctypes.c_size_t]
# DTLSv1_handle_timeout is a macro over SSL_ctrl in this libssl build
DTLS_CTRL_HANDLE_TIMEOUT = 74


def is_dtls(data: bytes) -> bool:
    """Demultiplex per RFC 7983: DTLS records lead with 20-63."""
    return len(data) >= 13 and 20 <= data[0] <= 63

_ssl.SSL_CTX_set_verify.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_void_p]

SSL_VERIFY_PEER = 0x01
# chain validation always "passes": WebRTC certificates are self-signed
# and trust comes ONLY from pinning the SDP a=fingerprint after the
# handshake (_finish_handshake)
_VERIFY_CB = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_int, ctypes.c_void_p)(
    lambda ok, store_ctx: 1
)

SSL_ERROR_WANT_READ = 2
SSL_ERROR_ZERO_RETURN = 6
BIO_CTRL_EOF_RETURN = 130  # BIO_C_SET_BUF_MEM_EOF_RETURN
SSL_CTRL_SET_MTU = 17
SRTP_PROFILE = b"SRTP_AES128_CM_SHA1_80"
EXTRACTOR = b"EXTRACTOR-dtls_srtp"


class DtlsError(RuntimeError):
    pass


def _err() -> str:
    buf = ctypes.create_string_buffer(256)
    parts = []
    while True:
        e = _crypto.ERR_get_error()
        if not e:
            break
        _crypto.ERR_error_string_n(e, buf, 256)
        parts.append(buf.value.decode())
    return "; ".join(parts) or "unknown OpenSSL error"


def make_certificate():
    """Self-signed ECDSA P-256 certificate -> (cert_der, key_der,
    sha256_fingerprint 'AB:CD:...'). Prefers the `cryptography` package;
    degrades to a ctypes libcrypto implementation when it is absent so
    the WebRTC plane still comes up on system-OpenSSL-only images."""
    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import ec
        from cryptography.x509.oid import NameOID
    except ImportError:
        return _make_certificate_libcrypto()

    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "selkies-tpu")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(days=1))
        .not_valid_after(now + datetime.timedelta(days=30))
        .sign(key, hashes.SHA256())
    )
    cert_der = cert.public_bytes(serialization.Encoding.DER)
    key_der = key.private_bytes(
        serialization.Encoding.DER,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )
    digest = hashlib.sha256(cert_der).hexdigest().upper()
    fp = ":".join(digest[i : i + 2] for i in range(0, 64, 2))
    return cert_der, key_der, fp


_NID_P256 = 415  # NID_X9_62_prime256v1
_MBSTRING_ASC = 0x1001


def _i2d(fn, obj) -> bytes:
    """DER-encode via the i2d_* two-call convention."""
    n = fn(obj, None)
    if n <= 0:
        raise DtlsError(f"i2d sizing failed: {_err()}")
    buf = ctypes.create_string_buffer(n)
    ptr = ctypes.cast(buf, ctypes.c_char_p)
    fn(obj, ctypes.byref(ptr))
    return buf.raw[:n]


def _make_certificate_libcrypto():
    """make_certificate without the `cryptography` package: EC P-256
    keygen + self-signed X509 straight from the libcrypto this module
    already loaded for DER parsing."""
    c = _crypto
    for name, restype, argtypes in (
        ("EC_KEY_new_by_curve_name", ctypes.c_void_p, [ctypes.c_int]),
        ("EC_KEY_generate_key", ctypes.c_int, [ctypes.c_void_p]),
        ("EC_KEY_free", None, [ctypes.c_void_p]),
        ("EVP_PKEY_new", ctypes.c_void_p, []),
        ("EVP_PKEY_set1_EC_KEY", ctypes.c_int, [ctypes.c_void_p] * 2),
        ("X509_new", ctypes.c_void_p, []),
        ("X509_set_version", ctypes.c_int, [ctypes.c_void_p, ctypes.c_long]),
        ("X509_get_serialNumber", ctypes.c_void_p, [ctypes.c_void_p]),
        ("ASN1_INTEGER_set", ctypes.c_int, [ctypes.c_void_p, ctypes.c_long]),
        ("X509_getm_notBefore", ctypes.c_void_p, [ctypes.c_void_p]),
        ("X509_getm_notAfter", ctypes.c_void_p, [ctypes.c_void_p]),
        ("X509_gmtime_adj", ctypes.c_void_p, [ctypes.c_void_p, ctypes.c_long]),
        ("X509_get_subject_name", ctypes.c_void_p, [ctypes.c_void_p]),
        ("X509_NAME_add_entry_by_txt", ctypes.c_int, [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int, ctypes.c_int, ctypes.c_int]),
        ("X509_set_issuer_name", ctypes.c_int, [ctypes.c_void_p] * 2),
        ("X509_set_pubkey", ctypes.c_int, [ctypes.c_void_p] * 2),
        ("X509_sign", ctypes.c_int, [ctypes.c_void_p] * 3),
        ("i2d_X509", ctypes.c_int, [ctypes.c_void_p,
                                    ctypes.POINTER(ctypes.c_char_p)]),
        ("i2d_PrivateKey", ctypes.c_int, [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_char_p)]),
    ):
        fn = getattr(c, name)
        fn.restype = restype
        fn.argtypes = argtypes

    ec_key = c.EC_KEY_new_by_curve_name(_NID_P256)
    if not ec_key or c.EC_KEY_generate_key(ec_key) != 1:
        raise DtlsError(f"EC P-256 keygen failed: {_err()}")
    pkey = c.EVP_PKEY_new()
    x509 = None
    try:
        if c.EVP_PKEY_set1_EC_KEY(pkey, ec_key) != 1:
            raise DtlsError(f"EVP_PKEY_set1_EC_KEY failed: {_err()}")
        x509 = c.X509_new()
        if not x509:
            raise DtlsError(f"X509_new failed: {_err()}")
        c.X509_set_version(x509, 2)  # X509v3
        import secrets

        c.ASN1_INTEGER_set(c.X509_get_serialNumber(x509),
                           secrets.randbits(31) or 1)
        c.X509_gmtime_adj(c.X509_getm_notBefore(x509), -86400)
        c.X509_gmtime_adj(c.X509_getm_notAfter(x509), 30 * 86400)
        name = c.X509_get_subject_name(x509)
        if c.X509_NAME_add_entry_by_txt(
                name, b"CN", _MBSTRING_ASC, b"selkies-tpu", -1, -1, 0) != 1:
            raise DtlsError(f"X509_NAME_add_entry failed: {_err()}")
        c.X509_set_issuer_name(x509, name)
        if c.X509_set_pubkey(x509, pkey) != 1:
            raise DtlsError(f"X509_set_pubkey failed: {_err()}")
        if c.X509_sign(x509, pkey, c.EVP_sha256()) == 0:
            raise DtlsError(f"X509_sign failed: {_err()}")
        cert_der = _i2d(c.i2d_X509, x509)
        key_der = _i2d(c.i2d_PrivateKey, pkey)
    finally:
        c.EC_KEY_free(ec_key)
        if x509:
            c.X509_free(x509)
        c.EVP_PKEY_free(pkey)
    digest = hashlib.sha256(cert_der).hexdigest().upper()
    fp = ":".join(digest[i : i + 2] for i in range(0, 64, 2))
    return cert_der, key_der, fp


@dataclass
class SrtpKeys:
    """RFC 5764 §4.2: exported key block split per role."""

    client_key: bytes
    server_key: bytes
    client_salt: bytes
    server_salt: bytes

    def for_role(self, is_client: bool):
        """(local_key, local_salt, remote_key, remote_salt)."""
        if is_client:
            return (self.client_key, self.client_salt,
                    self.server_key, self.server_salt)
        return (self.server_key, self.server_salt,
                self.client_key, self.client_salt)


class DtlsEndpoint:
    """One DTLS association over memory BIOs.

    Usage: feed incoming datagrams with `put_datagram`, collect outgoing
    ones from `take_datagrams` after any call, drive with `handshake_step`
    until `handshake_complete`, then `send`/`recv` application data
    (SCTP) and read `srtp_keys`.
    """

    def __init__(self, *, is_server: bool, cert_der: bytes, key_der: bytes,
                 peer_fingerprint: str | None = None, mtu: int = 1200):
        self._ctx = _ssl.SSL_CTX_new(_ssl.DTLS_method())
        if not self._ctx:
            raise DtlsError(f"SSL_CTX_new: {_err()}")
        p = ctypes.c_char_p(cert_der)
        x509 = _crypto.d2i_X509(None, ctypes.byref(p), len(cert_der))
        if not x509 or _ssl.SSL_CTX_use_certificate(self._ctx, x509) != 1:
            raise DtlsError(f"use_certificate: {_err()}")
        _crypto.X509_free(x509)
        p = ctypes.c_char_p(key_der)
        pkey = _crypto.d2i_AutoPrivateKey(None, ctypes.byref(p), len(key_der))
        if not pkey or _ssl.SSL_CTX_use_PrivateKey(self._ctx, pkey) != 1:
            raise DtlsError(f"use_PrivateKey: {_err()}")
        _crypto.EVP_PKEY_free(pkey)
        if _ssl.SSL_CTX_set_tlsext_use_srtp(self._ctx, SRTP_PROFILE) != 0:
            raise DtlsError(f"use_srtp: {_err()}")
        # request (and on the server side, demand) the peer certificate
        _ssl.SSL_CTX_set_verify(self._ctx, SSL_VERIFY_PEER, _VERIFY_CB)
        self._ssl = _ssl.SSL_new(self._ctx)
        if not self._ssl:
            raise DtlsError(f"SSL_new: {_err()}")
        self._rbio = _crypto.BIO_new(_crypto.BIO_s_mem())
        self._wbio = _crypto.BIO_new(_crypto.BIO_s_mem())
        # empty read BIO must report retry, not EOF
        _crypto.BIO_ctrl(self._rbio, BIO_CTRL_EOF_RETURN, -1, None)
        _crypto.BIO_ctrl(self._wbio, BIO_CTRL_EOF_RETURN, -1, None)
        _ssl.SSL_set_bio(self._ssl, self._rbio, self._wbio)
        _ssl.SSL_ctrl(self._ssl, SSL_CTRL_SET_MTU, mtu, None)
        self.is_server = is_server
        if is_server:
            _ssl.SSL_set_accept_state(self._ssl)
        else:
            _ssl.SSL_set_connect_state(self._ssl)
        self.peer_fingerprint = peer_fingerprint
        self.handshake_complete = False
        self.srtp_keys: SrtpKeys | None = None
        self._closed = False

    # -- datagram plumbing -------------------------------------------

    def put_datagram(self, data: bytes) -> None:
        _crypto.BIO_write(self._rbio, data, len(data))

    def take_datagrams(self) -> list[bytes]:
        out = []
        while True:
            n = _crypto.BIO_ctrl_pending(self._wbio)
            if not n:
                return out
            buf = ctypes.create_string_buffer(int(n))
            got = _crypto.BIO_read(self._wbio, buf, int(n))
            if got <= 0:
                return out
            out.append(buf.raw[:got])

    # -- handshake ----------------------------------------------------

    def handshake_step(self) -> bool:
        """Advance the handshake; True when complete. Call after feeding
        each incoming datagram (and once to kick off a client)."""
        if self.handshake_complete:
            return True
        rc = _ssl.SSL_do_handshake(self._ssl)
        if rc == 1:
            self._finish_handshake()
            return True
        err = _ssl.SSL_get_error(self._ssl, rc)
        if err == SSL_ERROR_WANT_READ:
            return False
        raise DtlsError(f"handshake failed (ssl_error={err}): {_err()}")

    def handle_timeout(self) -> None:
        """Retransmit a lost flight (call on a ~1 s timer until done)."""
        if not self.handshake_complete:
            _ssl.SSL_ctrl(self._ssl, DTLS_CTRL_HANDLE_TIMEOUT, 0, None)

    def _finish_handshake(self) -> None:
        if self.peer_fingerprint is not None:
            cert = _SSL_get_peer_cert(self._ssl)
            if not cert:
                raise DtlsError("peer sent no certificate")
            md = ctypes.create_string_buffer(32)
            n = ctypes.c_uint(0)
            _crypto.X509_digest(cert, _crypto.EVP_sha256(), md, ctypes.byref(n))
            _crypto.X509_free(cert)
            fp = ":".join(f"{b:02X}" for b in md.raw[: n.value])
            if fp != self.peer_fingerprint.upper():
                raise DtlsError("peer certificate fingerprint mismatch")
        block = ctypes.create_string_buffer(60)
        if _ssl.SSL_export_keying_material(
            self._ssl, block, 60, EXTRACTOR, len(EXTRACTOR), None, 0, 0
        ) != 1:
            raise DtlsError(f"export_keying_material: {_err()}")
        b = block.raw
        self.srtp_keys = SrtpKeys(
            client_key=b[0:16], server_key=b[16:32],
            client_salt=b[32:46], server_salt=b[46:60],
        )
        self.handshake_complete = True

    # -- application data (SCTP rides here) --------------------------

    def send(self, data: bytes) -> None:
        rc = _ssl.SSL_write(self._ssl, data, len(data))
        if rc <= 0:
            err = _ssl.SSL_get_error(self._ssl, rc)
            raise DtlsError(f"SSL_write failed (ssl_error={err}): {_err()}")

    def recv(self) -> list[bytes]:
        """Drain decrypted application datagrams."""
        out = []
        buf = ctypes.create_string_buffer(65536)
        while True:
            rc = _ssl.SSL_read(self._ssl, buf, 65536)
            if rc > 0:
                out.append(buf.raw[:rc])
                continue
            err = _ssl.SSL_get_error(self._ssl, rc)
            if err in (SSL_ERROR_WANT_READ, SSL_ERROR_ZERO_RETURN):
                return out
            raise DtlsError(f"SSL_read failed (ssl_error={err}): {_err()}")

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            _ssl.SSL_shutdown(self._ssl)

    def __del__(self):  # pragma: no cover - gc order dependent
        try:
            if getattr(self, "_ssl", None):
                _ssl.SSL_free(self._ssl)  # frees the BIOs too
                self._ssl = None
            if getattr(self, "_ctx", None):
                _ssl.SSL_CTX_free(self._ctx)
                self._ctx = None
        except Exception:
            pass
