"""From-scratch WebRTC media plane (TPU-native framework counterpart of
the reference's webrtcbin, gstwebrtc_app.py:149-196).

The reference delegates its entire transport to GStreamer's webrtcbin
(libnice ICE + DTLS-SRTP + SCTP). None of those libraries exist in this
image, so the stack is reimplemented directly on asyncio UDP:

- stun.py  — RFC 5389 STUN + RFC 8445 ICE attributes + RFC 5766 TURN
- dtls.py  — DTLS 1.2 over ctypes libssl.so.3 (memory BIOs), with the
             use_srtp extension and EXTRACTOR-dtls_srtp key export
- srtp.py  — RFC 3711 SRTP/SRTCP, AES_CM_128_HMAC_SHA1_80
- ice.py   — ICE agent: host/srflx/relay gathering, connectivity checks
- sctp.py  — minimal SCTP over DTLS + RFC 8832 DCEP data channels
- sdp.py   — offer/answer with the reference's munging list
- peer.py  — the peer connection tying the layers together
"""
