"""SDP offer/answer for the bundled video+audio+datachannel session.

Mirrors the reference's munged webrtcbin offer (gstwebrtc_app.py
__on_offer_created, :1581-1636): H.264 fmtp carries
level-asymmetry-allowed=1;packetization-mode=1;profile-level-id=42e01f;
sps-pps-idr-in-keyframe=1, Opus gets ptime:10 + in-band FEC, video
carries nack/nack pli/transport-cc feedback and the transport-wide-cc +
playout-delay header extensions (rtp_add_extensions, :1657-1689).
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass, field

VIDEO_PT = 96
RED_PT = 98
ULPFEC_PT = 99
AUDIO_PT = 111
TWCC_EXT_ID = 3
PLAYOUT_DELAY_EXT_ID = 2
TWCC_URI = "http://www.ietf.org/id/draft-holmer-rmcat-transport-wide-cc-extensions-01"
PLAYOUT_DELAY_URI = "http://www.webrtc.org/experiments/rtp-hdrext/playout-delay"

H264_FMTP = ("level-asymmetry-allowed=1;packetization-mode=1;"
             "profile-level-id=42e01f;sps-pps-idr-in-keyframe=1")
# Main profile (profile_idc 77, constraint_set1, level 3.1) — what the
# CABAC entropy backend's SPS declares (bitstream.py write_sps); the
# fmtp must match the stream or strict browsers refuse the track
H264_FMTP_MAIN = ("level-asymmetry-allowed=1;packetization-mode=1;"
                  "profile-level-id=4d401f;sps-pps-idr-in-keyframe=1")
VP8_FMTP = ""
VP9_FMTP = "profile-id=0"

# AV1 level 5.1 (seq_level_idx 13): MaxDisplayRate covers 1080p60
# (124.4 Mpx/s needs ≥ 5.0) and 4K30 (248 Mpx/s needs 5.1)
AV1_FMTP = "level-idx=13;profile=0;tier=0"
# RFC 7798 §7.1: level-id 123 = level 4.1 (max luma rate 133.7 Ms/s ≥
# 1080p60's 124.4); sprop parameter sets ride in-band (repeat-headers),
# matching the H.264 row's sps-pps-idr-in-keyframe approach
H265_FMTP = "level-id=123;tx-mode=SRST"

CODEC_RTPMAP = {
    "h264": f"{VIDEO_PT} H264/90000",
    "vp8": f"{VIDEO_PT} VP8/90000",
    "vp9": f"{VIDEO_PT} VP9/90000",
    "av1": f"{VIDEO_PT} AV1/90000",
    "h265": f"{VIDEO_PT} H265/90000",
}
CODEC_FMTP = {"h264": H264_FMTP, "vp8": VP8_FMTP, "vp9": VP9_FMTP,
              "av1": AV1_FMTP, "h265": H265_FMTP}


def build_offer(*, ice_ufrag: str, ice_pwd: str, fingerprint: str,
                video_ssrc: int, audio_ssrc: int, codec: str = "h264",
                session_id: str | None = None, audio: bool = True,
                h264_profile: str = "baseline") -> str:
    sid = session_id or str(int.from_bytes(secrets.token_bytes(6), "big"))
    cname = "selkies-tpu"
    mids = ["video0"] + (["audio0"] if audio else []) + ["application0"]
    lines = [
        "v=0",
        f"o=- {sid} 2 IN IP4 127.0.0.1",
        "s=-",
        "t=0 0",
        "a=group:BUNDLE " + " ".join(mids),
        "a=msid-semantic: WMS selkies",
        "a=ice-options:trickle",
    ]

    def transport_attrs():
        return [
            f"a=ice-ufrag:{ice_ufrag}",
            f"a=ice-pwd:{ice_pwd}",
            f"a=fingerprint:sha-256 {fingerprint}",
            "a=setup:actpass",
        ]

    lines += [
        f"m=video 9 UDP/TLS/RTP/SAVPF {VIDEO_PT} {RED_PT} {ULPFEC_PT}",
        "c=IN IP4 0.0.0.0",
        "a=rtcp:9 IN IP4 0.0.0.0",
        "a=mid:video0",
        "a=sendonly",
        "a=rtcp-mux",
        "a=rtcp-rsize",
        *transport_attrs(),
        "a=rtpmap:" + CODEC_RTPMAP[codec],
        f"a=extmap:{TWCC_EXT_ID} {TWCC_URI}",
        f"a=extmap:{PLAYOUT_DELAY_EXT_ID} {PLAYOUT_DELAY_URI}",
        f"a=rtcp-fb:{VIDEO_PT} nack",
        f"a=rtcp-fb:{VIDEO_PT} nack pli",
        f"a=rtcp-fb:{VIDEO_PT} transport-cc",
        f"a=rtpmap:{RED_PT} red/90000",
        f"a=rtpmap:{ULPFEC_PT} ulpfec/90000",
        f"a=msid:selkies selkies-video",
        f"a=ssrc:{video_ssrc} cname:{cname}",
        f"a=ssrc:{video_ssrc} msid:selkies selkies-video",
    ]
    fmtp = CODEC_FMTP[codec]
    if codec == "h264" and h264_profile == "main":
        fmtp = H264_FMTP_MAIN
    if fmtp:
        lines.insert(lines.index("a=rtpmap:" + CODEC_RTPMAP[codec]) + 1,
                     f"a=fmtp:{VIDEO_PT} {fmtp}")
    if audio:
        lines += [
            f"m=audio 9 UDP/TLS/RTP/SAVPF {AUDIO_PT}",
            "c=IN IP4 0.0.0.0",
            "a=rtcp:9 IN IP4 0.0.0.0",
            "a=mid:audio0",
            "a=sendonly",
            "a=rtcp-mux",
            *transport_attrs(),
            f"a=rtpmap:{AUDIO_PT} OPUS/48000/2",
            f"a=fmtp:{AUDIO_PT} minptime=10;useinbandfec=1;stereo=1",
            "a=ptime:10",
            f"a=extmap:{TWCC_EXT_ID} {TWCC_URI}",
            f"a=rtcp-fb:{AUDIO_PT} transport-cc",
            f"a=msid:selkies selkies-audio",
            f"a=ssrc:{audio_ssrc} cname:{cname}",
            f"a=ssrc:{audio_ssrc} msid:selkies selkies-audio",
        ]
    lines += [
        "m=application 9 UDP/DTLS/SCTP webrtc-datachannel",
        "c=IN IP4 0.0.0.0",
        "a=mid:application0",
        *transport_attrs(),
        "a=sctp-port:5000",
        "a=max-message-size:262144",
    ]
    return "\r\n".join(lines) + "\r\n"


@dataclass
class RemoteDescription:
    ice_ufrag: str = ""
    ice_pwd: str = ""
    fingerprint: str = ""
    setup: str = ""
    candidates: list[str] = field(default_factory=list)
    video_pt: int | None = None
    audio_pt: int | None = None
    red_pt: int | None = None
    ulpfec_pt: int | None = None
    twcc_id: int | None = None
    playout_delay_id: int | None = None
    sctp_port: int = 5000
    # lowercase codec name of the chosen video_pt ("h264"/"vp8"/"vp9"/
    # "av1"/"h265"); peer.py compares it against the offered codec and
    # fails the session loudly on a mismatch
    video_codec: str | None = None
    # JSEP rejection: the answer carried "m=video 0 ..." (libwebrtc still
    # echoes the offered rtpmaps inside a rejected section, so video_pt
    # stays None and peer.py refuses the session)
    video_rejected: bool = False


def parse_answer(sdp: str, prefer: str = "h264") -> RemoteDescription:
    """Extract what the transport needs from the browser's answer.

    Session-level attributes apply to every m-section; the first
    media-level occurrence wins otherwise (BUNDLE shares one transport).
    `prefer` is the codec the offer carried: an AV1/H.265 session must
    pick that payload type even if the answer also lists H.264/VP8/VP9
    (and vice versa — an answer listing AV1 first must not shadow an
    H.264 session's PT)."""
    r = RemoteDescription()
    prefer_token = {
        "h264": "H264/", "vp8": "VP8/", "vp9": "VP9/",
        "av1": "AV1/", "h265": "H265/",
    }.get(prefer.lower(), "H264/")
    video_tokens = ("H264/", "VP8/", "VP9/", "AV1/", "H265/")
    preferred_seen = False
    in_rejected_video = False
    current_rtpmaps: dict[int, str] = {}
    for raw in sdp.replace("\r\n", "\n").split("\n"):
        line = raw.strip()
        if line.startswith("m="):
            # JSEP rejects an m-section by setting its port to 0; any
            # rtpmaps echoed inside it must not negotiate the codec
            parts = line.split()
            in_rejected_video = (line.startswith("m=video")
                                 and len(parts) >= 2 and parts[1] == "0")
            if in_rejected_video:
                r.video_rejected = True
        if line.startswith("a=ice-ufrag:") and not r.ice_ufrag:
            r.ice_ufrag = line.split(":", 1)[1]
        elif line.startswith("a=ice-pwd:") and not r.ice_pwd:
            r.ice_pwd = line.split(":", 1)[1]
        elif line.startswith("a=fingerprint:sha-256") and not r.fingerprint:
            parts = line.split(None, 1)
            if len(parts) < 2:
                raise ValueError("fingerprint attribute missing its value")
            r.fingerprint = parts[1].strip()
        elif line.startswith("a=setup:") and not r.setup:
            r.setup = line.split(":", 1)[1]
        elif line.startswith("a=candidate:"):
            r.candidates.append(line[2:])
        elif line.startswith("a=rtpmap:"):
            body = line[len("a=rtpmap:"):]
            pt, enc = body.split(" ", 1)
            current_rtpmaps[int(pt)] = enc
            token = next((t for t in video_tokens
                          if enc.upper().startswith(t)), None)
            if token is not None and not in_rejected_video:
                if token == prefer_token and not preferred_seen:
                    r.video_pt = int(pt)
                    r.video_codec = token[:-1].lower()
                    preferred_seen = True
                elif r.video_pt is None:
                    # fallback: the offered codec is missing from the
                    # answer; record what the browser gave us so the
                    # peer can refuse the session with a clear error
                    r.video_pt = int(pt)
                    r.video_codec = token[:-1].lower()
            elif enc.lower().startswith("red/") and r.red_pt is None:
                r.red_pt = int(pt)
            elif enc.lower().startswith("ulpfec/") and r.ulpfec_pt is None:
                r.ulpfec_pt = int(pt)
            elif enc.upper().startswith("OPUS/") and r.audio_pt is None:
                # RFC 3264 lets the answer re-number audio too; the
                # payloader must send what the answer negotiated
                r.audio_pt = int(pt)
        elif line.startswith("a=extmap:"):
            body = line[len("a=extmap:"):]
            eid, uri = body.split(" ", 1)
            if uri.strip() == TWCC_URI and r.twcc_id is None:
                r.twcc_id = int(eid.split("/")[0])
            elif uri.strip() == PLAYOUT_DELAY_URI and r.playout_delay_id is None:
                r.playout_delay_id = int(eid.split("/")[0])
        elif line.startswith("a=sctp-port:"):
            r.sctp_port = int(line.split(":", 1)[1])
    return r
