"""RTP packetization for VP8 (RFC 7741) and VP9
(draft-ietf-payload-vp9) — the rtpvp8pay/rtpvp9pay equivalents
(reference chain: vp8enc/vp9enc ! rtpvp8pay/rtpvp9pay,
gstwebrtc_app.py:685-722, 873-915).

Both codecs ship whole frames (no NAL structure): the payloader
fragments the frame across packets behind a small payload descriptor.
Keyframe detection reads the codec's own uncompressed header — VP8's
first byte carries frame_type in bit 0 (keyframe=0); VP9's carries
frame_marker/profile/show_existing/frame_type bits (see _vp9_is_key).
The wire-overhead reserve matches transport/rtp.py's H.264 payloader.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from selkies_tpu.transport.rtp import MTU_DEFAULT, RtpPacket, RtpSequenceMixin

__all__ = ["Vp8Payloader", "Vp9Payloader", "Vp8Depayloader", "Vp9Depayloader"]


def vp8_is_keyframe(frame: bytes) -> bool:
    # VP8 frame tag (RFC 6386 §9.1): bit 0 of byte 0 is frame_type,
    # 0 = key frame
    return bool(frame) and not frame[0] & 0x01


def vp9_is_keyframe(frame: bytes) -> bool:
    """VP9 uncompressed header (spec 6.2): frame_marker(2)=0b10,
    profile_low(1), profile_high(1), then (profile<3):
    show_existing_frame(1), frame_type(1) with 0 = key."""
    if not frame:
        return False
    b0 = frame[0]
    if b0 >> 6 != 0b10:
        return False
    profile = ((b0 >> 5) & 1) | (((b0 >> 4) & 1) << 1)
    if profile == 3:
        # reserved bit shifts the layout; profile 3 is 4:4:4 12-bit —
        # not produced by this framework's rows
        return False
    if (b0 >> 3) & 1:  # show_existing_frame
        return False
    return not (b0 >> 2) & 1


@dataclass
class Vp8Payloader(RtpSequenceMixin):
    """VP8 frames → RTP packets (RFC 7741).

    Descriptor: X=1 with a 15-bit PictureID (libwebrtc's jitter buffer
    uses it for frame continuity across loss), S=1 on the first packet
    of a frame, PID(partition)=0 — the non-aggregated layout every
    browser accepts."""

    payload_type: int = 97
    ssrc: int = 0x53454C38  # 'SEL8'
    mtu: int = MTU_DEFAULT
    sequence: int = 0
    picture_id: int = 0

    def payload_au(self, frame: bytes, timestamp: int) -> list[RtpPacket]:
        if not frame:
            return []
        max_payload = self.mtu - 54 - 4  # descriptor: 1 + X byte + 2 PID
        pid = self.picture_id
        self.picture_id = (self.picture_id + 1) & 0x7FFF
        out = []
        for i in range(0, len(frame), max_payload):
            first = i == 0
            desc = bytes([0x80 | (0x10 if first else 0)])  # X=1, S, PID=0
            desc += bytes([0x80])                          # I=1
            desc += struct.pack("!H", 0x8000 | pid)        # M=1, 15-bit ID
            out.append(RtpPacket(
                self.payload_type, self._next_seq(), timestamp, self.ssrc,
                desc + frame[i: i + max_payload]))
        out[-1].marker = True
        return out


@dataclass
class Vp9Payloader(RtpSequenceMixin):
    """VP9 frames → RTP packets (draft-ietf-payload-vp9, non-flexible
    mode): I=1 15-bit PictureID, P set on inter frames, B/E mark frame
    boundaries."""

    payload_type: int = 98
    ssrc: int = 0x53454C39  # 'SEL9'
    mtu: int = MTU_DEFAULT
    sequence: int = 0
    picture_id: int = 0

    def payload_au(self, frame: bytes, timestamp: int) -> list[RtpPacket]:
        if not frame:
            return []
        max_payload = self.mtu - 54 - 3  # descriptor: 1 + 2-byte PID
        inter = 0x40 if not vp9_is_keyframe(frame) else 0
        pid = self.picture_id
        self.picture_id = (self.picture_id + 1) & 0x7FFF
        chunks = [frame[i: i + max_payload]
                  for i in range(0, len(frame), max_payload)]
        out = []
        for i, chunk in enumerate(chunks):
            b = 0x08 if i == 0 else 0                 # B: frame start
            e = 0x04 if i == len(chunks) - 1 else 0   # E: frame end
            desc = bytes([0x80 | inter | b | e])      # I=1
            desc += struct.pack("!H", 0x8000 | pid)   # M=1, 15-bit ID
            out.append(RtpPacket(
                self.payload_type, self._next_seq(), timestamp, self.ssrc,
                desc + chunk))
        out[-1].marker = True
        return out


class _VpxDepayloader:
    """Common fragment reassembly: descriptor length is codec-specific."""

    def __init__(self) -> None:
        self._frame = bytearray()

    def _desc_len(self, p: bytes) -> int:
        raise NotImplementedError

    def push(self, pkt: RtpPacket) -> bytes | None:
        p = pkt.payload
        if not p:
            return None
        self._frame.extend(p[self._desc_len(p):])
        if pkt.marker:
            frame = bytes(self._frame)
            self._frame = bytearray()
            return frame
        return None


class Vp8Depayloader(_VpxDepayloader):
    def _desc_len(self, p: bytes) -> int:
        n = 1
        if p[0] & 0x80:  # X
            x = p[n]
            n += 1
            if x & 0x80:  # I: PictureID
                n += 2 if p[n] & 0x80 else 1
            if x & 0x40:  # L: TL0PICIDX
                n += 1
            if x & 0x30:  # T/K: TID/KEYIDX byte
                n += 1
        return n


class Vp9Depayloader(_VpxDepayloader):
    def _desc_len(self, p: bytes) -> int:
        b0 = p[0]
        n = 1
        if b0 & 0x80:  # I: PictureID
            n += 2 if p[n] & 0x80 else 1
        if b0 & 0x20:  # L: layer indices (non-flexible adds TL0PICIDX)
            n += 1
            if not b0 & 0x10:  # F=0
                n += 1
        if b0 & 0x10 and b0 & 0x40:  # F and P: P_DIFF chain
            while p[n] & 0x01:
                n += 1
            n += 1
        if b0 & 0x02:  # V: scalability structure — parse and skip
            ss = p[n]
            n += 1
            n_s = (ss >> 5) + 1
            if ss & 0x10:  # Y: each layer has W/H
                n += 4 * n_s
            if ss & 0x08:  # G: picture group
                n_g = p[n]
                n += 1
                for _ in range(n_g):
                    g = p[n]
                    n += 1
                    n += (g >> 2) & 0x3  # R reference indices
        return n
