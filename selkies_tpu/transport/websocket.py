"""WebSocket media transport.

The reference's byte plane is webrtcbin (ICE+DTLS+SRTP+SCTP).  This
transport is the framework's always-available fallback and test plane: one
WebSocket carries both the media stream (binary messages) and the data
channel (text messages), multiplexed by message type.  The browser client
plays the video messages with WebCodecs (H.264 Annex-B) and treats text
messages exactly like RTCDataChannel payloads, so every protocol above
this layer (input vocabulary, stats, clipboard, cursor, system actions) is
identical to the WebRTC path.

Binary frame layout (network order):
    u8  kind      1=video 2=audio
    u8  flags     bit0 = keyframe (IDR)
    u16 seq       video: per-frame sequence (congestion-control feedback
                  key: the client echoes `_ack,<seq>,<recv_ms>`); audio: 0
    u32 timestamp video: 90 kHz clock; audio: 48 kHz sample clock
    ... payload   video: Annex-B access unit; audio: Opus packet
"""

from __future__ import annotations

import asyncio
import logging
import struct
import time
from typing import Any, Awaitable, Callable

from aiohttp import WSMsgType, web

from selkies_tpu.monitoring.telemetry import telemetry
from selkies_tpu.monitoring.tracing import tracer

logger = logging.getLogger("transport.ws")

HEADER = struct.Struct("!BBHI")
KIND_VIDEO = 1
KIND_AUDIO = 2
FLAG_KEYFRAME = 1


def pack_media_frame(kind: int, flags: int, timestamp: int, payload: bytes, seq: int = 0) -> bytes:
    return HEADER.pack(kind, flags, seq & 0xFFFF, timestamp & 0xFFFFFFFF) + payload


def parse_media_frame(data: bytes) -> tuple[int, int, int, bytes]:
    kind, flags, _, ts = HEADER.unpack_from(data)
    return kind, flags, ts, data[HEADER.size :]


def parse_media_frame_seq(data: bytes) -> int:
    return HEADER.unpack_from(data)[2]


class WebSocketTransport:
    """Server side of the WS media plane; implements the app Transport
    protocol (pipeline/app.py) and registers under /media on the web
    server."""

    def __init__(self) -> None:
        self._ws: web.WebSocketResponse | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self.on_data_message: Callable[[str], Awaitable[None] | None] = lambda m: None
        self.on_connect: Callable[[], Any] = lambda: None
        self.on_disconnect: Callable[[], Any] = lambda: None
        # congestion control taps (GccController.on_frame_sent wiring)
        self.on_video_sent: Callable[[int, float, int], None] = lambda seq, ms, size: None
        self.frames_sent = 0
        self.bytes_sent = 0
        self._video_seq = 0
        # telemetry session label (fleet sets its slot index; solo = "0")
        self.session = "0"

    # -- Transport protocol -------------------------------------------

    @property
    def data_channel_ready(self) -> bool:
        return self._ws is not None and not self._ws.closed

    async def close(self) -> None:
        """Server-initiated disconnect (admission refused, drain): close
        the live socket; the connection handler's finally runs the
        normal on_disconnect path."""
        ws = self._ws
        if ws is not None and not ws.closed:
            await ws.close()

    def send_data_channel(self, message: str) -> None:
        """Callable from the event loop or worker threads (reference
        bridges with run_coroutine_threadsafe, gstwebrtc_app.py:1792)."""
        ws, loop = self._ws, self._loop
        if ws is None or ws.closed or loop is None:
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        coro = self._safe_send_str(ws, message)
        if running is loop:
            loop.create_task(coro)
        else:
            asyncio.run_coroutine_threadsafe(coro, loop)

    @staticmethod
    async def _safe_send_str(ws: web.WebSocketResponse, message: str) -> None:
        try:
            await ws.send_str(message)
        except (ConnectionError, RuntimeError):
            pass

    async def send_video(self, ef) -> bool:
        """EncodedFrame (pipeline/elements.py) → binary WS message.
        Returns False when the client is gone / the socket failed so the
        fleet's per-slot send accounting sees it (parallel/fleet.py)."""
        flags = FLAG_KEYFRAME if ef.idr else 0
        seq = self._video_seq = (self._video_seq + 1) & 0xFFFF
        # sample the send clock BEFORE the await: under TCP backpressure
        # send_bytes blocks until the socket drains, and enqueue-time deltas
        # are what let the trendline see the queue growing (congestion would
        # otherwise inflate Δsend to match Δrecv and hide itself)
        send_ms = time.monotonic() * 1000.0
        # register with the estimator BEFORE the await: under backpressure
        # the client's ack can arrive while send_bytes is still draining,
        # and an ack for an unregistered seq would be dropped. A frame that
        # fails to send leaves a stale entry, which simply ages out.
        self.on_video_sent(seq, send_ms, len(ef.au) + HEADER.size)
        tele = telemetry.enabled
        if tele:
            # seq -> frame-id so the client's ack correlates back to the
            # frame's capture/encode events (congestion.on_frame_ack)
            telemetry.map_seq(self.session, seq, getattr(ef, "frame_id", 0))
            t0 = time.perf_counter()
        with tracer.span("ws-send"):
            ok = await self._send_binary(
                pack_media_frame(KIND_VIDEO, flags, ef.timestamp_90k, ef.au, seq))
        if tele:
            telemetry.stage_ms("ws-send", (time.perf_counter() - t0) * 1e3,
                               session=self.session,
                               frame=getattr(ef, "frame_id", 0),
                               seq=seq, bytes=len(ef.au), ok=ok)
        return ok

    async def send_audio(self, ea) -> None:
        """EncodedAudio (audio/pipeline.py) → binary WS message."""
        await self._send_binary(pack_media_frame(KIND_AUDIO, 0, ea.timestamp_48k, ea.packet))

    async def _send_binary(self, data: bytes) -> bool:
        ws = self._ws
        if ws is None or ws.closed:
            return False
        try:
            await ws.send_bytes(data)
            self.frames_sent += 1
            self.bytes_sent += len(data)
            return True
        except (ConnectionError, RuntimeError):
            logger.info("media send failed; client gone")
            return False

    # -- aiohttp endpoint ---------------------------------------------

    async def handle_connection(self, request: web.Request) -> web.WebSocketResponse:
        """Register under the web server's ws_routes as the /media path."""
        ws = web.WebSocketResponse(heartbeat=20.0, max_msg_size=32 * 1024 * 1024)
        await ws.prepare(request)
        if self._ws is not None and not self._ws.closed:
            logger.info("replacing existing media client")
            await self._ws.close()
        self._ws = ws
        self._loop = asyncio.get_running_loop()
        logger.info("media client connected from %s", request.remote)
        try:
            result = self.on_connect()
            if asyncio.iscoroutine(result):
                await result
            async for msg in ws:
                if msg.type == WSMsgType.TEXT:
                    result = self.on_data_message(msg.data)
                    if asyncio.iscoroutine(result):
                        await result
                # binary upstream messages are not part of the protocol
        finally:
            if self._ws is ws:
                self._ws = None
                result = self.on_disconnect()
                if asyncio.iscoroutine(result):
                    await result
            logger.info("media client disconnected")
        return ws
