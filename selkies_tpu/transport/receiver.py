"""RecoveringReceiver: a loss-simulating receiver that actually recovers.

In production the recovery half of the transport runs in the browser:
it NACKs gaps, rebuilds singles from ULP FEC parity, and freezes the
canvas when a frame can never be completed. To *measure* the sender's
recovery ladder (bench.py --impair) and to regression-test it
deterministically (tests/test_recovery.py), this module implements that
half honestly: RED demux, duplicate suppression, gap detection with
NACK scheduling, FEC single-loss rebuild (webrtc/fec.recover), an
in-order delivery cursor with a freeze deadline, and per-repair
latency/source accounting.

Everything is simulated-clock driven: callers push wire datagrams with
``receive(wire, now_ms)`` and pump ``poll(now_ms)`` for the NACK/freeze
timers, so a whole gauntlet run is reproducible bit-for-bit.
"""

from __future__ import annotations

import struct

from selkies_tpu.monitoring.telemetry import telemetry
from selkies_tpu.transport.rtp import RtpPacket
from selkies_tpu.transport.webrtc import fec

__all__ = ["RecoveringReceiver"]


def _seq_lt(a: int, b: int) -> bool:
    """a < b in 16-bit serial-number arithmetic (RFC 1982)."""
    return ((b - a) & 0xFFFF) != 0 and ((b - a) & 0xFFFF) < 0x8000


class _Missing:
    __slots__ = ("since_ms", "nacks", "last_nack_ms")

    def __init__(self, now_ms: float):
        self.since_ms = now_ms
        self.nacks = 0
        self.last_nack_ms = float("-inf")


class RecoveringReceiver:
    """Browser-half recovery model over a simulated clock."""

    def __init__(self, *, session: str = "0", red_pt: int = 98,
                 ulpfec_pt: int = 99, nack_delay_ms: float = 20.0,
                 nack_retry_ms: float = 80.0, max_nacks: int = 4,
                 freeze_after_ms: float = 400.0,
                 parity_ttl_ms: float = 2000.0):
        self.session = str(session)
        self.red_pt = int(red_pt)
        self.ulpfec_pt = int(ulpfec_pt)
        self.nack_delay_ms = float(nack_delay_ms)
        self.nack_retry_ms = float(nack_retry_ms)
        self.max_nacks = int(max_nacks)
        self.freeze_after_ms = float(freeze_after_ms)
        self.parity_ttl_ms = float(parity_ttl_ms)
        # wire state
        self._wire: dict[int, bytes] = {}          # seq -> full RTP bytes
        self._meta: dict[int, tuple] = {}          # seq -> (kind, ts, marker)
        self._missing: dict[int, _Missing] = {}
        self._repaired: set[int] = set()           # seqs that closed a gap
        self._parities: list[tuple[bytes, float, frozenset]] = []
        self._ssrc: int | None = None
        self._highest: int | None = None
        self._next: int | None = None              # delivery cursor
        # frame assembly
        self._frame_ts: int | None = None
        self._frame_poisoned = False
        self._frame_repaired = False
        # accounting
        self.packets = 0
        self.dups = 0
        self.losses_detected = 0
        self.repaired_rtx = 0
        self.repaired_fec = 0
        self.given_up = 0
        self.nacks_sent = 0
        self.frames_recovered = 0
        self.frames_repaired = 0
        self.frames_frozen = 0
        self.recovery_ms: list[float] = []

    # -- ingest -------------------------------------------------------

    def receive(self, wire: bytes, now_ms: float) -> None:
        """One wire datagram off the (impaired) link."""
        try:
            pkt = RtpPacket.parse(wire)
        except ValueError:
            return
        self._ingest(pkt, wire, now_ms, rebuilt=False)

    def _ingest(self, pkt: RtpPacket, wire: bytes, now_ms: float,
                *, rebuilt: bool) -> None:
        seq = pkt.sequence & 0xFFFF
        if seq in self._wire:
            self.dups += 1
            return
        if self._ssrc is None:
            self._ssrc = pkt.ssrc
        self.packets += 1
        self._wire[seq] = wire
        kind, ts, marker = self._classify(pkt, now_ms)
        self._meta[seq] = (kind, ts, marker)
        # gap bookkeeping
        gone = self._missing.pop(seq, None)
        if gone is not None:
            lat = now_ms - gone.since_ms
            self.recovery_ms.append(lat)
            self._repaired.add(seq)
            if rebuilt:
                self.repaired_fec += 1
                if telemetry.enabled:
                    telemetry.count("selkies_fec_recovered_total",
                                    session=self.session)
            else:
                # the original was lost and this copy closed a gap we had
                # (or would have) NACKed: the retransmission rung at work
                self.repaired_rtx += 1
        if self._highest is None:
            self._highest = seq
            self._next = seq
        elif _seq_lt(self._highest, seq):
            s = (self._highest + 1) & 0xFFFF
            while s != seq:
                # only track gaps the cursor still cares about
                if self._next is None or not _seq_lt(s, self._next):
                    self._missing[s] = _Missing(now_ms)
                    self.losses_detected += 1
                s = (s + 1) & 0xFFFF
            self._highest = seq
        self._try_fec(now_ms)
        self._deliver()

    def _classify(self, pkt: RtpPacket, now_ms: float) -> tuple:
        """-> (kind, ts, marker); queues parity payloads for recovery."""
        if pkt.payload_type == self.red_pt:
            try:
                block_pt, inner = fec.red_unwrap(pkt.payload)
            except ValueError:
                return ("media", pkt.timestamp, pkt.marker)
            if block_pt == self.ulpfec_pt:
                group = self._parity_group(inner)
                if group:
                    self._parities.append((inner, now_ms, group))
                return ("fec", pkt.timestamp, False)
        return ("media", pkt.timestamp, pkt.marker)

    @staticmethod
    def _parity_group(parity: bytes) -> frozenset:
        """Seqs a ULP FEC payload protects (header base_seq + mask)."""
        if len(parity) < 14:
            return frozenset()
        base_seq = struct.unpack_from("!H", parity, 2)[0]
        mask = struct.unpack_from("!H", parity, 12)[0]
        return frozenset((base_seq + off) & 0xFFFF
                         for off in range(16) if mask & (1 << (15 - off)))

    def _try_fec(self, now_ms: float) -> None:
        if not self._parities or self._ssrc is None:
            return
        keep: list[tuple[bytes, float, frozenset]] = []
        for parity, born_ms, group in self._parities:
            missing = [s for s in group if s not in self._wire]
            if not missing:
                continue  # group complete: parity spent
            if len(missing) == 1:
                rebuilt = fec.recover(parity, self._wire, self._ssrc)
                if rebuilt is not None:
                    try:
                        pkt = RtpPacket.parse(rebuilt)
                    except ValueError:
                        pkt = None
                    if pkt is not None:
                        self._ingest(pkt, rebuilt, now_ms, rebuilt=True)
                        continue
            if now_ms - born_ms <= self.parity_ttl_ms:
                keep.append((parity, born_ms, group))
        self._parities = keep

    # -- timers -------------------------------------------------------

    def poll(self, now_ms: float) -> list[int]:
        """Run the NACK/freeze timers; returns seqs to NACK now (feed
        them through rtcp.build_nack back to the sender)."""
        to_nack: list[int] = []
        for seq, m in sorted(self._missing.items()):
            age = now_ms - m.since_ms
            if age >= self.freeze_after_ms:
                # this gap will never close: skip the cursor past it and
                # let frame assembly freeze the affected frame
                del self._missing[seq]
                self.given_up += 1
                if self._next is not None and not _seq_lt(seq, self._next):
                    self._poison_through(seq)
                continue
            if age < self.nack_delay_ms or m.nacks >= self.max_nacks:
                continue
            if now_ms - m.last_nack_ms < self.nack_retry_ms:
                continue
            m.last_nack_ms = now_ms
            m.nacks += 1
            to_nack.append(seq)
        if to_nack:
            self.nacks_sent += len(to_nack)
        self._deliver()
        return to_nack

    def _poison_through(self, seq: int) -> None:
        """Give up on `seq`: advance the cursor over it (delivering any
        packets queued before it) and poison the in-progress frame."""
        self._deliver()
        nxt = self._next
        if nxt is None or _seq_lt(seq, nxt):
            return
        s = nxt
        while True:
            if s not in self._wire:
                self._frame_poisoned = True
            if s == seq:
                break
            s = (s + 1) & 0xFFFF
        self._next = (seq + 1) & 0xFFFF
        self._deliver()

    # -- in-order delivery / frame assembly ---------------------------

    def _deliver(self) -> None:
        while self._next is not None and self._next in self._wire:
            seq = self._next
            kind, ts, marker = self._meta.get(seq, ("media", None, False))
            if kind == "media" and ts is not None:
                if self._frame_ts is None:
                    self._frame_ts = ts
                elif ts != self._frame_ts:
                    # marker packet lost and given up on: close the old
                    # frame on the timestamp change instead
                    self._close_frame()
                    self._frame_ts = ts
                if seq in self._repaired:
                    self._frame_repaired = True
                if marker:
                    self._close_frame()
                    self._frame_ts = None
            self._next = (seq + 1) & 0xFFFF
            if self._next in self._missing:
                break

    def _close_frame(self) -> None:
        if self._frame_poisoned:
            self.frames_frozen += 1
            if telemetry.enabled:
                telemetry.count("selkies_frames_frozen_total",
                                session=self.session)
        else:
            self.frames_recovered += 1
            if self._frame_repaired:
                self.frames_repaired += 1
        self._frame_poisoned = False
        self._frame_repaired = False

    def flush(self) -> None:
        """End of run: close any half-assembled frame."""
        self._deliver()
        if self._frame_ts is not None:
            self._close_frame()
            self._frame_ts = None

    # -- observability ------------------------------------------------

    @staticmethod
    def _pct(samples: list[float], q: float) -> float:
        if not samples:
            return 0.0
        xs = sorted(samples)
        i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
        return xs[i]

    def stats(self) -> dict:
        total = self.frames_recovered + self.frames_frozen
        return {
            "packets": self.packets,
            "dups": self.dups,
            "losses_detected": self.losses_detected,
            "repaired_rtx": self.repaired_rtx,
            "repaired_fec": self.repaired_fec,
            "given_up": self.given_up,
            "nacks_sent": self.nacks_sent,
            "frames_total": total,
            "frames_recovered": self.frames_recovered,
            "frames_repaired": self.frames_repaired,
            "frames_frozen": self.frames_frozen,
            "recovered_ratio": (self.frames_recovered / total) if total else 1.0,
            "recovery_ms_p50": round(self._pct(self.recovery_ms, 0.50), 3),
            "recovery_ms_p95": round(self._pct(self.recovery_ms, 0.95), 3),
        }
