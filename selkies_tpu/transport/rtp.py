"""RTP packetization: H.264 (RFC 6184) payloader + RTP header handling.

Parity target: the reference's rtph264pay element configuration —
mtu=1200, aggregate-mode zero-latency, config-interval -1 (in-band
SPS/PPS on every IDR) — gstwebrtc_app.py:806-846. STAP-A aggregates the
parameter sets with small NALs; FU-A fragments large slices.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

__all__ = ["RtpPacket", "H264Payloader", "OpusPayloader", "split_annexb"]

RTP_VERSION = 2
MTU_DEFAULT = 1200
H264_CLOCK = 90000


@dataclass
class RtpPacket:
    payload_type: int
    sequence: int
    timestamp: int
    ssrc: int
    payload: bytes
    marker: bool = False
    # RFC 8285 one-byte-header extensions: [(id 1-14, data 1-16 bytes)].
    # The WebRTC transport adds transport-wide-cc / playout-delay here
    # (reference: rtp_add_extensions, gstwebrtc_app.py:1657-1689).
    extensions: list = field(default_factory=list)

    def serialize(self) -> bytes:
        b0 = RTP_VERSION << 6
        ext = b""
        if self.extensions:
            b0 |= 0x10
            body = b"".join(
                bytes([(eid << 4) | (len(data) - 1)]) + data
                for eid, data in self.extensions
            )
            body += b"\x00" * ((4 - len(body) % 4) % 4)
            ext = struct.pack("!HH", 0xBEDE, len(body) // 4) + body
        b1 = (0x80 if self.marker else 0) | (self.payload_type & 0x7F)
        return (
            struct.pack(
                "!BBHII", b0, b1, self.sequence & 0xFFFF, self.timestamp & 0xFFFFFFFF, self.ssrc
            )
            + ext
            + self.payload
        )

    @classmethod
    def parse(cls, data: bytes) -> "RtpPacket":
        if len(data) < 12:
            raise ValueError("short RTP packet")
        b0, b1, seq, ts, ssrc = struct.unpack("!BBHII", data[:12])
        if b0 >> 6 != RTP_VERSION:
            raise ValueError("bad RTP version")
        csrc = b0 & 0x0F
        offset = 12 + csrc * 4
        if b0 & 0x10:  # extension
            if len(data) < offset + 4:
                raise ValueError("short RTP extension")
            ext_len = struct.unpack("!H", data[offset + 2 : offset + 4])[0]
            offset += 4 + ext_len * 4
        payload = data[offset:]
        if b0 & 0x20:  # padding
            if not payload:
                raise ValueError("padded packet with empty payload")
            pad = payload[-1]
            if pad < 1 or pad > len(payload):
                raise ValueError(f"invalid RTP pad count {pad}")
            payload = payload[:-pad]
        return cls(
            payload_type=b1 & 0x7F,
            sequence=seq,
            timestamp=ts,
            ssrc=ssrc,
            payload=payload,
            marker=bool(b1 & 0x80),
        )


def split_annexb(au: bytes) -> list[bytes]:
    """Split an Annex-B access unit into NAL units (start codes stripped)."""
    nals: list[bytes] = []
    n = len(au)
    i = 0
    start = None
    while i + 2 < n:
        if au[i] == 0 and au[i + 1] == 0 and au[i + 2] == 1:
            if start is not None:
                end = i
                # the extra 0x00 of a 4-byte start code belongs to the separator
                while end > start and au[end - 1] == 0:
                    end -= 1
                nals.append(au[start:end])
            start = i + 3
            i += 3
        else:
            i += 1
    if start is not None:
        nals.append(au[start:])
    return [x for x in nals if x]


MTU_FLOOR = 128


class RtpSequenceMixin:
    """Shared payloader invariants — every codec payloader (H.264 here,
    H.265/AV1/VP8/VP9 in their modules) draws the 16-bit sequence
    counter and the MTU floor from this one implementation so policy
    changes land once.

    The MTU floor exists because every payloader sizes fragments as
    `mtu - reserve - descriptor` with no lower bound; a toy MTU would
    drive that non-positive and mis-slice (RFC 3550 transports never go
    below ~576 anyway)."""

    sequence: int
    mtu: int

    def __post_init__(self) -> None:
        if self.mtu < MTU_FLOOR:
            raise ValueError(f"mtu {self.mtu} below the {MTU_FLOOR}-byte floor")

    def _next_seq(self) -> int:
        s = self.sequence
        self.sequence = (self.sequence + 1) & 0xFFFF
        return s


@dataclass
class H264Payloader(RtpSequenceMixin):
    """Annex-B access units → RTP packets (single NAL / STAP-A / FU-A)."""

    payload_type: int = 102
    ssrc: int = 0x53454C4B  # 'SELK'
    mtu: int = MTU_DEFAULT
    sequence: int = 0

    def payload_au(self, au: bytes, timestamp: int) -> list[RtpPacket]:
        """Packetize one access unit; the last packet carries the marker."""
        nals = split_annexb(au)
        packets: list[RtpPacket] = []
        # header budget: 12-byte RTP header + 8 bytes of RFC 8285
        # extension (transport-cc) + 1-byte RED encapsulation + the
        # 10-byte SRTP auth tag, PLUS enough slack that a ULP FEC parity
        # packet covering a full fragment (14-byte FEC header over the
        # ext+RED+payload region) still fits: the largest wire packet
        # must stay inside the 1200-byte path-MTU assumption after
        # protection. 12+8+1+10 = 31 for media; the parity packet adds
        # 14+13 more over the protected span -> reserve 54.
        max_payload = self.mtu - 54

        params: list[bytes] = []
        for nal in nals:
            ntype = nal[0] & 0x1F
            if ntype in (7, 8) and len(nal) < 200:
                params.append(nal)  # aggregate SPS/PPS (config-interval -1)
                continue
            if params:
                stap_total = 1 + sum(len(x) + 2 for x in params) + len(nal) + 2
                if stap_total <= max_payload:
                    packets.append(self._stap_a(params + [nal], timestamp))
                else:
                    if len(params) > 1:
                        packets.append(self._stap_a(params, timestamp))
                    else:
                        packets.append(self._single(params[0], timestamp))
                    packets.extend(self._fragment(nal, timestamp, max_payload))
                params = []
                continue
            packets.extend(self._fragment(nal, timestamp, max_payload))
        if params:  # AU was only parameter sets
            packets.append(self._stap_a(params, timestamp))
        if packets:
            packets[-1].marker = True
        return packets

    def _single(self, nal: bytes, ts: int) -> RtpPacket:
        return RtpPacket(self.payload_type, self._next_seq(), ts, self.ssrc, nal)

    def _stap_a(self, nals: list[bytes], ts: int) -> RtpPacket:
        nri = max((n[0] >> 5) & 3 for n in nals)
        payload = bytes([24 | (nri << 5)])  # STAP-A
        for n in nals:
            payload += struct.pack("!H", len(n)) + n
        return RtpPacket(self.payload_type, self._next_seq(), ts, self.ssrc, payload)

    def _fragment(self, nal: bytes, ts: int, max_payload: int) -> list[RtpPacket]:
        if len(nal) <= max_payload:
            return [self._single(nal, ts)]
        header = nal[0]
        nri = header & 0x60
        ntype = header & 0x1F
        fu_indicator = 28 | nri  # FU-A
        chunk = max_payload - 2
        data = nal[1:]
        out = []
        for i in range(0, len(data), chunk):
            part = data[i : i + chunk]
            s = 0x80 if i == 0 else 0
            e = 0x40 if i + chunk >= len(data) else 0
            fu_header = s | e | ntype
            out.append(
                RtpPacket(
                    self.payload_type,
                    self._next_seq(),
                    ts,
                    self.ssrc,
                    bytes([fu_indicator, fu_header]) + part,
                )
            )
        return out


@dataclass
class OpusPayloader:
    """Opus packets → RTP (RFC 7587: the payload is the raw Opus packet).

    Parity: rtpopuspay (gstwebrtc_app.py:1069-1080); 48 kHz RTP clock,
    marker set on the first packet of a talkspurt (we mark stream start).
    """

    payload_type: int = 111
    ssrc: int = 0x53454C41  # 'SELA'
    sequence: int = 0
    _first: bool = True

    def payload_packet(self, opus_packet: bytes, timestamp_48k: int) -> RtpPacket:
        pkt = RtpPacket(
            payload_type=self.payload_type,
            sequence=self.sequence,
            timestamp=timestamp_48k,
            ssrc=self.ssrc,
            payload=opus_packet,
            marker=self._first,
        )
        self._first = False
        self.sequence = (self.sequence + 1) & 0xFFFF
        return pkt


class H264Depayloader:
    """RTP packets → Annex-B access units (for tests and the loopback client)."""

    def __init__(self) -> None:
        self._fu: bytearray | None = None
        self._au: list[bytes] = []

    def push(self, pkt: RtpPacket) -> bytes | None:
        """Feed one packet; returns a complete AU when the marker arrives."""
        p = pkt.payload
        ntype = p[0] & 0x1F
        if ntype == 24:  # STAP-A
            i = 1
            while i + 2 <= len(p):
                (ln,) = struct.unpack("!H", p[i : i + 2])
                self._au.append(p[i + 2 : i + 2 + ln])
                i += 2 + ln
        elif ntype == 28:  # FU-A
            ind, hdr = p[0], p[1]
            if hdr & 0x80:
                self._fu = bytearray([(ind & 0x60) | (hdr & 0x1F)])
            if self._fu is not None:
                self._fu.extend(p[2:])
                if hdr & 0x40:
                    self._au.append(bytes(self._fu))
                    self._fu = None
        else:
            self._au.append(p)
        if pkt.marker:
            au = b"".join(b"\x00\x00\x00\x01" + n for n in self._au)
            self._au = []
            return au
        return None
