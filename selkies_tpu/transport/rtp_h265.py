"""RTP packetization for HEVC (RFC 7798) — the rtph265pay/depay
equivalent (reference chain: x265enc ! h265parse ! rtph265pay,
gstwebrtc_app.py:848-871; mtu=1200, config-interval -1 semantics come
from the encoder's repeat-headers, so VPS/SPS/PPS ride every IDR AU).

HEVC NAL units carry a 2-byte header — F(1) Type(6) LayerId(6) TID(3) —
so aggregation packets (AP, type 48) and fragmentation units (FU, type
49) differ from RFC 6184's STAP-A/FU-A in header layout but not shape.
The wire-overhead reserve matches transport/rtp.py's H.264 payloader
(RTP header + TWCC extension + RED byte + SRTP tag + ULP FEC slack).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from selkies_tpu.transport.rtp import (
    MTU_DEFAULT, RtpPacket, RtpSequenceMixin, split_annexb,
)

__all__ = ["H265Payloader", "H265Depayloader"]

NAL_VPS, NAL_SPS, NAL_PPS = 32, 33, 34
NAL_AP, NAL_FU = 48, 49


def nal_type(nal: bytes) -> int:
    return (nal[0] >> 1) & 0x3F


def _is_param_set(nal: bytes) -> bool:
    return nal_type(nal) in (NAL_VPS, NAL_SPS, NAL_PPS)


@dataclass
class H265Payloader(RtpSequenceMixin):
    """Annex-B HEVC access units → RTP packets (single NAL / AP / FU)."""

    payload_type: int = 103
    ssrc: int = 0x53454C48  # 'SELH'
    mtu: int = MTU_DEFAULT
    sequence: int = 0

    def payload_au(self, au: bytes, timestamp: int) -> list[RtpPacket]:
        """Packetize one access unit; the last packet carries the marker."""
        nals = split_annexb(au)
        packets: list[RtpPacket] = []
        max_payload = self.mtu - 54  # same reserve as rtp.py (FEC-safe)

        params: list[bytes] = []
        for nal in nals:
            if _is_param_set(nal) and len(nal) < 200:
                params.append(nal)  # aggregate VPS/SPS/PPS onto the IDR
                continue
            if params:
                ap_total = 2 + sum(len(x) + 2 for x in params) + len(nal) + 2
                if ap_total <= max_payload:
                    packets.append(self._ap(params + [nal], timestamp))
                else:
                    if len(params) > 1:
                        packets.append(self._ap(params, timestamp))
                    else:
                        packets.append(self._single(params[0], timestamp))
                    packets.extend(self._fragment(nal, timestamp, max_payload))
                params = []
                continue
            packets.extend(self._fragment(nal, timestamp, max_payload))
        if params:  # AU was only parameter sets
            packets.append(self._ap(params, timestamp) if len(params) > 1
                           else self._single(params[0], timestamp))
        if packets:
            packets[-1].marker = True
        return packets

    def _single(self, nal: bytes, ts: int) -> RtpPacket:
        return RtpPacket(self.payload_type, self._next_seq(), ts, self.ssrc, nal)

    def _ap(self, nals: list[bytes], ts: int) -> RtpPacket:
        # AP PayloadHdr: type=48; LayerId and TID each take their own
        # minimum across the aggregated NALs (RFC 7798 §4.4.2 — the two
        # fields are minimized independently, not as one 9-bit value)
        words = [struct.unpack("!H", n[:2])[0] for n in nals]
        layer = min((w >> 3) & 0x3F for w in words)
        tid = min(w & 0x07 for w in words)
        hdr = struct.pack("!H", (NAL_AP << 9) | (layer << 3) | tid)
        payload = hdr + b"".join(
            struct.pack("!H", len(n)) + n for n in nals)
        return RtpPacket(self.payload_type, self._next_seq(), ts, self.ssrc, payload)

    def _fragment(self, nal: bytes, ts: int, max_payload: int) -> list[RtpPacket]:
        if len(nal) <= max_payload:
            return [self._single(nal, ts)]
        first_word = struct.unpack("!H", nal[:2])[0]
        ntype = (first_word >> 9) & 0x3F
        fu_payload_hdr = struct.pack(
            "!H", (first_word & ~(0x3F << 9)) | (NAL_FU << 9))
        chunk = max_payload - 3  # 2-byte PayloadHdr + 1-byte FU header
        data = nal[2:]
        out = []
        for i in range(0, len(data), chunk):
            part = data[i: i + chunk]
            s = 0x80 if i == 0 else 0
            e = 0x40 if i + chunk >= len(data) else 0
            out.append(RtpPacket(
                self.payload_type, self._next_seq(), ts, self.ssrc,
                fu_payload_hdr + bytes([s | e | ntype]) + part,
            ))
        return out


class H265Depayloader:
    """RTP packets → Annex-B access units (for tests and the loopback
    client; rtph265depay equivalent)."""

    def __init__(self) -> None:
        self._fu: bytearray | None = None
        self._au: list[bytes] = []

    def push(self, pkt: RtpPacket) -> bytes | None:
        """Feed one packet; returns a complete AU when the marker arrives."""
        p = pkt.payload
        if len(p) < 2:
            return None
        ntype = (p[0] >> 1) & 0x3F
        if ntype == NAL_AP:
            i = 2
            while i + 2 <= len(p):
                (ln,) = struct.unpack("!H", p[i: i + 2])
                self._au.append(p[i + 2: i + 2 + ln])
                i += 2 + ln
        elif ntype == NAL_FU:
            fu_hdr = p[2]
            if fu_hdr & 0x80:  # start: rebuild the original NAL header
                word = struct.unpack("!H", p[:2])[0]
                orig = (word & ~(0x3F << 9)) | ((fu_hdr & 0x3F) << 9)
                self._fu = bytearray(struct.pack("!H", orig))
            if self._fu is not None:
                self._fu.extend(p[3:])
                if fu_hdr & 0x40:  # end
                    self._au.append(bytes(self._fu))
                    self._fu = None
        else:
            self._au.append(p)
        if pkt.marker:
            au = b"".join(b"\x00\x00\x00\x01" + n for n in self._au)
            self._au = []
            return au if au else None
        return None
