"""RTP payload format for AV1 (AOM "RTP Payload Format For AV1" v1.0).

The reference gets this from gst-plugins-rs `rtpav1pay` / `rtpav1depay`
(gstwebrtc_app.py:917-938, addons/gstreamer/Dockerfile:90). This is a
from-scratch implementation of the same wire format so the AV1 transport
layer exists independently of which AV1 encoder produces the OBUs:

* 1-byte aggregation header: Z (first element is a continuation),
  Y (last element continues in the next packet), W (element count, the
  last element then omits its length), N (first packet of a new coded
  video sequence);
* OBU elements with LEB128 length prefixes, obu_has_size_field stripped
  (the RTP framing carries sizes, §4.4 of the payload spec);
* temporal-delimiter OBUs dropped (§5);
* fragmentation of large OBUs across packets via Z/Y.

The depayloader reassembles temporal units and restores size fields so
the output is a valid low-overhead bitstream ("Section 5" / .obu) frame.
"""

from __future__ import annotations

from dataclasses import dataclass

from selkies_tpu.transport.rtp import MTU_DEFAULT, RtpPacket, RtpSequenceMixin

__all__ = ["Av1Payloader", "Av1Depayloader", "leb128_encode", "leb128_decode",
           "split_obus", "obu_type"]

OBU_SEQUENCE_HEADER = 1
OBU_TEMPORAL_DELIMITER = 2
OBU_FRAME = 6

AV1_CLOCK = 90000


def leb128_encode(value: int) -> bytes:
    out = bytearray()
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return bytes(out)


def leb128_decode(data: bytes, offset: int = 0) -> tuple[int, int]:
    """-> (value, bytes consumed). Raises ValueError on truncation."""
    value = 0
    for i in range(8):
        if offset + i >= len(data):
            raise ValueError("truncated LEB128")
        byte = data[offset + i]
        value |= (byte & 0x7F) << (7 * i)
        if not byte & 0x80:
            return value, i + 1
    raise ValueError("LEB128 too long")


def obu_type(obu: bytes) -> int:
    return (obu[0] >> 3) & 0x0F


def _header_len(obu: bytes) -> int:
    return 2 if obu[0] & 0x04 else 1  # extension flag adds one byte


def _strip_size_field(obu: bytes) -> bytes:
    """Return the OBU with obu_has_size_field cleared and the field removed."""
    if not obu[0] & 0x02:
        return obu
    hl = _header_len(obu)
    size, n = leb128_decode(obu, hl)
    body = obu[hl + n : hl + n + size]
    return bytes([obu[0] & ~0x02]) + obu[1:hl] + body


def _add_size_field(obu: bytes) -> bytes:
    """Return the OBU with obu_has_size_field set and the field inserted."""
    if obu[0] & 0x02:
        return obu
    hl = _header_len(obu)
    body = obu[hl:]
    return bytes([obu[0] | 0x02]) + obu[1:hl] + leb128_encode(len(body)) + body


def split_obus(tu: bytes) -> list[bytes]:
    """Split a low-overhead-bitstream temporal unit into OBUs (size fields
    must be present, as in .obu files and encoder output)."""
    obus: list[bytes] = []
    i = 0
    while i < len(tu):
        first = tu[i]
        if first & 0x80:
            raise ValueError("forbidden bit set in OBU header")
        hl = 2 if first & 0x04 else 1
        if not first & 0x02:
            raise ValueError("OBU without size field in temporal unit")
        size, n = leb128_decode(tu, i + hl)
        end = i + hl + n + size
        if end > len(tu):
            raise ValueError("truncated OBU")
        obus.append(tu[i:end])
        i = end
    return obus


def _agg_header(z: bool, y: bool, w: int, n: bool) -> bytes:
    return bytes([(0x80 if z else 0) | (0x40 if y else 0)
                  | ((w & 3) << 4) | (0x08 if n else 0)])


@dataclass
class Av1Payloader(RtpSequenceMixin):
    """OBU temporal units → RTP packets (rtpav1pay equivalent)."""

    payload_type: int = 45
    ssrc: int = 0x53454C56  # 'SELV'
    mtu: int = MTU_DEFAULT
    sequence: int = 0

    def payload_au(self, au: bytes, timestamp: int) -> list[RtpPacket]:
        """H264Payloader-compatible facade (peer.py calls payload_au on
        whatever payloader the codec selected): a TU carrying a sequence
        header OBU starts a new coded video sequence -> N bit set."""
        raw = split_obus(au)
        new_seq = any(obu_type(o) == OBU_SEQUENCE_HEADER for o in raw)
        return self._payload(raw, timestamp, new_seq)

    def payload_tu(self, tu: bytes, timestamp: int,
                   new_sequence: bool = False) -> list[RtpPacket]:
        """Packetize one temporal unit (low-overhead bitstream bytes).

        `new_sequence` sets the N bit on the first packet — use it on the
        first TU of a coded video sequence (keyframe with sequence header).
        The last packet carries the RTP marker.
        """
        return self._payload(split_obus(tu), timestamp, new_sequence)

    def _payload(self, raw_obus: list[bytes], timestamp: int,
                 new_sequence: bool) -> list[RtpPacket]:
        obus = [_strip_size_field(o) for o in raw_obus
                if obu_type(o) != OBU_TEMPORAL_DELIMITER]
        if not obus:
            return []
        # same wire-overhead reserve as the H.264 payloader: RTP header,
        # TWCC/playout-delay extensions, RED byte, SRTP tag, FEC slack
        max_payload = self.mtu - 54

        packets: list[RtpPacket] = []
        # elements for the packet being built: (data, is_continuation)
        elems: list[bytes] = []
        z = False  # first element of the current packet is a continuation
        used = 1  # aggregation header

        def flush(y: bool) -> None:
            nonlocal elems, z, used
            if not elems:
                return
            w = len(elems) if len(elems) <= 3 else 0
            body = b""
            for i, el in enumerate(elems):
                last = i == len(elems) - 1
                if w and last:
                    body += el  # W>0: last element length is implicit
                else:
                    body += leb128_encode(len(el)) + el
            n_bit = new_sequence and not packets
            packets.append(RtpPacket(
                self.payload_type, self._next_seq(), timestamp, self.ssrc,
                _agg_header(z, y, w, n_bit) + body,
            ))
            elems = []
            z = False
            used = 1

        for obu in obus:
            data = obu
            while True:
                room = max_payload - used - len(leb128_encode(len(data))) - len(data)
                if room >= 0:
                    elems.append(data)
                    used += len(leb128_encode(len(data))) + len(data)
                    break
                # fragment: fill this packet, continue in the next (Y/Z)
                space = max_payload - used - 2  # ≥ length prefix worst case
                if space < 16 and elems:
                    flush(False)  # not worth a tiny fragment; start fresh
                    continue
                head, data = data[:space], data[space:]
                elems.append(head)
                flush(True)
                z = True
        flush(False)
        if packets:
            packets[-1].marker = True
        return packets


class Av1Depayloader:
    """RTP packets → temporal units (rtpav1depay equivalent; for tests
    and the loopback client). Output OBUs carry restored size fields."""

    def __init__(self) -> None:
        self._obus: list[bytes] = []
        self._frag: bytearray | None = None
        self._last_seq: int | None = None
        self._broken = False  # loss detected: drop the TU at its marker

    def push(self, pkt: RtpPacket) -> bytes | None:
        # a sequence gap means part of this TU is gone: a truncated TU
        # must be dropped at the marker, not emitted as if complete.
        # (Checked before the empty-payload return so keepalive/padding
        # packets still advance the expected sequence.)
        if self._last_seq is not None and pkt.sequence != (self._last_seq + 1) & 0xFFFF:
            self._broken = True
        self._last_seq = pkt.sequence
        p = pkt.payload
        if not p:
            return None
        b0 = p[0]
        z, y, w = bool(b0 & 0x80), bool(b0 & 0x40), (b0 >> 4) & 3
        i = 1
        elements: list[bytes] = []
        count = 0
        while i < len(p):
            count += 1
            if w and count == w:
                elements.append(p[i:])
                i = len(p)
            else:
                try:
                    ln, n = leb128_decode(p, i)
                except ValueError:
                    break
                elements.append(p[i + n : i + n + ln])
                i += n + ln
        for j, el in enumerate(elements):
            first, last = j == 0, j == len(elements) - 1
            if first and z:
                if self._frag is None:
                    self._broken = True  # continuation of a lost start
                    continue
                self._frag.extend(el)
                if last and y:
                    return self._finish(pkt.marker)
                self._obus.append(bytes(self._frag))
                self._frag = None
            elif last and y:
                self._frag = bytearray(el)
            else:
                self._obus.append(el)
        return self._finish(pkt.marker)

    def _finish(self, marker: bool) -> bytes | None:
        if not marker:
            return None
        self._frag = None
        obus, self._obus = self._obus, []
        broken, self._broken = self._broken, False
        if broken or not obus:
            return None
        return b"".join(_add_size_field(o) for o in obus)
