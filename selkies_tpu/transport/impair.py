"""Deterministic network impairment: the ``net:*`` fault plane.

Two drivers share one packet-pipe model (``admit(datagram, now_ms) ->
[(delay_ms, datagram), ...]``, empty on loss):

* :class:`NetImpairment` — wired into PeerConnection's send boundary
  (webrtc/peer.py ``_net_send``) and driven by the seeded
  ``SELKIES_FAULTS`` schedule (resilience/faultinject.py), so every
  recovery-ladder transition is reproducible tick-for-tick:

  - ``net:loss``       ``drop`` discards the datagram
  - ``net:jitter``     ``delay:<ms>`` defers its delivery
  - ``net:reorder``    any firing holds the datagram behind the next one
  - ``net:dup``        any firing delivers it twice
  - ``net:bandwidth:<kbps>`` any firing rate-shapes it through a
    serialization queue at the site-qualifier's kbps

* :class:`TraceImpairment` — trace-driven profiles for the gauntlet
  bench (``bench.py --impair``): piecewise link segments (loss
  probability, jitter, duplication, reordering, bandwidth) replayed on
  a seeded RNG over a simulated clock. The committed profiles model the
  networks the source papers evaluate under: an LTE handover (clean ->
  outage -> congested recovery), a contended hotel/conference WLAN, and
  the V2X vehicular burst-loss regime of the 8K60 edge-streaming study.

:class:`LoopbackSender` is the measurement apparatus both the bench and
tests/test_recovery.py use: a real PeerConnection armed with an
identity SRTP stub and a capture-sink ICE stub, so the full send path —
payloader, RED/FEC, RTX ring, the net shim — runs in-process with no
sockets and an injectable clock.
"""

from __future__ import annotations

import random

from selkies_tpu.resilience.faultinject import FaultInjector, get_injector

__all__ = ["NetImpairment", "TraceImpairment", "LoopbackSender", "PROFILES"]


class _Shaper:
    """Serialization queue: a datagram admitted at ``now_ms`` leaves
    after every byte ahead of it has drained at ``kbps``."""

    def __init__(self, kbps: float):
        self.kbps = max(1.0, float(kbps))
        self._busy_until = 0.0

    def delay_ms(self, nbytes: int, now_ms: float) -> float:
        start = max(now_ms, self._busy_until)
        self._busy_until = start + nbytes * 8.0 / self.kbps
        return self._busy_until - now_ms


class NetImpairment:
    """Faultinject-driven impairment at the peer's send boundary."""

    def __init__(self, injector: FaultInjector):
        self.injector = injector
        self._held: list[tuple[float, bytes]] | None = None
        # net:bandwidth:<kbps> rules carry the rate in the site
        # qualifier; each keeps its own shaper + schedule counter
        self._shapers: list[tuple[str, _Shaper]] = []
        for rule in injector.rules:
            if rule.site.startswith("net:bandwidth:"):
                try:
                    kbps = float(rule.site.rsplit(":", 1)[1])
                except ValueError:
                    continue
                self._shapers.append((rule.site, _Shaper(kbps)))

    @classmethod
    def from_faults(cls) -> "NetImpairment | None":
        """None unless the active injector has a ``net`` rule — the
        disabled send path stays one attribute load."""
        fi = get_injector()
        if fi is None:
            return None
        if not any(r.site == "net" or r.site.startswith("net:")
                   for r in fi.rules):
            return None
        return cls(fi)

    def admit(self, datagram: bytes,
              now_ms: float) -> list[tuple[float, bytes]]:
        """-> [(delay_ms, datagram), ...] in delivery order; [] = lost.
        Advances each net site's tick counter exactly once per call, so
        a ``net:loss@5,9:drop`` schedule counts datagrams."""
        fi = self.injector
        held, self._held = self._held, None
        # every site's counter advances on EVERY datagram (checked before
        # any early-out), so "net:dup@7" always means the 7th datagram
        # regardless of what the loss schedule did to earlier ones
        loss = fi.check("net:loss")
        jitter = fi.check("net:jitter")
        shaped = [(shaper, fi.check(site) is not None)
                  for site, shaper in self._shapers]
        dup = fi.check("net:dup")
        reorder = fi.check("net:reorder")
        if loss is not None and loss[0] == "drop":
            return held or []
        delay = 0.0
        if jitter is not None and jitter[0] == "delay":
            delay += jitter[1]
        for shaper, fired in shaped:
            if fired:
                delay += shaper.delay_ms(len(datagram), now_ms + delay)
        out = [(delay, datagram)]
        if dup is not None:
            out.append((delay, datagram))
        if reorder is not None:
            # hold this datagram: it rides BEHIND whatever comes next
            self._held = out
            return held or []
        return (held or []) + out if held else out


# ---------------------------------------------------------------------------
# trace profiles (bench.py --impair)
# ---------------------------------------------------------------------------

# segment: (duration_ms, loss_prob, jitter_ms, dup_prob, reorder_prob,
#           bandwidth_kbps or 0 = unshaped); profiles cycle.
PROFILES: dict[str, list[tuple[float, float, float, float, float, float]]] = {
    # LTE handover: long clean stretch, a ~400 ms cell switch where most
    # packets die, then a congested recovery window on the new cell
    "lte_handover": [
        (3000.0, 0.002, 5.0, 0.0, 0.005, 0.0),
        (400.0, 0.45, 60.0, 0.0, 0.05, 2000.0),
        (1600.0, 0.05, 20.0, 0.0, 0.02, 6000.0),
    ],
    # contended hotel/conference WLAN: persistent moderate loss, heavy
    # jitter, occasional duplicates and reordering, capped throughput
    "hotel_wifi": [
        (5000.0, 0.03, 30.0, 0.01, 0.02, 4000.0),
    ],
    # V2X vehicular edge (8K60 study's regime): mostly-clean driving
    # punctuated by deep burst loss at obstructions
    "v2x": [
        (2000.0, 0.01, 10.0, 0.0, 0.01, 0.0),
        (600.0, 0.30, 40.0, 0.0, 0.05, 8000.0),
        (1000.0, 0.08, 20.0, 0.0, 0.02, 0.0),
    ],
}


class TraceImpairment:
    """Seeded trace-driven link model over a simulated clock."""

    def __init__(self, profile: str, seed: int = 0):
        if profile not in PROFILES:
            raise ValueError(f"unknown impairment profile {profile!r} "
                             f"(one of {sorted(PROFILES)})")
        self.profile = profile
        self.segments = PROFILES[profile]
        self.total_ms = sum(s[0] for s in self.segments)
        self.rng = random.Random(seed)
        self._held: list[tuple[float, bytes]] | None = None
        self._shaper: _Shaper | None = None
        self._shaper_kbps = 0.0
        # accounting the bench reports
        self.admitted = 0
        self.dropped = 0
        self.duplicated = 0
        self.reordered = 0

    def _segment(self, now_ms: float):
        t = now_ms % self.total_ms
        for seg in self.segments:
            if t < seg[0]:
                return seg
            t -= seg[0]
        return self.segments[-1]

    def admit(self, datagram: bytes,
              now_ms: float) -> list[tuple[float, bytes]]:
        _, loss, jitter, dup, reorder, kbps = self._segment(now_ms)
        held, self._held = self._held, None
        self.admitted += 1
        if self.rng.random() < loss:
            self.dropped += 1
            return held or []
        delay = self.rng.random() * jitter
        if kbps > 0:
            if self._shaper is None or self._shaper_kbps != kbps:
                self._shaper = _Shaper(kbps)
                self._shaper_kbps = kbps
            delay += self._shaper.delay_ms(len(datagram), now_ms + delay)
        out = [(delay, datagram)]
        if self.rng.random() < dup:
            self.duplicated += 1
            out.append((delay, datagram))
        if self.rng.random() < reorder:
            self.reordered += 1
            self._held = out
            return held or []
        return (held or []) + out if held else out


# ---------------------------------------------------------------------------
# loopback measurement apparatus
# ---------------------------------------------------------------------------

class _IdentitySrtp:
    """SRTP stub: the loopback link is in-process, so protect is the
    identity — what the receiver sees IS what ULP FEC protects."""

    def protect(self, wire: bytes) -> bytes:
        return wire

    def protect_rtcp(self, wire: bytes) -> bytes:
        return wire

    def unprotect_rtcp(self, wire: bytes) -> bytes:
        return wire


class _SinkIce:
    """ICE stub: connected, delivers every datagram to a callback."""

    def __init__(self, on_wire):
        self.connected = True
        self.on_wire = on_wire
        self.local_candidates: list = []

    def send(self, datagram: bytes) -> None:
        self.on_wire(datagram)

    def close(self) -> None:
        self.connected = False


class LoopbackSender:
    """A PeerConnection armed for direct in-process delivery: identity
    SRTP + capture-sink ICE, FEC armed as a red/ulpfec answer would,
    and an injectable clock. ``on_wire(datagram)`` receives every
    outgoing pre-SRTP packet (media, FEC, retransmits)."""

    def __init__(self, *, on_wire, fec_percentage: int = 20,
                 clock=None, media_pt: int = 96, red_pt: int = 98,
                 ulpfec_pt: int = 99):
        import asyncio

        from selkies_tpu.transport.webrtc import fec as fec_mod
        from selkies_tpu.transport.webrtc.peer import PeerConnection

        # a loop object is required by the constructor but never run:
        # the loopback path is synchronous (no DTLS ticks, no jitter
        # timers — trace delays are applied by the caller's event queue)
        self._loop = asyncio.new_event_loop()
        pc = PeerConnection(audio=False, fec_percentage=fec_percentage,
                            loop=self._loop)
        pc.ice.close()  # release the gathering sockets; replace with sink
        pc.ice = _SinkIce(on_wire)
        pc.srtp = _IdentitySrtp()
        if clock is not None:
            pc._clock = clock
            pc._rtx_refill_at = clock()
        pc.video_pay.payload_type = media_pt
        if fec_percentage >= 0:
            pc._fec = fec_mod.FecEncoder(fec_percentage)
            pc._red_pt, pc._ulpfec_pt = red_pt, ulpfec_pt
        self.pc = pc

    def close(self) -> None:
        self.pc._closed = True
        self.pc.ice.close()
        self._loop.close()
