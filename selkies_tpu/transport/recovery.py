"""Adaptive recovery ladder: loss telemetry -> protection level.

The transport has had the recovery *primitives* since PR 1 — a NACK
retransmit ring with abuse bounds (webrtc/peer.py), RED/ULP FEC build +
recover (webrtc/fec.py), RR/NACK/TWCC parsing (webrtc/rtcp.py), GCC
loss reports (congestion.py) — but no policy connecting them: FEC ran
at a fixed 20 % whether the link was loss-free fibre or a hotel WLAN,
and the only response to loss the transport could not repair was the
supervisor's failure ladder, whose first move (downscale / fps-halve)
is exactly the user-visible degradation recovery exists to avoid.

:class:`RecoveryController` closes the loop. The ladder, cheapest rung
first:

====  ========  ======================================================
rung  name      meaning
====  ========  ======================================================
0     clean     no measured loss; FEC at 0 % (NACK/RTX stays armed —
                it costs nothing until a NACK arrives)
1     rtx       loss seen recently and NACK/RTX is recovering it;
                smoothed loss still below the FEC threshold
2     fec       smoothed loss crossed ``fec_loss``: FEC percentage
                tracks the smoothed loss fraction up to
                ``SELKIES_FEC_MAX_PCT`` (raises immediately, lowers
                only after ``recover_after`` consecutive calmer
                reports — the supervisor ladder's hysteresis shape)
3     refresh   an unrecoverable gap (a NACKed seq aged out of the RTX
                ring, or a FEC span that could not be rebuilt) forced
                an IDR through the existing keyframe path; at most one
                per ``idr_floor_s`` so a gap *burst* costs one refresh
4     degrade   sustained unrecoverable loss with FEC already at its
                cap: only now do the PR 2 degradation rungs fire
                (``on_degrade`` -> the link-pressure downscale path);
                reversed after ``undegrade_after`` consecutive clean
                loss reports
====  ========  ======================================================

Off switch: ``SELKIES_RECOVERY=0`` leaves the controller inert — no
``on_set_fec`` call is ever made, so the peer keeps its static
constructor-time FEC percentage and the wire bytes are identical to a
build without this module (tests/test_recovery.py pins the sha256).

Wiring (orchestrator.py solo, parallel/fleet.py per slot)::

    rc = RecoveryController(session="0")
    rc.on_set_fec    = webrtc.set_fec_percentage
    rc.on_force_idr  = app.force_keyframe        # unthrottled internal path
    rc.on_degrade    = app._policy_link_degrade  # downscale before fps
    rc.on_undegrade  = app._policy_link_undegrade
    webrtc.on_loss          = chain(gcc.on_loss_report, rc.on_loss_report)
    webrtc.on_nack          = rc.on_nack
    webrtc.on_unrecoverable = rc.on_unrecoverable
"""

from __future__ import annotations

import logging
import math
import os
import time

from selkies_tpu.monitoring.telemetry import telemetry

logger = logging.getLogger("transport.recovery")

__all__ = ["RecoveryController", "recovery_enabled", "max_fec_pct"]

ENV_VAR = "SELKIES_RECOVERY"
ENV_MAX_FEC = "SELKIES_FEC_MAX_PCT"

RUNG_NAMES = ("clean", "rtx", "fec", "refresh", "degrade")


def recovery_enabled() -> bool:
    """Adaptive recovery is ON by default; ``SELKIES_RECOVERY=0`` keeps
    the pre-ladder static behavior (fixed constructor FEC percentage,
    no forced IDRs, no escalation) byte-identical."""
    return os.environ.get("SELKIES_RECOVERY", "1") != "0"


def max_fec_pct() -> int:
    """``SELKIES_FEC_MAX_PCT`` cap on the adaptive FEC percentage
    (default 50 -> one parity packet per two media packets under the
    worst burst loss; 1..100)."""
    try:
        pct = int(os.environ.get("SELKIES_FEC_MAX_PCT", "50"))
    except ValueError:
        return 50
    return max(1, min(100, pct))


class RecoveryController:
    """One session's recovery-ladder policy. Event-driven and clock-
    injectable (tests and the impairment bench run it on a simulated
    clock); every input is a no-op when the controller is disabled."""

    def __init__(self, *, session: str = "0", enabled: bool | None = None,
                 fec_max: int | None = None, alpha: float = 0.3,
                 clean_loss: float = 0.005, fec_loss: float = 0.02,
                 recover_after: int = 6, undegrade_after: int = 10,
                 degrade_after: int = 3, window_s: float = 10.0,
                 idr_floor_s: float = 1.0, nack_window_s: float = 3.0,
                 clock=time.monotonic):
        self.session = str(session)
        self.enabled = recovery_enabled() if enabled is None else bool(enabled)
        self.fec_max = max_fec_pct() if fec_max is None else int(fec_max)
        self.alpha = float(alpha)
        self.clean_loss = float(clean_loss)
        self.fec_loss = float(fec_loss)
        self.recover_after = int(recover_after)
        self.undegrade_after = int(undegrade_after)
        self.degrade_after = int(degrade_after)
        self.window_s = float(window_s)
        self.idr_floor_s = float(idr_floor_s)
        self.nack_window_s = float(nack_window_s)
        self._clock = clock
        # outputs (wired by the orchestrator / fleet)
        self.on_set_fec = lambda pct: None
        self.on_force_idr = lambda: None
        self.on_degrade = lambda: None
        self.on_undegrade = lambda: None
        # state
        self.fec_pct = 0
        self.rung = 0
        self.smoothed_loss = 0.0
        self._calm_reports = 0      # reports with target pct below current
        self._healthy_reports = 0   # reports at/below clean_loss
        self._last_nack = float("-inf")
        self._last_idr = float("-inf")
        self._unrec_times: list[float] = []
        self._degraded = False
        # counters (stats() / the /statz recovery block)
        self.nacks_total = 0
        self.unrecoverable_total = 0
        self.idr_forced_total = 0
        self.degrades_total = 0
        self.undegrades_total = 0

    # -- session lifecycle --------------------------------------------

    def attach(self) -> None:
        """Apply the current protection level to a (re)started session's
        fresh peer: a clean-link session starts at 0 % FEC instead of
        the static constructor default."""
        if self.enabled:
            self.on_set_fec(self.fec_pct)

    # -- inputs -------------------------------------------------------

    def on_loss_report(self, fraction: float) -> None:
        """RTCP RR loss fraction (the same tap GCC consumes)."""
        if not self.enabled:
            return
        f = max(0.0, min(1.0, float(fraction)))
        self.smoothed_loss = self.alpha * f + (1 - self.alpha) * self.smoothed_loss
        target = self._target_pct(self.smoothed_loss)
        if target > self.fec_pct:
            # more loss: protect immediately
            self._calm_reports = 0
            self._set_fec(target)
        elif target < self.fec_pct:
            # less loss: lower only after a sustained calm window — the
            # supervisor ladder's hysteresis shape (one flap must not
            # thrash the group size)
            self._calm_reports += 1
            if self._calm_reports >= self.recover_after:
                self._calm_reports = 0
                self._set_fec(target)
        else:
            self._calm_reports = 0
        if f <= self.clean_loss:
            self._healthy_reports += 1
            if self._degraded and self._healthy_reports >= self.undegrade_after:
                self._degraded = False
                self._healthy_reports = 0
                self._unrec_times.clear()
                self.undegrades_total += 1
                logger.info("recovery: link healthy — reversing degradation "
                            "(session %s)", self.session)
                self.on_undegrade()
                self._transition("undegrade")
        else:
            self._healthy_reports = 0
        self._update_rung()

    def on_nack(self, n_seqs: int) -> None:
        """NACKs arrived and the RTX ring is answering them (first rung)."""
        if not self.enabled:
            return
        self.nacks_total += int(n_seqs)
        self._last_nack = self._clock()
        self._update_rung()

    def on_unrecoverable(self, seq: int) -> None:
        """A gap neither RTX nor FEC can close (NACKed seq aged out of
        the ring / past the FEC span): force ONE IDR through the
        existing keyframe path, and only escalate to the degradation
        rungs when this keeps happening with FEC already at its cap."""
        if not self.enabled:
            return
        now = self._clock()
        self.unrecoverable_total += 1
        self._unrec_times = [t for t in self._unrec_times
                             if now - t <= self.window_s]
        self._unrec_times.append(now)
        if now - self._last_idr >= self.idr_floor_s:
            self._last_idr = now
            self.idr_forced_total += 1
            logger.warning("recovery: unrecoverable gap at seq %d — forcing "
                           "IDR (session %s)", seq, self.session)
            self.on_force_idr()
            self._transition("force_idr", seq=int(seq))
        if (not self._degraded and self.fec_pct >= self.fec_max
                and len(self._unrec_times) >= self.degrade_after):
            self._degraded = True
            self._healthy_reports = 0
            self.degrades_total += 1
            logger.warning("recovery: sustained unrecoverable loss with FEC "
                           "at cap — degrading (session %s)", self.session)
            self.on_degrade()
            self._transition("degrade")
        self._update_rung()

    # -- internals ----------------------------------------------------

    def _target_pct(self, loss: float) -> int:
        """FEC adaptation curve: 0 below ``fec_loss``, then ~2x the
        smoothed loss fraction quantized to 5 % steps (5 % loss -> 10 %
        FEC -> one parity per 10 packets), capped at ``fec_max``."""
        if loss < self.fec_loss:
            return 0
        pct = int(math.ceil(loss * 200.0 / 5.0)) * 5
        return max(5, min(self.fec_max, pct))

    def _set_fec(self, pct: int) -> None:
        if pct == self.fec_pct:
            return
        self.fec_pct = pct
        self.on_set_fec(pct)
        self._transition("set_fec", pct=pct,
                         loss=round(self.smoothed_loss, 4))

    def _update_rung(self) -> None:
        now = self._clock()
        if self._degraded:
            rung = 4
        elif any(now - t <= self.window_s for t in self._unrec_times):
            rung = 3
        elif self.fec_pct > 0:
            rung = 2
        elif now - self._last_nack <= self.nack_window_s:
            rung = 1
        else:
            rung = 0
        if rung != self.rung:
            self.rung = rung
            if telemetry.enabled:
                telemetry.gauge("selkies_recovery_rung", rung,
                                session=self.session)
            self._transition("rung", rung=rung, name=RUNG_NAMES[rung])

    def _transition(self, action: str, **fields) -> None:
        if telemetry.enabled:
            telemetry.event("recovery", session=self.session,
                            action=action, **fields)

    # -- observability ------------------------------------------------

    def stats(self) -> dict:
        return {
            "enabled": self.enabled,
            "rung": self.rung,
            "rung_name": RUNG_NAMES[self.rung],
            "fec_pct": self.fec_pct,
            "fec_max": self.fec_max,
            "smoothed_loss": round(self.smoothed_loss, 4),
            "degraded": self._degraded,
            "nacks": self.nacks_total,
            "unrecoverable": self.unrecoverable_total,
            "idr_forced": self.idr_forced_total,
            "degrades": self.degrades_total,
            "undegrades": self.undegrades_total,
        }
