"""Google-Congestion-Control-style bandwidth estimation.

The reference attaches `rtpgccbwe` (gst-plugins-rs) as webrtcbin's aux
sender and drives `set_video_bitrate(estimate, cc=True)` from its
notify::estimated-bitrate signal (gstwebrtc_app.py:1638-1655). This module
is that estimator rebuilt for the framework's transports:

* delay-based control (draft-ietf-rmcat-gcc-02): per-frame one-way delay
  gradients, smoothed, fed to a trendline slope estimator over a sliding
  window; an adaptive-threshold overuse detector drives an AIMD rate
  controller (multiplicative 0.85x decrease to measured throughput on
  overuse; multiplicative-then-additive increase near convergence).
* loss-based control: the classic >10% / <2% rules, fed from client RTC
  stats when the transport reports loss (WS/TCP transports never do —
  their congestion shows up purely as delay, which the trendline sees).

Feedback arrives as `_ack,<seq>,<recv_ms>` data-channel messages (one per
video frame, the frame-granularity analogue of transport-wide-CC
feedback); send times and frame sizes are recorded server-side at send
time, so the client only echoes the sequence number and its local receive
clock (deltas cancel the clock offset).

Everything takes explicit timestamps — no wall-clock reads — so tests
drive synthetic timelines deterministically.
"""

from __future__ import annotations

import logging
from collections import deque
from dataclasses import dataclass
from typing import Callable

from selkies_tpu.monitoring.telemetry import telemetry

logger = logging.getLogger("transport.gcc")

# trendline / detector constants (draft-ietf-rmcat-gcc-02 §5)
_WINDOW = 20              # delay-gradient samples in the regression window
_SMOOTHING = 0.9          # EWMA on accumulated delay
_THRESHOLD_GAIN = 4.0     # slope -> modified trend multiplier
_K_UP = 0.0087            # adaptive threshold gain (overshoot direction)
_K_DOWN = 0.039           # adaptive threshold gain (recovery direction)
_INIT_THRESHOLD_MS = 12.5
_OVERUSE_TIME_MS = 10.0   # sustained overuse before signalling
_BETA = 0.85              # multiplicative decrease factor


@dataclass
class _Sent:
    send_ms: float
    size: int


class TrendlineEstimator:
    """Delay-gradient slope detector: normal / overuse / underuse."""

    def __init__(self) -> None:
        self._samples: deque[tuple[float, float]] = deque(maxlen=_WINDOW)
        self._acc = 0.0
        self._smoothed = 0.0
        self._prev_send: float | None = None
        self._prev_recv: float | None = None
        self._first_recv: float | None = None
        self._threshold = _INIT_THRESHOLD_MS
        self._overuse_start: float | None = None
        self._last_update: float | None = None
        self.state = "normal"

    def add(self, send_ms: float, recv_ms: float) -> str:
        if self._prev_send is not None:
            d = (recv_ms - self._prev_recv) - (send_ms - self._prev_send)
            self._acc += d
            self._smoothed = _SMOOTHING * self._smoothed + (1 - _SMOOTHING) * self._acc
            if self._first_recv is None:
                self._first_recv = recv_ms
            self._samples.append((recv_ms - self._first_recv, self._smoothed))
            self._update_state(recv_ms)
        self._prev_send = send_ms
        self._prev_recv = recv_ms
        return self.state

    def _slope(self) -> float | None:
        if len(self._samples) < _WINDOW // 2:
            return None
        n = len(self._samples)
        mx = sum(t for t, _ in self._samples) / n
        my = sum(y for _, y in self._samples) / n
        num = sum((t - mx) * (y - my) for t, y in self._samples)
        den = sum((t - mx) ** 2 for t, _ in self._samples)
        return num / den if den else None

    def _update_state(self, now_ms: float) -> None:
        slope = self._slope()
        if slope is None:
            return
        # modified trend: scale by window size like the reference impl
        trend = slope * min(len(self._samples), _WINDOW) * _THRESHOLD_GAIN
        if trend > self._threshold:
            if self._overuse_start is None:
                self._overuse_start = now_ms
            elif now_ms - self._overuse_start >= _OVERUSE_TIME_MS:
                self.state = "overuse"
        elif trend < -self._threshold:
            self._overuse_start = None
            self.state = "underuse"
        else:
            self._overuse_start = None
            self.state = "normal"
        # adaptive threshold (§5.5): track |trend| so persistent queues
        # don't starve us, recover fast when the network clears
        if abs(trend) < self._threshold + 15.0:  # ignore wild outliers
            # k is positive both ways (§5.5): (|trend| - threshold) sets the
            # direction, k only sets how fast each direction adapts
            k = _K_UP if abs(trend) > self._threshold else _K_DOWN
            dt = 0.0 if self._last_update is None else min(now_ms - self._last_update, 100.0)
            self._threshold += k * (abs(trend) - self._threshold) * dt / 25.0
            self._threshold = max(6.0, min(self._threshold, 600.0))
        self._last_update = now_ms


class GccController:
    """Full estimator: feedback in, bitrate-estimate callback out.

    on_estimate(kbps) fires whenever the target changes by >=5% (or on
    every decrease) — the consumer wires it to
    TPUWebRTCApp.set_video_bitrate(kbps, cc=True).
    """

    def __init__(
        self,
        start_kbps: int = 2000,
        min_kbps: int = 100,
        max_kbps: int = 20000,
        on_estimate: Callable[[int], None] | None = None,
        session: str = "0",
    ) -> None:
        # telemetry label: bitrate flaps must be attributable to the
        # session whose link caused them (fleet passes its slot index)
        self.session = str(session)
        self.max_kbps = max_kbps
        self.min_kbps = min(min_kbps, max_kbps)
        self._floor = self.min_kbps  # audio-headroom floor; survives retargets
        self.estimate_kbps = float(start_kbps)
        self.on_estimate = on_estimate or (lambda kbps: None)
        self._trend = TrendlineEstimator()
        self._sent: dict[int, _Sent] = {}
        # (recv_ms, bytes); maxlen backstops the time-window prune below —
        # hostile TWCC whose receive clock never advances would otherwise
        # grow this forever (one entry per acked packet). 4096 >> the ~300
        # entries a real 1 s window holds at 300 pps.
        self._recv_window: deque[tuple[float, int]] = deque(maxlen=4096)
        self._last_decrease_throughput: float | None = None
        self._last_increase_ms: float | None = None
        self._last_reported = float(start_kbps)
        self.last_loss = 0.0  # policy-engine congestion signal

    def reset(self) -> None:
        """New client connection: the receive clock epoch changed
        (performance.now() restarts on reload), so all delay state and the
        in-flight ledger are garbage. Keeps the current estimate — the
        network likely didn't change, only the client did."""
        self._trend = TrendlineEstimator()
        self._sent.clear()
        self._recv_window.clear()
        self._last_decrease_throughput = None
        self._last_increase_ms = None

    def set_target(self, kbps: int) -> None:
        """User-chosen bitrate (UI 'vb' message): retarget the cap and
        restart the probe from it — GCC will cut back within a few frames
        if the link can't actually carry it. The audio-headroom floor set
        at construction is preserved whenever the cap allows it."""
        self.max_kbps = int(kbps)
        self.min_kbps = min(self._floor, self.max_kbps)
        self.estimate_kbps = float(kbps)
        self._last_reported = float(kbps)
        if telemetry.enabled:
            telemetry.gauge("selkies_congestion_target_kbps", float(kbps),
                            session=self.session)
            telemetry.count("selkies_congestion_events_total",
                            session=self.session, event="retarget")

    # -- send side -----------------------------------------------------

    def on_frame_sent(self, seq: int, send_ms: float, size: int) -> None:
        self._sent[seq] = _Sent(send_ms, size)
        if len(self._sent) > 4096:  # acks lost / client gone: bound memory
            # evict by send time, not seq: seq is a 16-bit wrapping counter,
            # so numeric order would evict the newest entries after wrap
            stale = sorted(self._sent, key=lambda k: self._sent[k].send_ms)
            for k in stale[: len(self._sent) - 2048]:
                del self._sent[k]

    # -- feedback ------------------------------------------------------

    def on_frame_ack(self, seq: int, recv_ms: float) -> None:
        sent = self._sent.pop(seq, None)
        if sent is None:
            return
        if telemetry.enabled:
            # closes the frame's timeline (fid resolved from the seq the
            # transport registered at send time)
            telemetry.ack(self.session, seq, recv_ms)
        self._recv_window.append((recv_ms, sent.size))
        while self._recv_window and recv_ms - self._recv_window[0][0] > 1000.0:
            self._recv_window.popleft()
        state = self._trend.add(sent.send_ms, recv_ms)
        self._apply_state(state, recv_ms)

    def on_loss_report(self, fraction_lost: float) -> None:
        """Loss-based bound (only meaningful on lossy transports)."""
        # last-reported loss fraction: the scenario policy engine reads
        # it to tell a link bottleneck from an encoder one
        self.last_loss = float(fraction_lost)
        if telemetry.enabled:
            telemetry.gauge("selkies_congestion_loss_ratio", fraction_lost,
                            session=self.session)
            telemetry.count("selkies_congestion_events_total",
                            session=self.session, event="loss_report")
        if fraction_lost > 0.10:
            self._set(self.estimate_kbps * (1.0 - 0.5 * fraction_lost))
        elif fraction_lost < 0.02:
            self._set(self.estimate_kbps * 1.02)

    # -- rate control --------------------------------------------------

    def _measured_kbps(self) -> float | None:
        if len(self._recv_window) < 2:
            return None
        span = self._recv_window[-1][0] - self._recv_window[0][0]
        if span <= 0:
            return None
        total = sum(b for _, b in self._recv_window)
        return total * 8.0 / span  # bytes / ms -> kbps

    def _apply_state(self, state: str, now_ms: float) -> None:
        measured = self._measured_kbps()
        if state == "overuse":
            target = measured * _BETA if measured is not None else self.estimate_kbps * _BETA
            if target < self.estimate_kbps:
                self._last_decrease_throughput = measured
                self._set(target)
            self._last_increase_ms = now_ms
        elif state == "normal":
            dt = 0.0 if self._last_increase_ms is None else now_ms - self._last_increase_ms
            self._last_increase_ms = now_ms
            if dt <= 0 or dt > 1000.0:
                return
            near = (
                self._last_decrease_throughput is not None
                and abs(self.estimate_kbps - self._last_decrease_throughput)
                < 0.5 * self._last_decrease_throughput
            )
            if near:
                # additive: ~ one mtu per rtt (assume 100 ms rtt bound)
                self._set(self.estimate_kbps + 9.6 * dt / 100.0)
            else:
                self._set(self.estimate_kbps * (1.0 + 0.08 * dt / 1000.0))
        # underuse: hold (the queues are draining; wait for normal)

    def _set(self, kbps: float) -> None:
        kbps = max(float(self.min_kbps), min(float(kbps), float(self.max_kbps)))
        decreased = kbps < self.estimate_kbps
        self.estimate_kbps = kbps
        if decreased or abs(kbps - self._last_reported) >= 0.05 * self._last_reported:
            self._last_reported = kbps
            if telemetry.enabled:
                telemetry.gauge("selkies_congestion_target_kbps", kbps,
                                session=self.session)
                telemetry.count("selkies_congestion_events_total",
                                session=self.session,
                                event="decrease" if decreased else "increase")
            self.on_estimate(int(round(kbps)))
