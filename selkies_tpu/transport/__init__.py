"""Media transport: RTP payloaders, WebSocket media transport, data channels.

The byte plane (RTP/ICE/DTLS) is host-side; only encode runs on TPU.
"""
