"""Multi-host cluster plane: membership, capacity routing, migration.

Turns N independent selkies-tpu hosts into one service (ROADMAP item 4's
multi-host tentpole). Three halves, each usable alone:

* :mod:`~selkies_tpu.cluster.membership` — per-host :class:`ClusterNode`
  heartbeating a signed capacity digest to the static seed list in
  ``SELKIES_CLUSTER_PEERS``, with lease-based failure detection and
  capped-backoff re-join;
* :mod:`~selkies_tpu.cluster.router` — :class:`ClusterRouter` answers
  client HELLOs on the signalling plane: serve locally or redirect to
  the best-scoring peer (free capacity up, chronic SLO burn and
  quarantined chips down, codec capability respected);
* :mod:`~selkies_tpu.cluster.migrate` — cross-host live migration of
  the PR 6 session checkpoints over an authenticated channel, driven by
  the drain controller's migrate-off-then-stop mode.

The plane is OFF unless ``SELKIES_CLUSTER_PEERS`` is set; a single-host
deployment pays nothing. :func:`build_cluster_plane` is the wiring
helper the orchestrators call.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from selkies_tpu.cluster.membership import (
    ClusterNode,
    build_digest,
    capacity_rows_from_env,
    cluster_enabled,
    cluster_peers_from_env,
    cluster_self_from_env,
    load_capacity_rows,
    measured_max_sessions,
)
from selkies_tpu.cluster.migrate import (
    HttpMigrationChannel,
    LocalMigrationChannel,
    MigrationError,
    MigrationTarget,
    migrate_session,
    migration_stats,
)
from selkies_tpu.cluster.router import (
    ClusterRouter,
    Redirect,
    parse_redirect,
    ws_url_of,
)

__all__ = [
    "ClusterNode",
    "ClusterPlane",
    "ClusterRouter",
    "HttpMigrationChannel",
    "LocalMigrationChannel",
    "MigrationError",
    "MigrationTarget",
    "Redirect",
    "build_cluster_plane",
    "build_digest",
    "capacity_rows_from_env",
    "cluster_enabled",
    "cluster_peers_from_env",
    "cluster_self_from_env",
    "load_capacity_rows",
    "measured_max_sessions",
    "migrate_session",
    "migration_stats",
    "parse_redirect",
    "wire_cluster_plane",
    "ws_url_of",
]


@dataclass
class ClusterPlane:
    """One host's assembled cluster wiring (node + router + optional
    migration halves), as attached to an orchestrator."""

    node: ClusterNode
    router: ClusterRouter
    target: MigrationTarget | None = None
    channel: HttpMigrationChannel | None = field(default=None)

    def stats(self) -> dict:
        """/statz ``cluster`` provider block."""
        return {
            "membership": self.node.stats(),
            "router": self.router.stats(),
            "migrations": migration_stats(),
        }

    async def start(self) -> None:
        await self.node.start()

    async def stop(self) -> None:
        await self.node.stop()
        if self.channel is not None:
            await self.channel.close()


def build_cluster_plane(*, fleet=None, is_local_session=None,
                        digest_fn=None) -> ClusterPlane:
    """Assemble the plane from the ``SELKIES_CLUSTER_*`` knobs:
    node + router always; the migration target/channel only when a
    fleet is wired (solo hosts route and heartbeat but don't receive
    migrations — a solo process has exactly one session shape)."""
    node = ClusterNode.from_env(digest_fn=digest_fn)
    router = ClusterRouter(node, is_local_session=is_local_session)
    target = channel = None
    if fleet is not None:
        target = MigrationTarget(fleet=fleet, secret=node.secret,
                                 advertise=node.host)
        channel = HttpMigrationChannel(secret=node.secret)
    return ClusterPlane(node=node, router=router, target=target,
                        channel=channel)


def wire_cluster_plane(plane: ClusterPlane, server, *,
                       enable_basic_auth: bool = False) -> ClusterPlane | None:
    """Attach an assembled plane to a signalling server, or refuse.

    The ``/cluster`` routes dispatch BEFORE the server's basic auth
    (HMAC replaces it there) — with no secret configured they would be
    the only unauthenticated write surface on an otherwise
    auth-protected server, so a basic-auth server without
    ``SELKIES_CLUSTER_SECRET`` refuses to wire the plane at all. The
    ONE place this security policy lives for both orchestrators.
    Returns the plane when wired, None when refused (the caller leaves
    its ``.cluster`` unset)."""
    import logging

    from selkies_tpu.monitoring.telemetry import telemetry

    logger = logging.getLogger("cluster")
    if bool(enable_basic_auth) and not plane.node.secret:
        logger.error(
            "SELKIES_CLUSTER_SECRET is unset while basic auth is on; "
            "cluster plane DISABLED (unsigned /cluster routes would "
            "bypass the server's auth)")
        return None
    if not plane.node.secret:
        logger.warning("cluster plane running UNSIGNED "
                       "(SELKIES_CLUSTER_SECRET unset) — closed "
                       "networks only")
    server.cluster_router = plane.router
    server.ws_routes["/cluster/heartbeat"] = plane.node.http_handler
    if plane.target is not None:
        server.ws_routes["/cluster/migrate"] = plane.target.http_handler
    telemetry.register_provider("cluster", plane.stats)
    return plane
