"""Cross-host live migration: checkpoint → ship → restore → redirect.

PR 6's `checkpoint_session`/`restore_session` made a session's minimal
encoder state a JSON blob whose restore opens with a recovery IDR
byte-identical to an uninterrupted oracle's. This module drives that
blob **between hosts** over an authenticated channel, with the ordering
that makes a mid-migration peer death safe:

1. the **source** checkpoints the session (read-only — the session
   keeps serving; the existing ``migrate:<k>`` fault site fires here);
2. the checkpoint is **shipped** to the target's ``/cluster/migrate``
   endpoint (HMAC-signed with the cluster secret; the ``cluster:ship``
   site injects slow ships and mid-migration deaths);
3. the **target** restores it into a freshly-admitted slot and forces
   the recovery IDR, answering with the landing session id. The slot is
   held under a **claim window** (``SELKIES_CLUSTER_CLAIM_S``): if the
   client never follows its redirect, the slot auto-releases — an
   ack lost on the way back can park capacity, never leak it;
4. only on a positive ack does the source **release** its placement
   and redirect the client (signalling/server.py ``redirect_peer``).

Failure at any step before (4) leaves the session serving on the
source untouched — a migration can be retried or abandoned, but a
session is never in two serving places (the target's restored slot is
*pending*, not connected, until the client actually arrives) and never
in zero (the source releases only after the target acked).

:class:`~selkies_tpu.parallel.lifecycle.DrainController`'s migrate hook
runs this for every connected session before the checkpoint hand-off,
so SIGTERM empties a host into the cluster (migrate-off-then-stop)
instead of dropping its sessions.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from collections import deque

from selkies_tpu.cluster.membership import sign_blob, verify_blob
from selkies_tpu.monitoring.telemetry import telemetry
from selkies_tpu.resilience import get_injector

logger = logging.getLogger("cluster.migrate")

__all__ = [
    "HttpMigrationChannel",
    "LocalMigrationChannel",
    "MigrationError",
    "MigrationTarget",
    "claim_window_from_env",
    "migrate_session",
    "migration_stats",
    "ship_checkpoint",
]

ENV_CLAIM = "SELKIES_CLUSTER_CLAIM_S"

# process-wide migration counters for /statz (monotonic; in_flight is
# the only gauge-like member)
_stats = {"out_ok": 0, "out_fail": 0, "in_ok": 0, "in_fail": 0,
          "in_flight": 0, "claims_expired": 0}


def migration_stats() -> dict:
    return dict(_stats)


def claim_window_from_env() -> float:
    """Seconds a migrated-in session waits for its client before the
    target releases the slot (the lost-ack capacity bound)."""
    env = os.environ.get(ENV_CLAIM, "")
    if not env:
        return 10.0
    try:
        return max(0.5, float(env))
    except ValueError:
        logger.warning("%s=%r is not a number; using 10", ENV_CLAIM, env)
        return 10.0


class MigrationError(RuntimeError):
    """A cross-host migration step failed; the session keeps serving on
    the source."""


# ---------------------------------------------------------------------------
# channels
# ---------------------------------------------------------------------------


class HttpMigrationChannel:
    """Production inter-host channel: HMAC-signed POST to the target's
    ``/cluster/migrate``."""

    def __init__(self, secret: str = ""):
        self.secret = secret
        self._http = None

    async def send(self, host: str, payload: dict) -> dict:
        import aiohttp

        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        body = json.dumps(payload, sort_keys=True)
        url = host.rstrip("/") + "/cluster/migrate"
        try:
            async with self._http.post(
                    url, data=body,
                    headers={"x-selkies-cluster-sig": sign_blob(self.secret,
                                                                body),
                             "Content-Type": "application/json"},
                    timeout=aiohttp.ClientTimeout(total=10.0)) as r:
                if r.status != 200:
                    raise MigrationError(
                        f"migrate to {host} refused: HTTP {r.status}")
                return await r.json()
        except MigrationError:
            raise
        except Exception as exc:
            raise MigrationError(f"migrate ship to {host} failed: "
                                 f"{exc!r}") from exc

    async def close(self) -> None:
        if self._http is not None:
            await self._http.close()
            self._http = None


class LocalMigrationChannel:
    """In-process channel for multi-host tests and single-machine sims:
    a host-label -> async handler registry."""

    def __init__(self):
        self.handlers: dict[str, object] = {}

    def register(self, host: str, handler) -> None:
        self.handlers[host.rstrip("/")] = handler

    async def send(self, host: str, payload: dict) -> dict:
        handler = self.handlers.get(host.rstrip("/"))
        if handler is None:
            raise MigrationError(f"no migration handler for {host}")
        result = handler(payload)
        if asyncio.iscoroutine(result):
            result = await result
        return result


async def ship_checkpoint(channel, host: str, ck, *, source: str = "") -> dict:
    """Ship one checkpoint; the ``cluster:ship`` site fires per ship
    (``delay:<ms>`` = a slow ship eating the drain deadline,
    ``drop``/``raise`` = mid-migration peer death)."""
    fi = get_injector()
    if fi is not None:
        act = fi.check("cluster:ship")  # raises InjectedFault on `raise`
        if act is not None:
            kind, ms = act
            if kind == "delay":
                await asyncio.sleep(ms / 1e3)
            else:  # drop / flap: the ship never reaches the peer
                raise MigrationError("checkpoint ship dropped (injected)")
    # the nonce rides inside the signed body: a captured ship can be
    # replayed byte-for-byte but never re-nonced without the secret,
    # so the target's seen-nonce window shuts replays out
    ack = await channel.send(host, {"checkpoint": ck.to_json(),
                                    "source": source,
                                    "nonce": os.urandom(16).hex()})
    if not isinstance(ack, dict) or not ack.get("ok"):
        raise MigrationError(f"target {host} refused the checkpoint: {ack!r}")
    return ack


# ---------------------------------------------------------------------------
# target (inbound) half
# ---------------------------------------------------------------------------


class MigrationTarget:
    """The receiving host: admit a slot, restore the checkpoint, hold a
    claim window for the redirected client."""

    def __init__(self, fleet=None, *, secret: str = "", advertise: str = "",
                 restore=None, claim_s: float | None = None,
                 clock=time.monotonic):
        self.fleet = fleet
        self.secret = secret
        self.advertise = advertise
        self._restore = restore or self._restore_into_fleet
        self.claim_s = (claim_window_from_env()
                        if claim_s is None else max(0.0, claim_s))
        self._clock = clock
        # session id -> claim deadline for restored-but-unclaimed slots
        self.pending_claims: dict[int, float] = {}
        # replay window: the HMAC authenticates a ship but (unlike the
        # heartbeat's boot+seq) carries no ordering, so a captured
        # signed POST would re-verify forever — refusing recently-seen
        # nonces bounds the damage to nothing (every legitimate ship
        # mints a fresh nonce inside the signed body)
        self._seen_nonces: deque = deque(maxlen=256)

    def handle(self, payload: dict) -> dict:
        """Restore one shipped checkpoint; returns the ack the source
        acts on. Never raises — a refusal is an ack with ok=False so
        the source keeps serving the session."""
        from selkies_tpu.parallel.lifecycle import SessionCheckpoint

        nonce = str(payload.get("nonce", ""))
        if nonce:
            if nonce in self._seen_nonces:
                _stats["in_fail"] += 1
                logger.warning("refusing replayed migrate ship (nonce "
                               "already seen)")
                if telemetry.enabled:
                    telemetry.count("selkies_cluster_migrations_total",
                                    direction="in", result="fail")
                return {"ok": False, "error": "replayed ship (nonce seen)"}
            self._seen_nonces.append(nonce)
        try:
            ck = SessionCheckpoint.from_json(payload["checkpoint"])
            k = self._restore(ck)
        except Exception as exc:
            _stats["in_fail"] += 1
            logger.exception("inbound migration refused")
            if telemetry.enabled:
                telemetry.count("selkies_cluster_migrations_total",
                                direction="in", result="fail")
            return {"ok": False, "error": repr(exc)}
        _stats["in_ok"] += 1
        if self.claim_s > 0:
            self.pending_claims[k] = self._clock() + self.claim_s
            self._arm_claim_timer(k)
        if telemetry.enabled:
            telemetry.count("selkies_cluster_migrations_total",
                            direction="in", result="ok")
            telemetry.event("cluster", session=str(k), action="migrate_in",
                            source=str(payload.get("source", "")))
        logger.warning("migrated session landed as slot %d (from %s)",
                       k, payload.get("source", "?"))
        return {"ok": True, "session": k, "host": self.advertise}

    def _restore_into_fleet(self, ck) -> int:
        """Default restore: the checkpoint's OWN slot index first (the
        client's signalling peer id encodes it — landing on the same
        index keeps the redirect's uid binding trivial), else the first
        unconnected slot admission accepts; GOP/RC state applied,
        recovery IDR forced. The landing index rides the ack so a
        cross-index landing re-targets the client's peer id."""
        from selkies_tpu.parallel.lifecycle import restore_session

        fleet = self.fleet
        if fleet is None:
            raise MigrationError("no fleet wired on this target")
        order = [int(ck.session)] if 0 <= int(ck.session) < len(fleet.slots) \
            else []
        order += [k for k in range(len(fleet.slots)) if k not in order]
        for k in order:
            slot = fleet.slots[k]
            if slot.connected or k in self.pending_claims:
                continue
            adm = fleet.admit_client(k)
            if not adm.accepted:
                continue
            try:
                restore_session(ck, fleet.service, k, slot=slot)
            except Exception:
                # the slot was already admitted: release it or this
                # failed restore parks its chips forever (the module's
                # "never leak capacity" promise) — the source keeps
                # serving either way (it only releases on a positive ack)
                try:
                    fleet.release_session(k)
                except Exception:
                    logger.exception("releasing slot %d after a failed "
                                     "restore also failed", k)
                raise
            return k
        raise MigrationError("no slot with capacity for the migration")

    # -- claim window ---------------------------------------------------

    def _arm_claim_timer(self, k: int) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # sync callers (tests) drive expire_claims directly
        loop.call_later(self.claim_s + 0.05, self.expire_claims)

    def expire_claims(self, now: float | None = None) -> list[int]:
        """Release restored slots whose client never arrived (lost ack
        or lost redirect): parked capacity returns to the pool instead
        of leaking. Returns the expired session ids."""
        now = self._clock() if now is None else now
        expired = []
        for k, deadline in list(self.pending_claims.items()):
            if self.fleet is not None and self.fleet.slots[k].connected:
                self.pending_claims.pop(k, None)  # claimed: keep serving
                continue
            if now >= deadline:
                self.pending_claims.pop(k, None)
                expired.append(k)
                _stats["claims_expired"] += 1
                logger.warning("migrated-in session %d unclaimed for %.1fs;"
                               " releasing the slot", k, self.claim_s)
                if self.fleet is not None:
                    try:
                        self.fleet.release_session(k)
                    except Exception:
                        logger.exception("releasing unclaimed slot %d "
                                         "failed", k)
                if telemetry.enabled:
                    telemetry.event("cluster", session=str(k),
                                    action="claim_expired")
        return expired

    async def http_handler(self, request):
        """aiohttp handler for ``/cluster/migrate`` (HMAC-gated)."""
        from aiohttp import web

        body = await request.text()
        sig = request.headers.get("x-selkies-cluster-sig", "")
        if not verify_blob(self.secret, body, sig):
            return web.json_response({"ok": False, "error": "bad signature"},
                                     status=403)
        try:
            payload = json.loads(body)
        except Exception:
            return web.json_response({"ok": False, "error": "bad body"},
                                     status=400)
        return web.json_response(self.handle(payload))


# ---------------------------------------------------------------------------
# source (outbound) half
# ---------------------------------------------------------------------------


async def migrate_session(fleet, k: int, host: str, channel, *,
                          source: str = "") -> dict:
    """Move fleet session ``k`` to ``host``: checkpoint, ship, and ON
    ACK release the local placement. Raises MigrationError (or the
    injected fault) with the session untouched when any step before the
    ack fails — the caller decides between retry and checkpoint
    hand-off. The client redirect is the CALLER's step (it owns the
    signalling peer)."""
    _stats["in_flight"] += 1
    try:
        from selkies_tpu.parallel.lifecycle import checkpoint_session

        ck = checkpoint_session(fleet.service, k, slot=fleet.slots[k])
        ack = await ship_checkpoint(channel, host, ck, source=source)
    except Exception:
        _stats["out_fail"] += 1
        if telemetry.enabled:
            telemetry.count("selkies_cluster_migrations_total",
                            direction="out", result="fail")
        raise
    finally:
        _stats["in_flight"] -= 1
    # the target holds the session now: free the local carve (queued
    # sessions may promote into it) — the caller redirects the client
    fleet.release_session(k)
    _stats["out_ok"] += 1
    if telemetry.enabled:
        telemetry.count("selkies_cluster_migrations_total",
                        direction="out", result="ok")
        telemetry.event("cluster", session=str(k), action="migrate_out",
                        target=host, landed=ack.get("session"))
    logger.warning("session %d migrated to %s (landing slot %s)",
                   k, host, ack.get("session"))
    return ack
