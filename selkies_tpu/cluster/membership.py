"""Cluster membership: signed capacity heartbeats, leases, re-join.

One host is a single point of failure and a hard capacity ceiling; the
reference platform's answer is Kubernetes (PAPER.md L6, `infra/gke`),
but the TPU-native stack needs a control plane that understands its own
capacity vocabulary — healthy chips and free session rows
(`DevicePool` / `SessionPlacer`), chronic SLO burn (`monitoring/slo.py`),
codec capability, drain state. This module is the peer-to-peer
membership half of that plane:

* **capacity digest** — :func:`build_digest` is the ONE derivation of a
  host's machine-readable capacity/drain summary. ``/healthz`` and
  ``/statz`` surface it through ``telemetry.capacity_digest()`` (which
  delegates here), and the heartbeat ships the same dict — three
  surfaces, one truth, additive-only field changes.
* **heartbeats** — each host runs a :class:`ClusterNode` that POSTs its
  digest (HMAC-SHA256-signed when ``SELKIES_CLUSTER_SECRET`` is set) to
  the static seed list in ``SELKIES_CLUSTER_PEERS`` every
  ``SELKIES_CLUSTER_HEARTBEAT_S`` seconds. The transport is pluggable —
  production uses aiohttp against the peers' ``/cluster/heartbeat``
  endpoint; tests wire nodes together in-process.
* **leases** — a received heartbeat grants its sender a lease of
  ``SELKIES_CLUSTER_LEASE_S`` seconds (default 3 heartbeats); a peer
  whose lease expired is *dead* to the router until it heartbeats again.
  There is no gossip and no consensus: every host holds its own
  eventually-consistent view, which is exactly enough for capacity
  routing (a stale view costs one extra redirect hop, never
  correctness — admission on the target re-checks everything).
* **re-join** — a peer that refuses or times out gets capped-backoff
  retries (`resilience.Backoff`, the signalling reconnect policy), so a
  restarting peer is neither hammered nor forgotten.

Chaos: the ``cluster:heartbeat`` fault site fires per heartbeat send
(``drop`` = lost beat, the lease must expire; ``raise`` = send failure
driving the backoff; ``delay`` stretches the beat) and
``cluster:partition`` fires per receive (``drop`` = a one-way
partition) — a seeded ``SELKIES_FAULTS`` schedule makes lease expiry
and re-join deterministic (tests/test_cluster.py).
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import logging
import os
import socket
import time

from selkies_tpu.monitoring.telemetry import telemetry
from selkies_tpu.resilience import Backoff, InjectedFault, get_injector

logger = logging.getLogger("cluster.membership")

__all__ = [
    "ClusterNode",
    "build_digest",
    "capacity_rows_from_env",
    "cluster_enabled",
    "cluster_peers_from_env",
    "cluster_self_from_env",
    "heartbeat_interval_from_env",
    "lease_from_env",
    "load_capacity_rows",
    "measured_max_sessions",
    "sign_blob",
    "verify_blob",
]

ENV_PEERS = "SELKIES_CLUSTER_PEERS"
ENV_SELF = "SELKIES_CLUSTER_SELF"
ENV_SECRET = "SELKIES_CLUSTER_SECRET"
ENV_HEARTBEAT = "SELKIES_CLUSTER_HEARTBEAT_S"
ENV_LEASE = "SELKIES_CLUSTER_LEASE_S"
ENV_CAPACITY = "SELKIES_CAPACITY_FILE"


def cluster_enabled() -> bool:
    """The cluster plane exists exactly when a peer seed list does."""
    return bool(os.environ.get(ENV_PEERS, "").strip())


def cluster_peers_from_env() -> list[str]:
    """Static seed list: comma-separated peer base URLs."""
    env = os.environ.get(ENV_PEERS, "")
    return [p.strip().rstrip("/") for p in env.split(",") if p.strip()]


def cluster_self_from_env() -> str:
    """This host's advertised base URL — what redirect records and the
    heartbeat envelope name. Defaults to the hostname on the stock
    port so a single-host lab config works unconfigured."""
    env = os.environ.get(ENV_SELF, "").strip().rstrip("/")
    return env or f"http://{socket.gethostname()}:8443"


def heartbeat_interval_from_env() -> float:
    env = os.environ.get(ENV_HEARTBEAT, "")
    if not env:
        return 2.0
    try:
        return max(0.05, float(env))
    except ValueError:
        logger.warning("%s=%r is not a number; using 2", ENV_HEARTBEAT, env)
        return 2.0


def lease_from_env(heartbeat_s: float) -> float:
    """Membership lease; default 3 heartbeats (one lost beat never
    flaps a peer dead, two in a row does by the next evaluation)."""
    env = os.environ.get(ENV_LEASE, "")
    if not env:
        return 3.0 * heartbeat_s
    try:
        return max(heartbeat_s, float(env))
    except ValueError:
        logger.warning("%s=%r is not a number; using 3x heartbeat",
                       ENV_LEASE, env)
        return 3.0 * heartbeat_s


def sign_blob(secret: str, body: str) -> str:
    """HMAC-SHA256 hex over the wire body; "" when unsigned (no secret
    configured — a closed lab network)."""
    if not secret:
        return ""
    return hmac.new(secret.encode(), body.encode(), hashlib.sha256).hexdigest()


def verify_blob(secret: str, body: str, signature: str) -> bool:
    return hmac.compare_digest(sign_blob(secret, body), signature or "")


# ---------------------------------------------------------------------------
# measured capacity curves (bench.py --capacity)
# ---------------------------------------------------------------------------


def load_capacity_rows(path: str) -> list[dict]:
    """Parse a ``bench.py --capacity`` record into capacity rows.

    Accepts the bench's native JSON-lines stream, a JSON array, or a
    driver wrapper dict (the ``BENCH_*.json`` shape, whose row rides in
    ``parsed``/``tail``). A capacity row is any object carrying a
    positive ``max_sessions_at_slo``; everything else in the file is
    ignored, and an unreadable file is an empty curve — the digest then
    falls back to structural free-slot counts, never an error."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        logger.warning("capacity file %s unreadable; using free slots", path)
        return []
    docs: list = []
    try:
        docs.append(json.loads(text))
    except ValueError:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    docs.append(json.loads(line))
                except ValueError:
                    continue
    rows: list[dict] = []

    def _walk(obj) -> None:
        if isinstance(obj, dict):
            if obj.get("max_sessions_at_slo"):
                rows.append(obj)
                return
            for key in ("parsed", "rows"):
                _walk(obj.get(key))
            tail = obj.get("tail")
            if isinstance(tail, str):
                for line in tail.splitlines():
                    if line.startswith("{"):
                        try:
                            _walk(json.loads(line))
                        except ValueError:
                            continue
        elif isinstance(obj, list):
            for item in obj:
                _walk(item)

    _walk(docs)
    return rows


_capacity_cache: tuple[str, float, list[dict]] | None = None


def capacity_rows_from_env() -> list[dict]:
    """Capacity rows from ``SELKIES_CAPACITY_FILE`` (cached by path and
    mtime, so a re-run bench is picked up without a restart)."""
    global _capacity_cache
    path = os.environ.get(ENV_CAPACITY, "").strip()
    if not path:
        return []
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        mtime = -1.0
    if _capacity_cache is not None and _capacity_cache[:2] == (path, mtime):
        return _capacity_cache[2]
    rows = load_capacity_rows(path)
    _capacity_cache = (path, mtime, rows)
    return rows


def measured_max_sessions(rows: list[dict], *, chips: int,
                          codecs: list[str] | None = None) -> int:
    """The measured sessions-at-SLO ceiling for a host shape, 0 when
    the curve has no applicable row (= not measured; callers fall back
    to structural free-slot counts).

    Selection is conservative: rows must name a codec this host serves;
    occupancy-mode rows win over lockstep ones when both exist (the
    production scheduler runs overlapped); an exact chip-count match
    wins over scaling, otherwise the ceiling scales linearly with the
    chip ratio (floored, min 1 — capacity curves are near-linear in
    chips until the host core saturates, PERF.md); and the MIN across
    scenario mixes is taken, so a host never advertises headroom its
    worst measured mix can't serve."""
    served = {str(c).lower() for c in (codecs or ["h264"])}
    usable = []
    for row in rows:
        try:
            ceiling = int(row.get("max_sessions_at_slo", 0))
        except (TypeError, ValueError):
            continue
        if ceiling <= 0:
            continue
        codec = str(row.get("codec", "h264")).lower()
        if codec not in served:
            continue
        usable.append(row)
    if not usable:
        return 0
    overlap = [r for r in usable
               if str(r.get("mode", "overlap")).lower() != "lockstep"]
    if overlap:
        usable = overlap
    exact = [r for r in usable if int(r.get("chips", 0) or 0) == chips]
    per_mix: dict[str, int] = {}
    for row in (exact or usable):
        ceiling = int(row["max_sessions_at_slo"])
        row_chips = int(row.get("chips", 0) or 0)
        if not exact and chips > 0 and row_chips > 0 and row_chips != chips:
            ceiling = max(1, (ceiling * chips) // row_chips)
        mix = str(row.get("mix", row.get("metric", "?")))
        prev = per_mix.get(mix)
        per_mix[mix] = ceiling if prev is None else min(prev, ceiling)
    return min(per_mix.values())


# ---------------------------------------------------------------------------
# the capacity digest — ONE derivation for /healthz, /statz, heartbeat
# ---------------------------------------------------------------------------


def build_digest(*, host: str = "", drain=None, placer=None,
                 devices_view: dict | None = None,
                 slo_views: dict | None = None,
                 codecs: list[str] | None = None,
                 capacity_rows: list[dict] | None = None) -> dict:
    """The machine-readable capacity/drain summary of one host.

    Pure: every source is injected, so two in-process test hosts can
    build digests off their own placers while production feeds the
    process-global registrations (``telemetry.capacity_digest()``).
    Fields are a wire contract shared by ``/healthz`` (``capacity``
    block), ``/statz`` and the cluster heartbeat — additive changes
    only. ``has_placer=False`` marks a host without a placement plane
    (bare solo); the router treats it as one free slot unless draining.

    ``measured_max_sessions`` is the sessions-at-SLO ceiling from this
    host's measured capacity curve (``bench.py --capacity`` via
    ``capacity_rows`` or ``SELKIES_CAPACITY_FILE``); 0 means not
    measured, and routers fall back to the structural ``free_slots``.
    """
    d = {
        "host": host,
        "ts": round(time.time(), 3),
        "draining": False,
        "drain_state": "serving",
        "chips": 0,
        "healthy_chips": 0,
        "quarantined_chips": 0,
        "capacity": 1.0,
        "bands": 1,
        "shared": False,
        "has_placer": False,
        "free_chips": 0,
        "free_slots": 0,
        "sessions": 0,
        "busy": 0,
        "queue": 0,
        "chronic_burn": [],
        "codecs": list(codecs) if codecs is not None else ["h264"],
        "measured_max_sessions": 0,
    }
    if devices_view:
        d["chips"] = int(devices_view.get("chips", 0))
        d["healthy_chips"] = int(devices_view.get("healthy", 0))
        d["quarantined_chips"] = len(devices_view.get("quarantined") or ())
        d["capacity"] = float(devices_view.get("capacity", 1.0))
    if drain is not None:
        state = getattr(drain, "state", "serving")
        d["drain_state"] = state
        d["draining"] = state != "serving"
        if placer is None:
            placer = getattr(drain, "placer", None)
    if placer is not None:
        st = placer.stats()
        states = placer.states()
        d["has_placer"] = True
        d["bands"] = int(getattr(placer, "bands", 1))
        d["shared"] = bool(st.get("shared"))
        d["draining"] = d["draining"] or bool(st.get("draining"))
        d["free_chips"] = int(st.get("free", 0))
        d["sessions"] = len(st.get("carve") or ())
        d["queue"] = len(st.get("queue") or ())
        d["busy"] = sum(1 for s in states.values() if s == "busy")
        idle = sum(1 for s in states.values() if s == "serving")
        d["free_slots"] = idle + (
            0 if d["shared"] else d["free_chips"] // max(1, d["bands"]))
        if d["chips"] == 0:
            # no device health plane registered: the placer's carve is
            # the only chip truth this host has
            d["chips"] = int(st.get("chips", 0))
            d["quarantined_chips"] = len(st.get("quarantined") or ())
            d["healthy_chips"] = d["chips"] - d["quarantined_chips"]
            d["capacity"] = (round(d["healthy_chips"] / d["chips"], 3)
                             if d["chips"] else 1.0)
    if slo_views:
        d["chronic_burn"] = sorted(
            s for s, v in slo_views.items()
            if isinstance(v, dict) and v.get("chronic"))
    rows = (capacity_rows if capacity_rows is not None
            else capacity_rows_from_env())
    if rows:
        d["measured_max_sessions"] = measured_max_sessions(
            rows, chips=d["chips"], codecs=d["codecs"])
    return d


# ---------------------------------------------------------------------------
# per-peer membership state
# ---------------------------------------------------------------------------


class _PeerState:
    __slots__ = ("url", "digest", "lease_until", "last_seq", "last_boot",
                 "backoff", "next_send", "sent", "ok", "failed", "received",
                 "rejected")

    def __init__(self, url: str):
        self.url = url
        self.digest: dict | None = None
        self.lease_until = 0.0
        self.last_seq = -1
        self.last_boot = ""
        # capped-backoff re-join: a dead/refusing peer decays to ~30 s
        # retries instead of a hot loop, and heals to the heartbeat
        # cadence on the first success
        self.backoff = Backoff(base=0.5, cap=30.0, jitter=0.0)
        self.next_send = 0.0
        self.sent = self.ok = self.failed = 0
        self.received = self.rejected = 0


class ClusterNode:
    """One host's membership agent: heartbeat out, leases in.

    ``transport`` is ``async (peer_url, body, signature) -> bool``;
    the default POSTs to ``{peer}/cluster/heartbeat`` (the signalling
    server routes that path here when the orchestrators wire the
    plane). ``digest_fn`` builds this host's capacity digest — the
    production wiring passes ``telemetry.capacity_digest``.
    """

    # non-seed senders are tracked so asymmetric seed configs converge,
    # but the table is bounded: every tracked host is a permanent
    # _PeerState plus a Prometheus peer-label series, and in unsigned
    # mode anything that can reach /cluster/heartbeat can name a fresh
    # host per POST — without a cap one scanner grows memory and scrape
    # size without bound. Dead non-seed peers are evicted to make room.
    MAX_TRACKED_PEERS = 64

    def __init__(self, host: str, peers: list[str], *, secret: str = "",
                 heartbeat_s: float | None = None, lease_s: float | None = None,
                 digest_fn=None, transport=None, clock=time.monotonic):
        self.host = host.rstrip("/")
        self.secret = secret
        self.heartbeat_s = (heartbeat_interval_from_env()
                            if heartbeat_s is None else max(0.05, heartbeat_s))
        self.lease_s = (lease_from_env(self.heartbeat_s)
                        if lease_s is None else max(self.heartbeat_s, lease_s))
        self._digest_fn = digest_fn or telemetry.capacity_digest
        self._transport = transport or self._http_send
        self._clock = clock
        self._peers: dict[str, _PeerState] = {
            p: _PeerState(p) for p in (u.rstrip("/") for u in peers)
            if p and p != self.host}
        self._seeds = frozenset(self._peers)
        self._seq = 0
        # per-process boot id: receivers pair it with the seq so a
        # captured beat from this boot can never be replayed past a
        # newer one, while a genuine restart (new boot id, seq reset)
        # re-joins immediately
        self._boot = os.urandom(8).hex()
        self._task: asyncio.Task | None = None
        self._http = None

    @classmethod
    def from_env(cls, *, digest_fn=None, transport=None) -> "ClusterNode":
        return cls(cluster_self_from_env(), cluster_peers_from_env(),
                   secret=os.environ.get(ENV_SECRET, ""),
                   digest_fn=digest_fn, transport=transport)

    # -- outbound -------------------------------------------------------

    def self_digest(self) -> dict:
        d = dict(self._digest_fn() or {})
        d["host"] = self.host
        return d

    def envelope(self) -> tuple[str, str]:
        """(body, signature) of one heartbeat."""
        self._seq += 1
        body = json.dumps({"host": self.host, "seq": self._seq,
                           "boot": self._boot,
                           "digest": self.self_digest()}, sort_keys=True)
        return body, sign_blob(self.secret, body)

    async def heartbeat_once(self) -> None:
        """One beat to every seed peer that is not backing off. Failures
        arm the peer's capped backoff; success heals it. The
        ``cluster:heartbeat`` site fires once per (beat, peer) send."""
        body, sig = self.envelope()
        now = self._clock()
        fi = get_injector()
        for st in self._peers.values():
            if now < st.next_send:
                continue
            if fi is not None:
                try:
                    act = fi.check("cluster:heartbeat")
                except InjectedFault:
                    self._send_failed(st, "injected")
                    continue
                if act is not None:
                    kind, ms = act
                    if kind in ("drop", "flap"):
                        continue  # the beat is lost in flight: no backoff,
                        # the peer's lease on US simply ages toward expiry
                    if kind == "delay":
                        await asyncio.sleep(ms / 1e3)
            st.sent += 1
            try:
                ok = await self._transport(st.url, body, sig)
            except Exception as exc:
                logger.info("heartbeat to %s failed: %r", st.url, exc)
                ok = False
            if ok:
                st.ok += 1
                st.backoff.reset()
                st.next_send = 0.0
                if telemetry.enabled:
                    telemetry.count("selkies_cluster_heartbeats_total",
                                    peer=st.url, result="ok")
            else:
                self._send_failed(st, "send")
        self._export_gauges()

    def _send_failed(self, st: _PeerState, why: str) -> None:
        st.failed += 1
        delay = st.backoff.next_delay()
        st.next_send = self._clock() + delay
        logger.info("peer %s unreachable (%s); re-join retry in %.1fs",
                    st.url, why, delay)
        if telemetry.enabled:
            telemetry.count("selkies_cluster_heartbeats_total",
                            peer=st.url, result="fail")

    async def _http_send(self, peer: str, body: str, sig: str) -> bool:
        import aiohttp

        if self._http is None or self._http.closed:
            self._http = aiohttp.ClientSession()
        url = peer.rstrip("/") + "/cluster/heartbeat"
        async with self._http.post(
                url, data=body,
                headers={"x-selkies-cluster-sig": sig,
                         "Content-Type": "application/json"},
                timeout=aiohttp.ClientTimeout(total=2.0)) as r:
            return r.status == 200

    # -- inbound --------------------------------------------------------

    def receive(self, body: str, signature: str = "") -> bool:
        """One inbound heartbeat: verify, refresh the sender's lease,
        store its digest. Unknown (but correctly signed) senders are
        tracked too — the seed list bounds who WE beat to, not who may
        beat to us, so asymmetric seed configs still converge — up to
        ``MAX_TRACKED_PEERS``, beyond which new hosts are refused
        (dead non-seed entries are evicted first). The
        ``cluster:partition`` site drops inbound beats (a one-way
        partition the lease must surface)."""
        fi = get_injector()
        if fi is not None:
            try:
                act = fi.check("cluster:partition")
            except InjectedFault:
                act = ("drop", 0.0)
            if act is not None and act[0] in ("drop", "flap"):
                return False
        if not verify_blob(self.secret, body, signature):
            logger.warning("rejecting unsigned/mis-signed heartbeat")
            if telemetry.enabled:
                telemetry.count("selkies_cluster_heartbeats_total",
                                peer="?", result="rejected")
            return False
        try:
            data = json.loads(body)
            host = str(data["host"]).rstrip("/")
            seq = int(data.get("seq", 0))
            boot = str(data.get("boot", ""))
            digest = dict(data.get("digest") or {})
        except Exception:
            logger.warning("rejecting malformed heartbeat body")
            return False
        if host == self.host:
            return True  # self-echo (a seed list including ourselves)
        st = self._peers.get(host)
        if st is None:
            if len(self._peers) >= self.MAX_TRACKED_PEERS:
                self._evict_dead_nonseed()
            if len(self._peers) >= self.MAX_TRACKED_PEERS:
                logger.warning("peer table full (%d tracked, all alive or "
                               "seeds); dropping heartbeat from unknown "
                               "host %s", len(self._peers), host)
                if telemetry.enabled:
                    telemetry.count("selkies_cluster_heartbeats_total",
                                    peer="?", result="rejected")
                return False
            st = self._peers[host] = _PeerState(host)
            st.next_send = float("inf")  # not in OUR seed list: track only
        was_alive = st.lease_until > self._clock()
        if boot == st.last_boot and seq <= st.last_seq:
            # stale duplicate / replay from the peer's CURRENT boot: an
            # out-of-order beat must not roll the digest back (a delayed
            # pre-drain digest would keep routers sending clients to a
            # draining host), and a captured beat must not revive a dead
            # peer's lease — alive or not, same-boot seqs only move
            # forward. A genuinely restarted peer arrives with a fresh
            # boot id (seq reset is fine) and re-joins immediately.
            # Residual: a replay of a beat from an OLDER, never/last-
            # unseen boot is indistinguishable from a restart without
            # timestamped envelopes; the digest it installs ages out
            # within one lease.
            return True
        st.digest = digest
        st.last_seq = seq
        st.last_boot = boot
        st.lease_until = self._clock() + self.lease_s
        st.received += 1
        if telemetry.enabled:
            telemetry.count("selkies_cluster_heartbeats_total",
                            peer=host, result="received")
            if not was_alive:
                telemetry.event("cluster", host=host, action="peer_alive",
                                seq=seq)
        if not was_alive:
            logger.info("peer %s alive (lease %.1fs)", host, self.lease_s)
        return True

    def _evict_dead_nonseed(self) -> None:
        """Drop lease-expired peers we never beat to (not in the seed
        list): they exist only because they once heartbeated us, and a
        full table must prefer live members over dead strangers."""
        now = self._clock()
        for url in [u for u, st in self._peers.items()
                    if u not in self._seeds and st.lease_until <= now]:
            del self._peers[url]
            logger.info("evicted dead non-seed peer %s (table full)", url)

    async def http_handler(self, request):
        """aiohttp handler for ``/cluster/heartbeat`` (registered into
        SignallingServer.ws_routes by the orchestrators; HMAC replaces
        basic auth on this path)."""
        from aiohttp import web

        body = await request.text()
        sig = request.headers.get("x-selkies-cluster-sig", "")
        ok = self.receive(body, sig)
        return web.json_response({"ok": ok}, status=200 if ok else 403)

    # -- read side ------------------------------------------------------

    def alive_peers(self) -> dict[str, dict]:
        """host -> last digest, for peers whose lease is unexpired and
        who have reported a digest at all."""
        now = self._clock()
        return {st.url: st.digest for st in self._peers.values()
                if st.digest is not None and st.lease_until > now}

    def peer_alive(self, host: str) -> bool:
        st = self._peers.get(host.rstrip("/"))
        return st is not None and st.lease_until > self._clock()

    def stats(self) -> dict:
        """/statz ``cluster.membership`` block."""
        now = self._clock()
        return {
            "self": self.host,
            "heartbeat_s": self.heartbeat_s,
            "lease_s": self.lease_s,
            "signed": bool(self.secret),
            "peers": {
                st.url: {
                    "alive": st.lease_until > now,
                    "lease_s": round(max(0.0, st.lease_until - now), 1),
                    "sent": st.sent, "ok": st.ok, "failed": st.failed,
                    "received": st.received,
                    "backoff_s": round(max(0.0, st.next_send - now), 1)
                    if st.next_send not in (0.0, float("inf")) else 0.0,
                    "free_slots": (st.digest or {}).get("free_slots"),
                    "draining": (st.digest or {}).get("draining"),
                }
                for st in sorted(self._peers.values(), key=lambda s: s.url)
            },
        }

    def _export_gauges(self) -> None:
        if not telemetry.enabled:
            return
        now = self._clock()
        alive = sum(1 for st in self._peers.values()
                    if st.lease_until > now)
        telemetry.gauge("selkies_cluster_peers", alive, state="alive")
        telemetry.gauge("selkies_cluster_peers",
                        len(self._peers) - alive, state="dead")

    # -- lifecycle ------------------------------------------------------

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(
                self._heartbeat_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._http is not None:
            await self._http.close()
            self._http = None

    async def _heartbeat_loop(self) -> None:
        while True:
            try:
                await self.heartbeat_once()
            except Exception:
                logger.exception("heartbeat round failed; next beat rides")
            await asyncio.sleep(self.heartbeat_s)
