"""Capacity-aware signalling admission: serve locally or redirect.

The signalling server consults a :class:`ClusterRouter` on every
client HELLO that carries meta (browsers always do — PR 8.1's codec
preference list rides there; the in-process server-side clients never
do, so backend planes are never re-routed). The router reads the
freshest membership view (cluster/membership.py) and answers one of:

* **serve locally** (``None``) — the default whenever this host can:
  not draining, has a free session slot (or a shared small-slice carve,
  where capacity gating is off), or the HELLO belongs to a session
  already served here (a reconnecting client must NEVER be bounced off
  the host that holds its carved row and encoder state);
* **redirect** (:class:`Redirect`) — a ``REDIRECT <b64 json>`` record
  (host, reason, retry-after) the client's reconnect loop follows
  (signalling/client.py caps the chain so two hosts can never ping-pong
  a client forever).

Scoring prefers free capacity (free session slots), penalizes chronic
SLO burn (the PR 12 slow-window autoscaling signal — a host that keeps
missing its latency objectives is the WRONG place to add load even
when chips are free) and quarantined chips, and respects codec
capability: an AV1-preferring client is only redirected to a host
whose digest lists av1, and never lands on an h264-only host when an
av1 host with capacity exists. Every decision is recorded for
``/statz`` (``cluster.router``). The ``cluster:redirect`` fault site
fires where the record is SENT (signalling/server.py) — a dropped
record is a lost redirect the client's reconnect loop must survive.
"""

from __future__ import annotations

import base64
import json
import logging
import time
from collections import deque
from dataclasses import asdict, dataclass

from selkies_tpu.monitoring.telemetry import telemetry

logger = logging.getLogger("cluster.router")

__all__ = ["ClusterRouter", "Redirect", "parse_redirect", "ws_url_of"]

# scoring weights: one free slot outweighs one chronically-burning
# session (2x) and two quarantined chips; a top-preference codec match
# breaks ties between equally-free hosts
_W_CHRONIC = 2.0
_W_QUARANTINE = 0.5
_W_CODEC = 0.25


@dataclass(frozen=True)
class Redirect:
    """One server-initiated redirect record, as shipped on the wire.

    ``session`` is set on migrate-off redirects when the session landed
    on a DIFFERENT slot index than it held on the source: the client
    must re-register under the landing slot's peer id or it would pair
    with the wrong slot's signalling loop on the target."""

    host: str              # the target's advertised base URL
    reason: str = ""       # draining | capacity | codec | migrated
    retry_after_s: float = 0.5
    session: int | None = None  # landing slot index on the target

    def to_wire(self) -> str:
        blob = json.dumps(asdict(self), sort_keys=True).encode()
        return "REDIRECT " + base64.b64encode(blob).decode("ascii")


def parse_redirect(message: str) -> Redirect | None:
    """Inverse of :meth:`Redirect.to_wire`; None on anything malformed
    (a garbled record must never crash the client's dispatch loop)."""
    try:
        _, b64 = message.split(None, 1)
        data = json.loads(base64.b64decode(b64))
        session = data.get("session")
        return Redirect(host=str(data["host"]).rstrip("/"),
                        reason=str(data.get("reason", "")),
                        retry_after_s=float(data.get("retry_after_s", 0.5)),
                        session=int(session) if session is not None else None)
    except Exception:
        logger.warning("ignoring malformed redirect record %r", message[:80])
        return None


def ws_url_of(host: str) -> str:
    """A redirect target's signalling WebSocket URL from its advertised
    base URL (http(s) base -> ws(s)://…/ws; ws URLs pass through)."""
    host = host.rstrip("/")
    if host.startswith(("ws://", "wss://")):
        base, rest = host.split("://", 1)
        return host if "/" in rest else host + "/ws"
    if host.startswith("https://"):
        return "wss://" + host[len("https://"):] + "/ws"
    if host.startswith("http://"):
        return "ws://" + host[len("http://"):] + "/ws"
    return "ws://" + host + "/ws"


class ClusterRouter:
    """Admission routing over one node's membership view.

    ``is_local_session(uid)`` is the owner's hook saying "this HELLO
    uid belongs to a session currently served here" — those are never
    redirected (their encoder state, carve row and SLO windows live on
    this host)."""

    def __init__(self, node, *, is_local_session=None,
                 retry_after_s: float = 0.5):
        self.node = node
        self.is_local_session = is_local_session
        self.retry_after_s = float(retry_after_s)
        # /statz: the last routing decisions, newest last
        self.decisions: deque = deque(maxlen=16)
        self.redirects = 0

    # -- scoring --------------------------------------------------------

    @staticmethod
    def _prefs_of(meta) -> list[str]:
        if isinstance(meta, dict):
            prefs = meta.get("codecs")
            if isinstance(prefs, (list, tuple)):
                return [str(c).lower() for c in prefs if c]
        return []

    @staticmethod
    def _measured_headroom(digest: dict) -> int | None:
        """Sessions the host's MEASURED capacity curve still admits
        (``measured_max_sessions`` minus placed sessions), or None when
        the host reports no curve (fall back to structural slots)."""
        measured = int(digest.get("measured_max_sessions", 0) or 0)
        if measured <= 0:
            return None
        return max(0, measured - int(digest.get("sessions", 0)))

    @classmethod
    def _has_capacity(cls, digest: dict) -> bool:
        if digest.get("draining"):
            return False
        if not digest.get("has_placer"):
            # bare solo host: its one session is the whole capacity —
            # `busy` (set by the solo wiring) is its free/full bit
            return int(digest.get("busy", 0)) == 0
        headroom = cls._measured_headroom(digest)
        if headroom is not None and headroom <= 0:
            # the measured sessions-at-SLO ceiling binds even a shared
            # placer: structurally admissible ≠ servable within SLO
            return False
        return bool(digest.get("shared")) or int(
            digest.get("free_slots", 0)) > 0

    @classmethod
    def score(cls, digest: dict, prefs: list[str]) -> float:
        """Higher is better. Free slots up — clamped to the measured
        sessions-at-SLO headroom when the host ships a capacity curve
        (a shared placer's headroom replaces its slot count outright) —
        chronic SLO burn and quarantined chips down, small bonus for
        serving the client's top codec preference natively."""
        s = float(digest.get("free_slots", 0))
        headroom = cls._measured_headroom(digest)
        if headroom is not None:
            s = float(headroom) if digest.get("shared") else min(
                s, float(headroom))
        if not digest.get("has_placer"):
            s = 0.0 if digest.get("busy") else 1.0
        s -= _W_CHRONIC * len(digest.get("chronic_burn") or ())
        s -= _W_QUARANTINE * int(digest.get("quarantined_chips", 0))
        if prefs and prefs[0] in (digest.get("codecs") or ()):
            s += _W_CODEC
        return s

    def _candidates(self, prefs: list[str]) -> list[tuple[str, dict]]:
        """Alive, non-draining peers with capacity, codec-capable for
        the client (any preferred codec; every host serves h264)."""
        out = []
        for host, digest in self.node.alive_peers().items():
            if not self._has_capacity(digest):
                continue
            codecs = digest.get("codecs") or ["h264"]
            if prefs and not any(c in codecs for c in [*prefs, "h264"]):
                continue
            out.append((host, digest))
        return out

    def _best(self, prefs: list[str], *,
              migration: bool = False) -> tuple[str, dict] | None:
        """The one scoring truth for HELLO routing and drain
        migrate-off. ``migration=True`` tightens eligibility: the
        target must be placement-capable (``has_placer`` — a bare solo
        host wires no /cluster/migrate endpoint, shipping it a
        checkpoint can only 404) and must serve the codec natively."""
        cands = self._candidates(prefs)
        if migration:
            cands = [(h, d) for h, d in cands if d.get("has_placer")]
        if prefs:
            # hard capability rule, not a tiebreak: when ANY candidate
            # serves the client's top preference natively, only those
            # are eligible — an av1 client never lands on an h264-only
            # host while an av1 host with capacity exists. For a
            # migration the native set is the ONLY eligible set (the
            # session already runs that codec).
            native = [(h, d) for h, d in cands
                      if prefs[0] in (d.get("codecs") or ())]
            if native or migration:
                cands = native
        if not cands:
            return None
        # deterministic: score desc, then host asc — two routers with
        # the same view pick the same target
        return sorted(cands, key=lambda hd: (-self.score(hd[1], prefs),
                                             hd[0]))[0]

    # -- the admission decision ----------------------------------------

    def route(self, meta, *, uid: str = "") -> Redirect | None:
        """None = serve locally; a Redirect = answer the HELLO with it.

        Local-first: a host that can serve, serves — the cluster only
        moves clients OFF a host that is draining or full, or ONWARD to
        a host that natively serves the client's top codec preference
        when this one cannot. Reconnects into live local sessions are
        pinned here unconditionally."""
        if uid and self.is_local_session is not None:
            try:
                if self.is_local_session(uid):
                    return None
            except Exception:
                logger.exception("is_local_session(%r) failed; serving "
                                 "locally", uid)
                return None
        prefs = self._prefs_of(meta)
        local = self.node.self_digest()
        rd: Redirect | None = None
        reason = "local"
        if not self._has_capacity(local):
            best = self._best(prefs)
            if best is not None:
                reason = "draining" if local.get("draining") else "capacity"
                rd = Redirect(host=best[0], reason=reason,
                              retry_after_s=self.retry_after_s)
            else:
                reason = "no-peer"  # local admission queues/rejects it
        elif prefs and prefs[0] not in (local.get("codecs") or ["h264"]):
            # codec-capability routing: this host would degrade the
            # client to h264 — prefer a peer that serves the preference
            best = self._best(prefs)
            if best is not None and prefs[0] in (best[1].get("codecs") or ()):
                reason = "codec"
                rd = Redirect(host=best[0], reason="codec",
                              retry_after_s=self.retry_after_s)
        self.decisions.append({
            "ts": round(time.time(), 1), "uid": str(uid),
            "to": rd.host if rd is not None else "local",
            "reason": rd.reason if rd is not None else reason,
        })
        if rd is not None:
            self.redirects += 1
            logger.info("redirecting HELLO %s -> %s (%s)",
                        uid or "?", rd.host, rd.reason)
        return rd

    def pick_migration_target(self, codec: str = "h264") -> str | None:
        """Best host to migrate a live session to (drain migrate-off):
        alive, not draining, has capacity, serves the session's codec.
        None when the cluster has nowhere to put it (the session falls
        back to the checkpoint hand-off)."""
        best = self._best([str(codec).lower() or "h264"], migration=True)
        return best[0] if best is not None else None

    def stats(self) -> dict:
        """/statz ``cluster.router`` block."""
        return {"redirects": self.redirects,
                "decisions": list(self.decisions)}
