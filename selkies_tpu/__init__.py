"""selkies-tpu — TPU-native low-latency remote desktop / game streaming framework.

A ground-up re-design of the capabilities of Selkies-GStreamer
(reference: maksgranko/selkies) for Google TPU hardware:

- Video encoding (H.264 / VP9 / AV1) runs as JAX/XLA + Pallas kernels on TPU
  (``tpuh264enc`` and friends) instead of NVENC / VA-API / x264
  (reference: gstwebrtc_app.py:260-783, the encoder matrix).
- The pipeline builder, signalling, input injection, congestion control, and
  observability layers are asyncio-native Python (reference layer map:
  SURVEY.md §1), with hot host-side byte work (CAVLC bit packing) in C++.
- Multi-session scale-out maps one 1080p60 stream per TPU chip over a
  ``jax.sharding.Mesh`` (reference's K8s fleet concern, re-imagined as
  SPMD session placement).

Package layout:
  models/    codec "model families": h264 (flagship), vp9, av1
  ops/       JAX/Pallas compute ops (colorspace, transforms, prediction)
  parallel/  device-mesh session placement and intra-frame sharding
  pipeline/  asyncio pipeline framework + TPUWebRTCApp app core
  signalling/ WebRTC signalling server + in-process client
  transport/ RTP payloaders, WebSocket media transport, data channels
  input_host/ keyboard/mouse/gamepad/clipboard injection into X11
  monitoring/ Prometheus metrics, system/TPU monitors
  utils/     bitstream writers, misc helpers
"""

__version__ = "0.1.0"
