"""ctypes wrapper for libx265: the real `x265enc` HEVC software row.

The reference's x265enc element (gstwebrtc_app.py:667-683) IS libx265
behind GObject properties; wrapping the same library gives behavioural
parity for the CPU HEVC row (round 3 aliased x265enc to the TPU H.264
encoder on the false claim that no HEVC library existed in this image;
libx265.so.199 is right there). Tuning mirrors the reference + x264enc
row: CBR, zerolatency tune, ultrafast preset, no B-frames, no lookahead,
VBV ≈ 1.5 frame-times, Annex-B byte-stream with repeated VPS/SPS/PPS
(config-interval -1 analogue), infinite GOP with IDR on demand.

ABI notes: built against libx265.so.199 (v3.5, Debian). Every tunable
goes through x265_param_parse (string API, offset-free — including
input-res/fps/input-csp, which x265 parses unlike x264). Only the
x265_picture struct is poked directly (pts @0, planes[3] @24,
stride[3] @48, bitDepth @60, sliceType @64, colorSpace @72), each
VERIFIED at load time against x265_picture_init ground truth
(bitDepth=8, colorSpace=I420=1, all else zero) and x265_api_get_199's
advertised build/sizes — a mismatched build disables the row instead of
corrupting memory. x265_nal is {u32 type; u32 sizeBytes; u8* payload}
(16 bytes padded), verified by checking header output starts with an
Annex-B start code.
"""

from __future__ import annotations

import ctypes
import logging
import struct as _struct
import time

import numpy as np

from selkies_tpu.models.libvpx_enc import _bgrx_to_i420_np
from selkies_tpu.models.stats import FrameStats

logger = logging.getLogger("models.x265")

_PARAM_BYTES = 2048   # api reports sizeof_param=1168
_PIC_BYTES = 17408    # api reports sizeof_picture=16816 (embeds analysisData)
# x265_picture offsets (verified in _load_and_verify)
_OFF_PTS = 0
_OFF_PLANES = 24
_OFF_STRIDES = 48
_OFF_BITDEPTH = 60
_OFF_SLICETYPE = 64
_OFF_COLORSPACE = 72
_CSP_I420 = 1
_TYPE_AUTO, _TYPE_IDR = 0, 1
# x265_nal: type u32, sizeBytes u32, payload u8* — 16 bytes with padding
_NAL_STRIDE = 16
_NAL_PAYLOAD_PTR_OFF = 8
_API_BUILD = 199

_lib = None
_lib_tried = False


def _load_and_verify():
    """Load libx265 and verify every struct offset this wrapper pokes."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    for name in ("libx265.so.199", "libx265.so", "x265"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        logger.info("libx265 not found; x265enc row unavailable")
        return None
    try:
        open_fn = lib.x265_encoder_open_199
    except AttributeError:
        logger.warning("libx265 present but not build 199; refusing ABI guess")
        return None
    lib._open = open_fn
    lib._open.restype = ctypes.c_void_p
    lib.x265_api_get_199.restype = ctypes.c_void_p
    lib.x265_encoder_encode.restype = ctypes.c_int
    lib.x265_encoder_encode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_uint32), ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.x265_encoder_close.argtypes = [ctypes.c_void_p]

    # --- verification against ground truth ----------------------------
    api = lib.x265_api_get_199(ctypes.c_int(8))
    ok = bool(api)
    if ok:
        major, build, sz_param, sz_pic = _struct.unpack_from(
            "<4i", ctypes.string_at(api, 16), 0)
        ok = (build == _API_BUILD and sz_param <= _PARAM_BYTES
              and sz_pic <= _PIC_BYTES)
    if ok:
        param = (ctypes.c_uint8 * _PARAM_BYTES)()
        ok = lib.x265_param_default_preset(param, b"ultrafast", b"zerolatency") == 0
        pic = (ctypes.c_uint8 * _PIC_BYTES)()
        if ok:
            lib.x265_picture_init(param, pic)
            pb = bytes(pic[:128])
            ok = (
                _struct.unpack_from("<i", pb, _OFF_BITDEPTH)[0] == 8
                and _struct.unpack_from("<i", pb, _OFF_COLORSPACE)[0] == _CSP_I420
                and _struct.unpack_from("<i", pb, _OFF_SLICETYPE)[0] == _TYPE_AUTO
                and not any(_struct.unpack_from("<3Q", pb, _OFF_PLANES))
            )
    if ok:
        # verify the x265_nal layout: open a tiny encoder, emit headers,
        # check the first payload starts with an Annex-B start code (a
        # layout mismatch disables the row instead of dereferencing junk)
        for k, v in ((b"input-res", b"64x48"), (b"fps", b"30/1"),
                     (b"annexb", b"1"), (b"repeat-headers", b"1"),
                     (b"log-level", b"none")):
            ok = ok and lib.x265_param_parse(param, k, v) == 0
        h = lib._open(param) if ok else None
        if h:
            nal_ptr = ctypes.c_void_p()
            n_nal = ctypes.c_uint32()
            size = lib.x265_encoder_headers(
                ctypes.c_void_p(h), ctypes.byref(nal_ptr), ctypes.byref(n_nal))
            ok = size > 0 and n_nal.value > 0
            if ok:
                payload = ctypes.cast(
                    nal_ptr.value + _NAL_PAYLOAD_PTR_OFF,
                    ctypes.POINTER(ctypes.c_uint64))[0]
                head = ctypes.string_at(payload, 4) if payload else b""
                ok = head == b"\x00\x00\x00\x01"
            lib.x265_encoder_close(ctypes.c_void_p(h))
        else:
            ok = False
    if not ok:
        logger.warning("libx265 struct layout mismatch; x265enc row disabled")
        return None
    _lib = lib
    return _lib


def x265_available() -> bool:
    return _load_and_verify() is not None


class X265Encoder:
    """x265enc: frame in, Annex-B HEVC access unit out (TPUH264Encoder
    facade — pipeline/elements.py calls encode_frame(frame, qp) and
    reads last_stats)."""

    codec = "h265"

    def __init__(self, width: int, height: int, fps: int = 60,
                 bitrate_kbps: int = 2000, preset: str = "ultrafast"):
        lib = _load_and_verify()
        if lib is None:
            raise RuntimeError("libx265 unavailable")
        if width % 2 or height % 2:
            raise ValueError("4:2:0 requires even dimensions")
        self._lib = lib
        self.width, self.height, self.fps = width, height, fps
        self.qp = 0
        param = (ctypes.c_uint8 * _PARAM_BYTES)()
        if lib.x265_param_default_preset(param, preset.encode(), b"zerolatency"):
            raise RuntimeError("x265_param_default_preset failed")

        def parse(k: str, v: str) -> None:
            if lib.x265_param_parse(param, k.encode(), v.encode()):
                raise RuntimeError(f"x265_param_parse {k}={v} failed")

        # reference x265enc row parity (gstwebrtc_app.py:667-683)
        parse("input-res", f"{width}x{height}")
        parse("fps", f"{fps}/1")
        parse("input-csp", "i420")
        parse("bitrate", str(bitrate_kbps))
        parse("vbv-maxrate", str(bitrate_kbps))
        vbv_kbit = max(1, int(bitrate_kbps * 1.5 / fps))  # 1.5 frame-times
        parse("vbv-bufsize", str(vbv_kbit))
        parse("bframes", "0")
        parse("rc-lookahead", "0")
        parse("keyint", "-1")          # infinite GOP; IDR on demand
        parse("repeat-headers", "1")   # in-band VPS/SPS/PPS
        parse("annexb", "1")           # byte-stream
        parse("aud", "0")
        parse("info", "0")             # no SEI version blob per-stream
        parse("log-level", "none")
        self._param = param
        self._h = lib._open(param)
        if not self._h:
            raise RuntimeError("x265_encoder_open failed")
        self._pic = (ctypes.c_uint8 * _PIC_BYTES)()
        lib.x265_picture_init(param, self._pic)
        self._pts = 0
        self._force_idr = True
        self.frame_index = 0
        self.last_stats: FrameStats | None = None
        self._pending_bitrate: int | None = None

    # -- live retune (set_video_bitrate path) -------------------------

    def set_bitrate(self, bitrate_kbps: int) -> None:
        self._pending_bitrate = int(bitrate_kbps)

    def set_qp(self, qp: int) -> None:  # CBR owns the quantizer
        pass

    def force_keyframe(self) -> None:
        self._force_idr = True

    def _apply_bitrate(self) -> None:
        """x265_encoder_reconfig returns 0 for rate-control params but
        silently ignores them (verified empirically on build 199), so a
        bitrate retune re-opens the encoder — a few ms — and the next
        frame is an IDR, which the GCC controller's retune cadence
        absorbs (the reference caps retunes to one per second,
        gstwebrtc_app.py set_video_bitrate)."""
        kbps = self._pending_bitrate
        self._pending_bitrate = None
        lib = self._lib
        for k, v in (("bitrate", str(kbps)), ("vbv-maxrate", str(kbps)),
                     ("vbv-bufsize", str(max(1, int(kbps * 1.5 / self.fps))))):
            rc = lib.x265_param_parse(self._param, k.encode(), v.encode())
            if rc != 0:
                # a rejected value would re-open with partially stale rate
                # params — keep the running encoder instead
                logger.warning(
                    "x265_param_parse(%s=%s) rc=%d during retune; keeping "
                    "old encoder", k, v, rc)
                return
        new_h = lib._open(self._param)
        if not new_h:
            logger.warning("x265 re-open for bitrate %s failed; keeping old", kbps)
            return
        lib.x265_encoder_close(ctypes.c_void_p(self._h))
        self._h = new_h
        self._force_idr = True

    # -- encode -------------------------------------------------------

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        t0 = time.perf_counter()
        if self._pending_bitrate is not None:
            self._apply_bitrate()
        y, u, v = _bgrx_to_i420_np(np.asarray(frame))
        # keep the plane buffers alive through the encode call
        self._bufs = [np.ascontiguousarray(p) for p in (y, u, v)]
        for j, b in enumerate(self._bufs):
            _struct.pack_into("<Q", self._pic, _OFF_PLANES + j * 8, b.ctypes.data)
            _struct.pack_into("<i", self._pic, _OFF_STRIDES + j * 4, b.shape[1])
        _struct.pack_into("<q", self._pic, _OFF_PTS, self._pts)
        _struct.pack_into("<i", self._pic, _OFF_SLICETYPE,
                          _TYPE_IDR if self._force_idr else _TYPE_AUTO)
        self._pts += 1
        t1 = time.perf_counter()
        nal_ptr = ctypes.c_void_p()
        n_nal = ctypes.c_uint32()
        rc = self._lib.x265_encoder_encode(
            ctypes.c_void_p(self._h), ctypes.byref(nal_ptr),
            ctypes.byref(n_nal), self._pic, None)
        if rc < 0:
            raise RuntimeError("x265_encoder_encode failed")
        au = b""
        idr = False
        for k in range(n_nal.value):
            base = nal_ptr.value + _NAL_STRIDE * k
            typ, sz = _struct.unpack("<II", ctypes.string_at(base, 8))
            payload = _struct.unpack(
                "<Q", ctypes.string_at(base + _NAL_PAYLOAD_PTR_OFF, 8))[0]
            au += ctypes.string_at(payload, sz)
            if 16 <= typ <= 21:  # BLA/IDR/CRA IRAP classes
                idr = True
        self._force_idr = False if idr else self._force_idr
        self.last_stats = FrameStats(
            frame_index=self.frame_index, idr=bool(idr), qp=self.qp,
            bytes=len(au), device_ms=(time.perf_counter() - t1) * 1e3,
            pack_ms=(t1 - t0) * 1e3, skipped_mbs=0,
        )
        self.frame_index += 1
        return au

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.x265_encoder_close(ctypes.c_void_p(self._h))
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
