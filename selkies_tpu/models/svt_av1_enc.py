"""ctypes wrapper for SVT-AV1: the real ``svtav1enc`` encoder row.

The reference's svtav1enc GStreamer element (gstwebrtc_app.py:724-739)
wraps this same library; binding it directly upgrades the row from an
alias (libaom) to the genuine encoder with the reference's realtime
tuning: ``preset 10``, ``rc=2`` (CBR), ``lookahead=0``,
``pred-struct=1`` (low delay), infinite GOP with on-demand keyframes,
``lp`` capped at 24 threads.

ABI notes (built against libSvtAv1Enc.so.1, v1.4.1, Debian):

* configuration goes through ``svt_av1_enc_parse_parameter`` — the
  string-keyed API the reference's ``parameters-string`` property uses —
  so no ``EbSvtAv1EncConfiguration`` struct offsets are guessed; the
  config block is an oversized opaque buffer the library fills.
* the only structs touched are ``EbBufferHeaderType`` (output fields
  size/p_buffer/n_filled_len at 0/8/16; input fields pts@56, pic_type@68,
  flags@96) and ``EbSvtIOFormat`` (three plane pointers + strides).
  Their layout is VERIFIED at load time: ``svt_av1_enc_stream_header``
  must yield a sequence-header OBU (first byte 0x0a) with a sane
  n_filled_len through these offsets, else the row disables itself and
  the registry alias (libaom) serves ``svtav1enc`` instead.
* the low-delay pipeline emits frame N's packet only after frame N+1 is
  sent (one-frame latency). The first capture is therefore sent twice —
  one duplicated inter frame at the head of the stream — so every
  ``encode_frame`` call returns exactly one temporal unit, in order.
* ``svt_av1_enc_deinit`` DEADLOCKS unless the EOS protocol ran first
  (verified empirically: worker threads park on a futex waiting for the
  flush). Teardown therefore always sends >=1 picture (a dummy gray
  frame if none was encoded — a bare-EOS drain also never completes),
  sends the EOS-flagged empty buffer, polls packets until the EOS flag,
  and only then deinits; if the EOS packet fails to appear within the
  deadline the handle is deliberately LEAKED instead of deadlocking
  shutdown.

Live bitrate retune re-opens the encoder (next frame is a keyframe),
like the x265 row: SVT 1.4 has no public mid-stream rate-change API.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct as _struct
import time
from ctypes import POINTER, byref, c_char_p, c_uint8, c_void_p

import numpy as np

from selkies_tpu.models.libvpx_enc import _bgrx_to_i420_np
from selkies_tpu.models.stats import FrameStats

logger = logging.getLogger("models.svt_av1")

_CFG_BYTES = 16384   # >> sizeof(EbSvtAv1EncConfiguration); library fills it
_HDR_BYTES = 136     # sizeof(EbBufferHeaderType), validated in _load
_IO_BYTES = 64       # sizeof(EbSvtIOFormat)
_OFF_PBUF, _OFF_NFILLED = 8, 16
_OFF_PTS, _OFF_PICTYPE, _OFF_FLAGS = 56, 68, 96
_KEY_PICTURE, _INTER_PICTURE = 3, 0
_EOS_FLAG = 1
_YUV420, _EIGHT_BIT = 1, 8

_lib = None
_lib_tried = False


def _bind(lib) -> None:
    for name, args in (
        ("svt_av1_enc_init_handle", [POINTER(c_void_p), c_void_p, c_void_p]),
        ("svt_av1_enc_parse_parameter", [c_void_p, c_char_p, c_char_p]),
        ("svt_av1_enc_set_parameter", [c_void_p, c_void_p]),
        ("svt_av1_enc_init", [c_void_p]),
        ("svt_av1_enc_send_picture", [c_void_p, c_void_p]),
        ("svt_av1_enc_get_packet", [c_void_p, POINTER(c_void_p), c_uint8]),
        ("svt_av1_enc_release_out_buffer", [POINTER(c_void_p)]),
        ("svt_av1_enc_stream_header", [c_void_p, POINTER(c_void_p)]),
        ("svt_av1_enc_stream_header_release", [c_void_p]),
        ("svt_av1_enc_deinit", [c_void_p]),
        ("svt_av1_enc_deinit_handle", [c_void_p]),
    ):
        fn = getattr(lib, name)
        fn.argtypes = args
        fn.restype = ctypes.c_int


def _load():
    """Load libSvtAv1Enc and verify the buffer-header offsets against a
    live stream-header round trip (wrong offsets would corrupt memory —
    a failed check disables the row instead)."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    for name in ("libSvtAv1Enc.so.1", "libSvtAv1Enc.so"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        logger.info("libSvtAv1Enc not found; svtav1enc aliases to libaom")
        return None
    try:
        _bind(lib)
        handle = c_void_p()
        cfg = (c_uint8 * _CFG_BYTES)()
        if lib.svt_av1_enc_init_handle(byref(handle), None, cfg):
            raise RuntimeError("init_handle failed")
        for k, v in (("width", "64"), ("height", "64"),
                     ("rc", "2"), ("tbr", "500"), ("preset", "12"),
                     ("lookahead", "0"), ("pred-struct", "1"), ("lp", "1")):
            if lib.svt_av1_enc_parse_parameter(cfg, k.encode(), v.encode()):
                raise RuntimeError(f"parse {k} rejected")
        if lib.svt_av1_enc_set_parameter(handle, cfg):
            raise RuntimeError("set_parameter failed")
        if lib.svt_av1_enc_init(handle):
            raise RuntimeError("enc_init failed")
        hdr = c_void_p()
        if lib.svt_av1_enc_stream_header(handle, byref(hdr)) or not hdr:
            raise RuntimeError("stream_header failed")
        raw = ctypes.string_at(hdr, 24)
        pbuf, = _struct.unpack_from("<Q", raw, _OFF_PBUF)
        nfill, = _struct.unpack_from("<I", raw, _OFF_NFILLED)
        if not (pbuf and 0 < nfill < 256):
            raise RuntimeError(f"header offsets invalid (n_filled={nfill})")
        obu = ctypes.string_at(pbuf, nfill)
        if obu[0] != 0x0A:  # OBU_SEQUENCE_HEADER, has_size_field
            raise RuntimeError(f"not a sequence header: {obu[:4].hex()}")
        lib.svt_av1_enc_stream_header_release(hdr)
        # deinit without the frame+EOS flush protocol deadlocks (module
        # docstring); run the full teardown on the probe handle too
        _teardown_handle(lib, handle, 64, 64, frames_sent=0)
    except Exception as exc:
        logger.warning("libSvtAv1Enc ABI validation failed (%s); "
                       "svtav1enc aliases to libaom", exc)
        return None
    _lib = lib
    return lib


def _send_raw(lib, handle, width: int, height: int, planes, pts: int,
              pic_type: int = _INTER_PICTURE, flags: int = 0):
    """Build + send one EbBufferHeaderType; returns the ctypes objects
    that must stay alive until the packet is out."""
    hdr = (c_uint8 * _HDR_BYTES)()
    _struct.pack_into("<I", hdr, 0, _HDR_BYTES)
    io = None
    if planes is not None:
        y, u, v = planes
        io = (c_uint8 * _IO_BYTES)()
        _struct.pack_into("<QQQ", io, 0, y.ctypes.data, u.ctypes.data,
                          v.ctypes.data)
        _struct.pack_into("<IIIIIII", io, 24, width, width // 2,
                          width // 2, width, height, 0, 0)
        _struct.pack_into("<II", io, 52, _YUV420, _EIGHT_BIT)
        _struct.pack_into("<Q", hdr, _OFF_PBUF, ctypes.addressof(io))
        _struct.pack_into("<I", hdr, _OFF_NFILLED, width * height * 3 // 2)
    _struct.pack_into("<q", hdr, _OFF_PTS, pts)
    _struct.pack_into("<I", hdr, _OFF_PICTYPE, pic_type)
    _struct.pack_into("<I", hdr, _OFF_FLAGS, flags)
    rc = lib.svt_av1_enc_send_picture(handle, hdr)
    if rc:
        raise RuntimeError(f"svt_av1_enc_send_picture: {rc}")
    return hdr, io, planes


def _teardown_handle(lib, handle, width: int, height: int, *,
                     frames_sent: int, timeout_s: float = 5.0) -> None:
    """EOS-flush-then-deinit. A pipeline that never saw a picture must
    get a dummy one first (a bare-EOS drain never completes); if the EOS
    packet doesn't surface by the deadline the handle is leaked — a
    bounded, crash-free degradation instead of a futex deadlock."""
    try:
        keep = []
        if frames_sent == 0:
            gray = (np.full((height, width), 128, np.uint8),
                    np.full((height // 2, width // 2), 128, np.uint8),
                    np.full((height // 2, width // 2), 128, np.uint8))
            keep.append(_send_raw(lib, handle, width, height, gray, 0,
                                  _KEY_PICTURE))
        keep.append(_send_raw(lib, handle, width, height, None, 0,
                              flags=_EOS_FLAG))
        deadline = time.perf_counter() + timeout_s
        got_eos = False
        while time.perf_counter() < deadline:
            out = c_void_p()
            if lib.svt_av1_enc_get_packet(handle, byref(out), 0) == 0 and out:
                raw = ctypes.string_at(out, _OFF_FLAGS + 4)
                flags, = _struct.unpack_from("<I", raw, _OFF_FLAGS)
                lib.svt_av1_enc_release_out_buffer(byref(out))
                if flags & _EOS_FLAG:
                    got_eos = True
                    break
            else:
                time.sleep(0.001)
        if not got_eos:
            logger.warning("SVT EOS flush timed out; leaking the handle "
                           "to avoid a deinit deadlock")
            return
        lib.svt_av1_enc_deinit(handle)
        lib.svt_av1_enc_deinit_handle(handle)
    except Exception as exc:
        logger.warning("SVT teardown failed (%s); handle leaked", exc)


def svt_av1_available() -> bool:
    return _load() is not None


class SvtAv1Encoder:
    """Realtime CBR SVT-AV1 (reference svtav1enc row parity)."""

    codec = "av1"

    def __init__(self, width: int, height: int, fps: int = 60,
                 bitrate_kbps: int = 2000, preset: int = 10):
        lib = _load()
        if lib is None:
            raise RuntimeError("libSvtAv1Enc unavailable")
        if width % 2 or height % 2:
            raise ValueError("4:2:0 requires even dimensions")
        self._lib = lib
        self.width, self.height, self.fps = width, height, fps
        self.preset = preset
        self.bitrate_kbps = int(bitrate_kbps)
        self._handle: c_void_p | None = None
        self._open()
        self.frame_index = 0
        self._pts = 0
        self._sent = 0
        self._force_idr = True
        self._primed = False
        self._pending_bitrate: int | None = None
        self.last_stats: FrameStats | None = None
        self.qp = 0
        # input buffers for frames whose packets haven't surfaced yet
        # (one-frame pipeline lag): freeing them at return would hand
        # SVT's worker threads freed memory if the copy is asynchronous
        from collections import deque

        self._inflight = deque(maxlen=4)

    def _open(self) -> None:
        lib = self._lib
        handle = c_void_p()
        self._cfg = (c_uint8 * _CFG_BYTES)()
        if lib.svt_av1_enc_init_handle(byref(handle), None, self._cfg):
            raise RuntimeError("svt_av1_enc_init_handle failed")
        lp = min(24, max(1, (os.cpu_count() or 4) - 1))
        # reference svtav1enc row (gstwebrtc_app.py:736-739): preset 10,
        # rc=2 CBR, lookahead 0, low-delay pred structure, VBV ≈ the
        # reference's buf-initial/optimal-sz milliseconds, infinite GOP
        params = (
            ("width", str(self.width)), ("height", str(self.height)),
            ("fps-num", str(self.fps * 1000)), ("fps-denom", "1000"),
            ("rc", "2"), ("tbr", str(self.bitrate_kbps)),
            ("preset", str(self.preset)), ("keyint", "-1"),
            ("lookahead", "0"), ("pred-struct", "1"),
            ("fast-decode", "1"), ("lp", str(lp)),
            ("buf-initial-sz", "100"), ("buf-optimal-sz", "120"),
            ("maxsection-pct", "250"),
        )
        for k, v in params:
            if lib.svt_av1_enc_parse_parameter(
                    self._cfg, k.encode(), v.encode()):
                raise RuntimeError(f"svt parse {k}={v} rejected")
        if lib.svt_av1_enc_set_parameter(handle, self._cfg):
            raise RuntimeError("svt_av1_enc_set_parameter failed")
        if lib.svt_av1_enc_init(handle):
            raise RuntimeError("svt_av1_enc_init failed")
        self._handle = handle

    # -- live retune ---------------------------------------------------

    def set_bitrate(self, bitrate_kbps: int) -> None:
        self._pending_bitrate = int(bitrate_kbps)

    def set_qp(self, qp: int) -> None:  # CBR owns the quantizer
        pass

    def force_keyframe(self) -> None:
        self._force_idr = True

    def _reopen(self) -> None:
        """Bitrate retune AND forced mid-stream keyframes re-open the
        encoder (a few ms): SVT 1.4 has no public rate-change API, and
        per-picture KEY forcing is RA-CRF/CQP-only ('Force key frame is
        only supported with RA CRF/CQP mode') — unavailable in the
        low-delay CBR mode this row runs. A fresh stream starts with a
        keyframe, which is exactly what PLI recovery needs; the GCC
        retune cadence absorbs the cost (same stance as the x265 row)."""
        self.bitrate_kbps = self._pending_bitrate or self.bitrate_kbps
        self._pending_bitrate = None
        self._teardown()
        self._open()
        self._pts = 0
        self._sent = 0
        self._force_idr = True
        self._primed = False

    # -- encode --------------------------------------------------------

    def _send(self, planes, key: bool):
        out = _send_raw(self._lib, self._handle, self.width, self.height,
                        planes, self._pts,
                        _KEY_PICTURE if key else _INTER_PICTURE)
        self._pts += 1
        self._sent += 1
        return out  # keep alive until the packet is out

    def _poll_packet(self, timeout_s: float = 4.0):
        """-> (temporal unit bytes, is_keyframe) or None on timeout.
        is_keyframe comes from the OUTPUT header's pic_type — ground
        truth for the AU actually returned (the pipeline lags the input
        by one frame, so the caller's own flags would be off by one)."""
        lib = self._lib
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            out = c_void_p()
            if lib.svt_av1_enc_get_packet(self._handle, byref(out), 0) == 0 \
                    and out:
                raw = ctypes.string_at(out, _OFF_PICTYPE + 4)
                pbuf, = _struct.unpack_from("<Q", raw, _OFF_PBUF)
                nfill, = _struct.unpack_from("<I", raw, _OFF_NFILLED)
                ptype, = _struct.unpack_from("<I", raw, _OFF_PICTYPE)
                data = ctypes.string_at(pbuf, nfill)
                lib.svt_av1_enc_release_out_buffer(byref(out))
                if self._inflight:
                    self._inflight.popleft()  # that frame's input is consumed
                return data, ptype in (_KEY_PICTURE, 5)  # KEY / FW_KEY
            time.sleep(0.0005)
        return None

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        t0 = time.perf_counter()
        if self._pending_bitrate is not None:
            self._reopen()
        elif self._force_idr and self._primed:
            # mid-stream keyframe (PLI recovery): restart the stream —
            # see _reopen for why per-picture forcing can't work here
            self._reopen()
        y, u, v = _bgrx_to_i420_np(np.asarray(frame))
        planes = tuple(np.ascontiguousarray(p) for p in (y, u, v))
        key = self._force_idr
        self._force_idr = False
        self._inflight.append(self._send(planes, key=key))
        if not self._primed:
            # the low-delay pipeline emits frame N only once frame N+1
            # is in: duplicate the first capture so output is 1:1 from
            # the start (one extra inter frame of the same picture)
            self._inflight.append(self._send(planes, key=False))
            self._primed = True
        got = self._poll_packet()
        if got is None:
            raise RuntimeError("svt_av1_enc_get_packet timed out")
        au, idr = got
        dt = (time.perf_counter() - t0) * 1e3
        self.last_stats = FrameStats(
            frame_index=self.frame_index, idr=idr, qp=self.qp,
            bytes=len(au), device_ms=dt, pack_ms=0.0)
        self.frame_index += 1
        return au

    # -- teardown ------------------------------------------------------

    def _teardown(self) -> None:
        if self._handle is not None:
            _teardown_handle(self._lib, self._handle, self.width,
                             self.height, frames_sent=self._sent)
            self._handle = None

    def close(self) -> None:
        self._teardown()

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
