"""Host-side reference mirror of the device sparse-P downlink packers.

`build_p_sparse_wire` produces byte-identical buffers to
encoder_core.pack_p_sparse_var / pack_p_sparse_packed from a host
PFrameCoeffs — the input generator for the sparse-native equivalence
suite (tests/test_sparse_native_pack.py) and for tools/profile_pack.py,
which must exercise the completion path at arbitrary densities and
geometries without a device (or the relay tunnel) in the loop. The
mirror is validated against the device packers' unpack contract by the
round-trip tests; it is NOT a production path.

`synth_pfc` generates random-but-consistent P frames: skip MBs carry
zero residual and the 8.4.1.1-derived MV (the invariants encode_frame_p
guarantees), so a wire built from one round-trips exactly.
"""

from __future__ import annotations

import numpy as np

from selkies_tpu.models.h264.native import derive_skip_mvs_fast
from selkies_tpu.models.h264.numpy_ref import PFrameCoeffs

__all__ = ["build_p_sparse_wire", "synth_pfc"]


def _bitpack32(bits: np.ndarray) -> np.ndarray:
    """(M,) bool -> (ceil(M/32),) int32, zero-padded (encoder_core._bitpack32)."""
    pad = (-len(bits)) % 32
    b = np.concatenate([bits.astype(np.int64), np.zeros(pad, np.int64)])
    words = (b.reshape(-1, 32) << np.arange(32, dtype=np.int64)).sum(-1)
    return (words & 0xFFFFFFFF).astype(np.uint32).view(np.int32)


def _p_rows(pfc: PFrameCoeffs):
    """PFrameCoeffs -> (rows (M*26, 16) int16, per-MB flags, mv/info words)
    in the P_ENTRIES row layout (encoder_core._p_components)."""
    mbh, mbw = pfc.skip.shape
    m = mbh * mbw
    rows = np.zeros((m, 26, 16), np.int16)
    rows[:, :16] = np.asarray(pfc.luma_ac).reshape(m, 16, 16)
    rows[:, 16:24] = np.asarray(pfc.chroma_ac).reshape(m, 8, 16)
    rows[:, 24:26, :4] = np.asarray(pfc.chroma_dc).reshape(m, 2, 4)
    flat = rows.reshape(m * 26, 16)
    fl = (flat != 0).any(-1)
    mbinfo = (
        (fl.reshape(m, 26).astype(np.int64) << np.arange(26, dtype=np.int64))
        .sum(-1).astype(np.int32)
    )
    mvs = np.asarray(pfc.mvs, np.int64)
    mv_words = ((mvs[..., 0] & 0xFFFF) | ((mvs[..., 1] << 16) & 0xFFFFFFFF))
    mv_words = mv_words.reshape(-1).astype(np.uint32).view(np.int32)
    return flat, fl, mv_words, mbinfo


def build_p_sparse_wire(pfc: PFrameCoeffs, nscap: int, cap_rows: int,
                        packed: bool = False, density_pct: int = 75):
    """-> (fused int16, dense_header int32, buf (M*26, 16) int16), the
    exact triple the device steps downlink (fused layout per
    pack_p_sparse_var, or pack_p_sparse_packed when `packed`)."""
    mbh, mbw = pfc.skip.shape
    m = mbh * mbw
    sw = (m + 31) // 32
    flat, fl, mv_words, mbinfo = _p_rows(pfc)
    n = int(fl.sum())
    buf = np.zeros((m * 26, 16), np.int16)
    buf[:n] = flat[fl]
    skip_flat = np.asarray(pfc.skip, bool).reshape(-1)
    skip_words = _bitpack32(skip_flat)
    ns = int((~skip_flat).sum())
    mv_c = mv_words[~skip_flat][:nscap]
    info_c = mbinfo[~skip_flat][:nscap]
    pairs16 = np.stack([mv_c, info_c], -1).reshape(-1).view(np.int16)
    held = min(n, cap_rows)

    if packed:
        rows = buf[:cap_rows]  # clamps when the geometry holds fewer rows
        sig = rows != 0
        bitmap16 = (
            (sig.astype(np.int64) << np.arange(16, dtype=np.int64)).sum(-1)
            & 0xFFFF
        ).astype(np.uint16).view(np.int16)
        counts = sig.sum(-1)
        width = 4 * ((counts + 3) // 4)
        off = np.cumsum(width) - width
        nw = int(width.sum())
        vals16 = np.zeros(16 * len(rows) + 1, np.int16)
        rr, cc = np.nonzero(sig)
        if len(rr):
            rank = (np.cumsum(sig, axis=1) - 1)[rr, cc]
            vals16[off[rr] + rank] = rows[rr, cc]
        vals16 = vals16[: 16 * len(rows)]
        dense_flag = int((held + nw) * 100 > (16 * held) * density_pct)
        meta = np.array([n, mbh, mbw, ns, nw, dense_flag], np.int32)
        base = 12 + 2 * sw
        fused = np.zeros(base + 4 * nscap + cap_rows + 16 * cap_rows, np.int16)
        fused[:base] = np.concatenate([meta, skip_words]).view(np.int16)
        fused[base : base + len(pairs16)] = pairs16
        rows_off = base + 4 * min(ns, nscap)
        if dense_flag:
            fused[rows_off : rows_off + 16 * len(rows)] = rows.reshape(-1)
        else:
            fused[rows_off : rows_off + len(rows)] = bitmap16
            fused[rows_off + held : rows_off + held + len(vals16)] = vals16
    else:
        meta = np.array([n, mbh, mbw, ns], np.int32)
        base = 8 + 2 * sw
        fused = np.zeros(base + 4 * nscap + 16 * cap_rows, np.int16)
        fused[:base] = np.concatenate([meta, skip_words]).view(np.int16)
        fused[base : base + len(pairs16)] = pairs16
        rows_off = base + 4 * min(ns, nscap)
        rows = buf[:cap_rows].reshape(-1)  # clamps on tiny geometries
        fused[rows_off : rows_off + len(rows)] = rows

    dense = np.concatenate([
        np.array([n, mbh, mbw, 0], np.int32), mv_words, mbinfo, skip_words,
    ])
    return fused, dense, buf


def synth_pfc(rng: np.random.Generator, mbh: int, mbw: int, *,
              skip_frac: float = 0.9, row_density: float = 0.15,
              lane_density: float = 0.25, big_levels: bool = False,
              qp: int = 30) -> PFrameCoeffs:
    """Random P frame honouring the encoder invariants (skip MBs have
    zero residual and the derived skip MV). `row_density` is the chance
    a coded MB's row is live; `lane_density` the per-lane nonzero chance
    inside a live row; `big_levels` sprinkles escape-coded magnitudes."""
    m = mbh * mbw
    skip = rng.random((mbh, mbw)) < skip_frac
    coded = ~skip.reshape(-1)
    rowmask = (rng.random((m, 26)) < row_density) & coded[:, None]
    lanes = rng.random((m, 26, 16)) < lane_density
    hi = 2400 if big_levels else 30
    vals = rng.integers(-hi, hi + 1, (m, 26, 16))
    rows = np.where(rowmask[..., None] & lanes, vals, 0).astype(np.int16)
    rows[:, 24:26, 4:] = 0  # chroma DC rows carry 4 values only
    mvs = np.zeros((mbh, mbw, 2), np.int32)
    mvs.reshape(-1, 2)[coded] = rng.integers(-32, 33, (int(coded.sum()), 2))
    pfc = PFrameCoeffs(
        mvs=mvs,
        skip=skip,
        luma_ac=rows[:, :16].reshape(mbh, mbw, 4, 4, 4, 4).astype(np.int32),
        chroma_dc=rows[:, 24:26, :4].reshape(mbh, mbw, 2, 2, 2).astype(np.int32),
        chroma_ac=rows[:, 16:24].reshape(mbh, mbw, 2, 2, 2, 4, 4).astype(np.int32),
        qp=qp,
    )
    derive_skip_mvs_fast(pfc.mvs, pfc.skip)
    return pfc
