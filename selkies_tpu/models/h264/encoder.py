"""tpuh264enc — the TPU-native H.264 encoder element.

Replaces the reference's nvh264enc/vah264enc/x264enc/openh264enc rows of
the encoder matrix (gstwebrtc_app.py:260-367,475-508,609-665). The device
half (colorspace, prediction, transforms, quantization) is one jitted XLA
program per resolution (encoder_core.py); the host half is the C++ CAVLC
packer (native/cavlc_pack.cc). QP is a traced argument, so the GCC
congestion-control loop can retune bitrate every frame without
recompilation (reference: set_video_bitrate, gstwebrtc_app.py:1296).

Latency design: the device step returns int16 coefficient tensors (half
the PCIe traffic of int32); reconstruction planes stay on device for the
future P-frame path. Double-buffering (dispatch frame N+1 while N packs on
host) happens naturally because JAX dispatch is async — encode_frame
blocks only on the coefficient device→host copy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.models.stats import FrameStats as _FrameStats
from selkies_tpu.models.h264.bitstream import StreamParams, write_pps, write_sps
from selkies_tpu.models.h264.encoder_core import encode_frame_p_planes, encode_frame_planes
from selkies_tpu.models.h264.native import pack_slice_fast, pack_slice_p_fast
from selkies_tpu.models.h264.numpy_ref import FrameCoeffs, PFrameCoeffs
from selkies_tpu.ops.colorspace import bgrx_to_i420, rgb_to_i420

__all__ = ["TPUH264Encoder", "make_frame_step"]


def _convert_pad(frame, *, pad_h: int, pad_w: int, channels: int):
    """Packed frame -> padded I420 planes (device)."""
    if channels == 4:
        y, u, v = bgrx_to_i420(frame)
    else:
        y, u, v = rgb_to_i420(frame)
    h, w = y.shape
    if (pad_h, pad_w) != (h, w):
        y = jnp.pad(y, ((0, pad_h - h), (0, pad_w - w)), mode="edge")
        u = jnp.pad(u, ((0, (pad_h - h) // 2), (0, (pad_w - w) // 2)), mode="edge")
        v = jnp.pad(v, ((0, (pad_h - h) // 2), (0, (pad_w - w) // 2)), mode="edge")
    return y, u, v


def _narrow(out):
    """int32 coeff tensors -> int16 (halves the device->host copy)."""
    return {
        k: (out[k].astype(jnp.int16) if out[k].dtype == jnp.int32 else out[k])
        for k in out
    }


def _device_step(frame, qp, *, pad_h: int, pad_w: int, channels: int):
    """Full IDR device path: packed frame -> padded planes -> coeff tensors."""
    y, u, v = _convert_pad(frame, pad_h=pad_h, pad_w=pad_w, channels=channels)
    return _narrow(encode_frame_planes(y, u, v, qp))


def _device_step_p(frame, qp, ref_y, ref_u, ref_v, *, pad_h: int, pad_w: int, channels: int):
    """P-frame device path: convert, hierarchical motion search (±32)
    against the previous reconstruction (which never leaves the device),
    encode inter residuals."""
    y, u, v = _convert_pad(frame, pad_h=pad_h, pad_w=pad_w, channels=channels)
    return _narrow(encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp))


FrameStats = _FrameStats  # shared definition (models/stats.py)


class TPUH264Encoder:
    """Stateful per-stream encoder: frame in, Annex-B access unit out.

    `codec` identifies the bitstream for client decoder configuration
    (media.js maps it to a WebCodecs codec string).

    GOP policy mirrors the reference default (keyframe_distance=-1,
    __main__.py:473-475): one IDR, then P frames forever; new IDRs only on
    force_keyframe() (client PLI / stream restart) or an explicit
    keyframe_interval. The previous frame's reconstruction stays on the
    TPU between frames — only quantized coefficients cross PCIe.
    """

    codec = "h264"

    def __init__(
        self,
        width: int,
        height: int,
        qp: int = 28,
        fps: int = 60,
        channels: int = 4,
        keyframe_interval: int = 0,
    ):
        self.width = width
        self.height = height
        self.fps = fps
        self.qp = int(qp)
        self.channels = channels
        self.keyframe_interval = int(keyframe_interval)  # 0 = infinite GOP
        self.params = StreamParams(width=width, height=height, qp=self.qp, fps=fps)
        self._headers = write_sps(self.params) + write_pps(self.params)
        self._pad_h = (height + 15) // 16 * 16
        self._pad_w = (width + 15) // 16 * 16
        self._step = jax.jit(
            lambda frame, qp: _device_step(
                frame, qp, pad_h=self._pad_h, pad_w=self._pad_w, channels=channels
            )
        )
        self._step_p = jax.jit(
            lambda frame, qp, ry, ru, rv: _device_step_p(
                frame, qp, ry, ru, rv,
                pad_h=self._pad_h, pad_w=self._pad_w, channels=channels,
            ),
            donate_argnums=(2, 3, 4),
        )
        self._ref = None  # (recon_y, recon_u, recon_v) device arrays
        self.frame_index = 0
        self._frames_since_idr = 0
        self._idr_pic_id = 0
        self._force_idr = True
        self.last_stats: FrameStats | None = None

    # -- live retune API (parity: set_video_bitrate path ends here) --

    def set_qp(self, qp: int) -> None:
        if not 0 <= qp <= 51:
            raise ValueError(f"qp {qp} out of range")
        self.qp = int(qp)

    def force_keyframe(self) -> None:
        self._force_idr = True

    # -- encoding --

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        """Encode one packed frame ((H, W, 4) BGRx or (H, W, 3) RGB uint8).

        Returns a complete Annex-B access unit (SPS/PPS prepended on IDR).
        """
        if qp is not None:
            self.set_qp(qp)
        idr = (
            self._force_idr
            or self.frame_index == 0
            or self._ref is None
            or (self.keyframe_interval > 0 and self._frames_since_idr >= self.keyframe_interval)
        )
        t0 = time.perf_counter()
        skipped = 0
        if idr:
            out = self._step(frame, np.int32(self.qp))
            fc = FrameCoeffs(
                luma_mode=np.asarray(out["luma_mode"]),
                chroma_mode=np.asarray(out["chroma_mode"]),
                luma_dc=np.asarray(out["luma_dc"]),
                luma_ac=np.asarray(out["luma_ac"]),
                chroma_dc=np.asarray(out["chroma_dc"]),
                chroma_ac=np.asarray(out["chroma_ac"]),
                qp=self.qp,
            )
            self._frames_since_idr = 0
            t1 = time.perf_counter()
            # frame_num counts from the last IDR (7.4.3: gaps are disallowed
            # by our SPS, so it must be PrevRefFrameNum+1 mod MaxFrameNum).
            slice_nal = pack_slice_fast(
                fc,
                self.params,
                frame_num=0,
                idr=True,
                idr_pic_id=self._idr_pic_id,
            )
        else:
            try:
                out = self._step_p(frame, np.int32(self.qp), *self._ref)
            except Exception:
                # _step_p donated the reference planes; a device error mid-step
                # leaves them deleted. Drop the ref so the next frame
                # self-heals as an IDR instead of failing forever.
                self._ref = None
                raise
            # reassign the reference IMMEDIATELY: _step_p donated the old
            # buffers, so a packing exception below must not leave self._ref
            # pointing at deleted arrays (every later frame would fail).
            self._ref = (out["recon_y"], out["recon_u"], out["recon_v"])
            skip = np.asarray(out["skip"])
            skipped = int(skip.sum())
            pfc = PFrameCoeffs(
                mvs=np.asarray(out["mvs"]),
                skip=skip,
                luma_ac=np.asarray(out["luma_ac"]),
                chroma_dc=np.asarray(out["chroma_dc"]),
                chroma_ac=np.asarray(out["chroma_ac"]),
                qp=self.qp,
            )
            t1 = time.perf_counter()
            slice_nal = pack_slice_p_fast(
                pfc, self.params, frame_num=self._frames_since_idr % 256
            )
        if idr:
            # the reconstruction never leaves the device: it is the P-frame
            # reference (donated into the next P step)
            self._ref = (out["recon_y"], out["recon_u"], out["recon_v"])
        t2 = time.perf_counter()
        au = (self._headers + slice_nal) if idr else slice_nal
        if idr:
            self._idr_pic_id = (self._idr_pic_id + 1) % 2
        self.last_stats = FrameStats(
            frame_index=self.frame_index,
            idr=idr,
            qp=self.qp,
            bytes=len(au),
            device_ms=(t1 - t0) * 1e3,
            pack_ms=(t2 - t1) * 1e3,
            skipped_mbs=skipped,
        )
        self.frame_index += 1
        self._frames_since_idr += 1
        if idr:
            # Only clear when consumed: a force_keyframe() landing from the
            # event loop mid-encode must still take effect next frame.
            self._force_idr = False
        return au

    def recon_planes(self, frame: np.ndarray):
        """Debug helper: (recon_y, recon_u, recon_v) for a frame."""
        out = self._step(frame, np.int32(self.qp))
        return (
            np.asarray(out["recon_y"]),
            np.asarray(out["recon_u"]),
            np.asarray(out["recon_v"]),
        )


def make_frame_step(width: int, height: int, qp: int = 28):
    """(jittable fn, example args) for the driver's compile check: the
    steady-state P-frame step (ME + MC + transform), the flagship path."""
    pad_h = (height + 15) // 16 * 16
    pad_w = (width + 15) // 16 * 16

    def fn(frame, qp_arr, ry, ru, rv):
        return _device_step_p(
            frame, qp_arr, ry, ru, rv, pad_h=pad_h, pad_w=pad_w, channels=4
        )

    rng = np.random.default_rng(0)
    frame = rng.integers(0, 256, size=(height, width, 4), dtype=np.uint8)
    ry = rng.integers(0, 256, size=(pad_h, pad_w), dtype=np.uint8)
    ru = rng.integers(0, 256, size=(pad_h // 2, pad_w // 2), dtype=np.uint8)
    rv = rng.integers(0, 256, size=(pad_h // 2, pad_w // 2), dtype=np.uint8)
    return fn, (frame, np.int32(qp), ry, ru, rv)
