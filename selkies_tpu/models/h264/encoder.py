"""tpuh264enc — the TPU-native H.264 encoder element.

Replaces the reference's nvh264enc/vah264enc/x264enc/openh264enc rows of
the encoder matrix (gstwebrtc_app.py:260-367,475-508,609-665). The device
half (colorspace, prediction, transforms, quantization) is one jitted XLA
program per resolution (encoder_core.py); the host half is the C++ CAVLC
packer (native/cavlc_pack.cc). QP is a traced argument, so the GCC
congestion-control loop can retune bitrate every frame without
recompilation (reference: set_video_bitrate, gstwebrtc_app.py:1296).

Latency design: the device step returns int16 coefficient tensors (half
the PCIe traffic of int32); reconstruction planes stay on device for the
future P-frame path. Double-buffering (dispatch frame N+1 while N packs on
host) happens naturally because JAX dispatch is async — encode_frame
blocks only on the coefficient device→host copy.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.models.h264.numpy_ref import PFrameCoeffs

from selkies_tpu.models.frameprep import FramePrep, delta_buckets_for, tile_width_for
from selkies_tpu.monitoring.telemetry import telemetry
from selkies_tpu.resilience.faultinject import get_injector
from selkies_tpu.monitoring.tracing import tracer
from selkies_tpu.models.stats import FrameStats as _FrameStats
from selkies_tpu.models.h264.bitstream import StreamParams, write_pps, write_sps
from selkies_tpu.models.h264.compact import (
    i_header_words,
    p_header_words,
    p_sparse_entropy_words,
    p_sparse_packed_words,
    p_sparse_var_words,
    split_prefix,
    unpack_i_compact,
    unpack_p_compact,
)
from selkies_tpu.models.h264.cabac import pack_slice_cabac, pack_slice_p_cabac
from selkies_tpu.models.h264.device_cabac import (
    assemble_p_cabac_nal,
    pack_p_slice_tokens_active,
)
from selkies_tpu.models.h264.device_cavlc import (
    WORD_CAP_DEFAULT as BITS_WORD_CAP,
    assemble_p_nal,
    entropy_coder_default,
    pack_p_slice_bits_active,
    resolve_entropy,
)
from selkies_tpu.models.h264.encoder_core import (
    _bitpack32,
    encode_frame_p_planes,
    encode_frame_planes,
    fuse_downlink,
    pack_i_compact,
    pack_p_compact,
    pack_p_sparse_entropy,
    pack_p_sparse_packed,
    pack_p_sparse_var,
    scatter_tiles,
)
from selkies_tpu.models.h264.sparse_complete import (
    complete_sparse_slice,
    fetch_rest,
)
from selkies_tpu.models.stats import LinkByteCounter
from selkies_tpu.models.tilecache import TileCache
from selkies_tpu.models.h264.native import (
    pack_slice_fast,
    pack_slice_p_fast,
)
from selkies_tpu.ops.colorspace import bgrx_to_i420, rgb_to_i420

__all__ = ["TPUH264Encoder", "make_frame_step"]


def _convert_pad(frame, *, pad_h: int, pad_w: int, channels: int):
    """Packed frame -> padded I420 planes (device)."""
    if channels == 4:
        y, u, v = bgrx_to_i420(frame)
    else:
        y, u, v = rgb_to_i420(frame)
    h, w = y.shape
    if (pad_h, pad_w) != (h, w):
        y = jnp.pad(y, ((0, pad_h - h), (0, pad_w - w)), mode="edge")
        u = jnp.pad(u, ((0, (pad_h - h) // 2), (0, (pad_w - w) // 2)), mode="edge")
        v = jnp.pad(v, ((0, (pad_h - h) // 2), (0, (pad_w - w) // 2)), mode="edge")
    return y, u, v


# Data rows carried in the single-fetch prefix buffer. The relay prices
# transfers per op (~200 ms, tools/profile_rpc.py), so typical frames must
# complete in ONE fetch; frames with more nonzero rows pay a second fetch.
CAP_ROWS = 4096
# Delta frames use the variable-packed sparse downlink
# (encoder_core.pack_p_sparse_var): live fetch bytes track frame activity
# (~11 KB for a typing update, ~60-130 KB through the decay tail that
# follows a full-frame change — measured on the bench trace). NSCAP and
# the row cap only bound the device buffer; they are sized so the decay
# tail (ns up to ~3k, n up to ~3.5k) never triggers the fallback fetches.
CAP_ROWS_DELTA = 4096
NSCAP = 4096
# Device-entropy downlink (full P frames): the slice-data BITSTREAM is
# produced on device (device_cavlc.py) and fetched instead of multi-MB
# coefficient tensors. The prefix fetch carries [nbits, trailing, nskip]
# + the first BITS_PREFIX_WORDS words; bigger frames spill one extra
# fetch; frames overflowing the word cap fall back to the dense path.
BITS_PREFIX_WORDS = 1 << 16  # 256 KB: covers typical full-P slices in ONE fetch
# Delta frames run the same device entropy coder activity-proportionally
# (pack_p_sparse_entropy); the live-MB threshold and the rest of the
# knob resolution live in device_cavlc.resolve_entropy, shared with the
# banded encoder.


def _device_step(frame, qp, *, pad_h: int, pad_w: int, channels: int):
    """Full IDR device path: packed frame -> padded planes -> compacted
    coefficient downlink (header, nonzero rows) + device-resident recon."""
    y, u, v = _convert_pad(frame, pad_h=pad_h, pad_w=pad_w, channels=channels)
    return _i_planes_step(y, u, v, qp)


def _i_planes_step(y, u, v, qp):
    out = encode_frame_planes(y, u, v, qp)
    header, buf = pack_i_compact(out)
    prefix = fuse_downlink(header, buf, CAP_ROWS)
    return prefix, buf, out["recon_y"], out["recon_u"], out["recon_v"]


def _device_step_p(frame, qp, ref_y, ref_u, ref_v, *, pad_h: int, pad_w: int, channels: int):
    """P-frame device path: convert, hierarchical motion search (±32)
    against the previous reconstruction (which never leaves the device),
    encode inter residuals, compact the downlink."""
    y, u, v = _convert_pad(frame, pad_h=pad_h, pad_w=pad_w, channels=channels)
    return _p_planes_step(y, u, v, qp, ref_y, ref_u, ref_v)


def _p_planes_step(y, u, v, qp, ref_y, ref_u, ref_v):
    out = encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp)
    header, buf = pack_p_compact(out)
    prefix = fuse_downlink(header, buf, CAP_ROWS)
    return prefix, buf, out["recon_y"], out["recon_u"], out["recon_v"]


def _p_bits_step(y, u, v, qp, ref_y, ref_u, ref_v):
    """Full-P with ON-DEVICE entropy coding: what crosses the link is the
    slice bitstream itself. The activity-proportional coder picks its
    bucket per frame (a moderately-busy scene cut pays for its live MBs,
    not the grid). Dense header/buf ride along device-side only, as the
    overflow fallback (fetched on the rare nbits > cap frame)."""
    out = encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp)
    words, nbits, trailing, _ns = pack_p_slice_bits_active(out, BITS_WORD_CAP)
    nskip = out["skip"].sum().astype(jnp.int32)
    meta = jnp.stack([nbits, trailing, nskip]).astype(jnp.uint32)
    prefix = jnp.concatenate([meta, words[:BITS_PREFIX_WORDS]])
    header, buf = pack_p_compact(out)
    return prefix, words, header, buf, out["recon_y"], out["recon_u"], out["recon_v"]


# CABAC full-P token downlink: tokens are 16-bit IR slots (two per
# word), so the cap and prefix double relative to the CAVLC bit path to
# cover the same slice activity.
TOK_WORD_CAP = 1 << 18
TOK_PREFIX_WORDS = 1 << 17


def _p_toks_step(y, u, v, qp, ref_y, ref_u, ref_v):
    """Full-P with ON-DEVICE CABAC binarization (device_cabac.py): the
    downlink is the 16-bit token IR plus what the host interleave needs
    — the skip bitmap and the coded MBs' token counts — packed into one
    uint32 prefix [ntok, ns, nskip] ++ skip_words ++ count pairs ++
    token words. The sequential arithmetic engine stays on the host
    (native/cabac_pack.cc)."""
    out = encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp)
    words, ntok, counts, ns = pack_p_slice_tokens_active(out, TOK_WORD_CAP)
    skip = out["skip"].reshape(-1)
    nskip = skip.sum().astype(jnp.int32)
    skip_words = _bitpack32(skip)
    m = counts.shape[0]
    cnt16 = jnp.pad(counts.astype(jnp.int16), (0, m & 1))
    cnt_words = jax.lax.bitcast_convert_type(
        cnt16.reshape(-1, 2), jnp.int32).reshape(-1)
    meta = jnp.stack([ntok, ns, nskip])
    prefix = jnp.concatenate([
        meta.astype(jnp.uint32), skip_words.astype(jnp.uint32),
        cnt_words.astype(jnp.uint32), words[:TOK_PREFIX_WORDS]])
    header, buf = pack_p_compact(out)
    return prefix, words, header, buf, out["recon_y"], out["recon_u"], out["recon_v"]


# Full-frame uploads ride in Y_CHUNKS+2 concurrent device_puts: h2d
# transfers overlap ~2.5x across Python threads on the relay
# (tools/profile_upload_chunks.py: 3.1 MB in 175 ms vs 264 serial; more
# chunks lose to per-op overhead). The chunked steps re-join the planes
# on device and return them so they stay resident as the delta base.
Y_CHUNKS = 4


def _i_planes_step_chunked(y0, y1, y2, y3, u, v, qp):
    y = jnp.concatenate([y0, y1, y2, y3], 0)
    return (*_i_planes_step(y, u, v, qp), y, u, v)


def _p_bits_step_chunked(y0, y1, y2, y3, u, v, qp, ref_y, ref_u, ref_v):
    y = jnp.concatenate([y0, y1, y2, y3], 0)
    return (*_p_bits_step(y, u, v, qp, ref_y, ref_u, ref_v), y, u, v)


def _p_toks_step_chunked(y0, y1, y2, y3, u, v, qp, ref_y, ref_u, ref_v):
    y = jnp.concatenate([y0, y1, y2, y3], 0)
    return (*_p_toks_step(y, u, v, qp, ref_y, ref_u, ref_v), y, u, v)


def _p_planes_step_chunked(y0, y1, y2, y3, u, v, qp, ref_y, ref_u, ref_v):
    y = jnp.concatenate([y0, y1, y2, y3], 0)
    return (*_p_planes_step(y, u, v, qp, ref_y, ref_u, ref_v), y, u, v)


# Delta steps: only the dirty bands cross the link; the full frame is
# assembled on device by scattering them into the resident source planes
# (donated -> in-place). Each returns the updated source planes so the
# encoder can keep them resident for the next frame's delta. The bands +
# indices ride in ONE packed uint8 buffer: the relay prices host<->device
# traffic per operation (tools/profile_rpc.py), so one upload beats four.


def _unpack_delta(packed, w):
    """packed: [idx int32 LE bytes (k,4)] ++ yb ++ ub ++ vb, k inferred.
    w is the TILE width in luma columns (== plane width for full bands)."""
    per_band = 4 + 24 * w  # 4 idx bytes + 16*w luma + 2*(8*(w//2)) chroma
    k = packed.shape[0] // per_band
    idx = jax.lax.bitcast_convert_type(packed[: 4 * k].reshape(k, 4), jnp.int32)
    off = 4 * k
    yb = jax.lax.dynamic_slice_in_dim(packed, off, k * 16 * w).reshape(k, 16, w)
    off += k * 16 * w
    ub = jax.lax.dynamic_slice_in_dim(packed, off, k * 8 * (w // 2)).reshape(k, 8, w // 2)
    off += k * 8 * (w // 2)
    vb = jax.lax.dynamic_slice_in_dim(packed, off, k * 8 * (w // 2)).reshape(k, 8, w // 2)
    return yb, ub, vb, idx


def _pack_sparse_p(out, nscap, cap, density, entropy=None):
    """Delta-P downlink packer: density=None keeps the 16-lane row
    layout (pack_p_sparse_var); an int percent enables the bit-packed
    rows with that dense-fallback cap (pack_p_sparse_packed). entropy
    (bits_words, min_mbs, buckets) wraps either layout in the
    activity-proportional device-entropy decision (pack_p_sparse_
    entropy): busy frames then ship final slice bits (CAVLC) or the
    binarized token IR (CABAC), quiet frames the sparse rows — same
    fused-buffer fetch either way."""
    if entropy is not None:
        bits_words, min_mbs, buckets, coder = entropy
        return pack_p_sparse_entropy(out, nscap, cap, density,
                                     bits_words, min_mbs, buckets,
                                     entropy_coder=coder)
    if density is None:
        return pack_p_sparse_var(out, nscap, cap)
    return pack_p_sparse_packed(out, nscap, cap, density)


def _p_scatter_step(packed, qp, sy, su, sv, ref_y, ref_u, ref_v, *, nscap, cap, tile_w,
                    density=None, entropy=None):
    yb, ub, vb, idx = _unpack_delta(packed, tile_w)
    y, u, v = scatter_tiles(sy, su, sv, yb, ub, vb, idx, tile_w)
    out = encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp)
    prefix, dense, buf = _pack_sparse_p(out, nscap, cap, density, entropy)
    return prefix, dense, buf, out["recon_y"], out["recon_u"], out["recon_v"], y, u, v


def _i_scatter_step(packed, qp, sy, su, sv, *, tile_w):
    yb, ub, vb, idx = _unpack_delta(packed, tile_w)
    y, u, v = scatter_tiles(sy, su, sv, yb, ub, vb, idx, tile_w)
    out = encode_frame_planes(y, u, v, qp)
    header, buf = pack_i_compact(out)
    prefix = fuse_downlink(header, buf, CAP_ROWS)
    return prefix, buf, out["recon_y"], out["recon_u"], out["recon_v"], y, u, v


def _p_scatter_multi_step(packed_a, packed_b, qps, sy, su, sv, ref_y, ref_u, ref_v,
                          *, nscap, cap, tile_w, density=None, entropy=None):
    """K delta frames in ONE device round trip.

    packed_a/packed_b: two (K/2, F) uint8 halves of the K frames' tile
    payloads (same bucket), uploaded CONCURRENTLY (h2d overlaps ~2.5x
    across threads on the relay) and re-joined here; qps: (K,) int32
    per-frame QP. The scan chains recon: frame k's motion estimation
    references frame k-1's reconstruction, exactly as K single steps
    would. One execute + one prefix fetch instead of 2K relay
    operations — the relay prices per op, so this is the difference
    between ~8 and ~30+ fps at 1080p (tools/profile_rpc.py)."""
    packed = jnp.concatenate([packed_a, packed_b], 0)

    def body(carry, xs):
        pk, qp = xs
        cy, cu, cv, ry, ru, rv = carry
        yb, ub, vb, idx = _unpack_delta(pk, tile_w)
        y, u, v = scatter_tiles(cy, cu, cv, yb, ub, vb, idx, tile_w)
        out = encode_frame_p_planes(y, u, v, ry, ru, rv, qp)
        prefix, dense, buf = _pack_sparse_p(out, nscap, cap, density, entropy)
        return (
            (y, u, v, out["recon_y"], out["recon_u"], out["recon_v"]),
            (prefix, dense, buf),
        )

    carry, (prefixes, denses, bufs) = jax.lax.scan(
        body, (sy, su, sv, ref_y, ref_u, ref_v), (packed, qps)
    )
    y, u, v, ry, ru, rv = carry
    return prefixes, denses, bufs, ry, ru, rv, y, u, v


def _i_resident_step(qp, sy, su, sv):
    # IDR over unchanged content (e.g. PLI-forced keyframe on an idle
    # desktop): zero upload, encode straight from the resident planes
    out = encode_frame_planes(sy, su, sv, qp)
    header, buf = pack_i_compact(out)
    prefix = fuse_downlink(header, buf, CAP_ROWS)
    return prefix, buf, out["recon_y"], out["recon_u"], out["recon_v"]


# Tile-cache delta steps (the CopyRect analogue, models/tilecache.py):
# the packed upload carries [upload idx (bucket int32, -1 pads)] ++
# [pool slot each upload is kept in (bucket int32, scratch = last pool
# row)] ++ [(src_slot, dst_idx) copy pairs (cbucket x 2 int32, src=-1
# pads)] ++ the uploaded pixel tiles. Copy pairs remap tiles already
# resident in the device slot pool into their new positions WITHOUT any
# pixel upload — a scrolled or window-moved tile costs 8 bytes instead
# of ~3 KB. bucket/cbucket are static (one executable per combination,
# same ladder discipline as the delta buckets); padding entries write a
# tile's own current content back (reading it first), which keeps every
# lane shape static without a device-side branch.


def _unpack_delta2(packed, w, bucket, cbucket):
    k = bucket
    up_idx = jax.lax.bitcast_convert_type(packed[: 4 * k].reshape(k, 4), jnp.int32)
    off = 4 * k
    pool_dst = jax.lax.bitcast_convert_type(
        packed[off : off + 4 * k].reshape(k, 4), jnp.int32
    )
    off += 4 * k
    pairs = jax.lax.bitcast_convert_type(
        packed[off : off + 8 * cbucket].reshape(2 * cbucket, 4), jnp.int32
    ).reshape(cbucket, 2)
    off += 8 * cbucket
    yb = packed[off : off + k * 16 * w].reshape(k, 16, w)
    off += k * 16 * w
    ub = packed[off : off + k * 8 * (w // 2)].reshape(k, 8, w // 2)
    off += k * 8 * (w // 2)
    vb = packed[off : off + k * 8 * (w // 2)].reshape(k, 8, w // 2)
    return up_idx, pool_dst, pairs, yb, ub, vb


def _apply_tiles2(sy, su, sv, py, pu, pv, packed, *, tile_w, bucket, cbucket):
    """Copy remaps (pool -> planes), then pixel uploads (-> planes AND
    their pool slots). Copies run first so a same-step upload can land
    on a position a remap also wrote (the upload is the newer content);
    the host never emits a remap from a slot inserted in the same call."""
    up_idx, pool_dst, pairs, yb, ub, vb = _unpack_delta2(packed, tile_w, bucket, cbucket)
    ctw = tile_w // 2

    def copy_body(i, planes):
        y, u, v = planes
        valid = pairs[i, 0] >= 0
        slot = jnp.maximum(pairs[i, 0], 0)
        d = jnp.maximum(pairs[i, 1], 0)
        band, tile = d // 1024, d % 1024
        ty = jax.lax.dynamic_slice(py, (slot, 0, 0), (1, 16, tile_w))[0]
        tu = jax.lax.dynamic_slice(pu, (slot, 0, 0), (1, 8, ctw))[0]
        tv = jax.lax.dynamic_slice(pv, (slot, 0, 0), (1, 8, ctw))[0]
        cy = jax.lax.dynamic_slice(y, (band * 16, tile * tile_w), (16, tile_w))
        cu = jax.lax.dynamic_slice(u, (band * 8, tile * ctw), (8, ctw))
        cv = jax.lax.dynamic_slice(v, (band * 8, tile * ctw), (8, ctw))
        y = jax.lax.dynamic_update_slice(
            y, jnp.where(valid, ty, cy), (band * 16, tile * tile_w))
        u = jax.lax.dynamic_update_slice(
            u, jnp.where(valid, tu, cu), (band * 8, tile * ctw))
        v = jax.lax.dynamic_update_slice(
            v, jnp.where(valid, tv, cv), (band * 8, tile * ctw))
        return y, u, v

    if cbucket:
        sy, su, sv = jax.lax.fori_loop(0, cbucket, copy_body, (sy, su, sv))
    if not bucket:  # pure-remap frame (scroll/window steady state)
        return sy, su, sv, py, pu, pv

    def up_body(i, state):
        y, u, v, qy, qu, qv = state
        valid = up_idx[i] >= 0
        d = jnp.maximum(up_idx[i], 0)
        band, tile = d // 1024, d % 1024
        cy = jax.lax.dynamic_slice(y, (band * 16, tile * tile_w), (16, tile_w))
        cu = jax.lax.dynamic_slice(u, (band * 8, tile * ctw), (8, ctw))
        cv = jax.lax.dynamic_slice(v, (band * 8, tile * ctw), (8, ctw))
        y = jax.lax.dynamic_update_slice(
            y, jnp.where(valid, yb[i], cy), (band * 16, tile * tile_w))
        u = jax.lax.dynamic_update_slice(
            u, jnp.where(valid, ub[i], cu), (band * 8, tile * ctw))
        v = jax.lax.dynamic_update_slice(
            v, jnp.where(valid, vb[i], cv), (band * 8, tile * ctw))
        # keep the uploaded tile in its assigned pool slot (padding and
        # not-kept uploads target the scratch row)
        qy = jax.lax.dynamic_update_slice(qy, yb[i][None], (pool_dst[i], 0, 0))
        qu = jax.lax.dynamic_update_slice(qu, ub[i][None], (pool_dst[i], 0, 0))
        qv = jax.lax.dynamic_update_slice(qv, vb[i][None], (pool_dst[i], 0, 0))
        return y, u, v, qy, qu, qv

    return jax.lax.fori_loop(0, bucket, up_body, (sy, su, sv, py, pu, pv))


def _pool_seed_step(pairs, sy, su, sv, py, pu, pv, *, tile_w, sbucket):
    """Seed pool slots by GATHERING tiles from the resident source
    planes — no pixel upload at all (only the (slot, idx) list crosses).
    Runs after a full-frame upload whose dirty set was over the delta
    budget: the next frame of a sustained scroll then remaps instead of
    aborting to another full upload. pairs: (sbucket, 2) int32
    (slot, dst_idx); padding rows target the scratch slot."""
    ctw = tile_w // 2

    def body(i, pool):
        qy, qu, qv = pool
        slot = pairs[i, 0]
        d = jnp.maximum(pairs[i, 1], 0)
        band, tile = d // 1024, d % 1024
        ty = jax.lax.dynamic_slice(sy, (band * 16, tile * tile_w), (16, tile_w))
        tu = jax.lax.dynamic_slice(su, (band * 8, tile * ctw), (8, ctw))
        tv = jax.lax.dynamic_slice(sv, (band * 8, tile * ctw), (8, ctw))
        qy = jax.lax.dynamic_update_slice(qy, ty[None], (slot, 0, 0))
        qu = jax.lax.dynamic_update_slice(qu, tu[None], (slot, 0, 0))
        qv = jax.lax.dynamic_update_slice(qv, tv[None], (slot, 0, 0))
        return qy, qu, qv

    return jax.lax.fori_loop(0, sbucket, body, (py, pu, pv))


def _p_scatter_step2(packed, qp, sy, su, sv, py, pu, pv, ref_y, ref_u, ref_v,
                     *, nscap, cap, tile_w, bucket, cbucket, density, entropy=None):
    y, u, v, qy, qu, qv = _apply_tiles2(
        sy, su, sv, py, pu, pv, packed, tile_w=tile_w, bucket=bucket, cbucket=cbucket)
    out = encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp)
    prefix, dense, buf = _pack_sparse_p(out, nscap, cap, density, entropy)
    return (prefix, dense, buf, out["recon_y"], out["recon_u"], out["recon_v"],
            y, u, v, qy, qu, qv)


def _i_scatter_step2(packed, qp, sy, su, sv, py, pu, pv, *, tile_w, bucket, cbucket):
    y, u, v, qy, qu, qv = _apply_tiles2(
        sy, su, sv, py, pu, pv, packed, tile_w=tile_w, bucket=bucket, cbucket=cbucket)
    out = encode_frame_planes(y, u, v, qp)
    header, buf = pack_i_compact(out)
    prefix = fuse_downlink(header, buf, CAP_ROWS)
    return (prefix, buf, out["recon_y"], out["recon_u"], out["recon_v"],
            y, u, v, qy, qu, qv)


def _p_scatter_multi_step2(packed_a, packed_b, qps, sy, su, sv, py, pu, pv,
                           ref_y, ref_u, ref_v,
                           *, nscap, cap, tile_w, bucket, cbucket, density,
                           entropy=None):
    """Grouped (lax.scan) variant of _p_scatter_step2: the slot pool
    rides in the carry, so frame k's copy remaps may reference slots
    frame k-1's uploads inserted — matching the host cache's sequential
    split() order exactly."""
    packed = jnp.concatenate([packed_a, packed_b], 0)

    def body(carry, xs):
        pk, qp = xs
        cy, cu, cv, qy, qu, qv, ry, ru, rv = carry
        y, u, v, qy, qu, qv = _apply_tiles2(
            cy, cu, cv, qy, qu, qv, pk, tile_w=tile_w, bucket=bucket, cbucket=cbucket)
        out = encode_frame_p_planes(y, u, v, ry, ru, rv, qp)
        prefix, dense, buf = _pack_sparse_p(out, nscap, cap, density, entropy)
        return (
            (y, u, v, qy, qu, qv, out["recon_y"], out["recon_u"], out["recon_v"]),
            (prefix, dense, buf),
        )

    carry, (prefixes, denses, bufs) = jax.lax.scan(
        body, (sy, su, sv, py, pu, pv, ref_y, ref_u, ref_v), (packed, qps)
    )
    y, u, v, qy, qu, qv, ry, ru, rv = carry
    return prefixes, denses, bufs, ry, ru, rv, y, u, v, qy, qu, qv


# shared with the band-parallel completion path (sparse_complete.py owns
# the implementation; the 4096 default there IS CAP_ROWS)
_fetch_rest = fetch_rest


FrameStats = _FrameStats  # shared definition (models/stats.py)


@dataclass
class _Pending:
    """One in-flight frame in the encode pipeline."""

    kind: str  # "static" | "i" | "p" | "pd" (sparse delta P) | "pb" (device-entropy P)
    frame_index: int
    qp: int
    frame_num: int
    idr_pic_id: int
    t0: float
    t1: float
    meta: object = None
    au: bytes | None = None  # static only
    prefix_d: object = None
    pfx_slice_d: object = None  # pd: hint-sized slice, dispatched with the step
    buf_d: object = None
    hdr_d: object = None  # pd/pb: dense header for the fallback fetch
    words_d: object = None  # pb only: full bit-word buffer (spill fetch)
    future: object = None  # completion future (threaded fetch+unpack+pack)
    batch_slot: int = -1  # >=0: index into a shared batch future's result list
    # device-stage attribution (FrameStats upload/step/fetch split):
    # up_ms is the HOST front-end cost of this frame — classify (fused
    # dirty scan + tile-cache hash/split) + convert (BGRx->I420 of the
    # upload payload) + h2d (transfer enqueue) + packing glue — split
    # out in classify_ms/convert_ms/h2d_ms. t_disp is the wall clock
    # just BEFORE the device-step dispatch call: workers measure
    # step_ms = outputs-ready - t_disp, so a dispatch call that blocks
    # (CPU backend contention, full dispatch queue) counts as device
    # step time, not as upload — the round-11 bench misread exactly
    # this (PERF.md round 12)
    up_ms: float = 0.0
    classify_ms: float = 0.0
    convert_ms: float = 0.0
    h2d_ms: float = 0.0
    t_disp: float = 0.0
    scene_cut: bool = False  # full-frame change transition (rate control)
    # dirty-tile accounting for the scenario policy signals
    # (FrameStats.upload_kind/dirty_frac/remap_frac): pixel-upload tiles
    # and tile-cache remap pairs of a delta frame
    n_up: int = 0
    n_remap: int = 0
    # LTR scene cache slice-header flags (bitstream.write_slice_header):
    ltr_ref: int | None = None   # predict from long-term reference j
    mark_ltr: int | None = None  # mark the previous frame as LT index k
    mmco_evict: tuple = ()       # MMCO 1 diffs for stale short-terms


class TPUH264Encoder:
    """Stateful per-stream encoder: frame in, Annex-B access unit out.

    `codec` identifies the bitstream for client decoder configuration
    (media.js maps it to a WebCodecs codec string).

    GOP policy mirrors the reference default (keyframe_distance=-1,
    __main__.py:473-475): one IDR, then P frames forever; new IDRs only on
    force_keyframe() (client PLI / stream restart) or an explicit
    keyframe_interval. The previous frame's reconstruction stays on the
    TPU between frames — only quantized coefficients cross PCIe.
    """

    codec = "h264"
    # the submit()/encode paths take capture-layer damage-rect hints
    # (FramePrep.scan superset contract); the pipeline only forwards
    # hints to encoders that declare this
    accepts_damage = True

    def __init__(
        self,
        width: int,
        height: int,
        qp: int = 28,
        fps: int = 60,
        channels: int = 4,
        keyframe_interval: int = 0,
        host_convert: bool = True,
        pipeline_depth: int = 2,
        frame_batch: int = 4,
        scene_qp_boost: int = 0,
        device_entropy: bool | None = None,
        bits_min_mbs: int | None = None,
        entropy_coder: str | None = None,
        ltr_scenes: bool = True,
        tile_cache: int | None = None,
        packed_downlink: bool | None = None,
        pack_density: int | None = None,
        bands: int | None = None,
    ):
        self.width = width
        self.height = height
        self.fps = fps
        # bands: intra-frame slice parallelism lives in the band-parallel
        # encoder (parallel/bands.py; the registry routes SELKIES_BANDS>1
        # there) — here the knob only sizes the pack pool, so a caller
        # that wraps this encoder per band fans its slices out correctly
        if bands is None:
            # lazy: parallel.bands imports this module
            from selkies_tpu.parallel.bands import bands_from_env

            bands = bands_from_env()
        self.bands = int(bands)
        self._nscap = NSCAP
        self._cap_delta = CAP_ROWS_DELTA
        # packed delta downlink: coefficient rows cross the link as a
        # significance bitmap + nonzeros (encoder_core.pack_p_sparse_
        # packed) — 3-6x fewer live bytes on desktop residuals, falling
        # back to dense rows above the density cap. SELKIES_PACK_DENSITY
        # overrides: "0" disables, an integer sets the cap percent.
        # env is the DEFAULT only: explicit constructor arguments win
        # (same precedence as the tile_cache knob); a malformed env
        # value falls back rather than failing construction
        dens_env = os.environ.get("SELKIES_PACK_DENSITY", "")
        if packed_downlink is None:
            packed_downlink = dens_env != "0"
        if pack_density is None:
            try:
                pack_density = int(dens_env) if dens_env not in ("", "0") else 75
            except ValueError:
                pack_density = 75
        self._density = int(pack_density) if packed_downlink else None
        self.set_qp(qp)
        self.channels = channels
        self.keyframe_interval = int(keyframe_interval)  # 0 = infinite GOP
        # entropy_coder: cavlc (Baseline, the byte-contract default) or
        # cabac (Main profile). PPS-scoped, so every slice of the stream
        # uses the same coder; SELKIES_ENTROPY_CODER is the env default,
        # explicit constructor arguments win.
        self._coder = entropy_coder_default(entropy_coder)
        self.params = StreamParams(width=width, height=height, qp=self.qp,
                                   fps=fps, entropy_coder=self._coder)
        self._headers = write_sps(self.params) + write_pps(self.params)
        self._pad_h = (height + 15) // 16 * 16
        self._pad_w = (width + 15) // 16 * 16
        # host_convert: BGRx->I420 on the host CPU (native/frameprep.cc) so
        # the upload is 1.5 B/px instead of 4 — the link is the bottleneck
        # (tools/profile_link.py). host_convert=False keeps conversion on
        # device (better when the device is PCIe-local and link-rich).
        self.pipeline_depth = max(0, int(pipeline_depth))
        # delta granularity: 16-row x _tile_w-col tiles. Column tiling
        # shrinks the upload by the width fraction that changed (a cursor
        # blink is one ~3 KB tile, not a ~46 KB full-width band); the
        # largest power-of-two width that divides pad_w keeps device
        # shapes static (pad_w itself => full bands, the old behavior).
        self._tile_w = tile_width_for(width)
        self._prep: FramePrep | None = None
        if host_convert and channels == 4:
            # one conversion slot per possibly-in-flight async upload plus
            # one being written: depth+1 frames can be pipelined before
            # submit() blocks on the oldest completion
            self._prep = FramePrep(
                width, height, self._pad_w, self._pad_h,
                nslots=self.pipeline_depth + 2,
            )
        # device_entropy: P frames emit their slice BITSTREAM on device
        # (device_cavlc.py) — the downlink is the final bits, not
        # coefficient tensors. Full-P frames always ship bits when this
        # is on; delta frames decide per frame ON DEVICE (busy frames —
        # >= bits_min_mbs live MBs — ship bits, quiet frames keep the
        # sparse coeff downlink whose host pack is already near-free).
        # Requires host conversion mode (the only production path);
        # byte-identical either way. Default is AUTO — on for real TPU
        # backends, off on CPU; SELKIES_DEVICE_ENTROPY=0/1 forces,
        # SELKIES_BITS_MIN_MBS moves the decision threshold; explicit
        # constructor arguments win (tile_cache precedence rules). The
        # resolved consts (_entropy) are what the jitted delta steps
        # close over: bits payload cap, live-MB threshold, bucket ladder.
        (self.device_entropy, self.bits_min_mbs, self._bits_words,
         self._entropy) = resolve_entropy(
            (self._pad_h // 16) * (self._pad_w // 16),
            device_entropy, bits_min_mbs, entropy_coder=self._coder)
        if self._prep is None:  # device conversion mode: host path only
            self.device_entropy = False
            self._entropy = None
        if self._prep is not None:
            self._step = jax.jit(_i_planes_step_chunked)
            self._step_p = jax.jit(_p_planes_step_chunked, donate_argnums=(7, 8, 9))
            self._step_pb = jax.jit(
                _p_toks_step_chunked if self._coder == "cabac"
                else _p_bits_step_chunked,
                donate_argnums=(7, 8, 9))
            # delta-upload steps: source planes are donated (scatter is
            # in-place) and returned updated; refs donated as usual
            # nscap/cap ride in a partial (not read from module globals
            # inside the traced body): jax's trace cache is keyed on the
            # function object, so a global read would leak one encoder's
            # constants into another's executable.
            _consts = dict(nscap=self._nscap, cap=self._cap_delta, tile_w=self._tile_w,
                           density=self._density, entropy=self._entropy)
            self._step_scatter_p = jax.jit(
                partial(_p_scatter_step, **_consts), donate_argnums=(2, 3, 4, 5, 6, 7)
            )
            self._step_scatter_pk = jax.jit(
                partial(_p_scatter_multi_step, **_consts), donate_argnums=(3, 4, 5, 6, 7, 8)
            )
            self._step_scatter_i = jax.jit(
                partial(_i_scatter_step, tile_w=self._tile_w), donate_argnums=(2, 3, 4)
            )
            self._step_resident_i = jax.jit(_i_resident_step)
            # LTR scene restore: same scatter+encode step but NON-donating
            # — the long-term slot's planes must survive the step (they
            # are the stash, not the working chain)
            self._step_scatter_ltr = jax.jit(partial(_p_scatter_step, **_consts))
            # device-side plane snapshot for the scene stash (six ~1 MB
            # HBM copies, dispatched once per scene cut)
            self._copy_planes = jax.jit(
                lambda *arrs: tuple(jnp.copy(a) for a in arrs))
        else:
            self._step = jax.jit(
                lambda frame, qp: _device_step(
                    frame, qp, pad_h=self._pad_h, pad_w=self._pad_w, channels=channels
                )
            )
            self._step_p = jax.jit(
                lambda frame, qp, ry, ru, rv: _device_step_p(
                    frame, qp, ry, ru, rv,
                    pad_h=self._pad_h, pad_w=self._pad_w, channels=channels,
                ),
                donate_argnums=(2, 3, 4),
            )
        self._ref = None  # (recon_y, recon_u, recon_v) device arrays
        self._src = None  # device-resident source planes (delta-upload base)
        # frame_batch > 1: consecutive delta frames are grouped into one
        # scan-over-frames device step (one upload/execute/fetch per
        # GROUP). Trades up to frame_batch-1 frame-times of latency for
        # K-fold fewer relay round trips; on PCIe-local devices set 1.
        # feed-forward scene-cut rate control: a full-frame change encoded
        # at the steady-state QP blows the VBV budget (reference holds VBV
        # at 1.5 frame-times); boost QP for that one frame — the decay
        # frames after it re-sharpen within a few hundred ms. 0 = off
        # (keeps delta-vs-full bit-exactness tests meaningful).
        self.scene_qp_boost = int(scene_qp_boost)
        self._prev_kind = "full"  # first frame is not a "scene cut"
        self.frame_batch = max(1, int(frame_batch))
        # scan executables compile for these group sizes only (greedy
        # grouping in _flush_batch); a half group beats singles when a
        # flush catches the accumulator mid-fill
        self._batch_sizes = tuple(
            sorted({self.frame_batch, max(2, self.frame_batch // 2)}, reverse=True)
        ) if self.frame_batch > 1 else ()
        # live policy cap on the effective group size (set_batch_cap):
        # <= frame_batch, snapped to a compiled scan size; the default
        # (== frame_batch) is byte- and behavior-identical to the
        # pre-policy encoder
        self._batch_cap = self.frame_batch
        self._batch_pend: list = []  # (rec, yb, ub, vb, idx) to group-dispatch
        ntx = self._pad_w // self._tile_w
        # total delta tiles in the frame (policy dirty_frac denominator)
        self._ntiles = (self._pad_h // 16) * ntx
        # delta bucket sizes: dirty-tile counts round up to one of these so
        # each resolution compiles a handful of scatter executables; frames
        # dirtier than the largest bucket use the full-upload path (the
        # delta would save little and each bucket costs a compile)
        self._delta_buckets = delta_buckets_for(width, height)
        # grouped-dispatch buckets: small sparse-update group, then the
        # area equivalents of the old 4- and 16-band group limits
        self.BATCH_BUCKETS = tuple(sorted({16, 4 * ntx, 16 * ntx} | (
            {self._delta_buckets[0]} if self._delta_buckets else set())))
        # uplink tile cache (CopyRect remaps, models/tilecache.py): dirty
        # tiles whose content already sits in the device slot pool become
        # 8-byte (slot -> position) remaps instead of pixel uploads.
        # SELKIES_TILE_CACHE sets the slot count ("0" disables; on
        # PCIe-local hosts the hash/memcmp cost can exceed the cheap
        # upload it saves — see docs/link_bytes.md).
        if tile_cache is None:
            tile_cache = int(os.environ.get("SELKIES_TILE_CACHE", "1024") or "0")
        self.tile_cache_slots = (
            int(tile_cache)
            if (self._prep is not None and self._delta_buckets) else 0
        )
        self._tcache = (
            TileCache(height, width, self._tile_w, self.tile_cache_slots)
            if self.tile_cache_slots > 0 else None
        )
        self._pool_d = None  # device slot-pool planes, allocated lazily
        # copy-pair bucket ladder: tiny (typing: no remaps) or full-delta
        # (scroll: most dirty tiles are remaps); every (bucket, cbucket)
        # combination is one compiled executable, so the ladder stays at 2.
        # Upload buckets gain a 0 rung: a pure-remap frame (scroll/window
        # steady state) ships ONLY the pair list — no pixel payload at all
        # over-budget delta attempts (maximized-window scrolls): dirty
        # counts up to 4x the delta cap still try the cache — remaps
        # don't upload pixels, so such frames usually fit after the
        # split; the bound keeps full-frame video off the hashing path
        ntiles_all = (self._pad_h // 16) * ntx
        self._tc_try_cap = (
            min(4 * self._delta_buckets[-1], ntiles_all) if self._delta_buckets else 0
        )
        self._copy_buckets = (
            tuple(sorted({16, self._delta_buckets[-1], self._tc_try_cap}))
            if self._delta_buckets else ()
        )
        self._up_buckets = (0,) + self._delta_buckets
        self._up_batch_buckets = (0,) + self.BATCH_BUCKETS
        self._step2_cache: dict = {}
        self._ltr_probe: object = ()  # per-frame memo, see _classify
        self.link_bytes = LinkByteCounter()
        # last-seen tile-cache totals, for per-frame telemetry deltas
        self._tc_seen = (0, 0, 0)
        self._prev_frame: np.ndarray | None = None  # device-convert mode only
        # per-dispatch front-end stage scratch (submit-thread only):
        # convert/h2d accumulate inside the dispatch helpers, _t_disp0
        # records the wall clock immediately before the jitted step call
        # (the step/upload attribution boundary — see _Pending)
        self._t_conv_ms = 0.0
        self._t_h2d_ms = 0.0
        self._t_disp0 = 0.0
        self._inflight: deque = deque()
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, self.pipeline_depth + 1),
            thread_name_prefix="h264-complete",
        )
        # Per-slot CAVLC fan-out pool: a delta GROUP's frames are
        # independent (separate slice NALs; the native packer releases
        # the GIL and its scratch is thread-local), so the group
        # completion spreads across cores instead of packing K frames
        # serially on one worker. Sized to cover every frame that can be
        # in flight at once — min(cores, bands x frame_batch x
        # pipeline_depth), the bands factor covering per-band slice
        # fan-out when this instance packs one band of a split frame —
        # SELKIES_PACK_WORKERS overrides.
        # Kept SEPARATE from self._pool: group coordinators block on
        # slot futures, and coordinators + leaves sharing one executor
        # can deadlock with every worker stuck coordinating.
        pack_workers = int(os.environ.get("SELKIES_PACK_WORKERS", "0") or 0)
        if pack_workers <= 0:
            pack_workers = min(
                os.cpu_count() or 4,
                max(2, self.bands * self.frame_batch * max(1, self.pipeline_depth)),
            )
        self._pack_pool = (
            ThreadPoolExecutor(max_workers=pack_workers,
                               thread_name_prefix="h264-pack")
            if self.frame_batch > 1 else None
        )
        self._upload_pool = ThreadPoolExecutor(
            max_workers=Y_CHUNKS + 2, thread_name_prefix="h264-upload",
        )
        mbh, mbw = self._pad_h // 16, self._pad_w // 16
        self._hdr_words_i = i_header_words(mbh, mbw)
        self._hdr_words_p = p_header_words(mbh, mbw)
        self._mbh, self._mbw = mbh, mbw
        # adaptive delta-downlink fetch: full var-buffer length and the
        # live slice hint (int16 words), grown/shrunk from recent frames.
        # The packed layout shrinks the live content 2-6x, which keeps
        # busy frames UNDER the small-fetch threshold where the 16-lane
        # layout escalates to a full-buffer fetch (measured on the bench
        # trace: typing needs 17.6k -> 8.9k words, i.e. a 164 KB full
        # fetch becomes the 32 KB small one). Still exactly TWO fetch
        # shapes (see PFX_SMALL).
        if self._entropy is not None:
            self._pfx_total = p_sparse_entropy_words(
                mbh, mbw, self._nscap, self._cap_delta,
                self._density is not None, self._bits_words,
                entropy_coder=self._coder)
        elif self._density is not None:
            self._pfx_total = p_sparse_packed_words(mbh, mbw, self._nscap, self._cap_delta)
        else:
            self._pfx_total = p_sparse_var_words(mbh, mbw, self._nscap, self._cap_delta)
        self._pfx_small = self.PFX_SMALL
        self._pfx_hint = min(self._pfx_small, self._pfx_total)
        self._pfx_recent: deque = deque(maxlen=8)
        # appended by completion workers and the submit thread; iterating
        # a deque during a concurrent append raises RuntimeError
        self._pfx_lock = threading.Lock()
        self._allskip: PFrameCoeffs | None = None
        # LTR scene cache (the alt-tab optimization): window switches
        # back to a remembered scene encode as a tiny delta against that
        # scene's long-term reference instead of a full-frame upload +
        # encode round trip — on this deployment's link that is the
        # difference between ~30 ms and ~400 ms for the switch frame.
        # Two slots, LRU replacement; each holds device copies of a
        # scene-cut frame's source+recon planes plus the host capture
        # for match detection. H.264 side: SPS max_num_ref_frames=3
        # (1 short-term + 2 long-term), the frame AFTER a scene cut
        # marks the cut frame long-term (MMCO 3 — it is still resident
        # short-term then, so no ref-list games are needed in between),
        # and restore frames select the slot via ref_pic_list
        # modification (write_slice_header ltr_ref/mark_ltr).
        self.ltr_scenes = bool(ltr_scenes) and self._prep is not None
        self._ltr_slots: list[dict | None] = [None, None]
        # MRU protection: new-scene candidates always target the slot
        # that was NOT most recently matched/stashed-by-restore, so
        # sustained full-frame motion (every frame becomes a candidate)
        # thrashes one slot and can never evict the last restored scene
        self._ltr_mru = 1
        self._ltr_candidate: dict | None = None
        # consecutive-full-frame run length: window switches arrive as
        # runs of 1-2 full frames, sustained motion (video playback) as
        # long runs — stash candidates only for the first two frames of
        # a run, so motion doesn't pay per-frame plane/capture copies or
        # thrash a slot with scenes that can never be restored mid-run
        self._full_run = 0
        self.ltr_restores = 0  # stats: scene switches served from cache
        # decoder-DPB mirror (short-term ref frame_nums, decode order):
        # slices that carry MMCO marking replace the sliding window
        # (8.2.5), so they must explicitly evict any short-terms that
        # accumulated while the DPB had slack — this list is what the
        # decoder's ST set contains, letting submit() compute the MMCO 1
        # diffs (see write_slice_header mmco_evict)
        self._dpb_st: list[int] = []
        self.frame_index = 0
        self._frames_since_idr = 0
        self._idr_pic_id = 0
        self._force_idr = True
        self.last_stats: FrameStats | None = None

    # -- live retune API (parity: set_video_bitrate path ends here) --

    def set_qp(self, qp: int) -> None:
        if not 0 <= qp <= 51:
            raise ValueError(f"qp {qp} out of range")
        self.qp = int(qp)

    def force_keyframe(self) -> None:
        self._force_idr = True

    @property
    def entropy_coder(self) -> str:
        """Active entropy backend ("cavlc"/"cabac") — telemetry stamps
        this onto every frame event (frame_done)."""
        return self._coder

    @property
    def h264_profile(self) -> str:
        """Profile the SPS declares ("baseline"/"main") — the WebRTC
        plane's fmtp profile-level-id must match it (sdp.py)."""
        return "main" if self._coder == "cabac" else "baseline"

    # -- policy actuation (selkies_tpu/policy): runtime-safe retunes ---

    def set_tile_cache(self, enabled: bool) -> bool:
        """Runtime uplink tile-cache toggle (policy actuation); returns
        True when the state changed. Byte-safe at any frame boundary:
        a remap reproduces the exact pixels an upload would (PR 1's
        bit-exactness contract), so the encoded stream is identical
        with the cache on or off. Only togglable when the cache
        machinery was built (slots > 0 at construction — the compiled
        scatter ladder and device pool shapes are sized then).
        Re-enabling starts from an EMPTY cache: while classification
        bypassed it the device pool went stale, and a stale host entry
        would remap garbage pixels."""
        enabled = bool(enabled)
        if self.tile_cache_slots <= 0 or not self._delta_buckets:
            return False
        if enabled == (self._tcache is not None):
            return False
        # pending group payloads were split for the OLD mode (with the
        # cache the tuple carries pool_dst/pairs): dispatch them first
        self._flush_batch()
        self._tcache = (
            TileCache(self.height, self.width, self._tile_w,
                      self.tile_cache_slots)
            if enabled else None)
        self._pool_d = None
        return True

    def set_batch_cap(self, cap: int) -> bool:
        """Cap the effective grouped-dispatch size (policy actuation);
        returns True when it changed. The cap snaps DOWN to an
        already-compiled scan size (1, frame_batch//2, frame_batch —
        _flush_batch's greedy ladder), so no policy flap can trigger a
        group-scan compile. Byte-safe at any frame boundary: grouped
        and single delta dispatches are byte-identical
        (tests/test_sparse_native_pack.py). Cap 1 dispatches every
        delta immediately — the latency posture: a frame never waits
        for group members that are whole capture intervals away."""
        cap = max(1, min(int(cap), self.frame_batch))
        sizes = (1,) + tuple(self._batch_sizes)
        cap = max(s for s in sizes if s <= cap)
        if cap == self._batch_cap:
            return False
        self._batch_cap = cap
        if len(self._batch_pend) >= cap:
            self._flush_batch()
        return True

    def retune_entropy(self, device_entropy: bool | None = None,
                       bits_min_mbs: int | None = None,
                       entropy_coder: str | None = None) -> bool:
        """Re-resolve the device-entropy downlink decision at runtime
        (policy actuation); returns True when anything changed. Bytes
        are identical either way (tests/test_device_entropy_sparse.py)
        — what changes is the DOWNLINK: busy frames ship final slice
        bits instead of multi-MB coefficient rows (PR 7). Expensive:
        the delta-scatter partials close over the entropy consts, so
        they are rebuilt and recompile on next use — the policy
        engine's dwell is what keeps this off the flap path. The
        caller must have NO frames in flight (the in-flight frames'
        completion reads the downlink sizing being replaced); the
        policy actuator drains the pipeline first.

        entropy_coder="cavlc"/"cabac" additionally switches the stream's
        entropy backend. Unlike the downlink knobs this changes the
        BITSTREAM (entropy_coding_mode_flag is PPS-scoped): new SPS/PPS
        are emitted and an IDR is forced so the decoder reconfigures at
        a clean boundary."""
        if self._prep is None:  # device-convert mode has no entropy path
            return False
        coder = self._coder if entropy_coder is None else (
            entropy_coder_default(entropy_coder))
        de, bm, bw, ent = resolve_entropy(
            self._mbh * self._mbw, device_entropy, bits_min_mbs,
            entropy_coder=coder)
        if (de == self.device_entropy and bm == self.bits_min_mbs
                and coder == self._coder):
            return False
        if coder != self._coder:
            if self._inflight or self._batch_pend:
                raise RuntimeError(
                    "retune_entropy with frames in flight; flush first")
            from selkies_tpu.monitoring import jitprof

            jitprof.mark("actuation", "entropy-coder-switch")
            self._coder = coder
            self.params = StreamParams(
                width=self.width, height=self.height, qp=self.qp,
                fps=self.fps, entropy_coder=coder)
            self._headers = write_sps(self.params) + write_pps(self.params)
            self._step_pb = jax.jit(
                _p_toks_step_chunked if coder == "cabac"
                else _p_bits_step_chunked,
                donate_argnums=(7, 8, 9))
            self.device_entropy, self.bits_min_mbs = de, bm
            self._bits_words, self._entropy = bw, ent
            self._rebuild_entropy_partials()
            # decoder must see the new PPS before any slice that uses
            # the other coder: restart the GOP
            self.force_keyframe()
            return True
        if ent == self._entropy and bw == self._bits_words:
            # threshold bookkeeping with the device coder disabled (or
            # consts unchanged): no jitted partial closes over it, so
            # nothing to rebuild and no flush needed
            self.device_entropy, self.bits_min_mbs = de, bm
            return True
        if self._inflight or self._batch_pend:
            raise RuntimeError(
                "retune_entropy with frames in flight; flush first")
        # recompile sentinel (monitoring/jitprof.py): the partials below
        # recompile lazily on their next call — attribute those compiles
        # to this actuation, wherever/whenever they land
        from selkies_tpu.monitoring import jitprof

        jitprof.mark("actuation", "entropy-retune")
        self.device_entropy, self.bits_min_mbs = de, bm
        self._bits_words, self._entropy = bw, ent
        self._rebuild_entropy_partials()
        return True

    def _rebuild_entropy_partials(self) -> None:
        """Rebuild the jitted delta-step partials and the downlink
        sizing after the entropy consts changed (retune_entropy, both
        the downlink-knob and the coder-switch paths)."""
        _consts = dict(nscap=self._nscap, cap=self._cap_delta,
                       tile_w=self._tile_w, density=self._density,
                       entropy=self._entropy)
        self._step_scatter_p = jax.jit(
            partial(_p_scatter_step, **_consts),
            donate_argnums=(2, 3, 4, 5, 6, 7))
        self._step_scatter_pk = jax.jit(
            partial(_p_scatter_multi_step, **_consts),
            donate_argnums=(3, 4, 5, 6, 7, 8))
        self._step_scatter_ltr = jax.jit(partial(_p_scatter_step, **_consts))
        self._step2_cache.clear()
        # downlink sizing tracks the fused-buffer layout
        if self._entropy is not None:
            self._pfx_total = p_sparse_entropy_words(
                self._mbh, self._mbw, self._nscap, self._cap_delta,
                self._density is not None, self._bits_words,
                entropy_coder=self._coder)
        elif self._density is not None:
            self._pfx_total = p_sparse_packed_words(
                self._mbh, self._mbw, self._nscap, self._cap_delta)
        else:
            self._pfx_total = p_sparse_var_words(
                self._mbh, self._mbw, self._nscap, self._cap_delta)
        with self._pfx_lock:
            self._pfx_recent.clear()
            self._pfx_hint = min(self._pfx_small, self._pfx_total)

    # -- frame classification (static / delta / full upload) -----------

    def _classify(self, frame: np.ndarray, damage=None):
        """-> ("static" | "delta" | "full", payload).

        Compares against the previous capture (FramePrep's per-tile
        memcmp when host conversion is on; tiles are 16 rows x _tile_w
        cols). "static": byte-identical — the dominant remote-desktop
        case, zero device work. "delta": few dirty tiles and the device
        holds resident source planes — upload only the changed tiles.
        "full": everything else. The previous-frame state advances on
        every call; that is safe because any encode failure nulls
        self._ref/_src, forcing a full-upload IDR that bypasses the
        static and delta paths.

        Sets _ltr_probe when the over-budget branch already ran the
        (expensive) scene-cache match, so submit() reuses it instead of
        recomputing; () means "not computed this frame".

        payload: dirty indices (band*1024 + tile, int32) without the
        tile cache, or the cache's (up_idx, pool_dst, pairs) split with
        it. With the cache the dirty-count gate moves to the POST-REMAP
        upload count: a maximized-window scroll dirties far more tiles
        than the delta buckets hold, but nearly all of them are
        pool-resident, so the frame still fits after remapping (split
        aborts without state change when it doesn't — the big win the
        cache exists for). The attempt is bounded at _tc_try_cap dirty
        tiles so sustained full-frame video skips the hashing.

        ``damage``: optional capture-layer dirty-rect hints (superset
        contract — see FramePrep.scan): the fused scan is bounded to
        their band/tile box, so an idle/typing frame stops paying a
        full-frame memcmp for a cursor blink. The scan also emits the
        tile-cache content hashes in the same pass (want_hashes), which
        probe/split consume instead of re-reading the dirty tiles."""
        self._ltr_probe = ()
        if self._prep is None:
            if self._prev_frame is None or self._prev_frame.shape != frame.shape:
                self._prev_frame = frame.copy()
                return "full", None
            if np.array_equal(self._prev_frame, frame):
                return "static", None
            np.copyto(self._prev_frame, frame)
            return "full", None
        res = self._prep.scan(frame, self._tile_w, damage=damage,
                              want_hashes=self._tcache is not None)
        if res is None:
            return "full", None
        tiles = res.tiles
        if not tiles.any():
            return "static", None
        if self._src is None or not self._delta_buckets:
            return "full", None
        band_i, tile_i = np.nonzero(tiles)
        cap = self._delta_buckets[-1]
        if len(band_i) > (self._tc_try_cap if self._tcache is not None else cap):
            return "full", None
        idx = (band_i * 1024 + tile_i).astype(np.int32)
        if self._tcache is None:
            return "delta", idx
        if len(band_i) > cap:
            if self.ltr_scenes:
                # a remembered scene covering this frame: the LTR
                # restore emits ~50x fewer slice bits than a remap-delta
                # predicting from the previous (entirely different)
                # frame — let submit()'s scene-cache path take it. The
                # probe is memoized for submit (it is a full per-tile
                # compare).
                self._ltr_probe = self._ltr_match(frame)
                if self._ltr_probe is not None:
                    return "full", None
            # sampled membership probe: scrolled content is pool-
            # resident after its seed frame, video content never is —
            # skip the full hash/split attempt when it cannot pay
            # (sustained motion then reads ~8 precomputed hashes per
            # frame, and the seed hook is additionally bounded by
            # _full_run)
            if self._tcache.probe(frame, idx, hashes=res.hashes) < 0.5:
                return "full", ("seed", idx, res.hashes)
        payload = self._tcache.split(frame, idx, max_up=cap, hashes=res.hashes)
        if payload is None:
            # too many genuinely-new tiles: full upload — but remember
            # the dirty set (and its fused-scan hashes) so submit() can
            # seed the pool from the freshly-resident planes without
            # re-reading the tiles (a sustained over-budget scroll then
            # fits from its second frame on)
            return "full", ("seed", idx, res.hashes)
        return "delta", payload

    def _emit_classify_telemetry(self, kind: str, payload) -> None:
        """Fold one frame's classification into the telemetry bus: per-tile
        cache hit/miss/evict deltas and the frame's upload class (a delta
        whose upload list is empty is a pure-remap frame — the tile
        cache's headline outcome). Called only when telemetry is enabled;
        the frame id rides the ContextVar set by the pipeline's span."""
        tc = self._tcache
        if tc is not None:
            hits, misses, evs = tc.hits, tc.misses, tc.evictions
            dh, dm, de = (hits - self._tc_seen[0], misses - self._tc_seen[1],
                          evs - self._tc_seen[2])
            self._tc_seen = (hits, misses, evs)
            if dh:
                telemetry.count("selkies_tile_cache_tiles_total", dh,
                                result="hit")
            if dm:
                telemetry.count("selkies_tile_cache_tiles_total", dm,
                                result="miss")
            if de:
                telemetry.count("selkies_tile_cache_tiles_total", de,
                                result="evict")
            if (kind == "delta" and isinstance(payload, tuple)
                    and len(payload[0]) == 0):
                kind = "remap_only"
        telemetry.count("selkies_tile_cache_frames_total", kind=kind)

    def _allskip_slice(self, frame_num: int, mark_ltr: int | None = None,
                       mmco_evict: tuple = ()) -> bytes:
        """P slice with every MB P_Skip: recon == ref exactly (zero MV,
        full-pel, no residual), so the device reference stays valid."""
        if self._allskip is None:
            mbh, mbw = self._pad_h // 16, self._pad_w // 16
            self._allskip = PFrameCoeffs(
                mvs=np.zeros((mbh, mbw, 2), np.int32),
                skip=np.ones((mbh, mbw), bool),
                luma_ac=np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32),
                chroma_dc=np.zeros((mbh, mbw, 2, 2, 2), np.int32),
                chroma_ac=np.zeros((mbh, mbw, 2, 2, 2, 4, 4), np.int32),
                qp=self.qp,
            )
        self._allskip.qp = self.qp
        if self._coder == "cabac":
            # the PPS pins entropy_coding_mode_flag for the whole stream
            return pack_slice_p_cabac(self._allskip, self.params, frame_num,
                                      mark_ltr=mark_ltr, mmco_evict=mmco_evict)
        return pack_slice_p_fast(self._allskip, self.params, frame_num=frame_num,
                                 mark_ltr=mark_ltr, mmco_evict=mmco_evict)

    # -- encoding --

    def _put_chunked(self, y, u, v):
        """Full-frame upload as Y_CHUNKS+2 concurrent transfers (h2d
        overlaps ~2.5x across threads on the relay). Explicit device_put
        (not passing numpy into the jit) keeps each transfer an async
        enqueue instead of a synchronous ~140 ms round trip
        (tools/profile_rpc.py)."""
        rows = y.shape[0] // Y_CHUNKS
        parts = [y[i * rows : (i + 1) * rows] if i < Y_CHUNKS - 1
                 else y[(Y_CHUNKS - 1) * rows :] for i in range(Y_CHUNKS)]
        parts += [u, v]
        self.link_bytes.add("up_full", sum(p.nbytes for p in parts))
        t0 = time.perf_counter()
        out = list(self._upload_pool.map(jax.device_put, parts))
        self._t_h2d_ms += (time.perf_counter() - t0) * 1e3
        return out

    def _convert_timed(self, frame: np.ndarray):
        t0 = time.perf_counter()
        planes = self._prep.convert(frame)
        self._t_conv_ms += (time.perf_counter() - t0) * 1e3
        return planes

    def _convert_tiles_timed(self, frame: np.ndarray, idx, tile_w: int):
        t0 = time.perf_counter()
        out = self._prep.convert_tiles(frame, idx, tile_w)
        self._t_conv_ms += (time.perf_counter() - t0) * 1e3
        return out

    def _put_timed(self, arr):
        t0 = time.perf_counter()
        out = jax.device_put(arr)
        self._t_h2d_ms += (time.perf_counter() - t0) * 1e3
        return out

    def _run_step_i(self, frame: np.ndarray):
        if self._prep is not None:
            parts = self._put_chunked(*self._convert_timed(frame))
            self._t_disp0 = time.perf_counter()
            *out, y, u, v = self._step(*parts, np.int32(self.qp))
            # keep the joined planes resident: they are the delta base
            # for the next frame (the I step does not donate them)
            self._src = (y, u, v)
            return out
        self.link_bytes.add("up_full", frame.nbytes)
        parts = self._put_timed(frame)
        self._t_disp0 = time.perf_counter()
        return self._step(parts, np.int32(self.qp))

    def _run_step_p(self, frame: np.ndarray):
        if self._prep is not None:
            parts = self._put_chunked(*self._convert_timed(frame))
            self._t_disp0 = time.perf_counter()
            if self.device_entropy:
                prefix_d, words_d, hdr_d, buf_d, ry, ru, rv, y, u, v = self._step_pb(
                    *parts, np.int32(self.qp), *self._ref
                )
                self._src = (y, u, v)
                return ("pb", prefix_d, words_d, hdr_d, buf_d, ry, ru, rv)
            out = self._step_p(*parts, np.int32(self.qp), *self._ref)
            self._src = (out[5], out[6], out[7])
            # (kind, prefix, words, hdr, buf, recon_y, recon_u, recon_v)
            return ("p", out[0], None, None, out[1], out[2], out[3], out[4])
        self.link_bytes.add("up_full", frame.nbytes)
        parts = self._put_timed(frame)
        self._t_disp0 = time.perf_counter()
        out = self._step_p(parts, np.int32(self.qp), *self._ref)
        return ("p", out[0], None, None, out[1], out[2], out[3], out[4])

    @staticmethod
    def _pack_tiles(yb, ub, vb, idx, bucket: int) -> np.ndarray:
        """Pad to `bucket` tiles (repeating the last tile — rewriting a
        tile is idempotent) and pack into one upload buffer:
        [idx int32 bytes (band*1024 + tile)] ++ yb ++ ub ++ vb
        (see _unpack_delta; element width is _tile_w luma cols)."""
        k = len(idx)
        if k < bucket:
            reps = bucket - k
            yb = np.concatenate([yb, np.repeat(yb[-1:], reps, 0)])
            ub = np.concatenate([ub, np.repeat(ub[-1:], reps, 0)])
            vb = np.concatenate([vb, np.repeat(vb[-1:], reps, 0)])
            idx = np.concatenate([idx, np.full(reps, idx[-1], np.int32)])
        return np.concatenate([idx.view(np.uint8), yb.ravel(), ub.ravel(), vb.ravel()])

    # -- tile cache (CopyRect remaps) -----------------------------------

    def _get_pool(self):
        """Device tile slot pool (slots + 1 rows; last row is scratch)."""
        if self._pool_d is None:
            s, tw = self.tile_cache_slots, self._tile_w
            self._pool_d = (
                jnp.zeros((s + 1, 16, tw), jnp.uint8),
                jnp.zeros((s + 1, 8, tw // 2), jnp.uint8),
                jnp.zeros((s + 1, 8, tw // 2), jnp.uint8),
            )
        return self._pool_d

    def _reset_tile_cache(self) -> None:
        """Host index and device pool must drop together: after a failed
        or abandoned dispatch the pool contents are unknowable, and a
        stale host entry would remap garbage pixels."""
        if self._tcache is not None:
            self._tcache.reset()
        self._pool_d = None

    def _get_step2(self, kind: str, bucket: int, cbucket: int):
        """Compiled tile-cache scatter step for one (bucket, cbucket)
        combination. kinds: "p"/"i" donate src+pool(+refs); "ltr" donates
        only the pool (the stash planes must survive); "pk" is the
        grouped scan."""
        key = (kind, bucket, cbucket)
        fn = self._step2_cache.get(key)
        if fn is None:
            consts = dict(tile_w=self._tile_w, bucket=bucket, cbucket=cbucket)
            pconsts = dict(nscap=self._nscap, cap=self._cap_delta,
                           density=self._density, entropy=self._entropy,
                           **consts)
            if kind == "p":
                fn = jax.jit(partial(_p_scatter_step2, **pconsts),
                             donate_argnums=(2, 3, 4, 5, 6, 7, 8, 9, 10))
            elif kind == "ltr":
                fn = jax.jit(partial(_p_scatter_step2, **pconsts),
                             donate_argnums=(5, 6, 7))
            elif kind == "i":
                fn = jax.jit(partial(_i_scatter_step2, **consts),
                             donate_argnums=(2, 3, 4, 5, 6, 7))
            elif kind == "seed":
                # gathers from the resident planes (NOT donated — they
                # are the next frame's delta base); pool donated
                fn = jax.jit(
                    partial(_pool_seed_step, tile_w=self._tile_w, sbucket=cbucket),
                    donate_argnums=(4, 5, 6))
            else:  # "pk": grouped scan
                fn = jax.jit(partial(_p_scatter_multi_step2, **pconsts),
                             donate_argnums=(3, 4, 5, 6, 7, 8, 9, 10, 11))
            self._step2_cache[key] = fn
        return fn

    def _seed_pool(self, frame: np.ndarray, idx: np.ndarray,
                   hashes: np.ndarray | None = None) -> None:
        """After an over-budget full upload: commit the dirty tiles to
        the host cache and fill their pool slots device-side by
        gathering from the freshly-resident source planes — only the
        (slot, idx) list crosses the link. `hashes` is the fused scan's
        content-hash array for this frame's dirty tiles (the classify
        pass already computed them — re-hashing here would repeat the
        exact redundant read the fused front-end removed)."""
        up_idx, pool_dst, _pairs = self._tcache.split(frame, idx, hashes=hashes)
        if not len(up_idx):
            return
        sbucket = next(cb for cb in self._copy_buckets if cb >= len(up_idx))
        pr = np.zeros((sbucket, 2), np.int32)
        pr[:, 0] = self.tile_cache_slots  # scratch padding
        pr[: len(up_idx), 0] = pool_dst
        pr[: len(up_idx), 1] = up_idx
        self.link_bytes.add("up_seed", pr.nbytes)
        pool2 = self._get_step2("seed", 0, sbucket)(
            jax.device_put(pr), *self._src, *self._get_pool())
        self._pool_d = tuple(pool2)

    def _pack_tiles2(self, yb, ub, vb, up_idx, pool_dst, pairs,
                     bucket: int, cbucket: int) -> np.ndarray:
        """Tile-cache upload buffer (see _unpack_delta2): uploads pad
        with idx -1 (identity writes) targeting the scratch pool row;
        copy pairs pad with src -1."""
        tw = self._tile_w
        k = len(up_idx)
        pad = bucket - k
        idxp = np.concatenate([up_idx, np.full(pad, -1, np.int32)])
        dstp = np.concatenate([pool_dst, np.full(pad, self.tile_cache_slots, np.int32)])
        if pad:
            zy = np.zeros((pad, 16, tw), np.uint8)
            zc = np.zeros((pad, 8, tw // 2), np.uint8)
            yb = np.concatenate([yb, zy]) if k else zy
            ub = np.concatenate([ub, zc]) if k else zc
            vb = np.concatenate([vb, zc]) if k else zc
        pr = np.full((cbucket, 2), -1, np.int32)
        pr[:, 1] = 0
        if len(pairs):
            pr[: len(pairs)] = pairs
        return np.concatenate([
            idxp.view(np.uint8), dstp.view(np.uint8), pr.reshape(-1).view(np.uint8),
            yb.ravel(), ub.ravel(), vb.ravel(),
        ])

    def _pack_payload2(self, frame: np.ndarray, payload):
        """Cache split result -> (packed, bucket, cbucket): convert the
        upload tiles and build the packed buffer (split itself already
        ran — in _classify for delta frames, or at the call site for
        LTR restores)."""
        up_idx, pool_dst, pairs = payload
        bucket = next(b for b in self._up_buckets if b >= len(up_idx))
        cbucket = next(cb for cb in self._copy_buckets if cb >= len(pairs))
        yb, ub, vb = self._convert_tiles_timed(frame, up_idx, self._tile_w)
        packed = self._pack_tiles2(yb, ub, vb, up_idx, pool_dst, pairs, bucket, cbucket)
        return packed, bucket, cbucket

    def _run_step_delta(self, frame: np.ndarray, idx, idr: bool):
        """Single-frame delta: upload only the dirty tiles (remapping
        cache-resident ones); scatter+encode on device. `idx` is the
        _classify payload: dirty indices, or the cache's split triple.
        Returns (prefix_d, hdr_d, buf_d, recon triple)."""
        qp = np.int32(self.qp)
        if self._tcache is not None:
            packed, bucket, cbucket = self._pack_payload2(frame, idx)
            self.link_bytes.add("up_delta", packed.nbytes)
            packed_d = self._put_timed(packed)
            pool = self._get_pool()
            self._t_disp0 = time.perf_counter()
            if idr:
                prefix_d, buf_d, ry, ru, rv, sy, su, sv, *pool2 = self._get_step2(
                    "i", bucket, cbucket)(packed_d, qp, *self._src, *pool)
                hdr_d = None
            else:
                prefix_d, hdr_d, buf_d, ry, ru, rv, sy, su, sv, *pool2 = self._get_step2(
                    "p", bucket, cbucket)(packed_d, qp, *self._src, *pool, *self._ref)
            self._pool_d = tuple(pool2)
            self._src = (sy, su, sv)
            return prefix_d, hdr_d, buf_d, ry, ru, rv
        bucket = next(b for b in self._delta_buckets if b >= len(idx))
        yb, ub, vb = self._convert_tiles_timed(frame, idx, self._tile_w)
        packed = self._pack_tiles(yb, ub, vb, idx, bucket)
        self.link_bytes.add("up_delta", packed.nbytes)
        packed_d = self._put_timed(packed)
        self._t_disp0 = time.perf_counter()
        if idr:
            prefix_d, buf_d, ry, ru, rv, sy, su, sv = self._step_scatter_i(
                packed_d, qp, *self._src
            )
            hdr_d = None
        else:
            prefix_d, hdr_d, buf_d, ry, ru, rv, sy, su, sv = self._step_scatter_p(
                packed_d, qp, *self._src, *self._ref
            )
        # reassign IMMEDIATELY: the old src (and refs on P) were donated
        self._src = (sy, su, sv)
        return prefix_d, hdr_d, buf_d, ry, ru, rv

    # -- LTR scene cache (alt-tab restore) ------------------------------

    def _dirty_vs(self, frame: np.ndarray, cap: np.ndarray) -> np.ndarray:
        """Per-tile inequality of two captures in FramePrep's geometry
        (16-row bands x _tile_w luma cols). Runs only on scene cuts."""
        d = (frame != cap).any(axis=2)
        h, w = d.shape
        pb = np.zeros((self._pad_h, self._pad_w), bool)
        pb[:h, :w] = d
        nb, nt = self._pad_h // 16, self._pad_w // self._tile_w
        return pb.reshape(nb, 16, nt, self._tile_w).any(axis=(1, 3))

    @staticmethod
    def _ltr_quick_reject(frame: np.ndarray, cap: np.ndarray) -> bool:
        """Sampled pre-filter (~8 K pixels) so sustained full-frame motion
        (video playback) rejects candidate scenes in microseconds instead
        of paying the full per-tile compare every frame. A genuine scene
        restore differs only in its dirty region (bounded by the delta
        buckets at ~25% of tiles), so a >35% sampled mismatch can never
        be a match."""
        s1, s2 = frame[8::48, 16::128], cap[8::48, 16::128]
        return float((s1 != s2).any(axis=-1).mean()) > 0.35

    def _ltr_match(self, frame: np.ndarray):
        """-> (slot, dirty_idx) of the best-matching remembered scene, or
        None when no slot matches within the delta-bucket budget."""
        if not self._delta_buckets:
            return None
        best = None
        for j, s in enumerate(self._ltr_slots):
            if s is None or s["cap"].shape != frame.shape:
                continue
            if self._ltr_quick_reject(frame, s["cap"]):
                continue
            tiles = self._dirty_vs(frame, s["cap"])
            band_i, tile_i = np.nonzero(tiles)
            if len(band_i) > self._delta_buckets[-1]:
                continue
            if best is None or len(band_i) < len(best[1]):
                best = (j, (band_i * 1024 + tile_i).astype(np.int32))
        if best is None:
            return None
        j, idx = best
        if len(idx) == 0:
            # capture identical to the stash: rewrite tile 0 with its own
            # content (idempotent) so the scatter step has a real input
            idx = np.zeros(1, np.int32)
        return j, idx

    def _run_step_ltr(self, frame: np.ndarray, idx: np.ndarray, stash: dict):
        """Scene restore: scatter the (few) tiles that differ from the
        remembered scene into a fresh copy of its source planes and
        encode against its recon — the stash planes survive untouched.
        Restore tiles hit the tile cache like any delta (the content a
        window switch re-exposes is often pool-resident)."""
        if self._tcache is not None:
            packed, bucket, cbucket = self._pack_payload2(
                frame, self._tcache.split(frame, idx))
            self.link_bytes.add("up_ltr", packed.nbytes)
            packed_d = self._put_timed(packed)
            pool = self._get_pool()
            self._t_disp0 = time.perf_counter()
            prefix_d, hdr_d, buf_d, ry, ru, rv, sy, su, sv, *pool2 = self._get_step2(
                "ltr", bucket, cbucket)(
                    packed_d, np.int32(self.qp), *stash["src"], *pool,
                    *stash["ref"])
            self._pool_d = tuple(pool2)
            self._src = (sy, su, sv)
            return prefix_d, hdr_d, buf_d, ry, ru, rv
        bucket = next(b for b in self._delta_buckets if b >= len(idx))
        yb, ub, vb = self._convert_tiles_timed(frame, idx, self._tile_w)
        packed = self._pack_tiles(yb, ub, vb, idx, bucket)
        self.link_bytes.add("up_ltr", packed.nbytes)
        packed_d = self._put_timed(packed)
        self._t_disp0 = time.perf_counter()
        prefix_d, hdr_d, buf_d, ry, ru, rv, sy, su, sv = self._step_scatter_ltr(
            packed_d, np.int32(self.qp), *stash["src"], *stash["ref"]
        )
        self._src = (sy, su, sv)
        return prefix_d, hdr_d, buf_d, ry, ru, rv

    def _stash_candidate(self, frame: np.ndarray, slot: int) -> None:
        """Snapshot this scene-cut frame as the pending LTR candidate.
        Device copies are dispatched NOW (before any later step donates
        the planes); the slot commits when the next frame emits MMCO 3."""
        if self._src is None or self._ref is None:
            return
        copies = self._copy_planes(*self._src, *self._ref)
        self._ltr_candidate = {
            "slot": int(slot),
            "src": tuple(copies[:3]),
            "ref": tuple(copies[3:]),
            "cap": np.array(frame, copy=True),
        }

    # -- grouped delta dispatch (frame_batch > 1) -----------------------


    def _flush_batch(self) -> None:
        """Dispatch the pending delta frames (if any) as device steps.

        Greedy grouping: full groups of frame_batch, then a half group,
        then singles — only those scan sizes ever compile. Must run
        before any other dispatch so device-side src/ref state advances
        in frame order."""
        pend = self._batch_pend
        if not pend:
            return
        self._batch_pend = []
        tc = self._tcache is not None
        try:
            i = 0
            while i < len(pend):
                t_d0 = time.perf_counter()
                take = next((s for s in self._batch_sizes if len(pend) - i >= s), 1)
                group = pend[i : i + take]
                i += take
                if take == 1:
                    rec, yb, ub, vb, idx, pool_dst, pairs = group[0]
                    self._t_h2d_ms = 0.0
                    if tc:
                        bucket = next(b for b in self._up_buckets if b >= len(idx))
                        cbucket = next(cb for cb in self._copy_buckets if cb >= len(pairs))
                        packed = self._pack_tiles2(yb, ub, vb, idx, pool_dst, pairs,
                                                   bucket, cbucket)
                        self.link_bytes.add("up_delta", packed.nbytes)
                        packed_d = self._put_timed(packed)
                        pool = self._get_pool()
                        self._t_disp0 = time.perf_counter()
                        (prefix_d, hdr_d, buf_d, ry, ru, rv, sy, su, sv,
                         *pool2) = self._get_step2("p", bucket, cbucket)(
                            packed_d, np.int32(rec.qp),
                            *self._src, *pool, *self._ref)
                        self._pool_d = tuple(pool2)
                    else:
                        bucket = next(b for b in self._delta_buckets if b >= len(idx))
                        packed = self._pack_tiles(yb, ub, vb, idx, bucket)
                        self.link_bytes.add("up_delta", packed.nbytes)
                        packed_d = self._put_timed(packed)
                        self._t_disp0 = time.perf_counter()
                        prefix_d, hdr_d, buf_d, ry, ru, rv, sy, su, sv = self._step_scatter_p(
                            packed_d, np.int32(rec.qp), *self._src, *self._ref
                        )
                    self._src, self._ref = (sy, su, sv), (ry, ru, rv)
                    rec.prefix_d, rec.hdr_d, rec.buf_d = prefix_d, hdr_d, buf_d
                    rec.pfx_slice_d = self._pfx_slice(prefix_d)
                    rec.batch_slot = -1
                    # upload/step boundary: t_disp is the instant BEFORE the
                    # step dispatch call, so a blocking dispatch reads as
                    # device step time (see _Pending); up_ms is the host
                    # front-end (classify + convert at submit, h2d + pack
                    # glue here)
                    rec.t_disp = self._t_disp0
                    rec.h2d_ms += self._t_h2d_ms
                    rec.up_ms = (rec.classify_ms + rec.convert_ms
                                 + (rec.t_disp - t_d0) * 1e3)
                    rec.future = self._pool.submit(self._complete_work, rec)
                    continue
                qps = np.array([g[0].qp for g in group], np.int32)
                if tc:
                    bucket = next(
                        b for b in self._up_batch_buckets
                        if b >= max(len(g[4]) for g in group)
                    )
                    cbucket = next(
                        cb for cb in self._copy_buckets
                        if cb >= max(len(g[6]) for g in group)
                    )
                    packed = np.stack([
                        self._pack_tiles2(yb, ub, vb, idx, pool_dst, pairs,
                                          bucket, cbucket)
                        for _, yb, ub, vb, idx, pool_dst, pairs in group
                    ])
                else:
                    bucket = next(
                        b for b in self.BATCH_BUCKETS
                        if b >= max(len(g[4]) for g in group)
                    )
                    packed = np.stack([
                        self._pack_tiles(yb, ub, vb, idx, bucket)
                        for _, yb, ub, vb, idx, _pd, _pr in group
                    ])
                self.link_bytes.add("up_delta", packed.nbytes)
                # two concurrent half uploads (h2d overlaps across threads)
                half = take // 2
                t_h0 = time.perf_counter()
                pa, pb = self._upload_pool.map(
                    jax.device_put, (packed[:half], packed[half:])
                )
                qps_d = jax.device_put(qps)
                h2d_ms = (time.perf_counter() - t_h0) * 1e3
                self._t_disp0 = time.perf_counter()
                if tc:
                    (prefixes_d, denses_d, bufs_d, ry, ru, rv, sy, su, sv,
                     *pool2) = self._get_step2("pk", bucket, cbucket)(
                        pa, pb, qps_d,
                        *self._src, *self._get_pool(), *self._ref)
                    self._pool_d = tuple(pool2)
                else:
                    prefixes_d, denses_d, bufs_d, ry, ru, rv, sy, su, sv = self._step_scatter_pk(
                        pa, pb, qps_d, *self._src, *self._ref
                    )
                self._src, self._ref = (sy, su, sv), (ry, ru, rv)
                recs = [g[0] for g in group]
                # per-slot full-row handles, dispatched NOW so a worker
                # shortfall refetch is a pure transfer (no queued slice)
                rows_d = [prefixes_d[i] for i in range(take)]
                # group-wide host front-end time (pack + h2d enqueue,
                # everything before the step dispatch call) stamped on
                # every member, plus each frame's own classify/convert
                # from submit time — the step/upload boundary is
                # t_disp = pre-dispatch (see _Pending)
                t_disp = self._t_disp0
                grp_ms = (t_disp - t_d0) * 1e3
                for rec in recs:
                    rec.t_disp = t_disp
                    rec.h2d_ms += h2d_ms
                    rec.up_ms = rec.classify_ms + rec.convert_ms + grp_ms
                shared = self._pool.submit(
                    self._complete_batch, recs, self._pfx_slice(prefixes_d),
                    rows_d, denses_d, bufs_d,
                )
                for slot, rec in enumerate(recs):
                    rec.future = shared
                    rec.batch_slot = slot
        except Exception:
            # dispatch failed: frames not yet dispatched never produced
            # AUs. Drop their queued records (the frame_num gap is healed
            # by the forced IDR that the nulled ref causes next frame);
            # already-dispatched groups stay deliverable.
            dropped = {id(g[0]) for g in pend if g[0].future is None}
            self._inflight = deque(r for r in self._inflight if id(r) not in dropped)
            self._ref = None
            self._src = None
            self._reset_tile_cache()
            raise

    # Small-slice length for the delta downlink fetch (int16 words =
    # 32 KB): covers typical desktop deltas (~11 K live content). Exactly
    # TWO fetch sizes exist — this and the full buffer — because every
    # distinct slice shape is a fresh executable and this deployment
    # compiles via a remote service (seconds, occasionally flaky); a
    # finer-grained adaptive ladder stalls the steady state on compiles.
    PFX_SMALL = 1 << 14

    def _update_pfx_hint(self) -> None:
        """Recompute the delta-downlink fetch length from recent frames.

        Runs on completion workers AND the submit thread; the compute and
        the `_pfx_hint` store both happen under `_pfx_lock` — the hint
        used to be assigned from pool workers with no lock while
        `_pfx_slice` read it on the main thread (a torn read can't happen
        for an int, but a stale one mis-sized the next fetch and the
        deque iteration raced appends)."""
        with self._pfx_lock:
            want = max([2048] + [n * 3 // 2 for n in self._pfx_recent])
            self._pfx_hint = (
                self._pfx_small if want <= self._pfx_small else self._pfx_total
            )

    def _pfx_slice(self, prefix_d):
        """Hint-sized view of a fused delta downlink, dispatched from the
        MAIN thread right behind the step that produced it. Slicing is a
        device op: doing it in the completion worker would enqueue it
        after later groups' scans and stall the fetch behind them."""
        with self._pfx_lock:
            L = self._pfx_hint
        if prefix_d.ndim == 1:
            return prefix_d[:L] if L < self._pfx_total else prefix_d
        return prefix_d[:, :L] if L < self._pfx_total else prefix_d

    def _note_need(self, need: int) -> None:
        """Record one slice's live word count for the fetch-hint loop
        (the hint itself recomputes in _update_pfx_hint)."""
        with self._pfx_lock:
            self._pfx_recent.append(need)

    def _complete_sparse_p(self, fused, fused_d, dense_d, buf_d, rec):
        """One delta frame's fused slice -> finished slice NAL: spliced
        straight from device bits when the frame shipped them, sparse
        end-to-end otherwise (native packer when available).

        The shared per-slice flow (sparse_complete.complete_sparse_slice)
        reads the entropy meta (when enabled) and handles the bits
        splice, slice shortfall, row spill past the cap, and the
        ns > nscap dense-header fallback, for either sparse layout
        (bit-packed when self._density is set). fused_d is a per-frame
        FULL-row handle created at dispatch time: the shortfall refetch
        is then a pure transfer — slicing here (a device op) would queue
        behind scans dispatched since.
        Returns (au, skipped_mbs, t_start, t_unpacked, t_done, mode)."""
        t1 = time.perf_counter()
        au, skipped, tu, mode = complete_sparse_slice(
            fused, mbh=self._mbh, mbw=self._mbw, nscap=self._nscap,
            cap_rows=self._cap_delta, qp=rec.qp, frame_num=rec.frame_num,
            params=self.params, packed=self._density is not None,
            device_bits=self._entropy is not None,
            full_d=fused_d, buf_d=buf_d, dense_d=dense_d,
            link_bytes=self.link_bytes, prefix_bytes=fused.nbytes,
            note_need=self._note_need,
            ltr_ref=rec.ltr_ref, mark_ltr=rec.mark_ltr,
            mmco_evict=rec.mmco_evict, entropy_coder=self._coder)
        return au, skipped, t1, tu, time.perf_counter(), mode

    def _complete_batch(self, recs, pfx_slice_d, pfx_rows_d, denses_d, bufs_d):
        """Worker half for a delta group: ONE transfer of the pre-sliced
        prefix stack, then per-frame unpack + CAVLC pack FANNED OUT
        per-slot across the pack pool — frames in a group are
        independent slices and the native packer releases the GIL, so a
        12-frame group completes in ~one frame's pack time instead of
        twelve. Results come back indexed by batch_slot (submission
        order is preserved by the ordered gather)."""
        step_ms, t_ready = self._wait_step(recs[0], pfx_slice_d)
        prefixes = np.asarray(pfx_slice_d)
        # the group shares ONE transfer: step/fetch attribution is the
        # group's, stamped onto every member frame
        fetch_ms = (time.perf_counter() - t_ready) * 1e3
        # down_prefix/down_bits accounting happens per slot inside
        # complete_sparse_slice (only the meta read knows the mode)
        if self._pack_pool is not None and len(recs) > 1:
            futs = [
                self._pack_pool.submit(
                    self._complete_sparse_p, prefixes[slot], pfx_rows_d[slot],
                    denses_d[slot], bufs_d[slot], rec)
                for slot, rec in enumerate(recs)
            ]
            results = [f.result() for f in futs]
        else:
            results = [
                self._complete_sparse_p(prefixes[slot], pfx_rows_d[slot],
                                        denses_d[slot], bufs_d[slot], rec)
                for slot, rec in enumerate(recs)
            ]
        self._update_pfx_hint()
        return [(*r, step_ms, fetch_ms) for r in results]

    def submit(self, frame: np.ndarray, qp: int | None = None, meta=None,
               damage=None) -> list:
        """Dispatch one frame into the encode pipeline.

        Returns completed (au, stats, meta) tuples, oldest first — empty
        while the pipeline (depth `pipeline_depth`) is filling. Device
        dispatch is async, so frame N+1's host front-end (the fused
        classify/hash/convert scan) overlaps frame N's device step,
        downlink fetch and host CAVLC pack: the round-trip latency of
        the host↔device link is hidden at steady state.

        ``damage``: optional capture-layer dirty-rect hints ((x, y, w, h)
        pixel tuples, superset contract — FramePrep.scan) bounding the
        classification scan. None = full scan; hints never change the
        encoded bytes, only how much of the frame the classifier reads.
        """
        if qp is not None:
            self.set_qp(qp)
        idr = (
            self._force_idr
            or self.frame_index == 0
            or self._ref is None
            or (self.keyframe_interval > 0 and self._frames_since_idr >= self.keyframe_interval)
        )
        t0 = time.perf_counter()
        fi = get_injector()
        if fi is not None:
            # "frontend" chaos site: a fault in the classify/hash/convert
            # stage must surface like any encode failure (submit raises,
            # the next frame self-heals as a full-upload IDR) and must
            # never strand the frames already in flight
            fi.check("frontend")
        # classify on every frame (advances the previous-frame state even
        # across IDRs) but only short-circuit on P frames
        with tracer.span("classify"):
            kind, dirty_idx = self._classify(frame, damage)
        classify_ms = (time.perf_counter() - t0) * 1e3
        if telemetry.enabled:
            self._emit_classify_telemetry(kind, dirty_idx)
        batch_full = False
        orig_qp = self.qp
        # a scene CUT is the transition into a full-frame change; during
        # sustained full-frame motion (video playback, scrolling) the
        # rate controller owns QP and the boost must stay out of the loop
        scene_cut = kind == "full" and self._src is not None and self._prev_kind != "full"
        self._prev_kind = kind
        self._full_run = self._full_run + 1 if kind == "full" else 0
        # LTR scene cache: look for a remembered scene on ANY full frame
        # (window-switch pairs arrive back-to-back, so the second switch
        # is not a `scene_cut` transition; the sampled quick-reject keeps
        # this out of the sustained-motion hot path). Match against the
        # CURRENT slot table — the pending candidate commits below, which
        # matches the decoder applying this slice's MMCO only after
        # decoding it.
        ltr_hit = None
        ltr_stash = None
        if self.ltr_scenes and not idr and kind == "full" and self._src is not None:
            # reuse _classify's probe when it already ran the match this
            # frame (slot table unchanged in between: the pending
            # candidate commits only below)
            hit = (self._ltr_probe if self._ltr_probe != ()
                   else self._ltr_match(frame))
            if hit is not None:
                ltr_hit = hit
                ltr_stash = self._ltr_slots[hit[0]]
        # commit the pending scene candidate: this slice emits the MMCO 3
        # that marks the previous full frame as long-term
        mark_ltr = None
        if self.ltr_scenes and not idr and self._ltr_candidate is not None:
            cand = self._ltr_candidate
            self._ltr_candidate = None
            self._ltr_slots[cand["slot"]] = cand
            mark_ltr = cand["slot"]
        # decoder-DPB mirror: marking slices bypass the sliding window,
        # so they must evict stale short-terms themselves (MMCO 1) or the
        # DPB would exceed max_num_ref_frames=3
        mmco_evict: tuple = ()
        if idr:
            self._dpb_st = [0]
        else:
            cur_fn = self._frames_since_idr % 256
            if mark_ltr is not None:
                prev_fn = (cur_fn - 1) % 256
                if prev_fn in self._dpb_st:
                    self._dpb_st.remove(prev_fn)  # it becomes long-term
                mmco_evict = tuple(sorted(
                    ((cur_fn - s) % 256) - 1 for s in self._dpb_st))
                self._dpb_st = [cur_fn]
            else:
                lt_count = sum(1 for s in self._ltr_slots if s is not None)
                if len(self._dpb_st) + lt_count >= 3:  # sliding window
                    self._dpb_st.pop(0)
                self._dpb_st.append(cur_fn)
        if scene_cut and self.scene_qp_boost and ltr_hit is None:
            self.qp = min(51, self.qp + self.scene_qp_boost)
        if kind == "static" and not idr:
            # unchanged capture: all-skip P slice host-side — no upload,
            # no device step, no downlink (idle-desktop steady state).
            # The screen just went idle, so stop waiting for more group
            # members: dispatch any pending deltas now.
            self._flush_batch()
            slice_nal = self._allskip_slice(self._frames_since_idr % 256,
                                            mark_ltr=mark_ltr,
                                            mmco_evict=mmco_evict)
            rec = _Pending(
                kind="static", frame_index=self.frame_index, qp=self.qp,
                frame_num=self._frames_since_idr % 256, idr_pic_id=0,
                t0=t0, t1=time.perf_counter(), meta=meta, au=slice_nal,
                mark_ltr=mark_ltr, mmco_evict=mmco_evict,
                classify_ms=classify_ms, up_ms=classify_ms,
            )
        elif (
            not idr
            and kind == "delta"
            and self.frame_batch > 1
            and (len(dirty_idx[0]) if self._tcache is not None else len(dirty_idx))
            <= self.BATCH_BUCKETS[-1]
        ):
            # group candidate: convert the (post-remap) upload tiles NOW
            # — the capture buffer may be reused before dispatch, and
            # the cache split already ran in _classify in frame order —
            # then dispatch when the group fills or a non-groupable
            # frame arrives
            self._t_conv_ms = 0.0
            if self._tcache is not None:
                up_idx, pool_dst, pairs = dirty_idx
                yb, ub, vb = self._convert_tiles_timed(frame, up_idx, self._tile_w)
            else:
                up_idx, pool_dst, pairs = dirty_idx, None, None
                yb, ub, vb = self._convert_tiles_timed(frame, dirty_idx, self._tile_w)
            rec = _Pending(
                kind="pd", frame_index=self.frame_index, qp=self.qp,
                frame_num=self._frames_since_idr % 256, idr_pic_id=0,
                t0=t0, t1=0.0, meta=meta, mark_ltr=mark_ltr,
                mmco_evict=mmco_evict,
                n_up=len(up_idx),
                n_remap=len(pairs) if pairs is not None else 0,
                classify_ms=classify_ms, convert_ms=self._t_conv_ms,
            )
            self._batch_pend.append((rec, yb, ub, vb, up_idx, pool_dst, pairs))
            # the policy batch cap (set_batch_cap) bounds the group; its
            # default is frame_batch, the pre-policy behavior
            batch_full = len(self._batch_pend) >= self._batch_cap
        else:
            try:
                # dispatch order must match frame order: drain any pending
                # delta group before this frame touches device state
                self._flush_batch()
                t_d0 = time.perf_counter()
                self._t_conv_ms = 0.0
                self._t_h2d_ms = 0.0
                self._t_disp0 = 0.0
                hdr_d = None
                if idr:
                    if kind == "delta":
                        prefix_d, hdr_d, buf_d, ry, ru, rv = self._run_step_delta(
                            frame, dirty_idx, idr=True
                        )
                    elif kind == "static" and self._src is not None:
                        # forced IDR over unchanged content: zero upload
                        self._t_disp0 = time.perf_counter()
                        prefix_d, buf_d, ry, ru, rv = self._step_resident_i(
                            np.int32(self.qp), *self._src
                        )
                    else:
                        prefix_d, buf_d, ry, ru, rv = self._run_step_i(frame)
                    # recon never leaves the device: it is the P-frame
                    # reference (donated into the next P step)
                    self._ref = (ry, ru, rv)
                    rec = _Pending(
                        kind="i", frame_index=self.frame_index, qp=self.qp,
                        frame_num=0, idr_pic_id=self._idr_pic_id,
                        t0=t0, t1=0.0, meta=meta,
                        prefix_d=prefix_d, buf_d=buf_d,
                    )
                    self._frames_since_idr = 0
                    self._idr_pic_id = (self._idr_pic_id + 1) % 2
                    self._force_idr = False
                else:
                    ltr_ref = None
                    n_up = n_remap = 0
                    if ltr_hit is not None:
                        # scene restore: a few tiles against the slot's
                        # long-term reference instead of a full-frame
                        # upload + encode
                        prefix_d, hdr_d, buf_d, ry, ru, rv = self._run_step_ltr(
                            frame, ltr_hit[1], ltr_stash
                        )
                        pk, words_d = "pd", None
                        ltr_ref = ltr_hit[0]
                        n_up = len(ltr_hit[1])
                        self.ltr_restores += 1
                    elif kind == "delta":
                        prefix_d, hdr_d, buf_d, ry, ru, rv = self._run_step_delta(
                            frame, dirty_idx, idr=False
                        )
                        pk, words_d = "pd", None
                        if isinstance(dirty_idx, tuple):  # tile-cache split
                            n_up, n_remap = len(dirty_idx[0]), len(dirty_idx[2])
                        else:
                            n_up = len(dirty_idx)
                    else:
                        (pk, prefix_d, words_d, hdr_d, buf_d, ry, ru, rv) = (
                            self._run_step_p(frame)
                        )
                    # reassign IMMEDIATELY: _step_p donated the old buffers
                    self._ref = (ry, ru, rv)
                    rec = _Pending(
                        kind=pk,
                        frame_index=self.frame_index, qp=self.qp,
                        frame_num=self._frames_since_idr % 256, idr_pic_id=0,
                        t0=t0, t1=0.0, meta=meta,
                        prefix_d=prefix_d, buf_d=buf_d, hdr_d=hdr_d,
                        words_d=words_d, scene_cut=scene_cut,
                        n_up=n_up, n_remap=n_remap,
                        ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                        mmco_evict=mmco_evict,
                    )
                    if pk == "pd":
                        rec.pfx_slice_d = self._pfx_slice(prefix_d)
                # upload/step attribution boundary: everything since
                # flush UP TO the step dispatch call (conversion, tile
                # packing, h2d enqueue) is the host front-end cost of
                # THIS frame; the dispatch call itself counts as step
                # time (it blocks exactly when the device is the
                # bottleneck — see _Pending)
                rec.t_disp = self._t_disp0 or time.perf_counter()
                rec.classify_ms = classify_ms
                rec.convert_ms = self._t_conv_ms
                rec.h2d_ms = self._t_h2d_ms
                rec.up_ms = classify_ms + (rec.t_disp - t_d0) * 1e3
                # over-budget delta that fell back to full: seed the tile
                # pool from the now-resident planes so the NEXT frame of
                # a sustained scroll fits the delta path via remaps.
                # Only the first frames of a full-frame run seed (same
                # policy as the LTR stash): video playback would pay a
                # full hash + commit + device gather per frame for
                # content that never repeats
                if (
                    self._tcache is not None
                    and kind == "full"
                    and isinstance(dirty_idx, tuple)
                    and self._src is not None
                    and self._full_run <= 2
                ):
                    self._seed_pool(frame, dirty_idx[1], dirty_idx[2])
                # scene-stash bookkeeping: every full frame (IDR, full-P,
                # or restore) becomes the pending LTR candidate — window
                # switches arrive back-to-back, so mid-run frames are
                # boundaries too; the next slice's MMCO 3 commits it.
                # Restores refresh their own slot and become MRU; new
                # scenes go to the unprotected slot.
                if self.ltr_scenes:
                    if idr:
                        # DPB reset: the decoder dropped every reference
                        self._ltr_slots = [None, None]
                        self._ltr_candidate = None
                        self._ltr_mru = 0  # protect the IDR's scene slot
                        self._stash_candidate(frame, 0)
                    elif ltr_hit is not None:
                        self._ltr_mru = ltr_hit[0]
                        self._stash_candidate(frame, ltr_hit[0])
                    elif kind == "full" and self._full_run <= 2:
                        self._stash_candidate(frame, 1 - self._ltr_mru)
                if kind == "full" and ltr_hit is None:
                    # decay feed-forward: the frames after a full-frame
                    # change carry a frame-wide quantization-error tail,
                    # so the next delta fetches will be large — grow the
                    # hint NOW instead of stalling on shortfall refetches
                    with self._pfx_lock:
                        self._pfx_recent.append(self._pfx_total // 2)
                    self._update_pfx_hint()
                # start the downlink fetch + entropy pack on a worker NOW:
                # fetch ops overlap across threads on the relay
                # (tools/profile_rpc.py: 4 concurrent fetches ≈ cost of 1)
                rec.future = self._pool.submit(self._complete_work, rec)
            except Exception:
                # device failure after donation: the old reference (and
                # possibly source) planes are gone. Null both so the next
                # frame self-heals as a full-upload IDR instead of
                # desyncing the decoder. Older frames already in flight
                # stay queued — they were dispatched against an intact
                # chain and remain deliverable.
                self._ref = None
                self._src = None
                self._ltr_candidate = None  # forced IDR will clear slots
                self._reset_tile_cache()
                self.qp = orig_qp
                raise
        self.qp = orig_qp
        self.frame_index += 1
        self._frames_since_idr += 1
        self._inflight.append(rec)
        if batch_full:
            self._flush_batch()
        out = []
        # emit completions in submission order; block only when the
        # dispatched (device-side) pipeline is deeper than pipeline_depth
        while self._inflight:
            head = self._inflight[0]
            if head.au is not None or (head.future is not None and head.future.done()):
                out.append(self._emit(self._inflight.popleft()))
                continue
            # depth counts device ROUND TRIPS (distinct futures), not
            # frames: a grouped dispatch of K frames is one round trip
            dispatched = len({
                id(r.future)
                for r in self._inflight
                if r.future is not None and not r.future.done()
            })
            if dispatched > self.pipeline_depth:
                out.append(self._emit(self._inflight.popleft()))  # blocking wait
                continue
            # frame-count backstop: pipeline_depth ROUND TRIPS of grouped
            # dispatches plus the group being accumulated (with
            # frame_batch=1 this is the old depth+1 frame bound)
            if len(self._inflight) > (self.pipeline_depth + 1) * self.frame_batch:
                if head.future is None:
                    self._flush_batch()  # give the stalled head a future
                else:
                    out.append(self._emit(self._inflight.popleft()))
                continue
            break
        return out

    def flush(self) -> list:
        """Complete every in-flight frame (oldest first)."""
        self._flush_batch()
        out = []
        while self._inflight:
            out.append(self._emit(self._inflight.popleft()))
        return out

    def _emit(self, rec: "_Pending"):
        """Resolve one pending frame (waiting on its worker if needed)."""
        if rec.kind == "static":
            au = rec.au
            stats = FrameStats(
                frame_index=rec.frame_index, idr=False, qp=rec.qp,
                bytes=len(au), device_ms=(rec.t1 - rec.t0) * 1e3,
                pack_ms=0.0,
                skipped_mbs=(self._pad_h // 16) * (self._pad_w // 16),
                upload_kind="static",
                upload_ms=rec.up_ms, classify_ms=rec.classify_ms,
            )
            self.last_stats = stats
            return au, stats, rec.meta
        # A fetch/pack failure means the client never receives this frame:
        # encoding successors against its recon would silently desync the
        # decoder, so null the ref (forces IDR) and drop the pipeline.
        try:
            if rec.batch_slot >= 0:
                au, skipped, t1, tu, t2, mode, step_ms, fetch_ms = (
                    rec.future.result()[rec.batch_slot])
            else:
                au, skipped, t1, tu, t2, mode, step_ms, fetch_ms = rec.future.result()
        except Exception:
            self._ref = None
            self._src = None
            self._inflight.clear()
            self._batch_pend.clear()
            self._reset_tile_cache()
            raise
        # upload classification signals for the policy engine: "pd" was
        # a tile delta (dirty = uploads + remaps), everything else that
        # reached the device was a full-frame upload
        dirty = rec.n_up + rec.n_remap
        stats = FrameStats(
            frame_index=rec.frame_index, idr=rec.kind == "i", qp=rec.qp,
            bytes=len(au), device_ms=(t1 - rec.t0) * 1e3,
            pack_ms=(t2 - t1) * 1e3, skipped_mbs=skipped,
            scene_cut=rec.scene_cut,
            unpack_ms=(tu - t1) * 1e3, cavlc_ms=(t2 - tu) * 1e3,
            upload_ms=rec.up_ms, step_ms=step_ms, fetch_ms=fetch_ms,
            classify_ms=rec.classify_ms, convert_ms=rec.convert_ms,
            h2d_ms=rec.h2d_ms,
            downlink_mode=mode,
            upload_kind="delta" if rec.kind == "pd" else "full",
            dirty_frac=(min(1.0, dirty / self._ntiles)
                        if rec.kind == "pd" else 1.0),
            remap_frac=(rec.n_remap / dirty
                        if rec.kind == "pd" and dirty else 0.0),
        )
        self.last_stats = stats
        return au, stats, rec.meta

    def _wait_step(self, rec: "_Pending", handle) -> tuple[float, float]:
        """Block until the frame's downlink buffer is ready on device and
        return (step_ms, t_ready). Worker-side only — the main thread
        never waits — so the upload/step/fetch attribution costs one
        block_until_ready per frame, not a pipeline stall."""
        with tracer.span("step"):
            jax.block_until_ready(handle)
        t_ready = time.perf_counter()
        t_disp = rec.t_disp or rec.t0
        return (t_ready - t_disp) * 1e3, t_ready

    def _complete_work(self, rec: "_Pending"):
        """Worker-thread half: single-fetch downlink + unpack/assemble.
        Returns (au, skipped_mbs, t_start, t_unpacked, t_done, step_ms,
        fetch_ms) — the unpack/cavlc and upload/step/fetch splits feed
        the stage attribution in FrameStats."""
        if rec.kind == "pb":
            if self._coder == "cabac":
                return self._complete_toks(rec)
            return self._complete_bits(rec)
        if rec.kind == "pd":
            step_ms, t_ready = self._wait_step(rec, rec.pfx_slice_d)
            with tracer.span("fetch"):
                fused = np.asarray(rec.pfx_slice_d)
            fetch_ms = (time.perf_counter() - t_ready) * 1e3
            out = self._complete_sparse_p(fused, rec.prefix_d, rec.hdr_d,
                                          rec.buf_d, rec)
            self._update_pfx_hint()
            return (*out, step_ms, fetch_ms)
        hdr_words = self._hdr_words_i if rec.kind == "i" else self._hdr_words_p
        cap = CAP_ROWS
        step_ms, t_ready = self._wait_step(rec, rec.prefix_d)
        prefix = np.asarray(rec.prefix_d)
        fetch_ms = (time.perf_counter() - t_ready) * 1e3
        self.link_bytes.add("down_prefix", prefix.nbytes)
        header, data, n = split_prefix(prefix, hdr_words)
        if n > cap:  # rare: heavy frame spilled past the prefix
            rest = _fetch_rest(rec.buf_d, n, cap)
            self.link_bytes.add("down_spill", rest.nbytes)
            data = np.concatenate([data, rest])
        t1 = time.perf_counter()
        skipped = 0
        if rec.kind == "i":
            with tracer.span("unpack"):
                fc = unpack_i_compact(header, data, rec.qp)
            tu = time.perf_counter()
            # frame_num counts from the last IDR (7.4.3: gaps are
            # disallowed by our SPS)
            with tracer.span("pack"):
                if self._coder == "cabac":
                    slice_nal = pack_slice_cabac(
                        fc, self.params, frame_num=0, idr=True,
                        idr_pic_id=rec.idr_pic_id)
                else:
                    slice_nal = pack_slice_fast(
                        fc, self.params, frame_num=0, idr=True,
                        idr_pic_id=rec.idr_pic_id)
            au = self._headers + slice_nal
        else:
            with tracer.span("unpack"):
                pfc = unpack_p_compact(header, data, rec.qp)
            tu = time.perf_counter()
            skipped = int(pfc.skip.sum())
            with tracer.span("pack"):
                if self._coder == "cabac":
                    au = pack_slice_p_cabac(
                        pfc, self.params, rec.frame_num,
                        ltr_ref=rec.ltr_ref, mark_ltr=rec.mark_ltr,
                        mmco_evict=rec.mmco_evict)
                else:
                    au = pack_slice_p_fast(
                        pfc, self.params, frame_num=rec.frame_num,
                        ltr_ref=rec.ltr_ref, mark_ltr=rec.mark_ltr,
                        mmco_evict=rec.mmco_evict)
        # downlink_mode is a P-frame label ("" on the IDR row — keyframes
        # can never ship device bits, so they must not count as "coeff")
        mode = "coeff" if rec.kind != "i" else ""
        return au, skipped, t1, tu, time.perf_counter(), mode, step_ms, fetch_ms

    def _complete_bits(self, rec: "_Pending"):
        """Device-entropy P frame: fetch [meta ++ bit words], splice the
        slice header, done — no coefficient unpack, no host CAVLC."""
        step_ms, t_ready = self._wait_step(rec, rec.prefix_d)
        arr = np.asarray(rec.prefix_d)  # uint32: nbits, trailing, nskip, words...
        fetch_ms = (time.perf_counter() - t_ready) * 1e3
        self.link_bytes.add("down_bits", arr.nbytes)
        nbits, trailing, skipped = int(arr[0]), int(arr[1]), int(arr[2])
        if nbits > BITS_WORD_CAP * 32:
            # pathological frame overflowed the bit buffer: dense fallback
            header = np.asarray(rec.hdr_d)
            data = _fetch_rest(rec.buf_d, int(header[0]), 0)
            self.link_bytes.add("down_spill", header.nbytes + data.nbytes)
            t1 = time.perf_counter()
            pfc = unpack_p_compact(header, data, rec.qp)
            tu = time.perf_counter()
            au = pack_slice_p_fast(pfc, self.params, frame_num=rec.frame_num,
                                   ltr_ref=rec.ltr_ref, mark_ltr=rec.mark_ltr,
                                   mmco_evict=rec.mmco_evict)
            return (au, int(pfc.skip.sum()), t1, tu, time.perf_counter(),
                    "dense", step_ms, fetch_ms)
        need = (nbits + 31) // 32
        words = arr[3 : 3 + min(need, BITS_PREFIX_WORDS)]
        if need > BITS_PREFIX_WORDS:  # spill: one extra fetch
            with tracer.span("bits_fetch"):
                rest = _fetch_rest(rec.words_d, need, BITS_PREFIX_WORDS)
            self.link_bytes.add("down_bits_spill", rest.nbytes)
            words = np.concatenate([words, rest])
        t1 = time.perf_counter()
        au = assemble_p_nal(words, nbits, trailing, self.params, rec.frame_num,
                            rec.qp, ltr_ref=rec.ltr_ref, mark_ltr=rec.mark_ltr,
                            mmco_evict=rec.mmco_evict)
        return au, skipped, t1, t1, time.perf_counter(), "bits", step_ms, fetch_ms

    def _complete_toks(self, rec: "_Pending"):
        """Device-CABAC P frame: fetch [meta ++ skip bitmap ++ counts ++
        token words], interleave the skip/terminate bins and run the
        host arithmetic engine — no coefficient unpack, no host
        binarization."""
        step_ms, t_ready = self._wait_step(rec, rec.prefix_d)
        arr = np.asarray(rec.prefix_d)  # uint32: ntok, ns, nskip, ...
        fetch_ms = (time.perf_counter() - t_ready) * 1e3
        self.link_bytes.add("down_bits", arr.nbytes)
        ntok, ns, skipped = int(arr[0]), int(arr[1]), int(arr[2])
        if ntok > 2 * TOK_WORD_CAP:
            # pathological frame overflowed the token buffer: dense
            # fallback — still through the host CABAC coder (the PPS
            # pins entropy_coding_mode_flag for the whole stream)
            header = np.asarray(rec.hdr_d)
            data = _fetch_rest(rec.buf_d, int(header[0]), 0)
            self.link_bytes.add("down_spill", header.nbytes + data.nbytes)
            t1 = time.perf_counter()
            pfc = unpack_p_compact(header, data, rec.qp)
            tu = time.perf_counter()
            au = pack_slice_p_cabac(pfc, self.params, rec.frame_num,
                                    ltr_ref=rec.ltr_ref,
                                    mark_ltr=rec.mark_ltr,
                                    mmco_evict=rec.mmco_evict)
            return (au, int(pfc.skip.sum()), t1, tu, time.perf_counter(),
                    "dense", step_ms, fetch_ms)
        m = self._mbh * self._mbw
        sw = (m + 31) // 32
        cw = (m + 1) // 2
        skip_words = arr[3:3 + sw].astype(np.int64)
        skip = (((skip_words[:, None] >> np.arange(32)) & 1)
                .astype(bool).reshape(-1)[:m].reshape(self._mbh, self._mbw))
        counts = (np.ascontiguousarray(arr[3 + sw:3 + sw + cw])
                  .view(np.int16)[:ns].astype(np.int64))
        base = 3 + sw + cw
        need = (ntok + 1) // 2
        words = arr[base:base + min(need, TOK_PREFIX_WORDS)]
        if need > TOK_PREFIX_WORDS:  # spill: one extra fetch
            with tracer.span("bits_fetch"):
                rest = _fetch_rest(rec.words_d, need, TOK_PREFIX_WORDS)
            self.link_bytes.add("down_bits_spill", rest.nbytes)
            words = np.concatenate([words, rest])
        t1 = time.perf_counter()
        au = assemble_p_cabac_nal(words, ntok, counts, skip, self.params,
                                  rec.frame_num, rec.qp, ltr_ref=rec.ltr_ref,
                                  mark_ltr=rec.mark_ltr,
                                  mmco_evict=rec.mmco_evict)
        return (au, skipped, t1, t1, time.perf_counter(), "cabac", step_ms,
                fetch_ms)

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        """Synchronous encode ((H, W, 4) BGRx or (H, W, 3) RGB uint8 in,
        complete Annex-B access unit out; SPS/PPS prepended on IDR).
        Equivalent to submit() + flush() — no pipelining."""
        if self._inflight:
            # mixing submit() and encode_frame() would silently drop the
            # in-flight frames' access units (only this frame's AU is
            # returned) — a decoder-visible frame_num gap. Refuse.
            raise RuntimeError("encode_frame() called with frames in flight; use flush() first")
        outs = self.submit(frame, qp)
        outs.extend(self.flush())
        return outs[-1][0]

    def prewarm(self) -> None:
        """Compile the hot executables (IDR full, P full) before the live
        loop starts. The device-entropy P program in particular is a
        large XLA build (~tens of seconds cold); paying it at session
        start instead of on the first real frame keeps the stream from
        stalling. Leaves the encoder in a fresh-GOP state."""
        rng = np.random.default_rng(0)
        shape = (self.height, self.width, self.channels)
        f0 = rng.integers(0, 255, shape, np.uint8)
        f1 = rng.integers(0, 255, shape, np.uint8)
        self.encode_frame(f0)  # IDR full
        self.encode_frame(f1)  # P full (device-entropy path)
        # reset stream state: the next real frame starts a clean GOP
        self._force_idr = True
        self._ref = None
        self._src = None
        self._reset_tile_cache()
        if self._prep is not None:
            self._prep.reset()
        self._prev_frame = None
        self.frame_index = 0
        self._frames_since_idr = 0
        self._idr_pic_id = 0
        self._prev_kind = "full"

    def close(self) -> None:
        """Discard in-flight frames and stop the completion workers."""
        self._inflight.clear()
        self._batch_pend.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._pack_pool is not None:
            self._pack_pool.shutdown(wait=False, cancel_futures=True)
        self._upload_pool.shutdown(wait=False, cancel_futures=True)

    def recon_planes(self, frame: np.ndarray):
        """Debug helper: (recon_y, recon_u, recon_v) for a frame."""
        _, _, ry, ru, rv = self._run_step_i(frame)
        return (np.asarray(ry), np.asarray(ru), np.asarray(rv))


def make_frame_step(width: int, height: int, qp: int = 28):
    """(jittable fn, example args) for the driver's compile check: the
    steady-state P-frame step (ME + MC + transform), the flagship path."""
    pad_h = (height + 15) // 16 * 16
    pad_w = (width + 15) // 16 * 16

    def fn(frame, qp_arr, ry, ru, rv):
        return _device_step_p(
            frame, qp_arr, ry, ru, rv, pad_h=pad_h, pad_w=pad_w, channels=4
        )

    rng = np.random.default_rng(0)
    frame = rng.integers(0, 256, size=(height, width, 4), dtype=np.uint8)
    ry = rng.integers(0, 256, size=(pad_h, pad_w), dtype=np.uint8)
    ru = rng.integers(0, 256, size=(pad_h // 2, pad_w // 2), dtype=np.uint8)
    rv = rng.integers(0, 256, size=(pad_h // 2, pad_w // 2), dtype=np.uint8)
    return fn, (frame, np.int32(qp), ry, ru, rv)
