"""tpuh264enc — the TPU-native H.264 encoder element.

Replaces the reference's nvh264enc/vah264enc/x264enc/openh264enc rows of
the encoder matrix (gstwebrtc_app.py:260-367,475-508,609-665). The device
half (colorspace, prediction, transforms, quantization) is one jitted XLA
program per resolution (encoder_core.py); the host half is the C++ CAVLC
packer (native/cavlc_pack.cc). QP is a traced argument, so the GCC
congestion-control loop can retune bitrate every frame without
recompilation (reference: set_video_bitrate, gstwebrtc_app.py:1296).

Latency design: the device step returns int16 coefficient tensors (half
the PCIe traffic of int32); reconstruction planes stay on device for the
future P-frame path. Double-buffering (dispatch frame N+1 while N packs on
host) happens naturally because JAX dispatch is async — encode_frame
blocks only on the coefficient device→host copy.
"""

from __future__ import annotations

import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.models.h264.numpy_ref import PFrameCoeffs

from selkies_tpu.models.frameprep import FramePrep
from selkies_tpu.models.stats import FrameStats as _FrameStats
from selkies_tpu.models.h264.bitstream import StreamParams, write_pps, write_sps
from selkies_tpu.models.h264.compact import (
    i_header_words,
    p_header_words,
    split_prefix,
    unpack_i_compact,
    unpack_p_compact,
)
from selkies_tpu.models.h264.encoder_core import (
    encode_frame_p_planes,
    encode_frame_planes,
    fuse_downlink,
    pack_i_compact,
    pack_p_compact,
)
from selkies_tpu.models.h264.native import pack_slice_fast, pack_slice_p_fast
from selkies_tpu.ops.colorspace import bgrx_to_i420, rgb_to_i420

__all__ = ["TPUH264Encoder", "make_frame_step"]


def _convert_pad(frame, *, pad_h: int, pad_w: int, channels: int):
    """Packed frame -> padded I420 planes (device)."""
    if channels == 4:
        y, u, v = bgrx_to_i420(frame)
    else:
        y, u, v = rgb_to_i420(frame)
    h, w = y.shape
    if (pad_h, pad_w) != (h, w):
        y = jnp.pad(y, ((0, pad_h - h), (0, pad_w - w)), mode="edge")
        u = jnp.pad(u, ((0, (pad_h - h) // 2), (0, (pad_w - w) // 2)), mode="edge")
        v = jnp.pad(v, ((0, (pad_h - h) // 2), (0, (pad_w - w) // 2)), mode="edge")
    return y, u, v


# Data rows carried in the single-fetch prefix buffer. The relay prices
# transfers per op (~200 ms, tools/profile_rpc.py), so typical frames must
# complete in ONE fetch; frames with more nonzero rows pay a second fetch.
CAP_ROWS = 4096


def _device_step(frame, qp, *, pad_h: int, pad_w: int, channels: int):
    """Full IDR device path: packed frame -> padded planes -> compacted
    coefficient downlink (header, nonzero rows) + device-resident recon."""
    y, u, v = _convert_pad(frame, pad_h=pad_h, pad_w=pad_w, channels=channels)
    return _i_planes_step(y, u, v, qp)


def _i_planes_step(y, u, v, qp):
    out = encode_frame_planes(y, u, v, qp)
    header, buf = pack_i_compact(out)
    prefix = fuse_downlink(header, buf, CAP_ROWS)
    return prefix, buf, out["recon_y"], out["recon_u"], out["recon_v"]


def _device_step_p(frame, qp, ref_y, ref_u, ref_v, *, pad_h: int, pad_w: int, channels: int):
    """P-frame device path: convert, hierarchical motion search (±32)
    against the previous reconstruction (which never leaves the device),
    encode inter residuals, compact the downlink."""
    y, u, v = _convert_pad(frame, pad_h=pad_h, pad_w=pad_w, channels=channels)
    return _p_planes_step(y, u, v, qp, ref_y, ref_u, ref_v)


def _p_planes_step(y, u, v, qp, ref_y, ref_u, ref_v):
    out = encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp)
    header, buf = pack_p_compact(out)
    prefix = fuse_downlink(header, buf, CAP_ROWS)
    return prefix, buf, out["recon_y"], out["recon_u"], out["recon_v"]


def _fetch_rest(buf, n: int) -> np.ndarray:
    """Overflow path: rows [CAP_ROWS, n) in power-of-two buckets."""
    total = buf.shape[0]
    bucket = CAP_ROWS
    while bucket < n:
        bucket <<= 1
    if bucket >= total:
        return np.asarray(buf)[CAP_ROWS:]
    return np.asarray(buf[CAP_ROWS:bucket])


FrameStats = _FrameStats  # shared definition (models/stats.py)


@dataclass
class _Pending:
    """One in-flight frame in the encode pipeline."""

    kind: str  # "static" | "i" | "p"
    frame_index: int
    qp: int
    frame_num: int
    idr_pic_id: int
    t0: float
    t1: float
    meta: object = None
    au: bytes | None = None  # static only
    prefix_d: object = None
    buf_d: object = None
    future: object = None  # completion future (threaded fetch+unpack+pack)


class TPUH264Encoder:
    """Stateful per-stream encoder: frame in, Annex-B access unit out.

    `codec` identifies the bitstream for client decoder configuration
    (media.js maps it to a WebCodecs codec string).

    GOP policy mirrors the reference default (keyframe_distance=-1,
    __main__.py:473-475): one IDR, then P frames forever; new IDRs only on
    force_keyframe() (client PLI / stream restart) or an explicit
    keyframe_interval. The previous frame's reconstruction stays on the
    TPU between frames — only quantized coefficients cross PCIe.
    """

    codec = "h264"

    def __init__(
        self,
        width: int,
        height: int,
        qp: int = 28,
        fps: int = 60,
        channels: int = 4,
        keyframe_interval: int = 0,
        host_convert: bool = True,
        pipeline_depth: int = 2,
    ):
        self.width = width
        self.height = height
        self.fps = fps
        self.set_qp(qp)
        self.channels = channels
        self.keyframe_interval = int(keyframe_interval)  # 0 = infinite GOP
        self.params = StreamParams(width=width, height=height, qp=self.qp, fps=fps)
        self._headers = write_sps(self.params) + write_pps(self.params)
        self._pad_h = (height + 15) // 16 * 16
        self._pad_w = (width + 15) // 16 * 16
        # host_convert: BGRx->I420 on the host CPU (native/frameprep.cc) so
        # the upload is 1.5 B/px instead of 4 — the link is the bottleneck
        # (tools/profile_link.py). host_convert=False keeps conversion on
        # device (better when the device is PCIe-local and link-rich).
        self.pipeline_depth = max(0, int(pipeline_depth))
        self._prep: FramePrep | None = None
        if host_convert and channels == 4:
            # one conversion slot per possibly-in-flight async upload plus
            # one being written: depth+1 frames can be pipelined before
            # submit() blocks on the oldest completion
            self._prep = FramePrep(
                width, height, self._pad_w, self._pad_h,
                nslots=self.pipeline_depth + 2,
            )
        if self._prep is not None:
            self._step = jax.jit(_i_planes_step)
            self._step_p = jax.jit(_p_planes_step, donate_argnums=(4, 5, 6))
        else:
            self._step = jax.jit(
                lambda frame, qp: _device_step(
                    frame, qp, pad_h=self._pad_h, pad_w=self._pad_w, channels=channels
                )
            )
            self._step_p = jax.jit(
                lambda frame, qp, ry, ru, rv: _device_step_p(
                    frame, qp, ry, ru, rv,
                    pad_h=self._pad_h, pad_w=self._pad_w, channels=channels,
                ),
                donate_argnums=(2, 3, 4),
            )
        self._ref = None  # (recon_y, recon_u, recon_v) device arrays
        self._prev_frame: np.ndarray | None = None  # device-convert mode only
        self._inflight: deque = deque()
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, self.pipeline_depth + 1),
            thread_name_prefix="h264-complete",
        )
        mbh, mbw = self._pad_h // 16, self._pad_w // 16
        self._hdr_words_i = i_header_words(mbh, mbw)
        self._hdr_words_p = p_header_words(mbh, mbw)
        self._allskip: PFrameCoeffs | None = None
        self.frame_index = 0
        self._frames_since_idr = 0
        self._idr_pic_id = 0
        self._force_idr = True
        self.last_stats: FrameStats | None = None

    # -- live retune API (parity: set_video_bitrate path ends here) --

    def set_qp(self, qp: int) -> None:
        if not 0 <= qp <= 51:
            raise ValueError(f"qp {qp} out of range")
        self.qp = int(qp)

    def force_keyframe(self) -> None:
        self._force_idr = True

    # -- static-frame fast path ----------------------------------------

    def _is_static(self, frame: np.ndarray) -> bool:
        """True when the capture is byte-identical to the previous one —
        the dominant remote-desktop case; it then costs zero device work.

        Uses FramePrep's band memcmp when host conversion is on (early-exit
        per 16-row band, collision-free); otherwise a full compare against
        a kept copy. Either way the previous-frame state advances, which is
        safe because any encode failure nulls self._ref and forces an IDR,
        bypassing this path."""
        if self._prep is not None:
            bands = self._prep.dirty_bands(frame)
            return bands is not None and not bands.any()
        if self._prev_frame is None or self._prev_frame.shape != frame.shape:
            self._prev_frame = frame.copy()
            return False
        if np.array_equal(self._prev_frame, frame):
            return True
        np.copyto(self._prev_frame, frame)
        return False

    def _allskip_slice(self, frame_num: int) -> bytes:
        """P slice with every MB P_Skip: recon == ref exactly (zero MV,
        full-pel, no residual), so the device reference stays valid."""
        if self._allskip is None:
            mbh, mbw = self._pad_h // 16, self._pad_w // 16
            self._allskip = PFrameCoeffs(
                mvs=np.zeros((mbh, mbw, 2), np.int32),
                skip=np.ones((mbh, mbw), bool),
                luma_ac=np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32),
                chroma_dc=np.zeros((mbh, mbw, 2, 2, 2), np.int32),
                chroma_ac=np.zeros((mbh, mbw, 2, 2, 2, 4, 4), np.int32),
                qp=self.qp,
            )
        self._allskip.qp = self.qp
        return pack_slice_p_fast(self._allskip, self.params, frame_num=frame_num)

    # -- encoding --

    @staticmethod
    def _put(planes):
        # Explicit async device_put: passing host numpy straight into the
        # jitted call makes the runtime do a SYNCHRONOUS per-argument
        # transfer (~140 ms each over the axon relay); an explicit
        # device_put enqueues without a round trip (tools/profile_rpc.py).
        return [jax.device_put(np.asarray(p)) for p in planes]

    def _run_step_i(self, frame: np.ndarray):
        if self._prep is not None:
            y, u, v = self._put(self._prep.convert(frame))
            return self._step(y, u, v, np.int32(self.qp))
        return self._step(jax.device_put(frame), np.int32(self.qp))

    def _run_step_p(self, frame: np.ndarray):
        if self._prep is not None:
            y, u, v = self._put(self._prep.convert(frame))
            return self._step_p(y, u, v, np.int32(self.qp), *self._ref)
        return self._step_p(jax.device_put(frame), np.int32(self.qp), *self._ref)

    def submit(self, frame: np.ndarray, qp: int | None = None, meta=None) -> list:
        """Dispatch one frame into the encode pipeline.

        Returns completed (au, stats, meta) tuples, oldest first — empty
        while the pipeline (depth `pipeline_depth`) is filling. Device
        dispatch is async, so frame N+1's upload/compute overlaps frame
        N's downlink fetch and host CAVLC pack: the round-trip latency of
        the host↔device link is hidden at steady state.
        """
        if qp is not None:
            self.set_qp(qp)
        idr = (
            self._force_idr
            or self.frame_index == 0
            or self._ref is None
            or (self.keyframe_interval > 0 and self._frames_since_idr >= self.keyframe_interval)
        )
        t0 = time.perf_counter()
        # evaluate on every frame (advances the previous-frame state even
        # across IDRs) but only short-circuit on P frames
        if self._is_static(frame) and not idr:
            # unchanged capture: all-skip P slice host-side — no upload,
            # no device step, no downlink (idle-desktop steady state)
            slice_nal = self._allskip_slice(self._frames_since_idr % 256)
            rec = _Pending(
                kind="static", frame_index=self.frame_index, qp=self.qp,
                frame_num=self._frames_since_idr % 256, idr_pic_id=0,
                t0=t0, t1=time.perf_counter(), meta=meta, au=slice_nal,
            )
        else:
            try:
                if idr:
                    prefix_d, buf_d, ry, ru, rv = self._run_step_i(frame)
                    # recon never leaves the device: it is the P-frame
                    # reference (donated into the next P step)
                    self._ref = (ry, ru, rv)
                    rec = _Pending(
                        kind="i", frame_index=self.frame_index, qp=self.qp,
                        frame_num=0, idr_pic_id=self._idr_pic_id,
                        t0=t0, t1=0.0, meta=meta,
                        prefix_d=prefix_d, buf_d=buf_d,
                    )
                    self._frames_since_idr = 0
                    self._idr_pic_id = (self._idr_pic_id + 1) % 2
                    self._force_idr = False
                else:
                    prefix_d, buf_d, ry, ru, rv = self._run_step_p(frame)
                    # reassign IMMEDIATELY: _step_p donated the old buffers
                    self._ref = (ry, ru, rv)
                    rec = _Pending(
                        kind="p", frame_index=self.frame_index, qp=self.qp,
                        frame_num=self._frames_since_idr % 256, idr_pic_id=0,
                        t0=t0, t1=0.0, meta=meta,
                        prefix_d=prefix_d, buf_d=buf_d,
                    )
                # start the downlink fetch + entropy pack on a worker NOW:
                # fetch ops overlap across threads on the relay
                # (tools/profile_rpc.py: 4 concurrent fetches ≈ cost of 1)
                rec.future = self._pool.submit(self._complete_work, rec)
            except Exception:
                # device failure after donation: the old reference planes
                # are gone. Null the ref so the next frame self-heals as a
                # clean IDR instead of desyncing the decoder. Older frames
                # already in flight stay queued — they were dispatched
                # against an intact chain and remain deliverable.
                self._ref = None
                raise
        self.frame_index += 1
        self._frames_since_idr += 1
        self._inflight.append(rec)
        out = []
        # emit completions in submission order; block only beyond depth
        while self._inflight and (
            len(self._inflight) > self.pipeline_depth
            or self._inflight[0].future is None
            or self._inflight[0].future.done()
        ):
            out.append(self._emit(self._inflight.popleft()))
        return out

    def flush(self) -> list:
        """Complete every in-flight frame (oldest first)."""
        out = []
        while self._inflight:
            out.append(self._emit(self._inflight.popleft()))
        return out

    def _emit(self, rec: "_Pending"):
        """Resolve one pending frame (waiting on its worker if needed)."""
        if rec.kind == "static":
            au = rec.au
            stats = FrameStats(
                frame_index=rec.frame_index, idr=False, qp=rec.qp,
                bytes=len(au), device_ms=(rec.t1 - rec.t0) * 1e3,
                pack_ms=0.0,
                skipped_mbs=(self._pad_h // 16) * (self._pad_w // 16),
            )
            self.last_stats = stats
            return au, stats, rec.meta
        # A fetch/pack failure means the client never receives this frame:
        # encoding successors against its recon would silently desync the
        # decoder, so null the ref (forces IDR) and drop the pipeline.
        try:
            au, skipped, t1, t2 = rec.future.result()
        except Exception:
            self._ref = None
            self._inflight.clear()
            raise
        stats = FrameStats(
            frame_index=rec.frame_index, idr=rec.kind == "i", qp=rec.qp,
            bytes=len(au), device_ms=(t1 - rec.t0) * 1e3,
            pack_ms=(t2 - t1) * 1e3, skipped_mbs=skipped,
        )
        self.last_stats = stats
        return au, stats, rec.meta

    def _complete_work(self, rec: "_Pending"):
        """Worker-thread half: single-fetch downlink + unpack + CAVLC."""
        prefix = np.asarray(rec.prefix_d)
        hdr_words = self._hdr_words_i if rec.kind == "i" else self._hdr_words_p
        header, data, n = split_prefix(prefix, hdr_words)
        if n > CAP_ROWS:  # rare: heavy frame spilled past the prefix
            data = np.concatenate([data, _fetch_rest(rec.buf_d, n)])
        t1 = time.perf_counter()
        skipped = 0
        if rec.kind == "i":
            fc = unpack_i_compact(header, data, rec.qp)
            # frame_num counts from the last IDR (7.4.3: gaps are
            # disallowed by our SPS)
            slice_nal = pack_slice_fast(
                fc, self.params, frame_num=0, idr=True, idr_pic_id=rec.idr_pic_id
            )
            au = self._headers + slice_nal
        else:
            pfc = unpack_p_compact(header, data, rec.qp)
            skipped = int(pfc.skip.sum())
            au = pack_slice_p_fast(pfc, self.params, frame_num=rec.frame_num)
        return au, skipped, t1, time.perf_counter()

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        """Synchronous encode ((H, W, 4) BGRx or (H, W, 3) RGB uint8 in,
        complete Annex-B access unit out; SPS/PPS prepended on IDR).
        Equivalent to submit() + flush() — no pipelining."""
        if self._inflight:
            # mixing submit() and encode_frame() would silently drop the
            # in-flight frames' access units (only this frame's AU is
            # returned) — a decoder-visible frame_num gap. Refuse.
            raise RuntimeError("encode_frame() called with frames in flight; use flush() first")
        outs = self.submit(frame, qp)
        outs.extend(self.flush())
        return outs[-1][0]

    def close(self) -> None:
        """Discard in-flight frames and stop the completion workers."""
        self._inflight.clear()
        self._pool.shutdown(wait=False, cancel_futures=True)

    def recon_planes(self, frame: np.ndarray):
        """Debug helper: (recon_y, recon_u, recon_v) for a frame."""
        _, _, ry, ru, rv = self._run_step_i(frame)
        return (np.asarray(ry), np.asarray(ru), np.asarray(rv))


def make_frame_step(width: int, height: int, qp: int = 28):
    """(jittable fn, example args) for the driver's compile check: the
    steady-state P-frame step (ME + MC + transform), the flagship path."""
    pad_h = (height + 15) // 16 * 16
    pad_w = (width + 15) // 16 * 16

    def fn(frame, qp_arr, ry, ru, rv):
        return _device_step_p(
            frame, qp_arr, ry, ru, rv, pad_h=pad_h, pad_w=pad_w, channels=4
        )

    rng = np.random.default_rng(0)
    frame = rng.integers(0, 256, size=(height, width, 4), dtype=np.uint8)
    ry = rng.integers(0, 256, size=(pad_h, pad_w), dtype=np.uint8)
    ru = rng.integers(0, 256, size=(pad_h // 2, pad_w // 2), dtype=np.uint8)
    rv = rng.integers(0, 256, size=(pad_h // 2, pad_w // 2), dtype=np.uint8)
    return fn, (frame, np.int32(qp), ry, ru, rv)
