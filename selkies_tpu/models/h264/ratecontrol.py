"""CBR rate control: bitrate target (kbps) → per-frame QP.

Parity target: the reference's encoder-side rate control properties — CBR
mode, VBV buffer ≈ 1.5 frame-times, zero-latency tuning (gstwebrtc_app.py
:100-105 vbv computation, :1296-1412 set_video_bitrate) — re-implemented
as an explicit controller because the TPU encoder exposes QP, not a rate
knob. The GCC congestion-control estimate feeds set_bitrate() exactly like
rtpgccbwe's notify::estimated-bitrate drives set_video_bitrate(cc=True)
(gstwebrtc_app.py:1638-1655).

Model: leaky-bucket VBV. Each frame drains target_bits/fps; the encoded
frame fills its actual size. QP steps to keep fullness near the midpoint,
with a proportional term on the error and a fast-attack clamp when a frame
overshoots the whole buffer (scene change with intra-only streams).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class CbrRateController:
    bitrate_kbps: int
    fps: float
    vbv_frames: float = 1.5
    min_qp: int = 10
    max_qp: int = 51
    qp: int = 30
    # a keyframe (IDR or scene-cut P) legitimately spends several frame
    # budgets; the allowance forgives overshoot up to this many frames —
    # and ONLY overshoot, so a cheap keyframe is accounted like any
    # other frame instead of wiping accumulated VBV debt
    keyframe_budget_frames: float = 8.0
    _fullness: float = field(default=0.0, init=False)

    @property
    def frame_budget_bits(self) -> float:
        return self.bitrate_kbps * 1000.0 / self.fps

    @property
    def vbv_size_bits(self) -> float:
        return self.frame_budget_bits * self.vbv_frames

    @property
    def fullness(self) -> float:
        """VBV fullness normalized to the buffer size — the exported RC
        state (telemetry's selkies_rc_fullness): 0 is neutral, 1.0 one
        full VBV of accumulated debt, clamped to [-1, 4] by update()."""
        return self._fullness / max(self.vbv_size_bits, 1.0)

    def set_bitrate(self, bitrate_kbps: int) -> None:
        """Live retune (UI 'vb' message or GCC estimate)."""
        if bitrate_kbps <= 0:
            raise ValueError("bitrate must be positive")
        self.bitrate_kbps = int(bitrate_kbps)

    def set_framerate(self, fps: float) -> None:
        if fps <= 0:
            raise ValueError("fps must be positive")
        self.fps = float(fps)

    def frame_qp(self) -> int:
        """QP to use for the next frame."""
        return self.qp

    def update(self, frame_bytes: int, idr: bool = False) -> int:
        """Account an encoded frame; returns the QP for the next frame.
        `idr` covers any keyframe-sized event: IDRs and scene-cut P
        frames both receive the overshoot allowance."""
        bits = frame_bytes * 8.0
        budget = self.frame_budget_bits
        if idr:
            # forgive overshoot up to the keyframe allowance; never
            # reward a cheap keyframe (min against actual bits)
            budget = max(budget, min(bits, self.keyframe_budget_frames * budget))
        self._fullness += bits - budget
        self._fullness = max(-self.vbv_size_bits, min(self._fullness, 4 * self.vbv_size_bits))

        ratio = bits / max(budget, 1.0)
        # proportional step on the instantaneous error
        if ratio > 4.0:
            step = 4
        elif ratio > 2.0:
            step = 2
        elif ratio > 1.15:
            step = 1
        elif ratio < 0.25:
            step = -3
        elif ratio < 0.5:
            step = -2
        elif ratio < 0.85:
            step = -1
        else:
            step = 0
        # integral correction from buffer fullness
        if self._fullness > self.vbv_size_bits:
            step = max(step, 1) + 1
        elif self._fullness > 0.5 * self.vbv_size_bits:
            step = max(step, 1)
        elif self._fullness < -0.5 * self.vbv_size_bits and step >= 0:
            step -= 1
        self.qp = max(self.min_qp, min(self.max_qp, self.qp + step))
        return self.qp
