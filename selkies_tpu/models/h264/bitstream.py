"""H.264 (ISO 14496-10) high-level bitstream syntax: SPS, PPS, slice headers.

Host-side, tiny, and cold — headers are written once per stream / per frame.
The hot per-macroblock entropy coding lives in cavlc.py (Python reference)
and native/cavlc_pack.cc (production C++).

Profile choices (mirroring the reference's browser-compatible settings,
gstwebrtc_app.py:788-804 — constrained-baseline, byte-stream):
  * profile_idc 66 (Baseline), constraint_set0+1 → Constrained Baseline,
    which every browser hardware decoder accepts.
  * CAVLC entropy coding, frame MBs only, POC type 2, 1 reference frame.
  * Deblocking disabled via slice header for bit-exact encoder/decoder
    reconstruction (re-enabled once the Pallas deblock kernel lands).
"""

from __future__ import annotations

from dataclasses import dataclass

from selkies_tpu.utils.bits import BitWriter, annexb_nal

__all__ = ["StreamParams", "write_sps", "write_pps", "write_slice_header", "ipcm_frame"]

NAL_SLICE_NON_IDR = 1
NAL_SLICE_IDR = 5
NAL_SPS = 7
NAL_PPS = 8

LOG2_MAX_FRAME_NUM = 8  # MaxFrameNum = 256

# Slice types (all-slices-in-pic variants)
SLICE_P = 5
SLICE_I = 7


# (level_idc, MaxMBPS, MaxFS) from table A-1, ascending.
_LEVELS = (
    (10, 1485, 99), (11, 3000, 396), (12, 6000, 396), (13, 11880, 396),
    (20, 11880, 396), (21, 19800, 792), (22, 20250, 1620), (30, 40500, 1620),
    (31, 108000, 3600), (32, 216000, 5120), (40, 245760, 8192), (41, 245760, 8192),
    (42, 522240, 8704), (50, 589824, 22080), (51, 983040, 36864), (52, 2073600, 36864),
)


@dataclass(frozen=True)
class StreamParams:
    width: int
    height: int
    qp: int = 28
    fps: int = 60
    disable_deblocking: bool = True
    # "cavlc" (Baseline, profile_idc 66, the default — byte-identical to
    # the pre-CABAC streams) or "cabac" (Main, profile_idc 77,
    # entropy_coding_mode_flag=1). Selecting the coder here rather than
    # per-call keeps SPS/PPS/slice-header emission and the entropy
    # packers agreeing by construction.
    entropy_coder: str = "cavlc"

    def __post_init__(self) -> None:
        if self.width % 2 or self.height % 2:
            raise ValueError(f"{self.width}x{self.height}: 4:2:0 requires even dimensions")
        if self.width <= 0 or self.height <= 0:
            raise ValueError("dimensions must be positive")
        if self.entropy_coder not in ("cavlc", "cabac"):
            raise ValueError(f"unknown entropy coder {self.entropy_coder!r}")

    @property
    def cabac(self) -> bool:
        return self.entropy_coder == "cabac"

    @property
    def mb_width(self) -> int:
        return (self.width + 15) // 16

    @property
    def mb_height(self) -> int:
        return (self.height + 15) // 16

    @property
    def level_idc(self) -> int:
        """Smallest level whose MaxFS and MaxMBPS cover this stream (A-1)."""
        fs = self.mb_width * self.mb_height
        mbps = fs * self.fps
        for level, max_mbps, max_fs in _LEVELS:
            if fs <= max_fs and mbps <= max_mbps:
                return level
        return 62


def write_sps(p: StreamParams) -> bytes:
    w = BitWriter()
    if p.cabac:
        w.write_bits(77, 8)  # profile_idc: Main (CABAC requires >= Main)
        w.write_bits(0b01000000, 8)  # constraint_set1 (Main-conformant)
    else:
        w.write_bits(66, 8)  # profile_idc: Baseline
        w.write_bits(0b11000000, 8)  # constraint_set0+1 (constrained baseline)
    w.write_bits(p.level_idc, 8)
    w.write_ue(0)  # seq_parameter_set_id
    w.write_ue(LOG2_MAX_FRAME_NUM - 4)
    w.write_ue(2)  # pic_order_cnt_type: POC from frame_num (no B frames)
    # 3 reference frames: 1 short-term (the previous frame — the only
    # default prediction source) + 2 long-term scene slots for the
    # alt-tab LTR cache (encoder.py: window switches back to a
    # remembered scene encode as a tiny delta against its LTR instead
    # of a full-frame round trip). At 1080p a 3-frame DPB needs
    # MaxDpbMbs >= 24480, within level 4.0's 32768.
    w.write_ue(3)  # max_num_ref_frames
    w.write_bit(0)  # gaps_in_frame_num_value_allowed_flag
    w.write_ue(p.mb_width - 1)
    w.write_ue(p.mb_height - 1)
    w.write_bit(1)  # frame_mbs_only_flag
    w.write_bit(1)  # direct_8x8_inference_flag
    crop_r = p.mb_width * 16 - p.width
    crop_b = p.mb_height * 16 - p.height
    if crop_r or crop_b:
        w.write_bit(1)
        w.write_ue(0)  # left
        w.write_ue(crop_r // 2)
        w.write_ue(0)  # top
        w.write_ue(crop_b // 2)
    else:
        w.write_bit(0)
    w.write_bit(0)  # vui_parameters_present_flag
    w.rbsp_trailing_bits()
    return annexb_nal(3, NAL_SPS, w.get_bytes())


def write_pps(p: StreamParams) -> bytes:
    w = BitWriter()
    w.write_ue(0)  # pic_parameter_set_id
    w.write_ue(0)  # seq_parameter_set_id
    w.write_bit(1 if p.cabac else 0)  # entropy_coding_mode_flag
    w.write_bit(0)  # bottom_field_pic_order_in_frame_present_flag
    w.write_ue(0)  # num_slice_groups_minus1
    w.write_ue(0)  # num_ref_idx_l0_default_active_minus1
    w.write_ue(0)  # num_ref_idx_l1_default_active_minus1
    w.write_bit(0)  # weighted_pred_flag
    w.write_bits(0, 2)  # weighted_bipred_idc
    w.write_se(p.qp - 26)  # pic_init_qp_minus26
    w.write_se(0)  # pic_init_qs_minus26
    w.write_se(0)  # chroma_qp_index_offset
    w.write_bit(1)  # deblocking_filter_control_present_flag
    w.write_bit(0)  # constrained_intra_pred_flag
    w.write_bit(0)  # redundant_pic_cnt_present_flag
    w.rbsp_trailing_bits()
    return annexb_nal(3, NAL_PPS, w.get_bytes())


def write_slice_header(
    w: BitWriter,
    p: StreamParams,
    slice_type: int,
    frame_num: int,
    idr: bool,
    idr_pic_id: int = 0,
    first_mb: int = 0,
    slice_qp: int | None = None,
    ltr_ref: int | None = None,
    mark_ltr: int | None = None,
    mmco_evict: tuple = (),
    cabac_init_idc: int = 0,
) -> None:
    """Write the slice header into an open BitWriter (slice data follows).

    When ``p.cabac``, P slice headers carry ``cabac_init_idc`` (7.3.3 —
    I slices have none) and the caller must byte-align with
    ``cabac_alignment_one_bit`` (ones) before the arithmetic payload.
    Each slice initializes its own contexts, so the per-band slice
    layout needs no cross-band state.

    LTR scene-cache syntax (encoder.py's alt-tab optimization):
      * ltr_ref=j — predict this P slice from long-term reference j
        instead of the previous frame (ref_pic_list_modification with
        long_term_pic_num, 7.3.3.1). Used ONLY by scene-restore frames;
        the frame after one predicts the restore's recon through the
        default ref list (the restore is still short-term when that
        frame's ref list is built — MMCO marking applies post-decode).
      * mark_ltr=k — mark the PREVIOUS frame as long-term index k
        (adaptive dec_ref_pic_marking: MMCO 4 sizes the LT set to 2,
        MMCO 3 with difference_of_pic_nums_minus1=0 targets
        CurrPicNum-1, 7.4.3.3 / 8.2.5.4). Emitted one frame after a
        scene cut so the cut frame's recon is remembered while it is
        still resident short-term.
      * mmco_evict=(d, ...) — MMCO 1 operations (short-term → unused,
        difference_of_pic_nums_minus1 values) emitted alongside
        mark_ltr. Adaptive marking REPLACES the sliding window (8.2.5),
        so any extra short-term refs that accumulated while the DPB had
        slack must be evicted explicitly or the marked frame would push
        the DPB past max_num_ref_frames. The encoder mirrors the DPB
        and passes the stale picNum diffs here.
    """
    # first_mb positions a slice of a MULTI-SLICE picture (the band-
    # parallel encode, parallel/bands.py: band b starts at mb-row-offset
    # × mb_width). An out-of-picture value would produce a stream every
    # decoder rejects — fail at write time, where the band math is.
    if not 0 <= first_mb < p.mb_width * p.mb_height:
        raise ValueError(
            f"first_mb_in_slice {first_mb} outside picture "
            f"({p.mb_width}x{p.mb_height} MBs)")
    w.write_ue(first_mb)
    w.write_ue(slice_type)
    w.write_ue(0)  # pic_parameter_set_id
    w.write_bits(frame_num % (1 << LOG2_MAX_FRAME_NUM), LOG2_MAX_FRAME_NUM)
    if idr:
        w.write_ue(idr_pic_id)
    # pic_order_cnt_type == 2: nothing to write
    if slice_type in (SLICE_P, 0):
        w.write_bit(0)  # num_ref_idx_active_override_flag
        if ltr_ref is not None:
            w.write_bit(1)  # ref_pic_list_modification_flag_l0
            w.write_ue(2)   # modification_of_pic_nums_idc: long_term_pic_num
            w.write_ue(ltr_ref)
            w.write_ue(3)   # end of modification list
        else:
            w.write_bit(0)  # ref_pic_list_modification_flag_l0
    if idr:
        w.write_bit(0)  # no_output_of_prior_pics_flag
        w.write_bit(0)  # long_term_reference_flag
    elif mark_ltr is not None:
        w.write_bit(1)  # adaptive_ref_pic_marking_mode_flag
        for diff in mmco_evict:
            w.write_ue(1)   # MMCO 1: stale short-term -> unused
            w.write_ue(diff)
        w.write_ue(4)   # MMCO 4: size the long-term set
        w.write_ue(2)   # max_long_term_frame_idx_plus1: LT indices {0,1}
        w.write_ue(3)   # MMCO 3: short-term -> long-term
        w.write_ue(0)   # difference_of_pic_nums_minus1: previous frame
        w.write_ue(mark_ltr)  # long_term_frame_idx
        w.write_ue(0)   # MMCO 0: end
    else:
        # dec_ref_pic_marking is present whenever nal_ref_idc != 0 (7.3.3);
        # every slice we emit is a reference (annexb_nal ref_idc=3).
        w.write_bit(0)  # adaptive_ref_pic_marking_mode_flag
    if p.cabac and slice_type in (SLICE_P, 0):
        w.write_ue(cabac_init_idc)
    qp = p.qp if slice_qp is None else slice_qp
    w.write_se(qp - p.qp)  # slice_qp_delta relative to pic_init_qp
    if p.disable_deblocking:
        w.write_ue(1)  # disable_deblocking_filter_idc = 1 (off)
    else:
        w.write_ue(0)
        w.write_se(0)  # slice_alpha_c0_offset_div2
        w.write_se(0)  # slice_beta_offset_div2


def ipcm_frame(p: StreamParams, y, u, v, frame_num: int = 0, idr: bool = True) -> bytes:
    """Encode one frame entirely as I_PCM macroblocks (lossless, huge).

    Exists to (a) prove NAL/SPS/PPS/slice framing against a reference
    decoder independently of transform/entropy code, and (b) serve as an
    escape hatch for pathological content. y/u/v are numpy uint8 planes
    padded to macroblock multiples.
    """
    w = BitWriter()
    write_slice_header(w, p, SLICE_I, frame_num, idr=idr)
    mbw, mbh = p.mb_width, p.mb_height
    for mby in range(mbh):
        for mbx in range(mbw):
            w.write_ue(25)  # mb_type I_PCM
            w.byte_align(0)  # pcm_alignment_zero_bit
            yb = y[mby * 16 : mby * 16 + 16, mbx * 16 : mbx * 16 + 16]
            ub = u[mby * 8 : mby * 8 + 8, mbx * 8 : mbx * 8 + 8]
            vb = v[mby * 8 : mby * 8 + 8, mbx * 8 : mbx * 8 + 8]
            for row in yb:
                for s in row:
                    w.write_bits(int(s), 8)
            for blk in (ub, vb):
                for row in blk:
                    for s in row:
                        w.write_bits(int(s), 8)
    w.rbsp_trailing_bits()
    nal_type = NAL_SLICE_IDR if idr else NAL_SLICE_NON_IDR
    return annexb_nal(3, nal_type, w.get_bytes())
