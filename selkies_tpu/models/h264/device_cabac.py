"""CABAC binarization + context derivation ON DEVICE (ISSUE 19).

Second entropy backend behind the two-pass device split. The structure
pass is device_cavlc._frame_structure, UNCHANGED — skip map, mv
prediction, cbp, coded-block gating and the coding-order block relayout
are entropy-coder agnostic. Only emission differs: instead of VLC
codewords this module binarizes every syntax element into the 16-bit
token IR of cabac.py (REG/RUN/BYP/TERM) and derives each regular bin's
context index, data-parallel over the activity-compacted coded-MB
prefix. The sequential half of CABAC — arithmetic interval updates and
context-state adaptation — stays on host (native/cabac_pack.cc at
~5 ns/bin), fed one finished token stream per slice.

Emission reuses the CAVLC bit-packing machinery verbatim: every token
is a (value, nbits) slot with nbits ∈ {0, 16}, so _pack_pairs +
_merge_streams concatenate per-segment token runs exactly like VLC
codewords, and the merged bit stream is 16-bit aligned — the host views
the big-endian words as uint16 to recover the token sequence.

Division of labour per P slice:

* device — per coded MB, the "body" tokens (mb_type, mvd, cbp,
  mb_qp_delta, residual blocks) over the compacted prefix, bucket-padded
  like _emit_slice_bits, plus a per-coded-MB token COUNT;
* host — mb_skip_flag tokens (one per MB; CABAC P slices have no skip
  runs) and the per-MB end_of_slice terminate bins, interleaved with
  the device bodies by cumsum/repeat arithmetic (numpy, no Python loop);
* host — the arithmetic engine over the interleaved stream, then header
  splice + emulation prevention (finish_cabac_nal).

Output NALs are byte-identical to cabac.pack_slice_p_cabac
(tests/test_device_cabac_tokens.py). IDR/I slices use the host packer —
intra frames are rare in the streaming steady state and their CABAC
syntax (prefix mb_type, intra pred modes) isn't worth a device path.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from selkies_tpu.models.h264.cabac import (
    _LVL_OFF,
    _SIG_OFF,
    TOK_BYP,
    TOK_REG,
    TOK_RUN,
    TOK_TERM,
)
from selkies_tpu.models.h264.device_cavlc import (
    _CHROMA_ORDER,
    _LUMA_ORDER,
    _clz32,
    _compact_structure,
    _frame_structure,
    _merge_streams,
    _mv_pred_grid,
    _pack_pairs,
    bits_buckets,
)

__all__ = [
    "pack_p_slice_tokens",
    "pack_p_slice_tokens_active",
    "cabac_tok_words",
    "skip_flag_tokens",
    "interleave_p_tokens",
    "assemble_p_cabac_nal",
]


def cabac_tok_words(m: int) -> int:
    """Token-payload capacity in uint32 words for an m-MB slice. Tokens
    are 16-bit, roughly one per 1-2 bins; 64 words/MB (128 tokens) covers
    busy desktop residuals. Overflow falls back to the coefficient
    downlink exactly like the CAVLC bits cap."""
    return min(1 << 18, max(4096, 64 * int(m)))


# ---------------------------------------------------------------- token slots
#
# Every emitter below produces (value, nbits) slot arrays for
# _pack_pairs with nbits ∈ {0, 16}: a slot either contributes one whole
# uint16 token or nothing, which keeps the merged stream token-aligned.


def _ontok(on):
    return jnp.where(on, 16, 0).astype(jnp.int32)


def _byp_pair(v, nb, on):
    """One bypass group of nb (<= 20) bits as two <=10-bit BYP tokens,
    MSB-first. Chunking need not match TokenWriter.bypass_bits — engine
    output depends only on the bin sequence, not its grouping."""
    n_lo = jnp.clip(nb - 10, 0, 10)
    n_hi = jnp.clip(nb - n_lo, 0, 10)
    v_hi = (v >> n_lo) & 0x3FF
    v_lo = v & ((jnp.int32(1) << n_lo) - 1)
    hi_v = TOK_BYP | (n_hi << 2) | (v_hi << 6)
    lo_v = TOK_BYP | (n_lo << 2) | (v_lo << 6)
    return hi_v, _ontok(on & (n_hi > 0)), lo_v, _ontok(on & (n_lo > 0))


def _ueg_slots(v, k0: int, on):
    """UEGk escape binarization (9.3.2.3 suffix): unary prefix of j ones
    + stop 0, then a (k0+j)-bit suffix — as four BYP slots. The prefix
    length has a closed form, j = floor(log2(v/2^k0 + 1)), replacing the
    reference's subtract loop."""
    j = 31 - _clz32((v >> k0) + 1)
    pv = (jnp.int32(1) << (j + 1)) - 2          # j ones then a zero
    sv = jnp.clip(v - ((jnp.int32(1) << (k0 + j)) - (1 << k0)), 0, None)
    ph_v, ph_b, pl_v, pl_b = _byp_pair(pv, j + 1, on)
    sh_v, sh_b, sl_v, sl_b = _byp_pair(sv, k0 + j, on)
    return ph_v, ph_b, pl_v, pl_b, sh_v, sh_b, sl_v, sl_b


def _token_blocks(coeffs, cbf_ctx, cat: int):
    """Tokenize a batch of residual_block_cabac (7.3.5.3.3): (B, L)
    scan-order coefficients + (B,) coded_block_flag contexts ->
    (vals (B, S), bits (B, S)) slot arrays. Mirrors cabac._residual_tokens
    with the two serial-looking pieces vectorized:

    * the significance map is elementwise over scan positions (sig/last
      context increments are functions of the position alone);
    * the level contexts' eq1/gt1 counters are EXCLUSIVE CUMSUMS over
      the reverse-scan nonzero sequence — no recurrence — and the UEG0
      escape prefix/suffix have closed forms (_ueg_slots).

    Slot layout: [cbf][per scan pos i<L-1: sig, last][per level k:
    gt0, ones-run a, ones-run b, stop-zero, esc prefix hi/lo, esc
    suffix hi/lo, sign] = 1 + 2(L-1) + 9L slots."""
    B, L = coeffs.shape
    nz = coeffs != 0
    total = nz.sum(-1).astype(jnp.int32)
    cbf = total > 0
    # reverse-scan nonzero compaction — same one-hot contraction as
    # device_cavlc._encode_blocks (sorts are ~30 ms at frame scale)
    rev = coeffs[:, ::-1]
    nzr = rev != 0
    rank = jnp.cumsum(nzr, -1, dtype=jnp.int32) - 1
    oh = ((rank[:, :, None] == jnp.arange(L, dtype=jnp.int32)[None, None, :])
          & nzr[:, :, None]).astype(jnp.int32)
    val_rev = jnp.einsum("blk,bl->bk", oh, rev)
    pos_of = jnp.broadcast_to(
        (L - 1 - jnp.arange(L, dtype=jnp.int32))[None, :], (B, L))
    pos_rev = jnp.einsum("blk,bl->bk", oh, pos_of)
    last = pos_rev[:, 0]  # scan index of the last nonzero (valid iff cbf)

    cbf_v = (cbf.astype(jnp.int32) << 2) | (cbf_ctx << 3)
    cbf_b = jnp.full((B, 1), 16, jnp.int32)

    # significance map: bins at scan positions 0..min(last, L-2)
    i = jnp.arange(L - 1, dtype=jnp.int32)[None, :]
    inc = jnp.minimum(i, 2) if cat == 3 else i
    soff, loff = 105 + _SIG_OFF[cat], 166 + _SIG_OFF[cat]
    sig = nz[:, : L - 1]
    on = cbf[:, None] & (i <= jnp.minimum(last, L - 2)[:, None])
    sig_v = (sig.astype(jnp.int32) << 2) | ((soff + inc) << 3)
    isl = i == last[:, None]
    last_v = (isl.astype(jnp.int32) << 2) | ((loff + inc) << 3)
    sl_v = jnp.stack([jnp.broadcast_to(sig_v, sig.shape), last_v], -1)
    sl_b = jnp.stack([_ontok(on), _ontok(on & sig)], -1)

    # levels, reverse scan order (k-th slot = k-th nonzero from the end)
    mag = jnp.abs(val_rev)
    kvalid = jnp.arange(L, dtype=jnp.int32)[None, :] < total[:, None]
    m = jnp.clip(jnp.minimum(mag - 1, 14), 0, 14)
    gt1 = ((mag > 1) & kvalid).astype(jnp.int32)
    eq1 = ((mag == 1) & kvalid).astype(jnp.int32)
    gt1c = jnp.cumsum(gt1, -1) - gt1            # exclusive: count before k
    eq1c = jnp.cumsum(eq1, -1) - eq1
    base = 227 + _LVL_OFF[cat]
    c0 = base + jnp.where(gt1c > 0, 0, jnp.minimum(4, 1 + eq1c))
    c1 = base + 5 + jnp.minimum(4 - (1 if cat == 3 else 0), gt1c)
    s0_v = ((m > 0).astype(jnp.int32) << 2) | (c0 << 3)
    n1 = jnp.clip(m - 1, 0, 13)                 # TU ones at c1
    na = jnp.minimum(n1, 7)                     # RUN n field is 3 bits
    nb2 = n1 - na
    ra_v = TOK_RUN | (1 << 2) | (c1 << 3) | (na << 13)
    rb_v = TOK_RUN | (1 << 2) | (c1 << 3) | (nb2 << 13)
    z_v = c1 << 3                               # TU stop zero
    esc_on = kvalid & (mag - 1 >= 14)
    ev = jnp.clip(mag - 1 - 14, 0, None)
    ph_v, ph_b, pl_v, pl_b, sh_v, sh_b, su_v, su_b = _ueg_slots(ev, 0, esc_on)
    sgn_v = TOK_BYP | (1 << 2) | ((val_rev < 0).astype(jnp.int32) << 6)
    lev_v = jnp.stack(
        [s0_v, ra_v, rb_v, z_v, ph_v, pl_v, sh_v, su_v, sgn_v], -1)
    lev_b = jnp.stack(
        [_ontok(kvalid), _ontok(kvalid & (na > 0)), _ontok(kvalid & (nb2 > 0)),
         _ontok(kvalid & (m > 0) & (m < 14)), ph_b, pl_b, sh_b, su_b,
         _ontok(kvalid)], -1)

    vals = jnp.concatenate(
        [cbf_v[:, None], sl_v.reshape(B, 2 * (L - 1)), lev_v.reshape(B, 9 * L)], 1)
    bits = jnp.concatenate(
        [cbf_b, sl_b.reshape(B, 2 * (L - 1)), lev_b.reshape(B, 9 * L)], 1)
    return vals, bits


def _header_slots(s):
    """P macroblock header tokens (mb_type, mvd_l0 x/y, cbp, mb_qp_delta)
    for a (possibly compacted) structure -> (vals (A, 32), bits (A, 32)).
    Mirrors cabac.mb_tokens_p's pre-residual half; the mvd UEG3 prefix
    bins j=0..3 double as the TU terminator when |mvd| < 4 (bin = m > j,
    present iff m >= j), the j>=4 ones collapse into one RUN slot."""
    live = s["coded"]
    A = live.shape[0]
    vs, bs = [], []
    for ctx in (14, 15, 16):  # P_L0_16x16 mb_type: three 0 bins
        vs.append(jnp.full((A,), ctx << 3, jnp.int32))
        bs.append(_ontok(live))
    mvd = s["cb_mvd"]
    ctx0 = s["cb_mvd_ctx"]
    for comp in range(2):
        b = 40 if comp == 0 else 47
        d = mvd[:, comp]
        a = jnp.abs(d)
        m = jnp.minimum(a, 9)
        for j in range(4):
            ctx = ctx0[:, comp] if j == 0 else jnp.full((A,), b + 2 + j, jnp.int32)
            vs.append(((m > j).astype(jnp.int32) << 2) | (ctx << 3))
            bs.append(_ontok(live & (m >= j)))
        n = jnp.clip(m - 4, 0, 5)               # prefix ones at positions 4..8
        vs.append(TOK_RUN | (1 << 2) | ((b + 6) << 3) | (n << 13))
        bs.append(_ontok(live & (n > 0)))
        vs.append(jnp.full((A,), (b + 6) << 3, jnp.int32))  # TU stop for m in 4..8
        bs.append(_ontok(live & (m >= 4) & (m < 9)))
        esc_on = live & (a >= 9)
        ph_v, ph_b, pl_v, pl_b, sh_v, sh_b, su_v, su_b = _ueg_slots(
            jnp.clip(a - 9, 0, None), 3, esc_on)
        vs += [ph_v, pl_v, sh_v, su_v]
        bs += [ph_b, pl_b, sh_b, su_b]
        vs.append(TOK_BYP | (1 << 2) | ((d < 0).astype(jnp.int32) << 6))
        bs.append(_ontok(live & (a > 0)))
    ctx6, bins6 = s["cb_cbp_ctx"], s["cb_cbp_bins"]
    for k in range(6):
        vs.append((bins6[:, k] << 2) | (ctx6[:, k] << 3))
        bs.append(_ontok(live if k < 5 else (live & s["cb_cbp5"])))
    vs.append(jnp.full((A,), 60 << 3, jnp.int32))  # mb_qp_delta = se(0)
    bs.append(_ontok(live & s["cb_qpd"]))
    return jnp.stack(vs, -1), jnp.stack(bs, -1)


# ------------------------------------------------------------ structure extras


def _shift_inc(grid):
    """condTermFlagA + 2*condTermFlagB for every cell of a cbf grid —
    left/top shifted reads with zero edges (9.3.3.1.1.9 inter rules:
    unavailable or skipped neighbours read 0)."""
    left = jnp.pad(grid, ((0, 0), (1, 0)))[:, :-1]
    top = jnp.pad(grid, ((1, 0), (0, 0)))[:-1]
    return left + 2 * top


def _cabac_structure(out):
    """_frame_structure + the CABAC context columns, all full-grid
    elementwise work (the cheap pass). New per-MB keys, each compactable
    by the same row scatter as the CAVLC keys:

      cb_mvd (M,2)        quarter-pel mvd
      cb_mvd_ctx (M,2)    first-bin ctx (40/47 + neighbour-|mvd|-sum inc)
      cb_cbp_ctx/bins (M,6), cb_cbp5 (M,)   cbp bin contexts/values
      cb_qpd (M,)         mb_qp_delta present
      cb_cbf_luma (M,16), cb_cbf_cdc (M,2), cb_cbf_cac (M,8)
                          coded_block_flag ctx per block, coding order
    """
    s = _frame_structure(out)
    skip = out["skip"]
    mbh, mbw = skip.shape
    M = mbh * mbw
    coded2 = ~skip
    cbp_l, cbp_c = s["cbp_luma"], s["cbp_chroma"]

    pred = _mv_pred_grid(out["mvs"], skip)
    mvd = 4 * (out["mvs"].astype(jnp.int32) - pred)
    amvd = jnp.where(coded2[..., None], jnp.abs(mvd), 0)
    ssum = (jnp.pad(amvd, ((0, 0), (1, 0), (0, 0)))[:, :-1]
            + jnp.pad(amvd, ((1, 0), (0, 0), (0, 0)))[:-1])
    inc = jnp.where(ssum < 3, 0, jnp.where(ssum > 32, 2, 1))
    s["cb_mvd"] = mvd.reshape(M, 2)
    s["cb_mvd_ctx"] = (jnp.asarray([40, 47], jnp.int32) + inc).reshape(M, 2)

    # cbp bin contexts: neighbour patterns read 15 (luma) / 0 (chroma)
    # when unavailable, 0 at skip MBs (cabac._cbp_tokens)
    clg = jnp.where(coded2, cbp_l, 0)
    ccg = jnp.where(coded2, cbp_c, 0)
    col = jnp.arange(mbw, dtype=jnp.int32)[None, :]
    row = jnp.arange(mbh, dtype=jnp.int32)[:, None]
    cl_left = jnp.where(col > 0, jnp.pad(clg, ((0, 0), (1, 0)))[:, :-1], 15)
    cl_top = jnp.where(row > 0, jnp.pad(clg, ((1, 0), (0, 0)))[:-1], 15)
    cc_left = jnp.where(col > 0, jnp.pad(ccg, ((0, 0), (1, 0)))[:, :-1], 0)
    cc_top = jnp.where(row > 0, jnp.pad(ccg, ((1, 0), (0, 0)))[:-1], 0)
    b0, b1 = cbp_l & 1, (cbp_l >> 1) & 1
    b2, b3 = (cbp_l >> 2) & 1, (cbp_l >> 3) & 1
    ctx6 = jnp.stack([
        73 + (1 - ((cl_left >> 1) & 1)) + 2 * (1 - ((cl_top >> 2) & 1)),
        73 + (1 - b0) + 2 * (1 - ((cl_top >> 3) & 1)),
        73 + (1 - ((cl_left >> 3) & 1)) + 2 * (1 - b0),
        73 + (1 - b2) + 2 * (1 - b1),
        77 + (cc_left > 0).astype(jnp.int32) + 2 * (cc_top > 0).astype(jnp.int32),
        81 + (cc_left == 2).astype(jnp.int32) + 2 * (cc_top == 2).astype(jnp.int32),
    ], -1)
    bins6 = jnp.stack([
        b0, b1, b2, b3,
        (cbp_c > 0).astype(jnp.int32), (cbp_c == 2).astype(jnp.int32)], -1)
    s["cb_cbp_ctx"] = ctx6.reshape(M, 6)
    s["cb_cbp_bins"] = bins6.reshape(M, 6)
    s["cb_cbp5"] = (cbp_c > 0).reshape(M)
    s["cb_qpd"] = ((cbp_l | cbp_c) > 0).reshape(M)

    # coded_block_flag contexts from the gated TotalCoeff grids the
    # structure pass already built (transmitted cbf == TotalCoeff > 0;
    # absent blocks hold 0, exactly condTermFlagN)
    luma_perm = jnp.asarray(
        np.asarray(_LUMA_ORDER)[:, 1] * 4 + np.asarray(_LUMA_ORDER)[:, 0])
    lcbf = (s["luma_tc_flat"] > 0).astype(jnp.int32)
    s["cb_cbf_luma"] = jnp.take(
        (93 + _shift_inc(lcbf)).reshape(mbh, 4, mbw, 4)
        .transpose(0, 2, 1, 3).reshape(M, 16), luma_perm, axis=1)
    ch_perm = jnp.asarray(
        np.asarray(_CHROMA_ORDER)[:, 1] * 2 + np.asarray(_CHROMA_ORDER)[:, 0])
    ccbf = (s["ch_tc_flat"] > 0).astype(jnp.int32)
    s["cb_cbf_cac"] = jnp.take(
        jnp.stack([101 + _shift_inc(ccbf[c]) for c in range(2)])
        .reshape(2, mbh, 2, mbw, 2).transpose(1, 3, 0, 2, 4).reshape(M, 2, 4),
        ch_perm, axis=2).reshape(M, 8)
    cdc = out["chroma_dc"].reshape(mbh, mbw, 2, 4)
    dc_cbf = ((cdc != 0).any(-1)
              & (coded2 & (cbp_c >= 1))[..., None]).astype(jnp.int32)
    s["cb_cbf_cdc"] = jnp.stack(
        [97 + _shift_inc(dc_cbf[..., c]) for c in range(2)], -1).reshape(M, 2)
    return s


# per-MB arrays the CABAC emission path needs compacted ("coded" rides
# along as the live mask: compaction makes it the dense ns-prefix)
CABAC_COMPACT_KEYS = (
    "coded", "luma_blocks", "luma_emit", "cdc_blocks", "cdc_emit",
    "ch_blocks", "ch_emit", "cb_mvd", "cb_mvd_ctx", "cb_cbp_ctx",
    "cb_cbp_bins", "cb_cbp5", "cb_qpd", "cb_cbf_luma", "cb_cbf_cdc",
    "cb_cbf_cac",
)


def _emit_slice_tokens(s, word_cap: int):
    """The expensive half over a compacted structure: tokenize every
    block + header, pack each MB's 27 segments (header, 16 luma, 2
    chroma DC, 8 chroma AC — same segment split as _emit_slice_bits) and
    merge into one token-aligned bit stream. Returns (words, ntok,
    counts) with counts the per-slot token count (zero on padded
    slots)."""
    U = s["coded"].shape[0]
    lv, lb = _token_blocks(
        s["luma_blocks"].reshape(U * 16, 16), s["cb_cbf_luma"].reshape(-1), 2)
    lb = jnp.where(s["luma_emit"].reshape(-1)[:, None], lb, 0)
    dv, db = _token_blocks(
        s["cdc_blocks"].reshape(U * 2, 4), s["cb_cbf_cdc"].reshape(-1), 3)
    db = jnp.where(s["cdc_emit"].reshape(-1)[:, None], db, 0)
    cv, cb = _token_blocks(
        s["ch_blocks"].reshape(U * 8, 15), s["cb_cbf_cac"].reshape(-1), 4)
    cb = jnp.where(s["ch_emit"].reshape(-1)[:, None], cb, 0)
    hv, hb = _header_slots(s)

    HW, DW, CW, BW = 16, 22, 82, 88  # ceil(16*S/32) per segment kind
    hdr_w, hdr_n = _pack_pairs(hv, hb, HW)
    luma_w, luma_n = _pack_pairs(lv, lb, BW)
    cdc_w, cdc_n = _pack_pairs(dv, db, DW)
    cac_w, cac_n = _pack_pairs(cv, cb, CW)
    seg_words = jnp.concatenate([
        jnp.pad(hdr_w.reshape(U, 1, HW), ((0, 0), (0, 0), (0, BW - HW))),
        luma_w.reshape(U, 16, BW),
        jnp.pad(cdc_w.reshape(U, 2, DW), ((0, 0), (0, 0), (0, BW - DW))),
        jnp.pad(cac_w.reshape(U, 8, CW), ((0, 0), (0, 0), (0, BW - CW))),
    ], axis=1).reshape(U * 27, BW)
    seg_bits = jnp.concatenate([
        hdr_n.reshape(U, 1), luma_n.reshape(U, 16), cdc_n.reshape(U, 2),
        cac_n.reshape(U, 8)], axis=1).reshape(U * 27)
    words, total = _merge_streams(seg_words, seg_bits, word_cap)
    counts = (hdr_n + luma_n.reshape(U, 16).sum(1) + cdc_n.reshape(U, 2).sum(1)
              + cac_n.reshape(U, 8).sum(1)) >> 4
    return words, total >> 4, counts


def pack_p_slice_tokens(out, word_cap: int | None = None):
    """Full-grid device tokenizer (every MB pays) — the fixed-shape
    oracle for tests and the profiler. Returns (words (word_cap,)
    uint32 big-endian bit order, ntok, counts (M,), ns): the first ns
    entries of counts are the coded MBs' body token counts in raster
    order."""
    s = _cabac_structure(out)
    M = s["coded"].shape[0]
    sc = _compact_structure(s, M, keys=CABAC_COMPACT_KEYS)
    words, ntok, counts = _emit_slice_tokens(
        sc, cabac_tok_words(M) if word_cap is None else word_cap)
    return words, ntok, counts, s["ns"]


def pack_p_slice_tokens_active(out, word_cap: int | None = None,
                               buckets: tuple[int, ...] | None = None):
    """Activity-proportional device CABAC: the emission half runs over a
    bucket-compacted coded-MB prefix selected ON DEVICE via lax.switch —
    the same discipline (and the same buckets) as
    pack_p_slice_bits_active. Unlike the CAVLC path the top bucket also
    compacts: counts must land in a dense prefix for the host
    interleave, and every branch pads them to buckets[-1] so the switch
    arms agree on shapes. Token output is identical for every bucket
    (compaction preserves raster order; padded slots emit zero bits)."""
    s = _cabac_structure(out)
    M = s["coded"].shape[0]
    if word_cap is None:
        word_cap = cabac_tok_words(M)
    if buckets is None:
        buckets = bits_buckets(M)
    A_max = buckets[-1]
    ns = s["ns"]

    def _run(A: int):
        sc = _compact_structure(s, A, keys=CABAC_COMPACT_KEYS)
        words, ntok, counts = _emit_slice_tokens(sc, word_cap)
        return words, ntok, jnp.pad(counts, (0, A_max - A))

    if len(buckets) == 1:
        words, ntok, counts = _run(buckets[0])
    else:
        idx = jnp.clip(
            jnp.searchsorted(jnp.asarray(buckets, jnp.int32), ns, side="left"),
            0, len(buckets) - 1)
        words, ntok, counts = jax.lax.switch(
            idx, [(lambda _, A=b: _run(A)) for b in buckets], jnp.int32(0))
    return words, ntok, counts, ns


# ---------------------------------------------------------------------------
# Host half: skip/terminate interleave, engine, NAL assembly
# ---------------------------------------------------------------------------


def skip_flag_tokens(skip: np.ndarray) -> np.ndarray:
    """mb_skip_flag REG tokens for every MB of a slice, raster order —
    ctx 11 + (#available-and-not-skipped of {left, top})."""
    sk = np.asarray(skip, bool)
    inc = np.zeros(sk.shape, np.int32)
    inc[:, 1:] += ~sk[:, :-1]
    inc[1:, :] += ~sk[:-1, :]
    return (TOK_REG | (sk.astype(np.int32) << 2)
            | ((11 + inc) << 3)).reshape(-1).astype(np.uint16)


def interleave_p_tokens(body: np.ndarray, counts: np.ndarray,
                        skip: np.ndarray) -> np.ndarray:
    """Splice per-MB streams into slice order without a Python loop:
    for each MB [skip_flag] [body tokens if coded] [end_of_slice], the
    last MB's end_of_slice being the TERM(1) flush. `body` is the device
    stream (coded-MB bodies concatenated in raster order), `counts` the
    per-coded-MB token counts (ns entries)."""
    sk = np.asarray(skip, bool).reshape(-1)
    m = sk.size
    cnt = np.zeros(m, np.int64)
    cnt[~sk] = np.asarray(counts, np.int64)
    stride = cnt + 2                      # skip flag + body + terminate
    starts = np.zeros(m, np.int64)
    np.cumsum(stride[:-1], out=starts[1:])
    out = np.empty(int(stride.sum()), np.uint16)
    out[starts] = skip_flag_tokens(skip)
    out[starts + 1 + cnt] = TOK_TERM
    tot = int(cnt.sum())
    if tot:
        body_counts = cnt[~sk]
        excl = np.cumsum(body_counts) - body_counts
        pos = (np.repeat(starts[~sk] + 1 - excl, body_counts)
               + np.arange(tot, dtype=np.int64))
        out[pos] = body[:tot]
    out[-1] = TOK_TERM | (1 << 2)         # end-of-slice flush
    return out


def tokens_from_words(words: np.ndarray, ntok: int) -> np.ndarray:
    """Recover the uint16 token sequence from device words: every slot
    is 16 bits, so the big-endian word stream IS the token stream."""
    nw = (int(ntok) + 1) // 2
    return (np.ascontiguousarray(words[:nw]).astype(">u4")
            .view(">u2").astype(np.uint16)[: int(ntok)])


def assemble_p_cabac_nal(words: np.ndarray, ntok: int, counts: np.ndarray,
                         skip: np.ndarray, p, frame_num: int, qp: int,
                         ltr_ref: int | None = None,
                         mark_ltr: int | None = None,
                         mmco_evict: tuple = (),
                         first_mb: int = 0,
                         cabac_init_idc: int = 0) -> bytes:
    """Finish a P slice from device tokens: interleave skip/terminate
    bins, run the arithmetic engine, splice after the host-written
    header. Byte-identical to cabac.pack_slice_p_cabac for the same
    inputs; first_mb/cabac_init_idc position a band slice exactly like
    assemble_p_nal does for CAVLC."""
    from selkies_tpu.models.h264.bitstream import (
        NAL_SLICE_NON_IDR, SLICE_P, write_slice_header)
    from selkies_tpu.models.h264.cabac import finish_cabac_nal
    from selkies_tpu.utils.bits import BitWriter

    toks = interleave_p_tokens(tokens_from_words(words, ntok), counts, skip)
    w = BitWriter()
    write_slice_header(w, p, SLICE_P, frame_num, idr=False, slice_qp=qp,
                       ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                       mmco_evict=mmco_evict, first_mb=first_mb,
                       cabac_init_idc=cabac_init_idc)
    return finish_cabac_nal(w, toks, qp, SLICE_P, cabac_init_idc,
                            NAL_SLICE_NON_IDR)
