"""Host-side unpack of the compact downlink (encoder_core.pack_*_compact).

Scatters the fetched nonzero rows back into dense coefficient arrays and
wraps them as FrameCoeffs / PFrameCoeffs, so the CAVLC packers are fed
bit-identical inputs to the dense path (tests assert exact equality).
Cost: a boolean unpack over M*26 flags + one fancy-index scatter of the
nonzero rows — a few ms at 1080p, far below the 6.4 MB dense fetch it
replaces on the tunnel/PCIe.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass

import numpy as np

# The int32 views over device-bitcast int16 streams below assume the host
# lane order matches TPU bitcast_convert_type (little-endian). Fail loudly
# on an exotic platform instead of decoding garbage lengths (a plain
# assert would vanish under python -O).
if sys.byteorder != "little":
    raise RuntimeError("compact downlink decode requires a little-endian host")

from selkies_tpu.models.h264.encoder_core import (
    I_ENTRIES,
    I_ROW_CHROMA,
    I_ROW_DC_C,
    I_ROW_LUMA,
    P_ENTRIES,
    P_ROW_CHROMA,
    P_ROW_DC,
)
from selkies_tpu.models.h264.native import derive_skip_mvs_fast
from selkies_tpu.models.h264.numpy_ref import FrameCoeffs, PFrameCoeffs


def p_header_words(mbh: int, mbw: int) -> int:
    m = mbh * mbw
    return 4 + 2 * m + (m + 31) // 32


def i_header_words(mbh: int, mbw: int) -> int:
    return 4 + 2 * mbh * mbw


def split_prefix(prefix: np.ndarray, header_words: int):
    """Undo encoder_core.fuse_downlink: (header int32, data rows (cap, 16)
    int16, n). The int32→int16 bit-cast is an in-memory reinterpretation,
    so viewing the int16 pairs back as int32 is exact."""
    hdr16 = np.ascontiguousarray(prefix[: 2 * header_words])
    header = hdr16.view(np.int32)
    data = prefix[2 * header_words :].reshape(-1, 16)
    return header, data, int(header[0])


def _flags_from_bitmap(words: np.ndarray, entries: int) -> np.ndarray:
    return ((words[:, None] >> np.arange(entries, dtype=np.int32)) & 1).astype(bool)


def _scatter_rows(flags: np.ndarray, data: np.ndarray) -> np.ndarray:
    """flags (M, E); data (>=n, 16) -> dense rows (M, E, 16) int16."""
    m, e = flags.shape
    flat_idx = np.flatnonzero(flags.reshape(-1))
    rows = np.zeros((m * e, 16), np.int16)
    if len(flat_idx):
        rows[flat_idx] = data[: len(flat_idx)]
    return rows.reshape(m, e, 16)


def unpack_p_compact(header: np.ndarray, data: np.ndarray, qp: int) -> PFrameCoeffs:
    """header int32, data int16 (>=n, 16) -> dense PFrameCoeffs."""
    n, mbh, mbw = int(header[0]), int(header[1]), int(header[2])
    m = mbh * mbw
    if data.shape[0] < n:
        raise ValueError(f"data has {data.shape[0]} rows, header says {n}")
    mv_words = header[4 : 4 + m].astype(np.int32)
    mvx = (mv_words << 16) >> 16  # sign-extend low half
    mvy = mv_words >> 16
    mvs = np.stack([mvx, mvy], -1).reshape(mbh, mbw, 2)
    mbinfo = header[4 + m : 4 + 2 * m].astype(np.int32)
    skip_words = header[4 + 2 * m :].astype(np.int64) & 0xFFFFFFFF
    skip_bits = ((skip_words[:, None] >> np.arange(32)) & 1).astype(bool).reshape(-1)[:m]
    flags = _flags_from_bitmap(mbinfo, P_ENTRIES)
    rows = _scatter_rows(flags, data)
    luma_ac = rows[:, :P_ROW_CHROMA].reshape(mbh, mbw, 4, 4, 4, 4).astype(np.int32)
    chroma_ac = rows[:, P_ROW_CHROMA:P_ROW_DC].reshape(mbh, mbw, 2, 2, 2, 4, 4).astype(np.int32)
    chroma_dc = rows[:, P_ROW_DC:P_ENTRIES, :4].reshape(mbh, mbw, 2, 2, 2).astype(np.int32)
    return PFrameCoeffs(
        mvs=mvs,
        skip=skip_bits.reshape(mbh, mbw),
        luma_ac=luma_ac,
        chroma_dc=chroma_dc,
        chroma_ac=chroma_ac,
        qp=qp,
    )


def p_sparse_var_words(mbh: int, mbw: int, nscap: int, cap_rows: int) -> int:
    """Total int16 length of the variable-packed sparse buffer."""
    sw = (mbh * mbw + 31) // 32
    return 8 + 2 * sw + 4 * nscap + 16 * cap_rows


def p_sparse_var_need(fused16: np.ndarray, mbh: int, mbw: int, nscap: int,
                      cap_rows: int):
    """(needed int16 length, n, ns) from a slice that covers the meta.

    `needed` counts only what the fused buffer HOLDS (rows cap at
    cap_rows — beyond that the caller spill-fetches from the full row
    buffer). ns > nscap means dense fallback (rows then sit at the
    full-pairs offset)."""
    meta = np.ascontiguousarray(fused16[:8]).view(np.int32)
    n, ns = int(meta[0]), int(meta[3])
    sw = (mbh * mbw + 31) // 32
    return 8 + 2 * sw + 4 * min(ns, nscap) + 16 * min(n, cap_rows), n, ns


def unpack_p_sparse_var(
    fused16: np.ndarray, qp: int, mbh: int, mbw: int, nscap: int,
    cap_rows: int, extra_rows: np.ndarray | None = None,
):
    """Variable-packed sparse buffer (encoder_core.pack_p_sparse_var) ->
    (PFrameCoeffs | None, rows): None means ns > nscap and the caller
    must fall back to the dense header; `rows` (n, 16) int16 is returned
    either way so the fallback reuses the already-fetched coefficients.
    extra_rows supplies rows [cap_rows, n) when the frame spilled."""
    m = mbh * mbw
    sw = (m + 31) // 32
    need, n, ns = p_sparse_var_need(fused16, mbh, mbw, nscap, cap_rows)
    if len(fused16) < need:
        raise ValueError(f"slice has {len(fused16)} int16, need {need}")
    base = 8 + 2 * sw
    rows_off = base + 4 * min(ns, nscap)
    held = min(n, cap_rows)
    rows = fused16[rows_off : rows_off + 16 * held].reshape(held, 16)
    if n > held:
        rows = np.concatenate([rows, extra_rows[: n - held]])
    if ns > nscap:
        return None, rows
    skip_words = (
        np.ascontiguousarray(fused16[8 : 8 + 2 * sw]).view(np.int32).astype(np.int64)
        & 0xFFFFFFFF
    )
    skip_bits = ((skip_words[:, None] >> np.arange(32)) & 1).astype(bool).reshape(-1)[:m]
    pairs = np.ascontiguousarray(fused16[base : base + 4 * ns]).view(np.int32)
    return _finish_sparse_p(pairs, skip_bits, rows, ns, qp, mbh, mbw)


def p_sparse_packed_words(mbh: int, mbw: int, nscap: int, cap_rows: int) -> int:
    """Total int16 length of the bit-packed sparse buffer
    (encoder_core.pack_p_sparse_packed)."""
    sw = (mbh * mbw + 31) // 32
    return 12 + 2 * sw + 4 * nscap + cap_rows + 16 * cap_rows


def p_sparse_packed_need(fused16: np.ndarray, mbh: int, mbw: int, nscap: int,
                         cap_rows: int):
    """(needed int16 length, n, ns) for a bit-packed sparse buffer, from
    a slice that covers the 12-word meta. Mirrors p_sparse_var_need:
    `needed` counts only what the fused buffer HOLDS (rows past cap_rows
    spill-fetch from the full row buffer, always dense)."""
    meta = np.ascontiguousarray(fused16[:12]).view(np.int32)
    n, ns, nw, dense = int(meta[0]), int(meta[3]), int(meta[4]), int(meta[5])
    sw = (mbh * mbw + 31) // 32
    held = min(n, cap_rows)
    rows_words = 16 * held if dense else held + nw
    return 12 + 2 * sw + 4 * min(ns, nscap) + rows_words, n, ns


ENTROPY_META16 = 16  # int16 words of the pack_p_sparse_entropy meta prefix


def p_sparse_entropy_words(mbh: int, mbw: int, nscap: int, cap_rows: int,
                           packed: bool, bits_words: int,
                           entropy_coder: str = "cavlc") -> int:
    """Total int16 length of the entropy-wrapped fused buffer
    (encoder_core.pack_p_sparse_entropy): the 8-int32 meta prefix plus a
    payload region sized for whichever of the two modes is larger. With
    entropy_coder="cabac" the mode-1 payload adds the skip bitmap and
    the per-coded-MB token-count block ahead of the token words."""
    coeff = (p_sparse_packed_words(mbh, mbw, nscap, cap_rows) if packed
             else p_sparse_var_words(mbh, mbw, nscap, cap_rows))
    m = mbh * mbw
    sw = (m + 31) // 32
    bits = (2 * sw + m + 2 * bits_words if entropy_coder == "cabac"
            else 2 * bits_words)
    return ENTROPY_META16 + max(coeff, bits)


def p_sparse_entropy_meta(fused16: np.ndarray):
    """(mode, nbits, trailing_skip, nskip, ns) from an entropy-wrapped
    fused buffer's meta prefix. mode 1 = the payload is slice-data bit
    words; mode 0 = the payload is the unchanged sparse coeff layout
    starting at ENTROPY_META16."""
    meta = np.ascontiguousarray(fused16[:ENTROPY_META16]).view(np.int32)
    return int(meta[0]), int(meta[1]), int(meta[2]), int(meta[3]), int(meta[4])


def _expand_packed_rows(bitmaps: np.ndarray, vals: np.ndarray) -> np.ndarray:
    """bitmaps (held,) int16 + packed values -> dense rows (held, 16).

    Values for row r start at 4*sum(ceil(popcount/4)) over earlier rows
    (each row's nonzeros pad to int16 QUADS — int64 lanes on device) and
    appear in scan-lane order."""
    bm = bitmaps.astype(np.int32) & 0xFFFF
    bits = ((bm[:, None] >> np.arange(16)) & 1).astype(bool)
    counts = bits.sum(-1)
    width = 4 * ((counts + 3) // 4)
    off = np.cumsum(width) - width
    rows = np.zeros((len(bm), 16), np.int16)
    rr, cc = np.nonzero(bits)
    if len(rr):
        rank = (np.cumsum(bits, axis=1) - 1)[rr, cc]
        rows[rr, cc] = vals[off[rr] + rank]
    return rows


def unpack_p_sparse_packed(
    fused16: np.ndarray, qp: int, mbh: int, mbw: int, nscap: int,
    cap_rows: int, extra_rows: np.ndarray | None = None,
):
    """Bit-packed sparse buffer (encoder_core.pack_p_sparse_packed) ->
    (PFrameCoeffs | None, rows) with the same contract as
    unpack_p_sparse_var: None means ns > nscap (dense-header fallback),
    `rows` is returned either way, extra_rows covers a cap_rows spill."""
    m = mbh * mbw
    sw = (m + 31) // 32
    need, n, ns = p_sparse_packed_need(fused16, mbh, mbw, nscap, cap_rows)
    if len(fused16) < need:
        raise ValueError(f"slice has {len(fused16)} int16, need {need}")
    meta = np.ascontiguousarray(fused16[:12]).view(np.int32)
    nw, dense_flag = int(meta[4]), int(meta[5])
    base = 12 + 2 * sw
    rows_off = base + 4 * min(ns, nscap)
    held = min(n, cap_rows)
    if dense_flag:
        rows = fused16[rows_off : rows_off + 16 * held].reshape(held, 16)
    else:
        bitmaps = fused16[rows_off : rows_off + held]
        vals = fused16[rows_off + held : rows_off + held + nw]
        rows = _expand_packed_rows(bitmaps, vals)
    if n > held:
        rows = np.concatenate([rows, extra_rows[: n - held]])
    if ns > nscap:
        return None, rows
    skip_words = (
        np.ascontiguousarray(fused16[12 : 12 + 2 * sw]).view(np.int32).astype(np.int64)
        & 0xFFFFFFFF
    )
    skip_bits = ((skip_words[:, None] >> np.arange(32)) & 1).astype(bool).reshape(-1)[:m]
    pairs = np.ascontiguousarray(fused16[base : base + 4 * ns]).view(np.int32)
    return _finish_sparse_p(pairs, skip_bits, rows, ns, qp, mbh, mbw)


@dataclass
class SparsePWire:
    """Zero-copy views into one frame's sparse-P downlink buffer, in the
    exact regions native/cavlc_pack.cc pack_slice_p_sparse_rbsp consumes.

    All array fields are contiguous int16 views of the fetched fused
    buffer (no scatter, no dtype copy — that is the point); `extra_rows`
    is the cap_rows spill fetch (16-lane rows for global row index >=
    held), empty when the frame fit. `packed` selects the bit-packed
    rows layout (bitmaps + quad-padded values) over 16-lane rows.
    """

    mbh: int
    mbw: int
    n: int              # total nonzero rows
    ns: int             # non-skip MBs (== len(pairs16) // 4)
    held: int           # rows present in the primary layout
    packed: bool
    skip16: np.ndarray       # (2*ceil(M/32),) skip bitmap words
    pairs16: np.ndarray      # (4*ns,) (mv, mbinfo) int32 pairs
    rows16: np.ndarray       # (16*held,) 16-lane rows (empty when packed)
    bitmaps: np.ndarray      # (held,) significance bitmaps (packed only)
    vals: np.ndarray         # (nw,) quad-padded nonzero values (packed only)
    extra_rows: np.ndarray   # ((n-held)*16,) spill rows, 16-lane


_EMPTY_I16 = np.empty(0, np.int16)


def p_sparse_wire_views(
    fused16: np.ndarray, mbh: int, mbw: int, nscap: int, cap_rows: int,
    packed: bool, extra_rows: np.ndarray | None = None,
) -> SparsePWire | None:
    """Sparse downlink buffer -> SparsePWire views for the sparse-native
    packer, or None when ns > nscap (the pair region is truncated; the
    caller must take the dense-header fallback). Validates the skip
    bitmap against ns exactly like _finish_sparse_p so a corrupt buffer
    fails loudly instead of packing garbage.

    Geometry is whatever the buffer was packed with: the band-parallel
    encoder (parallel/bands.py) calls this once per BAND with the band's
    own (band_mbh, mbw) grid — each band's fused buffer is a complete,
    self-describing sparse downlink, so per-band wire views need no
    extra layout; the band's first_mb_in_slice enters only at the
    pack_slice_p_sparse_native call."""
    m = mbh * mbw
    sw = (m + 31) // 32
    if packed:
        meta = np.ascontiguousarray(fused16[:12]).view(np.int32)
        n, ns, nw, dense = int(meta[0]), int(meta[3]), int(meta[4]), int(meta[5])
        base = 12 + 2 * sw
    else:
        meta = np.ascontiguousarray(fused16[:8]).view(np.int32)
        n, ns = int(meta[0]), int(meta[3])
        nw, dense = 0, 1
        base = 8 + 2 * sw
    if ns > nscap:
        return None
    skip16 = fused16[base - 2 * sw : base]
    nskip = int(np.unpackbits(np.ascontiguousarray(skip16).view(np.uint8)).sum())
    if m - nskip != ns:
        raise ValueError(f"skip bitmap has {m - nskip} non-skip MBs, header says {ns}")
    held = min(n, cap_rows)
    rows_off = base + 4 * ns
    if packed and not dense:
        rows16 = _EMPTY_I16
        bitmaps = fused16[rows_off : rows_off + held]
        vals = fused16[rows_off + held : rows_off + held + nw]
    else:
        rows16 = fused16[rows_off : rows_off + 16 * held]
        bitmaps = vals = _EMPTY_I16
    if n > held:
        extra = np.ascontiguousarray(extra_rows[: n - held], np.int16).reshape(-1)
    else:
        extra = _EMPTY_I16
    return SparsePWire(
        mbh=mbh, mbw=mbw, n=n, ns=ns, held=held, packed=bool(packed and not dense),
        skip16=skip16, pairs16=fused16[base:rows_off], rows16=rows16,
        bitmaps=bitmaps, vals=vals, extra_rows=extra,
    )


def _finish_sparse_p(pairs, skip_bits, rows, ns, qp, mbh, mbw):
    """Shared tail of the sparse-P unpackers: (mv, info) pairs + skip
    bitmap + dense-scattered rows -> PFrameCoeffs."""
    m = mbh * mbw
    mv_c, info_c = pairs[0::2], pairs[1::2]
    pos = np.flatnonzero(~skip_bits)
    if len(pos) != ns:
        raise ValueError(f"skip bitmap has {len(pos)} non-skip MBs, header says {ns}")
    mv_words = np.zeros(m, np.int32)
    mv_words[pos] = mv_c
    mbinfo = np.zeros(m, np.int32)
    mbinfo[pos] = info_c
    mvx = (mv_words << 16) >> 16
    mvy = mv_words >> 16
    flags = _flags_from_bitmap(mbinfo, P_ENTRIES)
    dense_rows = _scatter_rows(flags, rows)
    skip = skip_bits.reshape(mbh, mbw)
    mvs = np.ascontiguousarray(np.stack([mvx, mvy], -1).reshape(mbh, mbw, 2))
    derive_skip_mvs_fast(mvs, skip)
    return (
        PFrameCoeffs(
            mvs=mvs,
            skip=skip,
            luma_ac=dense_rows[:, :P_ROW_CHROMA].reshape(mbh, mbw, 4, 4, 4, 4).astype(np.int32),
            chroma_dc=dense_rows[:, P_ROW_DC:P_ENTRIES, :4].reshape(mbh, mbw, 2, 2, 2).astype(np.int32),
            chroma_ac=dense_rows[:, P_ROW_CHROMA:P_ROW_DC].reshape(mbh, mbw, 2, 2, 2, 4, 4).astype(np.int32),
            qp=qp,
        ),
        rows,
    )


def unpack_i_compact(header: np.ndarray, data: np.ndarray, qp: int) -> FrameCoeffs:
    """header int32, data int16 (>=n, 16) -> dense FrameCoeffs."""
    n, mbh, mbw = int(header[0]), int(header[1]), int(header[2])
    m = mbh * mbw
    if data.shape[0] < n:
        raise ValueError(f"data has {data.shape[0]} rows, header says {n}")
    mbinfo = header[4 : 4 + m].astype(np.int32)
    modes = header[4 + m : 4 + 2 * m].astype(np.int32)
    flags = _flags_from_bitmap(mbinfo, I_ENTRIES)
    rows = _scatter_rows(flags, data)
    luma_dc = rows[:, 0].reshape(mbh, mbw, 4, 4).astype(np.int32)
    luma_ac = rows[:, I_ROW_LUMA:I_ROW_CHROMA].reshape(mbh, mbw, 4, 4, 4, 4).astype(np.int32)
    chroma_ac = rows[:, I_ROW_CHROMA:I_ROW_DC_C].reshape(mbh, mbw, 2, 2, 2, 4, 4).astype(np.int32)
    chroma_dc = rows[:, I_ROW_DC_C:I_ENTRIES, :4].reshape(mbh, mbw, 2, 2, 2).astype(np.int32)
    return FrameCoeffs(
        luma_mode=(modes & 0xFF).reshape(mbh, mbw),
        chroma_mode=(modes >> 8).reshape(mbh, mbw),
        luma_dc=luma_dc,
        luma_ac=luma_ac,
        chroma_dc=chroma_dc,
        chroma_ac=chroma_ac,
        qp=qp,
    )
