"""Numpy golden-model H.264 intra encoder: transform, quant, predict, recon.

This is the bit-exact reference the TPU path (encoder.py, JAX/Pallas) and
the C++ CAVLC packer are validated against, and the authority for
conformance tests (FFmpeg must reconstruct exactly these pixels).

Scope (first milestone): Intra16x16 luma + Intra8x8 chroma, CAVLC, single
slice per frame, deblocking disabled. Prediction-mode policy is chosen for
TPU-friendliness (see encoder.py): vertical prediction everywhere the top
neighbour exists (dependencies run down rows only, so a row of MBs is a
single data-parallel batch), DC prediction on the first row (left-to-right
chain, one scan per frame).

The quantization/rescale math follows ISO/IEC 14496-10 §8.5; integer
shifts are arithmetic (numpy's >> on signed ints), matching the spec.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from selkies_tpu.models.h264.tables import chroma_qp, mf_matrix, v_matrix

# Forward core transform matrix Cf (8.5.12 inverse's encoder-side dual).
CF = np.array([[1, 1, 1, 1], [2, 1, -1, -2], [1, -1, -1, 1], [1, -2, 2, -1]], dtype=np.int64)
# 4x4 Hadamard for Intra16x16 luma DC.
H4 = np.array([[1, 1, 1, 1], [1, 1, -1, -1], [1, -1, -1, 1], [1, -1, 1, -1]], dtype=np.int64)
# 2x2 Hadamard for chroma DC.
H2 = np.array([[1, 1], [1, -1]], dtype=np.int64)

# Intra16x16 luma prediction modes (coded in mb_type).
I16_VERTICAL = 0
I16_HORIZONTAL = 1
I16_DC = 2
I16_PLANE = 3

# Chroma prediction modes (intra_chroma_pred_mode syntax element).
CHROMA_DC = 0
CHROMA_HORIZONTAL = 1
CHROMA_VERTICAL = 2
CHROMA_PLANE = 3


def fdct4(blocks: np.ndarray) -> np.ndarray:
    """Forward 4x4 core transform over (..., 4, 4) int blocks."""
    return CF @ blocks.astype(np.int64) @ CF.T


def idct4(coeffs: np.ndarray) -> np.ndarray:
    """Inverse 4x4 core transform (8.5.12.2), bit-exact with >> semantics.

    Input: dequantized coefficients (..., 4, 4). Output: residual (..., 4, 4)
    after the final (x + 32) >> 6 rounding.
    """
    d = coeffs.astype(np.int64)
    # horizontal first (8.5.12.2): mix columns within each row
    e0 = d[..., 0] + d[..., 2]
    e1 = d[..., 0] - d[..., 2]
    e2 = (d[..., 1] >> 1) - d[..., 3]
    e3 = d[..., 1] + (d[..., 3] >> 1)
    g = np.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-1)
    # then vertical: mix rows
    e0 = g[..., 0, :] + g[..., 2, :]
    e1 = g[..., 0, :] - g[..., 2, :]
    e2 = (g[..., 1, :] >> 1) - g[..., 3, :]
    e3 = g[..., 1, :] + (g[..., 3, :] >> 1)
    out = np.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-2)
    return (out + 32) >> 6


def quant4(coeffs: np.ndarray, qp: int, intra: bool = True) -> np.ndarray:
    """Quantize (..., 4, 4) transform coefficients (AC path incl. DC pos)."""
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3 if intra else (1 << qbits) // 6
    mf = mf_matrix(qp)
    c = coeffs.astype(np.int64)
    level = (np.abs(c) * mf + f) >> qbits
    return np.where(c < 0, -level, level)


def dequant4(levels: np.ndarray, qp: int) -> np.ndarray:
    """Rescale (..., 4, 4) levels (AC path); feeds idct4."""
    return levels.astype(np.int64) * v_matrix(qp) * (1 << (qp // 6))


def quant_luma_dc(dc: np.ndarray, qp: int) -> np.ndarray:
    """Forward Hadamard + quant for the (..., 4, 4) luma DC block."""
    t = (H4 @ dc.astype(np.int64) @ H4) >> 1
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3
    mf00 = mf_matrix(qp)[0, 0]
    level = (np.abs(t) * mf00 + 2 * f) >> (qbits + 1)
    return np.where(t < 0, -level, level)


def dequant_luma_dc(levels: np.ndarray, qp: int) -> np.ndarray:
    """Inverse Hadamard + rescale; returns DC values to substitute into
    each 4x4 block before idct4 (8.5.10)."""
    f = H4 @ levels.astype(np.int64) @ H4
    v00 = v_matrix(qp)[0, 0]
    qp_per = qp // 6
    if qp_per >= 2:
        return (f * v00) << (qp_per - 2)
    return (f * v00 + (1 << (1 - qp_per))) >> (2 - qp_per)


def quant_chroma_dc(dc: np.ndarray, qp: int, intra: bool = True) -> np.ndarray:
    """Forward 2x2 Hadamard + quant for (..., 2, 2) chroma DC (qp = chroma QP)."""
    t = H2 @ dc.astype(np.int64) @ H2
    qbits = 15 + qp // 6
    f = (1 << qbits) // 3 if intra else (1 << qbits) // 6
    mf00 = mf_matrix(qp)[0, 0]
    level = (np.abs(t) * mf00 + 2 * f) >> (qbits + 1)
    return np.where(t < 0, -level, level)


def dequant_chroma_dc(levels: np.ndarray, qp: int) -> np.ndarray:
    """8.5.11 with the default flat scaling list (LevelScale = 16·V):
    dcC = ((f · 16·V00) << (qP/6)) >> 5  ==  ((f · V00) << (qP/6)) >> 1,
    validated empirically against FFmpeg (tools/cavlc_probe.py)."""
    f = H2 @ levels.astype(np.int64) @ H2
    v00 = v_matrix(qp)[0, 0]
    return ((f * v00) << (qp // 6)) >> 1


def split_blocks(mb: np.ndarray, n: int) -> np.ndarray:
    """(N*n, M*n) -> (N, M, n, n) grid of nxn blocks."""
    h, w = mb.shape
    return mb.reshape(h // n, n, w // n, n).swapaxes(1, 2)


def merge_blocks(blocks: np.ndarray) -> np.ndarray:
    """(N, M, n, n) -> (N*n, M*n)."""
    nby, nbx, n, _ = blocks.shape
    return blocks.swapaxes(1, 2).reshape(nby * n, nbx * n)


@dataclass
class FrameCoeffs:
    """Stacked per-MB quantized coefficients for one frame.

    This is the contract between the encode core (numpy golden model /
    JAX TPU path) and the entropy packers (cavlc.py, native/cavlc_pack.cc):
      luma_mode / chroma_mode: (mbh, mbw) int32 prediction modes
      luma_dc:   (mbh, mbw, 4, 4)        quantized Hadamard DC levels
      luma_ac:   (mbh, mbw, 4, 4, 4, 4)  [by][bx][i][j]; DC position ignored
      chroma_dc: (mbh, mbw, 2, 2, 2)     [comp][i][j] (comp 0=Cb, 1=Cr)
      chroma_ac: (mbh, mbw, 2, 2, 2, 4, 4) [comp][by][bx][i][j]
    """

    luma_mode: np.ndarray
    chroma_mode: np.ndarray
    luma_dc: np.ndarray
    luma_ac: np.ndarray
    chroma_dc: np.ndarray
    chroma_ac: np.ndarray
    qp: int


def encode_mb_luma(orig: np.ndarray, pred: np.ndarray, qp: int):
    """Intra16x16 luma: transform+quant+recon for one (16, 16) MB.

    Returns (dc_levels (4,4), ac_levels (4,4,4,4), recon (16,16) uint8).
    """
    resid = orig.astype(np.int64) - pred.astype(np.int64)
    blocks = split_blocks(resid, 4)  # (4,4,4,4)
    w = fdct4(blocks)
    dc = w[..., 0, 0]  # (4,4) raster of block DCs
    dc_levels = quant_luma_dc(dc, qp)
    ac_levels = quant4(w, qp, intra=True)
    # Reconstruction: dequant AC, substitute dequantized DC, inverse transform.
    deq = dequant4(ac_levels, qp)
    deq[..., 0, 0] = dequant_luma_dc(dc_levels, qp)
    r = idct4(deq)
    recon = np.clip(merge_blocks(r) + pred.astype(np.int64), 0, 255).astype(np.uint8)
    return dc_levels, ac_levels, recon


def encode_mb_chroma(orig: np.ndarray, pred: np.ndarray, qp_c: int):
    """One chroma component (8, 8): returns (dc (2,2), ac (2,2,4,4), recon)."""
    resid = orig.astype(np.int64) - pred.astype(np.int64)
    blocks = split_blocks(resid, 4)  # (2,2,4,4)
    w = fdct4(blocks)
    dc = w[..., 0, 0]  # (2,2)
    dc_levels = quant_chroma_dc(dc, qp_c)
    ac_levels = quant4(w, qp_c, intra=True)
    deq = dequant4(ac_levels, qp_c)
    deq[..., 0, 0] = dequant_chroma_dc(dc_levels, qp_c)
    r = idct4(deq)
    recon = np.clip(merge_blocks(r) + pred.astype(np.int64), 0, 255).astype(np.uint8)
    return dc_levels, ac_levels, recon


def _dc_pred_luma(top: np.ndarray | None, left: np.ndarray | None) -> np.ndarray:
    if top is not None and left is not None:
        dc = (int(top.sum()) + int(left.sum()) + 16) >> 5
    elif left is not None:
        dc = (int(left.sum()) + 8) >> 4
    elif top is not None:
        dc = (int(top.sum()) + 8) >> 4
    else:
        dc = 128
    return np.full((16, 16), dc, dtype=np.int64)


def _dc_pred_chroma(top: np.ndarray | None, left: np.ndarray | None) -> np.ndarray:
    """8.3.4.1 chroma DC prediction: per-4x4 rules."""
    pred = np.empty((8, 8), dtype=np.int64)
    for by in (0, 1):
        for bx in (0, 1):
            t = top[bx * 4 : bx * 4 + 4] if top is not None else None
            l = left[by * 4 : by * 4 + 4] if left is not None else None
            if bx == by:  # corner blocks (0,0) and (1,1): use both if avail
                if t is not None and l is not None:
                    dc = (int(t.sum()) + int(l.sum()) + 4) >> 3
                elif l is not None:
                    dc = (int(l.sum()) + 2) >> 2
                elif t is not None:
                    dc = (int(t.sum()) + 2) >> 2
                else:
                    dc = 128
            elif by == 0:  # block (1,0): prefer top
                if t is not None:
                    dc = (int(t.sum()) + 2) >> 2
                elif l is not None:
                    dc = (int(l.sum()) + 2) >> 2
                else:
                    dc = 128
            else:  # block (0,1): prefer left
                if l is not None:
                    dc = (int(l.sum()) + 2) >> 2
                elif t is not None:
                    dc = (int(t.sum()) + 2) >> 2
                else:
                    dc = 128
            pred[by * 4 : by * 4 + 4, bx * 4 : bx * 4 + 4] = dc
    return pred


@dataclass
class FrameEncoding:
    """Output of the frame encoder: coefficients + reconstruction."""

    coeffs: FrameCoeffs
    recon_y: np.ndarray
    recon_u: np.ndarray
    recon_v: np.ndarray


def pad_planes(y: np.ndarray, u: np.ndarray, v: np.ndarray):
    """Edge-pad planes to macroblock multiples (the SPS crops them back)."""
    h, w = y.shape
    hp, wp = (h + 15) // 16 * 16, (w + 15) // 16 * 16
    if (hp, wp) == (h, w):
        return y, u, v
    y = np.pad(y, ((0, hp - h), (0, wp - w)), mode="edge")
    u = np.pad(u, ((0, hp // 2 - u.shape[0]), (0, wp // 2 - u.shape[1])), mode="edge")
    v = np.pad(v, ((0, hp // 2 - v.shape[0]), (0, wp // 2 - v.shape[1])), mode="edge")
    return y, u, v


# ---------------------------------------------------------------------------
# Inter (P-frame) golden model
# ---------------------------------------------------------------------------
#
# Partitioning policy: P_Skip / P_L0_16x16 only, one reference frame,
# full-pel luma motion vectors (chroma lands on half-pel, bilinear per
# 8.4.2.2.2). There is no intra prediction in P frames, so — unlike the
# I-frame row scan — every macroblock is independent given the reference
# frame: the TPU path (encoder_core.py) batches the whole frame as one
# tensor op. The reference's encoders get this from NVENC silicon
# (gstwebrtc_app.py:260-367); for remote-desktop content the dominant case
# is a P_Skip carpet over unchanged screen regions.

# Max motion-vector magnitude (full-pel); reference planes are edge-padded
# by this much so unrestricted MVs never index out of bounds. Sized for the
# hierarchical search reach: COARSE_DS*COARSE_R + REFINE_R = 34 <= MV_PAD.
MV_PAD = 40

# Hierarchical ME geometry (hier_search_me / encoder_core.hier_motion_search)
COARSE_DS = 4   # coarse level downsample factor
COARSE_R = 8    # coarse search radius in downsampled pels (→ ±32 full-pel)
REFINE_R = 2    # full-res refine radius around each upscaled global candidate
                # (±2 exactly covers the COARSE_DS=4 grid; ±3 only added
                # overlap and cost ~2x the refine-scan device time)
TOPK = 3        # dominant global motion candidates carried to full-res refine


@dataclass
class PFrameCoeffs:
    """Per-MB data for one P frame (contract with the entropy packers).

    mvs:       (mbh, mbw, 2) int32 full-pel motion vectors, [..., 0]=x, [..., 1]=y
    skip:      (mbh, mbw) bool — MB coded as P_Skip (requires mv == skip MV
               and all residual levels zero; enforced by encode_frame_p)
    luma_ac:   (mbh, mbw, 4, 4, 4, 4) [by][bx][i][j] — all 16 coeffs coded
               (inter MBs have no luma DC Hadamard)
    chroma_dc: (mbh, mbw, 2, 2, 2) [comp][i][j]
    chroma_ac: (mbh, mbw, 2, 2, 2, 4, 4)
    """

    mvs: np.ndarray
    skip: np.ndarray
    luma_ac: np.ndarray
    chroma_dc: np.ndarray
    chroma_ac: np.ndarray
    qp: int


@dataclass
class PFrameEncoding:
    coeffs: PFrameCoeffs
    recon_y: np.ndarray
    recon_u: np.ndarray
    recon_v: np.ndarray


def _median3(a: int, b: int, c: int) -> int:
    return int(np.median([a, b, c]))


def mv_pred_16x16(mvs: np.ndarray, mbx: int, mby: int) -> tuple[int, int]:
    """8.4.1.3 motion-vector prediction for a 16x16 partition.

    All coded MBs share refIdx 0 (single reference), so the "exactly one
    neighbour matches refIdx" rule reduces to availability counting.
    mvs holds the ACTUAL per-MB motion vectors (skip MBs included).
    """
    mbh, mbw = mvs.shape[:2]
    a_avail = mbx > 0
    b_avail = mby > 0
    c_avail = mby > 0 and mbx + 1 < mbw
    d_avail = mby > 0 and mbx > 0
    # top-right substitution: C unavailable -> D takes its place
    if not c_avail and d_avail:
        c_mv, c_avail = mvs[mby - 1, mbx - 1], True
    elif c_avail:
        c_mv = mvs[mby - 1, mbx + 1]
    else:
        c_mv = np.zeros(2, np.int32)
    a_mv = mvs[mby, mbx - 1] if a_avail else np.zeros(2, np.int32)
    b_mv = mvs[mby - 1, mbx] if b_avail else np.zeros(2, np.int32)
    # 8.4.1.3.1: B, C, D all unavailable and A available -> mvA
    if a_avail and not b_avail and not c_avail:
        return int(a_mv[0]), int(a_mv[1])
    # exactly one available neighbour (refIdx match) -> its mv
    n_avail = int(a_avail) + int(b_avail) + int(c_avail)
    if n_avail == 1:
        only = a_mv if a_avail else (b_mv if b_avail else c_mv)
        return int(only[0]), int(only[1])
    return (
        _median3(int(a_mv[0]), int(b_mv[0]), int(c_mv[0])),
        _median3(int(a_mv[1]), int(b_mv[1]), int(c_mv[1])),
    )


def skip_mv_16x16(mvs: np.ndarray, mbx: int, mby: int) -> tuple[int, int]:
    """8.4.1.1 P_Skip motion-vector derivation."""
    if mbx == 0 or mby == 0:
        return 0, 0
    a = mvs[mby, mbx - 1]
    b = mvs[mby - 1, mbx]
    if (a[0] == 0 and a[1] == 0) or (b[0] == 0 and b[1] == 0):
        return 0, 0
    return mv_pred_16x16(mvs, mbx, mby)


def pad_ref(plane: np.ndarray, pad: int = MV_PAD) -> np.ndarray:
    return np.pad(plane, pad, mode="edge")


def mc_luma_16x16(ref_pad: np.ndarray, mbx: int, mby: int, mv) -> np.ndarray:
    """Full-pel 16x16 luma motion compensation from an MV_PAD-padded ref."""
    y0 = mby * 16 + int(mv[1]) + MV_PAD
    x0 = mbx * 16 + int(mv[0]) + MV_PAD
    return ref_pad[y0 : y0 + 16, x0 : x0 + 16].astype(np.int64)


def mc_chroma_8x8(ref_pad: np.ndarray, mbx: int, mby: int, mv) -> np.ndarray:
    """8x8 chroma MC (8.4.2.2.2). Full-pel luma MVs land chroma on
    half-pel: frac ∈ {0, 4} eighths per axis -> bilinear with weights 4/4."""
    mvx, mvy = int(mv[0]), int(mv[1])
    x0 = mbx * 8 + (mvx >> 1) + MV_PAD
    y0 = mby * 8 + (mvy >> 1) + MV_PAD
    xf = 4 * (mvx & 1)
    yf = 4 * (mvy & 1)
    p = ref_pad.astype(np.int64)
    a = p[y0 : y0 + 8, x0 : x0 + 8]
    b = p[y0 : y0 + 8, x0 + 1 : x0 + 9]
    c = p[y0 + 1 : y0 + 9, x0 : x0 + 8]
    d = p[y0 + 1 : y0 + 9, x0 + 1 : x0 + 9]
    return ((8 - xf) * (8 - yf) * a + xf * (8 - yf) * b + (8 - xf) * yf * c + xf * yf * d + 32) >> 6


def encode_mb_inter_luma(orig: np.ndarray, pred: np.ndarray, qp: int):
    """Inter 16x16 luma: plain 4x4 transform+quant (no DC Hadamard).

    Returns (ac_levels (4,4,4,4) with all 16 coeffs live, recon (16,16))."""
    resid = orig.astype(np.int64) - pred
    w = fdct4(split_blocks(resid, 4))
    ac_levels = quant4(w, qp, intra=False)
    r = idct4(dequant4(ac_levels, qp))
    recon = np.clip(merge_blocks(r) + pred, 0, 255).astype(np.uint8)
    return ac_levels, recon


def encode_mb_inter_chroma(orig: np.ndarray, pred: np.ndarray, qp_c: int):
    """Inter 8x8 chroma: 2x2 DC Hadamard + AC, inter rounding."""
    resid = orig.astype(np.int64) - pred
    w = fdct4(split_blocks(resid, 4))
    dc_levels = quant_chroma_dc(w[..., 0, 0], qp_c, intra=False)
    ac_levels = quant4(w, qp_c, intra=False)
    deq = dequant4(ac_levels, qp_c)
    deq[..., 0, 0] = dequant_chroma_dc(dc_levels, qp_c)
    r = idct4(deq)
    recon = np.clip(merge_blocks(r) + pred, 0, 255).astype(np.uint8)
    return dc_levels, ac_levels, recon


def full_search_me(
    y: np.ndarray, ref_y: np.ndarray, search: int = 8
) -> np.ndarray:
    """Exhaustive full-pel SAD search over ±search per MB (golden model).

    Zero MV wins ties (preferred: cheaper to code, skip-eligible)."""
    h, w = y.shape
    mbh, mbw = h // 16, w // 16
    ref_pad = pad_ref(ref_y)
    cur = y.astype(np.int64)
    best_sad = np.full((mbh, mbw), np.iinfo(np.int64).max)
    best_mv = np.zeros((mbh, mbw, 2), np.int32)
    cand = sorted(
        ((dx, dy) for dy in range(-search, search + 1) for dx in range(-search, search + 1)),
        key=lambda c: (c != (0, 0)),
    )
    for dx, dy in cand:
        shifted = ref_pad[
            MV_PAD + dy : MV_PAD + dy + h, MV_PAD + dx : MV_PAD + dx + w
        ].astype(np.int64)
        sad = (
            np.abs(cur - shifted).reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))
        )
        better = sad < best_sad
        best_sad = np.where(better, sad, best_sad)
        best_mv[better] = (dx, dy)
    return best_mv


def downsample4(plane: np.ndarray) -> np.ndarray:
    """4x4 box downsample with round-half-up: ds[i,j] = (Σ 4x4 block + 8)>>4.

    Exact integer arithmetic (the device mirror must match bit-for-bit —
    the coarse ME level runs on these planes)."""
    h, w = plane.shape
    return (
        plane.astype(np.int64).reshape(h // 4, 4, w // 4, 4).sum(axis=(1, 3)) + 8
    ) >> 4


def coarse_vote_candidates(y: np.ndarray, ref_y: np.ndarray) -> np.ndarray:
    """Level-1 ME: exhaustive ±COARSE_R search on 4x-downsampled planes,
    then the TOPK most-voted coarse displacements across the frame.

    Returns (TOPK, 2) int32 coarse MVs (downsampled units). Ties in the
    vote count resolve to the lower candidate rank (zero-first raster),
    mirrored exactly by the device path. Desktop motion is dominated by a
    few global displacements (scroll/pan/drag), which is what makes a
    frame-level candidate set competitive with per-MB search at a fraction
    of the cost — and it keeps the device path free of gathers, which are
    pathologically slow on TPU (tools/profile_slope2.py: 30 ms per
    full-plane gather vs 0.26 ms per global-shift SAD map).
    """
    h, w = y.shape
    mbh, mbw = h // 16, w // 16
    yd = downsample4(y)
    rd = downsample4(ref_y)
    pad = COARSE_R
    rp = np.pad(rd, pad, mode="edge")
    hd, wd = yd.shape
    cand = sorted(
        ((dx, dy) for dy in range(-COARSE_R, COARSE_R + 1) for dx in range(-COARSE_R, COARSE_R + 1)),
        key=lambda c: (c != (0, 0)),
    )
    best_sad = np.full((mbh, mbw), np.iinfo(np.int64).max)
    best_rank = np.zeros((mbh, mbw), np.int32)
    for rank, (dx, dy) in enumerate(cand):
        shifted = rp[pad + dy : pad + dy + hd, pad + dx : pad + dx + wd]
        sad = np.abs(yd - shifted).reshape(mbh, 4, mbw, 4).sum(axis=(1, 3))
        better = sad < best_sad
        best_sad = np.where(better, sad, best_sad)
        best_rank = np.where(better, rank, best_rank)
    votes = np.bincount(best_rank.reshape(-1), minlength=len(cand))
    # deterministic top-K: score = votes desc, then rank asc
    order = np.lexsort((np.arange(len(cand)), -votes))
    return np.array([cand[i] for i in order[:TOPK]], np.int32)


def refine_candidate_list(coarse: np.ndarray) -> np.ndarray:
    """Full-res candidate shift list: zero MV (rank 0), then for each
    global candidate g the raster grid g*COARSE_DS + (dx, dy),
    |dx|,|dy| <= REFINE_R. Duplicates are harmless (earlier rank wins)."""
    out = [(0, 0)]
    for g in coarse:
        for dy in range(-REFINE_R, REFINE_R + 1):
            for dx in range(-REFINE_R, REFINE_R + 1):
                out.append((int(g[0]) * COARSE_DS + dx, int(g[1]) * COARSE_DS + dy))
    return np.array(out, np.int32)


def hier_search_me(y: np.ndarray, ref_y: np.ndarray) -> np.ndarray:
    """Global-candidate hierarchical full-pel ME (golden model).

    Level 1 picks TOPK dominant coarse displacements by per-MB vote;
    level 0 evaluates global-shift SAD maps for every refine candidate
    (zero MV first) and each MB takes the earliest-ranked minimum. All
    full-res work is global shifts — the device mirror runs entirely on
    dynamic slices + dense selects (no gathers).
    """
    h, w = y.shape
    mbh, mbw = h // 16, w // 16
    cands = refine_candidate_list(coarse_vote_candidates(y, ref_y))
    ref_pad = pad_ref(ref_y)
    cur = y.astype(np.int64)
    best_sad = np.full((mbh, mbw), np.iinfo(np.int64).max)
    best_mv = np.zeros((mbh, mbw, 2), np.int32)
    for dx, dy in cands:
        shifted = ref_pad[
            MV_PAD + dy : MV_PAD + dy + h, MV_PAD + dx : MV_PAD + dx + w
        ].astype(np.int64)
        sad = np.abs(cur - shifted).reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))
        better = sad < best_sad
        best_sad = np.where(better, sad, best_sad)
        best_mv[better] = (dx, dy)
    return best_mv


def encode_frame_p(
    y: np.ndarray,
    u: np.ndarray,
    v: np.ndarray,
    ref_y: np.ndarray,
    ref_u: np.ndarray,
    ref_v: np.ndarray,
    mvs: np.ndarray,
    qp: int,
) -> PFrameEncoding:
    """Encode a P frame given per-MB full-pel motion vectors.

    Planes must be pre-padded to MB multiples; ref_* are the previous
    frame's reconstruction (decoder state), same shapes.
    """
    h, w = y.shape
    mbh, mbw = h // 16, w // 16
    if mvs.shape != (mbh, mbw, 2):
        raise ValueError(f"mvs shape {mvs.shape} != {(mbh, mbw, 2)}")
    if np.abs(mvs).max(initial=0) > MV_PAD:
        raise ValueError(f"|mv| exceeds MV_PAD={MV_PAD}")
    qp_c = chroma_qp(qp)
    ry, ru, rv = pad_ref(ref_y), pad_ref(ref_u), pad_ref(ref_v)
    recon_y = np.zeros_like(y)
    recon_u = np.zeros_like(u)
    recon_v = np.zeros_like(v)
    fc = PFrameCoeffs(
        mvs=mvs.astype(np.int32),
        skip=np.zeros((mbh, mbw), bool),
        luma_ac=np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32),
        chroma_dc=np.zeros((mbh, mbw, 2, 2, 2), np.int32),
        chroma_ac=np.zeros((mbh, mbw, 2, 2, 2, 4, 4), np.int32),
        qp=qp,
    )
    for mby in range(mbh):
        for mbx in range(mbw):
            mv = mvs[mby, mbx]
            pred_y = mc_luma_16x16(ry, mbx, mby, mv)
            pred_u = mc_chroma_8x8(ru, mbx, mby, mv)
            pred_v = mc_chroma_8x8(rv, mbx, mby, mv)
            ac_y, rec_y = encode_mb_inter_luma(
                y[mby * 16 : mby * 16 + 16, mbx * 16 : mbx * 16 + 16], pred_y, qp
            )
            dc_u, ac_u, rec_u = encode_mb_inter_chroma(
                u[mby * 8 : mby * 8 + 8, mbx * 8 : mbx * 8 + 8], pred_u, qp_c
            )
            dc_v, ac_v, rec_v = encode_mb_inter_chroma(
                v[mby * 8 : mby * 8 + 8, mbx * 8 : mbx * 8 + 8], pred_v, qp_c
            )
            recon_y[mby * 16 : mby * 16 + 16, mbx * 16 : mbx * 16 + 16] = rec_y
            recon_u[mby * 8 : mby * 8 + 8, mbx * 8 : mbx * 8 + 8] = rec_u
            recon_v[mby * 8 : mby * 8 + 8, mbx * 8 : mbx * 8 + 8] = rec_v
            fc.luma_ac[mby, mbx] = ac_y
            fc.chroma_dc[mby, mbx] = np.stack([dc_u, dc_v])
            fc.chroma_ac[mby, mbx] = np.stack([ac_u, ac_v])
    # Skip pass: residual-free MBs whose mv equals the 8.4.1.1 skip MV.
    # (Depends only on the final mv field, so order doesn't matter.)
    for mby in range(mbh):
        for mbx in range(mbw):
            if (
                not fc.luma_ac[mby, mbx].any()
                and not fc.chroma_dc[mby, mbx].any()
                and not fc.chroma_ac[mby, mbx].any()
                and tuple(mvs[mby, mbx]) == skip_mv_16x16(mvs, mbx, mby)
            ):
                fc.skip[mby, mbx] = True
    return PFrameEncoding(coeffs=fc, recon_y=recon_y, recon_u=recon_u, recon_v=recon_v)


def encode_frame_i16(y: np.ndarray, u: np.ndarray, v: np.ndarray, qp: int) -> FrameEncoding:
    """Encode planes (padded to MB multiples) as an all-Intra16x16 frame.

    Prediction policy (mirrors the TPU row-scan in encoder.py):
      row 0:  luma DC (left/none), chroma DC  — serial left-to-right
      row>0:  luma vertical, chroma vertical  — rows depend only on the row above
    """
    h, w = y.shape
    if h % 16 or w % 16:
        raise ValueError(f"luma plane {w}x{h} must be padded to multiples of 16 (see pad_planes)")
    if u.shape != (h // 2, w // 2) or v.shape != (h // 2, w // 2):
        raise ValueError("chroma planes must be (h/2, w/2) for 4:2:0")
    if not 0 <= qp <= 51:
        raise ValueError(f"qp {qp} out of range [0, 51]")
    mbh, mbw = h // 16, w // 16
    qp_c = chroma_qp(qp)
    recon_y = np.zeros_like(y)
    recon_u = np.zeros_like(u)
    recon_v = np.zeros_like(v)
    fc = FrameCoeffs(
        luma_mode=np.zeros((mbh, mbw), np.int32),
        chroma_mode=np.zeros((mbh, mbw), np.int32),
        luma_dc=np.zeros((mbh, mbw, 4, 4), np.int32),
        luma_ac=np.zeros((mbh, mbw, 4, 4, 4, 4), np.int32),
        chroma_dc=np.zeros((mbh, mbw, 2, 2, 2), np.int32),
        chroma_ac=np.zeros((mbh, mbw, 2, 2, 2, 4, 4), np.int32),
        qp=qp,
    )
    for mby in range(mbh):
        for mbx in range(mbw):
            ys, xs = mby * 16, mbx * 16
            cys, cxs = mby * 8, mbx * 8
            if mby == 0:
                left_y = recon_y[ys : ys + 16, xs - 1] if mbx > 0 else None
                pred_y = _dc_pred_luma(None, left_y)
                luma_mode = I16_DC
                left_u = recon_u[cys : cys + 8, cxs - 1] if mbx > 0 else None
                left_v = recon_v[cys : cys + 8, cxs - 1] if mbx > 0 else None
                pred_u = _dc_pred_chroma(None, left_u)
                pred_v = _dc_pred_chroma(None, left_v)
                chroma_mode = CHROMA_DC
            else:
                pred_y = np.broadcast_to(recon_y[ys - 1, xs : xs + 16].astype(np.int64), (16, 16))
                luma_mode = I16_VERTICAL
                pred_u = np.broadcast_to(recon_u[cys - 1, cxs : cxs + 8].astype(np.int64), (8, 8))
                pred_v = np.broadcast_to(recon_v[cys - 1, cxs : cxs + 8].astype(np.int64), (8, 8))
                chroma_mode = CHROMA_VERTICAL
            dc_y, ac_y, rec_y = encode_mb_luma(y[ys : ys + 16, xs : xs + 16], pred_y, qp)
            dc_u, ac_u, rec_u = encode_mb_chroma(u[cys : cys + 8, cxs : cxs + 8], pred_u, qp_c)
            dc_v, ac_v, rec_v = encode_mb_chroma(v[cys : cys + 8, cxs : cxs + 8], pred_v, qp_c)
            recon_y[ys : ys + 16, xs : xs + 16] = rec_y
            recon_u[cys : cys + 8, cxs : cxs + 8] = rec_u
            recon_v[cys : cys + 8, cxs : cxs + 8] = rec_v
            fc.luma_mode[mby, mbx] = luma_mode
            fc.chroma_mode[mby, mbx] = chroma_mode
            fc.luma_dc[mby, mbx] = dc_y
            fc.luma_ac[mby, mbx] = ac_y
            fc.chroma_dc[mby, mbx] = np.stack([dc_u, dc_v])
            fc.chroma_ac[mby, mbx] = np.stack([ac_u, ac_v])
    return FrameEncoding(coeffs=fc, recon_y=recon_y, recon_u=recon_u, recon_v=recon_v)
