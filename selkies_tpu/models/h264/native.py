"""ctypes binding for the C++ CAVLC packer (native/cavlc_pack.cc).

Loads (and lazily builds, when a toolchain is present) native/libcavlc.so.
`pack_slice_native` is byte-identical to cavlc.pack_slice (asserted by
tests/test_native_pack.py); callers use `pack_slice_fast`, which picks the
native packer when available and falls back to pure Python.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading

import numpy as np

from selkies_tpu.models.h264.bitstream import (
    NAL_SLICE_IDR,
    NAL_SLICE_NON_IDR,
    SLICE_I,
    SLICE_P,
    StreamParams,
    write_slice_header,
)
from selkies_tpu.models.h264.cavlc import pack_slice as pack_slice_py
from selkies_tpu.models.h264.cavlc import pack_slice_p as pack_slice_p_py
from selkies_tpu.models.h264.numpy_ref import FrameCoeffs, PFrameCoeffs
from selkies_tpu.utils.bits import BitWriter

logger = logging.getLogger("h264.native")

_NATIVE_DIR = os.environ.get("SELKIES_NATIVE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))), "native"
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libcavlc.so")

_lib = None
_lib_tried = False
# First-call init is racy without a lock now that the per-slot pack pool
# makes concurrent first-calls routine: a worker racing the builder would
# see _lib_tried=True with _lib still None and silently fall back to the
# Python packer for the whole build window (and two racers could spawn
# duplicate `make` processes).
_load_lock = threading.Lock()


def _lib_stale() -> bool:
    """True when libcavlc.so is absent or older than its sources."""
    try:
        so_m = os.path.getmtime(_LIB_PATH)
    except OSError:
        return True
    for src in ("cavlc_pack.cc", "cavlc_tables.h"):
        try:
            if os.path.getmtime(os.path.join(_NATIVE_DIR, src)) > so_m:
                return True
        except OSError:
            continue
    return False


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    if _lib_tried:  # unlocked fast path: set only after init finishes
        return _lib
    with _load_lock:
        if not _lib_tried:
            try:
                _lib = _load_impl()
            finally:
                _lib_tried = True  # build/load failure is permanent fallback
        return _lib


def _load_impl() -> ctypes.CDLL | None:
    if os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")) and _lib_stale():
        # rebuild when the .so is missing or older than its sources: a
        # stale prebuilt library loads fine but lacks newer entries like
        # pack_slice_p_sparse_rbsp. The mtime gate (not an unconditional
        # make) keeps toolchain-less deploys with a prebuilt .so from
        # spawning a failing compiler on every process start; the
        # Makefile builds to a temp name + rename, so a concurrent
        # starter never loads a half-written library.
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "-s", "libcavlc.so"],
                           check=True, capture_output=True, timeout=120)
        except (OSError, subprocess.SubprocessError) as exc:
            if not os.path.exists(_LIB_PATH):
                logger.warning("could not build libcavlc.so (%s); using Python packer", exc)
                return None
            # keep the existing (possibly stale) library; entry-point
            # availability is still gated per-symbol below
            logger.warning("libcavlc.so rebuild failed (%s); using existing library", exc)
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as exc:
        logger.warning("could not load libcavlc.so (%s); using Python packer", exc)
        return None
    lib.pack_slice_rbsp.restype = ctypes.c_int64
    lib.pack_slice_rbsp.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int16),
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int16),
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int16),
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.pack_slice_p_rbsp.restype = ctypes.c_int64
    lib.pack_slice_p_rbsp.argtypes = [
        ctypes.c_char_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int16),
        ctypes.POINTER(ctypes.c_int16),
        ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
    ]
    try:
        # sparse-native P packer (wire format in, RBSP out) — absent from
        # a stale .so; callers gate on sparse_native_available()
        lib.pack_slice_p_sparse_rbsp.restype = ctypes.c_int64
        lib.pack_slice_p_sparse_rbsp.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int16),
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int16), ctypes.POINTER(ctypes.c_int16),
            ctypes.POINTER(ctypes.c_int16), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int16), ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
    except AttributeError:
        pass
    lib.emulation_prevent.restype = ctypes.c_int64
    lib.emulation_prevent.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ]
    try:
        lib.derive_skip_mvs.restype = None
        lib.derive_skip_mvs.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_int, ctypes.c_int,
        ]
    except AttributeError:
        pass  # stale .so; python fallback used
    try:
        # CABAC token-stream arithmetic coder (cabac_pack.cc) — absent
        # from a stale .so; callers gate on cabac_native_available()
        lib.cabac_encode_tokens.restype = ctypes.c_int64
        lib.cabac_encode_tokens.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint16),
            ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ]
    except AttributeError:
        pass
    return lib


def derive_skip_mvs_fast(mvs: np.ndarray, skip: np.ndarray) -> None:
    """Fill P_Skip MBs' motion vectors in place (8.4.1.1) from the coded
    MBs' MVs — the sparse downlink omits them. C when available, exact
    python mirror otherwise."""
    mbh, mbw = skip.shape
    lib = _load()
    if lib is not None and hasattr(lib, "derive_skip_mvs"):
        assert mvs.dtype == np.int32 and mvs.flags["C_CONTIGUOUS"]
        sk = np.ascontiguousarray(skip, np.uint8)
        lib.derive_skip_mvs(
            mvs.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            sk.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            mbh, mbw,
        )
        return
    from selkies_tpu.models.h264.numpy_ref import skip_mv_16x16

    for y in range(mbh):
        for x in range(mbw):
            if skip[y, x]:
                mvs[y, x] = skip_mv_16x16(mvs, x, y)


def native_available() -> bool:
    return _load() is not None


# Per-geometry scratch buffers reused across frames (the packer runs every
# 16 ms; per-frame multi-MB allocations would dominate small-slice cost).
# THREAD-LOCAL: the multi-session service packs N same-geometry streams
# concurrently (parallel/serving.py pack pool); a process-global buffer
# set raced across sessions and silently corrupted bitstreams (caught by
# the chaos suite's byte-identity check). Pack-pool threads are
# persistent, so per-thread reuse keeps the no-allocation steady state.
_scratch_tls = threading.local()


def _get_scratch(mbh: int, mbw: int, cap: int) -> dict[str, np.ndarray]:
    store = getattr(_scratch_tls, "by_geom", None)
    if store is None:
        store = _scratch_tls.by_geom = {}
    s = store.get((mbh, mbw))
    if s is None or len(s["rbsp"]) < cap:
        s = {
            "rbsp": np.empty(cap, np.uint8),
            "ebsp": np.empty(cap + cap // 2 + 16, np.uint8),
            "luma_tc": np.empty(mbh * 4 * mbw * 4, np.int32),
            "chroma_tc": np.empty(2 * mbh * 2 * mbw * 2, np.int32),
            # sparse-native packer's MV grid (skip MBs re-derived in C)
            "mv": np.empty(mbh * mbw * 2, np.int32),
        }
        store[(mbh, mbw)] = s
    return s


def _i32ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i16ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int16))


def pack_slice_native(
    fc: FrameCoeffs,
    p: StreamParams,
    frame_num: int = 0,
    idr: bool = True,
    idr_pic_id: int = 0,
    first_mb: int = 0,
) -> bytes:
    # first_mb rides entirely in the pre-built header bytes: the C packer
    # walks whatever (mbh, mbw) grid it is handed as ONE slice, which is
    # exactly the band-slice contract (neighbour context resets at the
    # grid's first row) — no native-code change needed for multi-slice.
    lib = _load()
    if lib is None:
        raise RuntimeError("libcavlc.so unavailable")
    mbh, mbw = fc.luma_mode.shape

    hdr = BitWriter()
    write_slice_header(hdr, p, SLICE_I, frame_num, idr=idr, idr_pic_id=idr_pic_id,
                       slice_qp=fc.qp, first_mb=first_mb)
    hdr_bytes, hdr_bits = hdr.get_partial()

    arrs = {
        name: np.ascontiguousarray(getattr(fc, name), dtype=np.int16)
        for name in ("luma_mode", "chroma_mode", "luma_dc", "luma_ac", "chroma_dc", "chroma_ac")
    }
    cap = mbh * mbw * 1024 + len(hdr_bytes) + 1024
    while True:
        s = _get_scratch(mbh, mbw, cap)
        rbsp = s["rbsp"]
        n = lib.pack_slice_rbsp(
            hdr_bytes, hdr_bits,
            _i16ptr(arrs["luma_mode"]), _i16ptr(arrs["chroma_mode"]),
            _i16ptr(arrs["luma_dc"]), _i16ptr(arrs["luma_ac"]),
            _i16ptr(arrs["chroma_dc"]), _i16ptr(arrs["chroma_ac"]),
            mbh, mbw,
            rbsp.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(rbsp),
            _i32ptr(s["luma_tc"]), _i32ptr(s["chroma_tc"]),
        )
        if n >= 0:
            break
        cap = len(rbsp) * 2  # pathological content; retry with more room
        if cap > (1 << 30):
            raise RuntimeError("pack_slice_rbsp overflow beyond 1 GiB")
    return _finish_nal(s, n, NAL_SLICE_IDR if idr else NAL_SLICE_NON_IDR)


def pack_slice_fast(fc, p, frame_num=0, idr=True, idr_pic_id=0,
                    first_mb=0) -> bytes:
    """Native packer when available, Python fallback otherwise."""
    if native_available():
        return pack_slice_native(fc, p, frame_num=frame_num, idr=idr,
                                 idr_pic_id=idr_pic_id, first_mb=first_mb)
    return pack_slice_py(fc, p, frame_num=frame_num, idr=idr,
                         idr_pic_id=idr_pic_id, first_mb=first_mb)


def _finish_nal(s: dict, n: int, nal_type: int) -> bytes:
    lib = _load()
    ebsp = s["ebsp"]
    m = lib.emulation_prevent(
        s["rbsp"].ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
        ebsp.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(ebsp),
    )
    if m < 0:
        raise RuntimeError("emulation_prevent overflow")
    return b"\x00\x00\x00\x01" + bytes([(3 << 5) | nal_type]) + ebsp[:m].tobytes()


def pack_slice_p_native(fc: PFrameCoeffs, p: StreamParams, frame_num: int,
                        ltr_ref: int | None = None,
                        mark_ltr: int | None = None,
                        mmco_evict: tuple = (),
                        first_mb: int = 0) -> bytes:
    lib = _load()
    if lib is None:
        raise RuntimeError("libcavlc.so unavailable")
    mbh, mbw = fc.skip.shape

    hdr = BitWriter()
    write_slice_header(hdr, p, SLICE_P, frame_num, idr=False, slice_qp=fc.qp,
                       ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                       mmco_evict=mmco_evict, first_mb=first_mb)
    hdr_bytes, hdr_bits = hdr.get_partial()

    mvs = np.ascontiguousarray(fc.mvs, dtype=np.int16)
    skip = np.ascontiguousarray(fc.skip, dtype=np.uint8)
    luma_ac = np.ascontiguousarray(fc.luma_ac, dtype=np.int16)
    chroma_dc = np.ascontiguousarray(fc.chroma_dc, dtype=np.int16)
    chroma_ac = np.ascontiguousarray(fc.chroma_ac, dtype=np.int16)
    cap = mbh * mbw * 1024 + len(hdr_bytes) + 1024
    while True:
        s = _get_scratch(mbh, mbw, cap)
        rbsp = s["rbsp"]
        n = lib.pack_slice_p_rbsp(
            hdr_bytes, hdr_bits,
            _i16ptr(mvs), skip.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            _i16ptr(luma_ac), _i16ptr(chroma_dc), _i16ptr(chroma_ac),
            mbh, mbw,
            rbsp.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(rbsp),
            _i32ptr(s["luma_tc"]), _i32ptr(s["chroma_tc"]),
        )
        if n >= 0:
            break
        cap = len(rbsp) * 2
        if cap > (1 << 30):
            raise RuntimeError("pack_slice_p_rbsp overflow beyond 1 GiB")
    return _finish_nal(s, n, NAL_SLICE_NON_IDR)


def cabac_native_available() -> bool:
    """True when libcavlc.so exports the CABAC arithmetic coder (a stale
    .so lacks it) and SELKIES_CABAC_NATIVE != 0."""
    if os.environ.get("SELKIES_CABAC_NATIVE", "1") == "0":
        return False
    lib = _load()
    return lib is not None and hasattr(lib, "cabac_encode_tokens")


def cabac_encode_tokens(states: np.ndarray, tokens: np.ndarray) -> bytes:
    """Run the token stream through the native arithmetic engine.
    Byte-identical to cabac.encode_tokens_py (tests/test_cabac.py)."""
    lib = _load()
    if lib is None or not hasattr(lib, "cabac_encode_tokens"):
        raise RuntimeError("libcavlc.so cabac coder unavailable")
    st = np.ascontiguousarray(states, np.uint8)
    tok = np.ascontiguousarray(tokens, np.uint16)
    # worst case ~1.03 bits/bin plus flush; 1 byte per token is generous
    cap = int(len(tok)) + 64
    while True:
        out = np.empty(cap, np.uint8)
        n = lib.cabac_encode_tokens(
            st.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            tok.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16)),
            len(tok),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
        )
        if n == -2:
            raise ValueError("token stream did not end in a TERM(1) flush")
        if n >= 0:
            return out[:n].tobytes()
        cap *= 2  # RUN/BYP tokens can expand past 1 byte/token
        if cap > (1 << 30):
            raise RuntimeError("cabac_encode_tokens overflow beyond 1 GiB")


def sparse_native_available() -> bool:
    """True when libcavlc.so exports the sparse-native P packer (a stale
    .so lacks it) and SELKIES_SPARSE_NATIVE != 0."""
    if os.environ.get("SELKIES_SPARSE_NATIVE", "1") == "0":
        return False
    lib = _load()
    return lib is not None and hasattr(lib, "pack_slice_p_sparse_rbsp")


def pack_slice_p_sparse_native(wire, p: StreamParams, frame_num: int, qp: int,
                               ltr_ref: int | None = None,
                               mark_ltr: int | None = None,
                               mmco_evict: tuple = (),
                               first_mb: int = 0) -> bytes:
    """Entropy-code one P slice straight from the sparse downlink wire
    views (compact.SparsePWire) — no dense coefficient scatter, no int16
    re-copy, no PFrameCoeffs. Byte-identical to cavlc.pack_slice_p fed
    the unpacked frame (the dense path stays as the equivalence oracle
    and the no-native fallback)."""
    lib = _load()
    if lib is None or not hasattr(lib, "pack_slice_p_sparse_rbsp"):
        raise RuntimeError("libcavlc.so sparse packer unavailable")
    mbh, mbw = wire.mbh, wire.mbw

    hdr = BitWriter()
    write_slice_header(hdr, p, SLICE_P, frame_num, idr=False, slice_qp=qp,
                       ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                       mmco_evict=mmco_evict, first_mb=first_mb)
    hdr_bytes, hdr_bits = hdr.get_partial()

    # sized for typical sparse content; pathological levels retry bigger.
    # The scratch is per-thread per-geometry and only ever grows, so the
    # steady state allocates nothing frame-to-frame.
    cap = len(hdr_bytes) + 4096 + 40 * wire.ns + 72 * wire.n
    while True:
        s = _get_scratch(mbh, mbw, cap)
        rbsp = s["rbsp"]
        n = lib.pack_slice_p_sparse_rbsp(
            hdr_bytes, hdr_bits,
            _i16ptr(wire.skip16), _i16ptr(wire.pairs16),
            wire.ns, 1 if wire.packed else 0,
            _i16ptr(wire.rows16), _i16ptr(wire.bitmaps), _i16ptr(wire.vals),
            wire.held, _i16ptr(wire.extra_rows), wire.n, len(wire.vals),
            mbh, mbw,
            rbsp.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), len(rbsp),
            _i32ptr(s["luma_tc"]), _i32ptr(s["chroma_tc"]), _i32ptr(s["mv"]),
        )
        if n >= 0:
            break
        if n == -2:
            raise ValueError(
                "sparse wire inconsistent: pair/row/value counts disagree "
                "with the skip bitmap or mbinfo words")
        cap = len(rbsp) * 2
        if cap > (1 << 30):
            raise RuntimeError("pack_slice_p_sparse_rbsp overflow beyond 1 GiB")
    return _finish_nal(s, n, NAL_SLICE_NON_IDR)


def pack_slice_p_fast(fc: PFrameCoeffs, p: StreamParams, frame_num: int,
                      ltr_ref: int | None = None,
                      mark_ltr: int | None = None,
                      mmco_evict: tuple = (),
                      first_mb: int = 0) -> bytes:
    """Native P-slice packer when available, Python fallback otherwise."""
    if native_available():
        return pack_slice_p_native(fc, p, frame_num, ltr_ref=ltr_ref,
                                   mark_ltr=mark_ltr, mmco_evict=mmco_evict,
                                   first_mb=first_mb)
    return pack_slice_p_py(fc, p, frame_num, ltr_ref=ltr_ref,
                           mark_ltr=mark_ltr, mmco_evict=mmco_evict,
                           first_mb=first_mb)
