"""Shared per-slice sparse P completion: fused downlink words → slice NAL.

Both host completion paths — the solo pipelined encoder's delta frames
(models/h264/encoder.py) and the band-parallel encoder's per-band slices
(parallel/bands.py) — finish a sparse P downlink the same way:

  1. read the fused prefix's need/row/non-skip counts
     (``p_sparse_*_need``) and feed the hint feedback loop;
  2. refetch the full live content when the hint-sized slice fell short;
  3. fetch the row spill past the fused cap (``fetch_rest``);
  4. hand the wire-format regions straight to the native C packer
     (``p_sparse_wire_views`` + ``pack_slice_p_sparse_native``) when
     it is available, else run the Python dense expansion
     (``unpack_p_sparse_*`` + ``pack_slice_p_fast``) — including the
     ns > nscap dense-header fallback fetch where the caller has one.

With ``device_bits=True`` the fused buffer is the entropy-wrapped
layout (encoder_core.pack_p_sparse_entropy): an 8-int32 meta prefix
whose mode flag says whether the payload is the unchanged sparse coeff
layout (the flow above, applied to the offset view) or the frame's
FINAL slice-data bits packed on device — in which case the host only
splices the slice header around the fetched words (``assemble_p_nal``)
and no coefficient unpack or CAVLC pack runs at all. That bits branch
is what turns a busy delta frame's completion into a near-zero host
tail (ISSUE 7 / PERF.md round 9).

PR 5 duplicated this flow per band; this module is the one definition
(flagged follow-up in CHANGES.md PR 5). The two callers differ only in
slice geometry (full frame vs one band), the ``first_mb`` slice-header
offset, and the LTR slice-header flags — all parameters here. Byte
output is identical to both former inline flows by construction
(tests/test_sparse_native_pack.py, tests/test_band_slices.py,
tests/test_device_entropy_sparse.py).
"""

from __future__ import annotations

import time
from typing import Callable

import numpy as np

from selkies_tpu.models.h264.compact import (
    ENTROPY_META16,
    p_sparse_entropy_meta,
    p_sparse_packed_need,
    p_sparse_var_need,
    p_sparse_wire_views,
    unpack_p_compact,
    unpack_p_sparse_packed,
    unpack_p_sparse_var,
)
from selkies_tpu.models.h264.device_cabac import assemble_p_cabac_nal
from selkies_tpu.models.h264.device_cavlc import assemble_p_nal
from selkies_tpu.models.h264.native import (
    pack_slice_p_fast,
    pack_slice_p_sparse_native,
    sparse_native_available,
)
from selkies_tpu.monitoring.tracing import tracer

__all__ = ["complete_sparse_slice", "fetch_rest"]


def _settle_device_bits(fused, need, note_need, link_bytes, prefix_bytes,
                        full_d):
    """Shared mode=1 completion plumbing — hint feedback, downlink-byte
    accounting and the hint-too-small refetch are identical for both
    entropy coders; only the payload parse after this differs. Returns
    the (possibly refetched) fused buffer."""
    if note_need is not None:
        note_need(need)
    if link_bytes is not None and prefix_bytes:
        link_bytes.add("down_bits", prefix_bytes)
    if need > len(fused):  # hint too small: refetch
        # span marks only the EXTRA transfer (tracing.py contract —
        # the main prefix fetch rode the caller's "fetch" span)
        with tracer.span("bits_fetch"):
            fused = np.asarray(full_d)
        if link_bytes is not None:
            link_bytes.add("down_bits_refetch", fused.nbytes)
    return fused


def fetch_rest(buf, n: int, base: int = 4096) -> np.ndarray:
    """Overflow path: rows [base, >=n) in power-of-two buckets (base=0
    fetches from the start, bucketed from 4096). Exactly two-ish fetch
    shapes per geometry keep the compile discipline of the prefix
    fetches (encoder.py PFX_SMALL)."""
    total = buf.shape[0]
    bucket = max(base, 4096)
    while bucket < n:
        bucket <<= 1
    if bucket >= total:
        return np.asarray(buf)[base:]
    return np.asarray(buf[base:bucket])


def complete_sparse_slice(
    fused: np.ndarray,
    *,
    mbh: int,
    mbw: int,
    nscap: int,
    cap_rows: int,
    qp: int,
    frame_num: int,
    params,
    packed: bool = False,
    device_bits: bool = False,
    full_d=None,
    buf_d=None,
    dense_d=None,
    link_bytes=None,
    prefix_bytes: int = 0,
    note_need: Callable[[int], None] | None = None,
    first_mb: int = 0,
    ltr_ref: int | None = None,
    mark_ltr: int | None = None,
    mmco_evict: tuple = (),
    entropy_coder: str = "cavlc",
    cabac_init_idc: int = 0,
) -> tuple[bytes, int, float, str]:
    """One P slice's fused sparse downlink → (nal, skipped_mbs,
    t_unpacked, downlink_mode).

    ``fused`` is the (possibly hint-sized) fetched prefix; ``full_d`` the
    full-length device handle for the shortfall refetch, ``buf_d`` the
    row-spill buffer, ``dense_d`` the dense header for the ns > nscap
    fallback (callers whose nscap equals the slice MB count pass None —
    that branch is structurally unreachable for them). ``t_unpacked`` is
    the unpack→pack boundary timestamp for the caller's stage split.

    ``prefix_bytes`` is the caller's already-fetched prefix size: the
    accounting lives here (not at the fetch site) because only the meta
    read knows whether those bytes were coefficient rows (``down_prefix``)
    or device bits (``down_bits``) — bench.py splits the per-frame
    downlink on exactly that stage-name prefix. ``downlink_mode`` is
    "bits" (device-entropy payload), "dense" (ns > nscap dense-header
    fallback) or "coeff" (sparse rows, either layout).
    """
    off = 0
    if device_bits:
        mode, nbits, trailing, nskip, ns = p_sparse_entropy_meta(fused)
        if mode == 1 and entropy_coder == "cabac":
            # device-token payload: interleave skip/terminate bins and
            # run the host arithmetic engine — no unpack, no host
            # binarization (the slice's mb token bodies came binarized
            # and context-indexed from the device)
            ntok = nbits  # the nbits meta slot carries ntok for cabac
            m = mbh * mbw
            sw = (m + 31) // 32
            nw = (ntok + 1) // 2
            base = ENTROPY_META16 + 2 * sw
            need = base + ns + 2 * nw
            fused = _settle_device_bits(fused, need, note_need,
                                        link_bytes, prefix_bytes, full_d)
            skip_words = (np.ascontiguousarray(
                fused[ENTROPY_META16:base]).view(np.int32)
                .astype(np.int64) & 0xFFFFFFFF)
            skip = (((skip_words[:, None] >> np.arange(32)) & 1)
                    .astype(bool).reshape(-1)[:m].reshape(mbh, mbw))
            counts = fused[base:base + ns].astype(np.int64)
            words = np.ascontiguousarray(
                fused[base + ns:base + ns + 2 * nw]).view(np.uint32)
            t_unpacked = time.perf_counter()
            with tracer.span("pack"):
                nal = assemble_p_cabac_nal(
                    words, ntok, counts, skip, params, frame_num, qp,
                    ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                    mmco_evict=mmco_evict, first_mb=first_mb,
                    cabac_init_idc=cabac_init_idc)
            return nal, nskip, t_unpacked, "cabac"
        if mode == 1:
            # device-entropy payload: the words ARE the slice data —
            # splice the header, no unpack, no host CAVLC
            nw = (nbits + 31) // 32
            need = ENTROPY_META16 + 2 * nw
            fused = _settle_device_bits(fused, need, note_need,
                                        link_bytes, prefix_bytes, full_d)
            words = np.ascontiguousarray(
                fused[ENTROPY_META16:ENTROPY_META16 + 2 * nw]).view(np.uint32)
            t_unpacked = time.perf_counter()
            with tracer.span("pack"):
                nal = assemble_p_nal(
                    words, nbits, trailing, params, frame_num, qp,
                    ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                    mmco_evict=mmco_evict, first_mb=first_mb)
            return nal, nskip, t_unpacked, "bits"
        # mode 0: the payload is the unchanged sparse layout at an offset
        off = ENTROPY_META16
        fused = fused[off:]
    if link_bytes is not None and prefix_bytes:
        link_bytes.add("down_prefix", prefix_bytes)
    downlink_mode = "coeff"
    with tracer.span("unpack"):
        need_fn = p_sparse_packed_need if packed else p_sparse_var_need
        need, n, ns = need_fn(fused, mbh, mbw, nscap, cap_rows)
        if note_need is not None:
            note_need(need + off)
        if need > len(fused):  # hint too small: refetch the live content
            fused = np.asarray(full_d)[off:]
            if link_bytes is not None:
                link_bytes.add("down_refetch", fused.nbytes)
        extra = None
        if n > cap_rows:  # rows spilled past the fused buffer
            extra = fetch_rest(buf_d, n, cap_rows)
            if link_bytes is not None:
                link_bytes.add("down_spill", extra.nbytes)
        wire = pfc = None
        if (ns <= nscap and entropy_coder == "cavlc"
                and sparse_native_available()):
            wire = p_sparse_wire_views(
                fused, mbh, mbw, nscap, cap_rows, packed, extra)
        if wire is None:
            unpack = unpack_p_sparse_packed if packed else unpack_p_sparse_var
            pfc, rows = unpack(fused, qp, mbh, mbw, nscap, cap_rows, extra)
            if pfc is None:  # ns > nscap: dense-header fallback fetch
                if dense_d is None:
                    raise RuntimeError(
                        "ns > nscap with no dense fallback buffer (caller "
                        "geometry should make this unreachable)")
                dense = np.asarray(dense_d)
                if link_bytes is not None:
                    link_bytes.add("down_spill", dense.nbytes)
                pfc = unpack_p_compact(dense, rows, qp)
                downlink_mode = "dense"
    t_unpacked = time.perf_counter()
    with tracer.span("pack"):
        if wire is not None:
            nal = pack_slice_p_sparse_native(
                wire, params, frame_num, qp, ltr_ref=ltr_ref,
                mark_ltr=mark_ltr, mmco_evict=mmco_evict, first_mb=first_mb)
            skipped = mbh * mbw - wire.ns
        elif entropy_coder == "cabac":
            # a Main-profile stream cannot mix in CAVLC slices
            # (entropy_coding_mode_flag is PPS-scoped) — the coefficient
            # fallback packs through the host CABAC coder instead
            from selkies_tpu.models.h264.cabac import pack_slice_p_cabac

            nal = pack_slice_p_cabac(
                pfc, params, frame_num, ltr_ref=ltr_ref,
                mark_ltr=mark_ltr, mmco_evict=mmco_evict,
                first_mb=first_mb, cabac_init_idc=cabac_init_idc)
            skipped = int(pfc.skip.sum())
        else:
            nal = pack_slice_p_fast(
                pfc, params, frame_num=frame_num, ltr_ref=ltr_ref,
                mark_ltr=mark_ltr, mmco_evict=mmco_evict, first_mb=first_mb)
            skipped = int(pfc.skip.sum())
    return nal, skipped, t_unpacked, downlink_mode
