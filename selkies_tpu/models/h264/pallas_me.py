"""Pallas fused motion-estimation + motion-compensation kernel.

Replaces encoder_core.hier_me_mc's two lax.scan walks (cost + pred) with
ONE kernel that keeps each MB row's reference window resident in VMEM:

  * grid = (mbh,): one program per 16-pixel MB row;
  * the luma/chroma reference windows for the row are DMA'd HBM->VMEM
    once (the XLA scans re-read the full padded plane from HBM for every
    one of the ~76 candidates — the dominant cost of the device step);
  * per-candidate SAD reduces 16x16 blocks via an MXU matmul against a
    0/1 block-indicator matrix (f32 exact: SAD*scale + rank < 2^23);
  * cost argmin and prediction selection fuse into the same candidate
    loop — a running min with payload blend, so the winner's luma and
    half-pel chroma prediction are produced in the same pass.

Bit-exactness contract: identical outputs to encoder_core.hier_me_mc
(tests/test_pallas_me.py asserts array equality), which mirrors
numpy_ref.hier_search_me + mc_luma/mc_chroma. All integer quantities
stay below 2^23 so the f32 cost path is exact; chroma bilinear runs in
int32 inside the kernel.

The reference's analogue of this file is NVENC silicon
(gstwebrtc_app.py:260-367) — there is nothing to port; this is the
TPU-native design the hardware wants.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from selkies_tpu.models.h264.numpy_ref import MV_PAD

_LANES = 128
_LUMA_WIN = 96  # rows of padded luma ref per program: 16 + 2*MV_PAD = 96
_CHROMA_WIN = 96  # rows of padded chroma ref per program (needs 8+2*(MV_PAD//2+1))
_CAND_GROUP = 8  # candidates per fat row-select matmul (G*16 = 128 MXU rows)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _me_mc_kernel(cand_ref, cur_ref, ry_ref, ru_ref, rv_ref, m_ref, mt_ref,
                  mct_ref, predy_ref, predu_ref, predv_ref, mvx_ref, mvy_ref,
                  ry_w, ru_w, rv_w, sems):
    h16, w = cur_ref.shape
    cw = predu_ref.shape[1]
    ncand = cand_ref.shape[0]
    i = pl.program_id(0)

    cy_dma = pltpu.make_async_copy(ry_ref.at[pl.ds(i * 16, _LUMA_WIN), :], ry_w, sems.at[0])
    cu_dma = pltpu.make_async_copy(ru_ref.at[pl.ds(i * 8, _CHROMA_WIN), :], ru_w, sems.at[1])
    cv_dma = pltpu.make_async_copy(rv_ref.at[pl.ds(i * 8, _CHROMA_WIN), :], rv_w, sems.at[2])
    cy_dma.start()
    cu_dma.start()
    cv_dma.start()
    cy_dma.wait()
    cu_dma.wait()
    cv_dma.wait()

    cur = cur_ref[:]  # (16, w) f32
    predy_ref[:] = jnp.zeros((16, w), jnp.int32)
    predu_ref[:] = jnp.zeros((8, cw), jnp.int32)
    predv_ref[:] = jnp.zeros((8, cw), jnp.int32)

    wp = ry_w.shape[1]
    cwp = ru_w.shape[1]
    # bf16 windows for the one-hot row-select matmuls: pixel values
    # <= 255 are exact in bf16 and each dot product has exactly one
    # nonzero term, so the f32-accumulated result is exact at 2x MXU rate
    winf = ry_w[:].astype(jnp.float32).astype(jnp.bfloat16)  # (96, wp)
    ruf = ru_w[:].astype(jnp.float32).astype(jnp.bfloat16)
    rvf = rv_w[:].astype(jnp.float32).astype(jnp.bfloat16)

    # scale = next power of two above ncand (static; matches golden model)
    scale = float(1 << int(ncand - 1).bit_length())

    # candidates are processed in groups of G: one fat (G*16, 96) one-hot
    # row-select matmul materializes all G shifted row-sets per step (the
    # MXU is ~idle at 16 rows; 128 rows is its native height), then G
    # cheap vector updates fold each candidate into the running best.
    G = _CAND_GROUP
    n_groups = ncand // G

    row_iota = jax.lax.broadcasted_iota(jnp.int32, (G * 16, _LUMA_WIN), 0)
    col_iota = jax.lax.broadcasted_iota(jnp.int32, (G * 16, _LUMA_WIN), 1)
    row_iota9 = jax.lax.broadcasted_iota(jnp.int32, (9, _CHROMA_WIN), 0)
    col_iota9 = jax.lax.broadcasted_iota(jnp.int32, (9, _CHROMA_WIN), 1)

    def body(g, carry):
        best, mvx, mvy = carry
        c0 = g * G
        dys = [cand_ref[c0 + k, 1] for k in range(G)]
        dxs = [cand_ref[c0 + k, 0] for k in range(G)]
        # win row for stacked row rr = 16k + r is MV_PAD + dy_k + r
        dy_rows = jnp.concatenate(
            [jnp.full((16, 1), d, jnp.int32) for d in dys], axis=0)
        sel = (col_iota == (row_iota % 16) + dy_rows + MV_PAD).astype(jnp.bfloat16)
        rows_g = jnp.dot(sel, winf, preferred_element_type=jnp.float32)  # (G*16, wp)

        shs = []
        rowsums = []
        for k in range(G):
            sh = pltpu.roll(rows_g[16 * k:16 * k + 16, :],
                            wp - MV_PAD - dxs[k], 1)[:, 0:w]
            shs.append(sh)
            rowsums.append(jnp.sum(jnp.abs(cur - sh), axis=0, keepdims=True))
        rs = jnp.concatenate(rowsums, axis=0)  # (G, w)
        mbsum = jnp.dot(rs, m_ref[:], preferred_element_type=jnp.float32)  # (G, 128)

        for k in range(G):
            c = c0 + k
            cost = mbsum[k:k + 1, :] * scale + c.astype(jnp.float32)
            better = cost < best
            best = jnp.where(better, cost, best)
            bf = better.astype(jnp.float32)
            dx, dy = dxs[k], dys[k]
            mvx = jnp.where(better, dx, mvx)
            mvy = jnp.where(better, dy, mvy)
            sh = shs[k]

            # prediction blend only when this candidate actually won some
            # MB: typical rows improve a handful of times over ~76 cands
            @pl.when(jnp.max(bf) > 0.0)
            def _(bf=bf, sh=sh, dx=dx, dy=dy):
                mask_y = jnp.dot(bf, mt_ref[:], preferred_element_type=jnp.float32)
                predy_ref[:] = jnp.where(mask_y > 0.5, sh.astype(jnp.int32), predy_ref[:])

                # chroma half-pel bilinear (8.4.2.2.2); one-hot select is
                # exact in f32 (values <= 255), arithmetic in int32
                cx = jax.lax.shift_right_arithmetic(dx, 1)
                cyy = jax.lax.shift_right_arithmetic(dy, 1)
                xf = 4 * jax.lax.bitwise_and(dx, 1)
                yf = 4 * jax.lax.bitwise_and(dy, 1)
                selc = (col_iota9 == row_iota9 + (MV_PAD + cyy)).astype(jnp.bfloat16)
                mask_c = jnp.dot(bf, mct_ref[:], preferred_element_type=jnp.float32) > 0.5

                def blend(winc):
                    rows9 = jnp.dot(selc, winc, preferred_element_type=jnp.float32)
                    rot = pltpu.roll(rows9, cwp - MV_PAD - cx, 1).astype(jnp.int32)
                    a = rot[0:8, 0:cw]
                    b = rot[0:8, 1:cw + 1]
                    cc = rot[1:9, 0:cw]
                    dd = rot[1:9, 1:cw + 1]
                    return jax.lax.shift_right_arithmetic(
                        (8 - xf) * (8 - yf) * a + xf * (8 - yf) * b
                        + (8 - xf) * yf * cc + xf * yf * dd + 32, 6)

                predu_ref[:] = jnp.where(mask_c, blend(ruf), predu_ref[:])
                predv_ref[:] = jnp.where(mask_c, blend(rvf), predv_ref[:])

        return best, mvx, mvy

    init = (
        jnp.full((1, _LANES), 3.4e38, jnp.float32),
        jnp.zeros((1, _LANES), jnp.int32),
        jnp.zeros((1, _LANES), jnp.int32),
    )
    _, mvx, mvy = jax.lax.fori_loop(0, n_groups, body, init)
    mvx_ref[pl.ds(i, 1), :] = mvx
    mvy_ref[pl.ds(i, 1), :] = mvy


@functools.partial(jax.jit, static_argnames=("interpret",))
def _me_mc_call(cands, cur, ry_pad, ru_pad, rv_pad, interpret=False):
    h, w = cur.shape
    mbh, mbw = h // 16, w // 16
    ch, cw = h // 2, w // 2
    if mbw > _LANES:
        raise ValueError(f"width {w} exceeds the kernel's {_LANES}-MB row limit")
    ncand = cands.shape[0]

    # pad refs so every program's DMA window is in-bounds
    wp = _round_up(w + 2 * MV_PAD, _LANES)
    hp = _round_up(16 * (mbh - 1) + _LUMA_WIN, 32)
    cwp = _round_up(cw + 2 * MV_PAD, _LANES)
    chp = _round_up(8 * (mbh - 1) + _CHROMA_WIN, 32)
    # int32 planes: tpu.DynamicRotate (the in-kernel shift) is 32-bit only
    ry = jnp.pad(ry_pad.astype(jnp.int32),
                 ((0, hp - ry_pad.shape[0]), (0, wp - ry_pad.shape[1])))
    ru = jnp.pad(ru_pad.astype(jnp.int32),
                 ((0, chp - ru_pad.shape[0]), (0, cwp - ru_pad.shape[1])))
    rv = jnp.pad(rv_pad.astype(jnp.int32),
                 ((0, chp - rv_pad.shape[0]), (0, cwp - rv_pad.shape[1])))

    # 0/1 block-indicator mats: M sums 16-pixel groups, Mc masks 8-pixel
    # groups; MT/McT broadcast an MB-lane mask back onto pixels
    cols = np.arange(w) // 16
    m = jnp.asarray((cols[:, None] == np.arange(_LANES)[None, :]).astype(np.float32))
    ccols = np.arange(cw) // 8
    mct = jnp.asarray((np.arange(_LANES)[:, None] == ccols[None, :]).astype(np.float32))

    grid = (mbh,)
    in_specs = [
            pl.BlockSpec(memory_space=pltpu.SMEM),  # cands (ncand, 2)
            pl.BlockSpec((16, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),  # ry (DMA'd manually)
            pl.BlockSpec(memory_space=pltpu.ANY),  # ru
            pl.BlockSpec(memory_space=pltpu.ANY),  # rv
            pl.BlockSpec((w, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_LANES, w), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((_LANES, cw), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    out_specs = [
            pl.BlockSpec((16, w), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, cw), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((8, cw), lambda i: (i, 0), memory_space=pltpu.VMEM),
            # mv outputs ride one full-array VMEM block (grid is sequential
            # on TPU); each program writes its own row
            pl.BlockSpec((mbh, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((mbh, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM),
    ]
    predy, predu, predv, mvx, mvy = pl.pallas_call(
        _me_mc_kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((h, w), jnp.int32),
            jax.ShapeDtypeStruct((ch, cw), jnp.int32),
            jax.ShapeDtypeStruct((ch, cw), jnp.int32),
            jax.ShapeDtypeStruct((mbh, _LANES), jnp.int32),
            jax.ShapeDtypeStruct((mbh, _LANES), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_LUMA_WIN, wp), jnp.int32),
            pltpu.VMEM((_CHROMA_WIN, cwp), jnp.int32),
            pltpu.VMEM((_CHROMA_WIN, cwp), jnp.int32),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=interpret,
    )(cands, cur.astype(jnp.float32), ry, ru, rv, m, jnp.transpose(m), mct)
    mvs = jnp.stack([mvx[:, :mbw], mvy[:, :mbw]], axis=-1)
    return mvs, predy, predu, predv


def hier_me_mc_pallas(cur, ref_y, ry_pad, ru_pad, rv_pad, *, interpret=None,
                      dy_max=None, dx_max=None, coarse=None):
    """Drop-in replacement for encoder_core.hier_me_mc (same signature,
    bit-identical outputs). Coarse candidate voting stays in XLA (tiny);
    the refine+MC walk runs in the fused kernel.

    dy_max (static int) band-clamps the candidate window for the
    band-sliced step (encoder_core.encode_band_p_planes): with a clamped
    vertical reach every row each program DMAs from the `ry_pad` window
    into VMEM is real reference content from the band's halo slab, so a
    band's kernel never depends on rows resident on another chip. The
    kernel body is unchanged — the clamp lands in the candidate list,
    keeping the rank/tie-break order bit-identical to hier_me_mc.
    dx_max is the horizontal mirror for the 2D tile grid
    (encoder_core.encode_tile_p_planes), and ``coarse`` injects the tile
    grid's row-merged (TOPK, 2) coarse candidate list — both land in the
    candidate list exactly like dy_max; the kernel is untouched."""
    from selkies_tpu.models.h264 import encoder_core as core

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if coarse is None:
        coarse = core.coarse_vote_candidates_jnp(cur, ref_y)
    cands = core._refine_cands_jnp(coarse, dy_max, dx_max)
    # pad to a multiple of the kernel's candidate group with zero-MV
    # duplicates: same SAD as the rank-0 zero MV but a later rank, so a
    # padded slot can never win (cost = sad*scale + rank is all-distinct)
    pad = (-cands.shape[0]) % _CAND_GROUP
    if pad:
        cands = jnp.concatenate([cands, jnp.zeros((pad, 2), jnp.int32)])
    return _me_mc_call(cands, cur, ry_pad, ru_pad, rv_pad, interpret=interpret)
