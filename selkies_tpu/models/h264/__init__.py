"""TPU-native H.264 encoder (``tpuh264enc``).

Replaces the reference's nvh264enc/vah264enc/x264enc/openh264enc family
(gstwebrtc_app.py:260-367,475-508,609-665) with a JAX/Pallas encode core and
a host-side CAVLC bit packer.
"""
