"""CAVLC entropy coding ON DEVICE: P-frame slice-data bits from XLA,
with cost proportional to frame ACTIVITY, not frame area.

The compact-coefficient downlink still ships multi-MB tensors for busy
frames (a 1080p full-frame change is ~4.5 MB of nonzero rows — the
dominant cost on a per-byte-priced link, PERF.md). This module moves the
entire §9.2 entropy coder into the frame jit, so what crosses the link
is the final slice-data bitstream (~50-300 KB), exactly like the
reference's NVENC emits finished bitstreams on-GPU.

Two entry points share one implementation:

* ``pack_p_slice_bits`` — the full-grid coder (every MB pays), used as
  the fixed-shape oracle by tests and the profiler;
* ``pack_p_slice_bits_active`` — the production coder: the skip map and
  per-MB TotalCoeff are known before any bit is written, so the coded
  (non-skip) MBs are COMPACTED into a dense prefix and the expensive
  per-block work (VLC one-hot LUT contractions, level suffix chains,
  prefix-sum bit concatenation) runs over a bucketed padded count of
  active MBs — a typing frame with ~200 live MBs pays ~256 MBs of
  entropy-coding work instead of the full 8160-MB grid. Buckets are
  powers-of-two-ish (`bits_buckets`) selected per frame ON DEVICE via
  ``lax.switch`` — one executable, no recompiles (the same discipline
  as the NSCAP dense fallback in encoder_core.pack_p_sparse_packed).
  Compaction preserves raster order and padded slots emit zero bits,
  so the merged stream is bit-identical to the full-grid coder.

Everything vectorizes: VLC tables become constant-array gathers; the
per-level suffix-length adaptation and run_before chains are unrolled
16-step walks across ALL blocks at once; nC neighbour contexts are plain
shifted-grid reads; the serial-looking bit concatenation is two levels
of prefix-sum offsets + shift/scatter-add (bit-disjoint, so add == or).

The host prepends the slice header (variable length, so the device
stream is bit-shifted to the header tail — ``first_mb_in_slice`` for a
band slice lives in that header, so band bits need no device change),
appends the trailing skip_run + rbsp trailing bits, and runs emulation
prevention (C++). Output is BIT-IDENTICAL to cavlc.pack_slice_p
(tests/test_device_cavlc.py, tests/test_device_entropy_sparse.py).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from selkies_tpu.models.h264 import tables as T
from selkies_tpu.models.h264.cavlc import INTER_CBP_TO_CODENUM

__all__ = [
    "pack_p_slice_bits",
    "pack_p_slice_bits_active",
    "bits_buckets",
    "device_entropy_default",
    "entropy_coder_default",
    "resolve_entropy",
    "BITS_MIN_MBS_DEFAULT",
    "WORD_CAP_DEFAULT",
]

# A delta/band P slice with at least this many live (non-skip) MBs ships
# its final slice bits; below it the sparse coefficient downlink is
# already small and its host pack near-free. SELKIES_BITS_MIN_MBS
# overrides (the density-threshold knob, docs/device_entropy.md).
BITS_MIN_MBS_DEFAULT = 512


def device_entropy_default(explicit=None) -> bool:
    """Resolve the device-entropy knob: an explicit constructor argument
    wins, then SELKIES_DEVICE_ENTROPY=0/1, then auto — on for real TPU
    backends, off on CPU, where the "device" coder competes with the
    host pack for the same cores and only adds compile time (the
    SELKIES_PALLAS_ME dispatch discipline)."""
    if explicit is not None:
        return bool(explicit)
    import os

    env = os.environ.get("SELKIES_DEVICE_ENTROPY", "")
    if env == "0":
        return False
    if env:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def entropy_coder_default(explicit=None) -> str:
    """Resolve the entropy-coder knob: an explicit constructor argument
    wins, then SELKIES_ENTROPY_CODER=cavlc/cabac/auto, else cavlc (the
    Baseline-profile default every pre-CABAC byte contract was recorded
    against). ``auto`` picks cabac on real TPU backends and cavlc on
    CPU — same dispatch discipline as device_entropy_default: the
    CABAC tail costs a host arithmetic-engine pass per slice, which
    the Main-profile bitrate win pays for on a TPU-fed stream but not
    on a CPU backend already contending for the same cores."""
    coder = explicit
    if coder is None:
        import os

        coder = os.environ.get("SELKIES_ENTROPY_CODER", "") or "cavlc"
    coder = str(coder).lower()
    if coder == "auto":
        try:
            return "cabac" if jax.default_backend() == "tpu" else "cavlc"
        except Exception:
            return "cavlc"
    if coder not in ("cavlc", "cabac"):
        raise ValueError(
            f"entropy_coder must be cavlc|cabac|auto, got {coder!r}")
    return coder


def resolve_entropy(m: int, device_entropy=None, bits_min_mbs=None,
                    entropy_coder=None):
    """One resolver for the device-entropy knobs, shared by the solo and
    banded encoders -> (enabled, min_mbs, bits_words, consts).

    `m` is the slice MB count (full grid, or one band). `consts` is the
    (bits_words, min_mbs, buckets, coder) tuple the jitted
    encoder_core.pack_p_sparse_entropy closes over — None when the
    feature is off. For CAVLC bits_words is the bit-payload cap in
    uint32 words (~16 words/MB covers busy desktop residuals, clamped
    to 256 KB); for CABAC it is the token-word cap
    (device_cabac.cabac_tok_words) since the payload is the 16-bit
    token IR, not final bits."""
    enabled = device_entropy_default(device_entropy)
    coder = entropy_coder_default(entropy_coder)
    if bits_min_mbs is None:
        import os

        try:
            bits_min_mbs = int(os.environ.get("SELKIES_BITS_MIN_MBS", "")
                               or BITS_MIN_MBS_DEFAULT)
        except ValueError:
            bits_min_mbs = BITS_MIN_MBS_DEFAULT
    min_mbs = max(0, int(bits_min_mbs))
    if coder == "cabac":
        from selkies_tpu.models.h264.device_cabac import cabac_tok_words

        bits_words = cabac_tok_words(m)
    else:
        bits_words = min(1 << 16, max(1024, 16 * int(m)))
    consts = ((bits_words, min_mbs, bits_buckets(m), coder)
              if enabled else None)
    return enabled, min_mbs, bits_words, consts

# ---------------------------------------------------------------------------
# VLC tables as dense arrays (generated from the FFmpeg-validated
# functions in tables.py, so the two representations cannot drift)
# ---------------------------------------------------------------------------

# coeff_token: class 0..2 -> nc buckets [0,2) [2,4) [4,8); class 3 = nc>=8
# (computed arithmetically); class 4 = chroma DC (nc == -1).
_CT_VAL = np.zeros((5, 17, 4), np.int32)
_CT_BITS = np.zeros((5, 17, 4), np.int32)
for cls, nc_probe in enumerate((0, 2, 4, 8, -1)):
    for total in range(17):
        for t1 in range(min(total, 3) + 1):
            if nc_probe == -1 and total > 4:
                continue
            v, b = T.coeff_token_code(nc_probe, total, t1)
            _CT_VAL[cls, total, t1] = v
            _CT_BITS[cls, total, t1] = b

_TZ_VAL = np.zeros((17, 16), np.int32)
_TZ_BITS = np.zeros((17, 16), np.int32)
for total in range(1, 16):
    for tz in range(0, 16 - total + 1):
        v, b = T.total_zeros_code(total, tz, chroma_dc=False)
        _TZ_VAL[total, tz] = v
        _TZ_BITS[total, tz] = b
_TZC_VAL = np.zeros((4, 4), np.int32)
_TZC_BITS = np.zeros((4, 4), np.int32)
for total in range(1, 4):
    for tz in range(0, 4 - total + 1):
        v, b = T.total_zeros_code(total, tz, chroma_dc=True)
        _TZC_VAL[total, tz] = v
        _TZC_BITS[total, tz] = b

# run_before: zeros_left clamps at 7 in the spec table; run <= 14
_RB_VAL = np.zeros((15, 15), np.int32)
_RB_BITS = np.zeros((15, 15), np.int32)
for zl in range(1, 15):
    for run in range(0, zl + 1):
        v, b = T.run_before_code(zl, run)
        _RB_VAL[zl, run] = v
        _RB_BITS[zl, run] = b

_ZIGZAG = np.asarray(T.ZIGZAG_FLAT, np.int32)            # (16,)
_CBP_CODENUM = np.asarray(INTER_CBP_TO_CODENUM, np.int32)

# luma 4x4 blocks in coding order -> (x4, y4); block index within MB
_LUMA_ORDER = np.asarray(
    [[x4, y4] for x4, y4 in T.LUMA_BLOCK_ORDER], np.int32
)  # (16, 2)
_CHROMA_ORDER = np.asarray([[x, y] for x, y in T.CHROMA_BLOCK_ORDER], np.int32)

WORD_CAP_DEFAULT = 1 << 17  # 512 KB frame bitstream capacity


def _lut(idx, pair: np.ndarray):
    """(value, bits) VLC lookup via a one-hot f32 matmul.

    pair: (N, 2) np table. Per-element gathers price ~17 ns on v5e — a
    (B, 15) run_before lookup pair costs 30+ ms as a gather and ~1 ms as
    an MXU contraction (tools/profile_device_entropy.py). f32 is exact for
    every VLC value (< 2^24)."""
    n = pair.shape[0]
    flat = idx.reshape(-1)
    oh = (flat[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]).astype(jnp.float32)
    out = jnp.dot(oh, jnp.asarray(pair, jnp.float32),
                  preferred_element_type=jnp.float32)
    return (out[:, 0].reshape(idx.shape).astype(jnp.int32),
            out[:, 1].reshape(idx.shape).astype(jnp.int32))


_RB_PAIR = np.stack([_RB_VAL.reshape(-1), _RB_BITS.reshape(-1)], 1).astype(np.float32)
_TZ_PAIR = np.stack([_TZ_VAL.reshape(-1), _TZ_BITS.reshape(-1)], 1).astype(np.float32)
_TZC_PAIR = np.stack([_TZC_VAL.reshape(-1), _TZC_BITS.reshape(-1)], 1).astype(np.float32)
_CT_PAIR = np.stack([_CT_VAL.reshape(-1), _CT_BITS.reshape(-1)], 1).astype(np.float32)


def _ue_bits(v):
    """Exp-Golomb codeword for v (vectorized): (value, nbits)."""
    v1 = v + 1
    # floor(log2(v1)): count significant bits - 1
    nb = 32 - jnp.clip(_clz32(v1), 0, 31)
    return v1, 2 * nb - 1


def _clz32(x):
    """Count leading zeros of a positive int32 (vectorized)."""
    x = x.astype(jnp.uint32)
    n = jnp.zeros_like(x, jnp.int32)
    for shift in (16, 8, 4, 2, 1):
        big = x >= (1 << shift)
        n = jnp.where(big, n + shift, n)
        x = jnp.where(big, x >> shift, x)
    return 31 - n


def _se_bits(v):
    """Signed Exp-Golomb: map se value -> ue codeword."""
    code = jnp.where(v > 0, 2 * v - 1, -2 * v)
    return _ue_bits(code)


def _level_bits(level_code, suffix_len):
    """Two (value, nbits) pairs — prefix codeword and suffix — for one
    level (9.2.2.1), matching cavlc._write_level exactly. Split keeps
    every emission slot <= 28 bits (a 64-bit pack lane covers any slot
    start within a word).

    Extended prefixes (16+) are solved arithmetically: with
    x = lc_adj - (15 << sl) + 2^12, prefix p covers x in
    [2^(p-3), 2^(p-2)), so p = floor(log2 x) + 3 — a 5-step clz instead
    of a 12-iteration search (this runs on every level of every block)."""
    lc0 = level_code
    lc_adj = jnp.where((suffix_len == 0) & (lc0 >= 30), lc0 - 15, lc0)
    sl = jnp.maximum(suffix_len, 0)
    prefix = lc_adj >> sl
    # regular: prefix zeros + 1, then sl suffix bits
    v1 = jnp.ones_like(lc0)
    b1 = prefix + 1
    v2 = lc_adj & ((jnp.int32(1) << sl) - 1)
    b2 = sl
    # escape: prefix 15 (16-bit '...1'), 12-bit suffix
    esc = lc_adj - (jnp.int32(15) << sl)
    in_esc = (prefix >= 15) & (esc < (1 << 12))
    b1 = jnp.where(in_esc, 16, b1)
    v2 = jnp.where(in_esc, jnp.clip(esc, 0, (1 << 12) - 1), v2)
    b2 = jnp.where(in_esc, 12, b2)
    # extended prefixes 16+
    x = jnp.maximum(esc + (1 << 12), 1)
    nb = 31 - _clz32(x)  # floor(log2 x)
    ext = (prefix >= 15) & ~in_esc
    b1 = jnp.where(ext, nb + 4, b1)          # pfx + 1 = (nb + 3) + 1
    v2 = jnp.where(ext, x - (jnp.int32(1) << nb), v2)
    b2 = jnp.where(ext, nb, b2)              # pfx - 3
    # suffix_len==0 specials
    small = (suffix_len == 0) & (lc0 < 14)
    b1 = jnp.where(small, lc0 + 1, b1)
    v2 = jnp.where(small, 0, v2)
    b2 = jnp.where(small, 0, b2)
    mid = (suffix_len == 0) & (lc0 >= 14) & (lc0 < 30)
    b1 = jnp.where(mid, 15, b1)
    v2 = jnp.where(mid, lc0 - 14, v2)
    b2 = jnp.where(mid, 4, b2)
    return v1, b1, v2, b2


def _encode_blocks(coeffs, nc, chroma_dc: bool):
    """CAVLC-encode a batch of residual blocks.

    coeffs: (B, L) int32 scan-order coefficients (L = 16, 15 or 4);
    nc: (B,) int32 neighbour context (-1 for chroma DC).
    Returns (vals (B, S), bits (B, S), total (B,)) — S emission slots in
    order; bits==0 slots contribute nothing.
    """
    B, L = coeffs.shape
    nz = coeffs != 0
    total = nz.sum(-1).astype(jnp.int32)
    # reverse-scan-order nonzero compaction WITHOUT argsort (sorts are
    # ~30 ms at frame scale on TPU; this one-hot contraction is ~free):
    # walking the reversed block, the k-th nonzero seen is slot k
    rev = coeffs[:, ::-1]
    nzr = rev != 0
    rank = jnp.cumsum(nzr, -1, dtype=jnp.int32) - 1
    oh = ((rank[:, :, None] == jnp.arange(L, dtype=jnp.int32)[None, None, :])
          & nzr[:, :, None]).astype(jnp.int32)
    val_rev = jnp.einsum("blk,bl->bk", oh, rev)
    pos_of = jnp.broadcast_to((L - 1 - jnp.arange(L, dtype=jnp.int32))[None, :], (B, L))
    pos_rev = jnp.einsum("blk,bl->bk", oh, pos_of)
    idx = jnp.arange(L, dtype=jnp.int32)[None, :]
    valid = idx < total[:, None]

    # trailing ones: leading run of |1| in val_rev, capped at 3
    is_one = (jnp.abs(val_rev) == 1) & valid
    run1 = jnp.cumprod(is_one, axis=-1, dtype=jnp.int32)
    t1 = jnp.minimum(run1.sum(-1), 3).astype(jnp.int32)

    # coeff_token
    cls = jnp.where(
        nc < 0, 4, jnp.where(nc < 2, 0, jnp.where(nc < 4, 1, jnp.where(nc < 8, 2, 3)))
    )
    ct_val, ct_bits = _lut(cls * 68 + total * 4 + t1, _CT_PAIR)
    # nc >= 8: arithmetic FLC (class 3 table rows were generated for nc=8;
    # they ARE the FLC — generated from the same function, so no special
    # case needed here)

    # Slot layout (emission order): token, 3 t1 signs, 2L interleaved
    # level (prefix, suffix) pairs, total_zeros, L-1 run_befores. The
    # segments are built separately and CONCATENATED once — strided
    # .at[].set() column writes into a (B, S) buffer relayout the whole
    # array per write on TPU.
    sign_v, sign_b = [], []
    for k in range(3):
        sign = (val_rev[:, k] < 0).astype(jnp.int32)
        use = (k < t1) & (total > 0)
        sign_v.append(jnp.where(use, sign, 0))
        sign_b.append(jnp.where(use, 1, 0))

    # levels after the trailing ones. The suffix-length adaptation is the
    # only sequential dependency (~10 ops/step); the codeword
    # construction (_level_bits with its escape/extended-prefix logic)
    # depends only on (level, suffix_len_before, is_first), so it runs
    # ONCE vectorized over all (L, B) slots. The L-step walk is UNROLLED
    # in Python: a lax.scan at this width pays ~1.5 ms of per-step launch
    # overhead on v5e (tools/profile_device_entropy.py) while the unrolled
    # form fuses into a handful of kernels.
    init_sl = jnp.where((total > 10) & (t1 < 3), 1, 0)
    val_t = val_rev.T  # (L, B)
    sls_l, firsts_l, uses_l = [], [], []
    suffix_len = init_sl
    first_done = jnp.zeros((B,), bool)
    for k in range(L):
        level = val_t[k]
        use = (k >= t1) & (k < total)
        is_first = use & ~first_done
        new_sl = jnp.where(suffix_len == 0, 1, suffix_len)
        new_sl = jnp.where(
            (jnp.abs(level) > (3 << jnp.maximum(new_sl - 1, 0))) & (new_sl < 6),
            new_sl + 1,
            new_sl,
        )
        sls_l.append(suffix_len)
        firsts_l.append(is_first)
        uses_l.append(use)
        suffix_len = jnp.where(use, new_sl, suffix_len)
        first_done = first_done | is_first
    sls = jnp.stack(sls_l)
    firsts = jnp.stack(firsts_l)
    uses = jnp.stack(uses_l)
    level_code = jnp.where(val_t > 0, 2 * val_t - 2, -2 * val_t - 1)
    level_code = jnp.where(firsts & (t1[None, :] < 3), level_code - 2, level_code)
    lv1, lb1, lv2, lb2 = _level_bits(level_code, sls)
    lv1 = jnp.where(uses, lv1, 0)
    lb1 = jnp.where(uses, lb1, 0)
    lv2 = jnp.where(uses, lv2, 0)
    lb2 = jnp.where(uses, lb2, 0)
    lev_v = jnp.stack([lv1.T, lv2.T], -1).reshape(B, 2 * L)
    lev_b = jnp.stack([lb1.T, lb2.T], -1).reshape(B, 2 * L)

    # total_zeros
    last_pos = pos_rev[:, 0]
    tz = jnp.where(total > 0, last_pos + 1 - total, 0)
    if chroma_dc:
        tz_val, tz_bits = _lut(jnp.clip(total, 0, 3) * 4 + jnp.clip(tz, 0, 3), _TZC_PAIR)
    else:
        tz_val, tz_bits = _lut(jnp.clip(total, 0, 16) * 16 + jnp.clip(tz, 0, 15), _TZ_PAIR)
    use_tz = (total > 0) & (total < L)
    tz_v = jnp.where(use_tz, tz_val, 0)
    tz_b = jnp.where(use_tz, tz_bits, 0)

    # run_before chain (reverse order). The zeros_left recurrence has a
    # CLOSED FORM (telescoping): zeros_left at step k
    #   = tz - sum_{j<k} run_j = tz - (pos_0 - pos_k - k)
    #   = pos_k + k + 1 - total          (since tz = pos_0 + 1 - total)
    # so the whole chain vectorizes — no scan.
    ks_col = jnp.arange(L - 1, dtype=jnp.int32)[None, :]
    run = pos_rev[:, :-1] - pos_rev[:, 1:] - 1            # (B, L-1)
    zl = pos_rev[:, :-1] + ks_col + 1 - total[:, None]    # zeros_left before step k
    use_r = (ks_col < total[:, None] - 1) & (zl > 0)
    rv, rb = _lut(jnp.clip(zl, 0, 14) * 15 + jnp.clip(run, 0, 14), _RB_PAIR)

    vals = jnp.concatenate(
        [ct_val[:, None], jnp.stack(sign_v, -1), lev_v, tz_v[:, None],
         jnp.where(use_r, rv, 0)], axis=1)
    bits = jnp.concatenate(
        [ct_bits[:, None], jnp.stack(sign_b, -1), lev_b, tz_b[:, None],
         jnp.where(use_r, rb, 0)], axis=1)
    return vals, bits, total


def _split2(val, start_in_word, bits):
    """32-bit-only placement of a codeword (<= 28 bits) whose first bit
    lands at `start_in_word` (0..31) of a word: returns (hi, lo) uint32
    contributions to that word and the next. MSB-first."""
    v = val.astype(jnp.uint32)
    fits = start_in_word + bits <= 32
    sh_hi = jnp.clip(32 - start_in_word - bits, 0, 31)
    hi_fit = v << sh_hi
    over = jnp.clip(start_in_word + bits - 32, 1, 31)  # valid in split case
    hi_split = v >> over
    lo_split = (v & ((jnp.uint32(1) << over) - 1)) << (32 - over)
    hi = jnp.where(fits, hi_fit, hi_split)
    lo = jnp.where(fits, 0, lo_split)
    return hi, lo


def _pack_pairs(vals, bits, nwords: int):
    """Pack (U, S) (value, nbits) emission slots into per-unit bit
    buffers: returns (words (U, nwords) uint32, nbits_total (U,)).
    MSB-first within the stream; word 0 holds the first 32 bits.
    32-bit ops only (jax default has no uint64).

    Formulation: a dense one-hot contraction over the output words.
    Slot word-targets are data-dependent, which invites a scatter-add —
    but TPU scatter runs ~20 ns/update (145 ms/frame at CAVLC scale)
    while this where-sum fuses into ~4 ms. Bits are disjoint by
    construction, so integer add == bitwise or."""
    U, S = vals.shape
    offs = jnp.concatenate(
        [jnp.zeros((U, 1), jnp.int32), jnp.cumsum(bits, -1)], -1
    )  # (U, S+1)
    total_bits = offs[:, -1]
    vmask = jnp.where(bits >= 32, jnp.uint32(0xFFFFFFFF),
                      (jnp.uint32(1) << jnp.clip(bits, 0, 31)) - 1)
    v = vals.astype(jnp.uint32) & vmask
    start = offs[:, :-1]
    w0 = start >> 5
    hi, lo = _split2(v, start & 31, bits)
    use = bits > 0
    hi = jnp.where(use, hi, jnp.uint32(0))
    lo = jnp.where(use, lo, jnp.uint32(0))
    wids = jnp.arange(nwords, dtype=jnp.int32)
    oh_hi = w0[:, :, None] == wids[None, None, :]
    oh_lo = (w0[:, :, None] + 1) == wids[None, None, :]
    words = (
        jnp.where(oh_hi, hi[:, :, None], jnp.uint32(0)).sum(1, dtype=jnp.uint32)
        + jnp.where(oh_lo, lo[:, :, None], jnp.uint32(0)).sum(1, dtype=jnp.uint32)
    )
    return words, total_bits


def _merge_streams(words, nbits, out_words: int):
    """Concatenate U bit-buffers: (U, W) words + (U,) lengths ->
    ((out_words,) uint32, total_bits).

    Scatter-adding every unit word (U*W elements) costs >100 ms/frame on
    TPU, so the scatter is shrunk to the words that actually EXIST:

    1. shift every unit to its final bit phase (elementwise, cheap);
    2. count the output words each unit touches (nwp) and lay the used
       words out compactly via cumsum; recover slot->unit with a marker
       scatter (U unique updates) + prefix sum — no searchsorted (its
       binary-search gathers cost more than the merge itself);
    3. gather each used word and scatter-add into the stream — ~T
       near-unique updates where T ≈ total_bits/32 + #nonempty units,
       an order of magnitude under U*W.

    Slots past T_CAP = 2U + out_words only exist when total_bits
    overflows out_words*32, which the caller already treats as the
    fall-back-to-host case. Adjacent units share at most boundary words
    with disjoint bits, so add == or."""
    U, W = words.shape
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(nbits)])
    starts = offs[:-1]
    total = offs[-1]
    sh = (starts & 31)[:, None]  # right-shift amount (0..31)
    hi = jnp.where(sh > 0, words >> jnp.clip(sh, 0, 31).astype(jnp.uint32), words)
    lo = jnp.where(
        sh > 0,
        (words & ((jnp.uint32(1) << jnp.clip(sh, 1, 31).astype(jnp.uint32)) - 1))
        << jnp.clip(32 - sh, 1, 31).astype(jnp.uint32),
        jnp.uint32(0),
    )
    shifted = jnp.concatenate([hi, jnp.zeros((U, 1), jnp.uint32)], 1) + jnp.concatenate(
        [jnp.zeros((U, 1), jnp.uint32), lo], 1
    )  # (U, W+1): unit words at final bit phase
    nwp = jnp.where(nbits > 0, (nbits + (starts & 31) + 31) >> 5, 0)  # words touched
    woffs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(nwp)])
    T_CAP = 2 * U + out_words
    mark = jnp.zeros((T_CAP + 1,), jnp.int32)
    mark = mark.at[jnp.clip(woffs[:-1], 0, T_CAP)].add(1)
    unit = jnp.cumsum(mark[:T_CAP]) - 1  # slot -> unit (empties map to none)
    unitc = jnp.clip(unit, 0, U - 1)
    slots = jnp.arange(T_CAP, dtype=jnp.int32)
    win = slots - woffs[unitc]
    valid = (unit >= 0) & (win >= 0) & (win < nwp[unitc])
    vals = shifted[unitc, jnp.clip(win, 0, W)]
    tgt = jnp.where(valid, (starts[unitc] >> 5) + win, out_words)
    out = jnp.zeros((out_words + 1,), jnp.uint32)
    out = out.at[jnp.clip(tgt, 0, out_words)].add(jnp.where(valid, vals, jnp.uint32(0)))
    return out[:out_words], total


def _mv_pred_grid(mvs, skip_unused):
    """Vectorized 8.4.1.3 prediction for every MB (mirrors
    numpy_ref.mv_pred_16x16 including availability cases)."""
    mbh, mbw = mvs.shape[:2]
    zeros = jnp.zeros_like(mvs)
    left = jnp.pad(mvs, ((0, 0), (1, 0), (0, 0)))[:, :-1]
    top = jnp.pad(mvs, ((1, 0), (0, 0), (0, 0)))[:-1]
    tr = jnp.pad(mvs, ((1, 0), (0, 1), (0, 0)))[:-1, 1:]
    tl = jnp.pad(mvs, ((1, 0), (1, 0), (0, 0)))[:-1, :-1]
    col = jnp.arange(mbw)[None, :, None]
    row = jnp.arange(mbh)[:, None, None]
    a_avail = col > 0
    b_avail = row > 0
    c_avail = (row > 0) & (col + 1 < mbw)
    d_avail = (row > 0) & (col > 0)
    c_sub = jnp.where(c_avail, tr, jnp.where(d_avail, tl, zeros))
    c_eff_avail = c_avail | d_avail
    a = jnp.where(a_avail, left, zeros)
    b = jnp.where(b_avail, top, zeros)
    med = a + b + c_sub - jnp.maximum(jnp.maximum(a, b), c_sub) - jnp.minimum(
        jnp.minimum(a, b), c_sub
    )
    n_avail = (
        a_avail.astype(jnp.int32) + b_avail.astype(jnp.int32) + c_eff_avail.astype(jnp.int32)
    )
    only = jnp.where(a_avail, a, jnp.where(b_avail, b, c_sub))
    pred = jnp.where(n_avail == 1, only, med)
    # 8.4.1.3.1: only A available (B, C, D all unavailable) -> mvA
    pred = jnp.where(a_avail & ~b_avail & ~c_eff_avail, a, pred)
    return pred


def _nc_grid(grid):
    """nC for every block position of a (BH, BW) TotalCoeff grid —
    elementwise shifted reads (9.2.1 availability: left/top within the
    slice), no per-block gather. Used instead of the old flat fancy-index
    reads so the per-MB structure compacts with plain row scatters."""
    bh, bw = grid.shape
    left = jnp.pad(grid, ((0, 0), (1, 0)))[:, :-1]
    top = jnp.pad(grid, ((1, 0), (0, 0)))[:-1]
    has_l = jnp.arange(bw, dtype=jnp.int32)[None, :] > 0
    has_t = jnp.arange(bh, dtype=jnp.int32)[:, None] > 0
    both = (left + top + 1) >> 1
    return jnp.where(
        has_l & has_t, both,
        jnp.where(has_l, left, jnp.where(has_t, top, 0)))


def _frame_structure(out):
    """Full-grid per-MB syntax structure — the CHEAP half of the coder.

    Everything here is elementwise work or an O(M) prefix scan over the
    MB grid: skip runs, mv prediction, cbp, TotalCoeff/nC context grids,
    header codewords, and the per-MB residual blocks re-laid into coding
    order. No VLC one-hot contraction or bit packing happens yet, so
    this pass costs the same for a busy and an idle frame — the
    expensive emission half (`_emit_slice_bits`) runs on the (optionally
    activity-compacted) structure it returns. Every per-MB array keys
    into `_COMPACT_KEYS` so `_compact_structure` can gather the coded
    MBs into a dense prefix with one row scatter each.
    """
    mvs = out["mvs"]
    skip = out["skip"]
    mbh, mbw = skip.shape
    M = mbh * mbw
    luma = out["luma_ac"].reshape(mbh, mbw, 4, 4, 16).astype(jnp.int32)
    chroma = out["chroma_ac"].reshape(mbh, mbw, 2, 2, 2, 16).astype(jnp.int32)
    cdc = out["chroma_dc"].reshape(mbh, mbw, 2, 4).astype(jnp.int32)

    zig = jnp.asarray(_ZIGZAG)
    luma_scan = luma[..., zig]                     # (mbh,mbw,4,4,16) scan order
    chroma_scan = chroma[..., zig]

    # ---- frame-wide structure ------------------------------------------
    coded = ~skip
    # cbp per MB
    # 8x8 group b8 = (y4>>1)*2 + (x4>>1): regroup (4,4) block grid into 2x2 of 2x2
    lg = luma_scan.reshape(mbh, mbw, 2, 2, 2, 2, 16).transpose(0, 1, 2, 4, 3, 5, 6)
    # lg[.., y8, x8, y4in, x4in, :]
    grp_nz = (lg != 0).any((-3, -2, -1))           # (mbh, mbw, 2, 2) -> b8 grid
    cbp_luma = (
        grp_nz[..., 0, 0].astype(jnp.int32)
        | (grp_nz[..., 0, 1].astype(jnp.int32) << 1)
        | (grp_nz[..., 1, 0].astype(jnp.int32) << 2)
        | (grp_nz[..., 1, 1].astype(jnp.int32) << 3)
    )
    chroma_ac_nz = (chroma_scan[..., 1:] != 0).any((-4, -3, -2, -1))
    chroma_dc_nz = (cdc != 0).any((-2, -1))
    cbp_chroma = jnp.where(chroma_ac_nz, 2, jnp.where(chroma_dc_nz, 1, 0))
    cbp = cbp_luma | (cbp_chroma << 4)

    # TotalCoeff context grids: block coded iff MB coded & its group in cbp
    luma_total = (luma_scan != 0).sum(-1).astype(jnp.int32)  # (mbh,mbw,4,4) [y4][x4]
    b8_of = (jnp.arange(4)[:, None] // 2) * 2 + (jnp.arange(4)[None, :] // 2)  # [y4][x4]
    luma_gate = (
        coded[..., None, None]
        & ((cbp_luma[..., None, None] >> b8_of[None, None]) & 1).astype(bool)
    )
    luma_tc_grid = jnp.where(luma_gate, luma_total, 0)  # (mbh,mbw,4,4)
    # flat (mbh*4, mbw*4) [by][bx]
    luma_tc_flat = luma_tc_grid.transpose(0, 2, 1, 3).reshape(mbh * 4, mbw * 4)
    ch_total = (chroma_scan[..., 1:] != 0).sum(-1).astype(jnp.int32)  # (mbh,mbw,2,2,2) [c][y][x]
    ch_gate = coded[..., None, None, None] & (cbp_chroma[..., None, None, None] == 2)
    ch_tc_grid = jnp.where(ch_gate, ch_total, 0)
    ch_tc_flat = ch_tc_grid.transpose(2, 0, 3, 1, 4).reshape(2, mbh * 2, mbw * 2)

    # ---- per-block inputs (coding order) -------------------------------
    # luma: MBs x 16 blocks in coding order. Block reorder as a STATIC
    # take over the 16-block axis: the equivalent multi-array fancy
    # gather lowers to a general gather that costs ~200 ms/frame on v5e
    # (tools/profile_device_entropy.py); nC likewise comes from the
    # elementwise grid (_nc_grid) statically re-laid into coding order.
    ox, oy = jnp.asarray(_LUMA_ORDER)[:, 0], jnp.asarray(_LUMA_ORDER)[:, 1]
    luma_perm = jnp.asarray(
        np.asarray(_LUMA_ORDER)[:, 1] * 4 + np.asarray(_LUMA_ORDER)[:, 0]
    )
    nc_luma = jnp.take(
        _nc_grid(luma_tc_flat).reshape(mbh, 4, mbw, 4).transpose(0, 2, 1, 3)
        .reshape(M, 16),
        luma_perm, axis=1,
    )  # (M, 16) in coding order
    luma_blocks = jnp.take(
        luma_scan.reshape(mbh, mbw, 16, 16), luma_perm, axis=2
    ).reshape(M, 16, 16)  # (M, 16, 16) in coding order
    # gate: block emitted iff MB coded & its b8 set
    b8_idx = (oy // 2) * 2 + (ox // 2)
    luma_emit = (
        coded[..., None] & ((cbp_luma[..., None] >> b8_idx[None, None]) & 1).astype(bool)
    ).reshape(M, 16)

    # chroma DC: MBs x 2 comps (4-coeff blocks, nc = -1)
    cdc_blocks = cdc.reshape(M, 2, 4)
    cdc_emit = jnp.broadcast_to(
        (coded & (cbp_chroma >= 1))[..., None], (mbh, mbw, 2)
    ).reshape(M, 2)

    # chroma AC: MBs x 2 comps x 4 blocks in coding order, 15 coeffs.
    # nC per component from its OWN grid (a component's row 0 must not
    # read the other component's bottom row).
    ch_perm = jnp.asarray(
        np.asarray(_CHROMA_ORDER)[:, 1] * 2 + np.asarray(_CHROMA_ORDER)[:, 0]
    )
    nc_ch = jnp.take(
        jnp.stack([_nc_grid(ch_tc_flat[c]) for c in range(2)])
        .reshape(2, mbh, 2, mbw, 2).transpose(1, 3, 0, 2, 4).reshape(M, 2, 4),
        ch_perm, axis=2,
    ).reshape(M, 8)
    ch_blocks = jnp.take(
        chroma_scan.reshape(mbh, mbw, 2, 4, 16), ch_perm, axis=3
    ).reshape(M, 8, 16)[..., 1:]  # (M, 8, 15) in coding order
    ch_emit = jnp.broadcast_to(
        (coded & (cbp_chroma == 2))[..., None, None], (mbh, mbw, 2, 4)
    ).reshape(M, 8)

    # ---- MB headers -----------------------------------------------------
    # skip_run before each coded MB: # of consecutive skips immediately
    # before it (raster order)
    skip_flat = skip.reshape(-1).astype(jnp.int32)
    csum_skip = jnp.cumsum(skip_flat)
    coded_flat = 1 - skip_flat
    # skip_run before coded MB i = skips since the previous coded MB:
    # csum_skip[i] - csum_skip[prev_coded(i)], with prev_coded found by a
    # running max over coded positions
    idxs = jnp.arange(M, dtype=jnp.int32)
    coded_pos = jnp.where(coded_flat.astype(bool), idxs, -1)
    prev_coded_pos = jax.lax.associative_scan(jnp.maximum, coded_pos)  # running max incl self
    prev_excl = jnp.concatenate([jnp.full(1, -1, jnp.int32), prev_coded_pos[:-1]])
    csum_at = jnp.concatenate([jnp.zeros(1, jnp.int32), csum_skip])  # csum_at[p+1]=csum incl p
    skip_run = csum_skip - jnp.where(prev_excl >= 0, csum_at[prev_excl + 1], 0)
    # (only meaningful at coded positions)

    pred = _mv_pred_grid(mvs, skip).reshape(-1, 2)
    mvd = 4 * (mvs.reshape(-1, 2) - pred)
    sr_v, sr_b = _ue_bits(skip_run)
    mt_v, mt_b = jnp.ones_like(skip_run), jnp.ones_like(skip_run)  # ue(0) = '1'
    mx_v, mx_b = _se_bits(mvd[:, 0])
    my_v, my_b = _se_bits(mvd[:, 1])
    cbp_flat = cbp.reshape(-1)
    cb_v, cb_b = _ue_bits(jnp.asarray(_CBP_CODENUM)[cbp_flat])
    qd_v = jnp.ones_like(skip_run)
    qd_b = jnp.where(cbp_flat > 0, 1, 0)  # se(0) = '1'
    hdr_vals = jnp.stack([sr_v, mt_v, mx_v, my_v, cb_v, qd_v], -1)
    hdr_bits = jnp.stack([sr_b, mt_b, mx_b, my_b, cb_b, qd_b], -1)
    emit_mb = coded_flat.astype(bool)
    hdr_bits = jnp.where(emit_mb[:, None], hdr_bits, 0)

    # trailing skip run (after the last coded MB)
    last_coded = prev_coded_pos[-1]
    trailing = jnp.where(last_coded >= 0, csum_skip[-1] - csum_at[last_coded + 1], csum_skip[-1])
    return {
        "hdr_vals": hdr_vals, "hdr_bits": hdr_bits,
        "luma_blocks": luma_blocks, "nc_luma": nc_luma, "luma_emit": luma_emit,
        "cdc_blocks": cdc_blocks, "cdc_emit": cdc_emit,
        "ch_blocks": ch_blocks, "nc_ch": nc_ch, "ch_emit": ch_emit,
        "coded": emit_mb, "trailing": trailing,
        "ns": coded_flat.sum().astype(jnp.int32),
        # full-grid context grids, consumed by the CABAC emitter
        # (device_cabac.py) for its neighbour ctx derivation — dead (and
        # DCE'd by the jit) on the CAVLC path
        "cbp_luma": cbp_luma, "cbp_chroma": cbp_chroma,
        "luma_tc_flat": luma_tc_flat, "ch_tc_flat": ch_tc_flat,
    }


# per-MB arrays the activity compaction gathers into a dense prefix
_COMPACT_KEYS = (
    "hdr_vals", "hdr_bits", "luma_blocks", "nc_luma", "luma_emit",
    "cdc_blocks", "cdc_emit", "ch_blocks", "nc_ch", "ch_emit",
)


def _compact_structure(s, A: int, keys=_COMPACT_KEYS):
    """Gather the coded MBs of a frame structure into a dense prefix of
    `A` padded slots (raster order preserved; slots past the coded count
    stay all-zero, so their segments emit zero bits and vanish in the
    merge). One row scatter per array — M near-unique updates each, the
    same cheap shape as encoder_core's sparse pair compaction. Coded MBs
    past slot A are DROPPED: the caller must only select this path when
    ns <= A (pack_p_slice_bits_active's bucket switch guarantees it).
    ``keys`` selects the per-MB arrays to gather (device_cabac passes
    its own set, which includes the CABAC context columns)."""
    coded = s["coded"]
    pos = jnp.cumsum(coded.astype(jnp.int32)) - 1
    dest = jnp.where(coded & (pos < A), pos, A)  # sentinel row, dropped

    def cp(a):
        buf = jnp.zeros((A + 1,) + a.shape[1:], a.dtype)
        return buf.at[dest].set(a)[:A]

    return {k: cp(s[k]) for k in keys}


def _emit_slice_bits(s, word_cap: int):
    """The EXPENSIVE half: VLC-encode every block of a (possibly
    compacted) per-MB structure, pack each segment's codewords into bit
    buffers, and merge them into one stream. Cost scales with the
    structure's leading axis (U MBs), which is what makes the bucket
    compaction activity-proportional. Returns (words, nbits)."""
    U = s["hdr_bits"].shape[0]
    lv, lb, _ = _encode_blocks(
        s["luma_blocks"].reshape(U * 16, 16), s["nc_luma"].reshape(-1),
        chroma_dc=False)
    lb = jnp.where(s["luma_emit"].reshape(-1)[:, None], lb, 0)
    dv, db, _ = _encode_blocks(
        s["cdc_blocks"].reshape(U * 2, 4),
        jnp.full((U * 2,), -1, jnp.int32), chroma_dc=True)
    db = jnp.where(s["cdc_emit"].reshape(-1)[:, None], db, 0)
    cv, cb, _ = _encode_blocks(
        s["ch_blocks"].reshape(U * 8, 15), s["nc_ch"].reshape(-1),
        chroma_dc=False)
    cb = jnp.where(s["ch_emit"].reshape(-1)[:, None], cb, 0)

    # ---- assemble: MB unit = header + 16 luma + 2 cdc + 8 cac ----------
    HW = 4      # header words (6 codewords <= 78 bits)
    BW = 32     # per-block words (hard bound: 16+3+16*52+9+14*11 = 1014 bits)
    hdr_w, hdr_n = _pack_pairs(s["hdr_vals"], s["hdr_bits"], HW)
    luma_w, luma_n = _pack_pairs(lv, lb, BW)
    cdc_w, cdc_n = _pack_pairs(dv, db, BW)
    cac_w, cac_n = _pack_pairs(cv, cb, BW)

    # stitch each MB's 27 segments in syntax order:
    # header, luma blocks 0..15, cdc 0..1, cac 0..7
    seg_words = jnp.concatenate(
        [
            jnp.pad(hdr_w.reshape(U, 1, HW), ((0, 0), (0, 0), (0, BW - HW))),
            luma_w.reshape(U, 16, BW),
            cdc_w.reshape(U, 2, BW),
            cac_w.reshape(U, 8, BW),
        ],
        axis=1,
    ).reshape(U * 27, BW)
    seg_bits = jnp.concatenate(
        [hdr_n.reshape(U, 1), luma_n.reshape(U, 16), cdc_n.reshape(U, 2),
         cac_n.reshape(U, 8)],
        axis=1,
    ).reshape(U * 27)
    return _merge_streams(seg_words, seg_bits, word_cap)


def pack_p_slice_bits(out, word_cap: int = WORD_CAP_DEFAULT):
    """P-frame encode outputs -> slice-data bitstream on device,
    FULL-GRID (every MB pays the emission cost regardless of activity).

    Returns (words (word_cap,) uint32 big-endian bit order, nbits int32,
    trailing_skip int32). The stream covers everything between the slice
    header and the final skip_run — the host splices it after its own
    header bits and finishes the NAL. Production paths use
    pack_p_slice_bits_active; this fixed-shape form remains the oracle
    for tests and the cost baseline for tools/profile_device_entropy.py.
    """
    s = _frame_structure(out)
    words, nbits = _emit_slice_bits(s, word_cap)
    return words, nbits, s["trailing"]


def bits_buckets(m: int, ladder=(256, 1024, 4096)) -> tuple[int, ...]:
    """Activity buckets for a slice of `m` MBs: the power-of-two-ish
    ladder clipped to the grid, always ending at m so every frame has a
    bucket. Tiny slices (tests, bands of small frames) collapse to a
    single full-grid bucket — no switch, no extra compile."""
    m = int(m)
    return tuple(sorted({min(int(b), m) for b in ladder} | {m}))


def pack_p_slice_bits_active(out, word_cap: int = WORD_CAP_DEFAULT,
                             buckets: tuple[int, ...] | None = None):
    """Activity-proportional device CAVLC: like pack_p_slice_bits, but
    the emission half runs over a compacted padded count of coded MBs.

    The bucket (smallest entry >= the frame's coded-MB count ns) is
    selected ON DEVICE with lax.switch — all buckets compile into the
    one executable, each frame executes only its own, so a typing frame
    pays the 256-slot coder while a scene cut pays the full grid.
    Returns (words, nbits, trailing_skip, ns); ns lets the caller make
    its ship-bits-or-coefficients decision in the same jit. Output is
    bit-identical to the full-grid coder for every ns (compaction
    preserves raster order; padded slots emit zero bits)."""
    s = _frame_structure(out)
    M = s["coded"].shape[0]
    if buckets is None:
        buckets = bits_buckets(M)
    ns = s["ns"]
    if len(buckets) == 1:
        A = buckets[0]
        words, nbits = _emit_slice_bits(
            s if A >= M else _compact_structure(s, A), word_cap)
        return words, nbits, s["trailing"], ns

    def _branch(A: int):
        if A >= M:
            return lambda _: _emit_slice_bits(s, word_cap)
        return lambda _: _emit_slice_bits(_compact_structure(s, A), word_cap)

    idx = jnp.clip(
        jnp.searchsorted(jnp.asarray(buckets, jnp.int32), ns, side="left"),
        0, len(buckets) - 1)
    words, nbits = jax.lax.switch(idx, [_branch(b) for b in buckets],
                                  jnp.int32(0))
    return words, nbits, s["trailing"], ns


# ---------------------------------------------------------------------------
# Host half: splice header + device bits + trailing, NAL-wrap
# ---------------------------------------------------------------------------


def _or_bits(out: np.ndarray, src: np.ndarray, bit_off: int, nbits: int) -> None:
    """OR `nbits` MSB-first bits of src into out at bit offset bit_off."""
    if nbits <= 0:
        return
    nbytes = (nbits + 7) // 8
    src = src[:nbytes]
    sh = bit_off & 7
    b0 = bit_off >> 3
    # src may be zero-padded past nbits (whole device words): clamp every
    # write to the output (the spilled-over bytes are zeros anyway)
    n1 = min(len(src), len(out) - b0)
    if sh == 0:
        out[b0 : b0 + n1] |= src[:n1]
        return
    out[b0 : b0 + n1] |= (src >> sh)[:n1]
    spill = ((src.astype(np.uint16) << (8 - sh)) & 0xFF).astype(np.uint8)
    n2 = min(len(spill), len(out) - b0 - 1)
    out[b0 + 1 : b0 + 1 + n2] |= spill[:n2]


def assemble_p_nal(words: np.ndarray, nbits: int, trailing_skip: int,
                   p, frame_num: int, qp: int,
                   ltr_ref: int | None = None,
                   mark_ltr: int | None = None,
                   mmco_evict: tuple = (),
                   first_mb: int = 0) -> bytes:
    """Finish a P slice from device bits: header + stream + trailing
    skip_run + rbsp stop, emulation-prevented and Annex-B wrapped.
    Byte-identical to cavlc.pack_slice_p for the same inputs. first_mb
    positions a band slice of a multi-slice picture (parallel/bands.py)
    — it lives entirely in the host-written header, so the device words
    are the same with or without it."""
    from selkies_tpu.models.h264.bitstream import SLICE_P, NAL_SLICE_NON_IDR, write_slice_header
    from selkies_tpu.utils.bits import BitWriter, annexb_nal

    w = BitWriter()
    write_slice_header(w, p, SLICE_P, frame_num, idr=False, slice_qp=qp,
                       ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                       mmco_evict=mmco_evict, first_mb=first_mb)
    hdr_bytes, hdr_bits = w.get_partial()

    dev_bytes = np.ascontiguousarray(words[: (nbits + 31) // 32]).astype(">u4").view(np.uint8)

    tail = BitWriter()
    if trailing_skip:
        tail.write_ue(int(trailing_skip))
    tail.write_bit(1)  # rbsp_stop_one_bit; byte-align zeros come from sizing
    tail_bytes, tail_bits = tail.get_partial()

    total_bits = hdr_bits + int(nbits) + tail_bits
    out = np.zeros((total_bits + 7) // 8, np.uint8)
    _or_bits(out, np.frombuffer(hdr_bytes, np.uint8), 0, hdr_bits)
    _or_bits(out, dev_bytes, hdr_bits, int(nbits))
    _or_bits(out, np.frombuffer(tail_bytes, np.uint8), hdr_bits + int(nbits), tail_bits)
    return annexb_nal(3, NAL_SLICE_NON_IDR, out.tobytes())
