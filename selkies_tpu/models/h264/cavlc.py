"""CAVLC entropy coding (ISO 14496-10 §9.2): FrameCoeffs → slice NAL bytes.

Pure-Python reference packer. The production path is the C++ packer in
native/cavlc_pack.cc (byte-identical output, validated by tests); this
module is the readable specification of the bit layout and the fallback
when the native library isn't built.

Design note: the bit-serial part of H.264 is the worst fit for TPU
hardware, so the split mirrors the reference's CPU/GPU division of labour
(NVENC keeps entropy coding in dedicated silicon): the TPU produces
quantized coefficient tensors (FrameCoeffs), the host packs bits.
"""

from __future__ import annotations

import numpy as np

from selkies_tpu.models.h264.bitstream import (
    NAL_SLICE_IDR,
    NAL_SLICE_NON_IDR,
    SLICE_I,
    SLICE_P,
    StreamParams,
    write_slice_header,
)
from selkies_tpu.models.h264.numpy_ref import FrameCoeffs, PFrameCoeffs, mv_pred_16x16
from selkies_tpu.models.h264.tables import (
    CHROMA_BLOCK_ORDER,
    LUMA_BLOCK_ORDER,
    ZIGZAG_FLAT,
    coeff_token_code,
    run_before_code,
    total_zeros_code,
)
from selkies_tpu.utils.bits import BitWriter, annexb_nal

__all__ = ["pack_slice", "pack_slice_p", "encode_stream", "residual_block", "nc_context"]

# Table 9-4 column for Inter MB prediction: coded_block_pattern -> codeNum
# for the me(v) mapping (index = cbp value 0..47).
INTER_CBP_TO_CODENUM = [
    0, 2, 3, 7, 4, 8, 17, 13, 5, 18, 9, 14, 10, 15, 16, 11,
    1, 32, 33, 36, 34, 37, 44, 40, 35, 45, 38, 41, 39, 42, 43, 19,
    6, 24, 25, 20, 26, 21, 46, 28, 27, 47, 22, 29, 23, 30, 31, 12,
]


def residual_block(w: BitWriter, coeffs: np.ndarray, max_coeff: int, nc: int) -> int:
    """Write one CAVLC residual block; coeffs already in scan order.

    Returns TotalCoeff (for neighbour nC context upkeep).
    """
    coeffs = [int(c) for c in coeffs]
    nz = [i for i, c in enumerate(coeffs) if c != 0]
    total = len(nz)
    # trailing ones: consecutive |1| at the end of the nonzero list, max 3
    t1 = 0
    for i in reversed(nz):
        if abs(coeffs[i]) == 1 and t1 < 3:
            t1 += 1
        else:
            break
    val, nbits = coeff_token_code(nc, total, t1)
    w.write_bits(val, nbits)
    if total == 0:
        return 0

    # trailing one signs, reverse scan order
    for k in range(t1):
        w.write_bit(1 if coeffs[nz[-1 - k]] < 0 else 0)

    # remaining levels, reverse scan order
    suffix_len = 1 if (total > 10 and t1 < 3) else 0
    for idx, k in enumerate(range(t1, total)):
        level = coeffs[nz[-1 - k]]
        level_code = 2 * level - 2 if level > 0 else -2 * level - 1
        if idx == 0 and t1 < 3:
            level_code -= 2
        _write_level(w, level_code, suffix_len)
        if suffix_len == 0:
            suffix_len = 1
        if abs(level) > (3 << (suffix_len - 1)) and suffix_len < 6:
            suffix_len += 1

    # total_zeros
    total_zeros = nz[-1] + 1 - total
    if total < max_coeff:
        val, nbits = total_zeros_code(total, total_zeros, chroma_dc=(max_coeff == 4))
        w.write_bits(val, nbits)

    # run_before, reverse scan order, last coeff's run implied
    zeros_left = total_zeros
    for k in range(total - 1):
        if zeros_left <= 0:
            break
        run = nz[-1 - k] - nz[-2 - k] - 1
        val, nbits = run_before_code(zeros_left, run)
        w.write_bits(val, nbits)
        zeros_left -= run
    return total


def _write_level(w: BitWriter, level_code: int, suffix_len: int) -> None:
    """Write level_prefix + level_suffix for one level (9.2.2.1)."""
    if suffix_len == 0:
        if level_code < 14:
            w.write_bits(1, level_code + 1)  # unary: level_code zeros then 1
            return
        if level_code < 30:
            w.write_bits(1, 15)  # prefix 14
            w.write_bits(level_code - 14, 4)
            return
        level_code -= 15  # decoder adds 15 back for prefix>=15, suffix_len==0
    if level_code < (15 << suffix_len):
        prefix = level_code >> suffix_len
        w.write_bits(1, prefix + 1)
        if suffix_len:
            w.write_bits(level_code & ((1 << suffix_len) - 1), suffix_len)
        return
    # escape: prefix 15, 12-bit suffix
    esc = level_code - (15 << suffix_len)
    if esc < (1 << 12):
        w.write_bits(1, 16)
        w.write_bits(esc, 12)
        return
    # extended prefixes (16+): suffix size = prefix - 3
    prefix = 16
    while True:
        base = (15 << suffix_len) + (1 << (prefix - 3)) - (1 << 12)
        if level_code - base < (1 << (prefix - 3)):
            w.write_bits(1, prefix + 1)
            w.write_bits(level_code - base, prefix - 3)
            return
        prefix += 1


def nc_context(counts: np.ndarray, bx: int, by: int) -> int:
    """Neighbour context for block at absolute block coords (bx, by)."""
    left = counts[by, bx - 1] if bx > 0 else None
    top = counts[by - 1, bx] if by > 0 else None
    if left is not None and top is not None:
        return (int(left) + int(top) + 1) >> 1
    if left is not None:
        return int(left)
    if top is not None:
        return int(top)
    return 0


def pack_slice(
    fc: FrameCoeffs,
    p: StreamParams,
    frame_num: int = 0,
    idr: bool = True,
    idr_pic_id: int = 0,
    first_mb: int = 0,
) -> bytes:
    """Entropy-code Intra16x16 MBs into one slice NAL.

    fc may cover the whole picture (first_mb=0, the single-slice default)
    or one horizontal BAND of it (parallel/bands.py): first_mb is the
    slice header's first_mb_in_slice, and fc's grid is the band's own
    (band_mbh, mbw) — neighbour/nC context starts fresh at the band's
    first row, which is exactly the slice-boundary availability rule
    (neighbours in another slice are unavailable)."""
    mbh, mbw = fc.luma_mode.shape
    w = BitWriter()
    # fc.qp is the QP the coefficients were quantized with; slice_qp_delta
    # carries any difference from pic_init_qp (live rate-control retunes).
    write_slice_header(w, p, SLICE_I, frame_num, idr=idr, idr_pic_id=idr_pic_id,
                       slice_qp=fc.qp, first_mb=first_mb)

    # nC context grids (TotalCoeff per 4x4 block, frame-wide)
    luma_tc = np.zeros((mbh * 4, mbw * 4), np.int32)
    chroma_tc = np.zeros((2, mbh * 2, mbw * 2), np.int32)

    # Precompute zigzag views once: AC scans positions 1..15.
    luma_ac = fc.luma_ac.reshape(mbh, mbw, 4, 4, 16)[..., ZIGZAG_FLAT]
    chroma_ac = fc.chroma_ac.reshape(mbh, mbw, 2, 2, 2, 16)[..., ZIGZAG_FLAT]
    luma_dc_scan = fc.luma_dc.reshape(mbh, mbw, 16)[..., ZIGZAG_FLAT]

    for mby in range(mbh):
        for mbx in range(mbw):
            cbp_luma = 15 if np.any(luma_ac[mby, mbx, :, :, 1:]) else 0
            if np.any(chroma_ac[mby, mbx, :, :, :, 1:]):
                cbp_chroma = 2
            elif np.any(fc.chroma_dc[mby, mbx]):
                cbp_chroma = 1
            else:
                cbp_chroma = 0
            mb_type = 1 + int(fc.luma_mode[mby, mbx]) + 4 * cbp_chroma + 12 * (1 if cbp_luma else 0)
            w.write_ue(mb_type)
            w.write_ue(int(fc.chroma_mode[mby, mbx]))
            w.write_se(0)  # mb_qp_delta (constant QP per slice)

            # Intra16x16 DC block: nC from luma block 0's neighbours
            nc = nc_context(luma_tc, mbx * 4, mby * 4)
            residual_block(w, luma_dc_scan[mby, mbx], 16, nc)

            if cbp_luma:
                for blk, (x4, y4) in enumerate(LUMA_BLOCK_ORDER):
                    bx, by = mbx * 4 + x4, mby * 4 + y4
                    nc = nc_context(luma_tc, bx, by)
                    tc = residual_block(w, luma_ac[mby, mbx, y4, x4, 1:], 15, nc)
                    luma_tc[by, bx] = tc
            # (cbp_luma == 0 leaves TotalCoeff 0 in the context grid)

            if cbp_chroma:
                for comp in range(2):
                    # chroma DC scan order: raster over the 2x2
                    residual_block(w, fc.chroma_dc[mby, mbx, comp].reshape(4), 4, -1)
            if cbp_chroma == 2:
                for comp in range(2):
                    for x4, y4 in CHROMA_BLOCK_ORDER:
                        bx, by = mbx * 2 + x4, mby * 2 + y4
                        nc = nc_context(chroma_tc[comp], bx, by)
                        tc = residual_block(w, chroma_ac[mby, mbx, comp, y4, x4, 1:], 15, nc)
                        chroma_tc[comp, by, bx] = tc

    w.rbsp_trailing_bits()
    nal_type = NAL_SLICE_IDR if idr else NAL_SLICE_NON_IDR
    return annexb_nal(3, nal_type, w.get_bytes())


def pack_slice_p(
    fc: PFrameCoeffs,
    p: StreamParams,
    frame_num: int,
    ltr_ref: int | None = None,
    mark_ltr: int | None = None,
    mmco_evict: tuple = (),
    first_mb: int = 0,
) -> bytes:
    """Entropy-code one P frame (P_Skip / P_L0_16x16 MBs) into a slice NAL.

    Syntax per 7.3.4 (slice data) + 7.3.5 (macroblock layer): mb_skip_run
    before every coded MB, mb_type 0 (P_L0_16x16), no ref_idx (single
    reference), mvd relative to the 8.4.1.3 predictor in quarter-pel units,
    me(v)-mapped CBP, and 16-coefficient luma residual blocks (inter MBs
    have no luma DC Hadamard).

    As with pack_slice, fc may be one band of a multi-slice picture:
    first_mb positions the slice and fc's (band_mbh, mbw) grid resets
    the MV-predictor / nC neighbourhood at the band's first row (slice
    boundaries make those neighbours unavailable, 8.4.1.3 / 9.2.1).
    """
    mbh, mbw = fc.skip.shape
    w = BitWriter()
    write_slice_header(w, p, SLICE_P, frame_num, idr=False, slice_qp=fc.qp,
                       ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                       mmco_evict=mmco_evict, first_mb=first_mb)

    luma_tc = np.zeros((mbh * 4, mbw * 4), np.int32)
    chroma_tc = np.zeros((2, mbh * 2, mbw * 2), np.int32)
    luma_scan = fc.luma_ac.reshape(mbh, mbw, 4, 4, 16)[..., ZIGZAG_FLAT]
    chroma_scan = fc.chroma_ac.reshape(mbh, mbw, 2, 2, 2, 16)[..., ZIGZAG_FLAT]

    skip_run = 0
    for mby in range(mbh):
        for mbx in range(mbw):
            if fc.skip[mby, mbx]:
                skip_run += 1
                continue  # TotalCoeff grids stay 0 for nC context
            w.write_ue(skip_run)
            skip_run = 0
            w.write_ue(0)  # mb_type P_L0_16x16
            px, py = mv_pred_16x16(fc.mvs, mbx, mby)
            w.write_se(4 * (int(fc.mvs[mby, mbx, 0]) - px))  # mvd quarter-pel
            w.write_se(4 * (int(fc.mvs[mby, mbx, 1]) - py))

            cbp_luma = 0
            for b8 in range(4):
                y8, x8 = b8 >> 1, b8 & 1
                if np.any(luma_scan[mby, mbx, y8 * 2 : y8 * 2 + 2, x8 * 2 : x8 * 2 + 2]):
                    cbp_luma |= 1 << b8
            if np.any(chroma_scan[mby, mbx, :, :, :, 1:]):
                cbp_chroma = 2
            elif np.any(fc.chroma_dc[mby, mbx]):
                cbp_chroma = 1
            else:
                cbp_chroma = 0
            cbp = cbp_luma | (cbp_chroma << 4)
            w.write_ue(INTER_CBP_TO_CODENUM[cbp])
            if cbp:
                w.write_se(0)  # mb_qp_delta (constant QP per slice)

            for x4, y4 in LUMA_BLOCK_ORDER:
                b8 = (y4 >> 1) * 2 + (x4 >> 1)
                if not cbp_luma & (1 << b8):
                    continue
                bx, by = mbx * 4 + x4, mby * 4 + y4
                nc = nc_context(luma_tc, bx, by)
                tc = residual_block(w, luma_scan[mby, mbx, y4, x4], 16, nc)
                luma_tc[by, bx] = tc

            if cbp_chroma:
                for comp in range(2):
                    residual_block(w, fc.chroma_dc[mby, mbx, comp].reshape(4), 4, -1)
            if cbp_chroma == 2:
                for comp in range(2):
                    for x4, y4 in CHROMA_BLOCK_ORDER:
                        bx, by = mbx * 2 + x4, mby * 2 + y4
                        nc = nc_context(chroma_tc[comp], bx, by)
                        tc = residual_block(w, chroma_scan[mby, mbx, comp, y4, x4, 1:], 15, nc)
                        chroma_tc[comp, by, bx] = tc

    if skip_run:
        w.write_ue(skip_run)
    w.rbsp_trailing_bits()
    return annexb_nal(3, NAL_SLICE_NON_IDR, w.get_bytes())


def encode_stream(y, u, v, qp: int, width: int | None = None, height: int | None = None):
    """Convenience: (annexb_bytes, FrameEncoding) for one IDR via the numpy model."""
    from selkies_tpu.models.h264.bitstream import write_pps, write_sps
    from selkies_tpu.models.h264.numpy_ref import encode_frame_i16

    h, w_ = y.shape
    p = StreamParams(width=width or w_, height=height or h, qp=qp)
    enc = encode_frame_i16(y, u, v, qp)
    return write_sps(p) + write_pps(p) + pack_slice(enc.coeffs, p), enc
