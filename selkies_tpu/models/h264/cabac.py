"""CABAC entropy coding (ISO 14496-10 §9.3): coefficients → Main-profile NAL.

Second entropy backend behind the two-pass device split (ISSUE 19). The
coder is layered around a 16-bit *token* IR so every producer feeds one
sequential arithmetic engine:

  binarization (+ context-index derivation)  →  tokens  →  engine  →  bytes

Producers of tokens:
  * this module's pure-Python packers (`pack_slice_cabac`,
    `pack_slice_p_cabac`) — the readable spec, the byte-exactness oracle
    and the host fallback when device entropy is off;
  * device_cabac.py — the same binarization data-parallel on device over
    the shared structure pass (only emission differs from CAVLC).

Consumers of tokens:
  * `encode_tokens_py` — the reference arithmetic engine (9.3.4.2
    flowcharts, verbatim);
  * native/cabac_pack.cc via native.cabac_encode_tokens — the production
    engine, byte-identical by test.

Token format (uint16, see also native/cabac_pack.cc):
  bits [1:0] type — 0 REG   regular bin:   bin=bit2,   ctx=bits[12:3]
                    1 RUN   n regular bins, same ctx/value: n=bits[16:13]
                    2 BYP   bypass bins:   n=bits[5:2] (1..10),
                                           values MSB-first in bits[15:6]
                    3 TERM  end-of-slice/terminate bin: bin=bit2
  RUN exists for the device emitter (TU prefixes as one slot); n REG
  tokens and one RUN(n) produce identical engine state by construction.

Context subset: this encoder emits only I_16x16 and P_Skip/P_L0_16x16
macroblocks (see cavlc.py), so of the 1024 spec contexts only
0..275 + the terminate bin are reachable: mb_type (3..10), skip (11..13),
P mb_type (14..16), mvd (40..53), qp_delta (60), chroma pred (64..67),
cbp (73..84), coded_block_flag (85..104), significant/last (105..226),
levels (227..265).
"""

from __future__ import annotations

import numpy as np

from selkies_tpu.models.h264.bitstream import (
    NAL_SLICE_IDR,
    NAL_SLICE_NON_IDR,
    SLICE_I,
    SLICE_P,
    StreamParams,
    write_slice_header,
)
from selkies_tpu.models.h264.cabac_tables import (
    INIT_I,
    INIT_PB,
    RANGE_LPS,
    TRANS_LPS,
)
from selkies_tpu.models.h264.numpy_ref import FrameCoeffs, PFrameCoeffs, mv_pred_16x16
from selkies_tpu.models.h264.tables import (
    CHROMA_BLOCK_ORDER,
    LUMA_BLOCK_ORDER,
    ZIGZAG_FLAT,
)
from selkies_tpu.utils.bits import BitWriter, annexb_nal

__all__ = [
    "N_STATES", "TOK_REG", "TOK_RUN", "TOK_BYP", "TOK_TERM",
    "tok_reg", "tok_run", "tok_term", "init_states", "encode_tokens_py",
    "TokenWriter", "pack_slice_cabac", "pack_slice_p_cabac",
    "mb_tokens_i16", "mb_tokens_p", "skip_ctx_inc", "finish_cabac_nal",
]

N_STATES = 276  # regular contexts we ever touch (terminate needs no state)

TOK_REG, TOK_RUN, TOK_BYP, TOK_TERM = 0, 1, 2, 3


def tok_reg(ctx: int, b: int) -> int:
    return TOK_REG | ((b & 1) << 2) | (ctx << 3)


def tok_run(ctx: int, b: int, n: int) -> int:
    return TOK_RUN | ((b & 1) << 2) | (ctx << 3) | (n << 13)


def tok_term(b: int) -> int:
    return TOK_TERM | ((b & 1) << 2)


def _clip3(lo: int, hi: int, v: int) -> int:
    return lo if v < lo else hi if v > hi else v


def init_states(qp: int, slice_type: int, cabac_init_idc: int = 0) -> np.ndarray:
    """(N_STATES, 2) uint8 [pStateIdx, valMPS] per 9.3.1.1."""
    table = INIT_I if slice_type == SLICE_I else INIT_PB[cabac_init_idc]
    out = np.empty((N_STATES, 2), np.uint8)
    q = _clip3(0, 51, qp)
    for ctx in range(N_STATES):
        m, n = table[ctx]
        pre = _clip3(1, 126, ((m * q) >> 4) + n)
        if pre <= 63:
            out[ctx] = (63 - pre, 0)
        else:
            out[ctx] = (pre - 64, 1)
    return out


def encode_tokens_py(states: np.ndarray, tokens) -> bytes:
    """Reference binary arithmetic engine (9.3.4.2). `states` is consumed
    as a working copy; the stream must end with a TERM(1) token (the
    end-of-slice flush, whose final written bit doubles as the
    rbsp_stop_one_bit) and the returned bytes are zero-padded to a byte
    boundary, ready to append to an aligned slice header."""
    st = [(int(s), int(m)) for s, m in states]
    low, rng, outstanding = 0, 510, 0
    first = True
    out = bytearray()
    acc, nacc = 0, 0

    def emit(b):
        nonlocal acc, nacc
        acc = (acc << 1) | b
        nacc += 1
        if nacc == 8:
            out.append(acc)
            acc, nacc = 0, 0

    def put_bit(b):
        nonlocal first, outstanding
        if first:
            first = False
        else:
            emit(b)
        while outstanding:
            emit(1 - b)
            outstanding -= 1

    def renorm():
        nonlocal low, rng, outstanding
        while rng < 256:
            if low < 256:
                put_bit(0)
            elif low >= 512:
                low -= 512
                put_bit(1)
            else:
                low -= 256
                outstanding += 1
            low <<= 1
            rng <<= 1

    def decision(ctx, b):
        nonlocal low, rng
        s, mps = st[ctx]
        lps = RANGE_LPS[s][(rng >> 6) & 3]
        rng -= lps
        if b != mps:
            low += rng
            rng = lps
            if s == 0:
                mps ^= 1
            st[ctx] = (TRANS_LPS[s], mps)
        else:
            st[ctx] = (s + 1 if s < 62 else 62, mps)
        renorm()

    def bypass(b):
        nonlocal low, outstanding
        low <<= 1
        if b:
            low += rng
        if low >= 1024:
            put_bit(1)
            low -= 1024
        elif low < 512:
            put_bit(0)
        else:
            low -= 512
            outstanding += 1

    flushed = False
    for t in tokens:
        t = int(t)
        kind = t & 3
        if kind == TOK_REG:
            decision((t >> 3) & 0x3FF, (t >> 2) & 1)
        elif kind == TOK_RUN:
            ctx, b = (t >> 3) & 0x3FF, (t >> 2) & 1
            for _ in range(t >> 13):
                decision(ctx, b)
        elif kind == TOK_BYP:
            n = (t >> 2) & 0xF
            v = t >> 6
            for i in range(n - 1, -1, -1):
                bypass((v >> i) & 1)
        else:  # TERM
            rng -= 2
            if (t >> 2) & 1:
                low += rng
                rng = 2
                renorm()
                put_bit((low >> 9) & 1)
                emit((low >> 8) & 1)
                emit(1)  # rbsp_stop_one_bit
                flushed = True
            else:
                renorm()
    if not flushed:
        raise ValueError("token stream did not end in a TERM(1) flush")
    while nacc:
        emit(0)  # alignment zero bits after the stop bit
    return bytes(out)


class TokenWriter:
    """Accumulates tokens; splits oversized runs/bypass groups."""

    __slots__ = ("toks",)

    def __init__(self) -> None:
        self.toks: list[int] = []

    def reg(self, ctx: int, b: int) -> None:
        self.toks.append(TOK_REG | ((b & 1) << 2) | (ctx << 3))

    def bypass_bits(self, value: int, nbits: int) -> None:
        while nbits > 0:
            n = min(nbits, 10)
            chunk = (value >> (nbits - n)) & ((1 << n) - 1)
            self.toks.append(TOK_BYP | (n << 2) | (chunk << 6))
            nbits -= n

    def term(self, b: int) -> None:
        self.toks.append(TOK_TERM | ((b & 1) << 2))

    def array(self) -> np.ndarray:
        return np.asarray(self.toks, np.uint16)


# ---------------------------------------------------------------- binarization

_SIG_OFF = (0, 15, 29, 44, 47)   # ctxBlockCat offsets for sig/last maps
_LVL_OFF = (0, 10, 20, 30, 39)   # ... for coeff_abs_level_minus1


def _residual_tokens(tw: TokenWriter, coeffs, cat: int, cbf_inc: int) -> int:
    """One residual_block_cabac (7.3.5.3.3): coded_block_flag,
    significance map, levels in reverse scan order. Returns the
    coded_block_flag (for the neighbour cbf grids)."""
    nz = [i for i, c in enumerate(coeffs) if c]
    cbf = 1 if nz else 0
    tw.reg(85 + 4 * cat + cbf_inc, cbf)
    if not cbf:
        return 0
    n = len(coeffs)
    last = nz[-1]
    soff, loff = 105 + _SIG_OFF[cat], 166 + _SIG_OFF[cat]
    nzset = set(nz)
    for i in range(min(last + 1, n - 1)):
        inc = min(i, 2) if cat == 3 else i
        sig = 1 if i in nzset else 0
        tw.reg(soff + inc, sig)
        if sig:
            tw.reg(loff + inc, 1 if i == last else 0)
    base = 227 + _LVL_OFF[cat]
    eq1 = gt1 = 0
    for i in reversed(nz):
        level = int(coeffs[i])
        mag = abs(level)
        m = min(mag - 1, 14)
        c0 = base + (0 if gt1 else min(4, 1 + eq1))
        c1 = base + 5 + min(4 - (1 if cat == 3 else 0), gt1)
        tw.reg(c0, 1 if m > 0 else 0)
        for _ in range(m - 1):
            tw.reg(c1, 1)
        if 0 < m < 14:
            tw.reg(c1, 0)
        if mag - 1 >= 14:  # UEG0 escape suffix, bypass
            v = mag - 1 - 14
            k = 0
            while v >= (1 << k):
                tw.bypass_bits(1, 1)
                v -= 1 << k
                k += 1
            tw.bypass_bits(0, 1)
            if k:
                tw.bypass_bits(v, k)
        tw.bypass_bits(1 if level < 0 else 0, 1)
        if mag > 1:
            gt1 += 1
        else:
            eq1 += 1
    return 1


def _mvd_tokens(tw: TokenWriter, mvd: int, comp: int, abs_a: int, abs_b: int) -> None:
    """UEG3 (uCoff 9) mvd binarization; ctx 40/47 + neighbour-sum inc."""
    base = 40 if comp == 0 else 47
    s = abs_a + abs_b
    inc = 0 if s < 3 else (2 if s > 32 else 1)
    a = abs(mvd)
    m = min(a, 9)
    ctx_of = lambda j: base + (inc if j == 0 else 3 + min(j - 1, 3))  # noqa: E731
    for j in range(m):
        tw.reg(ctx_of(j), 1)
    if m < 9:
        tw.reg(ctx_of(m), 0)
    if a >= 9:  # EG3 suffix, bypass
        v = a - 9
        k = 3
        while v >= (1 << k):
            tw.bypass_bits(1, 1)
            v -= 1 << k
            k += 1
        tw.bypass_bits(0, 1)
        tw.bypass_bits(v, k)
    if a:
        tw.bypass_bits(1 if mvd < 0 else 0, 1)


def skip_ctx_inc(skip, mbx: int, mby: int) -> int:
    """mb_skip_flag ctxIdxInc: available-and-not-skipped neighbours."""
    inc = 0
    if mbx > 0 and not skip[mby, mbx - 1]:
        inc += 1
    if mby > 0 and not skip[mby - 1, mbx]:
        inc += 1
    return inc


class _CbfGrids:
    """Neighbour coded_block_flag state for one slice.

    Grid cells hold the *transmitted* cbf where the block was coded and
    0 where it was absent (skip MB / cbp bit clear) — which is exactly
    condTermFlagN for an available neighbour (9.3.3.1.1.9: a missing
    transform block reads as 0 unless the edge rules below apply).
    Out-of-slice neighbours read 1 for intra macroblocks, 0 for inter.
    """

    def __init__(self, mbh: int, mbw: int) -> None:
        self.luma_dc = np.zeros((mbh, mbw), np.int8)
        self.luma = np.zeros((mbh * 4, mbw * 4), np.int8)
        self.chroma_dc = np.zeros((2, mbh, mbw), np.int8)
        self.chroma = np.zeros((2, mbh * 2, mbw * 2), np.int8)

    @staticmethod
    def inc(grid, bx: int, by: int, intra: bool) -> int:
        edge = 1 if intra else 0
        a = grid[by, bx - 1] if bx > 0 else edge
        b = grid[by - 1, bx] if by > 0 else edge
        return int(a) + 2 * int(b)


def _cbp_tokens(tw: TokenWriter, cbp_luma: int, cbp_chroma: int,
                cl_left: int, cl_top: int, cc_left: int, cc_top: int) -> None:
    """coded_block_pattern: FL4 luma prefix + TU2 chroma suffix.

    cl_left/cl_top are the neighbouring MBs' CodedBlockPatternLuma with
    unavailable neighbours passed as 15 (an absent neighbour reads as
    coded, condTermFlag 0); cc_* are neighbouring CodedBlockPatternChroma
    with unavailable as 0.
    """
    # luma bit 0: A = left MB bit 1, B = top MB bit 2
    c = (0 if (cl_left >> 1) & 1 else 1) + 2 * (0 if (cl_top >> 2) & 1 else 1)
    tw.reg(73 + c, cbp_luma & 1)
    c = (0 if cbp_luma & 1 else 1) + 2 * (0 if (cl_top >> 3) & 1 else 1)
    tw.reg(73 + c, (cbp_luma >> 1) & 1)
    c = (0 if (cl_left >> 3) & 1 else 1) + 2 * (0 if cbp_luma & 1 else 1)
    tw.reg(73 + c, (cbp_luma >> 2) & 1)
    c = (0 if (cbp_luma >> 2) & 1 else 1) + 2 * (0 if (cbp_luma >> 1) & 1 else 1)
    tw.reg(73 + c, (cbp_luma >> 3) & 1)
    c = (1 if cc_left else 0) + 2 * (1 if cc_top else 0)
    tw.reg(77 + c, 1 if cbp_chroma else 0)
    if cbp_chroma:
        c = (1 if cc_left == 2 else 0) + 2 * (1 if cc_top == 2 else 0)
        tw.reg(81 + c, 1 if cbp_chroma == 2 else 0)


def _mb_residual_tokens(tw, grids, mbx, mby, intra, cbp_luma, cbp_chroma,
                        luma_dc_scan, luma_scan, chroma_dc, chroma_scan,
                        luma_from: int) -> None:
    """Shared residual walk for I16 (luma_from=1, cat 0/1 + always-on DC)
    and inter (luma_from=0, cat 2) macroblocks."""
    if intra:
        inc = _CbfGrids.inc(grids.luma_dc, mbx, mby, intra)
        grids.luma_dc[mby, mbx] = _residual_tokens(tw, luma_dc_scan, 0, inc)
    cat_l = 1 if intra else 2
    for x4, y4 in LUMA_BLOCK_ORDER:
        b8 = (y4 >> 1) * 2 + (x4 >> 1)
        if not cbp_luma & (1 << b8):
            continue
        bx, by = mbx * 4 + x4, mby * 4 + y4
        inc = _CbfGrids.inc(grids.luma, bx, by, intra)
        grids.luma[by, bx] = _residual_tokens(
            tw, luma_scan[y4, x4, luma_from:], cat_l, inc)
    if cbp_chroma:
        for comp in range(2):
            inc = _CbfGrids.inc(grids.chroma_dc[comp], mbx, mby, intra)
            grids.chroma_dc[comp, mby, mbx] = _residual_tokens(
                tw, chroma_dc[comp].reshape(4), 3, inc)
    if cbp_chroma == 2:
        for comp in range(2):
            for x4, y4 in CHROMA_BLOCK_ORDER:
                bx, by = mbx * 2 + x4, mby * 2 + y4
                inc = _CbfGrids.inc(grids.chroma[comp], bx, by, intra)
                grids.chroma[comp, by, bx] = _residual_tokens(
                    tw, chroma_scan[comp, y4, x4, 1:], 4, inc)


def mb_tokens_i16(tw, grids, chroma_modes, mbx, mby, luma_mode, chroma_mode,
                  cbp_luma, cbp_chroma, luma_dc_scan, luma_scan, chroma_dc,
                  chroma_scan) -> None:
    """One I_16x16 macroblock_layer's tokens (9.3.2.5 Table 9-36 mb_type
    binarization: prefix 1, I_PCM terminate 0, cbp/predMode suffix)."""
    inc = (1 if mbx > 0 else 0) + (1 if mby > 0 else 0)
    tw.reg(3 + inc, 1)
    tw.term(0)  # the I_PCM escape is a terminate bin
    tw.reg(6, 1 if cbp_luma else 0)
    tw.reg(7, 1 if cbp_chroma else 0)
    if cbp_chroma:
        tw.reg(8, 1 if cbp_chroma == 2 else 0)
    tw.reg(9, (luma_mode >> 1) & 1)
    tw.reg(10, luma_mode & 1)  # predMode bins: ctx 9 then 10 (9.3.3.1.2
    # conditions both incs on the chroma-CBP bin, already consumed above)
    # intra_chroma_pred_mode: TU cMax 3, ctx 64 + neighbour inc, then 67
    inc = 0
    if mbx > 0 and chroma_modes[mby, mbx - 1]:
        inc += 1
    if mby > 0 and chroma_modes[mby - 1, mbx]:
        inc += 1
    for j in range(chroma_mode):
        tw.reg(64 + inc if j == 0 else 67, 1)
    if chroma_mode < 3:
        tw.reg(64 + inc if chroma_mode == 0 else 67, 0)
    chroma_modes[mby, mbx] = chroma_mode
    tw.reg(60, 0)  # mb_qp_delta (constant QP per slice)
    _mb_residual_tokens(tw, grids, mbx, mby, True, cbp_luma, cbp_chroma,
                        luma_dc_scan, luma_scan, chroma_dc, chroma_scan, 1)


def mb_tokens_p(tw, grids, mbx, mby, mvdx, mvdy, abs_mvd, cbp_luma,
                cbp_chroma, cbp_l_grid, cbp_c_grid, luma_scan, chroma_dc,
                chroma_scan) -> None:
    """One coded P_L0_16x16 macroblock_layer's tokens. `abs_mvd` is the
    per-MB |mvd| grid (skip MBs hold 0); cbp_*_grid the per-MB coded
    block patterns (skip MBs hold 0) — both updated here."""
    tw.reg(14, 0)  # P mb_type prefix: P_L0_16x16 = b(14:0, 15:0, 16:0)
    tw.reg(15, 0)
    tw.reg(16, 0)
    for comp, mvd in ((0, mvdx), (1, mvdy)):
        a = abs_mvd[mby, mbx - 1, comp] if mbx > 0 else 0
        b = abs_mvd[mby - 1, mbx, comp] if mby > 0 else 0
        _mvd_tokens(tw, mvd, comp, int(a), int(b))
    abs_mvd[mby, mbx, 0] = abs(mvdx)
    abs_mvd[mby, mbx, 1] = abs(mvdy)
    cl_left = int(cbp_l_grid[mby, mbx - 1]) if mbx > 0 else 15
    cl_top = int(cbp_l_grid[mby - 1, mbx]) if mby > 0 else 15
    cc_left = int(cbp_c_grid[mby, mbx - 1]) if mbx > 0 else 0
    cc_top = int(cbp_c_grid[mby - 1, mbx]) if mby > 0 else 0
    _cbp_tokens(tw, cbp_luma, cbp_chroma, cl_left, cl_top, cc_left, cc_top)
    cbp_l_grid[mby, mbx] = cbp_luma
    cbp_c_grid[mby, mbx] = cbp_chroma
    if cbp_luma or cbp_chroma:
        tw.reg(60, 0)  # mb_qp_delta
    _mb_residual_tokens(tw, grids, mbx, mby, False, cbp_luma, cbp_chroma,
                        None, luma_scan, chroma_dc, chroma_scan, 0)


# ------------------------------------------------------------------- packers

def _encode_engine(tokens: np.ndarray, qp: int, slice_type: int,
                   cabac_init_idc: int) -> bytes:
    """Engine dispatch: native one-pass coder when built, Python oracle
    otherwise (byte-identical by tests/test_cabac.py)."""
    from selkies_tpu.models.h264 import native

    states = init_states(qp, slice_type, cabac_init_idc)
    if getattr(native, "cabac_native_available", lambda: False)():
        return native.cabac_encode_tokens(states, tokens)
    return encode_tokens_py(states, tokens)


def finish_cabac_nal(w: BitWriter, tokens: np.ndarray, qp: int,
                     slice_type: int, cabac_init_idc: int, nal_type: int) -> bytes:
    """Slice header writer state + token stream → Annex-B NAL: alignment
    ones, arithmetic payload, emulation prevention."""
    w.byte_align(1)  # cabac_alignment_one_bit
    payload = _encode_engine(tokens, qp, slice_type, cabac_init_idc)
    return annexb_nal(3, nal_type, w.get_bytes() + payload)


def pack_slice_cabac(
    fc: FrameCoeffs,
    p: StreamParams,
    frame_num: int = 0,
    idr: bool = True,
    idr_pic_id: int = 0,
    first_mb: int = 0,
) -> bytes:
    """Entropy-code Intra16x16 MBs into one CABAC slice NAL. Mirrors
    cavlc.pack_slice (same grid/band contract: fc may be one band, with
    neighbour availability resetting at the slice's first row)."""
    mbh, mbw = fc.luma_mode.shape
    w = BitWriter()
    write_slice_header(w, p, SLICE_I, frame_num, idr=idr,
                       idr_pic_id=idr_pic_id, slice_qp=fc.qp,
                       first_mb=first_mb)
    luma_ac = fc.luma_ac.reshape(mbh, mbw, 4, 4, 16)[..., ZIGZAG_FLAT]
    chroma_ac = fc.chroma_ac.reshape(mbh, mbw, 2, 2, 2, 16)[..., ZIGZAG_FLAT]
    luma_dc_scan = fc.luma_dc.reshape(mbh, mbw, 16)[..., ZIGZAG_FLAT]

    tw = TokenWriter()
    grids = _CbfGrids(mbh, mbw)
    chroma_modes = np.zeros((mbh, mbw), np.int8)
    last = mbh * mbw - 1
    for mby in range(mbh):
        for mbx in range(mbw):
            cbp_luma = 15 if np.any(luma_ac[mby, mbx, :, :, 1:]) else 0
            if np.any(chroma_ac[mby, mbx, :, :, :, 1:]):
                cbp_chroma = 2
            elif np.any(fc.chroma_dc[mby, mbx]):
                cbp_chroma = 1
            else:
                cbp_chroma = 0
            mb_tokens_i16(tw, grids, chroma_modes, mbx, mby,
                          int(fc.luma_mode[mby, mbx]),
                          int(fc.chroma_mode[mby, mbx]),
                          cbp_luma, cbp_chroma,
                          luma_dc_scan[mby, mbx], luma_ac[mby, mbx],
                          fc.chroma_dc[mby, mbx], chroma_ac[mby, mbx])
            tw.term(1 if mby * mbw + mbx == last else 0)  # end_of_slice_flag
    return finish_cabac_nal(w, tw.array(), fc.qp, SLICE_I, 0,
                            NAL_SLICE_IDR if idr else NAL_SLICE_NON_IDR)


def pack_slice_p_cabac(
    fc: PFrameCoeffs,
    p: StreamParams,
    frame_num: int,
    ltr_ref: int | None = None,
    mark_ltr: int | None = None,
    mmco_evict: tuple = (),
    first_mb: int = 0,
    cabac_init_idc: int = 0,
) -> bytes:
    """Entropy-code one P frame (P_Skip / P_L0_16x16) into a CABAC slice
    NAL. CABAC P slices carry a per-MB mb_skip_flag (no skip runs) and a
    per-MB end_of_slice terminate bin; everything else mirrors
    cavlc.pack_slice_p's syntax subset."""
    mbh, mbw = fc.skip.shape
    w = BitWriter()
    write_slice_header(w, p, SLICE_P, frame_num, idr=False, slice_qp=fc.qp,
                       ltr_ref=ltr_ref, mark_ltr=mark_ltr,
                       mmco_evict=mmco_evict, first_mb=first_mb,
                       cabac_init_idc=cabac_init_idc)
    luma_scan = fc.luma_ac.reshape(mbh, mbw, 4, 4, 16)[..., ZIGZAG_FLAT]
    chroma_scan = fc.chroma_ac.reshape(mbh, mbw, 2, 2, 2, 16)[..., ZIGZAG_FLAT]

    tw = TokenWriter()
    grids = _CbfGrids(mbh, mbw)
    abs_mvd = np.zeros((mbh, mbw, 2), np.int32)
    cbp_l_grid = np.zeros((mbh, mbw), np.int8)
    cbp_c_grid = np.zeros((mbh, mbw), np.int8)
    last = mbh * mbw - 1
    for mby in range(mbh):
        for mbx in range(mbw):
            skip = bool(fc.skip[mby, mbx])
            tw.reg(11 + skip_ctx_inc(fc.skip, mbx, mby), 1 if skip else 0)
            if not skip:
                px, py = mv_pred_16x16(fc.mvs, mbx, mby)
                mvdx = 4 * (int(fc.mvs[mby, mbx, 0]) - px)
                mvdy = 4 * (int(fc.mvs[mby, mbx, 1]) - py)
                cbp_luma = 0
                for b8 in range(4):
                    y8, x8 = b8 >> 1, b8 & 1
                    if np.any(luma_scan[mby, mbx, y8 * 2:y8 * 2 + 2,
                                        x8 * 2:x8 * 2 + 2]):
                        cbp_luma |= 1 << b8
                if np.any(chroma_scan[mby, mbx, :, :, :, 1:]):
                    cbp_chroma = 2
                elif np.any(fc.chroma_dc[mby, mbx]):
                    cbp_chroma = 1
                else:
                    cbp_chroma = 0
                mb_tokens_p(tw, grids, mbx, mby, mvdx, mvdy, abs_mvd,
                            cbp_luma, cbp_chroma, cbp_l_grid, cbp_c_grid,
                            luma_scan[mby, mbx], fc.chroma_dc[mby, mbx],
                            chroma_scan[mby, mbx])
            tw.term(1 if mby * mbw + mbx == last else 0)  # end_of_slice_flag
    return finish_cabac_nal(w, tw.array(), fc.qp, SLICE_P, cabac_init_idc,
                            NAL_SLICE_NON_IDR)
