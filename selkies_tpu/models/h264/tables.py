"""H.264 static tables: quantization matrices, scan orders, CAVLC VLCs.

Sources: ISO/IEC 14496-10 tables 9-5 (coeff_token), 9-7/9-8 (total_zeros),
9-9 (total_zeros chroma DC), 9-10 (run_before), and the standard
quantization multiplier/rescale factors (8.5.9).

All VLC tables are expressed as human-auditable bit strings and converted
to (value, nbits) pairs at import. Conformance is enforced empirically by
tests/test_h264_conformance.py, which decodes generated streams with
FFmpeg (via cv2) and compares reconstructions bit-exactly.
"""

from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------------------
# Scan orders
# ---------------------------------------------------------------------------

# 4x4 zigzag scan: index -> (row, col)
ZIGZAG_4x4 = [
    (0, 0), (0, 1), (1, 0), (2, 0),
    (1, 1), (0, 2), (0, 3), (1, 2),
    (2, 1), (3, 0), (3, 1), (2, 2),
    (1, 3), (2, 3), (3, 2), (3, 3),
]
ZIGZAG_FLAT = np.array([r * 4 + c for r, c in ZIGZAG_4x4], dtype=np.int32)

# Luma 4x4 block coding order within a macroblock (8x8 quadrant Z-order,
# 4x4 Z-order within): blk index -> (x4, y4) in units of 4 samples.
LUMA_BLOCK_ORDER = [
    (0, 0), (1, 0), (0, 1), (1, 1),
    (2, 0), (3, 0), (2, 1), (3, 1),
    (0, 2), (1, 2), (0, 3), (1, 3),
    (2, 2), (3, 2), (2, 3), (3, 3),
]

# Chroma 4x4 block order within the 8x8 plane (raster): blk -> (x4, y4)
CHROMA_BLOCK_ORDER = [(0, 0), (1, 0), (0, 1), (1, 1)]

# ---------------------------------------------------------------------------
# Quantization (8.5.9): MF (encoder multiplier) and V (decoder rescale)
# ---------------------------------------------------------------------------

# Rows: QP % 6. Columns: position class 0 (both even), 1 (both odd), 2 (mixed).
QUANT_MF = np.array(
    [
        [13107, 5243, 8066],
        [11916, 4660, 7490],
        [10082, 4194, 6554],
        [9362, 3647, 5825],
        [8192, 3355, 5243],
        [7282, 2893, 4559],
    ],
    dtype=np.int64,
)

DEQUANT_V = np.array(
    [
        [10, 16, 13],
        [11, 18, 14],
        [13, 20, 16],
        [14, 23, 18],
        [16, 25, 20],
        [18, 29, 23],
    ],
    dtype=np.int64,
)

# Position class for each coefficient of a 4x4 block.
_POS_CLASS = np.array(
    [[0 if (i % 2 == 0 and j % 2 == 0) else 1 if (i % 2 and j % 2) else 2 for j in range(4)] for i in range(4)],
    dtype=np.int64,
)


def mf_matrix(qp: int) -> np.ndarray:
    """4x4 encoder quant multipliers for QP."""
    return QUANT_MF[qp % 6][_POS_CLASS]


def v_matrix(qp: int) -> np.ndarray:
    """4x4 decoder rescale factors for QP."""
    return DEQUANT_V[qp % 6][_POS_CLASS]


# Chroma QP mapping (table 8-15) for qPi 30..51; below 30 identity.
_CHROMA_QP_TAIL = [29, 30, 31, 32, 32, 33, 34, 34, 35, 35, 36, 36, 37, 37, 37, 38, 38, 38, 39, 39, 39, 39]


def chroma_qp(qp: int, offset: int = 0) -> int:
    qpi = max(0, min(51, qp + offset))
    return qpi if qpi < 30 else _CHROMA_QP_TAIL[qpi - 30]


# ---------------------------------------------------------------------------
# CAVLC VLC tables
# ---------------------------------------------------------------------------


def _vlc(s: str) -> tuple[int, int]:
    """'0101' -> (value, nbits)."""
    return (int(s, 2), len(s))


def _tbl(rows: list[list[str | None]]) -> list[list[tuple[int, int] | None]]:
    return [[None if c is None else _vlc(c) for c in row] for row in rows]


# coeff_token, Table 9-5. Indexed [TotalCoeff][TrailingOnes].
# Three VLC tables by nC range plus the chroma-DC table; nC>=8 is 6-bit FLC.
# Row i = TotalCoeff i (0..16); column j = TrailingOnes j (0..3).

COEFF_TOKEN_NC_0_2: list[list[str | None]] = [
    ["1", None, None, None],
    ["000101", "01", None, None],
    ["00000111", "000100", "001", None],
    ["000000111", "00000110", "0000101", "00011"],
    ["0000000111", "000000110", "00000101", "000011"],
    ["00000000111", "0000000110", "000000101", "0000100"],
    ["0000000001111", "00000000110", "0000000101", "00000100"],
    ["0000000001011", "0000000001110", "00000000101", "000000100"],
    ["0000000001000", "0000000001010", "0000000001101", "0000000100"],
    ["00000000001111", "00000000001110", "0000000001001", "00000000100"],
    ["00000000001011", "00000000001010", "00000000001101", "0000000001100"],
    ["000000000001111", "000000000001110", "00000000001001", "00000000001100"],
    ["000000000001011", "000000000001010", "000000000001101", "00000000001000"],
    ["0000000000001111", "000000000000001", "000000000001001", "000000000001100"],
    ["0000000000001011", "0000000000001110", "0000000000001101", "000000000001000"],
    ["0000000000000111", "0000000000001010", "0000000000001001", "0000000000001100"],
    ["0000000000000100", "0000000000000110", "0000000000000101", "0000000000001000"],
]

COEFF_TOKEN_NC_2_4: list[list[str | None]] = [
    ["11", None, None, None],
    ["001011", "10", None, None],
    ["000111", "00111", "011", None],
    ["0000111", "001010", "001001", "0101"],
    ["00000111", "000110", "000101", "0100"],
    ["00000100", "0000110", "0000101", "00110"],
    ["000000111", "00000110", "00000101", "001000"],
    ["00000001111", "000000110", "000000101", "000100"],
    ["00000001011", "00000001110", "00000001101", "0000100"],
    ["000000001111", "00000001010", "00000001001", "000000100"],
    ["000000001011", "000000001110", "000000001101", "00000001100"],
    ["000000001000", "000000001010", "000000001001", "00000001000"],
    ["0000000001111", "0000000001110", "0000000001101", "000000001100"],
    ["0000000001011", "0000000001010", "0000000001001", "0000000001100"],
    ["0000000000111", "00000000001011", "0000000000110", "0000000001000"],
    ["00000000001001", "00000000001000", "00000000001010", "0000000000001"],
    ["00000000000111", "00000000000110", "00000000000101", "00000000000100"],
]

COEFF_TOKEN_NC_4_8: list[list[str | None]] = [
    ["1111", None, None, None],
    ["001111", "1110", None, None],
    ["001011", "01111", "1101", None],
    ["001000", "01100", "01110", "1100"],
    ["0001111", "01010", "01011", "1011"],
    ["0001011", "01000", "01001", "1010"],
    ["0001001", "001110", "001101", "1001"],
    ["0001000", "001010", "001001", "1000"],
    ["00001111", "0001110", "0001101", "01101"],
    ["00001011", "00001110", "0001010", "001100"],
    ["000001111", "00001010", "00001101", "0001100"],
    ["000001011", "000001110", "00001001", "00001100"],
    ["000001000", "000001010", "000001101", "00001000"],
    ["0000001101", "000000111", "000001001", "000001100"],
    ["0000001001", "0000001100", "0000001011", "0000001010"],
    ["0000000101", "0000001000", "0000000111", "0000000110"],
    ["0000000001", "0000000100", "0000000011", "0000000010"],
]

COEFF_TOKEN_CHROMA_DC: list[list[str | None]] = [
    ["01", None, None, None],
    ["000111", "1", None, None],
    ["000100", "000110", "001", None],
    ["000011", "0000011", "0000010", "000101"],
    ["000010", "00000011", "00000010", "0000000"],
]

_COEFF_TOKEN_TABLES = {
    0: _tbl(COEFF_TOKEN_NC_0_2),
    2: _tbl(COEFF_TOKEN_NC_2_4),
    4: _tbl(COEFF_TOKEN_NC_4_8),
    -1: _tbl(COEFF_TOKEN_CHROMA_DC),
}


def coeff_token_code(nc: int, total_coeff: int, trailing_ones: int) -> tuple[int, int]:
    """Return (value, nbits) for coeff_token."""
    if nc >= 8:
        if total_coeff == 0:
            return (0b000011, 6)
        return (((total_coeff - 1) << 2) | trailing_ones, 6)
    if nc == -1:
        table = _COEFF_TOKEN_TABLES[-1]
    elif nc < 2:
        table = _COEFF_TOKEN_TABLES[0]
    elif nc < 4:
        table = _COEFF_TOKEN_TABLES[2]
    else:
        table = _COEFF_TOKEN_TABLES[4]
    code = table[total_coeff][trailing_ones]
    if code is None:
        raise ValueError(f"invalid coeff_token TC={total_coeff} T1={trailing_ones}")
    return code


# total_zeros for 4x4 blocks (Tables 9-7, 9-8). TOTAL_ZEROS_4x4[tc-1][tz].
TOTAL_ZEROS_4x4: list[list[str]] = [
    # tzVlcIndex 1
    ["1", "011", "010", "0011", "0010", "00011", "00010", "000011", "000010",
     "0000011", "0000010", "00000011", "00000010", "000000011", "000000010", "000000001"],
    # 2
    ["111", "110", "101", "100", "011", "0101", "0100", "0011", "0010",
     "00011", "00010", "000011", "000010", "000001", "000000"],
    # 3
    ["0101", "111", "110", "101", "0100", "0011", "100", "011", "0010",
     "00011", "00010", "000001", "00001", "000000"],
    # 4
    ["00011", "111", "0101", "0100", "110", "101", "100", "0011", "011",
     "0010", "00010", "00001", "00000"],
    # 5
    ["0101", "0100", "0011", "111", "110", "101", "100", "011", "0010",
     "00001", "0001", "00000"],
    # 6
    ["000001", "00001", "111", "110", "101", "100", "011", "010", "0001",
     "001", "000000"],
    # 7
    ["000001", "00001", "101", "100", "011", "11", "010", "0001", "001", "000000"],
    # 8
    ["000001", "0001", "00001", "011", "11", "10", "010", "001", "000000"],
    # 9
    ["000001", "000000", "0001", "11", "10", "001", "01", "00001"],
    # 10
    ["00001", "00000", "001", "11", "10", "01", "0001"],
    # 11
    ["0000", "0001", "001", "010", "1", "011"],
    # 12
    ["0000", "0001", "01", "1", "001"],
    # 13
    ["000", "001", "1", "01"],
    # 14
    ["00", "01", "1"],
    # 15
    ["0", "1"],
]

# total_zeros for chroma DC 2x2 blocks (Table 9-9).
TOTAL_ZEROS_CHROMA_DC: list[list[str]] = [
    ["1", "01", "001", "000"],
    ["1", "01", "00"],
    ["1", "0"],
]

_TZ_4x4 = [[_vlc(c) for c in row] for row in TOTAL_ZEROS_4x4]
_TZ_CDC = [[_vlc(c) for c in row] for row in TOTAL_ZEROS_CHROMA_DC]


def total_zeros_code(total_coeff: int, total_zeros: int, chroma_dc: bool = False) -> tuple[int, int]:
    table = _TZ_CDC if chroma_dc else _TZ_4x4
    return table[total_coeff - 1][total_zeros]


# run_before (Table 9-10). RUN_BEFORE[min(zerosLeft,7)-1][run]; zerosLeft>6
# extends with unary codes for run >= 7.
RUN_BEFORE: list[list[str]] = [
    ["1", "0"],
    ["1", "01", "00"],
    ["11", "10", "01", "00"],
    ["11", "10", "01", "001", "000"],
    ["11", "10", "011", "010", "001", "000"],
    ["11", "000", "001", "011", "010", "101", "100"],
    ["111", "110", "101", "100", "011", "010", "001"],
]

_RUN_BEFORE = [[_vlc(c) for c in row] for row in RUN_BEFORE]


def run_before_code(zeros_left: int, run: int) -> tuple[int, int]:
    if zeros_left <= 6:
        return _RUN_BEFORE[zeros_left - 1][run]
    if run <= 6:
        return _RUN_BEFORE[6][run]
    # run 7..14: '0001', '00001', ... (run-4 zeros then a 1)
    return (1, run - 3)
