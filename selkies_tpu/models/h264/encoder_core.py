"""JAX encode core for tpuh264enc: the jit-compiled per-frame device step.

This is the TPU re-design of the encoder matrix's device work (the
reference delegates it to NVENC/VAAPI silicon, gstwebrtc_app.py:260-783):
intra prediction, forward/inverse 4x4 transforms, Hadamard DC paths, and
quantization — everything except bit-serial entropy coding, which stays on
the host (cavlc.py / native/cavlc_pack.cc).

Parallelisation strategy (the reason the prediction-mode policy exists):
  * rows 1..N use Intra16x16 VERTICAL prediction — each MB depends only on
    the reconstructed row above, so one `lax.scan` step processes an
    entire MB row as a single batched tensor op (120 MBs at 1080p).
  * row 0 uses DC prediction (left-only chain) — a short scan over
    columns, paid once per IDR frame.

TPU mapping: the 4x4 DCT/Hadamard transforms are expressed as explicit
add/shift butterflies over batched int32 tensors — pure VPU element-wise
work that XLA fuses with the quantizer (no integer-matmul lowering, no
float roundoff). All arithmetic is int32: the widest intermediate
(|coeff|·MF + f at QP 0) stays under 2^27. QP is a traced scalar, so
rate-control retunes never recompile.

Bit-exactness contract: every op mirrors numpy_ref.py exactly
(tests/test_encoder_core.py asserts array equality), which in turn is
FFmpeg-conformant (tools/cavlc_probe.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.models.h264 import tables

_POS_CLASS = np.array(
    [[0 if (i % 2 == 0 and j % 2 == 0) else 1 if (i % 2 and j % 2) else 2 for j in range(4)] for i in range(4)],
    np.int32,
)
_MF_BY_REM = jnp.asarray(np.asarray(tables.QUANT_MF, np.int32)[:, _POS_CLASS])  # (6, 4, 4)
_V_BY_REM = jnp.asarray(np.asarray(tables.DEQUANT_V, np.int32)[:, _POS_CLASS])  # (6, 4, 4)
_CHROMA_QP = jnp.asarray([tables.chroma_qp(q) for q in range(52)], jnp.int32)


def _last(x, i):
    return x[..., i]


def _fdct1d(x):
    """1-D forward core transform along the last axis of (..., 4)."""
    x0, x1, x2, x3 = _last(x, 0), _last(x, 1), _last(x, 2), _last(x, 3)
    s0, s1 = x0 + x3, x1 + x2
    d0, d1 = x0 - x3, x1 - x2
    return jnp.stack([s0 + s1, 2 * d0 + d1, s0 - s1, d0 - 2 * d1], axis=-1)


def fdct4(blocks):
    """Forward 4x4 core transform over (..., 4, 4) int32 blocks (exact)."""
    b = blocks.astype(jnp.int32)
    b = _fdct1d(b)  # transform columns index (last axis = j)
    b = _fdct1d(b.swapaxes(-1, -2)).swapaxes(-1, -2)  # transform rows
    return b


def _idct1d(x):
    """1-D inverse butterfly along the last axis (8.5.12.2 step)."""
    x0, x1, x2, x3 = _last(x, 0), _last(x, 1), _last(x, 2), _last(x, 3)
    e0, e1 = x0 + x2, x0 - x2
    e2 = jnp.right_shift(x1, 1) - x3
    e3 = x1 + jnp.right_shift(x3, 1)
    return jnp.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-1)


def idct4(coeffs):
    """Bit-exact inverse 4x4 transform: horizontal first, then vertical."""
    d = coeffs.astype(jnp.int32)
    d = _idct1d(d)  # horizontal: mix columns within each row
    d = _idct1d(d.swapaxes(-1, -2)).swapaxes(-1, -2)  # vertical
    return jnp.right_shift(d + 32, 6)


def _had1d(x):
    x0, x1, x2, x3 = _last(x, 0), _last(x, 1), _last(x, 2), _last(x, 3)
    s0, s1 = x0 + x1, x2 + x3
    d0, d1 = x0 - x1, x2 - x3
    return jnp.stack([s0 + s1, s0 - s1, d0 - d1, d0 + d1], axis=-1)


def _had4(x):
    """H4 · X · H4 for (..., 4, 4) (H4 symmetric)."""
    x = _had1d(x.astype(jnp.int32))
    return _had1d(x.swapaxes(-1, -2)).swapaxes(-1, -2)


def _had2(x):
    """H2 · X · H2 for (..., 2, 2)."""
    x = x.astype(jnp.int32)
    a = x[..., 0, 0] + x[..., 0, 1]
    b = x[..., 0, 0] - x[..., 0, 1]
    c = x[..., 1, 0] + x[..., 1, 1]
    d = x[..., 1, 0] - x[..., 1, 1]
    return jnp.stack(
        [jnp.stack([a + c, b + d], axis=-1), jnp.stack([a - c, b - d], axis=-1)], axis=-2
    )


def _qparams(qp, intra: bool = True):
    qbits = 15 + qp // 6
    f = jnp.left_shift(jnp.int32(1), qbits) // (3 if intra else 6)
    return qbits, f


def quant4(coeffs, qp, intra: bool = True):
    qbits, f = _qparams(qp, intra)
    mf = _MF_BY_REM[qp % 6]
    c = coeffs.astype(jnp.int32)
    level = jnp.right_shift(jnp.abs(c) * mf + f, qbits)
    return jnp.where(c < 0, -level, level)


def dequant4(levels, qp):
    return levels.astype(jnp.int32) * _V_BY_REM[qp % 6] * jnp.left_shift(jnp.int32(1), qp // 6)


def quant_luma_dc(dc, qp):
    t = jnp.right_shift(_had4(dc), 1)
    qbits, f = _qparams(qp, True)
    mf00 = _MF_BY_REM[qp % 6, 0, 0]
    level = jnp.right_shift(jnp.abs(t) * mf00 + 2 * f, qbits + 1)
    return jnp.where(t < 0, -level, level)


def dequant_luma_dc(levels, qp):
    f = _had4(levels)
    v00 = _V_BY_REM[qp % 6, 0, 0]
    qp_per = qp // 6
    hi = jnp.left_shift(f * v00, jnp.maximum(qp_per - 2, 0))
    lo = jnp.right_shift(
        f * v00 + jnp.left_shift(jnp.int32(1), jnp.maximum(1 - qp_per, 0)),
        jnp.maximum(2 - qp_per, 0),
    )
    return jnp.where(qp_per >= 2, hi, lo)


def quant_chroma_dc(dc, qp_c, intra: bool = True):
    t = _had2(dc)
    qbits, f = _qparams(qp_c, intra)
    mf00 = _MF_BY_REM[qp_c % 6, 0, 0]
    level = jnp.right_shift(jnp.abs(t) * mf00 + 2 * f, qbits + 1)
    return jnp.where(t < 0, -level, level)


def dequant_chroma_dc(levels, qp_c):
    f = _had2(levels)
    v00 = _V_BY_REM[qp_c % 6, 0, 0]
    return jnp.right_shift(jnp.left_shift(f * v00, qp_c // 6), 1)


def _row_to_blocks(row, n: int):
    """(n*4, W) plane row -> (mbw, n, n, 4, 4) indexed [mb][by][bx][i][j]."""
    h, w = row.shape
    mbw = w // (n * 4)
    return row.reshape(n, 4, mbw, n, 4).transpose(2, 0, 3, 1, 4)


def _blocks_to_row(blocks):
    """Inverse of _row_to_blocks: (mbw, n, n, 4, 4) -> (n*4, mbw*n*4)."""
    mbw, n = blocks.shape[0], blocks.shape[1]
    return blocks.transpose(1, 3, 0, 2, 4).reshape(n * 4, mbw * n * 4)


def _encode_plane_row(row, pred, qp, n: int, luma: bool):
    """Batched encode of one MB row of a plane.

    row, pred: (n*4, W) int32. Returns (dc (mbw,n,n), ac (mbw,n,n,4,4),
    recon (n*4, W))."""
    blocks = _row_to_blocks(row - pred, n)
    w = fdct4(blocks)
    dc = w[..., 0, 0]
    if luma:
        dc_levels = quant_luma_dc(dc, qp)
        dc_deq = dequant_luma_dc(dc_levels, qp)
    else:
        dc_levels = quant_chroma_dc(dc, qp)
        dc_deq = dequant_chroma_dc(dc_levels, qp)
    ac_levels = quant4(w, qp, intra=True)
    deq = dequant4(ac_levels, qp)
    deq = deq.at[..., 0, 0].set(dc_deq)
    recon = jnp.clip(_blocks_to_row(idct4(deq)) + pred, 0, 255)
    return dc_levels, ac_levels, recon


def _dc_pred_luma_jnp(left_col, has_left):
    dc = jnp.where(has_left, jnp.right_shift(left_col.sum() + 8, 4), 128)
    return jnp.broadcast_to(dc, (16, 16))


def _dc_pred_chroma_jnp(left_col, has_left):
    """Chroma DC prediction with top unavailable (8.3.4.1): the two block
    rows use the matching 4-sample left segments; no left -> 128."""
    top = jnp.where(has_left, jnp.right_shift(left_col[:4].sum() + 2, 2), 128)
    bot = jnp.where(has_left, jnp.right_shift(left_col[4:].sum() + 2, 2), 128)
    rows = jnp.concatenate([jnp.broadcast_to(top, (4,)), jnp.broadcast_to(bot, (4,))])
    return jnp.broadcast_to(rows[:, None], (8, 8))


def _encode_row0(y_row, u_row, v_row, qp, qp_c):
    """Row 0: DC prediction, serial scan over MB columns."""
    mbw = y_row.shape[1] // 16
    y_mbs = y_row.reshape(16, mbw, 16).transpose(1, 0, 2)
    u_mbs = u_row.reshape(8, mbw, 8).transpose(1, 0, 2)
    v_mbs = v_row.reshape(8, mbw, 8).transpose(1, 0, 2)

    def step(carry, xs):
        yl, ul, vl, has_left = carry
        y_mb, u_mb, v_mb = xs
        dc_y, ac_y, rec_y = _encode_plane_row(y_mb, _dc_pred_luma_jnp(yl, has_left), qp, 4, True)
        dc_u, ac_u, rec_u = _encode_plane_row(u_mb, _dc_pred_chroma_jnp(ul, has_left), qp_c, 2, False)
        dc_v, ac_v, rec_v = _encode_plane_row(v_mb, _dc_pred_chroma_jnp(vl, has_left), qp_c, 2, False)
        carry = (rec_y[:, -1], rec_u[:, -1], rec_v[:, -1], jnp.bool_(True))
        return carry, (dc_y[0], ac_y[0], dc_u[0], ac_u[0], dc_v[0], ac_v[0], rec_y, rec_u, rec_v)

    init = (
        jnp.zeros(16, jnp.int32),
        jnp.zeros(8, jnp.int32),
        jnp.zeros(8, jnp.int32),
        jnp.bool_(False),
    )
    _, outs = jax.lax.scan(step, init, (y_mbs, u_mbs, v_mbs))
    dc_y, ac_y, dc_u, ac_u, dc_v, ac_v, rec_y, rec_u, rec_v = outs
    rec_y = rec_y.transpose(1, 0, 2).reshape(16, mbw * 16)
    rec_u = rec_u.transpose(1, 0, 2).reshape(8, mbw * 8)
    rec_v = rec_v.transpose(1, 0, 2).reshape(8, mbw * 8)
    return dc_y, ac_y, dc_u, ac_u, dc_v, ac_v, rec_y, rec_u, rec_v


@jax.jit
def encode_frame_planes(y, u, v, qp):
    """Jitted all-Intra16x16 frame encode on padded planes.

    y: (H, W) uint8/int32, u/v: (H/2, W/2). qp: int32 scalar (traced — no
    recompile on rate-control changes). Returns a dict of FrameCoeffs-layout
    arrays plus recon planes (recon also feeds future P-frame prediction).
    """
    y = y.astype(jnp.int32)
    u = u.astype(jnp.int32)
    v = v.astype(jnp.int32)
    qp = jnp.asarray(qp, jnp.int32)
    qp_c = _CHROMA_QP[qp]
    h, w_ = y.shape
    mbh = h // 16

    r0 = _encode_row0(y[:16], u[:8], v[:8], qp, qp_c)
    dc_y0, ac_y0, dc_u0, ac_u0, dc_v0, ac_v0, rec_y0, rec_u0, rec_v0 = r0

    if mbh > 1:
        nrows = mbh - 1
        y_rows = y[16:].reshape(nrows, 16, w_)
        u_rows = u[8:].reshape(nrows, 8, w_ // 2)
        v_rows = v[8:].reshape(nrows, 8, w_ // 2)

        def step(carry, xs):
            yb, ub, vb = carry
            y_row, u_row, v_row = xs
            dc_y, ac_y, rec_y = _encode_plane_row(
                y_row, jnp.broadcast_to(yb, (16, yb.shape[0])), qp, 4, True
            )
            dc_u, ac_u, rec_u = _encode_plane_row(
                u_row, jnp.broadcast_to(ub, (8, ub.shape[0])), qp_c, 2, False
            )
            dc_v, ac_v, rec_v = _encode_plane_row(
                v_row, jnp.broadcast_to(vb, (8, vb.shape[0])), qp_c, 2, False
            )
            return (rec_y[-1], rec_u[-1], rec_v[-1]), (dc_y, ac_y, dc_u, ac_u, dc_v, ac_v, rec_y, rec_u, rec_v)

        init = (rec_y0[-1], rec_u0[-1], rec_v0[-1])
        _, outs = jax.lax.scan(step, init, (y_rows, u_rows, v_rows))
        dc_yr, ac_yr, dc_ur, ac_ur, dc_vr, ac_vr, rec_yr, rec_ur, rec_vr = outs
        luma_dc = jnp.concatenate([dc_y0[None], dc_yr])
        luma_ac = jnp.concatenate([ac_y0[None], ac_yr])
        cb_dc = jnp.concatenate([dc_u0[None], dc_ur])
        cb_ac = jnp.concatenate([ac_u0[None], ac_ur])
        cr_dc = jnp.concatenate([dc_v0[None], dc_vr])
        cr_ac = jnp.concatenate([ac_v0[None], ac_vr])
        recon_y = jnp.concatenate([rec_y0[None], rec_yr]).reshape(mbh * 16, w_)
        recon_u = jnp.concatenate([rec_u0[None], rec_ur]).reshape(mbh * 8, w_ // 2)
        recon_v = jnp.concatenate([rec_v0[None], rec_vr]).reshape(mbh * 8, w_ // 2)
    else:
        luma_dc, luma_ac = dc_y0[None], ac_y0[None]
        cb_dc, cb_ac = dc_u0[None], ac_u0[None]
        cr_dc, cr_ac = dc_v0[None], ac_v0[None]
        recon_y, recon_u, recon_v = rec_y0, rec_u0, rec_v0

    mbw = luma_dc.shape[1]
    row0 = (jnp.arange(mbh) == 0)[:, None] & jnp.ones((1, mbw), bool)
    return {
        "luma_mode": jnp.where(row0, 2, 0).astype(jnp.int32),  # DC / vertical
        "chroma_mode": jnp.where(row0, 0, 2).astype(jnp.int32),  # DC / vertical
        "luma_dc": luma_dc,
        "luma_ac": luma_ac,
        "chroma_dc": jnp.stack([cb_dc, cr_dc], axis=2),
        "chroma_ac": jnp.stack([cb_ac, cr_ac], axis=2),
        "recon_y": recon_y.astype(jnp.uint8),
        "recon_u": recon_u.astype(jnp.uint8),
        "recon_v": recon_v.astype(jnp.uint8),
    }


# ---------------------------------------------------------------------------
# Inter (P-frame) device path
# ---------------------------------------------------------------------------
#
# Unlike the intra row scan above, P frames have NO spatial prediction
# dependencies (P_Skip / P_L0_16x16 partitions only, prediction comes from
# the previous frame's reconstruction), so everything below is a single
# batched tensor program: full-search motion estimation, gather-based
# motion compensation, transform+quant, and skip-mask derivation all run
# over the whole macroblock grid at once. This is the steady-state hot
# path — a remote-desktop stream is one IDR then P frames forever
# (reference: keyframe_distance=-1 default, __main__.py:473-475).

# single source of truth for the ME geometry (the golden model owns it)
from selkies_tpu.models.h264.numpy_ref import COARSE_DS, COARSE_R, MV_PAD, REFINE_R, TOPK

# JAX clamps out-of-bounds gathers silently (no IndexError like numpy), so
# a reach that outgrows the pad would corrupt bitstreams without erroring.
assert COARSE_DS * COARSE_R + REFINE_R <= MV_PAD, "ME reach exceeds MV_PAD"

_ME_CHUNK = 17


def _me_candidates(search: int) -> tuple[np.ndarray, np.ndarray]:
    """Candidate (dx, dy) list in golden-model order: zero MV first, then
    raster (dy outer) — rank breaks SAD ties identically to numpy_ref."""
    cands = [(dx, dy) for dy in range(-search, search + 1) for dx in range(-search, search + 1)]
    cands.sort(key=lambda c: c != (0, 0))
    arr = np.array(cands, np.int32)
    ranks = np.arange(len(arr), dtype=np.int32)
    pad = (-len(arr)) % _ME_CHUNK
    if pad:
        # padding duplicates the zero MV at ranks beyond every real
        # candidate: same SAD as the real zero but a worse tie-break, so a
        # padded entry can never be selected (and ranks stay small enough
        # that SAD·scale + rank fits int32)
        arr = np.concatenate([arr, np.zeros((pad, 2), np.int32)])
        ranks = np.concatenate([ranks, np.arange(len(ranks), len(ranks) + pad, dtype=np.int32)])
    return arr, ranks


def motion_search(cur, ref_pad, search: int = 8):
    """Exhaustive full-pel SAD search: (H, W) planes -> (mbh, mbw, 2) MVs.

    Cost = SAD·scale + candidate rank (scale = next power of two above the
    candidate count), so ties resolve to the golden model's zero-first
    raster order exactly (tests assert array equality).
    Scanned in chunks of 17 candidates (vmap inside scan) to bound the
    live intermediate to chunk×H×W while keeping dispatch count low.
    """
    if search > MV_PAD:
        raise ValueError(f"search {search} exceeds MV_PAD={MV_PAD}")
    h, w = cur.shape
    mbh, mbw = h // 16, w // 16
    cands, ranks = _me_candidates(search)
    # tie-break scale: next power of two above the candidate count so
    # rank never aliases into SAD units
    scale = 1 << int(ranks.max()).bit_length()
    cand_chunks = jnp.asarray(cands.reshape(-1, _ME_CHUNK, 2))
    rank_chunks = jnp.asarray(ranks.reshape(-1, _ME_CHUNK))
    cur = cur.astype(jnp.int32)

    def sad_one(dxdy):
        sh = jax.lax.dynamic_slice(
            ref_pad, (MV_PAD + dxdy[1], MV_PAD + dxdy[0]), (h, w)
        ).astype(jnp.int32)
        return jnp.abs(cur - sh).reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))

    def step(carry, xs):
        best_cost, best_mv = carry
        cand, rank = xs
        sads = jax.vmap(sad_one)(cand)  # (C, mbh, mbw)
        cost = sads * scale + rank[:, None, None]
        i = jnp.argmin(cost, axis=0)
        c = jnp.take_along_axis(cost, i[None], 0)[0]
        mv = cand[i]
        better = c < best_cost
        return (
            jnp.where(better, c, best_cost),
            jnp.where(better[..., None], mv, best_mv),
        ), None

    init = (
        jnp.full((mbh, mbw), jnp.iinfo(jnp.int32).max, jnp.int32),
        jnp.zeros((mbh, mbw, 2), jnp.int32),
    )
    (best_cost, best_mv), _ = jax.lax.scan(step, init, (cand_chunks, rank_chunks))
    return best_mv


def _downsample4(plane):
    """4x4 box downsample, round-half-up (mirrors numpy_ref.downsample4)."""
    h, w = plane.shape
    s = plane.astype(jnp.int32).reshape(h // 4, 4, w // 4, 4).sum(axis=(1, 3))
    return jnp.right_shift(s + 8, 4)


def coarse_votes_jnp(cur, rd_ext, halo_dcols: int = 0):
    """Per-MB coarse-rank vote histogram: ((2*COARSE_R+1)^2,) int32.

    ``rd_ext`` is the DOWNSAMPLED reference, optionally pre-extended by
    ``halo_dcols`` REAL neighbour columns each side (the 2D tile grid's
    column exchange, parallel/bands.py — in downsampled space, so a
    tile's votes are element-exact with the full-row computation whose
    edge pad also happens after downsampling). halo_dcols=0 with a
    full-width plane is the classic band/frame case. Votes from the
    tiles of one slice row SUM to the row's histogram (psum over the
    ``col`` mesh axis / a host-side add), which is what makes the
    merged candidate list identical to the full-row encoder's."""
    h, w = cur.shape
    mbh, mbw = h // 16, w // 16
    yd = _downsample4(cur)
    rd = rd_ext.astype(jnp.int32)
    hd, wd = yd.shape
    if not 0 <= halo_dcols <= COARSE_R:
        raise ValueError(f"halo_dcols {halo_dcols} not in [0, {COARSE_R}]")
    px = COARSE_R - halo_dcols  # edge-pad the remaining horizontal reach

    cands, ranks = _me_candidates(COARSE_R)
    scale = 1 << int(ranks.max()).bit_length()
    cand_chunks = jnp.asarray(cands.reshape(-1, _ME_CHUNK, 2))
    rank_chunks = jnp.asarray(ranks.reshape(-1, _ME_CHUNK))
    rp = jnp.pad(rd, ((COARSE_R, COARSE_R), (px, px)), mode="edge")

    def sad_one(dxdy):
        sh = jax.lax.dynamic_slice(rp, (COARSE_R + dxdy[1], COARSE_R + dxdy[0]), (hd, wd))
        return jnp.abs(yd - sh).reshape(mbh, 4, mbw, 4).sum(axis=(1, 3))

    def step(carry, xs):
        best_cost, = carry
        cand, rank = xs
        sads = jax.vmap(sad_one)(cand)
        cost = sads * scale + rank[:, None, None]
        c = jnp.min(cost, axis=0)
        better = c < best_cost
        return (jnp.where(better, c, best_cost),), None

    init = (jnp.full((mbh, mbw), jnp.iinfo(jnp.int32).max, jnp.int32),)
    (best_cost,), _ = jax.lax.scan(step, init, (cand_chunks, rank_chunks))
    best_rank = best_cost & (scale - 1)  # cost = sad*scale + rank

    n_real = (2 * COARSE_R + 1) ** 2
    # dense bincount (gather/scatter-free): votes[r] = #{MBs with rank r}
    return (best_rank.reshape(-1, 1) == jnp.arange(n_real)[None, :]).sum(0)


def select_coarse_jnp(votes):
    """Vote histogram -> (TOPK, 2) int32 coarse candidates, in the golden
    model's order (votes desc, then rank asc)."""
    cands, _ = _me_candidates(COARSE_R)
    n_real = (2 * COARSE_R + 1) ** 2
    # top-K by votes desc then rank asc; vote count <= mbh*mbw < 2^22
    score = votes * 512 + (511 - jnp.arange(n_real))
    _, top_idx = jax.lax.top_k(score, TOPK)
    return jnp.asarray(cands[:n_real])[top_idx]  # (TOPK, 2) — tiny gather


def coarse_vote_candidates_jnp(cur, ref):
    """Device mirror of numpy_ref.coarse_vote_candidates: (TOPK, 2) int32
    coarse MVs in downsampled units, element-exact with the golden model.
    (Split into coarse_votes_jnp + select_coarse_jnp so the tile grid can
    psum the vote histograms of one slice row before selection — the
    composition here is graph-identical to the pre-split definition.)"""
    return select_coarse_jnp(coarse_votes_jnp(cur, _downsample4(ref.astype(jnp.int32))))


def _refine_cands_jnp(coarse, dy_max: int | None = None,
                      dx_max: int | None = None):
    """(TOPK, 2) coarse -> (1 + TOPK*(2R+1)^2, 2) full-res shift list,
    zero MV first (mirrors numpy_ref.refine_candidate_list).

    dy_max (static) clamps the VERTICAL component of every refined
    candidate to |dy| <= dy_max — the band-sliced step's candidate
    window (parallel/bands.py): a band's chip holds only its reference
    rows plus a `halo`, so when halo is below the full hierarchical
    reach the coarse votes are clamped such that no refined candidate
    can select prediction rows the slab doesn't really hold (predicting
    from replicated slab-edge rows would diverge from the decoder's MC,
    which reads the true full-frame reference). The clamp is applied to
    the coarse displacement, so the refine grid stays the golden ±R
    raster and candidate ORDER (rank tie-breaks) is preserved.
    dx_max is the HORIZONTAL mirror for the 2D tile grid: a tile's chip
    holds only `halo_cols` neighbour columns, so sub-reach column halos
    clamp the coarse dx the same way."""
    side = 2 * REFINE_R + 1
    if dy_max is not None:
        cmax = max(0, (int(dy_max) - REFINE_R) // COARSE_DS)
        coarse = coarse.at[:, 1].set(jnp.clip(coarse[:, 1], -cmax, cmax))
    if dx_max is not None:
        cmax = max(0, (int(dx_max) - REFINE_R) // COARSE_DS)
        coarse = coarse.at[:, 0].set(jnp.clip(coarse[:, 0], -cmax, cmax))
    d = jnp.stack(
        jnp.meshgrid(
            jnp.arange(-REFINE_R, REFINE_R + 1),
            jnp.arange(-REFINE_R, REFINE_R + 1),
            indexing="ij",
        ),
        axis=-1,
    )  # (side, side, 2) with [..., 0]=dy, [..., 1]=dx
    grid = jnp.stack([d[..., 1], d[..., 0]], axis=-1).reshape(1, -1, 2)  # raster dy-outer
    cands = (coarse[:, None, :] * COARSE_DS + grid).reshape(-1, 2)
    return jnp.concatenate([jnp.zeros((1, 2), jnp.int32), cands.astype(jnp.int32)])


def hier_me_mc(cur, ref_y, ry_pad, ru_pad, rv_pad, dy_max: int | None = None,
               dx_max: int | None = None, coarse=None):
    """Global-candidate ME fused with motion compensation — gather-free.

    Two scans over 1+TOPK*(2R+1)^2 global shifts. The COST scan carries
    only (best_cost,) and does a dynamic slice + dense SAD per step; the
    PRED scan re-walks the shifts carrying the luma/chroma prediction
    planes, selecting where the step's rank equals the decoded winner
    rank — no SAD recompute and no chroma math on losing steps' critical
    path state. Splitting keeps the heavy chroma bilinear + 3 plane
    selects out of the cost loop (~2x over the fused single scan).
    Returns (mvs (mbh,mbw,2) i32, pred_y, pred_u, pred_v i32).
    Element-exact vs numpy_ref.hier_search_me + mc_luma/mc_chroma: the
    chroma bilinear runs on the globally-shifted plane with the same
    frac weights, so selected values match the per-MB gather formulation.
    (Why no gathers: tools/profile_slope2.py measured 30 ms per full-plane
    gather on v5e vs 0.26 ms per global-shift SAD map.)

    ``coarse`` (a (TOPK, 2) candidate array) overrides the internal
    coarse vote — the 2D tile grid passes the row-merged selection
    (parallel/bands.py) so every tile of a slice row refines the same
    global candidates the full-row encoder would. ``dx_max`` clamps the
    horizontal window for sub-reach column halos (see _refine_cands_jnp).
    """
    h, w = cur.shape
    mbh, mbw = h // 16, w // 16
    ch, cw = h // 2, w // 2
    if coarse is None:
        coarse = coarse_vote_candidates_jnp(cur, ref_y)
    cands = _refine_cands_jnp(coarse, dy_max, dx_max)
    ncand = cands.shape[0]
    ranks = jnp.arange(ncand, dtype=jnp.int32)
    scale = 1 << int(np.int64(ncand - 1)).bit_length()
    # statically unrolled chunks. NOT a vmap: batched dynamic_slice
    # lowers to a gather (~30 ms per full plane on v5e,
    # tools/profile_slope2.py); the unrolled Python loop keeps every
    # shift a cheap DynamicSlice. Measured at 1080p/ncand=76: chunk=4
    # ~= chunk=19 ~= unchunked within the tunnel's noise floor (the
    # arithmetic, not step launches, bounds this scan) — 4 is kept for
    # its smaller compiled body.
    chunk = next(c for c in (4, 19, 13, 11, 7, 5, 3, 2, 1) if ncand % c == 0)
    cands_c = cands.reshape(-1, chunk, 2)
    ranks_c = ranks.reshape(-1, chunk)

    def cost_step(best_cost, xs):
        mvs_k, ranks_k = xs
        for k in range(chunk):
            mv = mvs_k[k]
            ys = jax.lax.dynamic_slice(ry_pad, (MV_PAD + mv[1], MV_PAD + mv[0]), (h, w))
            sad = jnp.abs(cur - ys.astype(jnp.int32)).reshape(mbh, 16, mbw, 16).sum(axis=(1, 3))
            best_cost = jnp.minimum(sad * scale + ranks_k[k], best_cost)
        return best_cost, None

    init_cost = jnp.full((mbh, mbw), jnp.iinfo(jnp.int32).max, jnp.int32)
    best_cost, _ = jax.lax.scan(cost_step, init_cost, (cands_c, ranks_c))
    best_rank = best_cost & (scale - 1)  # cost = sad*scale + rank

    def pred_step(carry, xs):
        best_mv, py, pu, pv = carry
        mvs_k, ranks_k = xs
        for k in range(chunk):
            mv, rank = mvs_k[k], ranks_k[k]
            better = best_rank == rank  # exactly one (step, k) wins per MB
            dx, dy = mv[0], mv[1]
            ys = jax.lax.dynamic_slice(ry_pad, (MV_PAD + dy, MV_PAD + dx), (h, w))

            # chroma prediction for this global shift (8.4.2.2.2 on the
            # whole plane): full-pel luma MV -> chroma half-pel bilinear
            cx, cy = jnp.right_shift(dx, 1), jnp.right_shift(dy, 1)
            xf, yf = 4 * (dx & 1), 4 * (dy & 1)

            def chroma_shift(rp):
                s = jax.lax.dynamic_slice(
                    rp, (MV_PAD + cy, MV_PAD + cx), (ch + 1, cw + 1)
                ).astype(jnp.int32)
                a, b = s[:-1, :-1], s[:-1, 1:]
                c, d = s[1:, :-1], s[1:, 1:]
                return jnp.right_shift(
                    (8 - xf) * (8 - yf) * a + xf * (8 - yf) * b
                    + (8 - xf) * yf * c + xf * yf * d + 32,
                    6,
                )

            us, vs = chroma_shift(ru_pad), chroma_shift(rv_pad)
            m16 = jnp.repeat(jnp.repeat(better, 16, 0), 16, 1)
            m8 = jnp.repeat(jnp.repeat(better, 8, 0), 8, 1)
            best_mv = jnp.where(better[..., None], mv, best_mv)
            py = jnp.where(m16, ys.astype(jnp.int32), py)
            pu = jnp.where(m8, us, pu)
            pv = jnp.where(m8, vs, pv)
        return (best_mv, py, pu, pv), None

    init_pred = (
        jnp.zeros((mbh, mbw, 2), jnp.int32),
        jnp.zeros((h, w), jnp.int32),
        jnp.zeros((ch, cw), jnp.int32),
        jnp.zeros((ch, cw), jnp.int32),
    )
    (mvs, py, pu, pv), _ = jax.lax.scan(pred_step, init_pred, (cands_c, ranks_c))
    return mvs, py, pu, pv


def hier_motion_search(cur, ref, ref_pad):
    """MV-only wrapper over hier_me_mc (parity tests / tools). ref_pad is
    the MV_PAD-padded luma; chroma planes are synthesized zeros."""
    h, w = cur.shape
    zero_c = jnp.zeros((h // 2 + 2 * MV_PAD, w // 2 + 2 * MV_PAD), jnp.uint8)
    mvs, _, _, _ = hier_me_mc(cur, jnp.asarray(ref), ref_pad, zero_c, zero_c)
    return mvs


def mc_luma(ref_pad, mvs):
    """Full-pel luma MC: gather the per-MB-shifted reference plane."""
    mbh, mbw = mvs.shape[:2]
    h, w = mbh * 16, mbw * 16
    mvx = jnp.repeat(jnp.repeat(mvs[..., 0], 16, 0), 16, 1)
    mvy = jnp.repeat(jnp.repeat(mvs[..., 1], 16, 0), 16, 1)
    iy = jnp.arange(h)[:, None] + mvy + MV_PAD
    ix = jnp.arange(w)[None, :] + mvx + MV_PAD
    return ref_pad[iy, ix].astype(jnp.int32)


def mc_chroma(ref_pad, mvs):
    """Chroma MC (8.4.2.2.2): full-pel luma MVs land chroma on half-pel;
    bilinear blend of the 4 neighbours with weights from frac ∈ {0, 4}."""
    mbh, mbw = mvs.shape[:2]
    h, w = mbh * 8, mbw * 8
    mvx = jnp.repeat(jnp.repeat(mvs[..., 0], 8, 0), 8, 1)
    mvy = jnp.repeat(jnp.repeat(mvs[..., 1], 8, 0), 8, 1)
    xf = 4 * (mvx & 1)
    yf = 4 * (mvy & 1)
    iy = jnp.arange(h)[:, None] + jnp.right_shift(mvy, 1) + MV_PAD
    ix = jnp.arange(w)[None, :] + jnp.right_shift(mvx, 1) + MV_PAD
    p = ref_pad.astype(jnp.int32)
    a = p[iy, ix]
    b = p[iy, ix + 1]
    c = p[iy + 1, ix]
    d = p[iy + 1, ix + 1]
    return jnp.right_shift(
        (8 - xf) * (8 - yf) * a + xf * (8 - yf) * b + (8 - xf) * yf * c + xf * yf * d + 32, 6
    )


def _plane_to_mb_blocks(plane, n: int):
    """(mbh*n*4, mbw*n*4) -> (mbh, mbw, n, n, 4, 4) [by][bx][i][j]."""
    h, w = plane.shape
    mbh, mbw = h // (n * 4), w // (n * 4)
    return plane.reshape(mbh, n, 4, mbw, n, 4).transpose(0, 3, 1, 4, 2, 5)


def _mb_blocks_to_plane(blocks):
    mbh, mbw, n = blocks.shape[0], blocks.shape[1], blocks.shape[2]
    return blocks.transpose(0, 2, 4, 1, 3, 5).reshape(mbh * n * 4, mbw * n * 4)


def _skip_mask(mvs, resid_zero):
    """Vectorized 8.4.1.1 P_Skip eligibility: residual-free MBs whose MV
    equals the skip-derived MV."""
    mbh, mbw = mvs.shape[:2]
    left = jnp.pad(mvs, ((0, 0), (1, 0), (0, 0)))[:, :-1]
    top = jnp.pad(mvs, ((1, 0), (0, 0), (0, 0)))[:-1]
    # C = top-right, replaced by D = top-left on the last column (both exist
    # whenever the else-branch below is reached: mbx>0 and mby>0).
    tr = jnp.pad(mvs, ((1, 0), (0, 1), (0, 0)))[:-1, 1:]
    tl = jnp.pad(mvs, ((1, 0), (1, 0), (0, 0)))[:-1, :-1]
    last_col = jnp.arange(mbw) == mbw - 1
    cmv = jnp.where(last_col[None, :, None], tl, tr)
    med = left + top + cmv - jnp.maximum(jnp.maximum(left, top), cmv) - jnp.minimum(
        jnp.minimum(left, top), cmv
    )
    edge = (jnp.arange(mbw)[None, :] == 0) | (jnp.arange(mbh)[:, None] == 0)
    left_zero = (left == 0).all(-1)
    top_zero = (top == 0).all(-1)
    zero_cond = edge | left_zero | top_zero
    skipmv = jnp.where(zero_cond[..., None], 0, med)
    return resid_zero & (mvs == skipmv).all(-1)


def _use_pallas_me(width: int) -> bool:
    """Pallas ME dispatch: on by default on real TPU backends (interpret
    mode on CPU is far slower than the XLA scan), off above the kernel's
    128-MB row width, SELKIES_PALLAS_ME=0/1 overrides."""
    import os

    env = os.environ.get("SELKIES_PALLAS_ME")
    if env == "0":
        return False
    if width // 16 > 128:
        return False
    if env == "1":
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def encode_frame_p_planes(y, u, v, ref_y, ref_u, ref_v, qp, search: int = 8, me: str = "hier"):
    """Jitted P-frame encode on padded planes against the previous recon.

    me="hier" (default): two-level hierarchical search covering ±32 —
    `search` is ignored on this path; me="full": flat exhaustive ±search
    (the original golden contract). `me`/`search` are Python-level config,
    not traceable values: close over them (functools.partial) when jitting
    with a non-default choice.
    Returns mvs/skip/coefficients (PFrameCoeffs layout) + recon planes.
    One batched program, no scans except the ME candidate loops.
    """
    y = y.astype(jnp.int32)
    u = u.astype(jnp.int32)
    v = v.astype(jnp.int32)
    qp = jnp.asarray(qp, jnp.int32)

    ry = jnp.pad(ref_y, MV_PAD, mode="edge")
    ru = jnp.pad(ref_u, MV_PAD, mode="edge")
    rv = jnp.pad(ref_v, MV_PAD, mode="edge")
    mvs, pred_y, pred_u, pred_v = _me_mc_dispatch(
        y, ref_y, ry, ru, rv, search=search, me=me)
    return _p_transform_tail(y, u, v, qp, mvs, pred_y, pred_u, pred_v)


def encode_band_p_planes(y, u, v, slab_y, slab_u, slab_v, qp, halo: int,
                         search: int = 8, me: str = "hier"):
    """Band-sliced P encode: one horizontal band of the frame against a
    halo-extended reference SLAB — the device half of the band-parallel
    slice step (parallel/bands.py).

    y/u/v are the band's source rows (16·band_mbh luma rows). slab_y
    carries the band's reference rows plus `halo` REAL reference rows
    above and below (slab_u/slab_v: halo//2 chroma rows each side); at
    picture edges the halo rows are edge-replicated, which matches both
    jnp.pad(mode="edge") on the full frame and the decoder's
    picture-boundary clamp (8.4.2.2.1). The slab is padded out to the
    full MV_PAD reach with edge replication, and when `halo` is below
    the hierarchical search's vertical reach the candidate list is
    band-clamped (dy_max = halo - 2, see _refine_cands_jnp) so every
    SELECTED prediction row is real reference content — exactly what
    the decoder's MC will read from the full decoded reference. That is
    the whole correctness story of the band split: each band's slice
    depends only on data resident on its chip, yet reconstructs
    identically on any conformant decoder.

    With halo=0 and a slab equal to the FULL reference this is
    graph-identical to encode_frame_p_planes (the SELKIES_BANDS=1
    byte-identity contract) — halo=0 is ONLY valid in that full-slab
    case. For a genuine band slab halo must be >= REFINE_R + 2: the
    refine grid always emits dy = ±REFINE_R around every (clamped)
    coarse candidate and the chroma bilinear reads one row past dy>>1,
    so a smaller halo could select predictions from replicated slab
    edges the decoder's full-frame reference does not contain. halo
    must be even and <= MV_PAD."""
    return encode_tile_p_planes(y, u, v, slab_y, slab_u, slab_v, qp,
                                halo=halo, search=search, me=me)


def encode_tile_p_planes(y, u, v, slab_y, slab_u, slab_v, qp, halo: int,
                         halo_cols: int = 0, search: int = 8, me: str = "hier",
                         coarse=None, defer_skip: bool = False):
    """Tile-sliced P encode: one rows×cols tile of the frame against a
    2D halo-extended reference SLAB — the device half of the 2D
    tile-grid step (parallel/bands.py, SELKIES_TILE_GRID).

    Generalizes encode_band_p_planes to a second (column) halo axis:
    ``slab_y`` carries the tile's reference pixels plus ``halo`` REAL
    rows above/below AND ``halo_cols`` REAL columns left/right (chroma
    slabs carry half of each), edge-replicated at picture boundaries —
    including the diagonal corner blocks, which the column-then-row
    exchange order in parallel/bands.py fills with the diagonal
    neighbour's pixels. ``halo_cols=0`` with a full-width slab is
    exactly the band case (same graph). The validity rule mirrors the
    vertical one: halo_cols must be even and either 0 (full-width slab)
    or in [REFINE_R + 2, MV_PAD]; below the full hierarchical reach + the
    chroma bilinear's one-column lookahead (COARSE_DS*COARSE_R +
    REFINE_R + 2 = 36) the horizontal candidate window is clamped to
    ``halo_cols - 2`` so no SELECTED prediction column is fabricated.

    ``coarse`` injects a precomputed (TOPK, 2) coarse candidate list:
    the tile grid merges the per-tile vote histograms of one slice row
    (psum over the ``col`` mesh axis) and selects ONCE, so every tile
    refines the same global candidates as the full-row band encoder —
    that, plus full-reach halos, is what makes an RxC grid's access
    units byte-identical to the SELKIES_BANDS=R oracle.

    ``defer_skip=True`` returns ``resid_zero`` instead of ``skip``: the
    P_Skip derivation needs the MV of the macroblock to the LEFT, which
    at an interior tile seam lives on the neighbouring chip — the tile
    grid derives skip AFTER the row gather, on the merged full-row MV
    grid, exactly reproducing the full-row semantics."""
    if halo % 2 or not 0 <= halo <= MV_PAD or 0 < halo < REFINE_R + 2:
        raise ValueError(
            f"halo {halo} must be even and 0 (full-reference slab) or in "
            f"[{REFINE_R + 2}, {MV_PAD}]")
    if halo_cols % 2 or not 0 <= halo_cols <= MV_PAD or \
            0 < halo_cols < REFINE_R + 2:
        raise ValueError(
            f"halo_cols {halo_cols} must be even and 0 (full-width slab) or "
            f"in [{REFINE_R + 2}, {MV_PAD}]")
    y = y.astype(jnp.int32)
    u = u.astype(jnp.int32)
    v = v.astype(jnp.int32)
    qp = jnp.asarray(qp, jnp.int32)
    halo_c, halo_cc = halo // 2, halo_cols // 2
    vt, vtc = MV_PAD - halo, MV_PAD - halo_c
    ht, htc = MV_PAD - halo_cols, MV_PAD - halo_cc
    ry = jnp.pad(slab_y, ((vt, vt), (ht, ht)), mode="edge")
    ru = jnp.pad(slab_u, ((vtc, vtc), (htc, htc)), mode="edge")
    rv = jnp.pad(slab_v, ((vtc, vtc), (htc, htc)), mode="edge")
    # tile-local reference (coarse candidate voting sees the tile when no
    # merged `coarse` list is injected)
    ref_y = slab_y[halo : slab_y.shape[0] - halo] if halo else slab_y
    if halo_cols:
        ref_y = ref_y[:, halo_cols : ref_y.shape[1] - halo_cols]
    # full reach is COARSE_DS*COARSE_R + REFINE_R = 34 luma rows/cols; the
    # chroma bilinear additionally reads one row/col past d>>1, so a halo
    # of 36+ already covers every candidate and no clamp is applied —
    # and neither is halo=0, where the slab IS the full reference
    full_reach = COARSE_DS * COARSE_R + REFINE_R + 2
    dy_max = None if halo == 0 or halo >= full_reach else halo - 2
    dx_max = (None if halo_cols == 0 or halo_cols >= full_reach
              else halo_cols - 2)
    mvs, pred_y, pred_u, pred_v = _me_mc_dispatch(
        y, ref_y, ry, ru, rv, search=search, me=me, dy_max=dy_max,
        dx_max=dx_max, coarse=coarse)
    return _p_transform_tail(y, u, v, qp, mvs, pred_y, pred_u, pred_v,
                             defer_skip=defer_skip)


def _me_mc_dispatch(y, ref_y, ry, ru, rv, *, search: int, me: str,
                    dy_max: int | None = None, dx_max: int | None = None,
                    coarse=None):
    """ME + MC over MV_PAD-padded reference planes (shared by the
    full-frame, band-sliced, and tile-sliced steps)."""
    if me == "hier":
        # fused gather-free ME+MC: predictions fall out of the same
        # candidate scan that picks the MVs. On TPU the Pallas kernel
        # (pallas_me.py) runs the same search ~3x faster by keeping each
        # MB row's reference window in VMEM; outputs are bit-identical
        # (tests/test_pallas_me.py), so this is purely a speed dispatch.
        if _use_pallas_me(y.shape[1]):
            from selkies_tpu.models.h264.pallas_me import hier_me_mc_pallas

            return hier_me_mc_pallas(y, ref_y, ry, ru, rv, dy_max=dy_max,
                                     dx_max=dx_max, coarse=coarse)
        return hier_me_mc(y, ref_y, ry, ru, rv, dy_max, dx_max, coarse)
    if dy_max is not None or dx_max is not None or coarse is not None:
        raise ValueError("tile-clamped candidate windows require me='hier'")
    mvs = motion_search(y, ry, search)
    return mvs, mc_luma(ry, mvs), mc_chroma(ru, mvs), mc_chroma(rv, mvs)


def _p_transform_tail(y, u, v, qp, mvs, pred_y, pred_u, pred_v,
                      defer_skip: bool = False):
    """Transform + quant + recon + skip derivation — everything after
    ME/MC, shared bit-exactly by encode_frame_p_planes and
    encode_band_p_planes/encode_tile_p_planes. ``defer_skip`` replaces
    the ``skip`` output with ``resid_zero`` (the residual-free mask) so
    a tile-grid caller can run _skip_mask on the row-merged MV grid."""
    qp_c = _CHROMA_QP[qp]
    # Luma: plain 4x4 transform, all 16 coeffs (no DC Hadamard in inter MBs)
    yb = _plane_to_mb_blocks(y - pred_y, 4)
    wy = fdct4(yb)
    luma_ac = quant4(wy, qp, intra=False)
    rec_y = jnp.clip(_mb_blocks_to_plane(idct4(dequant4(luma_ac, qp))) + pred_y, 0, 255)

    def chroma(plane, pred):
        cb = _plane_to_mb_blocks(plane - pred, 2)
        wc = fdct4(cb)
        dc = quant_chroma_dc(wc[..., 0, 0], qp_c, intra=False)
        ac = quant4(wc, qp_c, intra=False)
        deq = dequant4(ac, qp_c)
        deq = deq.at[..., 0, 0].set(dequant_chroma_dc(dc, qp_c))
        rec = jnp.clip(_mb_blocks_to_plane(idct4(deq)) + pred, 0, 255)
        return dc, ac, rec

    cb_dc, cb_ac, rec_u = chroma(u, pred_u)
    cr_dc, cr_ac, rec_v = chroma(v, pred_v)

    resid_zero = (
        (luma_ac == 0).all((-4, -3, -2, -1))
        & (cb_dc == 0).all((-2, -1))
        & (cr_dc == 0).all((-2, -1))
        & (cb_ac == 0).all((-4, -3, -2, -1))
        & (cr_ac == 0).all((-4, -3, -2, -1))
    )
    skip_kv = ({"resid_zero": resid_zero} if defer_skip
               else {"skip": _skip_mask(mvs, resid_zero)})

    return {
        "mvs": mvs,
        **skip_kv,
        "luma_ac": luma_ac,
        "chroma_dc": jnp.stack([cb_dc, cr_dc], axis=2),
        "chroma_ac": jnp.stack([cb_ac, cr_ac], axis=2),
        "recon_y": rec_y.astype(jnp.uint8),
        "recon_u": rec_u.astype(jnp.uint8),
        "recon_v": rec_v.astype(jnp.uint8),
    }


# ---------------------------------------------------------------------------
# Compact downlink
# ---------------------------------------------------------------------------
#
# The coefficient tensors are the device->host traffic (the reference's
# encoders emit final bitstreams on the GPU; ours entropy-codes on the
# host). Dense P-frame coeffs at 1080p are ~6.4 MB/frame — far more than
# the actual information content (desktop P frames are mostly zero blocks).
# pack_*_compact runs INSIDE the frame jit and emits:
#   * one int32 header: counts + packed MVs + per-MB nonzero-block bitmap
#     + skip bitmask (+ intra modes for IDR) — ~65 KB at 1080p, fixed size;
#   * one int16 data buffer whose first n rows are the nonzero 4x4 blocks
#     in global scan order — the host fetches only that prefix.
# The host scatters rows back into dense arrays (models/h264/compact.py)
# and feeds the unchanged CAVLC packer, so bitstreams are bit-identical to
# the dense path.

# Row-layout constants — the ONLY definition; compact.py (host unpack)
# imports these, so pack and unpack cannot drift apart.
# P frame, per-MB rows: [0:16) luma AC, [16:24) chroma AC, [24:26) chroma DC.
P_ROW_CHROMA = 16
P_ROW_DC = 24
P_ENTRIES = 26
# IDR, per-MB rows: [0] luma DC, [1:17) luma AC, [17:25) chroma AC,
# [25:27) chroma DC.
I_ROW_LUMA = 1
I_ROW_CHROMA = 17
I_ROW_DC_C = 25
I_ENTRIES = 27


def _compact_rows(rows):
    """rows: (M, E, 16) int16 -> (flags (M,E) bool, buf (M*E, 16) int16,
    n int32). buf's first n rows are the nonzero rows in scan order."""
    m, e, _ = rows.shape
    flat = rows.reshape(m * e, 16)
    fl = (flat != 0).any(-1)
    pos = jnp.cumsum(fl) - 1
    dest = jnp.where(fl, pos, m * e)  # sentinel row, dropped below
    buf = jnp.zeros((m * e + 1, 16), jnp.int16).at[dest].set(flat)[: m * e]
    return fl.reshape(m, e), buf, fl.sum().astype(jnp.int32)


def _bitmap_words(flags):
    """(M, E<=32) bool -> (M,) int32 per-MB bitmap."""
    e = flags.shape[1]
    return (flags.astype(jnp.int32) << jnp.arange(e, dtype=jnp.int32)).sum(-1)


def _bitpack32(bits):
    """(M,) bool -> (ceil(M/32),) int32."""
    m = bits.shape[0]
    pad = (-m) % 32
    b = jnp.pad(bits.astype(jnp.int32), (0, pad)).reshape(-1, 32)
    return (b << jnp.arange(32, dtype=jnp.int32)).sum(-1)


def _p_components(out):
    mbh, mbw = out["mvs"].shape[:2]
    m = mbh * mbw
    luma = out["luma_ac"].reshape(m, 16, 16).astype(jnp.int16)
    chroma = out["chroma_ac"].reshape(m, 8, 16).astype(jnp.int16)
    dc = out["chroma_dc"].reshape(m, 2, 4).astype(jnp.int16)
    dc_rows = jnp.pad(dc, ((0, 0), (0, 0), (0, 12)))
    rows = jnp.concatenate([luma, chroma, dc_rows], axis=1)  # (M, 26, 16)
    flags, buf, n = _compact_rows(rows)
    mv = out["mvs"]
    mv_words = (mv[..., 0] & 0xFFFF) | (mv[..., 1] << 16)
    return n, mbh, mbw, mv_words.reshape(-1).astype(jnp.int32), _bitmap_words(flags), buf


def pack_p_compact(out):
    """P-frame outputs -> (header int32, data int16 (M*26, 16)).

    Header layout: [n, mbh, mbw, 0] ++ mv_words(M) ++ mbinfo(M) ++
    skip_words(ceil(M/32)); mv_words = (mvx & 0xFFFF) | (mvy << 16)."""
    n, mbh, mbw, mv_words, mbinfo, buf = _p_components(out)
    header = jnp.concatenate([
        jnp.stack([n, jnp.int32(mbh), jnp.int32(mbw), jnp.int32(0)]),
        mv_words,
        mbinfo,
        _bitpack32(out["skip"].reshape(-1)),
    ])
    return header, buf


def pack_p_sparse_var(out, nscap: int, cap_rows: int):
    """Skip-aware variable-density P downlink (the delta-upload path):
    ONE int16 buffer whose live content is proportional to frame
    activity, not to the caps.

    Most desktop P frames are almost-all-skip, so only the first `nscap`
    NON-skip MBs carry their mv/mbinfo words (the host reconstructs
    positions from the dense skip bitmap). A fixed-layout prefix would
    still fetch nscap pairs + cap_rows coefficient rows — 165 KB at
    1080p even for a 2-band cursor blink, and the relay prices d2h at
    ~0.4 ms/KB (tools/profile_bench_loop.py: the group fetch WAS the
    steady-state bottleneck). Here the host fetches only a slice sized by
    recent history (encoder._pfx_hint):

      [meta: n, mbh, mbw, ns (4 int32)] ++ skip_words(ceil(M/32) int32)
      ++ (mv, info) int32 pairs for the first ns non-skip MBs
      ++ coefficient rows (n x 16 int16)  -- at dynamic offset 4*ns

    so live content = 8 + 2*ceil(M/32)*2 + 4*ns + 16*n int16 words. The
    pair region is written at its nscap-sized static offset first, then
    the rows overwrite its dead tail via a dynamic slice — content stays
    contiguous without a device-side size branch. Returns
    (fused int16 (p_sparse_var_words(...),), dense_header, buf); dense
    header is the ns > nscap fallback, buf the n > cap_rows spill."""
    n, mbh, mbw, mv_words, mbinfo, buf = _p_components(out)
    m = mbh * mbw
    mask = ~out["skip"].reshape(-1)
    ns = mask.sum().astype(jnp.int32)
    pos = jnp.cumsum(mask) - 1
    dest = jnp.where(mask & (pos < nscap), pos, nscap)  # sentinel dropped
    mv_c = jnp.zeros((nscap + 1,), jnp.int32).at[dest].set(mv_words)[:nscap]
    info_c = jnp.zeros((nscap + 1,), jnp.int32).at[dest].set(mbinfo)[:nscap]
    skip_words = _bitpack32(out["skip"].reshape(-1))
    sw = skip_words.shape[0]
    pairs16 = jax.lax.bitcast_convert_type(
        jnp.stack([mv_c, info_c], -1).reshape(-1), jnp.int16
    ).reshape(-1)  # (4*nscap,)
    head16 = jax.lax.bitcast_convert_type(
        jnp.concatenate([jnp.stack([n, jnp.int32(mbh), jnp.int32(mbw), ns]), skip_words]),
        jnp.int16,
    ).reshape(-1)  # (8 + 2*sw,)
    base = 8 + 2 * sw
    total16 = base + 4 * nscap + 16 * cap_rows
    fused = jnp.zeros((total16,), jnp.int16)
    fused = jax.lax.dynamic_update_slice(fused, head16, (0,))
    fused = jax.lax.dynamic_update_slice(fused, pairs16, (base,))
    rows16 = buf[:cap_rows].reshape(-1)  # (16*cap_rows,) zero past n
    fused = jax.lax.dynamic_update_slice(
        fused, rows16, (base + 4 * jnp.clip(ns, 0, nscap),)
    )
    dense = jnp.concatenate([
        jnp.stack([n, jnp.int32(mbh), jnp.int32(mbw), jnp.int32(0)]),
        mv_words,
        mbinfo,
        skip_words,
    ])
    return fused, dense, buf


def pack_p_sparse_packed(out, nscap: int, cap_rows: int, density_pct: int = 75):
    """Bit-packed variant of pack_p_sparse_var: coefficient rows ride as
    a significance bitmap + their nonzero values only.

    A typical desktop-residual 4x4 block has 1-4 nonzero coefficients,
    so shipping all 16 int16 lanes (32 B/row) wastes 3-6x of the
    dominant d2h term (PERF.md: group prefix fetch ~12-19 ms/frame on
    the relay). Per nonzero row the packed stream carries:

      * one int16 significance bitmap (bit j = scan-order lane j != 0);
      * the nonzero values, compacted to the front and padded to groups
        of FOUR int16 — one int64 lane per group, so the stream stays
        8-byte aligned and the host can bulk-view it.

    Layout (int16 words):
      [meta: n, mbh, mbw, ns, nw, dense_flag (6 int32 = 12)]
      ++ skip_words(ceil(M/32) int32) ++ (mv, info) pairs for the first
      ns non-skip MBs  -- as in pack_p_sparse_var --
      ++ at dynamic offset base + 4*min(ns, nscap):
           dense_flag=0: bitmaps (held int16) ++ values (nw int16)
           dense_flag=1: rows (16 * held int16, the var layout)

    `nw` = total packed value words (4 * sum of per-row groups). The
    DENSE FALLBACK triggers when the packed stream would exceed
    `density_pct`% of the dense rows — busy frames approach 16 nonzeros
    per row, where bitmap + padding overhead inverts the win and the
    host-side re-expansion is pure loss. Both layouts reconstruct the
    exact same PFrameCoeffs (compact.unpack_p_sparse_packed), so
    bitstreams are byte-identical either way. Returns (fused, dense
    header, buf) with the same fallback contract as pack_p_sparse_var."""
    n, mbh, mbw, mv_words, mbinfo, buf = _p_components(out)
    mask = ~out["skip"].reshape(-1)
    ns = mask.sum().astype(jnp.int32)
    pos = jnp.cumsum(mask) - 1
    dest = jnp.where(mask & (pos < nscap), pos, nscap)
    mv_c = jnp.zeros((nscap + 1,), jnp.int32).at[dest].set(mv_words)[:nscap]
    info_c = jnp.zeros((nscap + 1,), jnp.int32).at[dest].set(mbinfo)[:nscap]
    skip_words = _bitpack32(out["skip"].reshape(-1))
    sw = skip_words.shape[0]
    pairs16 = jax.lax.bitcast_convert_type(
        jnp.stack([mv_c, info_c], -1).reshape(-1), jnp.int16
    ).reshape(-1)

    rows = buf[:cap_rows]  # (cap, 16) int16; zero past row n
    sig = rows != 0
    bitmap16 = (sig.astype(jnp.int32) << jnp.arange(16, dtype=jnp.int32)).sum(-1).astype(jnp.int16)
    counts = sig.sum(-1).astype(jnp.int32)  # per-row nonzeros (>=1 while live)
    width = 4 * ((counts + 3) // 4)  # int16 slots incl group padding
    off = jnp.cumsum(width) - width  # exclusive prefix
    nw = width.sum().astype(jnp.int32)
    lane = jnp.cumsum(sig, axis=-1) - 1  # within-row rank of each nonzero
    vdest = jnp.where(sig, off[:, None] + lane, 16 * cap_rows)  # sentinel dropped
    vals16 = (
        jnp.zeros((16 * cap_rows + 1,), jnp.int16)
        .at[vdest.reshape(-1)]
        .set(rows.reshape(-1))[: 16 * cap_rows]
    )

    held = jnp.minimum(n, cap_rows)
    # fallback when the packed stream stops paying (bitmaps + padding vs
    # the 16-lane rows it replaces)
    dense_flag = (held + nw) * 100 > (16 * held) * density_pct
    meta = jnp.stack([n, jnp.int32(mbh), jnp.int32(mbw), ns, nw,
                      dense_flag.astype(jnp.int32)])
    head16 = jax.lax.bitcast_convert_type(
        jnp.concatenate([meta, skip_words]), jnp.int16
    ).reshape(-1)  # (12 + 2*sw,)
    base = 12 + 2 * sw
    total16 = base + 4 * nscap + cap_rows + 16 * cap_rows
    fused = jnp.zeros((total16,), jnp.int16)
    fused = jax.lax.dynamic_update_slice(fused, head16, (0,))
    fused = jax.lax.dynamic_update_slice(fused, pairs16, (base,))
    rows_off = base + 4 * jnp.clip(ns, 0, nscap)
    rows16 = rows.reshape(-1)

    def write_dense(f):
        return jax.lax.dynamic_update_slice(f, rows16, (rows_off,))

    def write_packed(f):
        # the values overwrite the bitmap array's dead tail (rows past
        # `held` have empty bitmaps), keeping the live content contiguous
        f = jax.lax.dynamic_update_slice(f, bitmap16, (rows_off,))
        return jax.lax.dynamic_update_slice(f, vals16, (rows_off + held,))

    fused = jax.lax.cond(dense_flag, write_dense, write_packed, fused)
    dense = jnp.concatenate([
        jnp.stack([n, jnp.int32(mbh), jnp.int32(mbw), jnp.int32(0)]),
        mv_words,
        mbinfo,
        skip_words,
    ])
    return fused, dense, buf


def pack_p_sparse_entropy(out, nscap: int, cap_rows: int,
                          density_pct: int | None, bits_words: int,
                          min_mbs: int, buckets: tuple[int, ...],
                          entropy_coder: str = "cavlc"):
    """Activity-proportional entropy downlink: busy frames ship their
    FINAL slice bits, quiet frames ship sparse coefficients — decided
    per frame ON DEVICE, inside the same jit (so it composes with the
    grouped lax.scan dispatch unchanged).

    Wraps the existing sparse layouts (pack_p_sparse_var /
    pack_p_sparse_packed — byte-for-byte the same payload, so the host
    parses it with the unchanged compact.py machinery) and the
    activity-compacted device CAVLC (device_cavlc.pack_p_slice_bits_
    active). The fused buffer gains an 8-int32 meta prefix:

      [mode, nbits, trailing_skip, nskip, ns, 0, 0, 0]   (16 int16)
      ++ mode=0: the untouched sparse layout (coeff rows)
         mode=1: the slice-data bit words (uint32, bit-cast)

    mode=1 is chosen when the frame is busy enough to pay
    (ns >= min_mbs), codeable (ns <= buckets[-1]) and the bits fit the
    `bits_words` payload cap — otherwise the coefficient path runs
    exactly as before (the word-cap overflow fallback). The sparse pack
    is cheap scatters and the bits pack is activity-proportional, so
    running both costs a quiet frame almost nothing; the decision only
    selects which payload lands in the fused buffer. Returns
    (fused, dense_header, buf) with the same fallback contract as the
    wrapped sparse packers (dense/buf are coeff-mode-only fetches).
    host half: models/h264/sparse_complete.complete_sparse_slice
    (device_bits=True).

    With entropy_coder="cabac" the device half is the token binarizer
    (device_cabac.pack_p_slice_tokens_active) and mode=1 carries the
    16-bit token IR instead of final bits — the host still owns the
    sequential arithmetic engine. Payload layout after the meta prefix
    (meta2 = [1, ntok, 0, nskip, ns, 0, 0, 0]):

      skip bitmap (2*sw int16, the host interleaves per-MB skip flags)
      ++ per-coded-MB token counts (first ns of an A_max block, int16)
      ++ token words at offset 2*sw + ns (the dead counts tail is
         overwritten, keeping the live fetch contiguous — the same
         trick as pack_p_sparse_packed's bitmap/value split)."""
    if entropy_coder == "cabac":
        return _pack_p_sparse_cabac(out, nscap, cap_rows, density_pct,
                                    bits_words, min_mbs, buckets)
    from selkies_tpu.models.h264.device_cavlc import pack_p_slice_bits_active

    if density_pct is None:
        fused, dense, buf = pack_p_sparse_var(out, nscap, cap_rows)
    else:
        fused, dense, buf = pack_p_sparse_packed(out, nscap, cap_rows, density_pct)
    words, nbits, trailing, ns = pack_p_slice_bits_active(
        out, word_cap=bits_words, buckets=buckets)
    nskip = out["skip"].reshape(-1).sum().astype(jnp.int32)
    use_bits = (
        (ns >= jnp.int32(min_mbs))
        & (ns <= jnp.int32(buckets[-1]))
        & (nbits <= jnp.int32(32 * bits_words))
    )
    meta2 = jnp.stack([
        use_bits.astype(jnp.int32), nbits, trailing, nskip, ns,
        jnp.int32(0), jnp.int32(0), jnp.int32(0)])
    head16 = jax.lax.bitcast_convert_type(meta2, jnp.int16).reshape(-1)
    total16 = 16 + max(int(fused.shape[0]), 2 * bits_words)
    fused2 = jnp.zeros((total16,), jnp.int16)

    def wr_coeff(f):
        return jax.lax.dynamic_update_slice(f, fused, (16,))

    def wr_bits(f):
        w16 = jax.lax.bitcast_convert_type(words, jnp.int16).reshape(-1)
        return jax.lax.dynamic_update_slice(f, w16, (16,))

    fused2 = jax.lax.cond(use_bits, wr_bits, wr_coeff, fused2)
    fused2 = jax.lax.dynamic_update_slice(fused2, head16, (0,))
    return fused2, dense, buf


def _pack_p_sparse_cabac(out, nscap: int, cap_rows: int,
                         density_pct: int | None, bits_words: int,
                         min_mbs: int, buckets: tuple[int, ...]):
    """CABAC arm of pack_p_sparse_entropy (layout documented there)."""
    from selkies_tpu.models.h264.device_cabac import (
        pack_p_slice_tokens_active)

    if density_pct is None:
        fused, dense, buf = pack_p_sparse_var(out, nscap, cap_rows)
    else:
        fused, dense, buf = pack_p_sparse_packed(out, nscap, cap_rows, density_pct)
    words, ntok, counts, ns = pack_p_slice_tokens_active(
        out, word_cap=bits_words, buckets=buckets)
    skip_words = _bitpack32(out["skip"].reshape(-1))
    sw = skip_words.shape[0]
    nskip = out["skip"].reshape(-1).sum().astype(jnp.int32)
    A_max = buckets[-1]
    use_bits = (
        (ns >= jnp.int32(min_mbs))
        & (ns <= jnp.int32(A_max))
        & (ntok <= jnp.int32(2 * bits_words))
    )
    meta2 = jnp.stack([
        use_bits.astype(jnp.int32), ntok, jnp.int32(0), nskip, ns,
        jnp.int32(0), jnp.int32(0), jnp.int32(0)])
    head16 = jax.lax.bitcast_convert_type(meta2, jnp.int16).reshape(-1)
    base = 16 + 2 * sw
    total16 = 16 + max(int(fused.shape[0]),
                       2 * sw + A_max + 2 * bits_words)
    fused2 = jnp.zeros((total16,), jnp.int16)

    def wr_coeff(f):
        return jax.lax.dynamic_update_slice(f, fused, (16,))

    def wr_toks(f):
        sk16 = jax.lax.bitcast_convert_type(skip_words, jnp.int16).reshape(-1)
        f = jax.lax.dynamic_update_slice(f, sk16, (16,))
        f = jax.lax.dynamic_update_slice(
            f, counts.astype(jnp.int16), (base,))
        w16 = jax.lax.bitcast_convert_type(words, jnp.int16).reshape(-1)
        return jax.lax.dynamic_update_slice(
            f, w16, (base + jnp.clip(ns, 0, A_max),))

    fused2 = jax.lax.cond(use_bits, wr_toks, wr_coeff, fused2)
    fused2 = jax.lax.dynamic_update_slice(fused2, head16, (0,))
    return fused2, dense, buf


def fuse_downlink(header, buf, cap_rows: int):
    """Fuse header + the first cap_rows data rows into ONE int16 buffer.

    The host↔device relay prices transfers per OPERATION (~200 ms each,
    tools/profile_rpc.py), so the downlink must be a single fetch: the
    prefix buffer carries the int32 header bit-cast to int16 pairs
    followed by cap_rows nonzero rows. Frames whose row count exceeds
    cap_rows pay one extra fetch from the full buffer (rare; sized for
    typical P frames)."""
    hdr16 = jax.lax.bitcast_convert_type(header, jnp.int16).reshape(-1)
    prefix = jnp.concatenate([hdr16, buf[:cap_rows].reshape(-1)])
    return prefix


def pack_i_compact(out):
    """IDR outputs -> (header int32, data int16 (M*27, 16)).

    Header: [n, mbh, mbw, 0] ++ mbinfo(M) ++ mode_words(M)
    (mode_words = luma_mode | chroma_mode << 8). Per-MB rows: 1 luma DC +
    16 luma AC + 8 chroma AC + 2 chroma DC."""
    mbh, mbw = out["luma_mode"].shape[:2]
    m = mbh * mbw
    luma_dc = out["luma_dc"].reshape(m, 1, 16).astype(jnp.int16)
    luma = out["luma_ac"].reshape(m, 16, 16).astype(jnp.int16)
    chroma = out["chroma_ac"].reshape(m, 8, 16).astype(jnp.int16)
    dc = out["chroma_dc"].reshape(m, 2, 4).astype(jnp.int16)
    dc_rows = jnp.pad(dc, ((0, 0), (0, 0), (0, 12)))
    rows = jnp.concatenate([luma_dc, luma, chroma, dc_rows], axis=1)  # (M, 27, 16)
    flags, buf, n = _compact_rows(rows)
    modes = out["luma_mode"].reshape(-1) | (out["chroma_mode"].reshape(-1) << 8)
    header = jnp.concatenate([
        jnp.stack([n, jnp.int32(mbh), jnp.int32(mbw), jnp.int32(0)]),
        _bitmap_words(flags),
        modes.astype(jnp.int32),
    ])
    return header, buf


# ---------------------------------------------------------------------------
# Delta upload: dirty-band scatter into device-resident source planes
# ---------------------------------------------------------------------------
def scatter_tiles(y, u, v, yb, ub, vb, idx, tile_w: int):
    """Scatter uploaded I420 TILES into device-resident planes.

    yb: (k, 16, tile_w) luma, ub/vb: (k, 8, tile_w/2) chroma, idx: (k,)
    int32 encoded band*1024 + tile (duplicates allowed — rewriting a
    tile is idempotent, which lets the host pad k to a static bucket).
    tile_w == plane width degenerates to full-width bands. Column tiling
    shrinks the host->device delta traffic by the width fraction that
    actually changed (a cursor blink is one tile, not a full-width band)."""
    ctw = tile_w // 2

    def body(i, planes):
        py, pu, pv = planes
        band = idx[i] // 1024
        tile = idx[i] % 1024
        py = jax.lax.dynamic_update_slice(py, yb[i], (band * 16, tile * tile_w))
        pu = jax.lax.dynamic_update_slice(pu, ub[i], (band * 8, tile * ctw))
        pv = jax.lax.dynamic_update_slice(pv, vb[i], (band * 8, tile * ctw))
        return py, pu, pv

    return jax.lax.fori_loop(0, yb.shape[0], body, (y, u, v))
