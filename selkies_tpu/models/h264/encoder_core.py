"""JAX encode core for tpuh264enc: the jit-compiled per-frame device step.

This is the TPU re-design of the encoder matrix's device work (the
reference delegates it to NVENC/VAAPI silicon, gstwebrtc_app.py:260-783):
intra prediction, forward/inverse 4x4 transforms, Hadamard DC paths, and
quantization — everything except bit-serial entropy coding, which stays on
the host (cavlc.py / native/cavlc_pack.cc).

Parallelisation strategy (the reason the prediction-mode policy exists):
  * rows 1..N use Intra16x16 VERTICAL prediction — each MB depends only on
    the reconstructed row above, so one `lax.scan` step processes an
    entire MB row as a single batched tensor op (120 MBs at 1080p).
  * row 0 uses DC prediction (left-only chain) — a short scan over
    columns, paid once per IDR frame.

TPU mapping: the 4x4 DCT/Hadamard transforms are expressed as explicit
add/shift butterflies over batched int32 tensors — pure VPU element-wise
work that XLA fuses with the quantizer (no integer-matmul lowering, no
float roundoff). All arithmetic is int32: the widest intermediate
(|coeff|·MF + f at QP 0) stays under 2^27. QP is a traced scalar, so
rate-control retunes never recompile.

Bit-exactness contract: every op mirrors numpy_ref.py exactly
(tests/test_encoder_core.py asserts array equality), which in turn is
FFmpeg-conformant (tools/cavlc_probe.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from selkies_tpu.models.h264 import tables

_POS_CLASS = np.array(
    [[0 if (i % 2 == 0 and j % 2 == 0) else 1 if (i % 2 and j % 2) else 2 for j in range(4)] for i in range(4)],
    np.int32,
)
_MF_BY_REM = jnp.asarray(np.asarray(tables.QUANT_MF, np.int32)[:, _POS_CLASS])  # (6, 4, 4)
_V_BY_REM = jnp.asarray(np.asarray(tables.DEQUANT_V, np.int32)[:, _POS_CLASS])  # (6, 4, 4)
_CHROMA_QP = jnp.asarray([tables.chroma_qp(q) for q in range(52)], jnp.int32)


def _last(x, i):
    return x[..., i]


def _fdct1d(x):
    """1-D forward core transform along the last axis of (..., 4)."""
    x0, x1, x2, x3 = _last(x, 0), _last(x, 1), _last(x, 2), _last(x, 3)
    s0, s1 = x0 + x3, x1 + x2
    d0, d1 = x0 - x3, x1 - x2
    return jnp.stack([s0 + s1, 2 * d0 + d1, s0 - s1, d0 - 2 * d1], axis=-1)


def fdct4(blocks):
    """Forward 4x4 core transform over (..., 4, 4) int32 blocks (exact)."""
    b = blocks.astype(jnp.int32)
    b = _fdct1d(b)  # transform columns index (last axis = j)
    b = _fdct1d(b.swapaxes(-1, -2)).swapaxes(-1, -2)  # transform rows
    return b


def _idct1d(x):
    """1-D inverse butterfly along the last axis (8.5.12.2 step)."""
    x0, x1, x2, x3 = _last(x, 0), _last(x, 1), _last(x, 2), _last(x, 3)
    e0, e1 = x0 + x2, x0 - x2
    e2 = jnp.right_shift(x1, 1) - x3
    e3 = x1 + jnp.right_shift(x3, 1)
    return jnp.stack([e0 + e3, e1 + e2, e1 - e2, e0 - e3], axis=-1)


def idct4(coeffs):
    """Bit-exact inverse 4x4 transform: horizontal first, then vertical."""
    d = coeffs.astype(jnp.int32)
    d = _idct1d(d)  # horizontal: mix columns within each row
    d = _idct1d(d.swapaxes(-1, -2)).swapaxes(-1, -2)  # vertical
    return jnp.right_shift(d + 32, 6)


def _had1d(x):
    x0, x1, x2, x3 = _last(x, 0), _last(x, 1), _last(x, 2), _last(x, 3)
    s0, s1 = x0 + x1, x2 + x3
    d0, d1 = x0 - x1, x2 - x3
    return jnp.stack([s0 + s1, s0 - s1, d0 - d1, d0 + d1], axis=-1)


def _had4(x):
    """H4 · X · H4 for (..., 4, 4) (H4 symmetric)."""
    x = _had1d(x.astype(jnp.int32))
    return _had1d(x.swapaxes(-1, -2)).swapaxes(-1, -2)


def _had2(x):
    """H2 · X · H2 for (..., 2, 2)."""
    x = x.astype(jnp.int32)
    a = x[..., 0, 0] + x[..., 0, 1]
    b = x[..., 0, 0] - x[..., 0, 1]
    c = x[..., 1, 0] + x[..., 1, 1]
    d = x[..., 1, 0] - x[..., 1, 1]
    return jnp.stack(
        [jnp.stack([a + c, b + d], axis=-1), jnp.stack([a - c, b - d], axis=-1)], axis=-2
    )


def _qparams(qp, intra: bool = True):
    qbits = 15 + qp // 6
    f = jnp.left_shift(jnp.int32(1), qbits) // (3 if intra else 6)
    return qbits, f


def quant4(coeffs, qp, intra: bool = True):
    qbits, f = _qparams(qp, intra)
    mf = _MF_BY_REM[qp % 6]
    c = coeffs.astype(jnp.int32)
    level = jnp.right_shift(jnp.abs(c) * mf + f, qbits)
    return jnp.where(c < 0, -level, level)


def dequant4(levels, qp):
    return levels.astype(jnp.int32) * _V_BY_REM[qp % 6] * jnp.left_shift(jnp.int32(1), qp // 6)


def quant_luma_dc(dc, qp):
    t = jnp.right_shift(_had4(dc), 1)
    qbits, f = _qparams(qp, True)
    mf00 = _MF_BY_REM[qp % 6, 0, 0]
    level = jnp.right_shift(jnp.abs(t) * mf00 + 2 * f, qbits + 1)
    return jnp.where(t < 0, -level, level)


def dequant_luma_dc(levels, qp):
    f = _had4(levels)
    v00 = _V_BY_REM[qp % 6, 0, 0]
    qp_per = qp // 6
    hi = jnp.left_shift(f * v00, jnp.maximum(qp_per - 2, 0))
    lo = jnp.right_shift(
        f * v00 + jnp.left_shift(jnp.int32(1), jnp.maximum(1 - qp_per, 0)),
        jnp.maximum(2 - qp_per, 0),
    )
    return jnp.where(qp_per >= 2, hi, lo)


def quant_chroma_dc(dc, qp_c):
    t = _had2(dc)
    qbits, f = _qparams(qp_c, True)
    mf00 = _MF_BY_REM[qp_c % 6, 0, 0]
    level = jnp.right_shift(jnp.abs(t) * mf00 + 2 * f, qbits + 1)
    return jnp.where(t < 0, -level, level)


def dequant_chroma_dc(levels, qp_c):
    f = _had2(levels)
    v00 = _V_BY_REM[qp_c % 6, 0, 0]
    return jnp.right_shift(jnp.left_shift(f * v00, qp_c // 6), 1)


def _row_to_blocks(row, n: int):
    """(n*4, W) plane row -> (mbw, n, n, 4, 4) indexed [mb][by][bx][i][j]."""
    h, w = row.shape
    mbw = w // (n * 4)
    return row.reshape(n, 4, mbw, n, 4).transpose(2, 0, 3, 1, 4)


def _blocks_to_row(blocks):
    """Inverse of _row_to_blocks: (mbw, n, n, 4, 4) -> (n*4, mbw*n*4)."""
    mbw, n = blocks.shape[0], blocks.shape[1]
    return blocks.transpose(1, 3, 0, 2, 4).reshape(n * 4, mbw * n * 4)


def _encode_plane_row(row, pred, qp, n: int, luma: bool):
    """Batched encode of one MB row of a plane.

    row, pred: (n*4, W) int32. Returns (dc (mbw,n,n), ac (mbw,n,n,4,4),
    recon (n*4, W))."""
    blocks = _row_to_blocks(row - pred, n)
    w = fdct4(blocks)
    dc = w[..., 0, 0]
    if luma:
        dc_levels = quant_luma_dc(dc, qp)
        dc_deq = dequant_luma_dc(dc_levels, qp)
    else:
        dc_levels = quant_chroma_dc(dc, qp)
        dc_deq = dequant_chroma_dc(dc_levels, qp)
    ac_levels = quant4(w, qp, intra=True)
    deq = dequant4(ac_levels, qp)
    deq = deq.at[..., 0, 0].set(dc_deq)
    recon = jnp.clip(_blocks_to_row(idct4(deq)) + pred, 0, 255)
    return dc_levels, ac_levels, recon


def _dc_pred_luma_jnp(left_col, has_left):
    dc = jnp.where(has_left, jnp.right_shift(left_col.sum() + 8, 4), 128)
    return jnp.broadcast_to(dc, (16, 16))


def _dc_pred_chroma_jnp(left_col, has_left):
    """Chroma DC prediction with top unavailable (8.3.4.1): the two block
    rows use the matching 4-sample left segments; no left -> 128."""
    top = jnp.where(has_left, jnp.right_shift(left_col[:4].sum() + 2, 2), 128)
    bot = jnp.where(has_left, jnp.right_shift(left_col[4:].sum() + 2, 2), 128)
    rows = jnp.concatenate([jnp.broadcast_to(top, (4,)), jnp.broadcast_to(bot, (4,))])
    return jnp.broadcast_to(rows[:, None], (8, 8))


def _encode_row0(y_row, u_row, v_row, qp, qp_c):
    """Row 0: DC prediction, serial scan over MB columns."""
    mbw = y_row.shape[1] // 16
    y_mbs = y_row.reshape(16, mbw, 16).transpose(1, 0, 2)
    u_mbs = u_row.reshape(8, mbw, 8).transpose(1, 0, 2)
    v_mbs = v_row.reshape(8, mbw, 8).transpose(1, 0, 2)

    def step(carry, xs):
        yl, ul, vl, has_left = carry
        y_mb, u_mb, v_mb = xs
        dc_y, ac_y, rec_y = _encode_plane_row(y_mb, _dc_pred_luma_jnp(yl, has_left), qp, 4, True)
        dc_u, ac_u, rec_u = _encode_plane_row(u_mb, _dc_pred_chroma_jnp(ul, has_left), qp_c, 2, False)
        dc_v, ac_v, rec_v = _encode_plane_row(v_mb, _dc_pred_chroma_jnp(vl, has_left), qp_c, 2, False)
        carry = (rec_y[:, -1], rec_u[:, -1], rec_v[:, -1], jnp.bool_(True))
        return carry, (dc_y[0], ac_y[0], dc_u[0], ac_u[0], dc_v[0], ac_v[0], rec_y, rec_u, rec_v)

    init = (
        jnp.zeros(16, jnp.int32),
        jnp.zeros(8, jnp.int32),
        jnp.zeros(8, jnp.int32),
        jnp.bool_(False),
    )
    _, outs = jax.lax.scan(step, init, (y_mbs, u_mbs, v_mbs))
    dc_y, ac_y, dc_u, ac_u, dc_v, ac_v, rec_y, rec_u, rec_v = outs
    rec_y = rec_y.transpose(1, 0, 2).reshape(16, mbw * 16)
    rec_u = rec_u.transpose(1, 0, 2).reshape(8, mbw * 8)
    rec_v = rec_v.transpose(1, 0, 2).reshape(8, mbw * 8)
    return dc_y, ac_y, dc_u, ac_u, dc_v, ac_v, rec_y, rec_u, rec_v


@jax.jit
def encode_frame_planes(y, u, v, qp):
    """Jitted all-Intra16x16 frame encode on padded planes.

    y: (H, W) uint8/int32, u/v: (H/2, W/2). qp: int32 scalar (traced — no
    recompile on rate-control changes). Returns a dict of FrameCoeffs-layout
    arrays plus recon planes (recon also feeds future P-frame prediction).
    """
    y = y.astype(jnp.int32)
    u = u.astype(jnp.int32)
    v = v.astype(jnp.int32)
    qp = jnp.asarray(qp, jnp.int32)
    qp_c = _CHROMA_QP[qp]
    h, w_ = y.shape
    mbh = h // 16

    r0 = _encode_row0(y[:16], u[:8], v[:8], qp, qp_c)
    dc_y0, ac_y0, dc_u0, ac_u0, dc_v0, ac_v0, rec_y0, rec_u0, rec_v0 = r0

    if mbh > 1:
        nrows = mbh - 1
        y_rows = y[16:].reshape(nrows, 16, w_)
        u_rows = u[8:].reshape(nrows, 8, w_ // 2)
        v_rows = v[8:].reshape(nrows, 8, w_ // 2)

        def step(carry, xs):
            yb, ub, vb = carry
            y_row, u_row, v_row = xs
            dc_y, ac_y, rec_y = _encode_plane_row(
                y_row, jnp.broadcast_to(yb, (16, yb.shape[0])), qp, 4, True
            )
            dc_u, ac_u, rec_u = _encode_plane_row(
                u_row, jnp.broadcast_to(ub, (8, ub.shape[0])), qp_c, 2, False
            )
            dc_v, ac_v, rec_v = _encode_plane_row(
                v_row, jnp.broadcast_to(vb, (8, vb.shape[0])), qp_c, 2, False
            )
            return (rec_y[-1], rec_u[-1], rec_v[-1]), (dc_y, ac_y, dc_u, ac_u, dc_v, ac_v, rec_y, rec_u, rec_v)

        init = (rec_y0[-1], rec_u0[-1], rec_v0[-1])
        _, outs = jax.lax.scan(step, init, (y_rows, u_rows, v_rows))
        dc_yr, ac_yr, dc_ur, ac_ur, dc_vr, ac_vr, rec_yr, rec_ur, rec_vr = outs
        luma_dc = jnp.concatenate([dc_y0[None], dc_yr])
        luma_ac = jnp.concatenate([ac_y0[None], ac_yr])
        cb_dc = jnp.concatenate([dc_u0[None], dc_ur])
        cb_ac = jnp.concatenate([ac_u0[None], ac_ur])
        cr_dc = jnp.concatenate([dc_v0[None], dc_vr])
        cr_ac = jnp.concatenate([ac_v0[None], ac_vr])
        recon_y = jnp.concatenate([rec_y0[None], rec_yr]).reshape(mbh * 16, w_)
        recon_u = jnp.concatenate([rec_u0[None], rec_ur]).reshape(mbh * 8, w_ // 2)
        recon_v = jnp.concatenate([rec_v0[None], rec_vr]).reshape(mbh * 8, w_ // 2)
    else:
        luma_dc, luma_ac = dc_y0[None], ac_y0[None]
        cb_dc, cb_ac = dc_u0[None], ac_u0[None]
        cr_dc, cr_ac = dc_v0[None], ac_v0[None]
        recon_y, recon_u, recon_v = rec_y0, rec_u0, rec_v0

    mbw = luma_dc.shape[1]
    row0 = (jnp.arange(mbh) == 0)[:, None] & jnp.ones((1, mbw), bool)
    return {
        "luma_mode": jnp.where(row0, 2, 0).astype(jnp.int32),  # DC / vertical
        "chroma_mode": jnp.where(row0, 0, 2).astype(jnp.int32),  # DC / vertical
        "luma_dc": luma_dc,
        "luma_ac": luma_ac,
        "chroma_dc": jnp.stack([cb_dc, cr_dc], axis=2),
        "chroma_ac": jnp.stack([cb_ac, cr_ac], axis=2),
        "recon_y": recon_y.astype(jnp.uint8),
        "recon_u": recon_u.astype(jnp.uint8),
        "recon_v": recon_v.astype(jnp.uint8),
    }
