"""ctypes wrapper for libaom: the real `av1enc` software encoder row.

The reference's av1enc GStreamer element (gstwebrtc_app.py:741-783) IS
libaom behind GObject properties — wrapping the same library gives the
encoder matrix a REAL AV1 row (round 3 shipped an H.264 fallback on the
false claim that no AV1 library existed in this image; libaom.so.3 is
right there). Tuning mirrors the reference's realtime row: usage=
realtime, CBR, zero lag, cpu-used 10, threads, keyframes only on demand.

ABI notes: built against libaom.so.3 (v3.6.0, Debian). libaom inherited
libvpx's encoder API shape, so the wrapper follows models/libvpx_enc.py:
aom_codec_enc_cfg offsets (uint32 words) were probed empirically against
aom_codec_enc_config_default's known realtime defaults (g_usage=1,
g_w=320, g_h=240, timebase 1/30, rc_end_usage=CBR, rc_target_bitrate=
256, kf_mode=AUTO, kf_max_dist=9999) and are re-verified at load time —
a mismatched build disables the row instead of corrupting memory. The
encoder ABI version (25) is probed by aom_codec_enc_init_ver returning
ABI_MISMATCH for wrong values.

Footgun note (verified by bisection on v3.6.0): kf_mode=AOM_KF_DISABLED
segfaults libaom's realtime path on content that trips its scene-change
detector. Infinite-GOP semantics (keyframe_distance=-1) are therefore
expressed as AOM_KF_AUTO with kf_max_dist=2^30 + AOM_EFLAG_FORCE_KF on
demand, which is behaviourally identical and stays on the tested path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct as _struct
import time

import numpy as np

from selkies_tpu.models.libvpx_enc import _bgrx_to_i420_np
from selkies_tpu.models.stats import FrameStats

logger = logging.getLogger("models.libaom")

# aom_codec_enc_cfg word offsets (uint32 units), probed + verified in _load
_OFF_G_USAGE = 0
_OFF_G_THREADS = 1
_OFF_G_W = 3
_OFF_G_H = 4
_OFF_TB_NUM = 10
_OFF_TB_DEN = 11
_OFF_ERROR_RESILIENT = 12
_OFF_LAG_IN_FRAMES = 14
_OFF_RC_DROPFRAME = 15
_OFF_RC_END_USAGE = 24
_OFF_TARGET_BITRATE = 34
_OFF_MIN_Q = 35
_OFF_MAX_Q = 36
_OFF_UNDERSHOOT = 37
_OFF_OVERSHOOT = 38
_OFF_BUF_SZ = 39
_OFF_BUF_INITIAL = 40
_OFF_BUF_OPTIMAL = 41
_OFF_KF_MODE = 46
_OFF_KF_MIN_DIST = 47
_OFF_KF_MAX_DIST = 48

_AOM_USAGE_REALTIME = 1
_AOM_CBR = 1
_AOM_KF_AUTO = 1
_KF_NEVER = 1 << 30  # kf_max_dist "infinite GOP" (see footgun note)
_AOM_IMG_FMT_I420 = 0x102
_AOM_EFLAG_FORCE_KF = 1
_AOM_FRAME_IS_KEY = 1
_AOME_SET_ACTIVEMAP = 9
_AOME_SET_CPUUSED = 13
_ENCODER_ABI_VERSION = 25  # probed; init returns ABI_MISMATCH(3) otherwise
_ABI_MISMATCH = 3
_CFG_BYTES = 8192
_CTX_BYTES = 4096  # aom_codec_ctx_t is far smaller; headroom is deliberate

# aom_image_t byte offsets (probed + verified in _load):
#   fmt u32 @0, w/h @28/32, d_w/d_h @40/44, planes[3] @64, stride[3] @88
_IMG_FMT_OFF = 0
_IMG_DW_OFF = 40
_IMG_DH_OFF = 44
_IMG_PLANES_OFF = 64
_IMG_STRIDE_OFF = 88

# aom_codec_cx_pkt_t byte offsets: kind @0, frame.buf @8, frame.sz @16,
# frame.pts @24, frame.duration @32 (unsigned long), frame.flags @40
_PKT_KIND_OFF = 0
_PKT_BUF_OFF = 8
_PKT_SZ_OFF = 16
_PKT_FLAGS_OFF = 40
_PKT_READ = 48


class _AomActiveMap(ctypes.Structure):
    # aom_active_map_t (aom/aom_encoder.h): per-16x16-block activity mask;
    # inactive blocks are forced to skip-from-reference (same contract as
    # vpx_active_map_t — libaom kept the struct)
    _fields_ = [
        ("active_map", ctypes.POINTER(ctypes.c_uint8)),
        ("rows", ctypes.c_uint),
        ("cols", ctypes.c_uint),
    ]


_lib = None
_lib_tried = False

# --- legacy libaom 1.0.x support (strip encoders only) ---------------------
# Some deployment images carry libaom.so.0 (AV1 1.0.0) instead of the 3.x
# the realtime row above is probed for.  1.0 has no string-option API and
# no realtime usage, but the cfg struct fields this module pokes sit at
# THE SAME word offsets (verified against config_default ground truth
# below), the encoder ABI is 12, and the control enum was recovered by an
# error-detail fingerprint scan (each range-checked control names its
# field, the same technique libvpx_enc._row_mt_available uses):
#   13 "cpu_used out of range [0..8]"      32 "lossless expected boolean"
#   33 "tile_columns out of range [..6]"   34 "tile_rows out of range [..6]"
#   54 "superblock_size out of range [...]"
# The fingerprints are re-verified at load time, so a shifted enum in some
# other v1.x build disables the legacy path instead of corrupting state.
# Only AomStripEncoder (lossless tile-column strips, parallel/codec_mesh)
# uses this path; the realtime CBR row still requires 3.x.
_LEGACY_ABI = 12
_LEGACY_IMG_STRIDE_OFF = 96  # aom 1.0 aom_image_t: planes @64, stride @96
_LEGACY_CTRL = {
    "cpu_used": 13,
    "lossless": 32,
    "tile_columns": 33,
    "tile_rows": 34,
    "superblock_size": 54,
}
_LEGACY_FINGERPRINT = {
    13: b"cpu_used",
    32: b"lossless",
    33: b"tile_columns",
    34: b"tile_rows",
    54: b"superblock_size",
}

_legacy = None
_legacy_tried = False


def _load_legacy():
    """Load and validate the aom 1.0 ABI for strip encoding."""
    global _legacy, _legacy_tried
    if _legacy_tried:
        return _legacy
    _legacy_tried = True
    for name in ("libaom.so.0", "libaom.so.1", "libaom.so.2"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        return None
    if getattr(lib, "aom_codec_set_option", None):
        # a modern library under an old soname: not the 1.0 ABI
        return None
    lib.aom_codec_av1_cx.restype = ctypes.c_void_p
    lib.aom_img_alloc.restype = ctypes.c_void_p
    lib.aom_codec_get_cx_data.restype = ctypes.c_void_p
    lib.aom_codec_error_detail.restype = ctypes.c_char_p
    lib.aom_codec_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_ulong, ctypes.c_long,
    ]
    iface = lib.aom_codec_av1_cx()
    cfg = (ctypes.c_uint8 * _CFG_BYTES)()
    if lib.aom_codec_enc_config_default(ctypes.c_void_p(iface), cfg, 0):
        return None
    w = ctypes.cast(cfg, ctypes.POINTER(ctypes.c_uint32))
    # good-quality (usage 0) ground truth for the shared word offsets
    ok = (
        w[_OFF_G_W] == 320 and w[_OFF_G_H] == 240
        and w[_OFF_TB_NUM] == 1 and w[_OFF_TB_DEN] == 30
        and w[_OFF_TARGET_BITRATE] == 256
        and w[_OFF_MAX_Q] == 63
        and w[_OFF_KF_MODE] == 1 and w[_OFF_KF_MAX_DIST] == 9999
    )
    if not ok:
        logger.info("legacy libaom cfg layout mismatch; strip path disabled")
        return None
    ctx = (ctypes.c_uint8 * _CTX_BYTES)()
    if lib.aom_codec_enc_init_ver(ctx, ctypes.c_void_p(iface), cfg, 0, _LEGACY_ABI):
        logger.info("legacy libaom ABI %d rejected; strip path disabled", _LEGACY_ABI)
        return None
    try:
        for cid, name in _LEGACY_FINGERPRINT.items():
            rc = lib.aom_codec_control_(ctx, cid, ctypes.c_int(999999))
            det = lib.aom_codec_error_detail(ctx) or b""
            if rc == 0 or name not in det:
                logger.info("legacy libaom control %d fingerprint mismatch "
                            "(%r); strip path disabled", cid, det)
                return None
    finally:
        lib.aom_codec_destroy(ctx)
    img = lib.aom_img_alloc(None, _AOM_IMG_FMT_I420, 320, 240, 16)
    if not img:
        return None
    raw = ctypes.string_at(img, _LEGACY_IMG_STRIDE_OFF + 12)
    planes = _struct.unpack_from("<3Q", raw, _IMG_PLANES_OFF)
    strides = _struct.unpack_from("<3i", raw, _LEGACY_IMG_STRIDE_OFF)
    lib.aom_img_free(ctypes.c_void_p(img))
    if not (all(planes) and strides[0] >= 320 and strides[1] >= 160
            and strides[1] == strides[2]):
        logger.info("legacy libaom image layout mismatch; strip path disabled")
        return None
    _legacy = lib
    return _legacy


def _load_and_verify():
    """Load libaom and verify every struct offset this wrapper pokes."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    for name in ("libaom.so.3", "libaom.so", "aom"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        logger.info("libaom not found; av1enc row unavailable")
        return None
    lib.aom_codec_av1_cx.restype = ctypes.c_void_p
    lib.aom_img_alloc.restype = ctypes.c_void_p
    lib.aom_codec_get_cx_data.restype = ctypes.c_void_p
    lib.aom_codec_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_ulong, ctypes.c_long,
    ]
    # string-keyed option API (aom >= 3.0): lets us set row-mt/tiles
    # without guessing control-enum values across library builds
    try:
        lib.aom_codec_set_option.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p]
        lib.aom_codec_set_option.restype = ctypes.c_int
    except AttributeError:
        lib.aom_codec_set_option = None

    # --- offset verification against config_default ground truth ------
    iface = lib.aom_codec_av1_cx()
    cfg = (ctypes.c_uint8 * _CFG_BYTES)()
    if lib.aom_codec_enc_config_default(ctypes.c_void_p(iface), cfg, _AOM_USAGE_REALTIME):
        logger.warning("aom_codec_enc_config_default failed; av1enc row disabled")
        return None
    w = ctypes.cast(cfg, ctypes.POINTER(ctypes.c_uint32))
    ok = (
        w[_OFF_G_USAGE] == _AOM_USAGE_REALTIME
        and w[_OFF_G_W] == 320 and w[_OFF_G_H] == 240
        and w[_OFF_TB_NUM] == 1 and w[_OFF_TB_DEN] == 30
        and w[_OFF_LAG_IN_FRAMES] == 0          # realtime default
        and w[_OFF_RC_END_USAGE] == _AOM_CBR    # realtime default
        and w[_OFF_TARGET_BITRATE] == 256
        and w[_OFF_MAX_Q] == 63
        and w[_OFF_KF_MODE] == _AOM_KF_AUTO
        and w[_OFF_KF_MAX_DIST] == 9999
    )
    if ok:
        # verify the encoder ABI version and the aom_image_t layout with
        # a real allocation instead of trusting the header transcription
        ctx = (ctypes.c_uint8 * _CTX_BYTES)()
        err = lib.aom_codec_enc_init_ver(
            ctx, ctypes.c_void_p(iface), cfg, 0, _ENCODER_ABI_VERSION)
        if err == 0:
            lib.aom_codec_destroy(ctx)
        else:
            # ABI_MISMATCH(3) or any other init failure: the row must
            # degrade (registry falls back to tpuh264enc), not crash the
            # orchestrator later in LibAomEncoder.__init__
            logger.warning("aom_codec_enc_init_ver failed (%d); av1enc row "
                           "disabled", err)
            ok = False
        img = lib.aom_img_alloc(None, _AOM_IMG_FMT_I420, 320, 240, 16) if ok else None
        if ok and img:
            raw = ctypes.string_at(img, _IMG_STRIDE_OFF + 12)
            fmt = _struct.unpack_from("<I", raw, _IMG_FMT_OFF)[0]
            dw = _struct.unpack_from("<I", raw, _IMG_DW_OFF)[0]
            dh = _struct.unpack_from("<I", raw, _IMG_DH_OFF)[0]
            planes = _struct.unpack_from("<3Q", raw, _IMG_PLANES_OFF)
            strides = _struct.unpack_from("<3i", raw, _IMG_STRIDE_OFF)
            ok = (fmt == _AOM_IMG_FMT_I420 and dw == 320 and dh == 240
                  and all(planes) and strides[0] >= 320
                  and strides[1] >= 160 and strides[1] == strides[2])
            lib.aom_img_free(ctypes.c_void_p(img))
        elif ok:
            ok = False
    if not ok:
        logger.warning("libaom struct layout mismatch; av1enc row disabled")
        return None
    _lib = lib
    return _lib


def libaom_available() -> bool:
    return _load_and_verify() is not None


def aom_strip_available() -> bool:
    """Can AomStripEncoder run?  True on either the modern (3.x) or the
    validated legacy (1.0) ABI."""
    return _load_and_verify() is not None or _load_legacy() is not None


class LibAomEncoder:
    """av1enc: frame in, AV1 temporal unit (OBU stream) out.

    Interface-compatible with TPUH264Encoder (pipeline/elements.py calls
    encode_frame(frame, qp) and reads last_stats). libaom runs its own
    CBR rate control; bitrate retunes go through set_bitrate() exactly
    like the libvpx rows (the reference pokes `target-bitrate` the same
    way, gstwebrtc_app.py:1370).
    """

    codec = "av1"

    def __init__(self, width: int, height: int, fps: int = 60,
                 bitrate_kbps: int = 2000, cpu_used: int = 10):
        lib = _load_and_verify()
        if lib is None:
            raise RuntimeError("libaom unavailable")
        if width % 2 or height % 2:
            raise ValueError("4:2:0 requires even dimensions")
        self._lib = lib
        self.width, self.height, self.fps = width, height, fps
        iface = lib.aom_codec_av1_cx()
        self._cfg = (ctypes.c_uint8 * _CFG_BYTES)()
        err = lib.aom_codec_enc_config_default(
            ctypes.c_void_p(iface), self._cfg, _AOM_USAGE_REALTIME)
        if err:
            raise RuntimeError(f"aom_codec_enc_config_default: {err}")
        w = ctypes.cast(self._cfg, ctypes.POINTER(ctypes.c_uint32))
        self._cfg_words = w
        w[_OFF_G_W], w[_OFF_G_H] = width, height
        w[_OFF_TB_NUM], w[_OFF_TB_DEN] = 1, fps
        # reference av1enc row: threads up to 24 (gstwebrtc_app.py:764);
        # row-mt + tiles below make them actually engage at 1080p
        w[_OFF_G_THREADS] = min(24, max(1, (os.cpu_count() or 4) - 1))
        w[_OFF_LAG_IN_FRAMES] = 0
        w[_OFF_RC_END_USAGE] = _AOM_CBR
        w[_OFF_TARGET_BITRATE] = bitrate_kbps
        w[_OFF_MIN_Q], w[_OFF_MAX_Q] = 2, 56
        w[_OFF_UNDERSHOOT], w[_OFF_OVERSHOOT] = 25, 25
        # VBV ≈ 1.5 frame-times, the reference's latency budget
        # (gstwebrtc_app.py:100-105); libaom buf sizes are in milliseconds
        frame_ms = 1000 // fps
        w[_OFF_BUF_SZ] = max(frame_ms * 3 // 2, 1)
        w[_OFF_BUF_INITIAL] = max(frame_ms, 1)
        w[_OFF_BUF_OPTIMAL] = max(frame_ms * 5 // 4, 1)
        # infinite GOP without AOM_KF_DISABLED (see module docstring)
        w[_OFF_KF_MODE] = _AOM_KF_AUTO
        w[_OFF_KF_MIN_DIST] = 0
        w[_OFF_KF_MAX_DIST] = _KF_NEVER
        w[_OFF_ERROR_RESILIENT] = 0
        self._ctx = (ctypes.c_uint8 * _CTX_BYTES)()
        err = lib.aom_codec_enc_init_ver(
            self._ctx, ctypes.c_void_p(iface), self._cfg, 0, _ENCODER_ABI_VERSION)
        if err:
            raise RuntimeError(f"aom_codec_enc_init_ver: {err}")
        # realtime speed preset (reference row's cpu-used knob)
        if lib.aom_codec_control(self._ctx, _AOME_SET_CPUUSED,
                                 ctypes.c_int(cpu_used)):
            logger.warning("AOME_SET_CPUUSED rejected")
        # threading parity with the reference av1enc row
        # (gstwebrtc_app.py:759-763: row-mt + tile-columns 2 + tile-rows
        # 2) via the string option API — g_threads alone does not engage
        # at 1080p without intra-frame parallelism units
        if getattr(lib, "aom_codec_set_option", None):
            for opt, val in (("row-mt", "1"),
                             ("tile-columns", "2"), ("tile-rows", "2")):
                rc = lib.aom_codec_set_option(
                    self._ctx, opt.encode(), val.encode())
                if rc:
                    logger.warning("aom option %s=%s rejected (rc=%d)",
                                   opt, val, rc)
        self._img = lib.aom_img_alloc(None, _AOM_IMG_FMT_I420, width, height, 16)
        if not self._img:
            raise RuntimeError("aom_img_alloc failed")
        raw = ctypes.string_at(self._img, _IMG_STRIDE_OFF + 12)
        self._planes = _struct.unpack_from("<3Q", raw, _IMG_PLANES_OFF)
        self._strides = _struct.unpack_from("<3i", raw, _IMG_STRIDE_OFF)
        self.frame_index = 0
        self._force_idr = True
        self._pending_bitrate: int | None = None
        self.last_stats: FrameStats | None = None
        self.qp = 0

    def close(self) -> None:
        if getattr(self, "_img", None):
            self._lib.aom_img_free(ctypes.c_void_p(self._img))
            self._img = None
        if getattr(self, "_ctx", None) is not None:
            self._lib.aom_codec_destroy(self._ctx)
            self._ctx = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

    # -- live retune ---------------------------------------------------

    def set_active_map(self, active: np.ndarray | None) -> bool:
        """Per-16x16-block activity mask: nonzero = encode, 0 = skip-from-
        reference. None clears the map. The delta front-end feeds dirty
        tiles here so libaom never runs ME/RD on unchanged blocks."""
        mb_rows = (self.height + 15) // 16
        mb_cols = (self.width + 15) // 16
        m = _AomActiveMap()
        if active is None:
            m.active_map = None
            m.rows, m.cols = mb_rows, mb_cols
            buf = None
        else:
            if active.shape != (mb_rows, mb_cols):
                raise ValueError(f"active map {active.shape} != {(mb_rows, mb_cols)}")
            buf = np.ascontiguousarray(active != 0).astype(np.uint8)
            m.active_map = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            m.rows, m.cols = mb_rows, mb_cols
        rc = self._lib.aom_codec_control(self._ctx, _AOME_SET_ACTIVEMAP, ctypes.byref(m))
        del buf
        return rc == 0

    def set_bitrate(self, bitrate_kbps: int) -> None:
        """Thread-safe: records the target; the encode thread applies it
        before the next frame (enc_config_set must never run concurrently
        with aom_codec_encode on the same context)."""
        self._pending_bitrate = max(int(bitrate_kbps), 1)

    def set_qp(self, qp: int) -> None:
        """Accepted for interface parity; libaom owns its rate control."""

    def force_keyframe(self) -> None:
        self._force_idr = True

    # -- encoding ------------------------------------------------------

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        t0 = time.perf_counter()
        pending = self._pending_bitrate
        if pending is not None:
            self._pending_bitrate = None
            self._cfg_words[_OFF_TARGET_BITRATE] = pending
            err = self._lib.aom_codec_enc_config_set(self._ctx, self._cfg)
            if err:
                logger.warning("aom_codec_enc_config_set: %d", err)
        y, u, v = _bgrx_to_i420_np(np.asarray(frame))
        for plane, arr, stride, rows in (
            (self._planes[0], y, self._strides[0], self.height),
            (self._planes[1], u, self._strides[1], self.height // 2),
            (self._planes[2], v, self._strides[2], self.height // 2),
        ):
            buf = np.ctypeslib.as_array(
                ctypes.cast(plane, ctypes.POINTER(ctypes.c_uint8)), (rows, stride))
            buf[:, : arr.shape[1]] = arr
        flags = _AOM_EFLAG_FORCE_KF if self._force_idr else 0
        t1 = time.perf_counter()
        err = self._lib.aom_codec_encode(
            self._ctx, ctypes.c_void_p(self._img), self.frame_index, 1, flags)
        if err:
            raise RuntimeError(f"aom_codec_encode: {err}")
        out = b""
        idr = False
        it = ctypes.c_void_p(None)
        while True:
            pkt = self._lib.aom_codec_get_cx_data(self._ctx, ctypes.byref(it))
            if not pkt:
                break
            raw = ctypes.string_at(pkt, _PKT_READ)
            if _struct.unpack_from("<i", raw, _PKT_KIND_OFF)[0] == 0:  # CX_FRAME
                buf, sz = _struct.unpack_from("<QQ", raw, _PKT_BUF_OFF)
                out += ctypes.string_at(buf, sz)
                idr = bool(_struct.unpack_from("<I", raw, _PKT_FLAGS_OFF)[0]
                           & _AOM_FRAME_IS_KEY)
        t2 = time.perf_counter()
        if idr:
            self._force_idr = False
        self.last_stats = FrameStats(
            frame_index=self.frame_index,
            idr=idr,
            qp=self.qp,
            bytes=len(out),
            device_ms=(t2 - t1) * 1e3,  # "device" = libaom encode on CPU
            pack_ms=(t1 - t0) * 1e3,    # colorspace conversion
        )
        self.frame_index += 1
        return out


class AomStripEncoder:
    """One tile column's encoder for the AV1 tile-column mesh
    (parallel/codec_mesh.py): lossless, all-intra, single-tile, 64px
    superblocks, one thread.  Every knob here is a CORRECTNESS pin, not
    a tuning choice — models/av1/stitch.py splices this encoder's tile
    payloads into a wider frame, which is only bit-compatible when the
    payload is position-independent (intra + default CDFs), the carve is
    64px-superblock aligned, and no cross-tile filter pass exists
    (CodedLossless).  See the stitch module docstring for the proof
    obligations; tests decode the splice with independent libdav1d.

    Runs against modern libaom (string-option API) or the validated
    legacy 1.0 ABI (_load_legacy) — both via good-quality usage 0, the
    only usage the legacy library has.  Parallelism comes from the mesh
    running one instance per column, so g_threads stays 1 and encodes
    are deterministic per instance.
    """

    codec = "av1"

    def __init__(self, width: int, height: int, cpu_used: int = 6):
        lib = _load_and_verify()
        self._legacy = False
        if lib is None:
            lib = _load_legacy()
            self._legacy = True
        if lib is None:
            raise RuntimeError("libaom unavailable")
        if width % 2 or height % 2:
            raise ValueError("4:2:0 requires even dimensions")
        self._lib = lib
        self.width, self.height = width, height
        iface = lib.aom_codec_av1_cx()
        self._cfg = (ctypes.c_uint8 * _CFG_BYTES)()
        err = lib.aom_codec_enc_config_default(ctypes.c_void_p(iface), self._cfg, 0)
        if err:
            raise RuntimeError(f"aom_codec_enc_config_default: {err}")
        w = ctypes.cast(self._cfg, ctypes.POINTER(ctypes.c_uint32))
        w[_OFF_G_W], w[_OFF_G_H] = width, height
        w[_OFF_G_THREADS] = 1
        w[_OFF_TB_NUM], w[_OFF_TB_DEN] = 1, 30
        w[_OFF_LAG_IN_FRAMES] = 0
        self._ctx = (ctypes.c_uint8 * _CTX_BYTES)()
        abi = _LEGACY_ABI if self._legacy else _ENCODER_ABI_VERSION
        err = lib.aom_codec_enc_init_ver(
            self._ctx, ctypes.c_void_p(iface), self._cfg, 0, abi)
        if err:
            raise RuntimeError(f"aom_codec_enc_init_ver: {err}")
        cpu_used = max(0, min(8, cpu_used))
        if self._legacy:
            pins = (("cpu_used", cpu_used), ("lossless", 1),
                    ("tile_columns", 0), ("tile_rows", 0),
                    ("superblock_size", 0))  # AOM_SUPERBLOCK_SIZE_64X64
            for name, val in pins:
                rc = lib.aom_codec_control_(
                    self._ctx, _LEGACY_CTRL[name], ctypes.c_int(val))
                if rc:
                    lib.aom_codec_destroy(self._ctx)
                    self._ctx = None
                    raise RuntimeError(f"aom control {name}={val} rejected ({rc})")
        else:
            if lib.aom_codec_control(self._ctx, _AOME_SET_CPUUSED,
                                     ctypes.c_int(cpu_used)):
                logger.warning("AOME_SET_CPUUSED rejected")
            for opt, val in (("lossless", "1"), ("tile-columns", "0"),
                             ("tile-rows", "0"), ("sb-size", "64")):
                rc = lib.aom_codec_set_option(self._ctx, opt.encode(), val.encode())
                if rc:
                    lib.aom_codec_destroy(self._ctx)
                    self._ctx = None
                    raise RuntimeError(f"aom option {opt}={val} rejected ({rc})")
        self._img = lib.aom_img_alloc(None, _AOM_IMG_FMT_I420, width, height, 16)
        if not self._img:
            raise RuntimeError("aom_img_alloc failed")
        stride_off = _LEGACY_IMG_STRIDE_OFF if self._legacy else _IMG_STRIDE_OFF
        raw = ctypes.string_at(self._img, stride_off + 12)
        self._planes = _struct.unpack_from("<3Q", raw, _IMG_PLANES_OFF)
        self._strides = _struct.unpack_from("<3i", raw, stride_off)
        self.frame_index = 0

    def close(self) -> None:
        if getattr(self, "_img", None):
            self._lib.aom_img_free(ctypes.c_void_p(self._img))
            self._img = None
        if getattr(self, "_ctx", None) is not None:
            self._lib.aom_codec_destroy(self._ctx)
            self._ctx = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: silent-except-audited — best-effort teardown
            pass

    def encode_planes(self, y: np.ndarray, u: np.ndarray, v: np.ndarray) -> bytes:
        """Encode pre-converted I420 planes as a forced keyframe; returns
        the temporal unit (sequence header included on the first call)."""
        for plane, arr, stride, rows in (
            (self._planes[0], y, self._strides[0], self.height),
            (self._planes[1], u, self._strides[1], self.height // 2),
            (self._planes[2], v, self._strides[2], self.height // 2),
        ):
            buf = np.ctypeslib.as_array(
                ctypes.cast(plane, ctypes.POINTER(ctypes.c_uint8)), (rows, stride))
            buf[:, : arr.shape[1]] = arr
        err = self._lib.aom_codec_encode(
            self._ctx, ctypes.c_void_p(self._img), self.frame_index, 1,
            _AOM_EFLAG_FORCE_KF)
        if err:
            raise RuntimeError(f"aom_codec_encode: {err}")
        out = b""
        it = ctypes.c_void_p(None)
        while True:
            pkt = self._lib.aom_codec_get_cx_data(self._ctx, ctypes.byref(it))
            if not pkt:
                break
            raw = ctypes.string_at(pkt, _PKT_READ)
            if _struct.unpack_from("<i", raw, _PKT_KIND_OFF)[0] == 0:
                buf, sz = _struct.unpack_from("<QQ", raw, _PKT_BUF_OFF)
                out += ctypes.string_at(buf, sz)
        self.frame_index += 1
        return out

    def encode_frame(self, frame: np.ndarray) -> bytes:
        """BGRx convenience entry (tests / oracle paths)."""
        y, u, v = _bgrx_to_i420_np(np.asarray(frame))
        return self.encode_planes(y, u, v)
