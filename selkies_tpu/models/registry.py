"""Encoder registry — the TPU-native analogue of the encoder matrix.

The reference builds one of 15 encoder element chains by name
(gstwebrtc_app.py:260-783, supported list :1133) with an `ADD_ENCODER:`
grep-marker protocol for extensions (:257,943,1132). Here the matrix
collapses: every codec targets the same TPU compute core, so the registry
maps encoder names to factory callables, and legacy GStreamer encoder
names alias to their TPU equivalent so existing SELKIES_ENCODER configs
keep working.

ADD_ENCODER: register new encoders with @register("name") below.
"""

from __future__ import annotations

import logging
import os
from typing import Callable

logger = logging.getLogger("models.registry")

_FACTORIES: dict[str, Callable] = {}
_ALIASES: dict[str, str] = {}


def register(name: str) -> Callable[[Callable], Callable]:
    def deco(factory: Callable) -> Callable:
        _FACTORIES[name] = factory
        return factory

    return deco


def alias(name: str, target: str) -> None:
    _ALIASES[name] = target


def encoder_exists(name: str) -> bool:
    return name in _FACTORIES or name in _ALIASES


def supported_encoders() -> list[str]:
    return sorted(_FACTORIES) + sorted(_ALIASES)


def create_encoder(name: str, *, width: int, height: int, fps: int = 60, **kw):
    if name in _ALIASES:
        target = _ALIASES[name]
        logger.info("encoder %r aliased to %r (TPU-native equivalent)", name, target)
        name = target
    if name not in _FACTORIES:
        raise ValueError(f"unknown encoder {name!r}; supported: {supported_encoders()}")
    return _FACTORIES[name](width=width, height=height, fps=fps, **kw)


# ADD_ENCODER: factories


def default_frame_batch() -> int:
    """Deployment-aware grouped-dispatch depth (see PERF.md): on the axon
    relay (per-operation link pricing) group 8 frames per device round
    trip; on PCIe-local hosts favor latency. SELKIES_FRAME_BATCH
    overrides either way — bench.py and the live pipeline share this."""
    env = os.environ.get("SELKIES_FRAME_BATCH")
    if env:
        return max(1, min(16, int(env)))
    return 8 if os.environ.get("PALLAS_AXON_POOL_IPS") else 4


@register("tpuh264enc")
def _tpuh264enc(*, width: int, height: int, fps: int = 60, qp: int = 28, **kw):
    from selkies_tpu.models.h264.encoder import TPUH264Encoder

    kw.setdefault("frame_batch", default_frame_batch())
    kw.setdefault("scene_qp_boost", 6)
    return TPUH264Encoder(width=width, height=height, qp=qp, fps=fps, **kw)


@register("tpuvp9enc")
def _tpuvp9enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    """VP9 row with the framework's capture-delta front-end: unchanged
    frames short-circuit to 1-byte show_existing_frame headers, changed
    frames go through libvpx (see models/vp9/encoder.py for why VP9's
    entropy back-end cannot be rebuilt from scratch in this image)."""
    from selkies_tpu.models.vp9.encoder import TPUVP9Encoder

    return TPUVP9Encoder(width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps)


@register("vp9enc")
def _vp9enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    from selkies_tpu.models.libvpx_enc import LibVpxEncoder

    return LibVpxEncoder(width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps)


@register("vp8enc")
def _vp8enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    from selkies_tpu.models.libvpx_enc import LibVpxEncoder

    return LibVpxEncoder(width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps, vp8=True)


@register("tpuav1enc")
def _tpuav1enc(**kw):
    raise NotImplementedError(
        "tpuav1enc: AV1's adaptive CDF entropy coder depends on normative "
        "default tables (spec data, not derivable) and no AV1 library "
        "exists in this image — use tpuh264enc (from-scratch TPU) or "
        "tpuvp9enc (delta front-end + libvpx)"
    )


# Legacy GStreamer encoder names (reference gstwebrtc_app.py:1133) map to
# the TPU equivalent so existing SELKIES_ENCODER values keep working.
for _legacy_h264 in ("nvh264enc", "vah264enc", "x264enc", "openh264enc"):
    alias(_legacy_h264, "tpuh264enc")
alias("vavp9enc", "tpuvp9enc")  # silicon VP9 row maps to the hybrid
for _legacy_av1 in ("nvav1enc", "vaav1enc", "svtav1enc", "av1enc", "rav1enc"):
    alias(_legacy_av1, "tpuav1enc")
