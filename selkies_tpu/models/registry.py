"""Encoder registry — the TPU-native analogue of the encoder matrix.

The reference builds one of 15 encoder element chains by name
(gstwebrtc_app.py:260-783, supported list :1133) with an `ADD_ENCODER:`
grep-marker protocol for extensions (:257,943,1132). Here the matrix
collapses: every codec targets the same TPU compute core, so the registry
maps encoder names to factory callables, and legacy GStreamer encoder
names alias to their TPU equivalent so existing SELKIES_ENCODER configs
keep working.

ADD_ENCODER: register new encoders with @register("name") below.
"""

from __future__ import annotations

import logging
import os
from typing import Callable

logger = logging.getLogger("models.registry")

_FACTORIES: dict[str, Callable] = {}
_ALIASES: dict[str, str] = {}
_CODECS: dict[str, str] = {}

# codec -> RTP payloader (module, class) — resolved lazily so importing
# the registry never drags the transport stack in.  Every codec a row
# declares MUST map here: tools/check_codec_rows.py ratchets the
# invariant, because a registry row whose codec has no payloader can be
# negotiated but never streamed.
_PAYLOADERS: dict[str, tuple[str, str]] = {
    "h264": ("selkies_tpu.transport.rtp", "H264Payloader"),
    "h265": ("selkies_tpu.transport.rtp_h265", "H265Payloader"),
    "av1": ("selkies_tpu.transport.rtp_av1", "Av1Payloader"),
    "vp8": ("selkies_tpu.transport.rtp_vpx", "Vp8Payloader"),
    "vp9": ("selkies_tpu.transport.rtp_vpx", "Vp9Payloader"),
}


def register(name: str, codec: str = "") -> Callable[[Callable], Callable]:
    """Register an encoder factory.  ``codec`` declares the bitstream the
    row emits ("h264"/"av1"/...) — per-client negotiation
    (signalling/negotiate.py) and the payloader wiring key off it, and
    tools/check_codec_rows.py fails the build when a row forgets it."""

    def deco(factory: Callable) -> Callable:
        _FACTORIES[name] = factory
        if codec:
            _CODECS[name] = codec
        return factory

    return deco


def alias(name: str, target: str) -> None:
    _ALIASES[name] = target


def encoder_exists(name: str) -> bool:
    return name in _FACTORIES or name in _ALIASES


def supported_encoders() -> list[str]:
    return sorted(_FACTORIES) + sorted(_ALIASES)


def codec_for_encoder(name: str) -> str:
    """The codec a registry row (or alias) declares; "" if unknown."""
    name = _ALIASES.get(name, name)
    return _CODECS.get(name, "")


def payloader_for_codec(codec: str):
    """The RTP payloader class for a codec (lazy import)."""
    import importlib

    try:
        mod_name, cls_name = _PAYLOADERS[codec.lower()]
    except KeyError:
        raise ValueError(f"no payloader for codec {codec!r}") from None
    return getattr(importlib.import_module(mod_name), cls_name)


def create_encoder(name: str, *, width: int, height: int, fps: int = 60, **kw):
    # encoder (re)builds — including the resilience ladder's RESTART rung —
    # reuse compiled executables across instances and process restarts
    from selkies_tpu.utils.jaxcache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()
    if name in _ALIASES:
        target = _ALIASES[name]
        logger.info("encoder %r aliased to %r (TPU-native equivalent)", name, target)
        name = target
    if name not in _FACTORIES:
        raise ValueError(f"unknown encoder {name!r}; supported: {supported_encoders()}")
    return _FACTORIES[name](width=width, height=height, fps=fps, **kw)


# ADD_ENCODER: factories


def default_frame_batch() -> int:
    """Deployment-aware grouped-dispatch depth (see PERF.md): on the axon
    relay (per-operation link pricing) group 8 frames per device round
    trip; on PCIe-local hosts favor latency. SELKIES_FRAME_BATCH
    overrides either way — bench.py and the live pipeline share this."""
    env = os.environ.get("SELKIES_FRAME_BATCH")
    if env:
        try:
            return max(1, min(16, int(env)))
        except ValueError:
            logger.warning(
                "SELKIES_FRAME_BATCH=%r is not an integer; using default", env)
    return 8 if os.environ.get("PALLAS_AXON_POOL_IPS") else 4


def default_pipeline_depth() -> int:
    """Deployment-aware in-flight round-trip cap. The relay's d2h fetch
    costs ~140 ms RTT + per-byte; fetches overlap across worker threads
    (PERF.md), so the steady state is fetch-bound unless 3+ group
    round trips are in flight. PCIe-local hosts keep the shallow
    pipeline (RTT is microseconds; depth only adds latency).
    SELKIES_PIPELINE_DEPTH overrides either way."""
    env = os.environ.get("SELKIES_PIPELINE_DEPTH")
    if env:
        try:
            return max(0, min(8, int(env)))
        except ValueError:
            logger.warning(
                "SELKIES_PIPELINE_DEPTH=%r is not an integer; using default", env)
    # depth 3 measured faster on the relay when the tunnel is healthy,
    # but two runs stalled during a tunnel degradation with 3 groups of
    # fetches outstanding — hold the default at 2 until that is
    # attributable; SELKIES_PIPELINE_DEPTH=3 opts in
    return 2


@register("tpuh264enc", codec="h264")
def _tpuh264enc(*, width: int, height: int, fps: int = 60, qp: int = 28, **kw):
    from selkies_tpu.models.h264.encoder import TPUH264Encoder

    # the TPU row is QP-driven (the app's CbrRateController owns the
    # rate loop via set_qp); the library rows consume bitrate_kbps
    kw.pop("bitrate_kbps", None)
    bands = kw.pop("bands", None)
    cols = kw.pop("cols", None)
    if bands is None and cols is None:
        from selkies_tpu.parallel.bands import bands_from_env, grid_from_env

        grid = grid_from_env()
        if grid is not None:
            # SELKIES_TILE_GRID=RxC owns the carve: R band-rows × C tile
            # columns (C=1 degenerates to SELKIES_BANDS=R exactly)
            bands, cols = grid
        else:
            bands = bands_from_env()
    bands = 1 if bands is None else bands
    cols = 1 if cols is None else cols
    if bands > 1 or cols > 1:
        # SELKIES_BANDS>1 / SELKIES_TILE_GRID: the frame splits across
        # the chip mesh as independent slices (parallel/bands.py) — the
        # 4K / full-motion path where the FIFO-serialized device step is
        # the bottleneck. Falls back to the single-device sliced encode
        # (identical bytes) when the mesh is smaller than the carve.
        # Routed BEFORE the solo-knob setdefaults so `dropped` sees only
        # what the caller actually passed.
        from selkies_tpu.parallel.bands import BandedH264Encoder

        dropped = set(kw) - {"frame_batch", "pipeline_depth",
                             "keyframe_interval", "device_entropy",
                             "bits_min_mbs", "entropy_coder"}
        if dropped:
            # the solo encoder's uplink machinery (tile cache, delta
            # paths, LTR scenes, scene QP boost) does not apply to band
            # mode — say so instead of silently ignoring an explicitly-
            # passed knob
            logger.warning(
                "band-parallel encoder ignores encoder kwargs %s "
                "(solo-encoder knobs; see docs/bands.md)", sorted(dropped))
        return BandedH264Encoder(
            width=width, height=height, qp=qp, fps=fps, bands=bands,
            cols=cols,
            frame_batch=kw.get("frame_batch", default_frame_batch()),
            pipeline_depth=kw.get("pipeline_depth", default_pipeline_depth()),
            keyframe_interval=kw.get("keyframe_interval", 0),
            device_entropy=kw.get("device_entropy"),
            bits_min_mbs=kw.get("bits_min_mbs"),
            entropy_coder=kw.get("entropy_coder"),
        )
    kw.setdefault("frame_batch", default_frame_batch())
    kw.setdefault("pipeline_depth", default_pipeline_depth())
    kw.setdefault("scene_qp_boost", 6)
    return TPUH264Encoder(width=width, height=height, qp=qp, fps=fps, **kw)


@register("tpuvp9enc", codec="vp9")
def _tpuvp9enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    """VP9 row with the framework's capture-delta front-end: unchanged
    frames short-circuit to 1-byte show_existing_frame headers, changed
    frames go through libvpx (see models/vp9/encoder.py for why VP9's
    entropy back-end cannot be rebuilt from scratch in this image).
    ``cols``/SELKIES_TILE_COLS > 1 routes to the tile-column mesh mode:
    column-sharded device front-end + libvpx tile columns pinned to the
    carve (parallel/codec_mesh.py)."""
    from selkies_tpu.parallel.codec_mesh import TileColumnVP9Encoder, cols_from_env

    cols = kw.pop("cols", None)
    cols = cols_from_env() if cols is None else max(1, int(cols))
    if cols > 1:
        return TileColumnVP9Encoder(
            width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps,
            cols=cols, frontend=kw.get("frontend"))
    from selkies_tpu.models.vp9.encoder import TPUVP9Encoder

    return TPUVP9Encoder(width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps)


@register("vp9enc", codec="vp9")
def _vp9enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    from selkies_tpu.models.libvpx_enc import LibVpxEncoder

    return LibVpxEncoder(width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps)


@register("vp8enc", codec="vp8")
def _vp8enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    from selkies_tpu.models.libvpx_enc import LibVpxEncoder

    return LibVpxEncoder(width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps, vp8=True)


@register("x264enc", codec="h264")
def _x264enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    """The REAL x264 software row (ctypes libx264, reference tuning —
    gstwebrtc_app.py:609-639); degrades to the TPU encoder when the
    library/ABI probe fails (models/x264enc.py)."""
    from selkies_tpu.models.x264enc import X264Encoder, x264_available

    if not x264_available():
        logger.warning("libx264 unavailable; x264enc falls back to tpuh264enc")
        return _FACTORIES["tpuh264enc"](width=width, height=height, fps=fps, **kw)
    return X264Encoder(width=width, height=height, fps=fps, bitrate_kbps=bitrate_kbps)


@register("tpuav1enc", codec="av1")
def _tpuav1enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    """AV1 row with the framework's capture-delta front-end: unchanged
    frames encode with an all-inactive active map (every block skips from
    reference), changed frames restrict libaom's per-block work to dirty
    tiles (see models/av1/encoder.py). Degrades to the from-scratch TPU
    H.264 encoder only if the libaom ABI probe fails — the reference's
    own policy when an encoder is missing is to fail the pipeline
    (gstwebrtc_app.py:1123-1140); we degrade instead and log."""
    from selkies_tpu.models.libaom_enc import (
        aom_strip_available, libaom_available)
    from selkies_tpu.parallel.codec_mesh import TileColumnAV1Encoder, cols_from_env

    cols = kw.pop("cols", None)
    cols = cols_from_env() if cols is None else max(1, int(cols))
    if cols > 1 and aom_strip_available():
        # SELKIES_TILE_COLS / negotiated carve: the tile-column mesh mode
        # (parallel/codec_mesh.py — per-column strip encoders spliced
        # into one frame). Pinned lossless; the realtime CBR hybrid row
        # below stays the single-column path.
        return TileColumnAV1Encoder(
            width=width, height=height, fps=fps, cols=cols,
            frontend=kw.get("frontend"),
            keyframe_interval=kw.get("keyframe_interval", 0))
    if not libaom_available():
        if aom_strip_available():
            # legacy-ABI libaom (1.0): no realtime usage for the hybrid
            # CBR row, but the lossless tile-column splice works — serve
            # AV1 through the mesh row at cols=1 rather than silently
            # negotiating H.264
            return TileColumnAV1Encoder(
                width=width, height=height, fps=fps, cols=1,
                frontend=kw.get("frontend"),
                keyframe_interval=kw.get("keyframe_interval", 0))
        logger.warning("libaom unavailable; tpuav1enc falls back to tpuh264enc "
                       "— the session will negotiate H.264")
        kw.pop("cpu_used", None)
        kw.pop("frontend", None)
        return _FACTORIES["tpuh264enc"](width=width, height=height, fps=fps, **kw)
    from selkies_tpu.models.av1.encoder import TPUAV1Encoder

    return TPUAV1Encoder(width=width, height=height, fps=fps,
                         bitrate_kbps=bitrate_kbps, **kw)


@register("av1enc", codec="av1")
def _av1enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    """The REAL libaom software row (ctypes, reference tuning —
    gstwebrtc_app.py:741-783); degrades to tpuav1enc's fallback chain
    when the library/ABI probe fails (models/libaom_enc.py)."""
    from selkies_tpu.models.libaom_enc import LibAomEncoder, libaom_available

    if not libaom_available():
        logger.warning("libaom unavailable; av1enc falls back to tpuh264enc")
        kw.pop("cpu_used", None)  # AV1-only knob; TPUH264Encoder rejects it
        return _FACTORIES["tpuh264enc"](width=width, height=height, fps=fps, **kw)
    return LibAomEncoder(width=width, height=height, fps=fps,
                         bitrate_kbps=bitrate_kbps, **kw)


@register("x265enc", codec="h265")
def _x265enc(*, width: int, height: int, fps: int = 60, bitrate_kbps: int = 2000, **kw):
    """The REAL x265 HEVC software row (ctypes libx265, reference tuning —
    gstwebrtc_app.py:667-683); degrades to the TPU encoder when the
    library/ABI probe fails (models/x265enc.py)."""
    from selkies_tpu.models.x265enc import X265Encoder, x265_available

    if not x265_available():
        logger.warning("libx265 unavailable; x265enc falls back to tpuh264enc")
        kw.pop("preset", None)  # x265-only knob; TPUH264Encoder rejects it
        return _FACTORIES["tpuh264enc"](width=width, height=height, fps=fps, **kw)
    return X265Encoder(width=width, height=height, fps=fps,
                       bitrate_kbps=bitrate_kbps,
                       preset=kw.get("preset", "ultrafast"))


# Legacy GStreamer encoder names (reference gstwebrtc_app.py:1133) map to
# the TPU equivalent so existing SELKIES_ENCODER values keep working.
# (x264enc / x265enc / av1enc are REAL rows above, not aliases.)
for _legacy_h264 in ("nvh264enc", "vah264enc", "openh264enc"):
    alias(_legacy_h264, "tpuh264enc")
# H.265 silicon rows (reference gstwebrtc_app.py:369-424,510-542) map to
# the libx265 software row — HEVC's CABAC-only entropy coding can't be
# rebuilt from scratch here (normative context tables), so the library
# the reference's own x265enc wraps carries the codec.
for _legacy_h265 in ("nvh265enc", "vah265enc"):
    alias(_legacy_h265, "x265enc")
alias("vavp9enc", "tpuvp9enc")  # silicon VP9 row maps to the hybrid
# AV1 silicon/alternative-library rows map to the hybrid libaom row
# (av1enc above is the REAL plain-libaom row, not an alias)
for _legacy_av1 in ("nvav1enc", "vaav1enc", "rav1enc"):
    alias(_legacy_av1, "tpuav1enc")


@register("svtav1enc", codec="av1")
def _svtav1enc(*, width: int, height: int, fps: int = 60,
               bitrate_kbps: int = 2000, **kw):
    """REAL SVT-AV1 row when libSvtAv1Enc passes ABI validation
    (models/svt_av1_enc.py — the same library the reference's svtav1enc
    element wraps, gstwebrtc_app.py:724-739); otherwise the hybrid
    libaom row serves the name, as the silicon aliases do."""
    from selkies_tpu.models.svt_av1_enc import SvtAv1Encoder, svt_av1_available

    if svt_av1_available():
        return SvtAv1Encoder(width=width, height=height, fps=fps,
                             bitrate_kbps=int(bitrate_kbps),
                             preset=int(kw.get("preset", 10)))
    return create_encoder("tpuav1enc", width=width, height=height, fps=fps,
                          bitrate_kbps=bitrate_kbps, **kw)
