"""AV1 OBU header parsing — sequence header + uncompressed frame header
up to refresh_frame_flags (AV1 spec 5.5, 5.9; all plain f(n) bits, no
arithmetic coding).

Why this exists: the hybrid AV1 row re-shows the previous frame for
static captures via a show_existing_frame header (spec 5.9.2), which
needs to know WHICH reference slot libaom refreshed with the last shown
frame. Rather than trusting libaom's (empirically cyclic) slot rotation,
the encoder parses its own output's refresh_frame_flags — robust across
scene-change keyframes, rate-control behaviour, and library upgrades.
Also used by tests to sanity-check temporal units.
"""

from __future__ import annotations

from dataclasses import dataclass

OBU_SEQUENCE_HEADER = 1
OBU_TEMPORAL_DELIMITER = 2
OBU_FRAME_HEADER = 3
OBU_TILE_GROUP = 4
OBU_METADATA = 5
OBU_FRAME = 6
OBU_REDUNDANT_FRAME_HEADER = 7
OBU_PADDING = 15

KEY_FRAME = 0
INTER_FRAME = 1
INTRA_ONLY_FRAME = 2
SWITCH_FRAME = 3


class _Bits:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def f(self, n: int) -> int:
        v = 0
        for _ in range(n):
            byte = self.data[self.pos >> 3]
            v = (v << 1) | ((byte >> (7 - (self.pos & 7))) & 1)
            self.pos += 1
        return v

    def uvlc(self) -> int:
        zeros = 0
        while self.f(1) == 0:
            zeros += 1
            if zeros > 32:
                raise ValueError("uvlc overrun")
        if zeros == 0:
            return 0
        return self.f(zeros) + (1 << zeros) - 1


def _leb128(data: bytes, off: int) -> tuple[int, int]:
    v = 0
    for i in range(8):
        b = data[off + i]
        v |= (b & 0x7F) << (7 * i)
        if not b & 0x80:
            return v, off + i + 1
    raise ValueError("leb128 overrun")


def iter_obus(tu: bytes):
    """Yield (obu_type, payload_bytes) for each OBU in a temporal unit
    (low-overhead bitstream: every OBU carries a size field)."""
    off = 0
    n = len(tu)
    while off < n:
        hdr = tu[off]
        if hdr & 0x80:
            raise ValueError("forbidden bit set")
        otype = (hdr >> 3) & 0xF
        ext = bool(hdr & 0x04)
        has_size = bool(hdr & 0x02)
        off += 1
        if ext:
            off += 1
        if has_size:
            size, off = _leb128(tu, off)
        else:
            size = n - off
        yield otype, tu[off:off + size]
        off += size


@dataclass
class SequenceHeader:
    """The subset of sequence-header state the frame header parse needs."""
    reduced_still_picture: bool
    decoder_model_info_present: bool
    equal_picture_interval: bool
    frame_presentation_time_length: int
    frame_id_numbers_present: bool
    frame_id_length: int
    delta_frame_id_length: int
    order_hint_bits: int
    force_screen_content_tools: int  # 2 = per-frame choice
    force_integer_mv: int            # 2 = per-frame choice


def parse_sequence_header(payload: bytes) -> SequenceHeader:
    b = _Bits(payload)
    b.f(3)  # seq_profile
    b.f(1)  # still_picture
    reduced = bool(b.f(1))
    decoder_model_info_present = False
    equal_picture_interval = False
    fpt_len = 0
    buffer_delay_length = 0
    if reduced:
        b.f(5)  # seq_level_idx[0]
    else:
        if b.f(1):  # timing_info_present
            b.f(32)  # num_units_in_display_tick
            b.f(32)  # time_scale
            equal_picture_interval = bool(b.f(1))
            if equal_picture_interval:
                b.uvlc()  # num_ticks_per_picture_minus_1
            decoder_model_info_present = bool(b.f(1))
            if decoder_model_info_present:
                buffer_delay_length = b.f(5) + 1
                b.f(32)  # num_units_in_decoding_tick
                b.f(5)   # buffer_removal_time_length_minus_1
                fpt_len = b.f(5) + 1
        initial_display_delay_present = bool(b.f(1))
        op_cnt = b.f(5) + 1
        for _ in range(op_cnt):
            b.f(12)  # operating_point_idc
            seq_level_idx = b.f(5)
            if seq_level_idx > 7:
                b.f(1)  # seq_tier
            if decoder_model_info_present:
                if b.f(1):  # decoder_model_present_for_this_op
                    b.f(buffer_delay_length)  # decoder_buffer_delay
                    b.f(buffer_delay_length)  # encoder_buffer_delay
                    b.f(1)   # low_delay_mode_flag
            if initial_display_delay_present:
                if b.f(1):
                    b.f(4)  # initial_display_delay_minus_1
    frame_width_bits = b.f(4) + 1
    frame_height_bits = b.f(4) + 1
    b.f(frame_width_bits)   # max_frame_width_minus_1
    b.f(frame_height_bits)  # max_frame_height_minus_1
    frame_id_numbers_present = False
    delta_len = 0
    id_len = 0
    if not reduced:
        frame_id_numbers_present = bool(b.f(1))
    if frame_id_numbers_present:
        delta_len = b.f(4) + 2
        id_len = delta_len + b.f(3) + 1
    b.f(1)  # use_128x128_superblock
    b.f(1)  # enable_filter_intra
    b.f(1)  # enable_intra_edge_filter
    order_hint_bits = 0
    force_sct = 2
    force_imv = 2
    if not reduced:
        b.f(1)  # enable_interintra_compound
        b.f(1)  # enable_masked_compound
        b.f(1)  # enable_warped_motion
        b.f(1)  # enable_dual_filter
        enable_order_hint = bool(b.f(1))
        if enable_order_hint:
            b.f(1)  # enable_jnt_comp
            b.f(1)  # enable_ref_frame_mvs
        force_sct = 2 if b.f(1) else b.f(1)  # seq_choose / seq_force sct
        if force_sct > 0:
            force_imv = 2 if b.f(1) else b.f(1)
        else:
            force_imv = 2
        if enable_order_hint:
            order_hint_bits = b.f(3) + 1
    else:
        force_sct = 2
        force_imv = 2
    # enable_superres / cdef / restoration / color_config follow — not
    # needed for the frame-header prefix this module parses
    return SequenceHeader(
        reduced_still_picture=reduced,
        decoder_model_info_present=decoder_model_info_present,
        equal_picture_interval=equal_picture_interval,
        frame_presentation_time_length=fpt_len,
        frame_id_numbers_present=frame_id_numbers_present,
        frame_id_length=id_len,
        delta_frame_id_length=delta_len,
        order_hint_bits=order_hint_bits,
        force_screen_content_tools=force_sct,
        force_integer_mv=force_imv,
    )


@dataclass
class FrameHeaderInfo:
    show_existing_frame: bool
    frame_to_show_map_idx: int | None
    frame_type: int | None
    show_frame: bool
    showable_frame: bool
    refresh_frame_flags: int


def parse_frame_header(payload: bytes, seq: SequenceHeader) -> FrameHeaderInfo:
    """Parse an OBU_FRAME / OBU_FRAME_HEADER payload up to
    refresh_frame_flags (spec 5.9.2 uncompressed_header)."""
    b = _Bits(payload)
    if seq.reduced_still_picture:
        return FrameHeaderInfo(False, None, KEY_FRAME, True, False, 0xFF)
    if b.f(1):  # show_existing_frame
        idx = b.f(3)
        return FrameHeaderInfo(True, idx, None, False, False, 0)
    frame_type = b.f(2)
    show_frame = bool(b.f(1))
    if show_frame and seq.decoder_model_info_present and not seq.equal_picture_interval:
        b.f(seq.frame_presentation_time_length)  # temporal_point_info
    if show_frame:
        showable = frame_type != KEY_FRAME
    else:
        showable = bool(b.f(1))
    if frame_type == SWITCH_FRAME or (frame_type == KEY_FRAME and show_frame):
        error_resilient = True
    else:
        error_resilient = bool(b.f(1))
    b.f(1)  # disable_cdf_update
    if seq.force_screen_content_tools == 2:
        allow_sct = bool(b.f(1))
    else:
        allow_sct = bool(seq.force_screen_content_tools)
    if allow_sct and seq.force_integer_mv == 2:
        b.f(1)  # force_integer_mv
    if seq.frame_id_numbers_present:
        b.f(seq.frame_id_length)  # current_frame_id
    if frame_type == SWITCH_FRAME:
        frame_size_override = True
    else:
        frame_size_override = bool(b.f(1))
    _ = frame_size_override  # consumed later in the full header; not needed here
    b.f(seq.order_hint_bits)  # order_hint
    frame_is_intra = frame_type in (KEY_FRAME, INTRA_ONLY_FRAME)
    if not (frame_is_intra or error_resilient):
        b.f(3)  # primary_ref_frame
    if seq.decoder_model_info_present:
        if b.f(1):  # buffer_removal_time_present_flag
            raise ValueError("buffer_removal_time parsing not supported")
    if frame_type == SWITCH_FRAME or (frame_type == KEY_FRAME and show_frame):
        refresh = 0xFF
    else:
        refresh = b.f(8)
    return FrameHeaderInfo(False, None, frame_type, show_frame, showable, refresh)


def scan_temporal_unit(tu: bytes, seq: SequenceHeader | None
                       ) -> tuple[SequenceHeader | None, FrameHeaderInfo | None]:
    """Walk one TU: returns (updated sequence header, first frame header).
    The sequence header from a previous TU must be threaded through —
    inter-only TUs don't repeat it."""
    fh = None
    for otype, payload in iter_obus(tu):
        if otype == OBU_SEQUENCE_HEADER:
            seq = parse_sequence_header(payload)
        elif otype in (OBU_FRAME, OBU_FRAME_HEADER) and fh is None:
            if seq is None:
                raise ValueError("frame before sequence header")
            fh = parse_frame_header(payload, seq)
    return seq, fh


def show_existing_frame_tu(map_idx: int) -> bytes:
    """A minimal temporal unit re-showing reference slot `map_idx`
    (spec 5.9.2): temporal delimiter + 1-byte frame header OBU —
    show_existing_frame(1)=1, frame_to_show_map_idx(3), trailing bits.
    Only legal when the slot holds a frame with showable_frame=1 (shown
    inter frames qualify; shown keyframes do NOT)."""
    if not 0 <= map_idx <= 7:
        raise ValueError(f"frame_to_show_map_idx {map_idx} out of range")
    td = bytes([0x12, 0x00])  # OBU_TEMPORAL_DELIMITER, has_size, size=0
    hdr = bytes([0x1A, 0x01, 0x80 | (map_idx << 4) | 0x08])
    return td + hdr
