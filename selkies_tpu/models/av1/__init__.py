"""AV1 encoder row: hybrid capture-delta front-end over ctypes libaom,
with ctypes libdav1d as the independent conformance decoder."""

from selkies_tpu.models.av1.encoder import TPUAV1Encoder  # noqa: F401
