"""ctypes wrapper for libdav1d: AV1 conformance decoding.

The AV1 row's conformance tests need a decoder that is independent of
the encoder (the same role FFmpeg plays for the H.264/VP9 rows — but
this image's OpenCV/FFmpeg build has no software AV1 decoder, only a
hwaccel stub). dav1d 1.0.0 is in the image; this wraps just enough of
its API to decode temporal units into Y/U/V numpy planes.

ABI notes (dav1d 1.0.0, verified empirically — see the picture-layout
check in _load): Dav1dPicture is {seq_hdr*, frame_hdr*, data[3] @16,
stride[2] @40, p{w @56, h @60, layout @64, bpc @68}, ...}. Dav1dData is
{data*, sz, ref*, props} and Dav1dSettings is filled entirely by
dav1d_default_settings — the wrapper never pokes either beyond what
the API functions write.
"""

from __future__ import annotations

import ctypes
import logging
import struct as _struct

import numpy as np

logger = logging.getLogger("models.av1.dav1d")

_SETTINGS_BYTES = 512   # sizeof(Dav1dSettings) ~ 96; headroom deliberate
_DATA_BYTES = 128       # sizeof(Dav1dData) = 72
_PIC_BYTES = 1024       # sizeof(Dav1dPicture) ~ 240
_PIC_DATA_OFF = 16
_PIC_STRIDE_OFF = 40
_PIC_W_OFF = 56
_PIC_H_OFF = 60
_PIC_LAYOUT_OFF = 64
_PIC_BPC_OFF = 68
_EAGAIN = -11
_LAYOUT_I420 = 1

_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    # .so.4 (0.7.x) kept the same API surface and picture layout as 1.0
    # (verified empirically: planes @16, strides @40, p.{w,h,layout,bpc}
    # @56..68); _get_picture's layout/bpc sanity check guards a drifted
    # build either way.
    for name in ("libdav1d.so.6", "libdav1d.so.5", "libdav1d.so.4",
                 "libdav1d.so", "dav1d"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        logger.info("libdav1d not found; AV1 conformance decode unavailable")
        return None
    lib.dav1d_data_create.restype = ctypes.c_void_p
    lib.dav1d_version.restype = ctypes.c_char_p
    _lib = lib
    return _lib


def dav1d_available() -> bool:
    return _load() is not None


class Dav1dDecoder:
    """Feed AV1 temporal units, get (Y, U, V) uint8 planes back."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("libdav1d unavailable")
        self._lib = lib
        settings = (ctypes.c_uint8 * _SETTINGS_BYTES)()
        lib.dav1d_default_settings(settings)
        self._ctx = ctypes.c_void_p()
        rc = lib.dav1d_open(ctypes.byref(self._ctx), settings)
        if rc:
            raise RuntimeError(f"dav1d_open: {rc}")

    def close(self) -> None:
        if getattr(self, "_ctx", None) and self._ctx.value:
            self._lib.dav1d_close(ctypes.byref(self._ctx))
            self._ctx = ctypes.c_void_p()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _get_picture(self):
        pic = (ctypes.c_uint8 * _PIC_BYTES)()
        rc = self._lib.dav1d_get_picture(self._ctx, pic)
        if rc == _EAGAIN:
            return None
        if rc:
            raise RuntimeError(f"dav1d_get_picture: {rc}")
        raw = bytes(pic)
        d0, d1, d2 = _struct.unpack_from("<3Q", raw, _PIC_DATA_OFF)
        s0, s1 = _struct.unpack_from("<2q", raw, _PIC_STRIDE_OFF)
        w, h, layout, bpc = _struct.unpack_from("<4i", raw, _PIC_W_OFF)
        if bpc != 8 or layout != _LAYOUT_I420:
            self._lib.dav1d_picture_unref(pic)
            raise RuntimeError(f"unexpected picture layout={layout} bpc={bpc}")

        def plane(ptr, stride, rows, cols):
            a = np.ctypeslib.as_array(
                ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)), (rows, stride))
            return a[:, :cols].copy()

        y = plane(d0, s0, h, w)
        u = plane(d1, s1, (h + 1) // 2, (w + 1) // 2)
        v = plane(d2, s1, (h + 1) // 2, (w + 1) // 2)
        self._lib.dav1d_picture_unref(pic)
        return y, u, v

    def decode(self, tu: bytes) -> list[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Decode one temporal unit; returns all pictures it produced
        (normally exactly one for a realtime stream)."""
        lib = self._lib
        data = (ctypes.c_uint8 * _DATA_BYTES)()
        ptr = lib.dav1d_data_create(data, len(tu))
        if not ptr:
            raise RuntimeError("dav1d_data_create failed")
        ctypes.memmove(ptr, tu, len(tu))
        out = []
        while True:
            rc = lib.dav1d_send_data(self._ctx, data)
            if rc == 0:
                break
            if rc == _EAGAIN:
                pic = self._get_picture()
                if pic is None:
                    raise RuntimeError("dav1d stalled: EAGAIN on both ends")
                out.append(pic)
                continue
            lib.dav1d_data_unref(data)
            raise RuntimeError(f"dav1d_send_data: {rc}")
        while True:
            pic = self._get_picture()
            if pic is None:
                break
            out.append(pic)
        return out

    def flush(self) -> list[tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Drain any delayed pictures (realtime streams have none)."""
        out = []
        while True:
            pic = self._get_picture()
            if pic is None:
                return out
            out.append(pic)
