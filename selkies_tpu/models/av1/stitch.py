"""AV1 tile-column bitstream stitching — splice N independently encoded
column strips into ONE spec-conformant frame (tile group with N tile
columns).

This is the entropy-layer half of the codec-mesh subsystem
(parallel/codec_mesh.py): the device front-end shards per tile column
across the chip mesh, each dirty column re-encodes through its own
libaom strip encoder, and this module rebuilds a single temporal unit
the client decodes as one frame.  The construction is only valid under
the constraints the strip encoders are pinned to (and this module
verifies on every frame):

* **intra-only** — intra prediction availability resets at tile
  boundaries exactly like at frame edges, so a strip's tile payload
  parses identically whether its left edge is a frame edge (strip
  encode) or a tile edge (stitched frame).  Inter strips would motion-
  compensate across the seam from edge-extension pixels that the
  stitched reference does not contain.
* **lossless** (base_q_idx=0, no deltas) — CodedLossless=1 removes the
  frame-level loop filter / CDEF / LR passes whose parameters are
  chosen per-encoder and applied ACROSS tile boundaries; with them gone
  the stitched decode is exact and `decode == source`, which is what
  makes the single-encoder oracle comparison in tests pixel-exact
  rather than approximate.
* **default CDFs** (primary_ref_frame=NONE: keyframes / intra-only
  frames) — every tile's arithmetic coder starts from spec-default
  contexts, so a payload encoded as "the only tile of a narrow frame"
  is bit-compatible with "tile k of a wide frame".

Frame sequencing mirrors the hybrid row's re-show ladder: the first
stitched frame is a KEY_FRAME (refresh all slots, carries the sequence
header), every later changed frame is a shown INTRA_ONLY_FRAME
refreshing slot 0 (showable, unlike shown keyframes — spec 5.9.2), and
unchanged frames ride the 5-byte show_existing_frame temporal unit
re-showing slot 0.  Columns whose content did not change splice their
cached tile payload back in without touching libaom at all (the
tile-column analogue of the active-map path: per-column work is decided
by the front-end's dirty map).

The header machinery below parses the strip encoders' own output
(sequence header + lossless-intra frame header, all plain f(n)/uvlc
bits) and re-emits the stitched frame header with the tile_info this
module owns.  Anything outside the constrained envelope raises
ValueError and the caller falls back to the full-frame encoder — a
malformed stitch must never reach the wire.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from selkies_tpu.models.av1.headers import (
    KEY_FRAME,
    INTRA_ONLY_FRAME,
    OBU_FRAME,
    OBU_FRAME_HEADER,
    OBU_SEQUENCE_HEADER,
    OBU_TEMPORAL_DELIMITER,
    OBU_TILE_GROUP,
    _Bits,
    iter_obus,
)

__all__ = [
    "SequenceInfo",
    "IntraFrameInfo",
    "parse_sequence_info",
    "parse_intra_frame_header",
    "extract_strip",
    "tile_columns",
    "write_stitched_frame",
    "build_stitched_tu",
    "StitchError",
]


class StitchError(ValueError):
    """The bitstream left the constrained lossless-intra envelope."""


# ---------------------------------------------------------------------------
# bit writer


class BitWriter:
    def __init__(self):
        self._bits: list[int] = []

    @property
    def pos(self) -> int:
        return len(self._bits)

    def f(self, value: int, n: int) -> None:
        if n and not 0 <= value < (1 << n):
            raise StitchError(f"value {value} does not fit in {n} bits")
        for i in range(n - 1, -1, -1):
            self._bits.append((value >> i) & 1)

    def align(self) -> None:
        while len(self._bits) % 8:
            self._bits.append(0)

    def trailing_bits(self) -> None:
        self._bits.append(1)
        self.align()

    def bytes(self) -> bytes:
        self.align()
        out = bytearray(len(self._bits) // 8)
        for i, b in enumerate(self._bits):
            if b:
                out[i >> 3] |= 0x80 >> (i & 7)
        return bytes(out)


def _leb128_encode(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def obu(otype: int, payload: bytes) -> bytes:
    """Wrap a payload as an OBU with has_size=1 (low-overhead stream)."""
    return bytes([(otype << 3) | 0x02]) + _leb128_encode(len(payload)) + payload


def temporal_delimiter() -> bytes:
    return obu(OBU_TEMPORAL_DELIMITER, b"")


# ---------------------------------------------------------------------------
# sequence header — full parse (the prefix parse in headers.py stops at
# order_hint_bits; stitching additionally needs the superres/cdef/
# restoration gates, the color config and the film-grain flag because
# they decide which frame-header bits exist)


@dataclass
class SequenceInfo:
    seq_profile: int
    still_picture: bool
    reduced_still_picture: bool
    decoder_model_info_present: bool
    equal_picture_interval: bool
    frame_presentation_time_length: int
    initial_display_delay_present: bool
    frame_width_bits: int
    frame_height_bits: int
    max_frame_width: int
    max_frame_height: int
    frame_id_numbers_present: bool
    frame_id_length: int
    delta_frame_id_length: int
    use_128x128_superblock: bool
    enable_filter_intra: bool
    enable_intra_edge_filter: bool
    enable_order_hint: bool
    order_hint_bits: int
    force_screen_content_tools: int
    force_integer_mv: int
    enable_superres: bool
    enable_cdef: bool
    enable_restoration: bool
    high_bitdepth: bool
    monochrome: bool
    separate_uv_delta_q: bool
    film_grain_params_present: bool

    @property
    def sb_size(self) -> int:
        return 128 if self.use_128x128_superblock else 64

    def tile_compatible(self, other: "SequenceInfo") -> bool:
        """Do tile payloads produced under `other` parse identically
        under this sequence header?  Compares every sequence-level field
        that gates tile-data syntax or frame-header bit presence."""
        keys = (
            "seq_profile", "reduced_still_picture",
            "decoder_model_info_present", "frame_id_numbers_present",
            "use_128x128_superblock", "enable_filter_intra",
            "enable_intra_edge_filter", "enable_order_hint",
            "order_hint_bits", "force_screen_content_tools",
            "force_integer_mv", "enable_superres", "high_bitdepth",
            "monochrome", "separate_uv_delta_q",
            "film_grain_params_present",
        )
        return all(getattr(self, k) == getattr(other, k) for k in keys)


def parse_sequence_info(payload: bytes) -> SequenceInfo:
    b = _Bits(payload)
    seq_profile = b.f(3)
    still_picture = bool(b.f(1))
    reduced = bool(b.f(1))
    decoder_model_info_present = False
    equal_picture_interval = False
    fpt_len = 0
    buffer_delay_length = 0
    initial_display_delay_present = False
    if reduced:
        b.f(5)  # seq_level_idx[0]
    else:
        if b.f(1):  # timing_info_present
            b.f(32)  # num_units_in_display_tick
            b.f(32)  # time_scale
            equal_picture_interval = bool(b.f(1))
            if equal_picture_interval:
                b.uvlc()
            decoder_model_info_present = bool(b.f(1))
            if decoder_model_info_present:
                buffer_delay_length = b.f(5) + 1
                b.f(32)
                b.f(5)
                fpt_len = b.f(5) + 1
        initial_display_delay_present = bool(b.f(1))
        op_cnt = b.f(5) + 1
        for _ in range(op_cnt):
            b.f(12)
            seq_level_idx = b.f(5)
            if seq_level_idx > 7:
                b.f(1)
            if decoder_model_info_present:
                if b.f(1):
                    b.f(buffer_delay_length)
                    b.f(buffer_delay_length)
                    b.f(1)
            if initial_display_delay_present:
                if b.f(1):
                    b.f(4)
    frame_width_bits = b.f(4) + 1
    frame_height_bits = b.f(4) + 1
    max_w = b.f(frame_width_bits) + 1
    max_h = b.f(frame_height_bits) + 1
    frame_id_numbers_present = False
    delta_len = 0
    id_len = 0
    if not reduced:
        frame_id_numbers_present = bool(b.f(1))
    if frame_id_numbers_present:
        delta_len = b.f(4) + 2
        id_len = delta_len + b.f(3) + 1
    use_128 = bool(b.f(1))
    enable_filter_intra = bool(b.f(1))
    enable_intra_edge = bool(b.f(1))
    enable_order_hint = False
    order_hint_bits = 0
    force_sct = 2
    force_imv = 2
    if not reduced:
        b.f(1)  # enable_interintra_compound
        b.f(1)  # enable_masked_compound
        b.f(1)  # enable_warped_motion
        b.f(1)  # enable_dual_filter
        enable_order_hint = bool(b.f(1))
        if enable_order_hint:
            b.f(1)  # enable_jnt_comp
            b.f(1)  # enable_ref_frame_mvs
        force_sct = 2 if b.f(1) else b.f(1)
        if force_sct > 0:
            force_imv = 2 if b.f(1) else b.f(1)
        else:
            force_imv = 2
        if enable_order_hint:
            order_hint_bits = b.f(3) + 1
    enable_superres = bool(b.f(1))
    enable_cdef = bool(b.f(1))
    enable_restoration = bool(b.f(1))
    # color_config()
    high_bitdepth = bool(b.f(1))
    if seq_profile == 2 and high_bitdepth:
        b.f(1)  # twelve_bit
    monochrome = False
    if seq_profile != 1:
        monochrome = bool(b.f(1))
    if b.f(1):  # color_description_present
        color_primaries = b.f(8)
        transfer_characteristics = b.f(8)
        matrix_coefficients = b.f(8)
    else:
        color_primaries = transfer_characteristics = matrix_coefficients = 2
    separate_uv_delta_q = False
    if monochrome:
        b.f(1)  # color_range
    elif (color_primaries == 1 and transfer_characteristics == 13
          and matrix_coefficients == 0):
        separate_uv_delta_q = bool(b.f(1))
    else:
        b.f(1)  # color_range
        if seq_profile == 0:
            pass  # 4:2:0
        elif seq_profile == 1:
            pass  # 4:4:4
        else:
            if high_bitdepth:  # profile 2, 12-bit: subsampling coded
                if b.f(1):  # subsampling_x
                    b.f(1)
        # chroma_sample_position for 4:2:0 streams
        if seq_profile != 1:
            b.f(2)
        separate_uv_delta_q = bool(b.f(1))
    film_grain = bool(b.f(1))
    return SequenceInfo(
        seq_profile=seq_profile,
        still_picture=still_picture,
        reduced_still_picture=reduced,
        decoder_model_info_present=decoder_model_info_present,
        equal_picture_interval=equal_picture_interval,
        frame_presentation_time_length=fpt_len,
        initial_display_delay_present=initial_display_delay_present,
        frame_width_bits=frame_width_bits,
        frame_height_bits=frame_height_bits,
        max_frame_width=max_w,
        max_frame_height=max_h,
        frame_id_numbers_present=frame_id_numbers_present,
        frame_id_length=id_len,
        delta_frame_id_length=delta_len,
        use_128x128_superblock=use_128,
        enable_filter_intra=enable_filter_intra,
        enable_intra_edge_filter=enable_intra_edge,
        enable_order_hint=enable_order_hint,
        order_hint_bits=order_hint_bits,
        force_screen_content_tools=force_sct,
        force_integer_mv=force_imv,
        enable_superres=enable_superres,
        enable_cdef=enable_cdef,
        enable_restoration=enable_restoration,
        high_bitdepth=high_bitdepth,
        monochrome=monochrome,
        separate_uv_delta_q=separate_uv_delta_q,
        film_grain_params_present=film_grain,
    )


# ---------------------------------------------------------------------------
# lossless-intra frame header: parse + write


@dataclass
class IntraFrameInfo:
    frame_type: int
    show_frame: bool
    error_resilient: bool
    disable_cdf_update: bool
    allow_screen_content_tools: bool
    order_hint: int
    refresh_frame_flags: int
    frame_width: int
    frame_height: int
    render_and_frame_size_different: bool
    render_width: int
    render_height: int
    allow_intrabc: bool
    disable_frame_end_update_cdf: bool
    reduced_tx_set: bool
    header_bits: int = 0  # parse position after the last header bit
    # fields that must match across every strip for the splice to parse
    SPLICE_KEYS = (
        "disable_cdf_update", "allow_screen_content_tools",
        "allow_intrabc", "disable_frame_end_update_cdf", "reduced_tx_set",
    )

    def splice_compatible(self, other: "IntraFrameInfo") -> bool:
        return all(getattr(self, k) == getattr(other, k)
                   for k in self.SPLICE_KEYS)


def parse_intra_frame_header(payload: bytes, seq: SequenceInfo) -> IntraFrameInfo:
    """Parse a shown lossless intra (KEY / INTRA_ONLY) frame header and
    return its fields plus total header bit length.  Raises StitchError
    whenever the header leaves the envelope write_stitched_frame() can
    re-emit (inter frame, superres, q>0, segmentation, qmatrix...)."""
    if seq.reduced_still_picture:
        raise StitchError("reduced still picture streams cannot be stitched")
    b = _Bits(payload)
    if b.f(1):
        raise StitchError("show_existing_frame header is not a coded frame")
    frame_type = b.f(2)
    if frame_type not in (KEY_FRAME, INTRA_ONLY_FRAME):
        raise StitchError(f"frame_type {frame_type} is not intra")
    show_frame = bool(b.f(1))
    if show_frame and seq.decoder_model_info_present and not seq.equal_picture_interval:
        b.f(seq.frame_presentation_time_length)
    if not show_frame:
        b.f(1)  # showable_frame
    if frame_type == KEY_FRAME and show_frame:
        error_resilient = True
    else:
        error_resilient = bool(b.f(1))
    disable_cdf_update = bool(b.f(1))
    if seq.force_screen_content_tools == 2:
        allow_sct = bool(b.f(1))
    else:
        allow_sct = bool(seq.force_screen_content_tools)
    if allow_sct and seq.force_integer_mv == 2:
        b.f(1)  # force_integer_mv (intra frames infer 1 regardless)
    if seq.frame_id_numbers_present:
        b.f(seq.frame_id_length)
    frame_size_override = bool(b.f(1))
    order_hint = b.f(seq.order_hint_bits)
    # intra frame: primary_ref_frame is inferred NONE, no bits
    if seq.decoder_model_info_present:
        if b.f(1):  # buffer_removal_time_present_flag
            raise StitchError("buffer_removal_time not supported")
    if frame_type == KEY_FRAME and show_frame:
        refresh = 0xFF
    else:
        refresh = b.f(8)
        if refresh == 0xFF:
            raise StitchError("intra-only frame refreshing all slots")
        if error_resilient and seq.enable_order_hint:
            for _ in range(8):
                b.f(seq.order_hint_bits)
    # FrameIsIntra: frame_size(), render_size(), allow_intrabc
    if frame_size_override:
        frame_width = b.f(seq.frame_width_bits) + 1
        frame_height = b.f(seq.frame_height_bits) + 1
    else:
        frame_width = seq.max_frame_width
        frame_height = seq.max_frame_height
    if seq.enable_superres:
        if b.f(1):  # use_superres
            raise StitchError("superres frames cannot be stitched")
    render_differs = bool(b.f(1))
    render_w, render_h = frame_width, frame_height
    if render_differs:
        render_w = b.f(16) + 1
        render_h = b.f(16) + 1
    allow_intrabc = False
    if allow_sct:
        allow_intrabc = bool(b.f(1))
    if disable_cdf_update:
        disable_frame_end_update_cdf = True
    else:
        disable_frame_end_update_cdf = bool(b.f(1))
    # tile_info() — the strip's own tiling must be a single tile
    _parse_tile_info_single(b, seq, frame_width, frame_height)
    # quantization_params() — must be lossless
    base_q_idx = b.f(8)
    if base_q_idx != 0:
        raise StitchError(f"base_q_idx {base_q_idx} != 0 (not lossless)")
    if _read_delta_q(b) != 0:
        raise StitchError("DeltaQYDc != 0")
    if not seq.monochrome:
        if seq.separate_uv_delta_q:
            diff_uv = bool(b.f(1))
        else:
            diff_uv = False
        if _read_delta_q(b) != 0 or _read_delta_q(b) != 0:
            raise StitchError("chroma delta q != 0")
        if diff_uv:
            if _read_delta_q(b) != 0 or _read_delta_q(b) != 0:
                raise StitchError("V delta q != 0")
    if b.f(1):  # using_qmatrix
        raise StitchError("qmatrix streams cannot be stitched")
    if b.f(1):  # segmentation_enabled
        raise StitchError("segmentation streams cannot be stitched")
    # base_q_idx == 0 -> no delta_q_params / delta_lf_params bits;
    # CodedLossless -> no loop filter / cdef / lr / tx_mode bits;
    # intra -> no reference mode / skip mode / warped motion bits
    reduced_tx_set = bool(b.f(1))
    # intra -> no global motion params; film grain gated by seq flag
    if seq.film_grain_params_present and (show_frame or frame_type != KEY_FRAME):
        if b.f(1):  # apply_grain
            raise StitchError("film grain streams cannot be stitched")
    return IntraFrameInfo(
        frame_type=frame_type,
        show_frame=show_frame,
        error_resilient=error_resilient,
        disable_cdf_update=disable_cdf_update,
        allow_screen_content_tools=allow_sct,
        order_hint=order_hint,
        refresh_frame_flags=refresh,
        frame_width=frame_width,
        frame_height=frame_height,
        render_and_frame_size_different=render_differs,
        render_width=render_w,
        render_height=render_h,
        allow_intrabc=allow_intrabc,
        disable_frame_end_update_cdf=disable_frame_end_update_cdf,
        reduced_tx_set=reduced_tx_set,
        header_bits=b.pos,
    )


def _read_delta_q(b: _Bits) -> int:
    if b.f(1):  # delta_coded
        v = b.f(7)  # su(7): sign bit is the high bit
        return v - 128 if v >= 64 else v
    return 0


def _tile_log2(blk: int, target: int) -> int:
    k = 0
    while (blk << k) < target:
        k += 1
    return k


def _sb_cols_rows(seq: SequenceInfo, width: int, height: int) -> tuple[int, int]:
    mi_cols = 2 * ((width + 7) >> 3)
    mi_rows = 2 * ((height + 7) >> 3)
    if seq.use_128x128_superblock:
        return (mi_cols + 31) >> 5, (mi_rows + 31) >> 5
    return (mi_cols + 15) >> 4, (mi_rows + 15) >> 4


def _min_log2_tile_cols(seq: SequenceInfo, width: int, height: int) -> tuple[int, int, int]:
    """(minLog2TileCols, maxLog2TileCols, maxLog2TileRows) per 5.9.15."""
    sb_cols, sb_rows = _sb_cols_rows(seq, width, height)
    sb_shift = 5 if seq.use_128x128_superblock else 4
    sb_size = sb_shift + 2
    max_tile_width_sb = 4096 >> sb_size
    max_tile_area_sb = (4096 * 2304) >> (2 * sb_size)
    max_log2_cols = _tile_log2(1, min(sb_cols, 64))
    max_log2_rows = _tile_log2(1, min(sb_rows, 64))
    min_log2_cols = _tile_log2(max_tile_width_sb, sb_cols)
    min_log2_tiles = max(min_log2_cols,
                         _tile_log2(max_tile_area_sb, sb_rows * sb_cols))
    return min_log2_cols, max_log2_cols, max_log2_rows, min_log2_tiles


def _parse_tile_info_single(b: _Bits, seq: SequenceInfo,
                            width: int, height: int) -> None:
    """Parse the strip's tile_info and require exactly one tile."""
    min_cols, max_cols, max_rows, min_tiles = _min_log2_tile_cols(seq, width, height)
    if min_cols > 0:
        raise StitchError("strip wider than one max-width tile")
    uniform = bool(b.f(1))
    if not uniform:
        raise StitchError("strip used explicit tile spacing")
    cols_log2 = min_cols
    while cols_log2 < max_cols:
        if b.f(1):
            cols_log2 += 1
        else:
            break
    min_rows = max(min_tiles - cols_log2, 0)
    rows_log2 = min_rows
    while rows_log2 < max_rows:
        if b.f(1):
            rows_log2 += 1
        else:
            break
    if cols_log2 or rows_log2:
        raise StitchError(
            f"strip is not single-tile (cols_log2={cols_log2}, rows_log2={rows_log2})")


def tile_columns(width: int, cols_log2: int, sb: int = 64) -> list[tuple[int, int]]:
    """The uniform-spacing column carve for `cols_log2` (spec 5.9.15):
    [(x0, w), ...] in pixels.  The actual column count can be smaller
    than 2**cols_log2 for narrow frames — callers size the mesh off
    len() of this."""
    mi_cols = 2 * ((width + 7) >> 3)
    sb_cols = (mi_cols + (sb >> 2) - 1) // (sb >> 2)
    tile_width_sb = (sb_cols + (1 << cols_log2) - 1) >> cols_log2
    out = []
    start = 0
    while start < sb_cols:
        x0 = start * sb
        end = min(start + tile_width_sb, sb_cols)
        x1 = min(end * sb, width)
        out.append((x0, x1 - x0))
        start = end
    return out


def write_stitched_frame(seq: SequenceInfo, template: IntraFrameInfo,
                         frame_type: int, refresh_frame_flags: int,
                         width: int, height: int, cols_log2: int,
                         tile_payloads: list[bytes],
                         tile_size_bytes: int = 4) -> bytes:
    """Emit one OBU_FRAME: a shown lossless intra frame of (width,
    height) with the uniform tile-column carve, splicing the given
    per-column tile payloads.  `template` supplies the strip encoders'
    shared per-frame choices (cdf update, sct, reduced_tx_set...)."""
    ncols = len(tile_columns(width, cols_log2))
    if len(tile_payloads) != ncols:
        raise StitchError(
            f"{len(tile_payloads)} payloads for {ncols} tile columns")
    w = BitWriter()
    w.f(0, 1)  # show_existing_frame
    w.f(frame_type, 2)
    w.f(1, 1)  # show_frame
    if seq.decoder_model_info_present and not seq.equal_picture_interval:
        w.f(0, seq.frame_presentation_time_length)
    # shown frames: showable inferred; KEY+show: error_resilient inferred
    if frame_type != KEY_FRAME:
        w.f(0, 1)  # error_resilient_mode (0: no ref_order_hint list)
    w.f(int(template.disable_cdf_update), 1)
    if seq.force_screen_content_tools == 2:
        w.f(int(template.allow_screen_content_tools), 1)
    if template.allow_screen_content_tools and seq.force_integer_mv == 2:
        w.f(1, 1)  # force_integer_mv (intra frames use 1)
    if seq.frame_id_numbers_present:
        raise StitchError("frame_id_numbers streams cannot be stitched")
    size_override = not (width == seq.max_frame_width
                         and height == seq.max_frame_height)
    w.f(int(size_override), 1)
    w.f(0, seq.order_hint_bits)  # order_hint
    if seq.decoder_model_info_present:
        w.f(0, 1)  # buffer_removal_time_present_flag
    if frame_type != KEY_FRAME:
        if refresh_frame_flags == 0xFF:
            raise StitchError("INTRA_ONLY frames must not refresh all slots")
        w.f(refresh_frame_flags, 8)
        # error_resilient written 0 above -> no ref_order_hint list
    if size_override:
        w.f(width - 1, seq.frame_width_bits)
        w.f(height - 1, seq.frame_height_bits)
    if seq.enable_superres:
        w.f(0, 1)  # use_superres
    w.f(0, 1)  # render_and_frame_size_different
    if template.allow_screen_content_tools:
        w.f(int(template.allow_intrabc), 1)
    if not template.disable_cdf_update:
        w.f(int(template.disable_frame_end_update_cdf), 1)
    _write_tile_info(w, seq, width, height, cols_log2, tile_size_bytes)
    # quantization_params: lossless
    w.f(0, 8)  # base_q_idx
    w.f(0, 1)  # DeltaQYDc delta_coded
    if not seq.monochrome:
        if seq.separate_uv_delta_q:
            w.f(0, 1)  # diff_uv_delta
        w.f(0, 1)  # DeltaQUDc
        w.f(0, 1)  # DeltaQUAc
    w.f(0, 1)  # using_qmatrix
    w.f(0, 1)  # segmentation_enabled
    # base_q_idx==0 -> no delta_q/delta_lf; CodedLossless -> no lf/cdef/
    # lr/tx_mode; intra -> no ref mode/skip mode/warped/global motion
    w.f(int(template.reduced_tx_set), 1)
    if seq.film_grain_params_present:
        w.f(0, 1)  # apply_grain
    # frame_header done; OBU_FRAME: byte-align then tile group
    w.align()
    ntiles = len(tile_payloads)
    body = bytearray(w.bytes())
    if ntiles > 1:
        body.append(0x00)  # tile_start_and_end_present_flag=0 + alignment
    for i, payload in enumerate(tile_payloads):
        if i < ntiles - 1:
            body += (len(payload) - 1).to_bytes(tile_size_bytes, "little")
        body += payload
    return obu(OBU_FRAME, bytes(body))


def _write_tile_info(w: BitWriter, seq: SequenceInfo, width: int,
                     height: int, cols_log2: int, tile_size_bytes: int) -> None:
    min_cols, max_cols, max_rows, min_tiles = _min_log2_tile_cols(seq, width, height)
    if not min_cols <= cols_log2 <= max_cols:
        raise StitchError(
            f"cols_log2 {cols_log2} outside [{min_cols}, {max_cols}]")
    w.f(1, 1)  # uniform_tile_spacing_flag
    for _ in range(cols_log2 - min_cols):
        w.f(1, 1)  # increment_tile_cols_log2
    if cols_log2 < max_cols:
        w.f(0, 1)
    min_rows = max(min_tiles - cols_log2, 0)
    if min_rows > 0:
        raise StitchError("frame area requires tile rows; columns only")
    rows_log2 = 0
    if rows_log2 < max_rows:
        w.f(0, 1)
    if cols_log2 > 0:
        w.f(0, cols_log2 + rows_log2)  # context_update_tile_id
        w.f(tile_size_bytes - 1, 2)


# ---------------------------------------------------------------------------
# strip extraction


@dataclass
class Strip:
    """One column encoder's parsed output."""
    seq_payload: bytes | None
    seq: SequenceInfo | None
    frame: IntraFrameInfo
    tile_payload: bytes


def extract_strip(tu: bytes, seq: SequenceInfo | None = None,
                  seq_payload: bytes | None = None) -> Strip:
    """Split a strip encoder's temporal unit into its sequence header
    (if present), parsed frame header, and raw single-tile payload."""
    frame_info = None
    tile_payload = None
    for otype, payload in iter_obus(tu):
        if otype == OBU_SEQUENCE_HEADER:
            seq_payload = payload
            seq = parse_sequence_info(payload)
        elif otype == OBU_FRAME:
            if seq is None:
                raise StitchError("frame before sequence header")
            frame_info = parse_intra_frame_header(payload, seq)
            tile_payload = payload[(frame_info.header_bits + 7) // 8:]
        elif otype in (OBU_FRAME_HEADER, OBU_TILE_GROUP):
            raise StitchError("split header/tile-group strips not supported")
    if frame_info is None or not tile_payload:
        raise StitchError("no frame OBU in strip temporal unit")
    return Strip(seq_payload=seq_payload, seq=seq, frame=frame_info,
                 tile_payload=tile_payload)


def build_stitched_tu(seq_payload: bytes | None, seq: SequenceInfo,
                      template: IntraFrameInfo, frame_type: int,
                      refresh_frame_flags: int, width: int, height: int,
                      cols_log2: int, tile_payloads: list[bytes]) -> bytes:
    """One temporal unit: TD [+ sequence header on keyframes] + stitched
    frame OBU."""
    out = temporal_delimiter()
    if seq_payload is not None:
        out += obu(OBU_SEQUENCE_HEADER, seq_payload)
    out += write_stitched_frame(seq, template, frame_type,
                                refresh_frame_flags, width, height,
                                cols_log2, tile_payloads)
    return out
