"""tpuav1enc — the AV1 encoder row with the framework's capture-delta
front-end (reference rows: av1enc/rav1enc/svtav1enc,
gstwebrtc_app.py:741-783; rtpav1pay :917-938).

Architecture note (why this row is a hybrid, mirroring tpuvp9enc): AV1
entropy coding is an adaptive multi-symbol arithmetic coder whose
default CDF tables are normative DATA from the spec — not derivable
computationally the way H.264's CAVLC tables are (tables.py regenerates
those from closed-form rules). The entropy back-end is therefore libaom
(exactly what the reference's av1enc element wraps; models/libaom_enc.py
is the ctypes row). What the framework adds is the same front-end the
TPU H.264 path proved out:

* per-MB change classification against the previous capture — ON DEVICE
  (models/hybrid_frontend.py: jitted dirty-MB step + the H.264 path's
  coarse ME voting for scroll hints) on PCIe-local accelerators, or
  FramePrep's native memcmp (the XDamage analogue) on the relay;
* UNCHANGED frames never reach libaom at all: they encode as a 5-byte
  show_existing_frame temporal unit (spec 5.9.2) re-showing the slot
  the previous frame landed in. Which slot that is comes from parsing
  refresh_frame_flags out of our own bitstream (models/av1/headers.py)
  — not from assuming libaom's slot rotation. Shown inter frames are
  always re-showable (spec derives showable_frame = frame_type !=
  KEY_FRAME); after a keyframe the first repeat falls back to an
  all-inactive ACTIVE MAP encode (every block skips from reference),
  which is cheap and immediately becomes re-showable. The re-show path
  is also bit-exact: unlike an all-skip encode, no loop filter / CDEF
  pass re-runs over the image, so idle desktops cannot blur over time;
* PARTIALLY-changed frames install a per-16x16-block active map from
  the dirty-tile classification (AOME_SET_ACTIVEMAP): libaom's ME/RD/
  transform run only over pixels that moved — the front-end decides
  per-block work, the entropy coder stays libaom's.

Conformance: tests/test_av1.py decodes the mixed stream with ctypes
libdav1d (an independent decoder — models/av1/dav1d.py) and asserts
re-shown frames are pixel-identical and active-map frames track the
source.
"""

from __future__ import annotations

import logging
import time

import numpy as np

from selkies_tpu.models.av1 import headers
from selkies_tpu.models.hybrid_frontend import HybridFrontendMixin
from selkies_tpu.models.libaom_enc import LibAomEncoder
from selkies_tpu.models.stats import FrameStats

logger = logging.getLogger("models.av1")


class TPUAV1Encoder(HybridFrontendMixin, LibAomEncoder):
    """LibAomEncoder plus the capture-delta front-end (device or host —
    models/hybrid_frontend.py)."""

    codec = "av1"

    def __init__(self, width: int, height: int, fps: int = 60,
                 bitrate_kbps: int = 2000, cpu_used: int = 10,
                 frontend: str | None = None):
        super().__init__(width=width, height=height, fps=fps,
                         bitrate_kbps=bitrate_kbps, cpu_used=cpu_used)
        self._init_frontend(width, height, frontend)
        self._have_ref = False
        self._map_active = False
        self._seq: headers.SequenceHeader | None = None
        self._show_slot: int | None = None  # re-showable slot, or None
        self.static_frames = 0
        self.active_map_frames = 0

    def force_keyframe(self) -> None:
        super().force_keyframe()
        # the next capture must re-encode even if unchanged
        self._have_ref = False
        self._show_slot = None

    def _track_output(self, au: bytes) -> None:
        """Parse our own bitstream: which slot can re-show this frame?"""
        try:
            self._seq, fh = headers.scan_temporal_unit(au, self._seq)
        except (ValueError, IndexError) as exc:
            # IndexError: truncated OBU drives the bit reader past the
            # end — same degrade as a malformed header
            logger.warning("AV1 header parse failed (%s); re-show disabled", exc)
            self._show_slot = None
            return
        if (fh is not None and fh.show_frame and fh.showable_frame
                and fh.refresh_frame_flags):
            self._show_slot = (fh.refresh_frame_flags
                               & -fh.refresh_frame_flags).bit_length() - 1
        else:
            self._show_slot = None

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        dirty = self._classify_mbs(np.asarray(frame))
        unchanged = dirty is not None and not dirty.any()
        if (unchanged and self._have_ref and not self._force_idr
                and self._show_slot is not None):
            t0 = time.perf_counter()
            au = headers.show_existing_frame_tu(self._show_slot)
            self.static_frames += 1
            self.last_stats = FrameStats(
                frame_index=self.frame_index, idr=False, qp=self.qp,
                bytes=len(au),
                device_ms=self.frontend_device_ms or
                (time.perf_counter() - t0) * 1e3,
                pack_ms=0.0,
                skipped_mbs=(self.height // 16) * (self.width // 16),
            )
            self.frame_index += 1
            return au
        restrict: np.ndarray | None = None
        if unchanged and self._have_ref and not self._force_idr:
            # post-keyframe repeat: keyframes can't be re-shown (spec
            # 5.9.2), so encode one all-skip inter frame — cheap, and
            # every later repeat rides the 5-byte path above
            restrict = np.zeros(((self.height + 15) // 16,
                                 (self.width + 15) // 16), np.uint8)
            self.static_frames += 1
        elif (dirty is not None and self._have_ref and not self._force_idr
              and dirty.any() and not dirty.all()):
            restrict = dirty
            self.active_map_frames += 1
        if restrict is not None and self.set_active_map(restrict):
            self._map_active = True
        try:
            au = super().encode_frame(frame, qp)
        finally:
            if self._map_active:
                # never leave a stale mask installed across keyframes or
                # error paths: correctness beats the tiny per-frame call
                self.set_active_map(None)
                self._map_active = False
        self._track_output(au)
        if self.last_stats is not None and self.frontend_device_ms:
            self.last_stats.device_ms += self.frontend_device_ms
        self._have_ref = True
        return au
