"""Host-side frame preparation (ctypes binding for native/frameprep.cc).

Converts captured BGRx frames to padded I420 planes on the host CPU and
tracks per-band dirty state vs the previous capture. Rationale: the
host↔device link (tunnel or PCIe) is the pipeline bottleneck
(tools/profile_link.py) — uploading I420 is 2.7x less data than BGRx, and
the dirty-band map feeds the encoder's static-frame fast path today (an
unchanged capture encodes as an all-skip P slice with zero device work;
partial-band uploads are the next step). The reference leans on
ximagesrc's XDamage for the same effect (gstwebrtc_app.py:210-241).

The uplink front-end is FUSED and band-parallel (ISSUE 12): one native
pass per band computes the dirty-tile map, updates the previous-frame
state for dirty tiles only, and emits the tile-cache content hashes —
replacing the serial band_diff + tile_diff + full-frame np.copyto +
tile_hash sequence (three full-frame memory passes). Bands are
independent 16-row stripes, so the scan fans out across a small shared
worker pool (``SELKIES_FRONTEND_WORKERS``); the sharded result is
byte-identical to the serial scan, which remains available as the
oracle behind ``SELKIES_PARALLEL_FRONTEND=0``. Capture layers that know
the damaged region (X11 XDamage, the synthetic traces' dirty boxes) can
pass ``damage`` rect hints: damage rects are authoritative SUPERSETS of
changed pixels, so bands/tiles outside them skip classification and the
previous-frame update entirely — with a forced periodic full scan
(``SELKIES_DAMAGE_FULL_SCAN``) as the safety ratchet against a buggy
hint source.

Contiguity contract: every converter and the scan walk raw BGRx bytes
via ctypes, so frames must arrive C-contiguous. The capture boundary
guarantees this (X11 grabs materialize via np.ascontiguousarray, the
synthetic sources build contiguous arrays); a non-contiguous frame from
a direct caller is copied here defensively — at 3.7 MB/frame (720p)
that copy is exactly the kind of hidden full-frame pass this module
exists to avoid, so keep captures contiguous.

The conversion is bit-exact with the device path (ops/colorspace.py); a
pure-numpy fallback keeps headless test environments working without the
shared library.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

import numpy as np

logger = logging.getLogger("models.frameprep")

_NATIVE_DIR = os.environ.get("SELKIES_NATIVE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native"
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libframeprep.so")

_lib = None
_lib_tried = False

BAND_ROWS = 16  # dirty-detection granularity = one MB row


def parallel_frontend_enabled() -> bool:
    """SELKIES_PARALLEL_FRONTEND gate (default on): 0 forces the serial
    single-call scan — the byte-identity oracle for the sharded path."""
    return os.environ.get("SELKIES_PARALLEL_FRONTEND", "1") != "0"


def frontend_workers() -> int:
    """Front-end scan/convert pool width. Sized like the h264-pack pool
    (bounded by host cores); SELKIES_FRONTEND_WORKERS overrides. The
    scan shards 16-row bands, so more workers than band-chunks is waste
    — 4 covers the measured knee on desktop geometries."""
    env = os.environ.get("SELKIES_FRONTEND_WORKERS", "")
    if env:
        try:
            return max(1, min(16, int(env)))
        except ValueError:
            logger.warning("SELKIES_FRONTEND_WORKERS=%r not an integer; "
                           "using default", env)
    return max(1, min(os.cpu_count() or 2, 4))


def damage_full_scan_interval() -> int:
    """Every Nth scan ignores damage hints and walks the whole frame —
    the safety ratchet bounding how long a wrong (non-superset) hint
    source could desync the previous-frame state. 0 disables the
    ratchet (trusted hint sources only)."""
    env = os.environ.get("SELKIES_DAMAGE_FULL_SCAN", "")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            logger.warning("SELKIES_DAMAGE_FULL_SCAN=%r not an integer; "
                           "using default", env)
    return 120


# below this many bands per worker the thread fan-out overhead exceeds
# the memcmp it parallelizes (a 720p frame is 45 bands)
_MIN_BANDS_PER_CHUNK = 8

_fe_pool: ThreadPoolExecutor | None = None
_fe_pool_lock = threading.Lock()


def _frontend_pool() -> ThreadPoolExecutor:
    """Shared process-wide front-end pool (scan shards + band converts).
    One pool serves every encoder in the process: front-end work is
    bursty per frame, and per-encoder pools would oversubscribe a fleet
    host the same way per-session pack pools used to (PERF.md)."""
    global _fe_pool
    with _fe_pool_lock:
        if _fe_pool is None:
            _fe_pool = ThreadPoolExecutor(
                max_workers=frontend_workers(),
                thread_name_prefix="frontend")
        return _fe_pool


def tile_width_for(width: int) -> int:
    """The delta-tile column width tpuh264enc uses for `width`: the
    largest power-of-two tile that divides the padded plane (pad_w
    itself degenerates to full bands). Single definition — the encoder,
    the trace generators (pipeline/elements.py), and the link-byte
    profiler all derive geometry from here."""
    pad_w = (width + 15) // 16 * 16
    return next((t for t in (128, 64, 32, 16) if pad_w % t == 0), pad_w)


def delta_buckets_for(width: int, height: int) -> tuple[int, ...]:
    """tpuh264enc's delta bucket ladder for a geometry: dirty-tile
    counts round up to one of these; frames dirtier than the largest
    bucket take the full-upload path. Single definition (see
    tile_width_for) so tools/tests sizing content to 'fits the delta
    path' cannot drift from the encoder."""
    pad_h = (height + 15) // 16 * 16
    pad_w = (width + 15) // 16 * 16
    ntiles = (pad_h // 16) * (pad_w // tile_width_for(width))
    return tuple(
        b for b in (8, 16, 32, 64, 128, 256, 512) if b <= ntiles // 2
    ) or ((ntiles // 2,) if ntiles >= 2 else ())


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    # always invoke make: it is a no-op when fresh and rebuilds a stale
    # .so after a frameprep.cc change (new exported symbols). A build
    # failure (no toolchain) is not fatal — a prebuilt .so may exist.
    if os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-s", "libframeprep.so"],
                check=True, capture_output=True, timeout=120,
            )
        except (OSError, subprocess.SubprocessError) as exc:
            logger.warning("could not (re)build libframeprep.so (%s); trying prebuilt", exc)
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as exc:
        logger.warning("could not load libframeprep.so (%s); numpy fallback", exc)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.bgrx_to_i420_pad.restype = None
    lib.bgrx_to_i420_pad.argtypes = [u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                     ctypes.c_int, u8p, u8p, u8p]
    lib.band_diff.restype = ctypes.c_int
    lib.band_diff.argtypes = [u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p]
    try:
        lib.tile_diff.restype = ctypes.c_int
        lib.tile_diff.argtypes = [u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, u8p, u8p]
        lib.bgrx_to_i420_tiles.restype = None
        lib.bgrx_to_i420_tiles.argtypes = [u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                           ctypes.c_int, i32p, ctypes.c_int, u8p, u8p, u8p]
        lib.tile_hash.restype = None
        lib.tile_hash.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_uint64)]
        lib.frontend_scan.restype = ctypes.c_int
        lib.frontend_scan.argtypes = [
            u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            u8p, ctypes.POINTER(ctypes.c_uint64)]
        lib.gather_tiles.restype = None
        lib.gather_tiles.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                     ctypes.c_int, i32p, ctypes.c_int, u8p]
        lib.bgrx_to_i420_pad_rows.restype = None
        lib.bgrx_to_i420_pad_rows.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, u8p, u8p, u8p]
        lib.pad_i420_bottom.restype = None
        lib.pad_i420_bottom.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p, u8p, u8p]
    except AttributeError:
        pass  # stale .so without the tile converters; numpy fallback used
    _lib = lib
    return lib


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _numpy_convert_pad(frame: np.ndarray, ph: int, pw: int):
    """Fallback mirror of bgrx_to_i420_pad (and of ops/colorspace.py)."""
    f = frame.astype(np.int32)
    r, g, b = f[..., 2], f[..., 1], f[..., 0]
    y = np.clip(((66 * r + 129 * g + 25 * b + 128) >> 8) + 16, 16, 235)
    u = np.clip(((-38 * r - 74 * g + 112 * b + 128) >> 8) + 128, 16, 240)
    v = np.clip(((112 * r - 94 * g - 18 * b + 128) >> 8) + 128, 16, 240)
    h, w = y.shape

    def sub(p):
        return (p.reshape(h // 2, 2, w // 2, 2).sum(axis=(1, 3)) + 2) >> 2

    u, v = sub(u), sub(v)

    def pad(p, th, tw):
        return np.pad(p, ((0, th - p.shape[0]), (0, tw - p.shape[1])), mode="edge")

    return (
        pad(y, ph, pw).astype(np.uint8),
        pad(u, ph // 2, pw // 2).astype(np.uint8),
        pad(v, ph // 2, pw // 2).astype(np.uint8),
    )


@dataclass
class ScanResult:
    """One fused front-end scan's outputs (FramePrep.scan).

    tiles: (nbands, ntiles) bool dirty map. hashes: (nbands, ntiles)
    uint64 tile-cache content hashes, valid ONLY at dirty cacheable
    tiles (None unless want_hashes). full_scan: True when the whole
    frame was walked (no damage hint, or the periodic ratchet fired)."""

    tiles: np.ndarray
    hashes: np.ndarray | None
    full_scan: bool


class FramePrep:
    """Per-stream host prep state: conversion buffers + previous frame."""

    def __init__(self, width: int, height: int, pad_w: int, pad_h: int, nslots: int = 4):
        self.width, self.height = width, height
        # odd capture geometry (DCI projectors, xrandr panning splits)
        # cannot carry 4:2:0 chroma siting — the 2x2 subsample and the
        # native converter both walk pixel quads. Odd frames are edge-
        # replicated to even dims on the host before conversion; the
        # extra column/row lands inside the encoder's 16-multiple pad
        # region (the capture layer normally pads BEFORE the encoder is
        # built — pipeline/capture.pad_frame_to_even — this is the
        # defensive mirror for direct FramePrep users).
        self._even_w = width + (width & 1)
        self._even_h = height + (height & 1)
        if pad_w < self._even_w or pad_h < self._even_h:
            raise ValueError(
                f"pad {pad_w}x{pad_h} cannot hold the even-padded "
                f"{self._even_w}x{self._even_h} frame")
        self.pad_w, self.pad_h = pad_w, pad_h
        self._lib = _load()
        # rotating output buffers: the encoder pipelines dispatches, and an
        # async h2d transfer may still be reading a plane when the next
        # capture converts — each convert() writes a different slot, so
        # nslots must cover every possibly-in-flight upload plus one
        self._nslots = max(2, int(nslots))
        # conversion slots allocate lazily: change-detection-only users
        # (the VP9 hybrid row) never call convert() and would otherwise
        # carry ~6 MB of dead plane buffers per encoder
        self._bufs: list | None = None
        self._slot = 0
        self._prev: np.ndarray | None = None
        self.nbands = (height + BAND_ROWS - 1) // BAND_ROWS
        self._bands = np.empty(self.nbands, np.uint8)
        # damage-hint safety ratchet (scan): every Nth scan is forced full
        self._scan_count = 0
        self._full_every = damage_full_scan_interval()

    @property
    def native(self) -> bool:
        return self._lib is not None

    def convert(self, frame: np.ndarray):
        """(H, W, 4) BGRx uint8 -> (y, u, v) padded planes.

        Buffers rotate over 4 slots, so up to 4 conversions can be in
        flight (async device uploads) before a slot is overwritten."""
        if frame.shape != (self.height, self.width, 4):
            raise ValueError(f"frame {frame.shape} != {(self.height, self.width, 4)}")
        if (self._even_h, self._even_w) != (self.height, self.width):
            frame = np.pad(frame, ((0, self._even_h - self.height),
                                   (0, self._even_w - self.width), (0, 0)),
                           mode="edge")
        if not frame.flags["C_CONTIGUOUS"]:
            frame = np.ascontiguousarray(frame)
        if self._bufs is None:
            self._bufs = [
                (
                    np.empty((self.pad_h, self.pad_w), np.uint8),
                    np.empty((self.pad_h // 2, self.pad_w // 2), np.uint8),
                    np.empty((self.pad_h // 2, self.pad_w // 2), np.uint8),
                )
                for _ in range(self._nslots)
            ]
        y, u, v = self._bufs[self._slot]
        self._slot = (self._slot + 1) % self._nslots
        if self._lib is not None:
            lib = self._lib
            eh, ew = self._even_h, self._even_w
            workers = (frontend_workers()
                       if parallel_frontend_enabled()
                       and hasattr(lib, "bgrx_to_i420_pad_rows") else 1)
            # band-parallel conversion: workers convert disjoint even-row
            # ranges of the same padded planes (byte-identical to the
            # single-call path); the bottom padding replicates afterwards
            nchunks = min(workers, max(1, eh // (2 * 16 * _MIN_BANDS_PER_CHUNK)))
            if nchunks <= 1:
                lib.bgrx_to_i420_pad(
                    _u8p(frame), eh, ew, self.pad_h,
                    self.pad_w, _u8p(y), _u8p(u), _u8p(v),
                )
            else:
                step = (-(-eh // (2 * nchunks))) * 2  # even row chunks
                futs = [
                    _frontend_pool().submit(
                        lib.bgrx_to_i420_pad_rows,
                        _u8p(frame), eh, ew, self.pad_h, self.pad_w,
                        r0, min(r0 + step, eh), _u8p(y), _u8p(u), _u8p(v))
                    for r0 in range(0, eh, step)
                ]
                for f in futs:
                    f.result()
                lib.pad_i420_bottom(eh, self.pad_h, self.pad_w,
                                    _u8p(y), _u8p(u), _u8p(v))
        else:
            y2, u2, v2 = _numpy_convert_pad(frame, self.pad_h, self.pad_w)
            y[:], u[:], v[:] = y2, u2, v2
        return y, u, v

    def reset(self) -> None:
        """Forget the previous frame: the next dirty_bands() reports
        everything dirty (used by encoder prewarm / stream restart)."""
        self._prev = None

    def convert_tiles(self, frame: np.ndarray, idx: np.ndarray, tile_w: int):
        """Convert only the 16-row x tile_w-col tiles listed in idx
        (int32, band*1024 + tile) to packed I420 tile buffers:
        (k, 16, tile_w) luma and (k, 8, tile_w/2) chroma, bit-exact with
        the same region of a full convert(). tile_w must divide pad_w and
        be a multiple of 16; tile_w == pad_w degenerates to bands."""
        if frame.shape != (self.height, self.width, 4):
            raise ValueError(f"frame {frame.shape} != {(self.height, self.width, 4)}")
        if tile_w % 16 or self.pad_w % tile_w:
            raise ValueError(f"tile_w {tile_w} must be a 16-multiple dividing {self.pad_w}")
        # odd geometry: same even-pad normalization as convert() — the
        # quad-walking converters (native and numpy) must never see an
        # odd plane, whichever entry point a direct FramePrep user hits
        if (self._even_h, self._even_w) != (self.height, self.width):
            frame = np.pad(frame, ((0, self._even_h - self.height),
                                   (0, self._even_w - self.width), (0, 0)),
                           mode="edge")
        if not frame.flags["C_CONTIGUOUS"]:
            frame = np.ascontiguousarray(frame)
        idx = np.ascontiguousarray(idx, np.int32)
        k = len(idx)
        yb = np.empty((k, 16, tile_w), np.uint8)
        ub = np.empty((k, 8, tile_w // 2), np.uint8)
        vb = np.empty((k, 8, tile_w // 2), np.uint8)
        if self._lib is not None and hasattr(self._lib, "bgrx_to_i420_tiles"):
            self._lib.bgrx_to_i420_tiles(
                _u8p(frame), self._even_h, self._even_w, self.pad_w, tile_w,
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), k,
                _u8p(yb), _u8p(ub), _u8p(vb),
            )
        else:
            y, u, v = _numpy_convert_pad(frame, self.pad_h, self.pad_w)
            ctw = tile_w // 2
            for i, t in enumerate(idx):
                band, tile = int(t) // 1024, int(t) % 1024
                yb[i] = y[band * 16:band * 16 + 16, tile * tile_w:(tile + 1) * tile_w]
                ub[i] = u[band * 8:band * 8 + 8, tile * ctw:(tile + 1) * ctw]
                vb[i] = v[band * 8:band * 8 + 8, tile * ctw:(tile + 1) * ctw]
        return yb, ub, vb

    # -- fused band-parallel dirty scan (ISSUE 12) ----------------------

    def _damage_box(self, damage, tile_w: int) -> tuple[int, int, int, int]:
        """Damage rects -> inclusive-exclusive (b0, b1, t0, t1) bounding
        box in band/tile units, clipped to the frame. Rects are
        (x, y, w, h) pixel tuples; an empty iterable means "nothing
        changed" (box collapses to zero bands)."""
        ntiles = (self.width + tile_w - 1) // tile_w
        b0, b1, t0, t1 = self.nbands, 0, ntiles, 0
        for (x, y, w, h) in damage:
            if w <= 0 or h <= 0:
                continue
            x0 = max(0, int(x))
            y0 = max(0, int(y))
            x1 = min(self.width, int(x) + int(w))
            y1 = min(self.height, int(y) + int(h))
            if x1 <= x0 or y1 <= y0:
                continue
            b0 = min(b0, y0 // BAND_ROWS)
            b1 = max(b1, (y1 + BAND_ROWS - 1) // BAND_ROWS)
            t0 = min(t0, x0 // tile_w)
            t1 = max(t1, (x1 + tile_w - 1) // tile_w)
        if b1 <= b0 or t1 <= t0:
            return 0, 0, 0, 0
        return b0, b1, t0, t1

    def _scan_chunk_numpy(self, frame: np.ndarray, tile_w: int,
                          b0: int, b1: int, t0: int, t1: int,
                          out: np.ndarray, hashes: np.ndarray | None) -> None:
        """Pure-numpy mirror of native frontend_scan for bands [b0, b1) x
        tiles [t0, t1): vectorized reshape + any-reduction instead of the
        historical O(ntiles) per-tile Python loop, prev updated for dirty
        tiles only, tile_hash_np values for dirty cacheable tiles."""
        h, w = self.height, self.width
        r0, r1 = b0 * BAND_ROWS, min(b1 * BAND_ROWS, h)
        c0, c1 = t0 * tile_w, min(t1 * tile_w, w)
        nb, nt = b1 - b0, t1 - t0
        neq = (frame[r0:r1, c0:c1] != self._prev[r0:r1, c0:c1]).any(axis=2)
        pad = np.zeros((nb * BAND_ROWS, nt * tile_w), bool)
        pad[: r1 - r0, : c1 - c0] = neq
        dirty = pad.reshape(nb, BAND_ROWS, nt, tile_w).any(axis=(1, 3))
        out[b0:b1, t0:t1] = dirty
        band_i, tile_i = np.nonzero(dirty)
        full_bands = h // BAND_ROWS
        full_tiles = w // tile_w
        raws = []
        hash_pos = []
        for bi, ti in zip(band_i + b0, tile_i + t0):
            rr0, rr1 = bi * BAND_ROWS, min((bi + 1) * BAND_ROWS, h)
            cc0, cc1 = ti * tile_w, min((ti + 1) * tile_w, w)
            if hashes is not None and bi < full_bands and ti < full_tiles:
                raws.append(frame[rr0:rr1, cc0:cc1].reshape(-1))
                hash_pos.append((bi, ti))
            self._prev[rr0:rr1, cc0:cc1] = frame[rr0:rr1, cc0:cc1]
        if raws:
            from selkies_tpu.models.tilecache import tile_hash_np

            hs = tile_hash_np(np.stack(raws))
            for (bi, ti), hv in zip(hash_pos, hs):
                hashes[bi, ti] = hv

    def scan(self, frame: np.ndarray, tile_w: int, *, damage=None,
             want_hashes: bool = False) -> "ScanResult | None":
        """Fused front-end scan: dirty-tile map + previous-frame update
        (+ tile-cache content hashes) in one pass over the frame bytes.

        Returns None on the first frame (prev seeded, everything dirty —
        the caller takes the full-upload path). ``damage`` is an optional
        iterable of (x, y, w, h) pixel rects known to be a SUPERSET of
        all changed pixels (XDamage / synthetic-trace dirty boxes): the
        scan is bounded to their band/tile bounding box and everything
        outside reports clean without being read — exact because a
        superset guarantees outside bytes are unchanged. Every
        ``SELKIES_DAMAGE_FULL_SCAN``-th call ignores the hints (safety
        ratchet). ``want_hashes`` additionally emits tile_hash_np-
        compatible content hashes for dirty CACHEABLE tiles (fully
        inside the unpadded capture — the tile cache's rule); other
        entries of the hash array are unspecified.

        Bands shard across the shared front-end pool
        (SELKIES_FRONTEND_WORKERS) unless SELKIES_PARALLEL_FRONTEND=0;
        the sharded output is byte-identical to the serial scan
        (tests/test_frontend_parallel.py)."""
        if frame.shape != (self.height, self.width, 4):
            raise ValueError(f"frame {frame.shape} != {(self.height, self.width, 4)}")
        if not frame.flags["C_CONTIGUOUS"]:
            frame = np.ascontiguousarray(frame)
        ntiles = (self.width + tile_w - 1) // tile_w
        if self._prev is None:
            self._prev = frame.copy()
            return None
        self._scan_count += 1
        full_scan = (
            damage is None
            or (self._full_every > 0
                and self._scan_count % self._full_every == 0))
        if full_scan:
            box = (0, self.nbands, 0, ntiles)
        else:
            box = self._damage_box(damage, tile_w)
        b0, b1, t0, t1 = box
        out = np.zeros((self.nbands, ntiles), np.uint8)
        hashes = np.zeros((self.nbands, ntiles), np.uint64) if want_hashes else None
        if b1 > b0:
            native = self._lib is not None and hasattr(self._lib, "frontend_scan")
            if native:
                hp = (hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
                      if hashes is not None else None)
                workers = frontend_workers() if parallel_frontend_enabled() else 1
                nchunks = min(workers, max(1, (b1 - b0) // _MIN_BANDS_PER_CHUNK))
                if nchunks <= 1:
                    self._lib.frontend_scan(
                        _u8p(frame), _u8p(self._prev), self.height, self.width,
                        BAND_ROWS, tile_w, b0, b1, t0, t1, _u8p(out), hp)
                else:
                    # contiguous band chunks; the C call releases the GIL,
                    # and chunks touch disjoint rows of prev/out/hashes
                    step = -(-(b1 - b0) // nchunks)
                    spans = [(b0 + i * step, min(b0 + (i + 1) * step, b1))
                             for i in range(nchunks)]
                    futs = [
                        _frontend_pool().submit(
                            self._lib.frontend_scan,
                            _u8p(frame), _u8p(self._prev), self.height,
                            self.width, BAND_ROWS, tile_w, s0, s1, t0, t1,
                            _u8p(out), hp)
                        for s0, s1 in spans if s1 > s0
                    ]
                    for f in futs:
                        f.result()
            else:
                self._scan_chunk_numpy(frame, tile_w, b0, b1, t0, t1,
                                       out, hashes)
        return ScanResult(tiles=out.astype(bool), hashes=hashes,
                          full_scan=bool(full_scan))

    def dirty_tiles(self, frame: np.ndarray, tile_w: int,
                    damage=None) -> np.ndarray | None:
        """Which 16-row x tile_w-col tiles changed vs the previous call's
        frame: (nbands, ntiles) bool, or None on the first frame. tile_w
        is in LUMA columns; detection compares the 4*tile_w BGRx bytes.
        Advances the previous-frame state for the changed tiles (clean
        tiles are already byte-equal, so the stored previous frame stays
        byte-identical to a full copy)."""
        res = self.scan(frame, tile_w, damage=damage)
        return None if res is None else res.tiles

    def dirty_bands(self, frame: np.ndarray, damage=None) -> np.ndarray | None:
        """Which 16-row bands changed vs the previous call's frame.

        Returns a bool array of shape (nbands,), or None on the first frame
        (everything dirty). Band granularity is the degenerate full-width
        tile of the fused scan."""
        res = self.scan(frame, self.width, damage=damage)
        return None if res is None else res.tiles[:, 0]
