"""Host-side frame preparation (ctypes binding for native/frameprep.cc).

Converts captured BGRx frames to padded I420 planes on the host CPU and
tracks per-band dirty state vs the previous capture. Rationale: the
host↔device link (tunnel or PCIe) is the pipeline bottleneck
(tools/profile_link.py) — uploading I420 is 2.7x less data than BGRx, and
the dirty-band map feeds the encoder's static-frame fast path today (an
unchanged capture encodes as an all-skip P slice with zero device work;
partial-band uploads are the next step). The reference leans on
ximagesrc's XDamage for the same effect (gstwebrtc_app.py:210-241).

The conversion is bit-exact with the device path (ops/colorspace.py); a
pure-numpy fallback keeps headless test environments working without the
shared library.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess

import numpy as np

logger = logging.getLogger("models.frameprep")

_NATIVE_DIR = os.environ.get("SELKIES_NATIVE_DIR") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native"
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libframeprep.so")

_lib = None
_lib_tried = False

BAND_ROWS = 16  # dirty-detection granularity = one MB row


def tile_width_for(width: int) -> int:
    """The delta-tile column width tpuh264enc uses for `width`: the
    largest power-of-two tile that divides the padded plane (pad_w
    itself degenerates to full bands). Single definition — the encoder,
    the trace generators (pipeline/elements.py), and the link-byte
    profiler all derive geometry from here."""
    pad_w = (width + 15) // 16 * 16
    return next((t for t in (128, 64, 32, 16) if pad_w % t == 0), pad_w)


def delta_buckets_for(width: int, height: int) -> tuple[int, ...]:
    """tpuh264enc's delta bucket ladder for a geometry: dirty-tile
    counts round up to one of these; frames dirtier than the largest
    bucket take the full-upload path. Single definition (see
    tile_width_for) so tools/tests sizing content to 'fits the delta
    path' cannot drift from the encoder."""
    pad_h = (height + 15) // 16 * 16
    pad_w = (width + 15) // 16 * 16
    ntiles = (pad_h // 16) * (pad_w // tile_width_for(width))
    return tuple(
        b for b in (8, 16, 32, 64, 128, 256, 512) if b <= ntiles // 2
    ) or ((ntiles // 2,) if ntiles >= 2 else ())


def _load() -> ctypes.CDLL | None:
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    # always invoke make: it is a no-op when fresh and rebuilds a stale
    # .so after a frameprep.cc change (new exported symbols). A build
    # failure (no toolchain) is not fatal — a prebuilt .so may exist.
    if os.path.exists(os.path.join(_NATIVE_DIR, "Makefile")):
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-s", "libframeprep.so"],
                check=True, capture_output=True, timeout=120,
            )
        except (OSError, subprocess.SubprocessError) as exc:
            logger.warning("could not (re)build libframeprep.so (%s); trying prebuilt", exc)
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError as exc:
        logger.warning("could not load libframeprep.so (%s); numpy fallback", exc)
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.bgrx_to_i420_pad.restype = None
    lib.bgrx_to_i420_pad.argtypes = [u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                     ctypes.c_int, u8p, u8p, u8p]
    lib.band_diff.restype = ctypes.c_int
    lib.band_diff.argtypes = [u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p]
    try:
        lib.tile_diff.restype = ctypes.c_int
        lib.tile_diff.argtypes = [u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                  ctypes.c_int, u8p, u8p]
        lib.bgrx_to_i420_tiles.restype = None
        lib.bgrx_to_i420_tiles.argtypes = [u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                           ctypes.c_int, i32p, ctypes.c_int, u8p, u8p, u8p]
        lib.tile_hash.restype = None
        lib.tile_hash.argtypes = [u8p, ctypes.c_int, ctypes.c_int,
                                  ctypes.POINTER(ctypes.c_uint64)]
    except AttributeError:
        pass  # stale .so without the tile converters; numpy fallback used
    _lib = lib
    return lib


def _u8p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _numpy_convert_pad(frame: np.ndarray, ph: int, pw: int):
    """Fallback mirror of bgrx_to_i420_pad (and of ops/colorspace.py)."""
    f = frame.astype(np.int32)
    r, g, b = f[..., 2], f[..., 1], f[..., 0]
    y = np.clip(((66 * r + 129 * g + 25 * b + 128) >> 8) + 16, 16, 235)
    u = np.clip(((-38 * r - 74 * g + 112 * b + 128) >> 8) + 128, 16, 240)
    v = np.clip(((112 * r - 94 * g - 18 * b + 128) >> 8) + 128, 16, 240)
    h, w = y.shape

    def sub(p):
        return (p.reshape(h // 2, 2, w // 2, 2).sum(axis=(1, 3)) + 2) >> 2

    u, v = sub(u), sub(v)

    def pad(p, th, tw):
        return np.pad(p, ((0, th - p.shape[0]), (0, tw - p.shape[1])), mode="edge")

    return (
        pad(y, ph, pw).astype(np.uint8),
        pad(u, ph // 2, pw // 2).astype(np.uint8),
        pad(v, ph // 2, pw // 2).astype(np.uint8),
    )


class FramePrep:
    """Per-stream host prep state: conversion buffers + previous frame."""

    def __init__(self, width: int, height: int, pad_w: int, pad_h: int, nslots: int = 4):
        self.width, self.height = width, height
        # odd capture geometry (DCI projectors, xrandr panning splits)
        # cannot carry 4:2:0 chroma siting — the 2x2 subsample and the
        # native converter both walk pixel quads. Odd frames are edge-
        # replicated to even dims on the host before conversion; the
        # extra column/row lands inside the encoder's 16-multiple pad
        # region (the capture layer normally pads BEFORE the encoder is
        # built — pipeline/capture.pad_frame_to_even — this is the
        # defensive mirror for direct FramePrep users).
        self._even_w = width + (width & 1)
        self._even_h = height + (height & 1)
        if pad_w < self._even_w or pad_h < self._even_h:
            raise ValueError(
                f"pad {pad_w}x{pad_h} cannot hold the even-padded "
                f"{self._even_w}x{self._even_h} frame")
        self.pad_w, self.pad_h = pad_w, pad_h
        self._lib = _load()
        # rotating output buffers: the encoder pipelines dispatches, and an
        # async h2d transfer may still be reading a plane when the next
        # capture converts — each convert() writes a different slot, so
        # nslots must cover every possibly-in-flight upload plus one
        self._nslots = max(2, int(nslots))
        # conversion slots allocate lazily: change-detection-only users
        # (the VP9 hybrid row) never call convert() and would otherwise
        # carry ~6 MB of dead plane buffers per encoder
        self._bufs: list | None = None
        self._slot = 0
        self._prev: np.ndarray | None = None
        self.nbands = (height + BAND_ROWS - 1) // BAND_ROWS
        self._bands = np.empty(self.nbands, np.uint8)

    @property
    def native(self) -> bool:
        return self._lib is not None

    def convert(self, frame: np.ndarray):
        """(H, W, 4) BGRx uint8 -> (y, u, v) padded planes.

        Buffers rotate over 4 slots, so up to 4 conversions can be in
        flight (async device uploads) before a slot is overwritten."""
        if frame.shape != (self.height, self.width, 4):
            raise ValueError(f"frame {frame.shape} != {(self.height, self.width, 4)}")
        if (self._even_h, self._even_w) != (self.height, self.width):
            frame = np.pad(frame, ((0, self._even_h - self.height),
                                   (0, self._even_w - self.width), (0, 0)),
                           mode="edge")
        if not frame.flags["C_CONTIGUOUS"]:
            frame = np.ascontiguousarray(frame)
        if self._bufs is None:
            self._bufs = [
                (
                    np.empty((self.pad_h, self.pad_w), np.uint8),
                    np.empty((self.pad_h // 2, self.pad_w // 2), np.uint8),
                    np.empty((self.pad_h // 2, self.pad_w // 2), np.uint8),
                )
                for _ in range(self._nslots)
            ]
        y, u, v = self._bufs[self._slot]
        self._slot = (self._slot + 1) % self._nslots
        if self._lib is not None:
            self._lib.bgrx_to_i420_pad(
                _u8p(frame), self._even_h, self._even_w, self.pad_h,
                self.pad_w, _u8p(y), _u8p(u), _u8p(v),
            )
        else:
            y2, u2, v2 = _numpy_convert_pad(frame, self.pad_h, self.pad_w)
            y[:], u[:], v[:] = y2, u2, v2
        return y, u, v

    def reset(self) -> None:
        """Forget the previous frame: the next dirty_bands() reports
        everything dirty (used by encoder prewarm / stream restart)."""
        self._prev = None

    def convert_tiles(self, frame: np.ndarray, idx: np.ndarray, tile_w: int):
        """Convert only the 16-row x tile_w-col tiles listed in idx
        (int32, band*1024 + tile) to packed I420 tile buffers:
        (k, 16, tile_w) luma and (k, 8, tile_w/2) chroma, bit-exact with
        the same region of a full convert(). tile_w must divide pad_w and
        be a multiple of 16; tile_w == pad_w degenerates to bands."""
        if frame.shape != (self.height, self.width, 4):
            raise ValueError(f"frame {frame.shape} != {(self.height, self.width, 4)}")
        if tile_w % 16 or self.pad_w % tile_w:
            raise ValueError(f"tile_w {tile_w} must be a 16-multiple dividing {self.pad_w}")
        # odd geometry: same even-pad normalization as convert() — the
        # quad-walking converters (native and numpy) must never see an
        # odd plane, whichever entry point a direct FramePrep user hits
        if (self._even_h, self._even_w) != (self.height, self.width):
            frame = np.pad(frame, ((0, self._even_h - self.height),
                                   (0, self._even_w - self.width), (0, 0)),
                           mode="edge")
        if not frame.flags["C_CONTIGUOUS"]:
            frame = np.ascontiguousarray(frame)
        idx = np.ascontiguousarray(idx, np.int32)
        k = len(idx)
        yb = np.empty((k, 16, tile_w), np.uint8)
        ub = np.empty((k, 8, tile_w // 2), np.uint8)
        vb = np.empty((k, 8, tile_w // 2), np.uint8)
        if self._lib is not None and hasattr(self._lib, "bgrx_to_i420_tiles"):
            self._lib.bgrx_to_i420_tiles(
                _u8p(frame), self._even_h, self._even_w, self.pad_w, tile_w,
                idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), k,
                _u8p(yb), _u8p(ub), _u8p(vb),
            )
        else:
            y, u, v = _numpy_convert_pad(frame, self.pad_h, self.pad_w)
            ctw = tile_w // 2
            for i, t in enumerate(idx):
                band, tile = int(t) // 1024, int(t) % 1024
                yb[i] = y[band * 16:band * 16 + 16, tile * tile_w:(tile + 1) * tile_w]
                ub[i] = u[band * 8:band * 8 + 8, tile * ctw:(tile + 1) * ctw]
                vb[i] = v[band * 8:band * 8 + 8, tile * ctw:(tile + 1) * ctw]
        return yb, ub, vb

    def dirty_tiles(self, frame: np.ndarray, tile_w: int) -> np.ndarray | None:
        """Which 16-row x tile_w-col tiles changed vs the previous call's
        frame: (nbands, ntiles) bool, or None on the first frame. tile_w
        is in LUMA columns; detection compares the 4*tile_w BGRx bytes.
        Advances the previous-frame state (same contract as dirty_bands)."""
        if not frame.flags["C_CONTIGUOUS"]:
            frame = np.ascontiguousarray(frame)
        ntiles = (self.width + tile_w - 1) // tile_w
        if self._prev is None:
            self._prev = frame.copy()
            return None
        out = np.empty((self.nbands, ntiles), np.uint8)
        if self._lib is not None and hasattr(self._lib, "tile_diff"):
            self._lib.band_diff(
                _u8p(frame), _u8p(self._prev), self.height, self.width,
                BAND_ROWS, _u8p(self._bands),
            )
            self._lib.tile_diff(
                _u8p(frame), _u8p(self._prev), self.height, self.width,
                BAND_ROWS, tile_w, _u8p(self._bands), _u8p(out),
            )
        else:
            for i in range(self.nbands):
                r0, r1 = i * BAND_ROWS, min((i + 1) * BAND_ROWS, self.height)
                for t in range(ntiles):
                    c0, c1 = t * tile_w, min((t + 1) * tile_w, self.width)
                    out[i, t] = not np.array_equal(
                        frame[r0:r1, c0:c1], self._prev[r0:r1, c0:c1])
        np.copyto(self._prev, frame)
        return out.astype(bool)

    def dirty_bands(self, frame: np.ndarray) -> np.ndarray | None:
        """Which 16-row bands changed vs the previous call's frame.

        Returns a bool array of shape (nbands,), or None on the first frame
        (everything dirty). Stores a copy of the frame as the new previous."""
        if not frame.flags["C_CONTIGUOUS"]:
            frame = np.ascontiguousarray(frame)
        if self._prev is None:
            self._prev = frame.copy()
            return None
        if self._lib is not None:
            self._lib.band_diff(
                _u8p(frame), _u8p(self._prev), self.height, self.width,
                BAND_ROWS, _u8p(self._bands),
            )
            out = self._bands.astype(bool)
        else:
            nb = self.nbands
            out = np.zeros(nb, bool)
            for i in range(nb):
                r0, r1 = i * BAND_ROWS, min((i + 1) * BAND_ROWS, self.height)
                out[i] = not np.array_equal(frame[r0:r1], self._prev[r0:r1])
        np.copyto(self._prev, frame)
        return out
