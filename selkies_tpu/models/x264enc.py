"""ctypes wrapper for libx264: the real `x264enc` software encoder row.

The reference's x264enc element (gstwebrtc_app.py:609-639) IS libx264
behind GObject properties; wrapping the same library gives exact
behavioural parity for the CPU H.264 row — and an independent encoder to
hold the TPU row's quality accountable (tests/test_quality_vs_software).
Tuning mirrors the reference: CBR, zerolatency tune, ultrafast preset,
no B-frames, no lookahead, sliced threads, VBV ~= 1.5 frame-times,
byte-stream output with repeated headers (config-interval -1 analogue).

ABI notes: built against libx264.so.164 (build 164). All tunables go
through x264_param_parse (string API, offset-free); only four struct
offsets are poked directly (i_width/i_height/i_csp in x264_param_t,
i_pts + the x264_image_t block in x264_picture_t), each VERIFIED at
load time against x264_param_default/x264_picture_alloc ground truth —
a mismatched build disables the row instead of corrupting memory.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import logging
import struct as _struct
import time

import numpy as np

from selkies_tpu.models.libvpx_enc import _bgrx_to_i420_np
from selkies_tpu.models.stats import FrameStats

logger = logging.getLogger("models.x264")

_PARAM_BYTES = 8192
_PIC_BYTES = 1024
# x264_param_t offsets (verified in _load_and_verify)
_OFF_WIDTH, _OFF_HEIGHT, _OFF_CSP, _OFF_BITDEPTH = 28, 32, 36, 40
# x264_picture_t offsets (verified): i_pts, then the x264_image_t block
_OFF_PTS = 16
_OFF_IMG_CSP, _OFF_IMG_PLANES = 40, 44
_OFF_STRIDES, _OFF_PLANES = 48, 64
# x264_nal_t: 6 ints then the payload pointer
_NAL_PAYLOAD_PTR_OFF = 24
_CSP_I420 = 2

_lib = None
_lib_tried = False


def _load_and_verify():
    """Load libx264 and verify every struct offset this wrapper pokes."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    for name in ("libx264.so.164", "libx264.so.160", "libx264.so", "x264"):
        try:
            lib = ctypes.CDLL(name)
            break
        except OSError:
            continue
    else:
        logger.info("libx264 not found; x264enc row unavailable")
        return None
    # builds 160-164 share every offset this wrapper pokes; the versioned
    # open symbol names the build, and the verification below is what
    # actually gates safety — an unexpected layout disables the row
    for sym in ("x264_encoder_open_164", "x264_encoder_open_160"):
        open_fn = getattr(lib, sym, None)
        if open_fn is not None:
            break
    else:
        logger.warning(
            "libx264 present but no known open symbol; refusing ABI guess")
        return None
    lib._open = open_fn
    lib._open.restype = ctypes.c_void_p
    lib.x264_encoder_encode.restype = ctypes.c_int
    lib.x264_encoder_encode.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_int), ctypes.c_void_p, ctypes.c_void_p,
    ]
    lib.x264_encoder_close.argtypes = [ctypes.c_void_p]

    # --- offset verification against ground truth ---------------------
    p = (ctypes.c_uint8 * _PARAM_BYTES)()
    lib.x264_param_default(p)
    b = bytes(p)
    ok = (
        _struct.unpack_from("<i", b, _OFF_WIDTH)[0] == 0
        and _struct.unpack_from("<i", b, _OFF_HEIGHT)[0] == 0
        and _struct.unpack_from("<i", b, _OFF_CSP)[0] == _CSP_I420
        and _struct.unpack_from("<i", b, _OFF_BITDEPTH)[0] == 8
    )
    pic = (ctypes.c_uint8 * _PIC_BYTES)()
    if ok and lib.x264_picture_alloc(pic, _CSP_I420, 64, 48) == 0:
        pb = bytes(pic)
        ok = (
            _struct.unpack_from("<i", pb, _OFF_IMG_CSP)[0] == _CSP_I420
            and _struct.unpack_from("<i", pb, _OFF_IMG_PLANES)[0] == 3
            and _struct.unpack_from("<3i", pb, _OFF_STRIDES) == (64, 32, 32)
            and all(_struct.unpack_from("<3Q", pb, _OFF_PLANES))
        )
        lib.x264_picture_clean(pic)
    else:
        ok = False
    if ok:
        # verify the x264_nal_t payload-pointer offset too: open a tiny
        # encoder, emit headers, and check the first payload starts with
        # an Annex-B start code (a layout mismatch disables the row
        # instead of dereferencing garbage)
        lib.x264_param_parse(p, b"repeat-headers", b"1")
        lib.x264_param_parse(p, b"annexb", b"1")
        _struct.pack_into("<i", p, _OFF_WIDTH, 64)
        _struct.pack_into("<i", p, _OFF_HEIGHT, 48)
        h = lib._open(p)
        if h:
            nal_ptr = ctypes.c_void_p()
            i_nal = ctypes.c_int()
            lib.x264_encoder_headers.restype = ctypes.c_int
            size = lib.x264_encoder_headers(
                ctypes.c_void_p(h), ctypes.byref(nal_ptr), ctypes.byref(i_nal))
            ok = size > 0 and i_nal.value > 0
            if ok:
                payload = ctypes.cast(
                    nal_ptr.value + _NAL_PAYLOAD_PTR_OFF,
                    ctypes.POINTER(ctypes.c_uint64))[0]
                head = ctypes.string_at(payload, 4) if payload else b""
                ok = head in (b"\x00\x00\x00\x01",)
            lib.x264_encoder_close(ctypes.c_void_p(h))
        else:
            ok = False
    if not ok:
        logger.warning("libx264 struct layout mismatch; x264enc row disabled")
        return None
    _lib = lib
    return _lib


def x264_available() -> bool:
    return _load_and_verify() is not None


class X264Encoder:
    """x264enc: frame in, Annex-B access unit out (TPUH264Encoder facade)."""

    codec = "h264"

    def __init__(self, width: int, height: int, fps: int = 60,
                 bitrate_kbps: int = 2000, preset: str = "ultrafast"):
        lib = _load_and_verify()
        if lib is None:
            raise RuntimeError("libx264 unavailable")
        if width % 2 or height % 2:
            raise ValueError("4:2:0 requires even dimensions")
        self._lib = lib
        self.width, self.height, self.fps = width, height, fps
        self.qp = 0
        param = (ctypes.c_uint8 * _PARAM_BYTES)()
        if lib.x264_param_default_preset(param, preset.encode(), b"zerolatency"):
            raise RuntimeError("x264_param_default_preset failed")

        def parse(k: str, v: str) -> None:
            if lib.x264_param_parse(param, k.encode(), v.encode()):
                raise RuntimeError(f"x264_param_parse {k}={v} failed")

        # reference x264enc row parity (gstwebrtc_app.py:609-639)
        parse("bitrate", str(bitrate_kbps))
        parse("vbv-maxrate", str(bitrate_kbps))
        vbv_kbit = max(1, int(bitrate_kbps * 1.5 / fps))  # 1.5 frame-times
        parse("vbv-bufsize", str(vbv_kbit))
        parse("fps", f"{fps}/1")
        parse("bframes", "0")
        parse("rc-lookahead", "0")
        parse("sync-lookahead", "0")
        parse("mbtree", "0")
        parse("keyint", "infinite")
        parse("sliced-threads", "1")
        parse("threads", "4")
        parse("repeat-headers", "1")   # in-band SPS/PPS (config-interval -1)
        parse("annexb", "1")           # byte-stream
        parse("aud", "0")
        parse("force-cfr", "1")
        _struct.pack_into("<i", param, _OFF_WIDTH, width)
        _struct.pack_into("<i", param, _OFF_HEIGHT, height)
        _struct.pack_into("<i", param, _OFF_CSP, _CSP_I420)
        self._param = param
        self._h = lib._open(param)
        if not self._h:
            raise RuntimeError("x264_encoder_open failed")
        self._pic = (ctypes.c_uint8 * _PIC_BYTES)()
        if lib.x264_picture_alloc(self._pic, _CSP_I420, width, height):
            raise RuntimeError("x264_picture_alloc failed")
        pb = bytes(self._pic)
        self._strides = _struct.unpack_from("<3i", pb, _OFF_STRIDES)
        self._planes = _struct.unpack_from("<3Q", pb, _OFF_PLANES)
        self._pic_out = (ctypes.c_uint8 * _PIC_BYTES)()
        self._pts = 0
        self._force_idr = True
        self.frame_index = 0
        self.last_stats: FrameStats | None = None
        self._pending_bitrate: int | None = None

    # -- live retune (set_video_bitrate path) -------------------------

    def set_bitrate(self, bitrate_kbps: int) -> None:
        self._pending_bitrate = int(bitrate_kbps)

    def set_qp(self, qp: int) -> None:  # CBR owns the quantizer
        pass

    def force_keyframe(self) -> None:
        self._force_idr = True

    def _apply_bitrate(self) -> None:
        kbps = self._pending_bitrate
        self._pending_bitrate = None
        lib = self._lib
        for k, v in (("bitrate", str(kbps)), ("vbv-maxrate", str(kbps)),
                     ("vbv-bufsize", str(max(1, int(kbps * 1.5 / self.fps))))):
            lib.x264_param_parse(self._param, k.encode(), v.encode())
        if lib.x264_encoder_reconfig(self._h, self._param):
            logger.warning("x264_encoder_reconfig rejected bitrate %s", kbps)

    # -- encode -------------------------------------------------------

    def encode_frame(self, frame: np.ndarray, qp: int | None = None) -> bytes:
        t0 = time.perf_counter()
        if self._pending_bitrate is not None:
            self._apply_bitrate()
        y, u, v = _bgrx_to_i420_np(np.asarray(frame))
        for plane, arr, stride in zip(self._planes, (y, u, v), self._strides):
            h, w = arr.shape
            if stride == w:
                ctypes.memmove(plane, np.ascontiguousarray(arr).ctypes.data, h * w)
            else:
                src = np.ascontiguousarray(arr)
                for r in range(h):
                    ctypes.memmove(plane + r * stride,
                                   src.ctypes.data + r * w, w)
        _struct.pack_into("<q", self._pic, _OFF_PTS, self._pts)
        # i_type: X264_TYPE_AUTO=0 / X264_TYPE_IDR=1
        _struct.pack_into("<i", self._pic, 0, 1 if self._force_idr else 0)
        self._pts += 1

        nal_ptr = ctypes.c_void_p()
        i_nal = ctypes.c_int()
        size = self._lib.x264_encoder_encode(
            self._h, ctypes.byref(nal_ptr), ctypes.byref(i_nal),
            self._pic, self._pic_out)
        if size < 0:
            raise RuntimeError("x264_encoder_encode failed")
        au = b""
        if size > 0 and i_nal.value > 0:
            # payloads are contiguous across the nal array (x264 API doc)
            first_payload = ctypes.cast(
                nal_ptr.value + _NAL_PAYLOAD_PTR_OFF,
                ctypes.POINTER(ctypes.c_uint64))[0]
            au = ctypes.string_at(first_payload, size)
        idr = self._force_idr or (b"\x00\x00\x00\x01\x65" in au[:8]
                                  or b"\x00\x00\x01\x65" in au[:8])
        self._force_idr = False
        self.last_stats = FrameStats(
            frame_index=self.frame_index, idr=bool(idr), qp=self.qp,
            bytes=len(au), device_ms=0.0,
            pack_ms=(time.perf_counter() - t0) * 1e3, skipped_mbs=0,
        )
        self.frame_index += 1
        return au

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.x264_encoder_close(self._h)
            self._h = None
        if getattr(self, "_pic", None) is not None:
            self._lib.x264_picture_clean(self._pic)
            self._pic = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
