"""Content-hash tile cache — the uplink's CopyRect analogue.

The delta-upload path ships every dirty 16-row x tile_w-col tile's
pixels, but scrolls, window moves, and alt-tab redraws mostly REARRANGE
content the device has already seen: VNC encodes those as CopyRect
(src rect -> dst rect) and ships no pixels. This cache provides the
same economy for the host->device link: the device keeps an LRU pool of
previously-uploaded I420 tiles, the host keeps a content-hash index of
what each pool slot holds, and a dirty tile whose BGRx bytes hash-match
(and memcmp-verify against) a pool slot becomes an 8-byte
(slot -> dst position) remap executed by the jitted scatter step
instead of a ~3 KB pixel upload.

Correctness contract: a remap is emitted ONLY after an exact memcmp of
the tile's BGRx bytes against the stored copy of what the slot was
uploaded from — the hash (xxhash-style multiply-fold, numpy or
native/frameprep.cc tile_hash) only selects the candidate slot, so a
collision costs one wasted compare, never a wrong pixel. BGRx equality
implies I420 equality because the tile converter is position-independent
for interior tiles; edge tiles (whose converted bytes embed replicated
padding) are excluded from the cache entirely.

The encoder owns the device half (pool planes threaded through the
scatter steps, models/h264/encoder.py); this class is pure host state
and must be reset whenever the device pool is discarded.
"""

from __future__ import annotations

import ctypes

import numpy as np

from selkies_tpu.models import frameprep

__all__ = ["TileCache", "tile_hash_np"]

# splitmix64 constants — shared with native/frameprep.cc tile_hash (the
# two implementations must produce identical hashes; tests compare them)
_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SM_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_M2 = np.uint64(0x94D049BB133111EB)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 finalizer (wrapping uint64 arithmetic)."""
    x = (x + _SM_GAMMA).astype(np.uint64)
    x ^= x >> np.uint64(30)
    x = (x * _SM_M1).astype(np.uint64)
    x ^= x >> np.uint64(27)
    x = (x * _SM_M2).astype(np.uint64)
    return x ^ (x >> np.uint64(31))


_mult_cache: dict[int, np.ndarray] = {}


def _mults(nwords: int) -> np.ndarray:
    """Per-position odd multipliers: splitmix64(position) | 1."""
    m = _mult_cache.get(nwords)
    if m is None:
        m = _splitmix64(np.arange(nwords, dtype=np.uint64)) | np.uint64(1)
        _mult_cache[nwords] = m
    return m


def tile_hash_np(tiles_u8: np.ndarray) -> np.ndarray:
    """(k, nbytes) uint8 tile rows -> (k,) uint64 content hashes.

    Multiply-fold: XOR-reduce of each 8-byte lane times a per-position
    splitmix64-derived odd multiplier, then a splitmix64 avalanche.
    Position-dependent multipliers make permuted content hash apart;
    one numpy pass over all k tiles (no per-tile Python loop)."""
    k, nbytes = tiles_u8.shape
    tiles_u8 = np.ascontiguousarray(tiles_u8)
    lib = frameprep._load()
    if lib is not None and hasattr(lib, "tile_hash"):
        out = np.empty(k, np.uint64)
        lib.tile_hash(
            frameprep._u8p(tiles_u8), k, nbytes,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        )
        return out
    words = tiles_u8.view(np.uint64).reshape(k, nbytes // 8)
    with np.errstate(over="ignore"):
        h = np.bitwise_xor.reduce(words * _mults(words.shape[1]), axis=1)
    return _splitmix64(h)


class TileCache:
    """Host half of the device tile-slot pool: hash index + LRU + the
    BGRx bytes each slot was filled from (for exact verification).

    Slot ids are [0, slots); slot id `slots` is the device pool's
    SCRATCH slot (writes land there when a tile should not be kept)."""

    def __init__(self, height: int, width: int, tile_w: int, slots: int):
        self.height, self.width, self.tile_w = height, width, tile_w
        self.slots = int(slots)
        # only tiles fully inside the unpadded capture are cacheable:
        # edge tiles' I420 bytes embed position-dependent padding
        self._full_bands = height // 16
        self._full_tiles = width // tile_w
        self._tile_bytes = 16 * tile_w * 4
        self._store = np.zeros((self.slots, self._tile_bytes), np.uint8)
        self._hash2slot: dict[int, int] = {}
        self._slot_hash: list[int | None] = [None] * self.slots
        self._free = list(range(self.slots - 1, -1, -1))
        self._stamp = np.zeros(self.slots, np.int64)
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def reset(self) -> None:
        """Forget everything (the device pool was discarded/reallocated)."""
        self._hash2slot.clear()
        self._slot_hash = [None] * self.slots
        self._free = list(range(self.slots - 1, -1, -1))
        self._stamp[:] = 0
        self._clock = 0

    def _tile_bgrx(self, frame: np.ndarray, band: int, tile: int) -> np.ndarray:
        tw = self.tile_w
        return np.ascontiguousarray(
            frame[band * 16 : band * 16 + 16, tile * tw : (tile + 1) * tw]
        ).reshape(-1)

    def _gather_tiles(self, frame: np.ndarray, cidx: list[int]) -> np.ndarray:
        """(k, tile_bytes) stack of the cacheable tiles' BGRx bytes: a
        native per-row memcpy gather (frameprep.cc gather_tiles), with a
        vectorized fancy-index fallback — either way one call instead of
        the historical per-tile _tile_bgrx Python walk (the split's
        dominant cost on scroll frames — ISSUE 12)."""
        tw = self.tile_w
        lib = frameprep._load()
        if lib is not None and hasattr(lib, "gather_tiles"):
            if not frame.flags["C_CONTIGUOUS"]:
                frame = np.ascontiguousarray(frame)
            cid = np.ascontiguousarray(cidx, np.int32)
            out = np.empty((len(cid), self._tile_bytes), np.uint8)
            lib.gather_tiles(
                frameprep._u8p(frame), self.height, self.width, tw,
                cid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                len(cid), frameprep._u8p(out))
            return out
        cid = np.asarray(cidx, np.int64)
        rows = cid[:, None] // 1024 * 16 + np.arange(16)[None, :]
        cols = cid[:, None] % 1024 * tw + np.arange(tw)[None, :]
        return frame[rows[:, :, None], cols[:, None, :]].reshape(len(cid), -1)

    def probe(self, frame: np.ndarray, idx: np.ndarray, samples: int = 8,
              hashes: np.ndarray | None = None) -> float:
        """Fraction of a sampled subset of dirty tiles whose content
        hash is already in the pool index — no memcmp, no state change.
        A cheap plausibility gate for over-budget frames: scrolled
        content probes near 1.0 after its seed frame, video content
        probes ~0.0 every frame (so the classifier skips the full
        hash/split attempt AND the per-frame seeding). ``hashes`` is the
        fused scan's (nbands, ntiles) content-hash array (FramePrep.scan
        want_hashes): with it the probe reads precomputed values and
        touches no pixel bytes at all."""
        step = max(1, len(idx) // samples)
        cand = [int(d) for d in idx[::step][:samples]
                if (int(d) // 1024 < self._full_bands
                    and int(d) % 1024 < self._full_tiles)]
        if not cand:
            return 0.0
        if hashes is not None:
            hs = [int(hashes[d // 1024, d % 1024]) for d in cand]
        else:
            hs = [int(h) for h in tile_hash_np(self._gather_tiles(frame, cand))]
        return sum(h in self._hash2slot for h in hs) / len(cand)

    def split(self, frame: np.ndarray, idx: np.ndarray, max_up: int | None = None,
              hashes: np.ndarray | None = None):
        """Dirty tiles -> (upload_idx, pool_dst, copy_pairs), or None.

        upload_idx: tiles whose pixels must cross the link;
        pool_dst[i]: pool slot the device stores upload i into (`slots`
        = scratch, i.e. not kept); copy_pairs (kc, 2) int32 rows
        (src_slot, dst_idx) for tiles already resident in the pool.

        With `max_up` set, a frame needing more than max_up pixel
        uploads returns None WITHOUT any state change — all decisions
        run against shadow copies of the index and commit atomically at
        the end, so the caller can fall back to the full-upload path
        with the pool still coherent. (This is what lets the encoder
        try the delta path on over-budget dirty frames like a
        maximized-window scroll: if enough tiles are pool-resident the
        frame fits after remapping, and if not, nothing was harmed.)

        Slots assigned IN THIS CALL are never referenced by this call's
        copy pairs: the device applies copies before pool inserts inside
        one step, so a same-step slot would read stale content. (Across
        frames of a grouped dispatch the scan carry orders inserts
        before the next frame's copies, matching host call order.)

        ``hashes`` is the fused front-end scan's (nbands, ntiles)
        content-hash array (FramePrep.scan want_hashes, valid at the
        dirty tiles `idx` names): with it the split skips its own
        hashing pass — the values are identical by construction
        (tests/test_frontend_parallel.py pins them against
        tile_hash_np)."""
        uploads: list[int] = []
        pool_dst: list[int] = []
        pairs: list[tuple[int, int]] = []
        cacheable = []
        for d in idx:
            d = int(d)
            band, tile = d // 1024, d % 1024
            cacheable.append(band < self._full_bands and tile < self._full_tiles)
        tiles_bytes = {}
        verified: dict[int, bool] = {}
        cidx = [int(d) for d, c in zip(idx, cacheable) if c]
        if cidx:
            stack = self._gather_tiles(frame, cidx)
            if hashes is not None:
                cid = np.asarray(cidx, np.int64)
                hvals = hashes[cid // 1024, cid % 1024]
            else:
                hvals = tile_hash_np(stack)
            tiles_bytes = {d: (stack[i], int(hvals[i])) for i, d in enumerate(cidx)}
            # batch the hash-hit memcmp verifies: ONE vectorized compare
            # of every pre-call candidate against its stored bytes
            # (replacing the per-tile array_equal loop — the split's
            # dominant cost on scroll frames). Valid because the loop
            # below only consults a verify for slots looked up from the
            # PRE-CALL index: an in-call insert is skipped via
            # new_slots, and an in-call eviction removes the hash so
            # the lookup misses before it could read a stale verdict.
            cand = [(i, self._hash2slot.get(int(hvals[i]))) for i in range(len(cidx))]
            cand = [(i, s) for i, s in cand if s is not None]
            if cand:
                ci = np.fromiter((i for i, _ in cand), np.int64, len(cand))
                cs = np.fromiter((s for _, s in cand), np.int64, len(cand))
                eq = (stack[ci] == self._store[cs]).all(axis=1)
                verified = {cidx[int(i)]: bool(e) for i, e in zip(ci, eq)}
        # shadow state: committed only if the frame fits the budget
        h2s = dict(self._hash2slot)
        slot_hash = list(self._slot_hash)
        free = list(self._free)
        stamp = self._stamp.copy()
        clock = self._clock + 1
        store_w: dict[int, np.ndarray] = {}
        hits = misses = evictions = 0
        new_slots: set[int] = set()
        for d, c in zip(idx, cacheable):
            d = int(d)
            if not c:
                uploads.append(d)
                pool_dst.append(self.slots)  # scratch: never kept
                if max_up is not None and len(uploads) > max_up:
                    return None  # over budget: shadow state discarded
                continue
            raw, h = tiles_bytes[d]
            slot = h2s.get(h)
            if (
                slot is not None
                and slot not in new_slots
                and verified.get(d, False)
            ):
                pairs.append((slot, d))
                stamp[slot] = clock
                hits += 1
                continue
            misses += 1
            if slot is None:
                if free:
                    slot = free.pop()
                else:
                    slot = int(np.argmin(stamp))  # LRU
                    old = slot_hash[slot]
                    if old is not None and old in h2s:
                        del h2s[old]
                    evictions += 1
                h2s[h] = slot
                slot_hash[slot] = h
            # else: hash collision or same-call duplicate — refresh the
            # existing slot with this content (idempotent on duplicates)
            store_w[slot] = raw
            stamp[slot] = clock
            new_slots.add(slot)
            uploads.append(d)
            pool_dst.append(slot)
            if max_up is not None and len(uploads) > max_up:
                return None  # over budget: shadow state discarded
        self._hash2slot = h2s
        self._slot_hash = slot_hash
        self._free = free
        self._stamp = stamp
        self._clock = clock
        for slot, raw in store_w.items():
            self._store[slot] = raw
        self.hits += hits
        self.misses += misses
        self.evictions += evictions
        return (
            np.array(uploads, np.int32),
            np.array(pool_dst, np.int32),
            np.array(pairs, np.int32).reshape(-1, 2),
        )
