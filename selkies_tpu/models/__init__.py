"""Codec "model families": the encoder implementations.

The flagship is models.h264 (``tpuh264enc``); vp9 and av1 mirror the
reference's encoder matrix (gstwebrtc_app.py:260-783) in later milestones.
Encoder selection goes through models.registry.
"""
