from selkies_tpu.models.vp9.encoder import TPUVP9Encoder, show_existing_frame  # noqa: F401
